"""Headline benchmark: images/sec/chip, ResNet-18 / MNIST, data-parallel.

The BASELINE.json north-star (``BASELINE.json:2``): data-parallel ResNet-18 on
MNIST, reported per chip. The reference publishes no numbers
(``BASELINE.json:13``), so ``vs_baseline`` is reported against
``BASELINE_IMAGES_PER_SEC_PER_CHIP`` below — this repo's first recorded TPU
run, so later rounds measure improvement against round 1.

The headline number is the **end-to-end training loop** including the input
pipeline — not a cached batch replayed. The input pipeline is the
device-resident one (``data/resident.py``): the dataset is placed in HBM
once, and the measured region is a multi-epoch ``lax.scan`` whose body
gathers each step's batch on device — one XLA launch and one host fetch for
the whole region (a device-trace profile showed per-epoch launch/fetch
costing ~8% on the tunneled runtime; the step itself is ~85% convolution
fusions — see PROFILE_r04.md for the HLO-verified breakdown that corrected
round 2's "BN-bound" misread). The JSON line carries the honesty
metadata: whether the data was a synthetic surrogate (no network egress in
the build env), a breakdown (streaming train, raw H2D ceiling, train step
alone), and the held-out eval accuracy against the stated 0.99 target (the
BASELINE "reaches reference accuracy" demonstration, measured unbiased —
wrap-padding masked).

Prints exactly one JSON line on stdout
(``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}``);
progress/epoch lines go to stderr.
"""

from __future__ import annotations

import contextlib
import json
import sys

# Round-1 first honest measurement on one TPU v5e chip (bf16 compute,
# slope-timed to cancel the axon tunnel's async dispatch + roundtrip latency).
# Round-1 measured the train step on a cached batch; from round 2 the headline
# includes the input pipeline. Later rounds divide by this to show the trend.
BASELINE_IMAGES_PER_SEC_PER_CHIP = 46400.0


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--real", action="store_true",
        help="require REAL MNIST on disk (scripts/fetch_datasets.py): "
        "refuse to bench the synthetic surrogate, so the receipt can "
        "only be a real-data receipt",
    )
    ap.add_argument(
        "--quiet", action="store_true",
        help="silence per-epoch trainer chatter on stderr (structured "
        "metrics still record; the JSON line is unaffected)",
    )
    args = ap.parse_args()

    import jax

    import optax

    from pytorch_distributed_training_tutorials_tpu.bench.headline import (
        make_headline_setup,
        make_step_chain,
    )
    from pytorch_distributed_training_tutorials_tpu.data import (
        ChunkedStreamingLoader,
        DeviceResidentLoader,
        mnist,
    )
    from pytorch_distributed_training_tutorials_tpu.obs import DriftBracket, MinOfN, make_receipt
    from pytorch_distributed_training_tutorials_tpu.train import Trainer

    # the canonical workload (uint8-resident MNIST, bf16 cifar-stem
    # ResNet-18, SGD+momentum) — shared with scripts/profile_step.py and
    # scripts/step_time_experiment.py so the profiler measures exactly what
    # this headline reports
    setup = make_headline_setup(per_device_batch=512, quiet=args.quiet)
    mesh, ds, loader, trainer = (
        setup.mesh, setup.dataset, setup.loader, setup.trainer
    )
    if args.real and ds.synthetic:
        raise SystemExit(
            "--real: no MNIST idx files under DATA_DIR — run "
            "scripts/fetch_datasets.py (needs network) first; refusing "
            "to report a synthetic receipt as real"
        )
    model = trainer.model
    n_chips = mesh.devices.size
    per_device_batch = setup.per_device_batch

    # 6 epochs per fused launch (was 3): the fused region pays ONE
    # launch + fetch (~100-130 ms on this tunnel) regardless of length, so
    # doubling the span halves the per-epoch share of it (round-5 A/B:
    # +~1.5% end-to-end). Accuracy trains a few epochs longer; the target
    # check is unaffected (MNIST plateaus >=0.996 well before epoch 10).
    fused_epochs = 6
    with contextlib.redirect_stdout(sys.stderr):
        # TIMING DISCIPLINE on the tunneled runtime (measured, round 3):
        # before the process's first D2H fetch, `block_until_ready` and
        # device_put report async mirages (an apparent 778k img/s streamed
        # epoch whose device trace shows ~7 s of real execution); the first
        # fetch stalls ~19 s and drops apparent H2D to the tunnel's TRUE
        # sustained bandwidth (~4-16 MB/s). Honest numbers therefore need
        # (a) the first fetch PRIMED outside any timed region and (b) every
        # timed region closed by a real fetch — which the legs below do.

        # Breakdown leg 1a: streaming END-TO-END TRAINING — the path a
        # larger-than-HBM dataset actually takes: chunked H2D (16 steps per
        # transfer), background prefetch, each chunk trained as one scanned
        # launch (data/streaming.py). Ceiling on this host: the tunnel's
        # true H2D bandwidth (drifts 2.5-11 MB/s minute to minute — leg 1b
        # measures it in the same window); on real PCIe hosts the step
        # rate (~36 MB/s needed) would be the bound instead.
        chunked = ChunkedStreamingLoader(
            ds, per_device_batch, mesh, seed=0,
            steps_per_chunk=16, transform=loader.transform,
        )
        stream_trainer = Trainer(
            model, chunked, optax.sgd(0.05, momentum=0.9),
            loss="cross_entropy", quiet=args.quiet,
        )
        # Breakdown leg 1: streaming train vs the RAW H2D ceiling. The
        # ceiling is pure device_put of the same dataset bytes in
        # chunk-sized buffers, primed and closed by a ONE-element terminal
        # fetch. The tunnel's bandwidth drifts minute to minute (observed
        # 2.5-11 MB/s across a day), so the ceiling is measured
        # immediately BEFORE and AFTER the streaming epoch and averaged —
        # bracketing the drift instead of racing it. Round-4 finding:
        # streaming training runs at ~100% of the same-window ceiling
        # (4,787 img/s train vs 4,728 img/s raw put, same process) — the
        # gap to the step-only rate is tunnel physics, not pipeline
        # overhead. (Round 3's 'pipeline-only slower than
        # pipeline+training' inversion was this drift plus per-chunk
        # syncs in the old pipeline-only leg.)
        import numpy as np

        n_bufs = 7
        rows_needed = chunked.steps_per_chunk * chunked.global_batch
        # np.resize wraps when the dataset has fewer rows than one chunk
        # needs (16 * 512 * n_chips can exceed 60000 on multi-chip hosts)
        chunk_imgs = np.resize(
            ds.arrays[0], (rows_needed, *ds.arrays[0].shape[1:])
        ).reshape(
            chunked.steps_per_chunk, chunked.global_batch,
            *ds.arrays[0].shape[1:]
        )

        def fetch_scalar(buf):
            # device-side index, then a ONE-element D2H — fetching the
            # whole buffer would charge MBs of D2H to the H2D timing
            return float(buf[-1, -1].ravel()[-1])

        def h2d_ceiling():
            bufs = [jax.device_put(chunk_imgs) for _ in range(n_bufs)]
            jax.block_until_ready(bufs)
            fetch_scalar(bufs[-1])

        # warm + prime the put path (first-fetch stall lives elsewhere but
        # the first put of a new shape pays layout/allocator setup)
        bufs = [jax.device_put(chunk_imgs) for _ in range(2)]
        jax.block_until_ready(bufs)
        fetch_scalar(bufs[-1])
        del bufs

        # compiles both chunk lengths AND primes the first-fetch stall
        # (the per-epoch loss fetch) outside the timed region — and
        # outside the bracket: epoch 0's compile takes long enough for
        # the tunnel to drift
        stream_trainer._run_epoch(0)
        # obs.DriftBracket: the ceiling leg runs immediately BEFORE and
        # AFTER the streaming epoch; ~1.0 drift = stable window (the
        # streaming fraction below is trustworthy), >>1 = the fraction is
        # drift noise around the controlled same-process finding (~1.0)
        bracket = DriftBracket(
            h2d_ceiling, payload_bytes=n_bufs * chunk_imgs.nbytes
        ).around(
            lambda: stream_trainer._run_epoch(1)["samples_per_sec"]
        )
        stream_train_images_s = bracket.result
        dt = (bracket.before_s + bracket.after_s) / 2
        h2d_drift = bracket.drift
        h2d_mb_s = n_bufs * chunk_imgs.nbytes / 1e6 / dt
        h2d_images_s = (
            n_bufs * chunked.steps_per_chunk * chunked.global_batch / dt
        )

        # Headline: epoch 0 compiles the per-epoch program; the first fused
        # call compiles the fused-run program (different scan length); the
        # best of the next two fused calls is the honest end-to-end
        # measurement: dataset residency, on-device gather, train step, ONE
        # launch + ONE host fetch for the whole region (profile finding:
        # per-epoch launch/fetch overhead was ~8% of epoch wall time on the
        # tunneled runtime). Max-of-2 on throughput = min-of-2 on time:
        # individual launches stall multi-second on this tunnel (round 4
        # measured a 0.25 s launch sampling at 528 s once), and the
        # headline must not be hostage to one bad draw.
        trainer._run_epoch(0)
        trainer.run_epochs_fused(1, fused_epochs)  # compile warmup
        e2e = max(
            trainer.run_epochs_fused(
                1 + k * fused_epochs, fused_epochs
            )["samples_per_sec"]
            for k in range(1, 3)
        )

        # Breakdown leg 2: train step alone on a cached batch — a jitted
        # scan of N chained steps, timed as one launch + one fetch. (Round 1
        # slope-timed individual dispatches, which over-reported ~60% on the
        # tunneled runtime vs the XLA device trace; the scanned chain matches
        # the trace's per-step time.)
        # the cached batch is normalized by the loader's jitted transform
        # (same bf16 dtype semantics as the in-scan path); unroll=8
        # amortizes while-loop bookkeeping and halves the loop-boundary
        # state copies (round-4 trace: device 10.60 -> 10.23 ms/step; see
        # PROFILE_r04.md). The fused-epoch leg ALSO unrolls x8 now — the
        # round-5 re-measure showed the round-4 "no win on the real epoch
        # scan" reading was tunnel weather (make_headline_setup).
        chain_len = 256
        chain = make_step_chain(setup, chain_len, unroll=8)

        # obs.MinOfN(n=2): the tunnel suffers rare multi-tens-of-seconds
        # stalls (observed once in ~6 runs: a 2.6 s chain read as 108 s);
        # the minimum of two closed timed regions rejects a one-off stall,
        # and the warmup run is the compile + first-fetch priming
        holder = {"state": trainer.state}

        def chain_run():
            holder["state"], losses = chain(holder["state"])
            float(losses[-1])

        step_timing = MinOfN(n=2).measure(chain_run)
        step_images_s = chain_len * loader.global_batch / step_timing.best_s

        # Accuracy demonstration (BASELINE north star: "reaches reference
        # accuracy"): evaluate on the held-out test split with wrap-padding
        # masked (unbiased). Target: 0.99 — conventional MNIST ResNet
        # accuracy. The surrogate is tuned so the target is FALSIFIABLE
        # (data/datasets.py signal=0.35: healthy training measures 0.9961
        # with nonzero loss; the signal=0.30 negative control misses at
        # 0.9867 after 7 epochs AND still at 0.9863 after the full
        # 19-epoch span this bench now trains — re-measured round 5 when
        # fused_epochs doubled, so longer training cannot sneak a degraded
        # config past the target; a broken config fails outright —
        # tests/test_accuracy_falsifiable.py pins that control).
        # `synthetic` says which data this was.
        test_loader = DeviceResidentLoader(
            mnist("test", raw=True),
            per_device_batch,
            mesh,
            seed=0,
            transform=loader.transform,
        )
        eval_metrics = trainer.evaluate(test_loader)

    per_chip = e2e / n_chips
    # the schema'd envelope (obs.receipt): payload keys stay top-level so
    # the one-JSON-line contract and its consumers are unchanged; the
    # envelope adds schema/kind/env (git sha, jax, mesh) + the drift window
    receipt = make_receipt(
        "bench_headline",
        {
                "metric": (
                    "images/sec/chip (ResNet-18 MNIST, data-parallel train, "
                    "end-to-end incl. input pipeline)"
                ),
                "value": round(per_chip, 1),
                "unit": "images/sec/chip",
                "vs_baseline": round(
                    per_chip / BASELINE_IMAGES_PER_SEC_PER_CHIP, 3
                ),
                "synthetic": bool(ds.synthetic),
                "n_chips": n_chips,
                "per_device_batch": per_device_batch,
                "eval_accuracy": round(eval_metrics["accuracy"], 4),
                "eval_loss": round(eval_metrics["loss"], 6),
                "accuracy_target": 0.99,
                "reaches_accuracy_target": bool(
                    eval_metrics["accuracy"] >= 0.99
                ),
                "breakdown": {
                    "streaming_train_images_per_sec_per_chip": round(
                        stream_train_images_s / n_chips, 1
                    ),
                    # round 4: the pipeline-alone leg became the RAW H2D
                    # ceiling (pure device_put, same bytes, same tunnel
                    # window) — streaming is judged as a fraction of it
                    "h2d_ceiling_images_per_sec_per_chip": round(
                        h2d_images_s / n_chips, 1
                    ),
                    "h2d_ceiling_mb_per_sec": round(h2d_mb_s, 2),
                    "h2d_window_drift": round(h2d_drift, 2),
                    "streaming_fraction_of_h2d_ceiling": round(
                        stream_train_images_s / max(h2d_images_s, 1e-9), 3
                    ),
                    "train_step_only_images_per_sec_per_chip": round(
                        step_images_s / n_chips, 1
                    ),
                    "train_step_only_stalled_samples": (
                        step_timing.n_stalled
                    ),
                },
        },
        mesh=mesh,
        drift=bracket.to_dict(),
    )
    print(json.dumps(receipt))


if __name__ == "__main__":
    main()
