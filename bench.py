"""Headline benchmark: images/sec/chip, ResNet-18 / MNIST, data-parallel.

The BASELINE.json north-star (``BASELINE.json:2``): data-parallel ResNet-18 on
MNIST, reported per chip. The reference publishes no numbers
(``BASELINE.json:13``), so ``vs_baseline`` is reported against
``BASELINE_IMAGES_PER_SEC_PER_CHIP`` below — set from this repo's first
recorded TPU run so later rounds measure improvement against round 1.

Prints exactly one JSON line:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``.
"""

from __future__ import annotations

import json

# Round-1 first honest measurement on one TPU v5e chip (bf16 compute,
# slope-timed to cancel the axon tunnel's async dispatch + roundtrip latency).
# Later rounds divide by this to show the trend.
BASELINE_IMAGES_PER_SEC_PER_CHIP = 46400.0


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from pytorch_distributed_training_tutorials_tpu.data import (
        ShardedLoader,
        mnist,
    )
    from pytorch_distributed_training_tutorials_tpu.models import resnet18
    from pytorch_distributed_training_tutorials_tpu.parallel.mesh import create_mesh
    from pytorch_distributed_training_tutorials_tpu.train import (
        Trainer,
    )

    mesh = create_mesh()
    n_chips = mesh.devices.size
    per_device_batch = 256

    ds = mnist("train")
    loader = ShardedLoader(ds, per_device_batch, mesh, seed=0)
    model = resnet18(num_classes=10, stem="cifar", dtype=jnp.bfloat16)
    trainer = Trainer(
        model, loader, optax.sgd(0.05, momentum=0.9), loss="cross_entropy"
    )

    batch = next(iter(loader))

    def run(k: int) -> None:
        # k chained steps ending in a host fetch (slope_time contract)
        last = None
        for _ in range(k):
            trainer.state, last = trainer.train_step(trainer.state, batch)
        float(last["loss"])

    from pytorch_distributed_training_tutorials_tpu.bench.harness import slope_time

    sec_per_step = slope_time(run, n1=5, n2=25, warmup=3)
    images_per_sec = loader.global_batch / sec_per_step
    per_chip = images_per_sec / n_chips
    print(
        json.dumps(
            {
                "metric": "images/sec/chip (ResNet-18 MNIST, data-parallel train)",
                "value": round(per_chip, 1),
                "unit": "images/sec/chip",
                "vs_baseline": round(
                    per_chip / BASELINE_IMAGES_PER_SEC_PER_CHIP, 3
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
