"""Headline benchmark: images/sec/chip, ResNet-18 / MNIST, data-parallel.

The BASELINE.json north-star (``BASELINE.json:2``): data-parallel ResNet-18 on
MNIST, reported per chip. The reference publishes no numbers
(``BASELINE.json:13``), so ``vs_baseline`` is reported against
``BASELINE_IMAGES_PER_SEC_PER_CHIP`` below — this repo's first recorded TPU
run, so later rounds measure improvement against round 1.

The headline number is the **end-to-end training loop** including the input
pipeline — not a cached batch replayed. The input pipeline is the
device-resident one (``data/resident.py``): the dataset is placed in HBM
once, and the measured region is a multi-epoch ``lax.scan`` whose body
gathers each step's batch on device — one XLA launch and one host fetch for
the whole region (a device-trace profile showed per-epoch launch/fetch
costing ~8% on the tunneled runtime; the remaining step time is dominated by
BatchNorm statistics/elementwise fusions, not convolutions — see the round-2
commit message for the trace analysis). The JSON line carries the honesty
metadata: whether the data was a synthetic surrogate (no network egress in
the build env), a breakdown (streaming input pipeline alone, train step
alone), and the held-out eval accuracy against the stated 0.99 target (the
BASELINE "reaches reference accuracy" demonstration, measured unbiased —
wrap-padding masked).

Prints exactly one JSON line on stdout
(``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}``);
progress/epoch lines go to stderr.
"""

from __future__ import annotations

import contextlib
import json
import sys

# Round-1 first honest measurement on one TPU v5e chip (bf16 compute,
# slope-timed to cancel the axon tunnel's async dispatch + roundtrip latency).
# Round-1 measured the train step on a cached batch; from round 2 the headline
# includes the input pipeline. Later rounds divide by this to show the trend.
BASELINE_IMAGES_PER_SEC_PER_CHIP = 46400.0


def main() -> None:
    import jax
    import time

    import jax.numpy as jnp
    import optax

    from pytorch_distributed_training_tutorials_tpu.data import (
        DeviceResidentLoader,
        ShardedLoader,
        mnist,
    )
    from pytorch_distributed_training_tutorials_tpu.models import resnet18
    from pytorch_distributed_training_tutorials_tpu.parallel.mesh import create_mesh
    from pytorch_distributed_training_tutorials_tpu.train import Trainer
    from pytorch_distributed_training_tutorials_tpu.train.trainer import (
        _train_step_fn,
    )

    mesh = create_mesh()
    n_chips = mesh.devices.size
    per_device_batch = 512

    # uint8 at rest in HBM (the on-disk dtype, 1/4 the f32 bytes, ~4x less
    # per-step gather traffic); the /255 normalize runs inside the compiled
    # step and fuses into the stem convolution
    ds = mnist("train", raw=True)
    loader = DeviceResidentLoader(
        ds,
        per_device_batch,
        mesh,
        seed=0,
        transform=lambda x, y: (x.astype(jnp.bfloat16) / 255.0, y),
    )
    model = resnet18(num_classes=10, stem="cifar", dtype=jnp.bfloat16)
    trainer = Trainer(
        model, loader, optax.sgd(0.05, momentum=0.9), loss="cross_entropy"
    )

    fused_epochs = 3
    with contextlib.redirect_stdout(sys.stderr):
        # Epoch 0 compiles the per-epoch program; the first fused call
        # compiles the fused-run program (different scan length); the second
        # fused call is the honest end-to-end measurement: dataset residency,
        # on-device gather, train step, ONE launch + ONE host fetch for the
        # whole region (profile finding: per-epoch launch/fetch overhead was
        # ~8% of epoch wall time on the tunneled runtime).
        trainer._run_epoch(0)
        trainer.run_epochs_fused(1, fused_epochs)  # compile warmup
        e2e = trainer.run_epochs_fused(1 + fused_epochs, fused_epochs)[
            "samples_per_sec"
        ]

        # Breakdown leg 1: the *streaming* input pipeline (native C++ row
        # gather + per-batch H2D), one full pass, no compute — what a
        # larger-than-HBM dataset would pay on the host side.
        streaming = ShardedLoader(ds, per_device_batch, mesh, seed=0)
        t0 = time.perf_counter()
        n_batches = 0
        for batch in streaming:
            jax.block_until_ready(batch)
            n_batches += 1
        input_images_s = n_batches * streaming.global_batch / (
            time.perf_counter() - t0
        )

        # Breakdown leg 2: train step alone on a cached batch — a jitted
        # scan of N chained steps, timed as one launch + one fetch. (Round 1
        # slope-timed individual dispatches, which over-reported ~60% on the
        # tunneled runtime vs the XLA device trace; the scanned chain matches
        # the trace's per-step time.)
        # normalized once outside the chain via the loader's jitted transform
        # (same bf16 dtype semantics as the in-scan path — a host-side numpy
        # transform would silently promote to f32 and time the wrong step):
        # this leg isolates the train step itself
        batch = jax.block_until_ready(
            loader._apply_transform(next(iter(streaming)))
        )
        step_fn = _train_step_fn("cross_entropy", has_batch_stats=True)
        chain_len = 256

        @jax.jit
        def chain(state):
            def body(s, _):
                s, m = step_fn(s, batch)
                return s, m["loss"]

            return jax.lax.scan(body, state, None, length=chain_len)

        state = trainer.state
        state, losses = chain(state)  # compile
        jax.block_until_ready(losses)
        t0 = time.perf_counter()
        state, losses = chain(state)
        float(losses[-1])
        step_images_s = (
            chain_len * streaming.global_batch / (time.perf_counter() - t0)
        )

        # Accuracy demonstration (BASELINE north star: "reaches reference
        # accuracy"): evaluate on the held-out test split with wrap-padding
        # masked (unbiased). Target: 0.99 — conventional MNIST ResNet
        # accuracy; the synthetic surrogate is easier, so missing the target
        # on ANY data flags a training regression (the `synthetic` field
        # says which data this run used).
        test_loader = DeviceResidentLoader(
            mnist("test", raw=True),
            per_device_batch,
            mesh,
            seed=0,
            transform=loader.transform,
        )
        eval_metrics = trainer.evaluate(test_loader)

    per_chip = e2e / n_chips
    print(
        json.dumps(
            {
                "metric": (
                    "images/sec/chip (ResNet-18 MNIST, data-parallel train, "
                    "end-to-end incl. input pipeline)"
                ),
                "value": round(per_chip, 1),
                "unit": "images/sec/chip",
                "vs_baseline": round(
                    per_chip / BASELINE_IMAGES_PER_SEC_PER_CHIP, 3
                ),
                "synthetic": bool(ds.synthetic),
                "n_chips": n_chips,
                "per_device_batch": per_device_batch,
                "eval_accuracy": round(eval_metrics["accuracy"], 4),
                "eval_loss": round(eval_metrics["loss"], 6),
                "accuracy_target": 0.99,
                "reaches_accuracy_target": bool(
                    eval_metrics["accuracy"] >= 0.99
                ),
                "breakdown": {
                    "input_pipeline_images_per_sec_per_chip": round(
                        input_images_s / n_chips, 1
                    ),
                    "train_step_only_images_per_sec_per_chip": round(
                        step_images_s / n_chips, 1
                    ),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
