"""Headline benchmark: images/sec/chip, ResNet-18 / MNIST, data-parallel.

The BASELINE.json north-star (``BASELINE.json:2``): data-parallel ResNet-18 on
MNIST, reported per chip. The reference publishes no numbers
(``BASELINE.json:13``), so ``vs_baseline`` is reported against
``BASELINE_IMAGES_PER_SEC_PER_CHIP`` below — this repo's first recorded TPU
run, so later rounds measure improvement against round 1.

The headline number is the **end-to-end training loop** including the input
pipeline — not a cached batch replayed. The input pipeline is the
device-resident one (``data/resident.py``): the dataset is placed in HBM
once, and each epoch is a single jitted ``lax.scan`` whose body gathers the
step's batch on device (the TPU-idiomatic shape for datasets far smaller
than HBM; on the tunneled runtime it is also ~3x faster end-to-end than
per-step dispatch). The JSON line carries the honesty metadata: whether the
data was a synthetic surrogate (no network egress in the build env) and a
breakdown (streaming input pipeline alone, train step alone) so a host-side
bottleneck is visible rather than hidden.

Prints exactly one JSON line on stdout
(``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}``);
progress/epoch lines go to stderr.
"""

from __future__ import annotations

import contextlib
import json
import sys

# Round-1 first honest measurement on one TPU v5e chip (bf16 compute,
# slope-timed to cancel the axon tunnel's async dispatch + roundtrip latency).
# Round-1 measured the train step on a cached batch; from round 2 the headline
# includes the input pipeline. Later rounds divide by this to show the trend.
BASELINE_IMAGES_PER_SEC_PER_CHIP = 46400.0


def main() -> None:
    import jax
    import jax.numpy as jnp
    import optax

    from pytorch_distributed_training_tutorials_tpu.bench.harness import slope_time
    from pytorch_distributed_training_tutorials_tpu.data import (
        DeviceResidentLoader,
        ShardedLoader,
        mnist,
    )
    from pytorch_distributed_training_tutorials_tpu.models import resnet18
    from pytorch_distributed_training_tutorials_tpu.parallel.mesh import create_mesh
    from pytorch_distributed_training_tutorials_tpu.train import (
        Trainer,
        make_train_step,
    )

    mesh = create_mesh()
    n_chips = mesh.devices.size
    per_device_batch = 256

    ds = mnist("train")
    loader = DeviceResidentLoader(ds, per_device_batch, mesh, seed=0)
    model = resnet18(num_classes=10, stem="cifar", dtype=jnp.bfloat16)
    trainer = Trainer(
        model, loader, optax.sgd(0.05, momentum=0.9), loss="cross_entropy"
    )

    with contextlib.redirect_stdout(sys.stderr):
        # Epoch 0 compiles and warms every cache; epochs 1-2 are the honest
        # end-to-end measurement (dataset residency + on-device gather +
        # train step, synced by the host fetch of the final loss).
        trainer._run_epoch(0)
        e2e = max(
            trainer._run_epoch(epoch)["samples_per_sec"] for epoch in (1, 2)
        )

        # Breakdown leg 1: the *streaming* input pipeline (native C++ row
        # gather + per-batch H2D), one full pass, no compute — what a
        # larger-than-HBM dataset would pay on the host side.
        import time

        streaming = ShardedLoader(ds, per_device_batch, mesh, seed=0)
        t0 = time.perf_counter()
        n_batches = 0
        for batch in streaming:
            jax.block_until_ready(batch)
            n_batches += 1
        input_images_s = n_batches * streaming.global_batch / (
            time.perf_counter() - t0
        )

        # Breakdown leg 2: train step alone on a cached batch (the round-1
        # measurement) — the device-side ceiling for per-step dispatch.
        batch = next(iter(streaming))
        step = make_train_step(loss="cross_entropy", has_batch_stats=True)
        state = trainer.state

        def run(k: int) -> None:
            nonlocal state
            last = None
            for _ in range(k):
                state, last = step(state, batch)
            float(last["loss"])

        step_images_s = streaming.global_batch / slope_time(
            run, n1=5, n2=25, warmup=3
        )

    per_chip = e2e / n_chips
    print(
        json.dumps(
            {
                "metric": (
                    "images/sec/chip (ResNet-18 MNIST, data-parallel train, "
                    "end-to-end incl. input pipeline)"
                ),
                "value": round(per_chip, 1),
                "unit": "images/sec/chip",
                "vs_baseline": round(
                    per_chip / BASELINE_IMAGES_PER_SEC_PER_CHIP, 3
                ),
                "synthetic": bool(ds.synthetic),
                "n_chips": n_chips,
                "per_device_batch": per_device_batch,
                "breakdown": {
                    "input_pipeline_images_per_sec_per_chip": round(
                        input_images_s / n_chips, 1
                    ),
                    "train_step_only_images_per_sec_per_chip": round(
                        step_images_s / n_chips, 1
                    ),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
