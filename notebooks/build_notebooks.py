"""Generate the three tutorial notebooks (twins of the reference's 01/02/03).

Each notebook reproduces the corresponding reference lesson's *observable*
behavior on TPU (SURVEY.md section 7, build step 8): the per-chip batch
split, the steps-per-epoch sharding proof, and the model-parallel placement
audit + benchmark. Run ``python notebooks/build_notebooks.py`` to regenerate
the ``.ipynb`` files; ``tests/test_notebooks.py`` executes every code cell.
"""

from __future__ import annotations

import os

import nbformat as nbf

HERE = os.path.dirname(os.path.abspath(__file__))


def build(name: str, cells: list[tuple[str, str]]) -> None:
    """Regenerate one notebook, CARRYING OVER captured outputs for code
    cells whose source is unchanged (matched by deterministic cell id).

    The reference's verification mechanism is captured outputs committed
    in the .ipynb — the "Steps 16" vs "Steps 64" sharding proof a reader
    sees without running anything (``02.ddp_toy_example.ipynb:255-318``).
    Carrying unchanged cells' outputs keeps regeneration byte-stable
    (pinned by test_notebooks_regenerate_cleanly) while an edited cell
    drops its stale output until ``--execute`` refreshes it.
    """
    path = os.path.join(HERE, name)
    prior: dict[str, tuple[str, list, object]] = {}
    if os.path.exists(path):
        try:
            old = nbf.read(path, as_version=4)
            for c in old.cells:
                if c.cell_type == "code":
                    prior[c.get("id")] = (
                        c.source,
                        c.get("outputs", []),
                        c.get("execution_count"),
                    )
        except Exception:
            pass
    nb = nbf.v4.new_notebook()
    nb.metadata["kernelspec"] = {
        "display_name": "Python 3", "language": "python", "name": "python3",
    }
    for i, (kind, src) in enumerate(cells):
        src = src.strip("\n")
        if kind == "md":
            cell = nbf.v4.new_markdown_cell(src)
        else:
            cell = nbf.v4.new_code_cell(src)
            old = prior.get(f"cell-{i}")
            if old is not None and old[0] == src:
                cell["outputs"] = old[1]
                cell["execution_count"] = old[2]
        cell["id"] = f"cell-{i}"  # deterministic: output is committed
        nb.cells.append(cell)
    with open(path, "w") as f:
        nbf.write(nb, f)
    print("wrote", path)


def execute(name: str) -> None:
    """Run every code cell in a fresh working dir and store its captured
    stdout as the cell's committed output (the reference's executed-
    notebook verification, SURVEY.md section 4). Subprocess-driving cells
    capture their own children's stdout and print it, so one
    stdout-stream output per cell is the complete observable record."""
    import contextlib
    import io
    import sys
    import tempfile

    # cells import the package the way a notebook user would — make the
    # checkout importable in this fresh interpreter
    repo_root = os.path.dirname(HERE)
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    os.environ["PYTHONPATH"] = (
        repo_root + os.pathsep + os.environ.get("PYTHONPATH", "")
    )
    path = os.path.join(HERE, name)
    nb = nbf.read(path, as_version=4)
    ns: dict = {"__name__": "__main__"}
    count = 0
    cwd = os.getcwd()
    with tempfile.TemporaryDirectory() as tmp:
        os.chdir(tmp)  # figures etc. land in a scratch dir
        try:
            for cell in nb.cells:
                if cell.cell_type != "code":
                    continue
                count += 1
                buf = io.StringIO()
                with contextlib.redirect_stdout(buf):
                    exec(compile(cell.source, f"{name}[{cell['id']}]",
                                 "exec"), ns)
                text = buf.getvalue()
                cell["outputs"] = (
                    [nbf.v4.new_output("stream", name="stdout", text=text)]
                    if text
                    else []
                )
                cell["execution_count"] = count
        finally:
            os.chdir(cwd)
    with open(path, "w") as f:
        nbf.write(nb, f)
    print("executed", path)


SETUP = """
# Hardware-portable setup: on a TPU host this uses the real chips; anywhere
# else it fakes an 8-device CPU mesh (the tutorials' "multi-node without a
# cluster" posture, SURVEY.md section 4).
import os
if not os.environ.get("TPU_DDP_NB_REAL"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )
import jax
if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")
print(f"{len(jax.devices())} devices:", jax.devices())
"""


# --------------------------------------------------------------------------
# 01 — data parallelism in one process (twin of 01.data_parallel.ipynb)
# --------------------------------------------------------------------------
NB01 = [
    ("md", """
# 01 — Data parallelism in one process

Twin of the reference's `01.data_parallel.ipynb`: one Python process drives
every local accelerator. In torch this is `nn.DataParallel` — per step it
**replicates** the module, **scatters** the batch, runs 4 GIL-bound threads,
and **gathers** the outputs. On TPU the whole dance collapses into one
compiled SPMD program: params live replicated (no per-step broadcast), the
batch is *sharded* along the `data` mesh axis, and XLA compiles the
scatter/gather away. This notebook reproduces the lesson's observable: **a
global batch of 32 splits into 8 per-chip blocks of 4** (the reference's
`Input shape: [8, 32]` prints, cell 16).
"""),
    ("code", SETUP),
    ("md", """
## Device inventory
The reference checks `torch.cuda.device_count()` (cell 3). The TPU twin is a
named **mesh** over the local devices — the one abstraction all later
parallelism configs reuse.
"""),
    ("code", """
from pytorch_distributed_training_tutorials_tpu import create_mesh
mesh = create_mesh()            # {'data': <all devices>}
print(dict(mesh.shape))
"""),
    ("md", """
## Dataset and the *global-batch* loader
`RandomDataset(32, 1024)` twin: 1,024 samples of `randn(32)`. The reference
feeds `DataLoader(batch_size=32)` and lets DataParallel split each batch;
here `batch_mode="global"` means 32 is the *whole-step* batch that the mesh
divides (the per-device default used everywhere else preserves the
reference's `--batch_size` per-device semantics).
"""),
    ("code", """
from pytorch_distributed_training_tutorials_tpu.data import (
    ShardedLoader, random_dataset,
)
ds = random_dataset(size=32, length=1024)
loader = ShardedLoader(ds, 32, mesh, batch_mode="global", shuffle=False)
batch = next(iter(loader))
print("global batch:", batch.shape)
"""),
    ("md", """
## The observable: the per-chip split
The reference *proves* the scatter with shape prints from inside each
replica's forward. Under SPMD there is no per-replica program to print from —
the proof lives on the array itself: its addressable shards.
"""),
    ("code", """
from pytorch_distributed_training_tutorials_tpu.ops import (
    per_shard_shapes, describe_sharding,
)
print("per-shard shapes:", per_shard_shapes(batch))   # 8 x (4, 32)
print(describe_sharding(batch))
"""),
    ("md", """
## One training step, compiled
`SampleModel` twin (`Linear(32, 2)`), Adam(1e-3), and the reference's
`loss = output.sum()` (cell 16). Params replicated x batch sharded: XLA
inserts the gradient allreduce — the compiled equivalent of DataParallel's
gather + backward reduction, minus the per-step replication cost.
"""),
    ("code", """
import jax, jax.numpy as jnp, optax
from pytorch_distributed_training_tutorials_tpu.models import SampleModel
from pytorch_distributed_training_tutorials_tpu.parallel import DataParallel

model = SampleModel()
dp = DataParallel(mesh)
params = jax.jit(model.init, out_shardings=dp.param_sharding)(
    jax.random.PRNGKey(0), batch
)
opt = optax.adam(1e-3)
opt_state = opt.init(params)

@jax.jit
def step(params, opt_state, x):
    def loss_fn(p):
        out = model.apply(p, x)
        return out.sum()          # the lesson's toy objective
    loss, grads = jax.value_and_grad(loss_fn)(params)
    updates, opt_state = opt.update(grads, opt_state)
    return optax.apply_updates(params, updates), opt_state, loss

for i, x in enumerate(loader):
    params, opt_state, loss = step(params, opt_state, x)
    if i < 3:
        print(f"step {i}: loss {float(loss):+.3f}  "
              f"input split {per_shard_shapes(x)[0]} x {len(jax.devices())}")
print("steps per epoch:", len(loader), "(1024 / 32)")
"""),
    ("md", """
## What replaced what

| torch `nn.DataParallel` (per step) | TPU SPMD (compiled once) |
|---|---|
| replicate module to N GPUs | params placed replicated **once** |
| scatter batch dim 0 | `data`-axis sharding annotation |
| 4 Python threads forward | one XLA program on all chips |
| gather outputs to GPU 0 | outputs stay sharded (or psum'd) |
| grads reduce to master | allreduce compiled into backward |

The GIL-threading bottleneck this lesson warns about does not exist here —
that is the point of the SPMD design.
"""),
]

# --------------------------------------------------------------------------
# 02 — DDP: multi-process data parallelism (twin of 02.ddp_toy_example.ipynb)
# --------------------------------------------------------------------------
NB02 = [
    ("md", """
# 02 — Distributed data parallelism

Twin of the reference's `02.ddp_toy_example.ipynb`. Vocabulary first (the
reference's cell 2): **all-to-one = reduce**, **one-to-all = broadcast**,
every process has a **rank** in `[0, world_size)`. Then the lesson itself:
the same trainer launched two ways — explicit ranks (`mp.spawn`) and
environment-discovered topology (`torchrun`) — proving the data *shards*
(`Steps 64` alone vs `Steps 16` at world size 4).
"""),
    ("code", SETUP),
    ("md", """
## Collectives, hands on
The reference names NCCL; here collectives are XLA ops over ICI. A `psum`
over the mesh *is* the DDP gradient allreduce.
"""),
    ("code", """
import jax, jax.numpy as jnp, numpy as np
from jax.sharding import PartitionSpec as P
from pytorch_distributed_training_tutorials_tpu import create_mesh

mesh = create_mesh()
n = mesh.devices.size

@jax.shard_map(mesh=mesh, in_specs=P("data"), out_specs=P())
def allreduce(x):
    return jax.lax.psum(x, "data")          # all-to-one... then to all

ranks = jnp.arange(n, dtype=jnp.float32)
print("per-device values:", ranks, "-> allreduce:", allreduce(ranks))

@jax.shard_map(mesh=mesh, in_specs=P(), out_specs=P("data"))
def broadcast(x):
    return x                                 # one-to-all: replication
print("broadcast 7.0 ->", broadcast(jnp.asarray([7.0])))
"""),
    ("md", """
## The trainer, in-notebook
The exact `ddp_gpus.py` workload: `Linear(20, 1)` on 2,048 synthetic
samples, SGD(1e-2), batch 32 **per device**. One SPMD process stands in for
the whole process group (multi-host runs use the identical code — see the
launch contracts below).
"""),
    ("code", """
import optax
from pytorch_distributed_training_tutorials_tpu.data import (
    ShardedLoader, synthetic_regression,
)
from pytorch_distributed_training_tutorials_tpu.models import LinearRegressor
from pytorch_distributed_training_tutorials_tpu.train import Trainer

loader = ShardedLoader(synthetic_regression(2048), 32, mesh)
trainer = Trainer(LinearRegressor(), loader, optax.sgd(1e-2), loss="mse")
trainer.train(3)
print("sanity: 2048 / 32 =", 2048 / 32, "steps if unsharded")
print(f"sharded across {mesh.devices.size}: {len(loader)} steps/epoch")
"""),
    ("md", """
## Launch contract 1 — spawn (explicit ranks)
`mp.spawn` twin: the parent forks N OS processes, injects each rank, and
fixes the rendezvous address up front (`ddp_gpus.py:12-17,104-105`). Real
jax.distributed worlds over CPU devices + gloo collectives — multi-process
without a cluster.
"""),
    ("code", """
import subprocess, sys, os
import pytorch_distributed_training_tutorials_tpu as pkg
repo_root = os.path.dirname(os.path.dirname(os.path.abspath(pkg.__file__)))
env = {
    k: v for k, v in os.environ.items()
    if k not in ("PALLAS_AXON_POOL_IPS", "TPU_WORKER_HOSTNAMES")
}
env["JAX_PLATFORMS"] = "cpu"
env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
out = subprocess.run(
    [sys.executable, "-m",
     "pytorch_distributed_training_tutorials_tpu.launch.train_ddp",
     "--max_epochs", "1", "--batch_size", "32",
     "--nprocs", "4", "--platform", "cpu"],
    capture_output=True, text=True, timeout=600, env=env,
)
print(out.stdout)
assert "Steps 16]" in out.stdout   # 2048 / 32 / 4 — the sharding proof
"""),
    ("md", """
## Launch contract 2 — environment-discovered (the torchrun twin)
The script owns *no* topology: `JAX_COORDINATOR_ADDRESS` /
`JAX_NUM_PROCESSES` / `JAX_PROCESS_ID` come from the launcher (on a real TPU
pod, from the runtime metadata — the pod is the elastic agent). Bare launch =
1 process = no sharding = `Steps 64`, the reference's cell 11 output.
"""),
    ("code", """
out = subprocess.run(
    [sys.executable, "-m",
     "pytorch_distributed_training_tutorials_tpu.launch.train_ddp_env",
     "--max_epochs", "1", "--batch_size", "32"],
    capture_output=True, text=True, timeout=600,
    env={**env, "XLA_FLAGS": "--xla_force_host_platform_device_count=1"},
)
print(out.stdout)
assert "Steps 64]" in out.stdout   # 2048 / 32, unsharded
"""),
    ("md", """
The delta between the two scripts is *only* where topology comes from —
the same seam as `ddp_gpus.py` vs `ddp_gpus_torchrun.py`. Everything after
`init()` is identical SPMD code.
"""),
]

# --------------------------------------------------------------------------
# 03 — model parallelism (twin of 03.model_parallel.ipynb)
# --------------------------------------------------------------------------
NB03 = [
    ("md", """
# 03 — Model parallelism

Twin of the reference's `03.model_parallel.ipynb`, three lessons:

1. **Auto placement + 8-bit load** (`device_map="auto"` +
   `load_in_8bit=True`): a checkpoint restored with matmul weights
   quantized to int8 and placement decided declaratively.
2. **Toy 2-device split**: `Linear(10000,10) -> relu -> Linear(10,5)` with
   the activation hopping devices mid-forward.
3. **Pipeline-split ResNet-50** benchmarked against single-device.
"""),
    ("code", SETUP),
    ("md", """
## Lesson 1 — quantize-on-load + placement audit
The reference streams Llama-7B into int8 (cell 2) and audits every param's
device/dtype (cell 4). Same flow, declarative: orbax restore ->
`load_quantized` -> audit. Int8 matmul weights, float norms — the same
mixed-precision layout the reference's audit shows.
"""),
    ("code", """
import jax, jax.numpy as jnp, numpy as np, tempfile, os
from pytorch_distributed_training_tutorials_tpu.models import (
    TransformerConfig, TransformerLM, model_size,
)
from pytorch_distributed_training_tutorials_tpu.parallel.auto import (
    save_checkpoint, load_quantized, audit_placement,
)

cfg = TransformerConfig(vocab_size=256, d_model=128, n_layers=2, n_heads=4)
lm = TransformerLM(cfg)
variables = lm.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
print(f"params: {model_size(variables['params']):,}")

ckpt = os.path.join(tempfile.mkdtemp(), "lm")
save_checkpoint(ckpt, dict(variables["params"]))
q = load_quantized(ckpt)

from pytorch_distributed_training_tutorials_tpu.ops import Int8Param
flat = jax.tree_util.tree_flatten_with_path(
    q, is_leaf=lambda x: isinstance(x, Int8Param))[0]
for kp, leaf in flat[:6]:
    name = "/".join(str(getattr(k, "key", k)) for k in kp)
    if isinstance(leaf, Int8Param):
        print(f"{name}: int8 {leaf.q.shape} + f32 scales")
    else:
        print(f"{name}: {leaf.dtype} {leaf.shape}")
"""),
    ("md", """
### Lesson 1b — serve it (the step the reference stops short of)
The reference loads Llama-7B 8-bit but never generates (`GenerationConfig`
imported, no `generate` call anywhere). Serving the quantized model exposed
two TPU lessons, both measured at 1.2B scale on a real chip (DECODE_r04.md,
2.7 -> 508 tok/s):

1. **One scanned block body, not L unrolled copies** — serve with
   `scan_layers=True` and `stack_quantized_lm_params` (per-layer int8
   scales are exactly per-layer quantization; generations are
   token-identical). Compile time and program size become O(1) in depth.
2. **Pin loaded checkpoints on device** — leaf-streamed restores land as
   host numpy, and jit re-uploads numpy arguments on *every* call
   (invisible over PCIe, ~16 s/launch over a thin tunnel).
   `utils.tree.device_materialize` is one exact-identity launch that
   fixes it; `load_quantized_lm` applies it automatically.
"""),
    ("code", """
import dataclasses
from pytorch_distributed_training_tutorials_tpu.models.transformer import (
    quantize_lm_params, stack_quantized_lm_params,
)
from pytorch_distributed_training_tutorials_tpu.models.generate import generate
from pytorch_distributed_training_tutorials_tpu.utils.tree import device_materialize

qparams = quantize_lm_params(dict(variables["params"]))
stacked = device_materialize(stack_quantized_lm_params(qparams))
serve_lm = TransformerLM(
    dataclasses.replace(cfg, quantized=True, scan_layers=True)
)
prompt = (jnp.arange(8, dtype=jnp.int32)[None].repeat(2, 0)) % cfg.vocab_size
out = generate(serve_lm, stacked, prompt, max_new_tokens=8)
print("generated:", np.asarray(out[:, 8:]))
# one (L, ...) leaf per weight instead of L separate copies:
print("stacked q_proj q:",
      stacked["layers"]["block"]["attn"]["q_proj"]["q"].shape, "int8")
"""),
    ("md", """
## Lesson 2 — the toy 2-device split
The reference pins `net1` to `cuda:0`, `net2` to `cuda:1`, and calls
`x.to("cuda:1")` mid-forward (cells 7/12). The twin: each stage is its own
XLA program committed to its device; the hop is an explicit transfer (ICI on
real hardware); backward re-crosses it in reverse.
"""),
    ("code", """
import optax
from pytorch_distributed_training_tutorials_tpu.models import ToyModel
from pytorch_distributed_training_tutorials_tpu.parallel import ManualPipeline

rng = np.random.Generator(np.random.PCG64(0))
pipe = ManualPipeline.from_linen(
    ToyModel(), np.zeros((2, 10000), np.float32),
    devices=jax.devices()[:2], loss="mse", optimizer=optax.sgd(1e-3),
)
for line in pipe.placement_audit():
    print(line)
for step in range(3):
    x = rng.standard_normal((20, 10000)).astype(np.float32)
    y = rng.standard_normal((20, 5)).astype(np.float32)
    print(f"step {step}: loss {float(pipe.train_step(x, y)):.4f}")
"""),
    ("md", """
## Lesson 3 — pipeline-split ResNet-50
conv1..layer2 on device 0, layer3..fc on device 1 (cells 18/26). The
param-count invariance check is the reference's cells 20/22: **25,557,032**
parameters whether split or not.
"""),
    ("code", """
from pytorch_distributed_training_tutorials_tpu.models import resnet50
from pytorch_distributed_training_tutorials_tpu.bench.harness import benchmark

BATCH, IMG = 16, 32   # reference uses 120 @ 3x128x128; scaled to run anywhere
model = resnet50(num_classes=1000)
pipe = ManualPipeline.from_linen(
    model, np.zeros((2, IMG, IMG, 3), np.float32),
    devices=jax.devices()[:2], loss="mse", optimizer=optax.sgd(1e-3),
)
counts = pipe.stage_param_counts()
print("per-stage params:", [f"{c:,}" for c in counts])
print(f"total {sum(counts):,} == unsplit 25,557,032:",
      sum(counts) == 25_557_032)
"""),
    ("code", """
# the reference's timeit.repeat benchmark (cell 28) — async-dispatch-correct
x = rng.standard_normal((BATCH, IMG, IMG, 3)).astype(np.float32)
y = rng.standard_normal((BATCH, 1000)).astype(np.float32)

from pytorch_distributed_training_tutorials_tpu.data import ShardedLoader
from pytorch_distributed_training_tutorials_tpu.data.datasets import ArrayDataset
from pytorch_distributed_training_tutorials_tpu.parallel.mesh import create_mesh
from pytorch_distributed_training_tutorials_tpu.train import Trainer

mesh1 = create_mesh({"data": 1}, devices=jax.devices()[:1])
single = Trainer(
    resnet50(num_classes=1000),
    ShardedLoader(ArrayDataset((x, y)), BATCH, mesh1), optax.sgd(1e-3),
    loss="mse",
)
batch = next(iter(single.loader))

def single_step():
    # train_step donates the state: rebind it every call
    single.state, metrics = single.train_step(single.state, batch)
    return metrics["loss"]

pp = benchmark(lambda: pipe.train_step(x, y), name="2-stage pipeline",
               warmup=1, repeat=5)
sg = benchmark(single_step, name="single device", warmup=1, repeat=5)
print(pp)
print(sg)
"""),
    ("code", """
# the reference's matplotlib bar chart (cells 29-30)
import matplotlib
matplotlib.use("Agg")
import matplotlib.pyplot as plt

fig, ax = plt.subplots(figsize=(5, 3.2))
names = [pp.name, sg.name]
means = [pp.mean_s, sg.mean_s]
stds = [pp.std_s, sg.std_s]
ax.bar(names, means, yerr=stds, color=["#4477aa", "#ee6677"], capsize=6)
ax.set_ylabel("seconds / step")
ax.set_title("ResNet-50: 2-stage pipeline vs single device")
fig.tight_layout()
fig.savefig("resnet50_pipeline_vs_single.png", dpi=120)
print("saved resnet50_pipeline_vs_single.png")
"""),
    ("md", """
Like the reference's chart, the 2-stage *sequential* pipeline is **not**
faster than one device — one batch flows stage0 -> stage1 with no microbatch
interleave, so stages idle (the reference makes the same point, cell 27's
discussion). The split buys *memory headroom* (each device holds ~half the
params), not throughput; adding microbatching is the classic fix and is
where a `stage`-axis `shard_map` schedule would slot in.
"""),
]


# --------------------------------------------------------------------------
# 04 — scaling out (beyond the reference: FSDP, microbatched pipelines,
#      elastic restart, scaling efficiency)
# --------------------------------------------------------------------------
NB04 = [
    ("md", """
# 04 — Scaling out: FSDP, microbatched pipelines, elastic training

The reference *declares* deepspeed and megatron-fsdp in its environment
(`environment.yml:62-63`) and writes its torchrun script against an elastic
agent — but never builds any of it. This lesson makes those capabilities
real, the TPU way: each one is a **sharding recipe over the same named
mesh**, not a wrapper framework.
"""),
    ("code", SETUP),
    ("md", """
## FSDP / ZeRO — shard the *parameters*, not just the batch
DDP keeps every parameter, gradient, and optimizer moment on every chip.
FSDP shards them over the `data` axis; XLA compiles the all-gather-at-use /
reduce-scatter schedule from the annotations. Per-chip HBM for everything
sharded drops to `1/world` — the ZeRO-3 memory curve — while the numerics
are *identical* to DDP (it's an execution schedule, not a new optimizer).
"""),
    ("code", """
import jax, numpy as np, optax
from pytorch_distributed_training_tutorials_tpu import create_mesh
from pytorch_distributed_training_tutorials_tpu.data import ShardedLoader
from pytorch_distributed_training_tutorials_tpu.data.datasets import ArrayDataset
from pytorch_distributed_training_tutorials_tpu.models import MLP
from pytorch_distributed_training_tutorials_tpu.parallel import FSDP
from pytorch_distributed_training_tutorials_tpu.train import Trainer

mesh = create_mesh()
rng = np.random.Generator(np.random.PCG64(0))
labels = rng.integers(0, 4, 512).astype(np.int32)
centers = rng.standard_normal((4, 64)).astype(np.float32) * 3
x = centers[labels] + 0.1 * rng.standard_normal((512, 64)).astype(np.float32)

loader = ShardedLoader(ArrayDataset((x, labels)), 8, mesh)
trainer = Trainer(
    MLP(features=(256, 4)), loader, optax.adam(1e-3),
    strategy=FSDP(mesh, min_size=256), loss="cross_entropy",
)
trainer.train(3)

k = trainer.state.params["Dense_0"]["kernel"]
mu = trainer.state.opt_state[0].mu["Dense_0"]["kernel"]
print("kernel:", k.shape, "spec", k.sharding.spec,
      "-> per-chip shard", k.addressable_shards[0].data.shape)
print("adam mu follows:", mu.sharding.spec)
"""),
    ("md", """
Each chip holds 1/8 of the kernel *and* 1/8 of Adam's moments — the audit
above is the observable. Swap `FSDP(mesh)` for `DataParallel(mesh)` and the
loss curve is bit-for-bit the same (`tests/test_fsdp.py` pins this).

## Pipeline parallelism with microbatching — one compiled program
The reference's 2-stage split runs one batch through stage0 then stage1,
stages idling in turn (lesson 03). The production schedule is **GPipe**:
split the batch into microbatches that fill and drain the pipeline. With a
scanned transformer the whole dp x pp schedule is ONE `shard_map` program:
the layer stack's leading axis is sharded over `stage` (placement = an
annotation), activations hop stages via `ppermute`, and data parallelism
rides the `data` axis of the same mesh.
"""),
    ("code", """
import jax.numpy as jnp
from pytorch_distributed_training_tutorials_tpu.data import synthetic_lm
from pytorch_distributed_training_tutorials_tpu.models.transformer import (
    TransformerConfig, TransformerLM,
)
from pytorch_distributed_training_tutorials_tpu.parallel import (
    PipelinedTransformerLM, PipelineParallel,
)

mesh_pp = create_mesh({"data": 4, "stage": 2})
cfg = TransformerConfig(vocab_size=64, d_model=32, n_layers=4, n_heads=2,
                        max_seq_len=32, scan_layers=True)
model = PipelinedTransformerLM(cfg, mesh_pp, num_microbatches=4)

key = jax.random.PRNGKey(0)
tokens = jax.random.randint(key, (16, 8), 0, 64)
variables = model.init(key, tokens)

# the schedule reorders compute, not math: identical logits
ref = TransformerLM(cfg)
diff = jnp.abs(model.apply(variables, tokens) - ref.apply(variables, tokens))
print("max |pipelined - unpipelined| =", float(diff.max()))

loader = ShardedLoader(synthetic_lm(size=256, seq_len=16, vocab_size=64),
                       16, mesh_pp)
t_pp = Trainer(model, loader, optax.adam(3e-3),
               strategy=PipelineParallel(mesh_pp, num_microbatches=4),
               loss="cross_entropy")
t_pp.train(2)
qk = t_pp.state.params["layers"]["block"]["attn"]["q_proj"]["kernel"]
print("4 stacked layers, spec", qk.sharding.spec,
      "-> resident per stage:", qk.addressable_shards[0].data.shape[0])
"""),
    ("md", """
## Heterogeneous stages: GPipe on sub-mesh columns
ResNet-style cuts have no common stacked-layer axis to shard, so each stage
gets one *column* of the `{'data': D, 'stage': S}` grid (its own data-parallel
sub-mesh); microbatches fill/drain across columns, gradients and BatchNorm
statistics accumulate and apply once per step — plain gradient accumulation,
verified against a single-device comparator in `tests/test_gpipe.py`.
"""),
    ("code", """
from pytorch_distributed_training_tutorials_tpu.models import ToyModel
from pytorch_distributed_training_tutorials_tpu.parallel import GPipe

toy_x = rng.standard_normal((32, 10000)).astype(np.float32)
toy_y = rng.standard_normal((32, 5)).astype(np.float32)
pipe = GPipe.from_linen(
    ToyModel(), toy_x, devices=mesh_pp, num_microbatches=4,
    loss="mse", optimizer=optax.sgd(1e-3),
)
first = float(pipe.train_step(toy_x, toy_y))
for _ in range(4):
    last = float(pipe.train_step(toy_x, toy_y))
print(f"loss {first:.4f} -> {last:.4f} over 5 GPipe steps")
for line in pipe.placement_audit():
    print(" ", line)
"""),
    ("md", """
## Elastic restart-and-resume
torchrun's elastic agent restarts a failed world — *from scratch*, because
the reference never checkpoints. Here `spawn(max_restarts=N)` gang-aborts
the world the moment any rank dies, re-forks it with a fresh rendezvous,
and the Trainer resumes from its latest checkpoint. Below, rank 1 hard-kills
itself (`os._exit`) on the first attempt once epochs 0-1 are checkpointed;
the relaunched world resumes at epoch 2 (the printed `resumed at epoch 2`)
and finishes all 3 epochs.
"""),
    ("code", """
import subprocess, sys, tempfile, textwrap, os
import pytorch_distributed_training_tutorials_tpu as pkg
repo_root = os.path.dirname(os.path.dirname(os.path.abspath(pkg.__file__)))

script = textwrap.dedent('''
    import json, os, sys
    import numpy as np

    def worker(rank, workdir):
        from pytorch_distributed_training_tutorials_tpu.parallel import distributed
        distributed.init()
        import optax
        from pytorch_distributed_training_tutorials_tpu import create_mesh
        from pytorch_distributed_training_tutorials_tpu.data import (
            ShardedLoader, synthetic_regression,
        )
        from pytorch_distributed_training_tutorials_tpu.models import LinearRegressor
        from pytorch_distributed_training_tutorials_tpu.train import Trainer

        loader = ShardedLoader(synthetic_regression(256), 32, create_mesh())
        t = Trainer(LinearRegressor(), loader, optax.sgd(1e-2), loss="mse")
        ckpt = os.path.join(workdir, "ckpt")
        sentinel = os.path.join(workdir, "crashed_once")
        if os.path.exists(ckpt):
            t.restore(ckpt)
            print(f"[rank {rank}] resumed at epoch {t.epoch}", flush=True)
        while t.epoch < 3:
            t.train(t.epoch + 1)
            t.save(ckpt)
            if t.epoch == 2 and rank == 1 and not os.path.exists(sentinel):
                open(sentinel, "w").write("1")
                os._exit(17)  # hard crash mid-training

    if __name__ == "__main__":
        from pytorch_distributed_training_tutorials_tpu.launch import spawn
        spawn(worker, 2, args=(sys.argv[1],), env_contract=True,
              platform="cpu", max_restarts=1, join_timeout_s=600)
        print("RESTART-AND-RESUME OK")
''')

workdir = tempfile.mkdtemp()
spath = os.path.join(workdir, "elastic_demo.py")
open(spath, "w").write(script)
env = {k: v for k, v in os.environ.items()
       if k not in ("PALLAS_AXON_POOL_IPS", "TPU_WORKER_HOSTNAMES")}
env["JAX_PLATFORMS"] = "cpu"
env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
out = subprocess.run([sys.executable, spath, workdir],
                     capture_output=True, text=True, timeout=900, env=env)
print(out.stdout[-2000:])
assert "RESTART-AND-RESUME OK" in out.stdout, out.stderr[-2000:]
assert os.path.exists(os.path.join(workdir, "crashed_once"))
"""),
    ("md", """
## Scaling efficiency — the number that matters at pod scale
Weak scaling: hold per-chip batch fixed, widen the `data` axis, and track
images/s/chip vs the 1-chip run. Perfect allreduce/backward overlap = 1.0;
an exposed allreduce shows up directly. (On this CPU mesh the fake devices
share one core, so efficiency drops mechanically — the harness is what
transfers to a pod, where the same command targets >=90% at 32 chips,
`BASELINE.json`.)
"""),
    ("code", """
from pytorch_distributed_training_tutorials_tpu.bench.scaling import sweep
from pytorch_distributed_training_tutorials_tpu.models import MLP as _MLP

def make_batch(global_batch):
    gx = rng.standard_normal((global_batch, 64)).astype(np.float32)
    gy = rng.integers(0, 4, global_batch).astype(np.int32)
    return gx, gy

points = sweep([1, 2, 4], per_device_batch=16,
               model=_MLP(features=(64, 4)), tx=optax.sgd(1e-2),
               make_batch=make_batch, n1=2, n2=6)
for p in points:
    print(f"  {p.num_chips} chips: {p.images_per_sec_per_chip:,.0f} "
          f"img/s/chip, efficiency {p.efficiency:.2f}")
"""),
    ("md", """
## Long context — the same attention contract, three executions

Dense causal attention materializes a `(B, H, S, S)` float32 score tensor
— quadratic HBM that caps single-chip context. Two escapes, both drop-in
`attention_fn`s for the same `TransformerLM`:

- **Pallas flash attention** (`ops.flash_attention`): blockwise online
  softmax — scores only ever exist as VMEM tiles, temp memory flat in S
  (`FLASH_r04.md` has the v5e evidence: ~2x faster training at S=4096,
  2.1 GB of dense temps avoided).
- **Ring attention** (`parallel.ring_attention`): shard the *sequence*
  over a mesh axis; K/V blocks rotate via `ppermute` while each device
  folds them into the same online-softmax state — context length scales
  linearly with the ring size.

They must agree with the dense reference exactly — one contract, three
executions:
"""),
    ("code", """
import dataclasses
import jax, jax.numpy as jnp, numpy as np
from pytorch_distributed_training_tutorials_tpu.models import TransformerConfig, TransformerLM
from pytorch_distributed_training_tutorials_tpu.ops import make_flash_attention
from pytorch_distributed_training_tutorials_tpu.parallel.ring_attention import make_ring_attention
from pytorch_distributed_training_tutorials_tpu import create_mesh as _cm

cfg = TransformerConfig(vocab_size=64, d_model=32, n_layers=2, n_heads=4,
                        max_seq_len=64)
toks = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 32)),
                   jnp.int32)
dense_lm = TransformerLM(cfg)
variables = dense_lm.init(jax.random.PRNGKey(0), toks)

flash_lm = TransformerLM(dataclasses.replace(
    cfg, attention_fn=make_flash_attention(16, 16)))
ring_lm = TransformerLM(dataclasses.replace(
    cfg, attention_fn=make_ring_attention(_cm({"seq": 4}))))

lg_dense = dense_lm.apply(variables, toks)
lg_flash = flash_lm.apply(variables, toks)
lg_ring = ring_lm.apply(variables, toks)
print("flash vs dense:", float(jnp.abs(lg_flash - lg_dense).max()))
print("ring  vs dense:", float(jnp.abs(lg_ring - lg_dense).max()))
assert float(jnp.abs(lg_flash - lg_dense).max()) < 1e-4
assert float(jnp.abs(lg_ring - lg_dense).max()) < 1e-4
"""),
    ("md", """
Serving composes with the same machinery: `models.generate` prefills the
prompt in one forward, decodes through a KV cache sized to the *request*
(not `max_seq_len`), and an SP-configured model falls back to the dense
path only for prompt lengths that don't divide the seq axis.
"""),
    ("md", """
## Tuning an LM train step for the MXU — the knobs that matter

`TRAIN_LLM_r05.md` measured a 1.01B-param model at **50% MFU** on one
v5e chip. Three configuration choices did the work (in order of effect):
flash attention over dense (+16.6 MFU points at S=2048), **unrolled**
layers over `nn.scan` for *training* (+2 points AND less memory — the
scan's stacked activation saves compile to badly-laid-out update-slice
copies), and `remat_policy="dots"` (save matmul outputs, recompute only
the cheap elementwise ops; full remat re-runs every matmul in the
backward, and *no* remat cannot even fit real batches). The same config
object expresses all three:
"""),
    ("code", """
import optax
from pytorch_distributed_training_tutorials_tpu.train.trainer import TrainState, make_train_step

train_cfg = TransformerConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=4, max_seq_len=64,
    attention_fn=make_flash_attention(16, 16),  # 1. flash, not dense
    scan_layers=False,                          # 2. unrolled for training
    remat=True, remat_policy="dots",            # 3. save the matmuls
)
lm = TransformerLM(train_cfg)
params = lm.init(jax.random.PRNGKey(0), toks)["params"]
state = TrainState.create(
    apply_fn=lm.apply, params=params, tx=optax.adamw(3e-4)
)
step = make_train_step("cross_entropy")  # the jitted donated SPMD step
state, metrics = step(state, (toks[:, :-1], toks[:, 1:]))
print("LM train step (flash x unrolled x dots-remat) loss:",
      float(metrics["loss"]))
# the real-chip receipt: python -m pytorch_distributed_training_tutorials_tpu.bench.lm_headline
"""),
    ("md", """
(Serving flips choice 2: `scan_layers=True` keeps the *program* O(1) in
depth, which is what launch-latency-bound decoding needs — DECODE_r04.md.
Training saves activations, serving doesn't; the two paths have different
binding constraints and the config lets each pick.)

Every recipe above — FSDP, both pipeline schedules, elastic restart, the
sweep, the long-context kernels — is the *same code* on a real pod slice;
only the mesh gets wider and the collectives move from shared-memory gloo
to ICI.
"""),
]


NOTEBOOKS = {
    "01_data_parallel.ipynb": NB01,
    "02_ddp.ipynb": NB02,
    "03_model_parallel.ipynb": NB03,
    "04_scaling_out.ipynb": NB04,
}


if __name__ == "__main__":
    import sys

    for nb_name, nb_cells in NOTEBOOKS.items():
        build(nb_name, nb_cells)
    if "--execute" in sys.argv:
        # each notebook re-execs the builder in a FRESH interpreter: the
        # SETUP cell must set XLA_FLAGS/JAX_PLATFORMS before jax
        # initializes, which a shared process could only do once
        import subprocess

        selected = [a for a in sys.argv[1:] if a != "--execute"]
        unknown = [a for a in selected if a not in NOTEBOOKS]
        if unknown:
            raise SystemExit(
                f"unknown notebook(s) {unknown}; choose from "
                f"{sorted(NOTEBOOKS)}"
            )
        for nb_name in selected or NOTEBOOKS:
            subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--_execute_one", nb_name],
                check=True,
            )
    elif "--_execute_one" in sys.argv:
        execute(sys.argv[sys.argv.index("--_execute_one") + 1])
