"""LLM training with composed parallelism: dp x pp (or dp x tp x sp).

Development run on a virtual mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_llm_3d.py --mode pp --max_epochs 2

Modes:
- ``pp``: {'data': N/2, 'stage': 2} — the one-program shard_map GPipe
  pipeline (layer stack sharded over stage, ppermute hops, microbatched).
- ``tp_sp``: {'data': 2, 'seq': 2, 'model': N/4} — Megatron tensor split +
  ring-attention sequence parallelism, tokens sharded (B over data, S over
  seq).
"""

from __future__ import annotations

import argparse
import os
import sys

# runnable from a checkout without installation
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> None:
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--mode", choices=["pp", "tp_sp"], default="pp")
    parser.add_argument("--max_epochs", type=int, default=3)
    parser.add_argument("--batch_size", type=int, default=8,
                        help="per data-parallel device")
    args = parser.parse_args()

    import jax
    import optax
    from jax.sharding import PartitionSpec as P

    from pytorch_distributed_training_tutorials_tpu import create_mesh
    from pytorch_distributed_training_tutorials_tpu.data import ShardedLoader, synthetic_lm
    from pytorch_distributed_training_tutorials_tpu.models import (
        TP_RULES, TransformerConfig, TransformerLM,
    )
    from pytorch_distributed_training_tutorials_tpu.parallel import (
        PipelinedTransformerLM, PipelineParallel, TensorParallel,
        make_ring_attention,
    )
    from pytorch_distributed_training_tutorials_tpu.train import Trainer

    n = len(jax.devices())
    ds = synthetic_lm(size=512, seq_len=32, vocab_size=64)

    if args.mode == "pp":
        mesh = create_mesh({"data": max(n // 2, 1), "stage": 2})
        cfg = TransformerConfig(
            vocab_size=64, d_model=64, n_layers=4, n_heads=4,
            max_seq_len=64, scan_layers=True,
        )
        model = PipelinedTransformerLM(cfg, mesh, num_microbatches=2)
        strategy = PipelineParallel(mesh, num_microbatches=2)
        loader = ShardedLoader(ds, args.batch_size, mesh)
    else:
        mesh = create_mesh({"data": 2, "seq": 2, "model": -1})
        cfg = TransformerConfig(
            vocab_size=64, d_model=64, n_layers=4, n_heads=4,
            max_seq_len=64, attention_fn=make_ring_attention(mesh),
        )
        model = TransformerLM(cfg)
        strategy = TensorParallel(mesh, TP_RULES, seq_axis="seq")
        loader = ShardedLoader(
            ds, args.batch_size, mesh, batch_spec=P("data", "seq")
        )

    trainer = Trainer(
        model, loader, optax.adam(3e-3), strategy=strategy,
        loss="cross_entropy",
    )
    trainer.train(args.max_epochs)


if __name__ == "__main__":
    main()
