"""Flagship example: data-parallel ResNet-18 on MNIST (the BASELINE workload).

Runs on whatever is available — a TPU slice (`create_mesh()` takes every
chip), one chip, or a virtual CPU mesh for development:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_resnet_mnist.py --max_epochs 2

The reference's two flags keep their exact semantics (`--batch_size` is per
device, `ddp_gpus.py:101`); add `--fsdp` to shard params/optimizer over the
data axis instead of replicating (ZeRO-3), everything else unchanged.
"""

from __future__ import annotations

import argparse
import os
import sys

# runnable from a checkout without installation
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> None:
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--max_epochs", type=int, default=10)
    parser.add_argument(
        "--batch_size", type=int, default=32,
        help="Input batch size on each device (reference semantics)",
    )
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--fsdp", action="store_true",
                        help="shard params + optimizer state over data (ZeRO-3)")
    parser.add_argument("--ckpt", type=str, default=None,
                        help="checkpoint dir: resume if present, save per epoch")
    args = parser.parse_args()

    import jax.numpy as jnp
    import optax

    from pytorch_distributed_training_tutorials_tpu import create_mesh
    from pytorch_distributed_training_tutorials_tpu.data import DeviceResidentLoader, mnist
    from pytorch_distributed_training_tutorials_tpu.models import resnet18
    from pytorch_distributed_training_tutorials_tpu.parallel import FSDP
    from pytorch_distributed_training_tutorials_tpu.train import Trainer

    mesh = create_mesh()
    loader = DeviceResidentLoader(
        mnist("train", raw=True), args.batch_size, mesh, seed=0,
        transform=lambda x, y: (x.astype(jnp.bfloat16) / 255.0, y),
    )
    trainer = Trainer(
        resnet18(num_classes=10, stem="cifar", dtype=jnp.bfloat16),
        loader,
        optax.sgd(args.lr, momentum=0.9),
        strategy=FSDP(mesh) if args.fsdp else None,
        loss="cross_entropy",
    )
    if args.ckpt and os.path.exists(args.ckpt):
        trainer.restore(args.ckpt)
        print(f"resumed at epoch {trainer.epoch}")
    while trainer.epoch < args.max_epochs:
        trainer.train(trainer.epoch + 1)
        if args.ckpt:
            trainer.save(args.ckpt)

    test = DeviceResidentLoader(
        mnist("test", raw=True), args.batch_size, mesh, seed=0,
        transform=loader.transform,
    )
    print("eval:", trainer.evaluate(test))


if __name__ == "__main__":
    main()
