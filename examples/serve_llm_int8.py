"""Serve a billion-parameter LM int8-quantized from a streamed checkpoint.

The reference's flagship model-parallel demo loads Llama-7B with
``from_pretrained(..., BitsAndBytesConfig(load_in_8bit=True),
device_map="auto")`` — 33 float shards streamed through bitsandbytes into
int8 matmul weights + float norms (``/root/reference/03.model_parallel.ipynb``
cells 2-4). This example is that loop at reference scale, TPU-native:

1. materialize a synthetic f32 checkpoint of a ~1B-param Llama-style config
   on disk (written once, in layer-sized slabs so the full f32 model is
   never resident anywhere);
2. stream it back leaf-by-leaf through
   :func:`...models.transformer.load_quantized_lm` — each kernel is
   restored, quantized to int8 (+ per-column f32 scales), placed on device,
   and freed before the next leaf is read. Host peak stays one-leaf-bounded
   (reported via max RSS); device holds 1/4 the f32 bytes;
3. serve: batched-prefill + KV-cache generation through the Pallas int8
   MXU kernel, reporting decode tokens/s.

Run on the real chip::

    python examples/serve_llm_int8.py --preset 1b

``--preset toy`` runs the same loop at CPU-test scale (seconds);
``--tp N`` shards the int8 weights over a ``{'model': N}`` mesh
(INT8_TP_RULES / shard_map kernel) when N devices are available.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import resource
import sys
import time

# runnable from a checkout without installation
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def presets():
    from pytorch_distributed_training_tutorials_tpu.models import TransformerConfig

    return {
        # ~1.20B params (16 layers x 67.1M + 2 x 65.5M embed/head):
        # Llama-ish shape scaled to one v5e chip's HBM — f32 checkpoint
        # 4.8 GB on disk, int8+scales+norms ~1.4 GB resident
        "1b": TransformerConfig(
            vocab_size=32000, d_model=2048, n_layers=16, n_heads=16,
            d_ff=8192, max_seq_len=512,
        ),
        # the Llama-2/3 serving layout: 4 KV heads shared by 16 query
        # heads — k/v projections and the KV cache shrink 4x (GQA;
        # models/transformer.py n_kv_heads)
        "1b-gqa": TransformerConfig(
            vocab_size=32000, d_model=2048, n_layers=16, n_heads=16,
            n_kv_heads=4, d_ff=8192, max_seq_len=512,
        ),
        "toy": TransformerConfig(
            vocab_size=128, d_model=64, n_layers=2, n_heads=4,
            max_seq_len=64,
        ),
    }


def rss_gb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def count_params(cfg, abstract=None) -> int:
    """Schema-derived param count (no weights materialized) — the one
    definition shared by the checkpoint writer and the reuse receipt.
    Pass ``abstract`` (an eval_shape params tree) to skip re-tracing."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_training_tutorials_tpu.models import TransformerLM

    if abstract is None:
        abstract = jax.eval_shape(
            TransformerLM(cfg).init, jax.random.PRNGKey(0),
            jnp.zeros((1, 4), jnp.int32),
        )["params"]
    return sum(
        int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(abstract)
    )


def write_synthetic_checkpoint(cfg, path: str, seed: int = 0) -> int:
    """Materialize a random-init f32 checkpoint WITHOUT ever holding the
    full model: each top-level param subtree (one block ~67M params at the
    1b preset) is initialized on device, appended to the on-disk tree, and
    freed. Returns the total param count.

    (A real deployment starts from a trained checkpoint; the synthetic one
    exercises the identical IO/quantize path at identical byte counts —
    the reference's demo similarly never trains its Llama.)
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import orbax.checkpoint as ocp

    from pytorch_distributed_training_tutorials_tpu.models import TransformerLM

    model = TransformerLM(cfg)
    abstract = jax.eval_shape(
        model.init, jax.random.PRNGKey(seed), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    total = count_params(cfg, abstract)

    # init one top-level subtree at a time: eval_shape gives the schema,
    # real PRNG init would need the whole model — random normals at the
    # init scale are byte-identical work for the IO/quantize loop
    rng = np.random.Generator(np.random.PCG64(seed))
    if os.path.isdir(path):  # torn previous attempt: regenerate from clean
        import shutil

        shutil.rmtree(path)
    os.makedirs(path)
    with ocp.Checkpointer(ocp.PyTreeCheckpointHandler()) as ckptr:
        for name, sub in abstract.items():
            part = jax.tree_util.tree_map(
                lambda l: (rng.standard_normal(l.shape) * 0.02).astype(
                    np.float32
                ),
                sub,
            )
            # saved as {name: subtree} so restored key paths match the full
            # model's (load_quantized_lm keys quantization off 'parent/
            # kernel' paths — lm_head/kernel must keep its parent)
            ckptr.save(
                os.path.join(path, name),
                args=ocp.args.PyTreeSave({name: part}),
            )
            del part
    # marker = every subtree landed; reuse checks (an interrupted write
    # would otherwise look complete and poison every later run)
    with open(os.path.join(path, "COMPLETE"), "w") as f:
        f.write("ok\n")
    return total


def load_streamed(cfg, path: str, mesh):
    """Stream-quantize every top-level subtree checkpoint back into the
    int8 serving layout (placed per INT8_TP_RULES when ``mesh``).

    ``materialize=False`` per subtree: main() materializes the final
    assembled (and possibly stacked) tree in ONE pass instead of paying
    a jit trace + launch per subtree here."""
    from pytorch_distributed_training_tutorials_tpu.models.transformer import (
        load_quantized_lm,
    )

    params = {}
    for name in sorted(os.listdir(path)):
        if name == "COMPLETE":
            continue
        params.update(
            load_quantized_lm(
                os.path.join(path, name), mesh=mesh, materialize=False
            )
        )
    return params


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--preset", choices=("1b", "1b-gqa", "toy"), default="toy"
    )
    ap.add_argument("--tp", type=int, default=1,
                    help="model-axis width for sharded int8 serving")
    ap.add_argument("--ckpt_dir", default=None)
    ap.add_argument(
        "--hf_checkpoint", default=None, metavar="DIR",
        help="serve a published HF-layout Llama checkpoint (config.json "
        "+ *.safetensors) instead of the synthetic orbax one: streamed "
        "tensor-by-tensor and quantized on load (parallel.hf_llama) — "
        "the from_pretrained(load_in_8bit=True) path, offline",
    )
    ap.add_argument("--prompt_len", type=int, default=32)
    ap.add_argument("--new_tokens", type=int, default=32)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument(
        "--json", default=None, metavar="PATH",
        help="also write a machine-readable receipt (params, bytes, load "
        "time, decode tok/s) to PATH",
    )
    ap.add_argument(
        "--max_seq_len", type=int, default=None,
        help="serve with a different context window than the preset "
        "trained at — weights are window-agnostic (RoPE is computed, the "
        "KV cache is config-sized), so the same checkpoint serves any "
        "window",
    )
    ap.add_argument(
        "--temperature", type=float, default=0.0,
        help="0 = greedy (the receipt default); > 0 samples at this "
        "temperature (optionally filtered by --top_k / --top_p)",
    )
    ap.add_argument("--top_k", type=int, default=0,
                    help="keep only the k highest logits when sampling")
    ap.add_argument("--top_p", type=float, default=1.0,
                    help="nucleus sampling mass when sampling")
    ap.add_argument(
        "--kv_cache_dtype", choices=("f32", "bf16", "int8"), default="f32",
        help="KV-cache storage dtype: bf16 halves per-step cache traffic, "
        "int8 quarters it (per-token absmax scales stored alongside) — "
        "decode at long windows is cache-bound (DECODE_r04.md); reduced "
        "dtypes round stored K/V, so greedy tokens can diverge at "
        "near-ties (int8 more than bf16)",
    )
    ap.add_argument(
        "--flash", action="store_true",
        help="prefill through the Pallas flash-attention kernel "
        "(ops.flash_attention) instead of dense causal attention — "
        "sub-quadratic attention temp memory; the long-prompt path "
        "(FLASH_r04.md). Decode always uses the cached dense path.",
    )
    ap.add_argument(
        "--server", action="store_true",
        help="serve a REQUEST STREAM through the continuous-batching "
        "engine (serve.ServeEngine: slot-indexed KV cache, chained "
        "decode launches) instead of the one-shot batch generate leg — "
        "the receipt gains p50/p95 per-request latency and aggregate "
        "tok/s over mixed prompt lengths",
    )
    ap.add_argument("--requests", type=int, default=12,
                    help="request count for the --server stream")
    ap.add_argument("--slots", type=int, default=4,
                    help="concurrent cache slots for --server")
    ap.add_argument(
        "--tokens_per_launch", type=int, default=8,
        help="decode chain length per dispatch for --server (the launch "
        "floor is per DISPATCH — longer chains amortize it)",
    )
    ap.add_argument(
        "--prefix-overlap", type=float, default=0.0, dest="prefix_overlap",
        help="for --server: fraction [0..1] of each prompt drawn from one "
        "shared prefix family (the rest is a per-request random tail) — "
        "synthesizes the shared-system-prompt workload the radix prefix "
        "cache (serve.PrefixIndex) targets; the receipt gains hit rate, "
        "splice counts, and TTFT p50/p95",
    )
    ap.add_argument(
        "--prefix-cache-mb", type=int, default=None, dest="prefix_cache_mb",
        help="prefix-cache byte budget in MiB for --server (0 disables; "
        "default: 512 when --prefix-overlap > 0, else 0)",
    )
    ap.add_argument(
        "--spec-k", type=int, default=0, dest="spec_k",
        help="for --server: self-speculative decoding with k n-gram "
        "draft tokens per verify step (serve.ServeEngine speculative_k; "
        "0 disables). Greedy output is token-identical either way; the "
        "win — fewer sequential decode steps per token — shows on "
        "templated/repetitive streams, so pair with --prefix-overlap. "
        "The receipt gains acceptance-rate/verify-forward counters",
    )
    ap.add_argument(
        "--spec-ngram", type=int, default=3, dest="spec_ngram",
        help="suffix length the n-gram draft matches on (--spec-k)",
    )
    ap.add_argument(
        "--adapters", type=int, default=0,
        help="for --server: serve a MULTI-TENANT stream through an N-row "
        "LoRA adapter bank (adapters.AdapterBank; 0 disables). Rows "
        "1..N-1 are registered as synthetic tenants and requests cycle "
        "through all ids (0 = base model) — heterogeneous tenants "
        "co-batch in the one compiled decode program; the receipt gains "
        "bank geometry and per-tenant traffic counters",
    )
    ap.add_argument(
        "--lora-rank", type=int, default=8, dest="lora_rank",
        help="LoRA rank of the adapter bank rows (--adapters)",
    )
    ap.add_argument(
        "--deadline-s", type=float, default=None, dest="deadline_s",
        help="for --server: per-request deadline in seconds "
        "(serve.ServeEngine default_deadline_s; None disables). Expired "
        "requests complete finish_reason='deadline' at the next chain "
        "boundary keeping the tokens they earned — the receipt gains "
        "fault_stats() counters (deadline_expired, cancelled, "
        "nonfinite_quarantined)",
    )
    ap.add_argument(
        "--flight-log", default=None, dest="flight_log",
        help="for --server: write graft-flightlog/v1 snapshots (fault "
        "auto-dumps + one end-of-stream dump) to this JSONL path; render "
        "with scripts/flight_view.py. The recorder itself is always on "
        "for --server (host-only, zero extra device fetches) — this "
        "flag only adds the on-disk dump",
    )
    ap.add_argument(
        "--no-sentry", action="store_true", dest="no_sentry",
        help="for --server: disable the runtime contract sentry "
        "(ISSUE 19). On by default — host-only counters watching the "
        "zero-steady-recompile, fetch-budget, and no-host-numpy "
        "contracts at runtime; a violation auto-dumps a flight "
        "snapshot and the receipt carries sentry_* fields. regress.py "
        "fingerprints `sentry`, so bare and instrumented rounds never "
        "gate each other",
    )
    ap.add_argument(
        "--pipeline-depth", type=int, default=1, dest="pipeline_depth",
        help="for --server: decode chains kept in flight before the host "
        "fetches the oldest (serve.ServeEngine pipeline_depth; 1 = "
        "serial, today's loop). Depth 2 dispatches chain i+1 before "
        "fetching chain i, hiding the per-launch roundtrip — on "
        "launch-bound runtimes the whole win, tokens byte-identical",
    )
    ap.add_argument(
        "--prefill-chunk", type=int, default=0, dest="prefill_chunk",
        help="for --server: prefill long prompts in bounded chunks of "
        "this many tokens interleaved with decode chains (pow2 >= 8; 0 "
        "disables) — caps the decode stall any single long prompt can "
        "inject between chains",
    )
    ap.add_argument(
        "--paged", action="store_true",
        help="for --server: paged KV cache (ISSUE 13) — the slot caches "
        "become one shared page pool + per-slot page tables, admission "
        "counts PAGES not slots, and prefix hits pin shared pages "
        "copy-free. The receipt gains hbm_high_water_bytes (the honest "
        "peak pool claim) and the pages_* counters. Real-chip recipe "
        "(deferred tunnel debt): --preset 1b --max_seq_len 4096 "
        "--server --paged",
    )
    ap.add_argument(
        "--page-size", type=int, default=64, dest="page_size",
        help="for --server --paged: tokens per KV page (must divide "
        "max_seq_len)",
    )
    ap.add_argument(
        "--pool-pages", type=int, default=0, dest="pool_pages",
        help="for --server --paged: pages in the pool; 0 (default) "
        "sizes it to slots * window / page_size — the whole-slot HBM "
        "footprint. Set it LOWER to oversubscribe slots against HBM "
        "(requests queue for pages; ones that can never fit shed at "
        "submit)",
    )
    ap.add_argument(
        "--kv-bits", type=int, choices=(8, 4), default=None, dest="kv_bits",
        help="quantized KV storage width (ISSUE 17): 8 = int8 + f32 "
        "scales (same as --kv_cache_dtype int8), 4 = packed-nibble "
        "uint8 + bf16 scales — EXACTLY half int8's bytes per "
        "token-head, so a paged pool fits 2x the pages at fixed HBM. "
        "Replaces --kv_cache_dtype (pass only one). Reduced dtypes "
        "round stored K/V, so greedy tokens can diverge at near-ties "
        "(int4 more than int8)",
    )
    ap.add_argument(
        "--paged-kernel", action="store_true", dest="paged_kernel",
        help="for --server --paged: decode attention through the fused "
        "Pallas page-walk kernel (ops.paged_attention) instead of the "
        "jnp.take gather — pages stream through an online-softmax "
        "accumulator, no dense (slots, window, ...) KV window is ever "
        "materialized. Engine-static (never per request); the gather "
        "path stays the numerics oracle",
    )
    ap.add_argument(
        "--replicas", type=int, default=1,
        help="for --server: serve through a FleetRouter over N replica "
        "engines (N KV-cache footprints in HBM — the same checkpoint "
        "params are shared). 1 (default) keeps the plain single-engine "
        "arm byte-for-byte; >1 adds fleet receipt fields (exactly-once "
        "ledger, health states, merged flight histograms)",
    )
    ap.add_argument(
        "--qps", type=float, default=0.0,
        help="for --server: offered load in requests/s — an "
        "OPEN-loop Poisson arrival process (seeded exponential "
        "inter-arrivals; QueueFull arrivals are shed and counted, the "
        "honest overload behavior). 0 (default) submits the whole "
        "stream up front (the closed-loop burst the single-engine arm "
        "uses)",
    )
    ap.add_argument(
        "--hedge-after", type=float, default=None, dest="hedge_after",
        help="for --server --replicas: duplicate a request stuck on a "
        "SUSPECT replica after this many seconds (first completion "
        "wins, the loser is cancelled and absorbed); default off",
    )
    ap.add_argument(
        "--disaggregate", default=None, metavar="NpMd",
        help="for --server: prefill/decode-disaggregated fleet (ISSUE "
        "18), e.g. 1p2d = one prefill-specialized replica (admission + "
        "bucketed/chunked prefill + the prefix cache) feeding two "
        "decode-specialized replicas (slots, speculation, paged pool) "
        "through device-side KV handoffs routed by the FleetRouter. "
        "Overrides --replicas; the receipt gains "
        "n_prefill/n_decode_replicas + handoffs_moved, and the "
        "interesting fields are ttft_p95 under mixed traffic and "
        "ledger_ok (exactly-once across the transfer)",
    )
    ap.add_argument(
        "--slo", action="store_true",
        help="for --server: two SLO priority classes (ISSUE 20) — every "
        "4th request submits class 0 (interactive), the rest class 1 "
        "(batch). When a class-0 arrival finds all slots busy, the "
        "engine preempts the lowest-class active slot at the chain "
        "boundary (its KV segment swaps to host and later resumes "
        "token-exact); the receipt gains slo_stats() (n_preemptions, "
        "swap counters) and the preempt_wait histogram. Pair with "
        "--qps so arrivals are spaced — an up-front burst is drained "
        "in strict class order and never needs to preempt. "
        "Single-engine arm only",
    )
    ap.add_argument(
        "--unrolled", action="store_true",
        help="serve with L unrolled block copies instead of the default "
        "stacked nn.scan body (the unrolled program is O(L) larger; on "
        "tunneled runtimes whose launch latency scales with program size "
        "it decodes ~an order of magnitude slower — see "
        "models.transformer.stack_quantized_lm_params)",
    )
    args = ap.parse_args()

    if args.slo and (args.replicas > 1 or args.disaggregate):
        # preemption swaps are a single-engine contract (the engine
        # forbids role= + priority_classes; a fleet would also need
        # class-aware routing the router spells class_deadline_s /
        # per-class hedge_after_s) — keep the receipt arm honest
        ap.error("--slo is the single-engine arm (ISSUE 20); drop "
                 "--replicas/--disaggregate")

    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_training_tutorials_tpu.models import TransformerLM
    from pytorch_distributed_training_tutorials_tpu.models.generate import generate
    from pytorch_distributed_training_tutorials_tpu.parallel.mesh import create_mesh

    cfg = presets()[args.preset]
    if args.hf_checkpoint:
        from pytorch_distributed_training_tutorials_tpu.parallel.hf_llama import (
            config_from_hf,
        )

        cfg = config_from_hf(args.hf_checkpoint)
    if args.max_seq_len is not None:
        # params are window-agnostic: only the cache shapes and the RoPE
        # offsets derive from max_seq_len, so the same checkpoint serves
        # any window (the generate() window trim still applies per request)
        cfg = dataclasses.replace(cfg, max_seq_len=args.max_seq_len)
    if args.flash:
        from pytorch_distributed_training_tutorials_tpu.ops import flash_attention

        cfg = dataclasses.replace(cfg, attention_fn=flash_attention)
    if args.kv_cache_dtype != "f32":
        import jax.numpy as _jnp

        cfg = dataclasses.replace(
            cfg,
            kv_cache_dtype=(
                _jnp.bfloat16 if args.kv_cache_dtype == "bf16" else _jnp.int8
            ),
        )
    if args.kv_bits is not None:
        # --kv-bits is the ISSUE 17 spelling of quantized KV storage
        # (8 = the int8 family above, 4 = packed nibbles + bf16 scales);
        # it sets the SAME cfg field, so passing both is ambiguous
        if args.kv_cache_dtype != "f32":
            ap.error("--kv-bits replaces --kv_cache_dtype; pass only one")
        import jax.numpy as _jnp

        cfg = dataclasses.replace(
            cfg,
            kv_cache_dtype="int4" if args.kv_bits == 4 else _jnp.int8,
        )
    if args.paged_kernel and not args.paged:
        ap.error("--paged-kernel requires --server --paged")
    ckpt = args.ckpt_dir or os.path.join(
        os.environ.get("TMPDIR", "/tmp"), f"llm_int8_{args.preset}"
    )

    mesh = None
    if args.tp > 1:
        mesh = create_mesh({"model": args.tp})

    t0 = time.perf_counter()
    # kv_bits/paged_kernel ride every receipt (0/False off) — regress.py
    # fingerprints them so int4/kernel rounds never gate int8/gather ones
    receipt = {
        "preset": args.preset, "tp": args.tp,
        "kv_bits": args.kv_bits or 0,
        "paged_kernel": bool(args.paged_kernel),
    }
    if args.hf_checkpoint:
        receipt["hf_checkpoint"] = os.path.abspath(args.hf_checkpoint)
        receipt["preset"] = "hf"
        n_params = count_params(cfg)
        receipt["n_params"] = n_params
        receipt["checkpoint_gb_f32"] = round(4 * n_params / 1e9, 2)
        print(f"checkpoint: HF layout at {args.hf_checkpoint} "
              f"({n_params/1e9:.2f}B params)")
    elif not os.path.isfile(os.path.join(ckpt, "COMPLETE")):
        n_params = write_synthetic_checkpoint(cfg, ckpt)
        receipt["n_params"] = n_params
        receipt["checkpoint_gb_f32"] = round(4 * n_params / 1e9, 2)
        receipt["checkpoint_write_s"] = round(time.perf_counter() - t0, 1)
        print(
            f"checkpoint: wrote {n_params/1e9:.2f}B params "
            f"({4*n_params/1e9:.1f} GB f32) to {ckpt} "
            f"in {time.perf_counter()-t0:.0f}s, peak RSS {rss_gb():.1f} GB"
        )
    else:
        # reuse: still report the checkpoint facts (schema-derived, cheap)
        n_params = count_params(cfg)
        receipt["n_params"] = n_params
        receipt["checkpoint_gb_f32"] = round(4 * n_params / 1e9, 2)
        receipt["checkpoint_reused"] = True
        print(f"checkpoint: reusing {ckpt}")

    scan_layers = not args.unrolled
    rss_before = rss_gb()
    t0 = time.perf_counter()
    if args.hf_checkpoint:
        from pytorch_distributed_training_tutorials_tpu.parallel.hf_llama import (
            load_hf_llama,
        )

        if mesh is not None and not scan_layers:
            raise SystemExit(
                "--hf_checkpoint with --tp requires the scanned layout "
                "(drop --unrolled): tensor-parallel placement of HF "
                "weights runs through place_int8_lm_params on the "
                "stacked tree"
            )
        # materialize=False: main() device-materializes ONCE after
        # placement below, same as the orbax path
        _, params = load_hf_llama(
            args.hf_checkpoint, cfg=cfg, quantize=True,
            scan_layers=scan_layers, materialize=False,
        )
    else:
        params = load_streamed(cfg, ckpt, mesh)
    n_bytes = sum(
        l.size * l.dtype.itemsize
        for l in jax.tree_util.tree_leaves(params)
    )
    # graftcheck: disable=naive-timing -- loader timing is informational:
    # restored leaves land host-side (numpy) and the device materialization
    # they feed is timed separately by the decode legs, which fetch
    load_s = time.perf_counter() - t0
    f32_gb = 4 * sum(
        l.size for l in jax.tree_util.tree_leaves(params)
        if l.dtype == jnp.int8
    ) / 1e9
    receipt.update(
        load_s=round(load_s, 1),
        resident_gb=round(n_bytes / 1e9, 2),
        f32_equivalent_gb=round(f32_gb, 2),
        peak_rss_gb=round(rss_gb(), 2),
        rss_before_load_gb=round(rss_before, 2),
    )
    print(
        f"load: streamed+quantized in {load_s:.0f}s — resident "
        f"{n_bytes/1e9:.2f} GB (int8+scales+float norms), peak RSS "
        f"{rss_gb():.1f} GB (was {rss_before:.1f} before load; the full "
        f"f32 tree would be {f32_gb:.1f} GB)"
    )

    if scan_layers:
        # one scanned block body instead of n_layers unrolled copies:
        # O(1) program size in depth. On this tunneled runtime the
        # unrolled 16-layer decode paid ~20-50 s PER LAUNCH (~0.14 s of
        # device work, trace-verified) — program size is serving latency.
        from pytorch_distributed_training_tutorials_tpu.models.transformer import (
            stack_quantized_lm_params,
        )

        if not args.hf_checkpoint:  # the HF loader stacked already
            params = stack_quantized_lm_params(params)
        if mesh is not None:
            from pytorch_distributed_training_tutorials_tpu.models.transformer import (
                place_int8_lm_params,
            )

            params = place_int8_lm_params(params, mesh)
    # ONE device-materialize pass over the final tree: loaded (host-put)
    # buffers re-stream through the tunnel on every consuming launch until
    # rewritten as device-computed buffers (DECODE_r04.md: 2.7 -> 508
    # tok/s), and doing it here — after stacking/placement — avoids
    # re-materializing per subtree or materializing buffers stacking
    # replaces
    from pytorch_distributed_training_tutorials_tpu.utils.tree import (
        device_materialize,
    )

    params = device_materialize(params)
    serve_cfg = dataclasses.replace(
        cfg, quantized=True, int8_mesh=mesh, scan_layers=scan_layers
    )
    lm = TransformerLM(serve_cfg)
    receipt["scan_layers"] = scan_layers
    rng = np.random.Generator(np.random.PCG64(7))
    prompt = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (args.batch, args.prompt_len)),
        jnp.int32,
    )

    sample_kw = {}
    if args.temperature > 0:
        import jax as _jax

        sample_kw = dict(
            temperature=args.temperature, top_k=args.top_k,
            top_p=args.top_p, rng=_jax.random.PRNGKey(7),
        )

    # prime the process's first D2H fetch OUTSIDE any timed region (the
    # ~19 s tunnel stall would otherwise be charged to compile_s)
    int(jnp.zeros((), jnp.int32) + 1)
    if args.server:
        if args.replicas > 1 or args.disaggregate:
            serve_fleet_stream(args, cfg, lm, params, receipt)
        else:
            serve_request_stream(args, cfg, lm, params, receipt)
        if args.json:
            from pytorch_distributed_training_tutorials_tpu.obs import (
                make_receipt,
                write_receipt,
            )

            write_receipt(args.json, make_receipt("serving", receipt))
            print(f"receipt -> {args.json}")
        return
    t0 = time.perf_counter()
    out = generate(lm, params, prompt, args.new_tokens, **sample_kw)
    int(out[0, -1])  # close the region with a real fetch
    compile_s = time.perf_counter() - t0
    # min-of-2 via obs.timing.MinOfN: individual launches on the tunneled
    # runtime suffer rare multi-tens-of-seconds stalls (CLAUDE.md;
    # observed here: the same compiled generate measured 47 s in one run
    # and 14.5 s in the next — a 3.3x swing that is tunnel weather, not
    # the kernel). All samples are reported so the receipt shows its own
    # spread; MinOfN additionally flags samples > 5x median as stalls.
    from pytorch_distributed_training_tutorials_tpu.obs import MinOfN

    holder = {"out": out}

    def run_gen():
        holder["out"] = generate(
            lm, params, prompt, args.new_tokens, **sample_kw
        )
        # close the timed region with a one-element D2H —
        # block_until_ready alone under-reports on the tunneled runtime
        int(holder["out"][0, -1])

    timing = MinOfN(n=2, warmup=False).measure(run_gen)
    out = holder["out"]
    gen_samples = timing.samples_s
    gen_s = timing.best_s
    toks = args.batch * args.new_tokens
    receipt.update(
        batch=args.batch,
        prompt_len=args.prompt_len,
        new_tokens=args.new_tokens,
        max_seq_len=cfg.max_seq_len,
        flash_prefill=bool(args.flash),
        kv_cache_dtype=args.kv_cache_dtype,
        # a sampled run's decode_tok_per_s is not comparable to the greedy
        # headline — make every receipt self-describing
        temperature=args.temperature,
        **(
            dict(top_k=args.top_k, top_p=args.top_p)
            if args.temperature > 0
            else {}
        ),
        decode_tok_per_s=round(toks / gen_s, 1),
        decode_s_samples=[round(s, 2) for s in gen_samples],
        decode_stalled_samples=timing.n_stalled,
        first_call_incl_compile_s=round(compile_s, 1),
        backend=jax.default_backend(),
    )
    print(
        f"serve: {args.batch}x({args.prompt_len} prompt + "
        f"{args.new_tokens} new) in {gen_s:.2f}s "
        f"({toks/gen_s:.1f} tok/s; first call incl. compile {compile_s:.0f}s)"
    )
    print("sample:", np.asarray(out[0, args.prompt_len:args.prompt_len+12]))
    if args.json:
        from pytorch_distributed_training_tutorials_tpu.obs import (
            make_receipt,
            write_receipt,
        )

        # schema'd envelope: git sha / jax version / device stamp ride
        # with every SERVING_rXX.json so receipts stay self-describing
        write_receipt(args.json, make_receipt("serving", receipt))
        print(f"receipt -> {args.json}")


def _reset_serving_counters(engine) -> None:
    """Zero the engine's traffic counters after the compile warmup so
    the timed stream's receipt measures serving, not tracing."""
    engine.n_chains = engine.n_prefills = engine.generated_tokens = 0
    engine.n_splices = engine.prefix_hit_tokens = 0
    engine.n_verify_forwards = engine.spec_steps_consumed = 0
    engine.spec_drafts_accepted = 0
    engine.adapter_requests = 0
    engine.n_deadline_expired = engine.n_cancelled = 0
    engine.nonfinite_quarantined = engine.n_prefill_errors = 0
    engine.n_chunks = 0
    engine.n_handoffs_out = engine.n_handoffs_in = 0
    if hasattr(engine, "n_swaps_out"):
        # SLO engines only (priority-off engines don't grow the attrs)
        engine.n_swaps_out = engine.n_swaps_in = 0
    if engine.prefix is not None:
        engine.prefix.hits = engine.prefix.misses = 0


def _serving_strategy(lm):
    """TensorParallel strategy for the ``--server`` engines when the
    model carries a TP mesh (ISSUE 15): the slot/KV state shards
    head-wise with the int8 Megatron split the params already use, so
    each chip holds 1/tp of the cache and the decode chain's only
    collectives are the forward's existing all-reduces. None (the
    replicated engine, byte-identical off-path) without a model axis."""
    mesh = getattr(lm.cfg, "int8_mesh", None)
    if mesh is None or mesh.shape.get("model", 1) <= 1:
        return None
    from pytorch_distributed_training_tutorials_tpu.models.transformer import (
        INT8_TP_RULES,
    )
    from pytorch_distributed_training_tutorials_tpu.parallel import (
        TensorParallel,
    )

    return TensorParallel(mesh, INT8_TP_RULES)


def _parse_disaggregate(spec: str) -> tuple[int, int]:
    """``"1p2d"`` -> ``(1, 2)``: the role geometry of a disaggregated
    fleet (ISSUE 18). Both counts must be >= 1 — a fleet missing either
    role can never complete a request."""
    import re

    m = re.fullmatch(r"(\d+)p(\d+)d", spec)
    if not m or int(m.group(1)) < 1 or int(m.group(2)) < 1:
        raise SystemExit(
            f"--disaggregate wants NpMd with N,M >= 1 (e.g. 1p2d), "
            f"got {spec!r}"
        )
    return int(m.group(1)), int(m.group(2))


def _paged_kwargs(args, window: int) -> dict:
    """ServeEngine paged-geometry kwargs from the CLI flags. --pool-pages
    0 sizes the pool to the whole-slot footprint (slots * window worth of
    pages) — same HBM, page-granular accounting; a smaller explicit pool
    oversubscribes slots against HBM."""
    if not args.paged:
        return {}
    pool = args.pool_pages or args.slots * window // args.page_size
    return dict(
        paged=True, page_size=args.page_size, pool_pages=pool,
        paged_kernel=bool(args.paged_kernel),
    )


def serve_fleet_stream(args, cfg, lm, params, receipt: dict) -> None:
    """The ``--server --replicas N`` leg (ISSUE 12): the same request
    stream through a :class:`...serve.FleetRouter` over N replica
    engines sharing one checkpoint's params (N KV-cache footprints in
    HBM — tenants-per-chip economics, but for whole replicas).

    ``--disaggregate NpMd`` (ISSUE 18) builds a ROLE-split fleet
    instead: N prefill-specialized replicas (prefix cache + chunked
    prefill, no decode machinery) and M decode-specialized replicas
    (spec/paged/pipelining, no prefix cache) joined by the router's
    device-side KV handoff — ``--replicas`` is ignored in that mode and
    the interesting receipt fields become ``ttft_p95`` under mixed
    traffic, ``handoffs_moved`` (== completed requests), and
    ``ledger_ok``.

    ``--qps`` makes the stream OPEN loop: Poisson arrivals from a
    seeded exponential inter-arrival process, submitted at their
    arrival instants regardless of completion progress; a ``QueueFull``
    arrival (every replica saturated) is SHED and counted — the honest
    overload behavior, vs a closed loop that politely self-throttles.
    ``--qps 0`` submits everything up front (the single-engine arm's
    burst).

    Every replica carries its own flight recorder on ONE shared t0, so
    the receipt's percentiles come from the bucket-wise MERGED
    histograms (``FleetRouter.stats``) — summing per-replica p95s would
    be meaningless — and ``--flight-log`` writes the merged
    ``graft-flightlog/v1`` snapshot (``dump_fleet``), which
    scripts/flight_view.py renders with ``replica=i`` tags and
    ``[dead]``/``[draining]`` health annotations."""
    import jax
    import numpy as np

    from pytorch_distributed_training_tutorials_tpu.obs import FlightRecorder
    from pytorch_distributed_training_tutorials_tpu.serve import (
        FleetRouter,
        QueueFull,
        Request,
        ServeEngine,
    )

    window = int(cfg.max_seq_len)
    new = args.new_tokens
    lengths = sorted(
        {
            max(1, args.prompt_len // 2),
            min(args.prompt_len, window - new),
            min(args.prompt_len + args.prompt_len // 2, window - new),
        }
    )
    cache_mb = args.prefix_cache_mb
    if cache_mb is None:
        cache_mb = 512 if args.prefix_overlap > 0 else 0

    def mk_bank():
        # per-replica banks with IDENTICAL tenants (deterministic
        # seeds), so a re-dispatched tenant request decodes under the
        # same factors wherever it lands
        if not args.adapters:
            return None
        from pytorch_distributed_training_tutorials_tpu.adapters import AdapterBank

        bank = AdapterBank(lm, n_adapters=args.adapters,
                           rank=args.lora_rank)
        frng = np.random.Generator(np.random.PCG64(13))
        for aid in range(1, args.adapters):
            bank.register(
                f"tenant-{aid}",
                jax.tree_util.tree_map(
                    lambda leaf: (
                        frng.standard_normal(leaf.shape) * 0.02
                    ).astype(np.float32),
                    bank.row_zeros(),
                ),
            )
        return bank

    t0 = time.perf_counter()
    n_pre, n_dec = (
        _parse_disaggregate(args.disaggregate)
        if args.disaggregate else (0, 0)
    )

    # contract sentry (ISSUE 19): ONE sentry shared by every replica —
    # compile/fetch hooks are process-global, and FleetRouter.stats()
    # dedupes the shared instance by identity instead of summing it N
    # times. It stamps into the ROUTER's recorder so violations land in
    # the merged fleet dump. --no-sentry reverts to the bare fleet.
    router_flight = FlightRecorder(capacity=4096, t0=t0)
    sentry = None
    if not args.no_sentry:
        from pytorch_distributed_training_tutorials_tpu.obs import ContractSentry

        sentry = ContractSentry(flight=router_flight).install()

    def mk_engine(role: str | None = None) -> ServeEngine:
        kw = dict(
            n_slots=args.slots,
            tokens_per_launch=args.tokens_per_launch,
            max_queue=max(64, args.requests),
            temperature=args.temperature,
            top_k=args.top_k,
            top_p=args.top_p,
            prefix_cache_bytes=cache_mb * 1024 * 1024,
            speculative_k=args.spec_k,
            spec_ngram=args.spec_ngram,
            adapter_bank=mk_bank(),
            default_deadline_s=args.deadline_s,
            pipeline_depth=args.pipeline_depth,
            prefill_chunk=args.prefill_chunk,
            flight=FlightRecorder(capacity=4096, t0=t0),
            sentry=sentry,
            strategy=_serving_strategy(lm),
            **_paged_kwargs(args, window),
        )
        if role == "prefill":
            # the prefill specialist keeps the prefix cache + chunked
            # prefill (its whole job) and sheds decode-side machinery —
            # spec/pipelining/paged pools never run on this replica
            kw.update(role="prefill", speculative_k=0, pipeline_depth=1)
            for k in ("paged", "page_size", "pool_pages", "paged_kernel"):
                kw.pop(k, None)
        elif role == "decode":
            # the decode specialist keeps spec/paged/pipelining and
            # sheds the prefix cache + chunking (prefill-side work it
            # never performs)
            kw.update(role="decode", prefix_cache_bytes=0,
                      prefill_chunk=0)
        return ServeEngine(lm, params, **kw)

    if args.disaggregate:
        engines = ([mk_engine("prefill") for _ in range(n_pre)]
                   + [mk_engine("decode") for _ in range(n_dec)])
    else:
        engines = [mk_engine() for _ in range(args.replicas)]
    if args.tp > 1:
        # homogeneous fleet: one replica's compiled chain speaks for all
        # (FleetRouter.stats passes the tp_* config keys through); in a
        # disaggregated fleet the decode role owns the chain, so audit
        # the first decode replica
        engines[n_pre if args.disaggregate else 0].audit_decode_hlo()
    router = FleetRouter(
        engines,
        hedge_after_s=args.hedge_after,
        flight=router_flight,
    )
    rng = np.random.Generator(np.random.PCG64(11))
    shared = rng.integers(0, cfg.vocab_size, (max(lengths),)).tolist()

    def mk_request(i: int, deadline_s: float | None = None) -> Request:
        p_len = lengths[i % len(lengths)]
        k = min(p_len, int(round(args.prefix_overlap * p_len)))
        tail = rng.integers(0, cfg.vocab_size, (p_len - k,)).tolist()
        return Request(
            prompt=shared[:k] + tail, max_new_tokens=new, seed=i,
            deadline_s=deadline_s,
            adapter=(i % args.adapters) if args.adapters else 0,
        )

    # compile warmup: the replicas share one set of jitted programs ONLY
    # per engine object, so every replica prefills each prompt bucket
    # once before the timed stream (same compile/serve split as the
    # single-engine arm, N times over)
    t_compile = time.perf_counter()
    warm_dl = 1e9 if args.deadline_s is not None else None
    if args.disaggregate:
        # role warmup drives the handoff path directly (prefill ->
        # take_handoff -> decode accept), so each prefill replica
        # compiles every prompt bucket and each decode replica compiles
        # its accept splice + chain before the timed stream
        import dataclasses

        pre, dec = engines[:n_pre], engines[n_pre:]
        for j in range(max(n_pre, n_dec)):
            pe, de = pre[j % n_pre], dec[j % n_dec]
            for i in range(len(lengths)):
                req = mk_request(i, deadline_s=warm_dl)
                rid = pe.submit(dataclasses.replace(req))
                pe.run_until_idle()
                de.accept(req, pe.take_handoff(rid))
            de.run_until_idle()
    else:
        for eng in engines:
            for i in range(len(lengths)):
                eng.submit(mk_request(i, deadline_s=warm_dl))
            eng.run_until_idle()
    compile_s = time.perf_counter() - t_compile
    for eng in engines:
        _reset_serving_counters(eng)
        eng._flight.reset()
    router.n_handoffs_moved = 0
    router._flight.reset()
    if sentry is not None:
        # same seam as the recorder resets: warmup compiles were legal,
        # anything past here is a steady-state violation
        sentry.mark_steady()

    # open-loop Poisson arrivals (qps > 0) or the up-front burst (0)
    arng = np.random.Generator(np.random.PCG64(17))
    t_arr = 0.0
    arrivals = []
    for _ in range(args.requests):
        if args.qps > 0:
            t_arr += float(arng.exponential(1.0 / args.qps))
        arrivals.append(t_arr)

    shed = 0
    next_i = 0
    t_start = time.perf_counter()
    while next_i < len(arrivals):
        due = t_start + arrivals[next_i]
        if time.perf_counter() >= due:
            try:
                router.submit(mk_request(len(lengths) + next_i))
            except QueueFull:
                shed += 1  # overload: shed at the door, keep serving
            next_i += 1
            continue
        if router.idle:
            time.sleep(min(0.001, max(0.0, due - time.perf_counter())))
        else:
            router.step()
    router.run_until_idle()
    for eng in engines:
        # close the timed region with a real fetch per replica
        jax.device_get(eng._state["remaining"])
    wall_s = time.perf_counter() - t_start

    rstats = router.stats()
    if sentry is not None:
        sentry.uninstall()
    toks = sum(e.generated_tokens for e in engines)
    receipt.update(
        server=True,
        server_requests=args.requests,
        server_slots=args.slots,
        tokens_per_launch=args.tokens_per_launch,
        server_prompt_lengths=lengths,
        new_tokens=new,
        max_seq_len=window,
        temperature=args.temperature,
        qps=args.qps,
        server_shed=shed,
        server_wall_s=round(wall_s, 2),
        server_tok_per_s=round(toks / wall_s, 1),
        server_generated_tokens=toks,
        server_chains=sum(e.n_chains for e in engines),
        server_prefills=sum(e.n_prefills for e in engines),
        server_handoffs=sum(
            getattr(e, "n_handoffs_in", 0) for e in engines
        ),
        server_p50_latency_s=round(rstats.get("e2e_p50_s", 0.0), 3),
        server_p95_latency_s=round(rstats.get("e2e_p95_s", 0.0), 3),
        server_ttft_p50_s=round(rstats.get("ttft_p50_s", 0.0), 3),
        server_ttft_p95_s=round(rstats.get("ttft_p95_s", 0.0), 3),
        server_compile_s=round(compile_s, 1),
        prefix_overlap=args.prefix_overlap,
        prefix_cache_mb=cache_mb,
        **rstats,
        backend=jax.default_backend(),
    )
    ledger_problems = router.ledger.verify()
    receipt["ledger_ok"] = not ledger_problems
    if ledger_problems:
        receipt["ledger_problems"] = ledger_problems
    if args.flight_log:
        router.dump_fleet(args.flight_log, reason="end_of_stream")
        print(f"fleet flight log -> {args.flight_log}")
    geometry = (
        f"{n_pre}p+{n_dec}d role replicas" if args.disaggregate
        else f"{args.replicas} replicas"
    )
    print(
        f"fleet: {args.requests} requests over {geometry} "
        f"x {args.slots} slots in {wall_s:.2f}s — {toks / wall_s:.1f} "
        f"tok/s aggregate, qps {args.qps or 'burst'} ({shed} shed), "
        f"p95 {receipt['server_p95_latency_s']}s, ttft p95 "
        f"{receipt['server_ttft_p95_s']}s, states "
        f"{router.replica_states()}, {rstats['redispatched']} "
        f"re-dispatched, {rstats['hedged']} hedged "
        f"(compile {compile_s:.0f}s)"
    )


def serve_request_stream(args, cfg, lm, params, receipt: dict) -> None:
    """The ``--server`` leg: a staggered stream of mixed-prompt-length
    requests through :class:`...serve.ServeEngine` — the continuous-
    batching arm of the serving receipt.

    Reports p50/p95 per-request latency (submit to completion; every
    completion's tokens come off a fetched chain block, so latencies are
    fetch-backed, not async mirages) and aggregate generated tok/s.
    Compile happens on a warmup request per prompt bucket BEFORE the
    timed stream, mirroring the one-shot leg's compile/serve split.

    ``--prefix-overlap r`` draws the first ``round(r * p_len)`` tokens of
    every prompt from ONE shared token family (the shared-system-prompt
    workload), so the radix prefix cache (serve.PrefixIndex) can retain
    and splice it; the warmup stream uses the same family, so the timed
    stream measures the STEADY state (cache warm, splice path compiled)
    and the receipt gains hit rate, splice counts, and TTFT p50/p95
    (submit to first token, the latency prefix reuse actually moves)."""
    import jax
    import numpy as np

    from pytorch_distributed_training_tutorials_tpu.obs import FlightRecorder
    from pytorch_distributed_training_tutorials_tpu.serve import Request, ServeEngine

    # flight recorder (ISSUE 10): always on for the server arm — host
    # bookkeeping only, zero extra device fetches — so every serving
    # receipt carries streaming-histogram percentiles and the lifecycle
    # counters. --flight-log additionally dumps graft-flightlog/v1
    # snapshots (fault auto-dumps + one end-of-stream dump) to disk.
    flight = FlightRecorder(capacity=4096, dump_path=args.flight_log)

    # contract sentry (ISSUE 19): on by default for every --server arm —
    # host-only counters, zero extra device fetches — so the receipt
    # carries sentry_steady_recompiles / sentry_fetch_budget_ok /
    # sentry_reupload_bytes and a contract break on the real chip
    # auto-dumps a flight snapshot instead of silently eating the round.
    # --no-sentry reverts to the bare engine (regress.py fingerprints
    # the `sentry` field, so the two never gate each other).
    sentry = None
    if not args.no_sentry:
        from pytorch_distributed_training_tutorials_tpu.obs import ContractSentry

        sentry = ContractSentry(flight=flight).install()

    bank = None
    if args.adapters:
        # multi-tenant arm: N-1 synthetic tenants (small random factors)
        # in one bank; requests cycle through ids 0..N-1 so the stream
        # mixes the base model with every tenant in the same slots
        from pytorch_distributed_training_tutorials_tpu.adapters import AdapterBank

        bank = AdapterBank(
            lm, n_adapters=args.adapters, rank=args.lora_rank
        )
        frng = np.random.Generator(np.random.PCG64(13))
        for aid in range(1, args.adapters):
            bank.register(
                f"tenant-{aid}",
                jax.tree_util.tree_map(
                    lambda leaf: (
                        frng.standard_normal(leaf.shape) * 0.02
                    ).astype(np.float32),
                    bank.row_zeros(),
                ),
            )
            flight.record(
                "adapter_register", adapter=aid, tenant=f"tenant-{aid}"
            )

    window = int(cfg.max_seq_len)
    new = args.new_tokens
    lengths = sorted(
        {
            max(1, args.prompt_len // 2),
            min(args.prompt_len, window - new),
            min(args.prompt_len + args.prompt_len // 2, window - new),
        }
    )
    cache_mb = args.prefix_cache_mb
    if cache_mb is None:
        cache_mb = 512 if args.prefix_overlap > 0 else 0
    engine = ServeEngine(
        lm, params,
        n_slots=args.slots,
        tokens_per_launch=args.tokens_per_launch,
        max_queue=max(64, args.requests),
        temperature=args.temperature,
        top_k=args.top_k,
        top_p=args.top_p,
        prefix_cache_bytes=cache_mb * 1024 * 1024,
        speculative_k=args.spec_k,
        spec_ngram=args.spec_ngram,
        adapter_bank=bank,
        default_deadline_s=args.deadline_s,
        flight=flight,
        sentry=sentry,
        pipeline_depth=args.pipeline_depth,
        prefill_chunk=args.prefill_chunk,
        priority_classes=2 if args.slo else 0,
        strategy=_serving_strategy(lm),
        **_paged_kwargs(args, window),
    )
    if args.tp > 1:
        # one extra AOT chain compile, once per receipt run: the
        # zero-unexpected-collectives verdict (tp_hlo_ok) rides into
        # the receipt via engine.stats()'s tp part
        audit = engine.audit_decode_hlo()
        print(
            f"tp={args.tp} decode HLO audit: ok={audit['ok']} "
            f"collectives={audit['collectives']}"
        )
    rng = np.random.Generator(np.random.PCG64(11))
    # one shared token family: request i's prompt = shared[:k] + tail,
    # k = round(overlap * p_len) — every prompt of the stream shares its
    # head with every other, the trie's best case at overlap 1.0 and a
    # plain random stream at 0.0
    shared = rng.integers(0, cfg.vocab_size, (max(lengths),)).tolist()

    def mk_request(i: int, deadline_s: float | None = None) -> Request:
        p_len = lengths[i % len(lengths)]
        k = min(p_len, int(round(args.prefix_overlap * p_len)))
        tail = rng.integers(0, cfg.vocab_size, (p_len - k,)).tolist()
        return Request(
            prompt=shared[:k] + tail, max_new_tokens=new, seed=i,
            deadline_s=deadline_s,
            # cycle every bank row (0 = base) through the shared slots
            adapter=(i % args.adapters) if bank is not None else 0,
            # SLO arm (ISSUE 20): every 4th request is interactive
            # (class 0), the rest batch (class 1) — the mix that makes
            # a class-0 arrival find the slots full of class-1 work
            priority=(0 if i % 4 == 0 else 1) if args.slo else 0,
        )

    # compile warmup: one request per prompt bucket + the decode chain,
    # outside the timed stream (compile is the multi-second cost; the
    # stream receipt should measure serving, not tracing). With overlap
    # the warmup also compiles the suffix splice buckets and leaves the
    # shared family resident, so the timed stream is steady-state.
    t0 = time.perf_counter()
    for i in range(len(lengths)):
        # warmup is COMPILE time (minutes at 1B) — exempt it from any
        # --deadline-s so the timed stream starts with live programs
        engine.submit(mk_request(
            i, deadline_s=1e9 if args.deadline_s is not None else None,
        ))
    engine.run_until_idle()
    compile_s = time.perf_counter() - t0
    _reset_serving_counters(engine)
    # the warmup's compile-dominated spans would poison the percentile
    # histograms — reset the recorder with the counters above
    flight.reset()
    if sentry is not None:
        # same seam: warmup compiles were legal and attributed; from
        # here any compilation is a steady-state violation (auto-dumped)
        sentry.mark_steady()

    t0 = time.perf_counter()
    if args.qps > 0:
        # open-loop Poisson arrivals (same seeded process as the fleet
        # arm): requests land at their arrival instants regardless of
        # progress. The SLO arm needs this spacing — an up-front burst
        # is drained in strict class order by the PriorityScheduler and
        # never needs to preempt an occupied slot
        arng = np.random.Generator(np.random.PCG64(17))
        arrivals, t_arr = [], 0.0
        for _ in range(args.requests):
            t_arr += float(arng.exponential(1.0 / args.qps))
            arrivals.append(t_arr)
        next_i = 0
        while next_i < len(arrivals):
            due = t0 + arrivals[next_i]
            if time.perf_counter() >= due:
                engine.submit(mk_request(len(lengths) + next_i))
                next_i += 1
                continue
            if engine.idle:
                time.sleep(min(0.001, max(0.0, due - time.perf_counter())))
            else:
                engine.step()
    else:
        for i in range(args.requests):
            engine.submit(mk_request(len(lengths) + i))
    engine.run_until_idle()
    # the drain's last chain ended in a real fetch (engine.step's
    # device_get), but close the region explicitly so wall-clock honesty
    # doesn't hinge on engine internals
    jax.device_get(engine._state["remaining"])
    wall_s = time.perf_counter() - t0

    # percentiles come from the recorder's streaming histograms (bounded
    # memory, mergeable across processes) rather than sorting the
    # completion list — same samples (the engine records each
    # Completion's own latency/ttft), bounded-error buckets
    lat_h, ttft_h = flight.hist["e2e"], flight.hist["ttft"]
    toks = engine.generated_tokens
    receipt.update(
        server=True,
        server_requests=args.requests,
        server_slots=args.slots,
        tokens_per_launch=args.tokens_per_launch,
        server_prompt_lengths=lengths,
        new_tokens=new,
        max_seq_len=window,
        temperature=args.temperature,
        qps=args.qps,
        server_wall_s=round(wall_s, 2),
        server_tok_per_s=round(toks / wall_s, 1),
        server_generated_tokens=toks,
        server_chains=engine.n_chains,
        server_prefills=engine.n_prefills,
        server_p50_latency_s=round(lat_h.quantile(0.50), 3),
        server_p95_latency_s=round(lat_h.quantile(0.95), 3),
        server_ttft_p50_s=round(ttft_h.quantile(0.50), 3),
        server_ttft_p95_s=round(ttft_h.quantile(0.95), 3),
        server_compile_s=round(compile_s, 1),
        prefix_overlap=args.prefix_overlap,
        prefix_cache_mb=cache_mb,
        **engine.stats(),
        backend=jax.default_backend(),
    )
    if args.flight_log:
        # end-of-stream snapshot (fault auto-dumps already appended)
        flight.dump(reason="end_of_stream")
        print(f"flight log -> {args.flight_log}")
    prefix_note = ""
    if engine.prefix is not None:
        st = engine.prefix_stats()
        prefix_note = (
            f", prefix hit rate {st['prefix_hit_rate']:.2f} "
            f"({engine.n_splices} splices, {engine.prefix_hit_tokens} "
            f"tokens reused)"
        )
    if args.spec_k:
        ss = engine.spec_stats()
        prefix_note += (
            f", spec-k {args.spec_k}: mean accepted "
            f"{ss['spec_mean_accepted_len']:.2f}, "
            f"{ss['n_verify_forwards']} verify forwards for {toks} tokens"
        )
    if bank is not None:
        ast = engine.adapter_stats()
        prefix_note += (
            f", adapters: {ast['adapters_registered']}/"
            f"{ast['n_adapters'] - 1} tenants (rank {ast['lora_rank']}), "
            f"{ast['adapter_requests']} tenant requests"
        )
    if args.deadline_s is not None:
        fst = engine.fault_stats()
        prefix_note += (
            f", deadline {args.deadline_s}s: "
            f"{fst['deadline_expired']} expired"
        )
    if args.pipeline_depth > 1 or args.prefill_chunk:
        ps = engine.pipeline_stats()
        prefix_note += (
            f", pipeline depth {ps['pipeline_depth']} "
            f"(chunk {ps['prefill_chunk']}, {ps['n_chunks']} chunks)"
        )
    if args.slo:
        st = engine.slo_stats()
        prefix_note += (
            f", slo: {st['priority_classes']} classes, "
            f"{st['n_preemptions']} preemptions "
            f"({st['n_swaps_out']} out / {st['n_swaps_in']} in)"
        )
    if sentry is not None:
        sentry.uninstall()
        prefix_note += (
            f", sentry: {sentry.n_steady_recompiles} steady recompiles, "
            f"budget {'OK' if not sentry.n_budget_violations else 'OVER'}"
            f", {sentry.reupload_bytes} B re-uploaded"
        )
    print(
        f"server: {args.requests} requests (prompts {lengths}, {new} new "
        f"each) over {args.slots} slots in {wall_s:.2f}s — "
        f"{toks / wall_s:.1f} tok/s, p50 {receipt['server_p50_latency_s']}s "
        f"/ p95 {receipt['server_p95_latency_s']}s per request, ttft p50 "
        f"{receipt['server_ttft_p50_s']}s, "
        f"{engine.n_chains} chains + {engine.n_prefills} prefills"
        f"{prefix_note} (compile {compile_s:.0f}s)"
    )


if __name__ == "__main__":
    main()
