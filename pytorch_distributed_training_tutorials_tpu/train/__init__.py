"""Training loop: the TPU twin of the reference's L4 Trainer layer."""

from pytorch_distributed_training_tutorials_tpu.train.trainer import (  # noqa: F401
    Trainer,
    TrainState,
    create_train_state,
    make_train_step,
    make_epoch_scan,
    make_eval_step,
)
