"""Trainer + jitted SPMD train step.

Twin of the reference's ``Trainer`` (reference ``ddp_gpus.py:19-53``, torchrun
variant ``ddp_gpus_torchrun.py:16-49``): owns model/loader/optimizer, runs
epoch -> batch loops, logs the per-epoch line, calls ``set_epoch`` for the
reshuffle. The differences are the TPU-native ones (SURVEY.md section 7):

- ``_run_batch``'s zero_grad/forward/loss/backward/step
  (``ddp_gpus.py:34-39``) is one ``jax.jit``-compiled ``train_step`` with
  donated state; the DDP gradient allreduce is compiled in by XLA from the
  sharding layout (replicated params x batch-sharded data), overlapped with
  the backward like NCCL's bucketed hooks.
- no per-step H2D ``.to(device)`` calls (``ddp_gpus.py:47-48``): the loader
  already delivers mesh-sharded device arrays.
- loss *is* logged (the reference never logs it — SURVEY.md section 5.5), and
  the trainer reports steps/s and samples/s for the benchmark harness.

Loss functions mirror the reference's: ``cross_entropy``
(``F.cross_entropy``, ``ddp_gpus.py:37``) and ``mse`` (the model-parallel
lesson, ``03.model_parallel.ipynb:991``). ``fused_cross_entropy`` is the
same objective computed logits-free: the model is applied with
``return_hidden=True`` and :func:`..ops.fused_loss.fused_cross_entropy`
streams the final hidden states against the ``lm_head`` kernel blockwise,
so the (B, S, vocab) logits tensor — the largest activation of an LM train
step — never exists in HBM.
"""

from __future__ import annotations

import time
from typing import Any

import jax
import jax.numpy as jnp
import optax
from flax import core, struct

from pytorch_distributed_training_tutorials_tpu.models.moe import moe_aux_loss
from pytorch_distributed_training_tutorials_tpu.ops.fused_loss import (
    fused_cross_entropy,
)
from pytorch_distributed_training_tutorials_tpu.parallel.data_parallel import (
    DataParallel,
)
from pytorch_distributed_training_tutorials_tpu.obs.metrics import MetricsLogger
from pytorch_distributed_training_tutorials_tpu.utils import chaos as chaos_lib
from pytorch_distributed_training_tutorials_tpu.utils.logging import epoch_line


class TrainState(struct.PyTreeNode):
    """Params + optimizer state + (optional) batch stats, one pytree.

    A minimal flax-style train state: everything the jitted step mutates lives
    here so the whole bundle can be donated and resharded as a unit.
    """

    step: jnp.ndarray
    apply_fn: Any = struct.field(pytree_node=False)
    params: core.FrozenDict
    tx: optax.GradientTransformation = struct.field(pytree_node=False)
    opt_state: optax.OptState
    batch_stats: core.FrozenDict | None = None

    @classmethod
    def create(cls, *, apply_fn, params, tx, batch_stats=None):
        return cls(
            step=jnp.zeros((), jnp.int32),
            apply_fn=apply_fn,
            params=params,
            tx=tx,
            opt_state=tx.init(params),
            batch_stats=batch_stats,
        )


def create_train_state(
    model,
    optimizer: optax.GradientTransformation,
    sample_input,
    *,
    strategy,
    seed: int = 0,
) -> TrainState:
    """Init model variables replicated on the mesh and wrap in a TrainState.

    The replicated placement is the twin of DDP's construction-time param
    broadcast from rank 0 (reference ``ddp_gpus.py:32``): every device starts
    from identical params (same PRNG key -> same init, placed replicated).
    """
    key = jax.random.PRNGKey(seed)
    # One row per data-parallel replica: models whose forward shards the batch
    # explicitly (shard_map, e.g. ring attention) need init shapes divisible
    # by the mesh axes; params themselves are batch-size independent.
    sample = jnp.asarray(
        sample_input[: max(1, getattr(strategy, "num_devices", 1))]
    )
    # Per-parameter placement: replicated for data parallelism, rule-driven
    # for tensor/hybrid parallelism — one strategy interface either way.
    abstract = jax.eval_shape(model.init, key, sample)
    out_shardings = strategy.variable_shardings(abstract)
    variables = jax.jit(model.init, out_shardings=out_shardings)(key, sample)
    params = variables["params"]
    batch_stats = variables.get("batch_stats")
    state = TrainState.create(
        apply_fn=model.apply, params=params, tx=optimizer, batch_stats=batch_stats
    )
    return strategy.shard_state(state)


def _fused_ce_loss(params, hidden, targets):
    """Mean logits-free cross entropy: final hidden states streamed against
    the model's own ``lm_head`` kernel (cast to the activation dtype, the
    same cast ``nn.Dense(dtype=cfg.dtype)`` applies before its matmul)."""
    if "lm_head" not in params:
        raise ValueError(
            'loss="fused_cross_entropy" needs a model with an lm_head '
            "Dense whose forward supports return_hidden=True "
            "(models.transformer.TransformerLM)"
        )
    w = params["lm_head"]["kernel"]
    return fused_cross_entropy(
        hidden, w.astype(hidden.dtype), targets
    ).mean()


def _compute_loss(loss: str, logits, targets):
    if loss == "cross_entropy":
        if targets.ndim == logits.ndim:  # one-hot / soft targets
            return optax.softmax_cross_entropy(logits, targets).mean()
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, targets
        ).mean()
    if loss == "mse":
        return jnp.mean((logits - targets) ** 2)
    raise ValueError(f"unknown loss {loss!r}")


def _make_loss_fn(
    loss: str, has_batch_stats: bool, aux_loss_weight: float,
    model_kwargs: dict | None = None,
):
    """The single definition of the training objective, shared by the plain
    step, the epoch scan, and the gradient-accumulation step — one place
    owns the batch_stats/mutable/aux-loss contract.

    ``model_kwargs`` are extra keywords forwarded verbatim to every model
    apply — e.g. ``{"adapter_ids": tid}`` to pin a LoRA fine-tune
    (:mod:`..adapters`) to one tenant row. They are closed over (trace-time
    constants), not per-batch data."""

    fused = loss == "fused_cross_entropy"

    def loss_fn(params, state: TrainState, batch):
        x, y = batch
        variables = {"params": params}
        mutable = []
        kwargs = dict(model_kwargs) if model_kwargs else {}
        if has_batch_stats:
            variables["batch_stats"] = state.batch_stats
            mutable.append("batch_stats")
            kwargs["train"] = True
        if aux_loss_weight:
            mutable.append("losses")
        if fused:
            # fused tail: the model stops at the final-norm hidden states;
            # the lm_head matmul happens inside the blockwise loss kernel
            kwargs["return_hidden"] = True
        if mutable:
            out, updates = state.apply_fn(
                variables, x, mutable=mutable, **kwargs
            )
        else:
            out, updates = state.apply_fn(variables, x, **kwargs), {}
        if fused:
            loss_val = _fused_ce_loss(params, out, y)
        else:
            loss_val = _compute_loss(loss, out, y)
        if aux_loss_weight:
            loss_val = loss_val + aux_loss_weight * moe_aux_loss(updates)
        return loss_val, updates.get("batch_stats")

    return loss_fn


def _apply_update(
    state: TrainState,
    grads,
    loss_val,
    new_stats,
    has_batch_stats,
    skip_nonfinite: bool = False,
    chaos=None,
):
    """The optimizer-update tail shared by the plain and gradient-
    accumulation steps — one place owns tx.update/apply/replace/metrics.

    ``skip_nonfinite`` adds the ISSUE 9 skip-step guard: when the loss or
    ANY gradient leaf is non-finite, the whole update is elided via a
    ``jnp.where`` tree-select — params, opt_state and batch_stats come out
    bitwise equal to the incoming state and ``step`` does not advance. The
    finite flag is DATA (graftcheck ``traced-control-flow`` clean) and the
    guard sits AFTER ``tx.update``, so it composes with any optax chain
    and with :func:`..ops.fused_optim.fused_adamw` unchanged (the fused
    kernel's aliased mu/nu buffers are reverted the same way — XLA copies
    live donated inputs, so the old values are still available to the
    select). Metrics gain a ``"skipped"`` 0/1 device scalar ONLY when the
    guard is on — guard-off programs keep a byte-identical jaxpr.

    ``chaos`` (a :class:`..utils.chaos.ChaosConfig` poisoning grads)
    injects NaN gradients at the configured ``TrainState.step`` BEFORE the
    update — the fault the guard is tested against, landing exactly where
    a real non-finite backward reduction would."""
    if chaos is not None and chaos.poisons_grads:
        grads = chaos_lib.poison_grads(grads, state.step, chaos.nan_grad_step)
    updates, new_opt_state = state.tx.update(
        grads, state.opt_state, state.params
    )
    new_params = optax.apply_updates(state.params, updates)
    metrics = {"loss": loss_val}
    if skip_nonfinite:
        ok = jnp.isfinite(loss_val)
        for g in jax.tree_util.tree_leaves(grads):
            ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(g)))

        def select(new, old):
            return jax.tree_util.tree_map(
                lambda n, o: jnp.where(ok, n, o), new, old
            )

        new_params = select(new_params, state.params)
        new_opt_state = select(new_opt_state, state.opt_state)
        if has_batch_stats and new_stats is not None:
            new_stats = select(new_stats, state.batch_stats)
        step_inc = ok.astype(state.step.dtype)
        metrics["skipped"] = jnp.int32(1) - step_inc.astype(jnp.int32)
    else:
        step_inc = 1
    new_state = state.replace(
        step=state.step + step_inc,
        params=new_params,
        opt_state=new_opt_state,
        batch_stats=new_stats if has_batch_stats else state.batch_stats,
    )
    return new_state, metrics


def _train_step_fn(
    loss: str = "cross_entropy",
    has_batch_stats: bool = False,
    aux_loss_weight: float = 0.0,
    model_kwargs: dict | None = None,
    skip_nonfinite: bool = False,
    chaos=None,
):
    """The raw (unjitted) SPMD train step, shared by :func:`make_train_step`
    (jit per step — streaming loaders) and :func:`make_epoch_scan` (one jit
    per epoch — device-resident datasets). ``skip_nonfinite``/``chaos``
    thread through to :func:`_apply_update` (the skip-step guard and the
    NaN-grad injector)."""
    loss_fn = _make_loss_fn(
        loss, has_batch_stats, aux_loss_weight, model_kwargs
    )

    def step_fn(state: TrainState, batch):
        (loss_val, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params, state, batch)
        return _apply_update(
            state, grads, loss_val, new_stats, has_batch_stats,
            skip_nonfinite=skip_nonfinite, chaos=chaos,
        )

    return step_fn


def make_train_step(
    loss: str = "cross_entropy",
    has_batch_stats: bool = False,
    aux_loss_weight: float = 0.0,
    grad_accum_steps: int = 1,
    model_kwargs: dict | None = None,
    skip_nonfinite: bool = False,
    chaos=None,
):
    """Build the jitted SPMD train step (donated state).

    One compiled program per step replaces the reference's
    zero_grad/forward/loss/backward/allreduce/step sequence
    (``ddp_gpus.py:34-39``). Gradients come out replicated — XLA inserts the
    ICI allreduce during the backward because params are replicated while the
    batch is sharded.

    ``aux_loss_weight`` > 0 collects the model's sown ``"losses"`` collection
    (MoE load-balancing) and adds it, weighted, to the objective.

    ``loss="fused_cross_entropy"`` trains an LM through the logits-free
    blockwise head+loss (:mod:`..ops.fused_loss`) — same objective as
    ``"cross_entropy"``, minus the (B, S, vocab) logits activation. Also
    accepted by :func:`make_epoch_scan` and the gradient-accumulation step
    (they all share one loss definition).

    ``grad_accum_steps`` > 1 splits the batch into that many microbatches
    inside the compiled step (a ``lax.scan``), averaging gradients (and
    BatchNorm statistics) before ONE optimizer update — the standard trade
    of peak activation memory for step time when the global batch exceeds
    HBM. Batch dim 0 must divide evenly; for the strided microbatch split to
    stay evenly spread over a ``data``-sharded batch, the *per-device* row
    count must also divide by ``grad_accum_steps`` (the Trainer validates
    this where the mesh width is known).

    ``model_kwargs`` forwards extra trace-time keywords to every model
    apply (see :func:`_make_loss_fn`) — the LoRA fine-tune path pins
    ``{"adapter_ids": tid}`` this way.

    ``skip_nonfinite`` turns on the skip-step guard (see
    :func:`_apply_update`): a non-finite loss/grad leaves the returned
    state bitwise equal to the input (step included) and the metrics dict
    gains a ``"skipped"`` 0/1 device scalar. With gradient accumulation
    the guard checks the AVERAGED gradients — one poisoned microbatch
    skips the whole optimizer step, matching what folding it in would
    have corrupted. ``chaos`` injects the tested fault
    (:class:`..utils.chaos.ChaosConfig`).
    """
    if grad_accum_steps < 1:
        raise ValueError(f"grad_accum_steps must be >= 1, got {grad_accum_steps}")
    if grad_accum_steps == 1:
        return jax.jit(
            _train_step_fn(
                loss, has_batch_stats, aux_loss_weight, model_kwargs,
                skip_nonfinite=skip_nonfinite, chaos=chaos,
            ),
            donate_argnums=0,
        )

    loss_fn = _make_loss_fn(
        loss, has_batch_stats, aux_loss_weight, model_kwargs
    )

    def step_fn(state: TrainState, batch):
        n = grad_accum_steps
        b = jax.tree_util.tree_leaves(batch)[0].shape[0]
        if b % n:
            raise ValueError(
                f"batch dim 0 ({b}) not divisible by "
                f"grad_accum_steps ({n})"
            )
        # strided split (microbatch m = rows m::n): with dim 0 sharded over
        # `data` in contiguous per-device blocks, every microbatch stays
        # evenly spread over all devices (a contiguous (n, B/n) reshape
        # would hand each microbatch to a fraction of the mesh and force a
        # reshard per scan iteration)
        micro = jax.tree_util.tree_map(
            lambda a: a.reshape(
                a.shape[0] // n, n, *a.shape[1:]
            ).swapaxes(0, 1),
            batch,
        )

        def body(acc, mb):
            g_acc, s_acc, l_acc = acc
            (loss_val, new_stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state.params, state, mb)
            g_acc = jax.tree_util.tree_map(jnp.add, g_acc, grads)
            if has_batch_stats:
                s_acc = jax.tree_util.tree_map(jnp.add, s_acc, new_stats)
            return (g_acc, s_acc, l_acc + loss_val), None

        zeros_g = jax.tree_util.tree_map(jnp.zeros_like, state.params)
        zeros_s = (
            jax.tree_util.tree_map(
                lambda a: jnp.zeros_like(a, jnp.float32), state.batch_stats
            )
            if has_batch_stats
            else None
        )
        (g_sum, s_sum, l_sum), _ = jax.lax.scan(
            body, (zeros_g, zeros_s, jnp.float32(0)), micro
        )
        inv = 1.0 / n
        grads = jax.tree_util.tree_map(lambda g: g * inv, g_sum)
        new_stats = (
            jax.tree_util.tree_map(
                lambda s, old: (s * inv).astype(old.dtype),
                s_sum,
                state.batch_stats,
            )
            if has_batch_stats
            else None
        )
        return _apply_update(
            state, grads, l_sum * inv, new_stats, has_batch_stats,
            skip_nonfinite=skip_nonfinite, chaos=chaos,
        )

    return jax.jit(step_fn, donate_argnums=0)


def make_epoch_scan(
    loss: str = "cross_entropy",
    has_batch_stats: bool = False,
    aux_loss_weight: float = 0.0,
    transform=None,
    unroll: int = 1,
    pregather: bool = False,
    skip_nonfinite: bool = False,
    chaos=None,
):
    """Build a jitted *whole-epoch* program: ``lax.scan`` of the train step
    over a device-resident dataset.

    ``epoch_fn(state, idx, data) -> (state, losses)`` where ``idx`` is the
    epoch's ``(steps, global_batch)`` index matrix
    (:meth:`..data.resident.DeviceResidentLoader.epoch_index_array`), ``data``
    the resident dataset arrays, and ``losses`` the per-step loss trace. The
    batch gather (and optional ``transform``, e.g. uint8 -> normalized float)
    happens inside the scan body, so XLA fuses it into the step. Replaces the
    reference's per-step ``for ... in dataloader`` hot loop
    (``ddp_gpus.py:46-49``) with one program launch per epoch.

    ``unroll`` passes through to ``lax.scan``: unrolling the step body lets
    XLA amortize while-loop bookkeeping and the carried-state copies across
    iterations (measured round 4 on v5e: unroll=8 removed ~4% of step time
    on the ResNet-18 bs512 leg — the loop-boundary ``copy-start/copy-done``
    pairs halved). Costs compile time roughly linearly; 1 (no unroll) keeps
    test-suite compiles fast.

    ``skip_nonfinite``/``chaos`` thread through to the scanned step (same
    guard as :func:`make_train_step`; the per-step ``"skipped"`` scalar is
    not carried out of the scan — a skipped step is visible as
    ``state.step`` advancing by less than the steps run).

    ``pregather`` hoists the row gather OUT of the scan body: one epoch-wide
    take reshapes the resident dataset to ``(steps, B, ...)`` and the scan
    consumes contiguous leading-axis slices instead of doing a 512-row
    gather per iteration, at the cost of a transient epoch-sized HBM copy
    (uint8 MNIST x 5 fused epochs ~ 0.3 GB). Measured on the v5e headline
    workload it is NEUTRAL TO SLIGHTLY WORSE (46.5k -> 45.7k img/s at
    unroll=1; 48.0k -> 47.7k at unroll=8, min-of-3) — the in-body gather
    fuses well there. Kept because the trade can flip for datasets whose
    gather does not fuse (host-padded layouts, very wide rows); measure
    before enabling. What DID move the headline is ``unroll=8`` on this
    scan (BENCH_r05).
    """
    step_fn = _train_step_fn(
        loss, has_batch_stats, aux_loss_weight,
        skip_nonfinite=skip_nonfinite, chaos=chaos,
    )

    def epoch_fn(state: TrainState, idx, data):
        def body(state, batch):
            if transform is not None:
                batch = transform(*batch)
            state, metrics = step_fn(state, batch)
            return state, metrics["loss"]

        if pregather:
            stacked = tuple(a[idx] for a in data)  # (T, B, ...) one take
            state, losses = jax.lax.scan(
                body, state, stacked, unroll=unroll
            )
        else:
            def gather_body(state, idx_step):
                return body(state, tuple(a[idx_step] for a in data))

            state, losses = jax.lax.scan(
                gather_body, state, idx, unroll=unroll
            )
        return state, losses

    return jax.jit(epoch_fn, donate_argnums=0)


def make_eval_step(loss: str = "cross_entropy", has_batch_stats: bool = False):
    """Jitted eval step: per-batch (summed per-sample loss, correct count,
    sample count), weighted by a per-row validity ``mask``.

    ``mask`` (shape ``(B,)``) zeroes out wrap-padded duplicate rows (the
    equal-shard padding the reference's DistributedSampler silently counts —
    the framework computes the pad, so eval can mask it;
    :meth:`..data.loader.ShardedLoader.valid_mask`). ``correct`` is an
    argmax-accuracy count for integer-label cross-entropy and 0 otherwise
    (regression has no accuracy).

    A ``"fused_cross_entropy"`` trainer evaluates through the standard
    logits path: eval needs the argmax anyway, and one forward per eval
    batch has no optimizer state competing for HBM — same objective,
    same numbers.
    """
    if loss == "fused_cross_entropy":
        loss = "cross_entropy"

    def eval_fn(state: TrainState, batch, mask):
        x, y = batch
        variables = {"params": state.params}
        if has_batch_stats:
            variables["batch_stats"] = state.batch_stats
            logits = state.apply_fn(variables, x, train=False)
        else:
            logits = state.apply_fn(variables, x)
        mask = mask.astype(jnp.float32)
        classification = loss == "cross_entropy" and y.ndim < logits.ndim
        if classification:
            # per-label stats (for an LM, labels = every token position);
            # the row mask broadcasts over the label positions
            mask_rows = mask.reshape(mask.shape[0], *([1] * (y.ndim - 1)))
            per_label = optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            )
            loss_sum = (per_label * mask_rows).sum()
            correct = jnp.sum(
                (jnp.argmax(logits, -1) == y) * mask_rows
            ).astype(jnp.int32)
            count = (jnp.ones_like(y, jnp.float32) * mask_rows).sum()
        else:
            # per-sample loss over feature dims; accuracy undefined
            if loss == "mse":
                feat_axes = tuple(range(1, y.ndim))
                per_sample = jnp.mean(
                    (logits - y) ** 2, axis=feat_axes
                ) if feat_axes else (logits - y) ** 2
            else:  # soft-target cross entropy: (B, ...) per-position losses
                per_sample = optax.softmax_cross_entropy(logits, y)
            # broadcast the row mask over any remaining positions (e.g. an
            # LM's (B, T) soft-target losses)
            mask_rows = mask.reshape(
                mask.shape[0], *([1] * (per_sample.ndim - 1))
            )
            loss_sum = (per_sample * mask_rows).sum()
            correct = jnp.zeros((), jnp.int32)
            count = (jnp.ones_like(per_sample) * mask_rows).sum()
        return loss_sum, correct, count

    return jax.jit(eval_fn)


class Trainer:
    """Epoch/batch training loop over a sharded loader.

    API twin of the reference Trainer (``ddp_gpus.py:19-53``)::

        trainer = Trainer(model, loader, optax.sgd(1e-2), strategy=dp)
        trainer.train(max_epochs)
    """

    def __init__(
        self,
        model,
        train_loader,
        optimizer: optax.GradientTransformation,
        *,
        strategy=None,  # DataParallel | TensorParallel | compatible
        loss: str = "cross_entropy",
        aux_loss_weight: float = 0.0,
        grad_accum_steps: int = 1,
        seed: int = 0,
        log_every: int | None = None,
        defer_host_fetch: bool = False,
        scan_unroll: int = 1,
        pregather: bool = False,
        metrics: MetricsLogger | None = None,
        quiet: bool = False,
        on_step=None,
        on_epoch=None,
        skip_nonfinite: bool = False,
        chaos=None,
        rollback_spike_factor: float | None = None,
        rollback_patience: int = 2,
        rollback_ema: float = 0.9,
        flight=None,
        sentry=None,
    ):
        self.model = model
        self.loader = train_loader
        self.strategy = strategy if strategy is not None else DataParallel(
            train_loader.mesh
        )
        # the loader-owned seam (no reaching into dataset internals): any
        # loader exposing sample_batch() works — streaming, resident, custom
        sample = train_loader.sample_batch()
        if isinstance(sample, tuple):
            sample = sample[0]
        self.state = create_train_state(
            model, optimizer, sample, strategy=self.strategy, seed=seed
        )
        self.has_batch_stats = self.state.batch_stats is not None
        # -- ISSUE 9 training guardrails ------------------------------------
        # skip_nonfinite: jnp.where-elide the optimizer update on any
        # non-finite loss/grad (see _apply_update) — the per-step
        # "skipped" 0/1 device scalar rides the MetricsLogger batched
        # drain (log_step extra), never a per-step sync.
        # chaos: a utils.chaos.ChaosConfig — deterministic fault injection
        # (NaN grads at a step, spiked monitor loss) for the tests.
        # rollback_spike_factor: when the monitored loss exceeds
        # factor x its EMA (or is non-finite) for `rollback_patience`
        # consecutive observations, restore the latest `save()` target and
        # continue (restore-and-continue: the data position — self.epoch —
        # is kept, only the state rolls back). The monitor observes host
        # floats: per step on the streaming path, per chunk on the chunked
        # path, per epoch on the scanned path — opting in costs that fetch
        # cadence (documented price; rollback needs loss visibility).
        self.skip_nonfinite = skip_nonfinite
        self.chaos = chaos
        if rollback_spike_factor is not None and rollback_spike_factor <= 1:
            raise ValueError(
                f"rollback_spike_factor must be > 1 (None = off), got "
                f"{rollback_spike_factor}"
            )
        if rollback_patience < 1:
            raise ValueError(
                f"rollback_patience must be >= 1, got {rollback_patience}"
            )
        if not 0.0 <= rollback_ema < 1.0:
            raise ValueError(
                f"rollback_ema must be in [0, 1), got {rollback_ema}"
            )
        self._rb_factor = rollback_spike_factor
        self._rb_patience = rollback_patience
        self._rb_decay = rollback_ema
        self._rb_ema = None  # EMA of healthy monitored losses
        self._rb_strikes = 0  # consecutive spike observations
        self._monitor_steps = 0  # monotonic host counter, never replays
        self._dispatches = 0  # monotonic step-dispatch counter (batch chaos)
        self.rollbacks = 0
        self._last_ckpt = None  # latest save() target (rollback restores it)
        self.train_step = make_train_step(
            loss=loss,
            has_batch_stats=self.has_batch_stats,
            aux_loss_weight=aux_loss_weight,
            grad_accum_steps=grad_accum_steps,
            skip_nonfinite=skip_nonfinite,
            chaos=chaos,
        )
        if grad_accum_steps > 1 and getattr(
            train_loader, "device_arrays", None
        ) is not None:
            raise ValueError(
                "grad_accum_steps applies to the per-step path; the "
                "device-resident epoch scan already amortizes memory — use "
                "a streaming ShardedLoader for gradient accumulation"
            )
        if grad_accum_steps > 1:
            if train_loader.global_batch % grad_accum_steps:
                # the compiled step would reject this at trace time anyway
                # (make_train_step's batch-dim check) — fail at construction
                raise ValueError(
                    f"global batch ({train_loader.global_batch}) not "
                    f"divisible by grad_accum_steps ({grad_accum_steps})"
                )
            # strategy.num_devices is the DATA-axis width by interface
            # contract (every strategy returns mesh.shape[data axis], not
            # the total device count — see DataParallel.num_devices), so
            # it is the right divisor on hybrid meshes too (ADVICE r3)
            d = self.strategy.num_devices
            per_dev = train_loader.global_batch // max(d, 1)
            if per_dev % grad_accum_steps:
                # semantically correct either way (microbatches are the same
                # rows), but each scan iteration pays a reshard of its
                # microbatch across the data axis — warn, don't break
                import warnings

                warnings.warn(
                    f"per-device batch ({per_dev}) not divisible by "
                    f"grad_accum_steps ({grad_accum_steps}): microbatches "
                    "cannot stay evenly spread over the data axis and will "
                    "reshard every accumulation step (slow, not wrong)",
                    stacklevel=2,
                )
        self.log_every = log_every
        # scan_unroll: lax.scan unroll factor for the compiled epoch/chunk
        # scans (see make_epoch_scan) — a perf knob for long device-resident
        # or chunked runs; leave 1 where compile time matters more (tests).
        # Baked into the cached scan at first trace — set it here, not after
        # an epoch has run.
        if scan_unroll < 1:
            raise ValueError(f"scan_unroll must be >= 1, got {scan_unroll}")
        self.scan_unroll = scan_unroll
        # pregather: hoist the per-step row gather out of the compiled
        # epoch scan (make_epoch_scan pregather) — a perf knob for
        # device-resident datasets, costing a transient epoch-sized copy
        self.pregather = pregather
        # defer_host_fetch: end chunked epochs with block_until_ready
        # (completion only) instead of a per-epoch loss fetch — standard
        # TPU practice to keep host-device syncs out of the training loop.
        # Losses stay on device in ``last_epoch_losses``; fetch after
        # training via :meth:`fetch_last_loss`. (On tunneled runtimes the
        # resulting wall-clock is NOT trustworthy without a terminal fetch
        # — see the CLAUDE.md async-mirage note.)
        self.defer_host_fetch = defer_host_fetch
        # metrics: every number and console line the loop produces flows
        # through one MetricsLogger (obs/metrics.py) — the verbose step
        # print and the structured record are the same fetch, and the
        # logger honors defer_host_fetch at epoch boundaries. ``quiet``
        # silences console output (bench runs) without losing events.
        self.metrics = metrics if metrics is not None else MetricsLogger(
            quiet=quiet, defer_host_fetch=defer_host_fetch, flight=flight
        )
        # flight recorder (ISSUE 10): skip-step observations reach it
        # through the MetricsLogger drain above (the "skipped" extra
        # already rides the batched fetch — no new per-step sync);
        # rollbacks stamp directly in _do_rollback (host-side already).
        self._flight = flight
        if flight is not None and self.metrics.flight is None:
            self.metrics.flight = flight
        # contract sentry (ISSUE 19): None = off (no behavior change at
        # all). On, each epoch attributes compile events to its phase
        # label and the TrainState tree is walked once per epoch for
        # host-numpy leaves — a restored-without-shardings checkpoint
        # re-uploads the whole model EVERY step (the device_materialize
        # trap); fresh data batches are deliberately NOT checked, their
        # H2D is the job.
        self._sentry = sentry
        # host-side hook points, called OUTSIDE traced code (graftcheck-
        # clean by construction): on_step(step, loss_device_scalar) after
        # each dispatched step/chunk, on_epoch(metrics_dict) after each
        # epoch. Hooks must not fetch if they care about throughput.
        self.on_step = on_step
        self.on_epoch = on_epoch
        self.last_epoch_losses = None  # device array, chunked path only
        self.loss_name = loss
        self.aux_loss_weight = aux_loss_weight
        self.grad_accum_steps = grad_accum_steps
        self.last_epoch_metrics: dict = {}
        self.epoch = 0  # next epoch to run; advanced by train(), restored
        self._eval_step = None
        self._epoch_scan = None
        self._chunk_scan = None

    def _epoch_metrics(self, epoch: int, loss, steps: int, dt: float) -> dict:
        """Shared metric dict + per-epoch log line for both epoch paths
        (streaming and scanned) — one place defines the keys/format."""
        m = {
            "epoch": epoch,
            "loss": float(loss) if loss is not None else float("nan"),
            "steps": steps,
            "steps_per_sec": steps / dt if dt > 0 else float("inf"),
            "samples_per_sec": steps * self.loader.global_batch / dt
            if dt > 0
            else float("inf"),
        }
        self.metrics.log_epoch(m)
        if self.on_epoch is not None:
            self.on_epoch(m)
        return m

    def _run_epoch_scanned(self, epoch: int) -> dict:
        """One program launch for the whole epoch (device-resident loader)."""
        loader = self.loader
        if self._epoch_scan is None:
            self._epoch_scan = make_epoch_scan(
                loss=self.loss_name,
                has_batch_stats=self.has_batch_stats,
                aux_loss_weight=self.aux_loss_weight,
                transform=loader.transform,
                unroll=self.scan_unroll,
                pregather=self.pregather,
                skip_nonfinite=self.skip_nonfinite,
                chaos=self.chaos,
            )
        self.metrics.say(
            epoch_line(
                self.strategy.num_devices, epoch,
                loader.per_device_batch, len(loader),
            )
        )
        idx = loader.epoch_index_array(epoch)
        t0 = time.perf_counter()
        self.state, losses = self._epoch_scan(
            self.state, idx, loader.device_arrays
        )
        loss = float(losses[-1])  # host fetch: the honest end-of-epoch sync
        if self._rb_factor is not None:
            self._monitor_loss(loss)  # per-epoch granularity on this path
        dt = time.perf_counter() - t0
        return self._epoch_metrics(epoch, loss, len(loader), dt)

    def run_epochs_fused(self, first_epoch: int, n_epochs: int) -> dict:
        """Run ``n_epochs`` consecutive epochs as ONE compiled program
        (device-resident loaders only): the per-epoch index matrices are
        stacked into a single scan, so launch + final-fetch overhead is paid
        once per *run* instead of once per epoch. Epoch-seeded reshuffle
        semantics are identical — each epoch's indices come from the same
        ``set_epoch`` permutation the per-epoch path uses.

        Returns the last epoch's metrics (with aggregate ``samples_per_sec``
        over the fused region — the honest end-to-end rate).
        """
        loader = self.loader
        if getattr(loader, "device_arrays", None) is None:
            raise ValueError("run_epochs_fused requires a device-resident loader")
        if self._epoch_scan is None:
            self._epoch_scan = make_epoch_scan(
                loss=self.loss_name,
                has_batch_stats=self.has_batch_stats,
                aux_loss_weight=self.aux_loss_weight,
                transform=loader.transform,
                unroll=self.scan_unroll,
                pregather=self.pregather,
                skip_nonfinite=self.skip_nonfinite,
                chaos=self.chaos,
            )
        idx = jnp.concatenate(
            [
                loader.epoch_index_array(first_epoch + e)
                for e in range(n_epochs)
            ],
            axis=0,
        )
        steps = len(loader)
        t0 = time.perf_counter()
        self.state, losses = self._epoch_scan(
            self.state, idx, loader.device_arrays
        )
        losses = jax.device_get(losses)  # one host fetch for the whole run
        dt = time.perf_counter() - t0
        for e in range(n_epochs):
            epoch_losses = losses[e * steps : (e + 1) * steps]
            self.metrics.say(
                f"  epoch {first_epoch + e}: loss "
                f"{float(epoch_losses[-1]):.4f} (fused scan)"
            )
        self.epoch = first_epoch + n_epochs
        m = self._epoch_metrics(
            first_epoch + n_epochs - 1,
            float(losses[-1]),
            steps * n_epochs,
            dt,
        )
        m["steps"] = steps  # per-epoch steps, like the per-epoch path
        self.last_epoch_metrics = m  # keep the train()-path contract
        return m

    def _run_epoch_chunked(self, epoch: int) -> dict:
        """Streaming twin of the epoch scan: each prefetched multi-step
        chunk (:meth:`..data.streaming.ChunkedStreamingLoader.iter_chunks`)
        trains as ONE compiled ``lax.scan`` launch, while the next chunk's
        gather + H2D runs in the background — the per-step dispatch and
        transfer latency the round-2 profile flagged amortizes over the
        chunk length."""
        loader = self.loader
        loader.set_epoch(epoch)
        self.metrics.say(
            epoch_line(
                self.strategy.num_devices, epoch,
                loader.per_device_batch, len(loader),
            )
        )
        if self._chunk_scan is None:
            step_fn = _train_step_fn(
                self.loss_name, self.has_batch_stats, self.aux_loss_weight,
                skip_nonfinite=self.skip_nonfinite, chaos=self.chaos,
            )
            transform = loader.transform

            def chunk_scan(state, chunk):
                def body(state, batch):
                    if transform is not None:
                        batch = transform(*batch)
                    state, metrics = step_fn(state, batch)
                    return state, metrics["loss"]

                return jax.lax.scan(body, state, chunk, unroll=self.scan_unroll)

            # two compilations at most: full chunks + a shorter tail chunk
            self._chunk_scan = jax.jit(chunk_scan, donate_argnums=0)
        t0 = time.perf_counter()
        losses = []
        steps = 0
        next_log = self.log_every or 0
        for chunk in loader.iter_chunks():
            steps += jax.tree_util.tree_leaves(chunk)[0].shape[0]
            self.state, chunk_losses = self._chunk_scan(self.state, chunk)
            losses.append(chunk_losses)
            if self.log_every and steps >= next_log:
                # per-chunk granularity (a chunk is one compiled launch;
                # per-step logs would force a D2H sync into the scan) —
                # costs one loss fetch, so only when log_every opted in
                self.metrics.log_step(steps, chunk_losses[-1], verbose=True)
                next_log = steps + self.log_every
            if self.on_step is not None:
                self.on_step(steps, chunk_losses[-1])
            if self._rb_factor is not None:
                # per-chunk granularity (one fetch per compiled launch)
                if self._monitor_loss(float(chunk_losses[-1])):
                    break  # rolled back: abandon the rest of this epoch
        self.last_epoch_losses = losses[-1] if losses else None
        if self.defer_host_fetch:
            # completion sync only — no D2H (see defer_host_fetch in
            # __init__ for why a fetch here would poison later epochs'
            # input bandwidth on tunneled runtimes)
            if losses:
                jax.block_until_ready(losses[-1])
            loss = None
        else:
            loss = float(losses[-1][-1]) if losses else None
        dt = time.perf_counter() - t0
        return self._epoch_metrics(epoch, loss, steps, dt)

    def fetch_last_loss(self) -> float:
        """Fetch the deferred final loss of the last chunked epoch (a D2H
        read — call AFTER throughput-sensitive work)."""
        if self.last_epoch_losses is None:
            raise ValueError("no deferred losses recorded")
        return float(self.last_epoch_losses[-1])

    def _run_epoch(self, epoch: int) -> dict:
        if self._sentry is not None:
            self._sentry.set_phase(f"epoch {epoch}")
            self._sentry.check_args(self.state, label="train_state")
        if getattr(self.loader, "device_arrays", None) is not None:
            return self._run_epoch_scanned(epoch)
        if (
            getattr(self.loader, "iter_chunks", None) is not None
            and self.grad_accum_steps == 1
        ):
            # grad accumulation composes with the per-step path only (its
            # microbatching lives inside make_train_step)
            return self._run_epoch_chunked(epoch)
        self.loader.set_epoch(epoch)  # reference ddp_gpus.py:45
        self.metrics.say(
            epoch_line(
                self.strategy.num_devices,
                epoch,
                self.loader.per_device_batch,
                len(self.loader),
            )
        )
        t0 = time.perf_counter()
        loss = None
        steps = 0
        for batch in self.loader:
            if not isinstance(batch, tuple):
                batch = (batch,)
            self._dispatches += 1
            if self.chaos is not None and self.chaos.poisons_batch:
                batch = chaos_lib.maybe_poison_batch(
                    self.chaos, self._dispatches, batch
                )
            self.state, metrics = self.train_step(self.state, batch)
            loss = metrics["loss"]
            steps += 1
            # device scalar retained un-fetched; the verbose line is the
            # log_every opt-in and costs its one historical loss fetch.
            # The skip-step counter (guard on only) rides the same batched
            # drain as the loss — still no per-step sync.
            self.metrics.log_step(
                steps, loss,
                verbose=bool(self.log_every)
                and steps % self.log_every == 0,
                extra=(
                    {"skipped": metrics["skipped"]}
                    if "skipped" in metrics else None
                ),
            )
            if self.on_step is not None:
                self.on_step(steps, loss)
            if self._rb_factor is not None:
                # rollback opted in: per-step loss visibility is its price
                self._monitor_loss(float(loss))
        jax.block_until_ready(self.state.params)
        dt = time.perf_counter() - t0
        return self._epoch_metrics(epoch, loss, steps, dt)

    def train(self, max_epochs: int) -> dict:
        """Run up to epoch ``max_epochs`` (reference ``ddp_gpus.py:51-53``).

        Starts from ``self.epoch``, so a trainer restored from a checkpoint
        continues where it left off instead of retraining from scratch (the
        reference is restart-safe only by being stateless — SURVEY.md
        section 5.3/5.4; this closes that gap).
        """
        if self.epoch >= max_epochs:
            self.metrics.say(
                f"train: already at epoch {self.epoch} >= {max_epochs}, "
                "nothing to run"
            )
            # same key shape as a real epoch so metric consumers don't branch
            self.last_epoch_metrics = {
                "epoch": self.epoch, "loss": float("nan"), "steps": 0,
                "steps_per_sec": 0.0, "samples_per_sec": 0.0,
                "skipped": True,
            }
            return self.last_epoch_metrics
        for epoch in range(self.epoch, max_epochs):
            self.last_epoch_metrics = self._run_epoch(epoch)
            self.epoch = epoch + 1
        return self.last_epoch_metrics

    # -- loss-spike rollback (ISSUE 9 guardrail) ---------------------------
    def _monitor_loss(self, loss_value: float) -> bool:
        """Feed one host-float loss observation to the spike monitor;
        returns True when it triggered a rollback. A spike is a value
        exceeding ``rollback_spike_factor`` x the EMA of healthy
        observations (or any non-finite value); ``rollback_patience``
        consecutive spikes trigger. Spiky observations are NEVER folded
        into the EMA (a sustained spike must not normalize itself), and
        the monitor's host step counter is monotonic across rollbacks —
        a chaos-injected spike keyed to it cannot re-fire after the
        restore (the livelock a state.step-keyed injector would hit)."""
        import math

        self._monitor_steps += 1
        if self.chaos is not None:
            loss_value = chaos_lib.host_spike_loss(
                loss_value, self._monitor_steps, self.chaos
            )
        spike = not math.isfinite(loss_value) or (
            self._rb_ema is not None
            and loss_value > self._rb_factor * self._rb_ema
        )
        if spike:
            self._rb_strikes += 1
            if self._rb_strikes >= self._rb_patience:
                self._do_rollback(loss_value)
                return True
            return False
        self._rb_strikes = 0
        d = self._rb_decay
        self._rb_ema = (
            loss_value if self._rb_ema is None
            else d * self._rb_ema + (1.0 - d) * loss_value
        )
        return False

    def _do_rollback(self, loss_value: float) -> None:
        """Restore the latest ``save()`` target and continue training.

        Restore-and-continue semantics: the TrainState (params/opt/step)
        rolls back; the data position (``self.epoch``) does NOT — the
        batches that drove the spike are skipped, not replayed, which is
        both the standard divergence recovery and what keeps a
        deterministic spike from re-firing. The monitor resets (EMA and
        strikes) so post-restore losses re-seed it."""
        if self._last_ckpt is None:
            raise RuntimeError(
                "loss-spike rollback triggered but no checkpoint exists — "
                "call save() at least once (e.g. per epoch) when "
                "rollback_spike_factor is set"
            )
        epoch_now = self.epoch
        self.restore(self._last_ckpt)
        self.epoch = epoch_now  # keep the data position (skip, don't replay)
        self.rollbacks += 1
        self._rb_strikes = 0
        self._rb_ema = None
        if self._flight is not None:
            # host-side already (the monitor observes fetched floats) —
            # stamping adds no sync; auto-dumps when dump_path is set
            self._flight.rollback(
                step=self._monitor_steps, loss=loss_value
            )
        self.metrics.say(
            f"  rollback #{self.rollbacks}: loss {loss_value:.4g} spiked "
            f">{self._rb_factor:g}x EMA for {self._rb_patience} obs — "
            f"restored {self._last_ckpt}"
        )

    @property
    def steps_skipped(self) -> int:
        """Total skip-step elisions recorded so far (``skip_nonfinite``
        path). Flushes the metrics logger — i.e. performs its batched
        drain fetch — so call at receipt/epoch boundaries, not per step."""
        self.metrics.flush()
        return int(
            sum(e.get("skipped", 0) for e in self.metrics.step_events())
        )

    # -- checkpoint / resume (SURVEY.md section 5.4 gap fix) ---------------
    def _state_tree(self) -> dict:
        import numpy as np

        tree = {
            "step": self.state.step,
            "params": self.state.params,
            "opt_state": self.state.opt_state,
            # host scalar, not a device array: a per-process
            # SingleDeviceSharding leaf would break multi-host orbax saves
            "epoch": np.asarray(self.epoch, np.int32),
        }
        if self.has_batch_stats:
            tree["batch_stats"] = self.state.batch_stats
        return tree

    def save(self, path, keep: int | None = None) -> None:
        """Sharded checkpoint of params/optimizer/step/epoch (orbax —
        each host writes only its addressable shards). ATOMIC either way
        (ISSUE 9): a crash mid-save can never corrupt the latest restore
        target, which the rollback leg and restart-resume both depend on.

        ``keep=None`` (default): ``path`` is one checkpoint, overwritten
        atomically — the new tree lands in ``path + ".tmp"`` first, the
        previous checkpoint is parked at ``path + ".old"`` while the tmp
        renames into place, then the parked copy is deleted. At every
        instant either ``path`` or ``path + ".old"`` is a COMPLETE
        checkpoint (:meth:`restore` falls back to ``.old`` when ``path``
        is missing). Plain ``save_checkpoint`` would not give this:
        orbax's ``force=True`` removes the old directory BEFORE writing.

        ``keep=K``: ``path`` is a rotation directory of
        ``ckpt-{step:08d}`` children; each save writes a fresh child
        (tmp + rename — atomic because the target never pre-exists) and
        prunes all but the newest K. :meth:`restore` pointed at the
        directory resolves the newest child.

        Either form records the written target as the rollback restore
        point (``rollback_spike_factor``)."""
        import os
        import shutil

        from pytorch_distributed_training_tutorials_tpu.parallel.auto import (
            save_checkpoint,
        )

        path = os.path.abspath(os.fspath(path))
        if keep is not None:
            if keep < 1:
                raise ValueError(f"keep must be >= 1 (None = single), got {keep}")
            os.makedirs(path, exist_ok=True)
            name = f"ckpt-{int(self.state.step):08d}"
            target = os.path.join(path, name)
            tmp = target + ".tmp"
            if os.path.exists(tmp):
                shutil.rmtree(tmp)  # stale crash residue
            save_checkpoint(tmp, self._state_tree())
            if os.path.exists(target):
                shutil.rmtree(target)  # re-save at the same step
            os.rename(tmp, target)
            kids = sorted(
                d for d in os.listdir(path)
                if d.startswith("ckpt-") and not d.endswith(".tmp")
            )
            for d in kids[:-keep]:
                shutil.rmtree(os.path.join(path, d))
        else:
            tmp, old = path + ".tmp", path + ".old"
            for stale in (tmp, old):
                if os.path.exists(stale):
                    shutil.rmtree(stale)  # crash residue from a prior save
            save_checkpoint(tmp, self._state_tree())
            if os.path.exists(path):
                os.rename(path, old)
            os.rename(tmp, path)
            if os.path.exists(old):
                shutil.rmtree(old)
        self._last_ckpt = path

    @staticmethod
    def _resolve_ckpt(path) -> str:
        """Map a restore path onto the atomic-save layout: a rotation
        directory resolves to its newest ``ckpt-*`` child; a missing
        single-checkpoint path falls back to the ``.old`` parked copy
        (present exactly when a crash hit the rename window)."""
        import os

        path = os.path.abspath(os.fspath(path))
        if os.path.isdir(path):
            kids = sorted(
                d for d in os.listdir(path)
                if d.startswith("ckpt-") and not d.endswith(".tmp")
            )
            if kids:
                return os.path.join(path, kids[-1])
        if not os.path.exists(path) and os.path.exists(path + ".old"):
            return path + ".old"
        return path

    def restore(self, path) -> None:
        """Restore in place, preserving the current sharding layout (the
        template tree's shardings drive orbax's placement). Accepts a
        plain checkpoint, a ``save(keep=K)`` rotation directory (newest
        child wins), or a crash-windowed single path (``.old``
        fallback)."""
        from pytorch_distributed_training_tutorials_tpu.parallel.auto import (
            restore_checkpoint,
        )

        restored = restore_checkpoint(
            self._resolve_ckpt(path), like=self._state_tree()
        )
        self.epoch = int(restored.pop("epoch"))
        self.state = self.state.replace(**restored)

    # -- evaluation (the reference never evaluates — SURVEY.md 5.5) --------
    def evaluate(self, eval_loader=None) -> dict:
        """Mean loss (the trainer's configured loss) + accuracy (for
        integer-label classification; 0.0 otherwise) over ``eval_loader``
        (default: the training loader).

        Wrap-padded duplicate rows (the equal-shard padding SPMD requires)
        are **masked out** when the loader can identify them
        (:meth:`..data.loader.ShardedLoader.valid_mask`), so metrics are
        unbiased on datasets that don't divide evenly — unlike the
        reference, whose DistributedSampler silently double-counts the pad.

        The returned ``"samples"`` counts *label positions*: for sequence
        targets (an LM's (B, T) labels) that is rows x tokens, not rows.
        Per-batch sums are float32 on device (exact up to 2^24 labels per
        batch); the cross-batch accumulation happens on host in float64.
        """
        import numpy as np

        from jax.sharding import NamedSharding, PartitionSpec

        loader = eval_loader if eval_loader is not None else self.loader
        if self._eval_step is None:
            self._eval_step = make_eval_step(
                self.loss_name, self.has_batch_stats
            )
        has_mask = hasattr(loader, "valid_mask")
        if has_mask and loader.axis in loader.mesh.shape:
            mask_sharding = NamedSharding(
                loader.mesh, PartitionSpec(loader.axis)
            )
        elif has_mask:
            # loader on a mesh without its batch axis (replicated batches,
            # e.g. a stage-only mesh with a custom batch_spec)
            mask_sharding = NamedSharding(loader.mesh, PartitionSpec())
        else:
            mask_sharding = None
        # accumulate device arrays; convert once after the loop so eval
        # dispatch stays async (a float() per batch would sync every step)
        losses, corrects, counts = [], [], []
        mask_cache: dict = {}  # padding lives in the tail steps; interior
        # steps share one all-ones mask — transfer each distinct mask once

        def device_mask(step, rows):
            if not has_mask:
                key = b"ones"
                if key not in mask_cache:
                    mask_cache[key] = jnp.ones((rows,), jnp.float32)
                return mask_cache[key]
            m = loader.valid_mask(step).astype(np.float32)
            key = m.tobytes()
            if key not in mask_cache:
                mask_cache[key] = jax.device_put(m, mask_sharding)
            return mask_cache[key]

        for step, batch in enumerate(loader):
            if not isinstance(batch, tuple) or len(batch) != 2:
                raise ValueError("evaluate() requires (x, y) batches")
            mask = device_mask(step, batch[0].shape[0])
            ls, c, n = self._eval_step(self.state, batch, mask)
            losses.append(ls)
            corrects.append(c)
            counts.append(n)
        loss_sum = float(sum(float(l) for l in jax.device_get(losses)))
        correct = int(sum(int(c) for c in jax.device_get(corrects)))
        seen = int(sum(float(n) for n in jax.device_get(counts)))
        return {
            "loss": loss_sum / max(seen, 1),
            "accuracy": correct / max(seen, 1),
            "samples": seen,
        }
