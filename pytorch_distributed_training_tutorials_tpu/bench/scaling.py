"""DDP allreduce scaling-efficiency sweep: 1 -> N chips.

The BASELINE north-star beyond raw throughput is *scaling*: >=90% ICI
allreduce efficiency from 1 to 32 chips on the ResNet-18 data-parallel
workload (``/root/repo/BASELINE.json:5``; the reference's own comparison is
the 2-GPU-vs-1 wall-clock chart at
``/root/reference/03.model_parallel.ipynb:1014-1037``). This module is the
sweep harness: weak scaling (fixed per-device batch), one mesh width at a
time, slope-timed so async dispatch and host-roundtrip latency cannot lie.

Efficiency definition (weak scaling): with per-device batch ``b`` held
constant, a D-chip run's ``images/s/chip`` divided by the 1-chip run's.
Perfect overlap of the gradient allreduce with the backward gives 1.0;
an exposed allreduce shows up directly as lost efficiency.

Runs unchanged on a CPU mesh (``--xla_force_host_platform_device_count``)
for CI smoke tests and on a real pod slice for the certified number —
device widths come from ``jax.devices()`` either way.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass

import jax
import numpy as np

from pytorch_distributed_training_tutorials_tpu.bench.harness import slope_time


@dataclass
class ScalePoint:
    """One mesh width's measurement."""

    num_chips: int
    per_device_batch: int
    global_batch: int
    step_time_s: float
    images_per_sec: float
    images_per_sec_per_chip: float
    efficiency: float  # vs the 1-chip (or narrowest) width


def _default_model_and_data(per_device_batch: int, image_px: int):
    import optax

    from pytorch_distributed_training_tutorials_tpu.models import resnet18

    model = resnet18(num_classes=10, stem="cifar")
    tx = optax.sgd(1e-2, momentum=0.9)

    def make_batch(global_batch: int):
        rng = np.random.Generator(np.random.PCG64(0))
        x = rng.standard_normal(
            (global_batch, image_px, image_px, 1)
        ).astype(np.float32)
        y = rng.integers(0, 10, global_batch).astype(np.int32)
        return x, y

    return model, tx, make_batch


def sweep(
    widths=None,
    *,
    per_device_batch: int = 64,
    image_px: int = 28,
    model=None,
    tx=None,
    make_batch=None,
    n1: int = 3,
    n2: int = 10,
) -> list[ScalePoint]:
    """Measure images/s/chip at each data-parallel mesh width.

    ``widths`` defaults to powers of two up to ``len(jax.devices())``.
    Each width gets its own ``{'data': D}`` mesh over a device prefix, a
    fresh replicated train state, and a slope-timed run of the jitted
    train step on a resident batch — the collective cost being measured is
    the gradient allreduce, exactly DDP's (reference ``ddp_gpus.py:38``).
    """
    from pytorch_distributed_training_tutorials_tpu.parallel.data_parallel import (
        DataParallel,
    )
    from pytorch_distributed_training_tutorials_tpu.parallel.mesh import create_mesh
    from pytorch_distributed_training_tutorials_tpu.train.trainer import (
        create_train_state,
        make_train_step,
    )

    devices = jax.devices()
    if not widths:  # None or [] (a bare --widths flag): powers of 2
        widths = []
        w = 1
        while w <= len(devices):
            widths.append(w)
            w *= 2
    widths = sorted(set(widths))
    if widths[-1] > len(devices):
        raise ValueError(
            f"width {widths[-1]} exceeds {len(devices)} available devices"
        )

    if model is None:
        model, tx, make_batch = _default_model_and_data(
            per_device_batch, image_px
        )
    elif tx is None or make_batch is None:
        raise ValueError(
            "sweep(model=...) requires tx and make_batch as well"
        )

    points: list[ScalePoint] = []
    base_per_chip: float | None = None
    for width in widths:
        mesh = create_mesh({"data": width}, devices=devices[:width])
        dp = DataParallel(mesh)
        global_batch = per_device_batch * width
        x, y = make_batch(global_batch)
        batch = (dp.shard_batch(x), dp.shard_batch(y))
        state = create_train_state(model, tx, x, strategy=dp)
        has_bn = state.batch_stats is not None
        step = make_train_step(loss="cross_entropy", has_batch_stats=has_bn)

        # state is donated: thread it through the chained steps
        state_box = [state]

        def run(k):
            s = state_box[0]
            for _ in range(k):
                s, metrics = step(s, batch)
            state_box[0] = s
            return float(metrics["loss"])

        dt = slope_time(run, n1=n1, n2=n2, warmup=2)
        per_chip = global_batch / dt / width
        if base_per_chip is None:
            base_per_chip = per_chip
        points.append(
            ScalePoint(
                num_chips=width,
                per_device_batch=per_device_batch,
                global_batch=global_batch,
                step_time_s=dt,
                images_per_sec=global_batch / dt,
                images_per_sec_per_chip=per_chip,
                efficiency=per_chip / base_per_chip,
            )
        )
    return points


def report(points: list[ScalePoint], *, workload: str | None = None) -> dict:
    """JSON-ready sweep summary (the shape committed as scaling JSON)."""
    return {
        "metric": "ddp_weak_scaling_efficiency",
        "workload": workload
        or "resnet18 synthetic images, cross-entropy, sgd+momentum",
        "backend": jax.default_backend(),
        "points": [asdict(p) for p in points],
        "efficiency_at_max_width": points[-1].efficiency if points else None,
    }


def main() -> None:
    import argparse
    import os

    if os.environ.get("JAX_PLATFORMS"):
        # this build's sitecustomize pre-imports jax._src, so the env var
        # alone can be captured too late — forward it via the config API
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--widths", type=int, nargs="*", default=None,
        help="mesh widths to sweep (default: powers of 2 up to all devices)",
    )
    parser.add_argument("--per_device_batch", type=int, default=64)
    parser.add_argument("--image_px", type=int, default=28)
    parser.add_argument("--out", type=str, default=None, help="JSON path")
    args = parser.parse_args()

    points = sweep(
        args.widths,
        per_device_batch=args.per_device_batch,
        image_px=args.image_px,
    )
    rep = report(
        points,
        workload=(
            f"resnet18 synthetic {args.image_px}x{args.image_px}, "
            "cross-entropy, sgd+momentum"
        ),
    )
    for p in points:
        print(
            f"  {p.num_chips:>3} chips: {p.images_per_sec_per_chip:,.0f} "
            f"img/s/chip, efficiency {p.efficiency:.3f}"
        )
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rep, f, indent=1)
        print(f"wrote {args.out}")
    else:
        print(json.dumps(rep))


if __name__ == "__main__":
    main()
