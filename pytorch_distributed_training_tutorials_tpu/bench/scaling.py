"""DDP allreduce scaling-efficiency sweep: 1 -> N chips.

The BASELINE north-star beyond raw throughput is *scaling*: >=90% ICI
allreduce efficiency from 1 to 32 chips on the ResNet-18 data-parallel
workload (``/root/repo/BASELINE.json:5``; the reference's own comparison is
the 2-GPU-vs-1 wall-clock chart at
``/root/reference/03.model_parallel.ipynb:1014-1037``). This module is the
sweep harness: weak scaling (fixed per-device batch), one mesh width at a
time, slope-timed so async dispatch and host-roundtrip latency cannot lie.

Efficiency definition (weak scaling): with per-device batch ``b`` held
constant, a D-chip run's ``images/s/chip`` divided by the 1-chip run's.
Perfect overlap of the gradient allreduce with the backward gives 1.0;
an exposed allreduce shows up directly as lost efficiency.

Runs unchanged on a CPU mesh (``--xla_force_host_platform_device_count``)
for CI smoke tests and on a real pod slice for the certified number —
device widths come from ``jax.devices()`` either way.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass

import jax
import numpy as np

from pytorch_distributed_training_tutorials_tpu.bench.harness import slope_time


@dataclass
class ScalePoint:
    """One mesh width's measurement."""

    num_chips: int
    per_device_batch: int
    global_batch: int
    step_time_s: float
    images_per_sec: float
    images_per_sec_per_chip: float
    efficiency: float  # vs the 1-chip (or narrowest) width


def _default_model_and_data(per_device_batch: int, image_px: int):
    import optax

    from pytorch_distributed_training_tutorials_tpu.models import resnet18

    model = resnet18(num_classes=10, stem="cifar")
    tx = optax.sgd(1e-2, momentum=0.9)

    def make_batch(global_batch: int):
        rng = np.random.Generator(np.random.PCG64(0))
        x = rng.standard_normal(
            (global_batch, image_px, image_px, 1)
        ).astype(np.float32)
        y = rng.integers(0, 10, global_batch).astype(np.int32)
        return x, y

    return model, tx, make_batch


def sweep(
    widths=None,
    *,
    per_device_batch: int = 64,
    image_px: int = 28,
    model=None,
    tx=None,
    make_batch=None,
    n1: int = 3,
    n2: int = 10,
) -> list[ScalePoint]:
    """Measure images/s/chip at each data-parallel mesh width.

    ``widths`` defaults to powers of two up to ``len(jax.devices())``.
    Each width gets its own ``{'data': D}`` mesh over a device prefix, a
    fresh replicated train state, and a slope-timed run of the jitted
    train step on a resident batch — the collective cost being measured is
    the gradient allreduce, exactly DDP's (reference ``ddp_gpus.py:38``).
    """
    from pytorch_distributed_training_tutorials_tpu.parallel.data_parallel import (
        DataParallel,
    )
    from pytorch_distributed_training_tutorials_tpu.parallel.mesh import create_mesh
    from pytorch_distributed_training_tutorials_tpu.train.trainer import (
        create_train_state,
        make_train_step,
    )

    devices = jax.devices()
    if not widths:  # None or [] (a bare --widths flag): powers of 2
        widths = []
        w = 1
        while w <= len(devices):
            widths.append(w)
            w *= 2
    widths = sorted(set(widths))
    if widths[-1] > len(devices):
        raise ValueError(
            f"width {widths[-1]} exceeds {len(devices)} available devices"
        )

    if model is None:
        model, tx, make_batch = _default_model_and_data(
            per_device_batch, image_px
        )
    elif tx is None or make_batch is None:
        raise ValueError(
            "sweep(model=...) requires tx and make_batch as well"
        )

    points: list[ScalePoint] = []
    base_per_chip: float | None = None
    for width in widths:
        mesh = create_mesh({"data": width}, devices=devices[:width])
        dp = DataParallel(mesh)
        global_batch = per_device_batch * width
        x, y = make_batch(global_batch)
        batch = (dp.shard_batch(x), dp.shard_batch(y))
        state = create_train_state(model, tx, x, strategy=dp)
        has_bn = state.batch_stats is not None
        step = make_train_step(loss="cross_entropy", has_batch_stats=has_bn)

        # state is donated: thread it through the chained steps
        state_box = [state]

        def run(k):
            s = state_box[0]
            for _ in range(k):
                s, metrics = step(s, batch)
            state_box[0] = s
            return float(metrics["loss"])

        dt = slope_time(run, n1=n1, n2=n2, warmup=2)
        per_chip = global_batch / dt / width
        if base_per_chip is None:
            base_per_chip = per_chip
        points.append(
            ScalePoint(
                num_chips=width,
                per_device_batch=per_device_batch,
                global_batch=global_batch,
                step_time_s=dt,
                images_per_sec=global_batch / dt,
                images_per_sec_per_chip=per_chip,
                efficiency=per_chip / base_per_chip,
            )
        )
    return points


def report(points: list[ScalePoint], *, workload: str | None = None) -> dict:
    """JSON-ready sweep summary (the shape committed as scaling JSON)."""
    return {
        "metric": "ddp_weak_scaling_efficiency",
        "workload": workload
        or "resnet18 synthetic images, cross-entropy, sgd+momentum",
        "backend": jax.default_backend(),
        "points": [asdict(p) for p in points],
        "efficiency_at_max_width": points[-1].efficiency if points else None,
    }


def main() -> None:
    import argparse
    import os

    if os.environ.get("JAX_PLATFORMS"):
        # this build's sitecustomize pre-imports jax._src, so the env var
        # alone can be captured too late — forward it via the config API
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--widths", type=int, nargs="*", default=None,
        help="mesh widths to sweep (default: powers of 2 up to all devices)",
    )
    parser.add_argument("--per_device_batch", type=int, default=64)
    parser.add_argument("--image_px", type=int, default=28)
    parser.add_argument("--out", type=str, default=None, help="JSON path")
    parser.add_argument(
        "--hlo_roofline", action="store_true",
        help="also extract per-width collective bytes from compiled HLO "
        "and emit a v4-32 ring-allreduce roofline PREDICTION (no "
        "hardware executed for it)",
    )
    parser.add_argument(
        "--predict_chips", type=int, default=32,
        help="target width for the roofline prediction",
    )
    parser.add_argument(
        "--predict_step_ms", type=float, default=10.23,
        help="measured single-chip step time anchoring the prediction "
        "(default: the ResNet-18 bs512 bf16 v5e trace anchor, "
        "PROFILE_r04.md — restate when predicting other workloads)",
    )
    args = parser.parse_args()

    points = sweep(
        args.widths,
        per_device_batch=args.per_device_batch,
        image_px=args.image_px,
    )
    rep = report(
        points,
        workload=(
            f"resnet18 synthetic {args.image_px}x{args.image_px}, "
            "cross-entropy, sgd+momentum"
        ),
    )
    if args.hlo_roofline:
        stats = [
            collective_stats(
                p.num_chips,
                per_device_batch=args.per_device_batch,
                image_px=args.image_px,
            )
            for p in points
            if p.num_chips > 1
        ]
        rep["hlo_collectives"] = stats
        if stats:
            # ring payload is width-independent; use the widest compiled
            payload = (
                stats[-1]["collectives"].get("all-reduce", {}).get("bytes", 0)
            )
            rep["ici_roofline_prediction"] = predict_ici_efficiency(
                payload,
                chips=args.predict_chips,
                step_compute_s=args.predict_step_ms / 1e3,
            )
            pr = rep["ici_roofline_prediction"]
            print(
                f"  roofline @ {args.predict_chips} chips: allreduce "
                f"{payload/1e6:.1f} MB -> efficiency floor "
                f"{pr['efficiency_no_overlap']:.3f}, ceiling "
                f"{pr['efficiency_full_overlap']:.3f} (PREDICTION)"
            )
    for p in points:
        print(
            f"  {p.num_chips:>3} chips: {p.images_per_sec_per_chip:,.0f} "
            f"img/s/chip, efficiency {p.efficiency:.3f}"
        )
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rep, f, indent=1)
        print(f"wrote {args.out}")
    else:
        print(json.dumps(rep))



# ---------------------------------------------------------------------------
# HLO collective roofline (no hardware required)
#
# The CPU sweep above certifies SPMD correctness, but 8 virtual devices on
# one core cannot say anything about ICI efficiency at real widths. What CAN
# be said without hardware: the compiled program's collective traffic is in
# the HLO — XLA compiles the gradient allreduce into explicit all-reduce ops
# whose operand shapes give exact per-device payload bytes. Combined with a
# measured single-chip step time (the bench anchor) and the ring-allreduce
# cost model, that yields a principled roofline *prediction* for the
# BASELINE >=90%-at-32-chips target, clearly labeled as a prediction.
# ---------------------------------------------------------------------------

_SHAPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
    "s64": 8, "s32": 4, "s16": 2, "s8": 1, "s4": 1,
    "u64": 8, "u32": 4, "u16": 2, "u8": 1, "u4": 1,
    "c64": 8, "c128": 16, "pred": 1,
}

# Base names plus XLA's async split forms: the TPU latency-hiding
# scheduler rewrites `all-reduce` into `all-reduce-start`/`-done` pairs in
# the optimized HLO. `-start` carries the payload shape; `-done` is
# counted as zero bytes so a pair isn't double-counted.
_COLLECTIVE_BASES = (
    "all-reduce", "reduce-scatter", "all-gather", "collective-permute",
    "all-to-all",
)
_COLLECTIVES = tuple(
    base + suffix for base in _COLLECTIVE_BASES
    for suffix in ("-start", "-done", "")
)


def _shape_nbytes(shape_str: str) -> int:
    """Bytes of one HLO shape literal like ``f32[64,128]{1,0:T(8,128)}``."""
    import re

    m = re.match(r"(\w+)\[([\d,]*)\]", shape_str)
    if not m:
        return 0
    dtype, dims = m.group(1), m.group(2)
    if dtype not in _SHAPE_BYTES:
        raise ValueError(
            f"unknown HLO dtype {dtype!r} in {shape_str!r} — add it to "
            "_SHAPE_BYTES (silently counting 0 would under-report the "
            "collective payload)"
        )
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _SHAPE_BYTES[dtype]


def collective_footprint(hlo_text: str) -> dict:
    """Per-collective op counts and payload bytes from compiled HLO text.

    Sums the OUTPUT shape bytes of every collective instruction (for
    all-reduce the payload each device contributes and receives; tuples —
    XLA's fused gradient buckets — are summed element-wise). Returns
    ``{"all-reduce": {"ops": N, "bytes": B}, ...}`` plus a ``"total"``.
    """
    import re

    out: dict = {}
    for line in hlo_text.splitlines():
        line = line.strip()
        m = re.match(
            r"%?[\w.\-]+\s*=\s*(\([^)]*\)|[\w\[\],{}:()\s]+?)\s+"
            r"(" + "|".join(_COLLECTIVES) + r")\(",
            line,
        )
        if not m:
            continue
        shapes_str, op = m.group(1), m.group(2)
        done = op.endswith("-done")
        for suffix in ("-start", "-done"):
            if op.endswith(suffix):
                op = op[: -len(suffix)]
        d = out.setdefault(op, {"ops": 0, "bytes": 0})
        if done:
            continue  # payload already counted on the matching -start
        shapes = re.findall(r"\w+\[[\d,]*\](?:\{[^}]*\})?", shapes_str)
        nbytes = sum(_shape_nbytes(sh) for sh in shapes)
        d["ops"] += 1
        d["bytes"] += nbytes
    out["total"] = {
        "ops": sum(v["ops"] for v in out.values()),
        "bytes": sum(v["bytes"] for v in out.values()),
    }
    return out


def collective_stats(width: int, *, per_device_batch: int = 64,
                     image_px: int = 28, model=None, tx=None,
                     make_batch=None) -> dict:
    """Compile the DDP train step for a ``{'data': width}`` mesh and
    extract its collective footprint from the optimized HLO.

    Needs ``width`` (virtual) devices — run under
    ``--xla_force_host_platform_device_count=N`` for widths beyond the
    host's real device count. Nothing executes; this is AOT lowering only.
    (It recompiles the step ``sweep()`` already compiled — accepted so the
    function stays usable WITHOUT running a sweep; the cost is one XLA
    compile per width on the receipt-generation path only.)
    """
    from pytorch_distributed_training_tutorials_tpu.parallel.data_parallel import (
        DataParallel,
    )
    from pytorch_distributed_training_tutorials_tpu.parallel.mesh import create_mesh
    from pytorch_distributed_training_tutorials_tpu.train.trainer import (
        create_train_state,
        make_train_step,
    )

    if model is None:
        model, tx, make_batch = _default_model_and_data(
            per_device_batch, image_px
        )
    mesh = create_mesh({"data": width}, devices=jax.devices()[:width])
    dp = DataParallel(mesh)
    global_batch = per_device_batch * width
    x, y = make_batch(global_batch)
    batch = (dp.shard_batch(x), dp.shard_batch(y))
    state = create_train_state(model, tx, x, strategy=dp)
    step = make_train_step(
        loss="cross_entropy", has_batch_stats=state.batch_stats is not None
    )
    compiled = step.lower(state, batch).compile()
    stats = collective_footprint(compiled.as_text())
    grad_bytes = 4 * sum(
        l.size for l in jax.tree_util.tree_leaves(state.params)
    )
    return {
        "num_chips": width,
        "collectives": stats,
        "f32_grad_bytes": grad_bytes,
    }


def predict_ici_efficiency(
    allreduce_bytes: int,
    *,
    chips: int = 32,
    step_compute_s: float,
    ici_bytes_per_s: float = 1.0e11,
) -> dict:
    """Ring-allreduce roofline at a target width — a PREDICTION, labeled.

    Model: a D-chip ring all-reduce moves ``2*(D-1)/D * payload`` bytes
    through each chip's ICI links (reduce-scatter + all-gather phases).
    ``ici_bytes_per_s`` defaults to 1e11 (100 GB/s) — a conservative
    per-chip algorithmic bandwidth for a v4 3D-torus ring (each v4 link
    runs ~50 GB/s/direction and a torus ring uses two of them; the
    scaling-book recipe). Two bounds are reported: ``efficiency_no_overlap``
    (the allreduce fully exposed after the backward — the floor) and
    ``efficiency_full_overlap`` (allreduce hidden under the backward's
    ~2/3 of step compute except any residue — the ceiling XLA's latency-
    hiding scheduler approaches when per-bucket allreduces interleave with
    grad computation).
    """
    ring = 2.0 * (chips - 1) / chips
    t_comm = ring * allreduce_bytes / ici_bytes_per_s
    no_overlap = step_compute_s / (step_compute_s + t_comm)
    backward_s = (2.0 / 3.0) * step_compute_s
    exposed = max(0.0, t_comm - backward_s)
    full_overlap = step_compute_s / (step_compute_s + exposed)
    return {
        "prediction": True,
        "chips": chips,
        "allreduce_payload_bytes": int(allreduce_bytes),
        "ici_bytes_per_s_assumed": ici_bytes_per_s,
        "ring_allreduce_s": t_comm,
        "step_compute_s": step_compute_s,
        "efficiency_no_overlap": no_overlap,
        "efficiency_full_overlap": full_overlap,
    }


if __name__ == "__main__":
    main()
