"""The canonical headline-benchmark recipe, in one place.

``bench.py``, ``scripts/profile_step.py``, and
``scripts/step_time_experiment.py`` all measure the same program — the
ResNet-18 bs512 bf16 MNIST data-parallel train step (BASELINE.json's north
star). This module owns that setup so a change to the workload (batch,
transform, optimizer) cannot silently desynchronize what the profiler or
an experiment script measures from what the headline bench reports.
"""

from __future__ import annotations

import dataclasses
from typing import Any


@dataclasses.dataclass
class HeadlineSetup:
    mesh: Any
    loader: Any          # DeviceResidentLoader over raw-uint8 MNIST
    trainer: Any
    batch: Any           # one transformed, device-ready cached batch
    step_fn: Any         # raw (unjitted) train step
    per_device_batch: int
    dataset: Any


def make_headline_setup(
    per_device_batch: int = 512, quiet: bool = False
) -> HeadlineSetup:
    """Build the headline workload: uint8-resident MNIST, bf16 cifar-stem
    ResNet-18, SGD+momentum trainer, plus a cached batch and the raw step
    function for chain-timing legs. ``quiet`` silences the trainer's epoch
    chatter (bench runs) without losing structured metrics."""
    import jax
    import jax.numpy as jnp
    import optax

    from pytorch_distributed_training_tutorials_tpu.data import (
        DeviceResidentLoader,
        ShardedLoader,
        mnist,
    )
    from pytorch_distributed_training_tutorials_tpu.models import resnet18
    from pytorch_distributed_training_tutorials_tpu.parallel.mesh import (
        create_mesh,
    )
    from pytorch_distributed_training_tutorials_tpu.train import Trainer
    from pytorch_distributed_training_tutorials_tpu.train.trainer import (
        _train_step_fn,
    )

    mesh = create_mesh()
    ds = mnist("train", raw=True)
    loader = DeviceResidentLoader(
        ds, per_device_batch, mesh, seed=0,
        transform=lambda x, y: (x.astype(jnp.bfloat16) / 255.0, y),
    )
    model = resnet18(num_classes=10, stem="cifar", dtype=jnp.bfloat16)
    # scan_unroll=8 on the fused-epoch program: round 4 measured the
    # in-body-gather epoch scan as unroll-flat, but the round-5 re-measure
    # (min-of-3 over 5-fused-epoch runs, same protocol as the headline
    # leg) shows 46.5k -> 48.0k img/s at unroll=8 — the round-4 reading
    # was tunnel weather. BENCH_r05 carries the A/B.
    trainer = Trainer(
        model, loader, optax.sgd(0.05, momentum=0.9),
        loss="cross_entropy", scan_unroll=8, quiet=quiet,
    )
    streaming = ShardedLoader(ds, per_device_batch, mesh, seed=0)
    batch = jax.block_until_ready(
        loader._apply_transform(next(iter(streaming)))
    )
    step_fn = _train_step_fn("cross_entropy", has_batch_stats=True)
    return HeadlineSetup(
        mesh=mesh,
        loader=loader,
        trainer=trainer,
        batch=batch,
        step_fn=step_fn,
        per_device_batch=per_device_batch,
        dataset=ds,
    )


def make_step_chain(setup: HeadlineSetup, chain_len: int, unroll: int = 8):
    """The jitted cached-batch step chain (one launch + one fetch) used by
    the ``train_step_only`` bench leg and the profiler."""
    import jax

    batch, step_fn = setup.batch, setup.step_fn

    def chain(state):
        def body(s, _):
            s, m = step_fn(s, batch)
            return s, m["loss"]

        return jax.lax.scan(body, state, None, length=chain_len, unroll=unroll)

    return jax.jit(chain)
