"""Receipt-trajectory regression gate: compare rounds, fail on decay.

Every benchmark entry point stamps a ``graft-receipt/v1`` envelope
(:mod:`..obs.receipt`) and the repo checks the JSON in per round
(``BENCH_r0*.json``, ``SERVING_r0*.json``, ``TRAIN_LLM_r05.json``, ...),
but until now nothing COMPARED rounds — a perf regression only surfaced
if someone eyeballed two files. This is the minimal standing gate
(ROADMAP item 4): load every receipt, key it by (kind, measurement
config), order each key's receipts by round (the ``_rNN`` filename
convention), and exit nonzero when the newest round's throughput/MFU
falls more than ``--tolerance`` below the best earlier round.

Scope decisions that keep the cut honest:

- HIGHER-IS-BETTER rate metrics are gated (tok/s families + MFU + the
  bench headline ``value`` when its ``unit`` is a rate), and — since the
  flight recorder made the tails stable (ISSUE 10) — so are the
  LOWER-IS-BETTER p95 latency metrics (``LATENCY_METRICS``): the latest
  round must stay within ``(1 + tolerance) *`` the lowest earlier p95.
  p50s and wall-clock fields stay informational, their noise floor on
  the tunneled runtime is launch/stall-bound (CLAUDE.md);
- receipts only compare within an identical measurement config
  (preset/batch/lengths/dtype/... fingerprint): the 1b f32 and 1b-gqa
  int8 serving receipts are different experiments, not a trajectory;
- legacy (pre-schema) receipts participate — kind is inferred from the
  filename prefix and the payload validated by
  :func:`..obs.receipt.validate_receipt`'s legacy mode — so the gate
  covers the repo's whole measurement history, not just new rounds.

Run: ``python -m pytorch_distributed_training_tutorials_tpu.bench.regress [paths...]
[--tolerance 0.05] [--json]``. No paths = every ``*.json`` at the repo
root. jax-free by construction (receipt validation never imports jax),
so tier-1 smokes it as pure host code (tests/test_regress.py).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

from pytorch_distributed_training_tutorials_tpu.obs.receipt import (
    load_receipt,
    validate_receipt,
)

# gated metrics: higher is better; "value" only when the unit is a rate
RATE_METRICS = (
    "tokens_per_s",
    "decode_tok_per_s",
    "server_tok_per_s",
    "tok_per_s",
    "mfu",
)

# gated metrics: LOWER is better (ISSUE 10). p95 tails come from the
# flight recorder's streaming histograms, so they are finally stable
# enough to gate: the bucket geometry (not sort order over a noisy
# sample) sets their resolution, and the recorder primes/fetch contract
# keeps warmup compiles out of the sample. p50s stay informational —
# median latency on the tunneled runtime is launch/stall-bound noise.
LATENCY_METRICS = (
    "server_p95_latency_s",
    "server_ttft_p95_s",
    "ttft_p95_s",
    "e2e_p95_s",
)

# payload fields that identify WHAT was measured — receipts compare only
# within an identical fingerprint
CONFIG_FIELDS = (
    "metric", "unit", "workload", "preset", "batch", "per_device_batch",
    "seq", "prompt_len", "new_tokens", "max_seq_len", "kv_cache_dtype",
    "tp", "scan_layers", "attn", "n_chips", "n_devices", "temperature",
    "flash_prefill", "prefix_overlap",
    # speculative decoding: k and the draft n-gram order change what a
    # tok/s number MEANS (a spec round must never gate — or be gated
    # by — a non-speculative one); acceptance RATE stays out of the
    # fingerprint on purpose, it is a workload-dependent outcome, not
    # part of the configuration
    "spec_k", "spec_ngram", "speculative",
    # multi-tenant LoRA serving: bank geometry changes the measurement
    # (per-slot factor gathers in every forward), so adapter rounds and
    # base rounds are different experiments; occupancy/traffic counters
    # (adapters_registered, adapter_requests) stay out — workload
    # outcomes, not configuration
    "n_adapters", "lora_rank", "adapters",
    # robustness layer (ISSUE 9): fault injection / deadlines / the
    # finite-logits guard change what a round measures, so chaos rounds
    # never gate — or get gated by — clean rounds. The fault COUNTERS
    # (deadline_expired, cancelled, nonfinite_quarantined, steps_skipped)
    # stay out deliberately: they are outcomes of the traffic, not
    # configuration of the experiment
    "chaos", "deadline_s", "guard_nonfinite",
    # flight recorder (ISSUE 10): instrumented rounds carry host-side
    # bookkeeping in the request loop, so they never gate — or get gated
    # by — bare rounds; the recorder's own counters (flight_events,
    # flight_dumps, ...) stay out, outcomes not configuration
    "flight",
    # request-loop pipelining (ISSUE 11): double-buffered chains and
    # chunked prefill change the dispatch schedule a tok/s or TTFT
    # number was measured under, so pipelined and serial rounds are
    # different experiments; n_chunks stays out — an outcome of the
    # traffic mix, not configuration
    "pipeline_depth", "prefill_chunk",
    # fleet router (ISSUE 12): replica count, hedging delay, affinity
    # depth, and the offered load change what an aggregate tok/s or
    # tail-latency number MEANS, so fleet rounds and single-engine
    # rounds are different experiments; the health/ledger counters
    # (replicas_dead, redispatched, hedged, probes, ...) stay out
    # deliberately — outcomes of the injected faults and traffic, not
    # configuration of the experiment
    "n_replicas", "hedge", "affinity", "qps",
    # paged KV cache (ISSUE 13): the pool geometry changes what a tok/s
    # or HBM number MEANS (gathered page reads vs whole-slot reads,
    # admission by pages vs slots), so paged and whole-slot rounds are
    # different experiments; the occupancy counters (pages_high_water,
    # pages_shares, pages_sheds, hbm_high_water_bytes) stay out
    # deliberately — outcomes of the traffic, not configuration
    "paged", "page_size", "pool_pages",
    # fused paged attention + quantized KV (ISSUE 17): the page-walk
    # kernel vs the jnp.take gather read path and the KV storage width
    # (0 = full precision, 8 = int8 + f32 scales, 4 = packed nibbles +
    # bf16 scales) each change what a tok/s or HBM number MEANS, so
    # int4/kernel rounds never gate — or get gated by — int8/gather
    # ones; page_bytes stays out (derived from geometry + kv_bits, not
    # an independent knob)
    "kv_bits", "paged_kernel",
    # sharded serving (ISSUE 15): "tp" above already fingerprints the
    # TP width (the int8 decode receipts have carried it since r04);
    # mesh_shape additionally separates mesh GEOMETRIES at equal tp
    # (model:4 vs data:2,model:2 compile different collective schedules,
    # so their tok/s are different experiments). The audit outcomes
    # (tp_collectives, tp_hlo_ok) and the per-chip KV footprint stay
    # out — outcomes, not configuration
    "mesh_shape",
    # prefill/decode disaggregation (ISSUE 18): an engine's role and the
    # fleet's role geometry change what a tok/s or TTFT number MEANS
    # (a prefill replica's "throughput" is segments, a decode replica
    # never prefills, and 1p2d vs 2p1d are different experiments), so
    # disaggregated and monolithic rounds never gate each other; the
    # handoff counters (handoffs_out/in/moved) stay out — outcomes of
    # the traffic, not configuration
    "role", "n_prefill_replicas", "n_decode_replicas",
    # contract sentry (ISSUE 19): an instrumented round carries a
    # jax.device_get wrapper + a compile listener in the request loop
    # (host-only, but still instrumentation), so sentry-on and bare
    # rounds never gate each other; the sentry's own counters
    # (sentry_compiles, sentry_steady_recompiles, sentry_fetched,
    # sentry_reupload_bytes, ...) stay out — outcomes, not configuration
    "sentry",
    # SLO tiers (ISSUE 20): the class count and the preemption flag
    # change what a tok/s or per-class TTFT number MEANS (a preempting
    # engine trades low-class latency for high-class tails), so SLO
    # rounds never gate — or get gated by — FIFO rounds; the swap
    # counters (n_preemptions, n_swaps_out/in, swapped_now) and the
    # preempted-wait histogram stay out — outcomes of the traffic mix,
    # not configuration
    "priority_classes", "preemption",
)

_ROUND_RE = re.compile(r"_r(\d+)")


def _payload(obj: dict) -> dict:
    """The measurement dict: bench.py's min-of-N wrapper nests it under
    ``parsed`` (the checked-in BENCH_r0*.json shape); everything else is
    already flat."""
    parsed = obj.get("parsed")
    if isinstance(parsed, dict):
        return {**obj, **parsed}
    return obj


def _kind(obj: dict, path: str) -> str:
    """Schema'd receipts carry ``kind``; legacy ones are keyed by the
    filename family (``SERVING_r04_long.json`` -> ``serving``)."""
    if isinstance(obj.get("kind"), str):
        return obj["kind"]
    stem = os.path.basename(path)
    return stem.split("_")[0].split(".")[0].lower()


def _round(path: str) -> int:
    """Round number from the ``_rNN`` filename convention; -1 when the
    file carries none (sorts before every numbered round)."""
    m = _ROUND_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else -1


def _metrics(payload: dict) -> dict[str, float]:
    out = {}
    for name in RATE_METRICS + LATENCY_METRICS:
        v = payload.get(name)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[name] = float(v)
    v, unit = payload.get("value"), payload.get("unit")
    if (isinstance(v, (int, float)) and not isinstance(v, bool)
            and isinstance(unit, str) and "/s" in unit):
        out[f"value[{unit}]"] = float(v)
    return out


def _lower_is_better(name: str) -> bool:
    return name in LATENCY_METRICS


def _config_key(payload: dict) -> tuple:
    return tuple(
        (f, repr(payload[f])) for f in CONFIG_FIELDS if f in payload
    )


def collect(paths: list[str]) -> tuple[dict, list[str]]:
    """Load + validate receipts; group by (kind, config fingerprint).

    Returns ``(groups, skipped)``: ``groups`` maps the key to the
    round-ordered list of ``{path, round, metrics}`` records (files
    without any gated metric are dropped — COPYCHECK.json and friends
    are receipts of a different trade); ``skipped`` names files that
    failed validation, for the report."""
    groups: dict[tuple, list[dict]] = {}
    skipped: list[str] = []
    for path in paths:
        try:
            obj = load_receipt(path)
        except (OSError, json.JSONDecodeError):
            skipped.append(f"{path}: unreadable/not JSON")
            continue
        problems = validate_receipt(obj)
        if problems:
            skipped.append(f"{path}: {problems[0]}")
            continue
        payload = _payload(obj)
        metrics = _metrics(payload)
        if not metrics:
            continue  # a valid receipt with nothing this gate watches
        key = (_kind(obj, path), _config_key(payload))
        groups.setdefault(key, []).append({
            "path": path,
            "round": _round(path),
            "metrics": metrics,
        })
    for recs in groups.values():
        recs.sort(key=lambda r: (r["round"], r["path"]))
    return groups, skipped


def check(groups: dict, tolerance: float) -> list[dict]:
    """Regressions: for every key/metric with >= 2 rounds, the LATEST
    round must reach ``(1 - tolerance) *`` the best earlier round —
    or, for the lower-is-better latency tails, stay within
    ``(1 + tolerance) *`` the best (lowest) earlier round."""
    regressions = []
    for (kind, cfg), recs in groups.items():
        if len(recs) < 2:
            continue
        latest = recs[-1]
        for name, value in latest["metrics"].items():
            earlier = [
                r["metrics"][name] for r in recs[:-1]
                if name in r["metrics"]
            ]
            if not earlier:
                continue
            if _lower_is_better(name):
                best = min(earlier)
                bad = value > best * (1.0 + tolerance)
                drop = value / best - 1.0 if best > 0 else 0.0
            else:
                best = max(earlier)
                bad = value < best * (1.0 - tolerance)
                drop = 1.0 - value / best
            if bad:
                regressions.append({
                    "kind": kind,
                    "config": dict(cfg),
                    "metric": name,
                    "direction": (
                        "lower" if _lower_is_better(name) else "higher"
                    ),
                    "best_earlier": best,
                    "latest": value,
                    "latest_path": latest["path"],
                    "drop": drop,
                })
    return regressions


def _print_table(groups: dict, regressions: list[dict]) -> None:
    bad = {(r["kind"], r["metric"], r["latest_path"]) for r in regressions}
    for (kind, cfg), recs in sorted(groups.items(), key=str):
        desc = " ".join(f"{k}={v}" for k, v in cfg) or "(no config fields)"
        print(f"{kind}  {desc}")
        names = sorted({n for r in recs for n in r["metrics"]})
        for name in names:
            traj = [
                (r["round"], r["metrics"][name], r["path"])
                for r in recs if name in r["metrics"]
            ]
            line = " -> ".join(
                f"r{rd:02d} {v:g}" if rd >= 0 else f"{v:g}"
                for rd, v, _ in traj
            )
            arrow = " (lower is better)" if _lower_is_better(name) else ""
            status = ""
            if len(traj) == 1:
                status = "  (single round)"
            elif (kind, name, traj[-1][2]) in bad:
                status = "  REGRESSION"
            print(f"  {name}{arrow}: {line}{status}")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="fail when the newest receipt round regresses"
    )
    ap.add_argument("paths", nargs="*",
                    help="receipt files or directories (default: repo root)")
    ap.add_argument("--tolerance", type=float, default=0.05,
                    help="allowed fractional drop vs best earlier round")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    args = ap.parse_args(argv)
    if not 0.0 <= args.tolerance < 1.0:
        print("--tolerance must be in [0, 1)", file=sys.stderr)
        return 2

    paths: list[str] = []
    roots = args.paths or [
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    ]
    for p in roots:
        if os.path.isdir(p):
            paths.extend(sorted(glob.glob(os.path.join(p, "*.json"))))
        else:
            paths.append(p)

    groups, skipped = collect(paths)
    regressions = check(groups, args.tolerance)
    if args.json:
        print(json.dumps({
            "tolerance": args.tolerance,
            "n_files": len(paths),
            "n_groups": len(groups),
            "skipped": skipped,
            "regressions": regressions,
        }, indent=2, sort_keys=True))
    else:
        _print_table(groups, regressions)
        for s in skipped:
            print(f"skipped {s}")
        for r in regressions:
            cmp = ">" if r.get("direction") == "lower" else "<"
            print(
                f"REGRESSION {r['kind']}.{r['metric']}: "
                f"{r['latest']:g} {cmp} best {r['best_earlier']:g} "
                f"({100 * r['drop']:+.1f}%, tolerance "
                f"{100 * args.tolerance:.1f}%) [{r['latest_path']}]"
            )
        print(f"{len(groups)} trajectories, {len(regressions)} regressions")
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
