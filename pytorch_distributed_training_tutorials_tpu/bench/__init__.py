"""Benchmark harness (twin of reference C17)."""

from pytorch_distributed_training_tutorials_tpu.bench.harness import (  # noqa: F401
    benchmark,
    BenchResult,
)
