"""Benchmark harness (twin of reference C17).

Re-exports are PEP 562 lazy (same pattern as the top-level package
init): importing ``pytorch_distributed_training_tutorials_tpu.bench`` does not import jax, so the
jax-free :mod:`.regress` receipt gate can live here without dragging a
backend into CI. Heavyweight legs stay import-lazy too: bench.headline /
bench.scaling / bench.lm_headline are CLI modules (``python -m ...``)
and import jax state on use, not at package import
(tests/test_import_purity.py).
"""

import importlib

# name -> submodule; resolved on first access via __getattr__.
_LAZY_EXPORTS = {
    "benchmark": "pytorch_distributed_training_tutorials_tpu.bench.harness",
    "BenchResult": "pytorch_distributed_training_tutorials_tpu.bench.harness",
}

__all__ = sorted(_LAZY_EXPORTS)


def __getattr__(name: str):
    try:
        module_name = _LAZY_EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    value = getattr(importlib.import_module(module_name), name)
    globals()[name] = value  # cache: __getattr__ runs once per name
    return value


def __dir__():
    return sorted(set(globals()) | set(_LAZY_EXPORTS))
