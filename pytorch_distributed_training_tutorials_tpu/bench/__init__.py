"""Benchmark harness (twin of reference C17)."""

from pytorch_distributed_training_tutorials_tpu.bench.harness import (  # noqa: F401
    benchmark,
    BenchResult,
)

# heavyweight legs stay import-lazy: bench.headline / bench.scaling /
# bench.lm_headline are CLI modules (python -m ...) and import jax state
# on use, not at package import (tests/test_import_purity.py)
