"""Benchmark harness: honest wall-clock timing for XLA programs.

Twin of the reference's ``timeit.repeat("train(model)", number=1, repeat=10)``
micro-benchmark (reference ``03.model_parallel.ipynb:1014-1037``, cell 28) —
with the correction TPU requires (SURVEY.md section 5.1): XLA dispatch is
asynchronous, so naive ``timeit`` measures enqueue time, not compute.
Every timed region here ends with ``block_until_ready`` and the first
(compile) iterations are excluded as warmup.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from statistics import mean, stdev

import jax


@dataclass
class BenchResult:
    name: str
    times_s: list[float] = field(default_factory=list)

    @property
    def mean_s(self) -> float:
        return mean(self.times_s)

    @property
    def std_s(self) -> float:
        return stdev(self.times_s) if len(self.times_s) > 1 else 0.0

    def throughput(self, items_per_call: int) -> float:
        """items/sec at the mean time."""
        return items_per_call / self.mean_s

    def __str__(self) -> str:
        return f"{self.name}: {self.mean_s * 1e3:.2f} ms +/- {self.std_s * 1e3:.2f} ms"


def benchmark(fn, *, name: str = "bench", warmup: int = 2, repeat: int = 10) -> BenchResult:
    """Time ``fn()`` ``repeat`` times after ``warmup`` untimed calls.

    ``fn`` should return its result (or any array tied to the computation) so
    the harness can ``block_until_ready`` it inside the timed region.
    """
    for _ in range(warmup):
        jax.block_until_ready(fn())
    res = BenchResult(name)
    for _ in range(repeat):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        res.times_s.append(time.perf_counter() - t0)
    return res
