"""Benchmark harness: honest wall-clock timing for XLA programs.

Twin of the reference's ``timeit.repeat("train(model)", number=1, repeat=10)``
micro-benchmark (reference ``03.model_parallel.ipynb:1014-1037``, cell 28) —
with the correction TPU requires (SURVEY.md section 5.1): XLA dispatch is
asynchronous, so naive ``timeit`` measures enqueue time, not compute.
Every timed region here ends with ``block_until_ready`` and the first
(compile) iterations are excluded as warmup.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from statistics import mean, stdev

import jax


@dataclass
class BenchResult:
    name: str
    times_s: list[float] = field(default_factory=list)

    @property
    def mean_s(self) -> float:
        return mean(self.times_s)

    @property
    def std_s(self) -> float:
        return stdev(self.times_s) if len(self.times_s) > 1 else 0.0

    def throughput(self, items_per_call: int) -> float:
        """items/sec at the mean time."""
        return items_per_call / self.mean_s

    def __str__(self) -> str:
        return f"{self.name}: {self.mean_s * 1e3:.2f} ms +/- {self.std_s * 1e3:.2f} ms"


def slope_time(run, *, n1: int = 5, n2: int = 20, warmup: int = 2) -> float:
    """Seconds per step via two-point slope: ``(t(n2) - t(n1)) / (n2 - n1)``.

    ``run(k)`` must execute ``k`` *chained* device steps and end with a host
    fetch (e.g. ``float(loss)``). The slope cancels two systematic errors that
    make naive timing lie on remote/tunneled TPUs: (a) ``block_until_ready``
    returning before remote completion, and (b) the fixed host-roundtrip
    latency of the final fetch. Validated against an 8192^3 bf16 matmul chain
    reaching ~94% of v5e peak FLOPs.
    """
    for _ in range(warmup):
        run(1)
    t1 = _timed(run, n1)
    t2 = _timed(run, n2)
    return max((t2 - t1) / (n2 - n1), 1e-12)


def _timed(run, k: int) -> float:
    t0 = time.perf_counter()
    run(k)
    # graftcheck: disable=naive-timing -- slope_time's contract (docstring
    # above) requires the caller's run(k) to end with a real fetch; the
    # fetch lives in the closure, invisible to static analysis
    return time.perf_counter() - t0


def benchmark(fn, *, name: str = "bench", warmup: int = 2, repeat: int = 10) -> BenchResult:
    """Time ``fn()`` ``repeat`` times after ``warmup`` untimed calls.

    ``fn`` should return its result (or any array tied to the computation) so
    the harness can ``block_until_ready`` it inside the timed region.
    """
    for _ in range(warmup):
        jax.block_until_ready(fn())
    res = BenchResult(name)
    for _ in range(repeat):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        res.times_s.append(time.perf_counter() - t0)
    return res
