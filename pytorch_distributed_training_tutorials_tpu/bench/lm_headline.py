"""LM train-step MFU: the transformer training headline (bench leg).

The ResNet headline (bench.py) is conv-architecture-capped at ~57% MFU
(PROFILE_r04.md); the standard figure of merit for a distributed-training
framework is what fraction of peak a TRANSFORMER train step achieves.
This module owns that measurement — model/batch/attention/remat
configuration, the one-launch lax.scan chain timing (CLAUDE.md tunnel
rules), the PaLM-convention model-FLOPs numerator — and a CLI that runs
the tuned winner and emits a one-line JSON receipt.

Round-5 tuning (TRAIN_LLM_r05.md, measured on the v5e lite chip):

- Pallas flash attention >> dense at S=2048 (41.5%% vs 24.9%% MFU at the
  350m scan point) — dense materializes (B, H, S, S) score temps.
- remat is the ENABLER, not a tax: without it a 350m/B=8 step wants
  32.5 GiB of activations (15.75 available); remat_policy="dots"
  (save projection/FFN matmul outputs, recompute elementwise+attention)
  beats full recompute by ~3 MFU points.
- UNROLLED layers beat nn.scan for TRAINING here: the scan's stacked
  activation saves are dynamic-update-slice fusions in awkward layouts —
  ~21%% of device time in the 350m trace — and cost MORE memory
  (15.6 vs 10.9 GiB at the same point). Serving keeps scan_layers (its
  constraint is program size / launch latency, DECODE_r04.md).
- Winner on one v5e lite chip: 760m preset (1.01B params), B=2,
  flash(1024,1024), remat="dots", unrolled, 12-step chain ->
  52.1%% MFU wall (53.9%% device-rate), 15.5k tok/s.

``--fused`` runs a second arm with the memory-bound tail fused away —
logits-free blockwise cross entropy (ops.fused_loss; the (B, S, V) logits
never materialize) plus single-pass fused AdamW (ops.fused_optim) — and
emits BOTH arms in the JSON ({"baseline": ..., "fused": ...}): the receipt
for what the fused tail buys at fixed HBM.

Run:  python -m pytorch_distributed_training_tutorials_tpu.bench.lm_headline [--json out.json]
Sweep CLI with the full tuning grid: scripts/train_llm_mfu.py.
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

PEAK_BF16 = 197e12  # TPU v5e lite chip peak, bf16

PRESETS = {
    # name: (d_model, n_layers, n_heads, vocab)
    "smoke": (64, 2, 4, 256),
    "125m": (768, 12, 12, 32768),
    "350m": (1024, 24, 16, 32768),
    "760m": (1536, 24, 16, 32768),
}


def build(args):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from pytorch_distributed_training_tutorials_tpu.models import (
        TransformerConfig, TransformerLM,
    )
    from pytorch_distributed_training_tutorials_tpu.ops.flash_attention import (
        make_flash_attention,
    )
    from pytorch_distributed_training_tutorials_tpu.train.trainer import (
        TrainState, _train_step_fn,
    )

    d_model, n_layers, n_heads, vocab = PRESETS[args.preset]
    attention_fn = None
    if args.attn == "flash":
        attention_fn = make_flash_attention(args.block_q, args.block_k)
    cfg = TransformerConfig(
        vocab_size=vocab,
        d_model=d_model,
        n_layers=n_layers,
        n_heads=n_heads,
        max_seq_len=args.seq,
        dtype=jnp.bfloat16,
        scan_layers=not args.no_scan,
        remat=args.remat,
        remat_policy=args.remat_policy,
        attention_fn=attention_fn,
    )
    model = TransformerLM(cfg)
    key = jax.random.PRNGKey(0)
    params = jax.jit(model.init)(key, jnp.zeros((1, args.seq), jnp.int32))[
        "params"
    ]
    fused = getattr(args, "fused", False)
    if fused:
        from pytorch_distributed_training_tutorials_tpu.ops.fused_optim import (
            fused_adamw,
        )

        tx = fused_adamw(3e-4, weight_decay=0.01)
    else:
        tx = optax.adamw(3e-4, weight_decay=0.01)
    state = TrainState.create(apply_fn=model.apply, params=params, tx=tx)

    rng = np.random.Generator(np.random.PCG64(0))
    toks = jnp.asarray(
        rng.integers(0, vocab, (args.batch, args.seq + 1)), jnp.int32
    )
    batch = (toks[:, :-1], toks[:, 1:])
    step_fn = _train_step_fn(
        "fused_cross_entropy" if fused else "cross_entropy",
        has_batch_stats=False,
    )

    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    # embedding + lm_head don't do 6N of matmul work per token
    n_embed = vocab * d_model  # tok_emb; lm_head IS a matmul, keep it
    return model, state, batch, step_fn, n_params, n_embed


def chain_fn(step_fn, batch, n_steps):
    import jax

    def body(state, _):
        state, metrics = step_fn(state, batch)
        return state, metrics["loss"]

    # donate the carried state: without aliasing, argument + output trees
    # double the resident optimizer state (measured: 350m B=4 remat probe
    # reported 14.9 GiB peak un-donated)
    @functools.partial(jax.jit, donate_argnums=0)
    def chain(state):
        return jax.lax.scan(body, state, None, length=n_steps)

    return chain


def measure(args) -> dict:
    import jax

    t_build = time.perf_counter()
    model, state, batch, step_fn, n_params, n_embed = build(args)
    jax.block_until_ready(state.params)

    chain = chain_fn(step_fn, batch, args.steps)
    compiled = chain.lower(state).compile()
    compile_s = time.perf_counter() - t_build
    mem = compiled.memory_analysis()
    peak_gb = None
    if mem is not None:
        peak_gb = round(
            (
                getattr(mem, "temp_size_in_bytes", 0)
                + getattr(mem, "argument_size_in_bytes", 0)
                + getattr(mem, "output_size_in_bytes", 0)
                - getattr(mem, "alias_size_in_bytes", 0)
            )
            / 2**30,
            2,
        )
        print(f"# peak HBM (XLA estimate): {peak_gb} GiB", file=sys.stderr)
        if args.mem_only:
            return {
                "preset": args.preset, "seq": args.seq,
                "batch": args.batch, "attn": args.attn,
                "remat": bool(args.remat), "peak_hbm_gib": peak_gb,
                "compile_s": round(compile_s, 1),
            }

    # executed FLOPs from XLA's own cost model (single un-scanned step so
    # scan-length bookkeeping can't distort it)
    cost = (
        jax.jit(step_fn).lower(state, batch).compile().cost_analysis()
    )
    if isinstance(cost, (list, tuple)):  # CPU backend: one dict per device
        cost = cost[0] if cost else {}
    executed_flops = float(cost.get("flops", 0.0))

    # the one scan-aware analytic MFU numerator (models.utils has the
    # cost_analysis caveat)
    from pytorch_distributed_training_tutorials_tpu.models.utils import (
        model_flops_per_token,
    )

    d_model, n_layers, _, vocab = PRESETS[args.preset]
    tokens_per_step = args.batch * args.seq
    # lm_head participates in the 6N term; only tok_emb is excluded
    mflops_tok = model_flops_per_token(
        n_params - n_embed, d_model, n_layers, args.seq
    )
    model_flops = mflops_tok * tokens_per_step

    # prime the process's first D2H fetch outside every timed region
    state2, losses = compiled(state)
    float(losses[-1])

    # obs.MinOfN: stalls (> 5x median) stay visible in the receipt instead
    # of silently widening the min; priming above is the warmup
    holder = {"state": state2}

    def run_chain():
        holder["state"], losses = compiled(holder["state"])
        float(losses[-1])  # close the region with a real fetch

    from pytorch_distributed_training_tutorials_tpu.obs import MinOfN

    timing = MinOfN(n=args.reps, warmup=False).measure(run_chain)
    state2 = holder["state"]
    samples = timing.samples_s
    step_s = timing.best_s / args.steps

    fused = getattr(args, "fused", False)
    out = {
        "preset": args.preset,
        "d_model": d_model,
        "n_layers": n_layers,
        "vocab": vocab,
        "seq": args.seq,
        "batch": args.batch,
        "loss": "fused_cross_entropy" if fused else "cross_entropy",
        "optimizer": "fused_adamw" if fused else "adamw",
        "attn": args.attn
        + (f"({args.block_q},{args.block_k})" if args.attn == "flash" else ""),
        "remat": bool(args.remat),
        "remat_policy": args.remat_policy,
        "scan_layers": not args.no_scan,
        "n_params": n_params,
        "steps_chained": args.steps,
        "wall_s_samples": [round(s, 3) for s in samples],
        "stalled_samples": timing.n_stalled,
        "step_ms": round(step_s * 1e3, 2),
        "tokens_per_s": round(tokens_per_step / step_s),
        "model_tflops_per_step": round(model_flops / 1e12, 3),
        "executed_tflops_per_step": round(executed_flops / 1e12, 3),
        "mfu": round(model_flops / step_s / PEAK_BF16, 4),
        "hw_util_executed": round(executed_flops / step_s / PEAK_BF16, 4),
        "compile_s": round(compile_s, 1),
        "peak_hbm_gib": peak_gb,
        "backend": jax.default_backend(),
    }

    if args.trace:
        import shutil

        from pytorch_distributed_training_tutorials_tpu.obs import StepReport
        from pytorch_distributed_training_tutorials_tpu.utils import profiling

        logdir = "/tmp/jax-trace-lm"
        shutil.rmtree(logdir, ignore_errors=True)
        with profiling.trace(logdir):
            state2, losses = compiled(state2)
            float(losses[-1])
        # HLO-verified classification (obs.trace): leaf/wrapper split plus
        # the where-did-the-step-go categories, not just a total
        report = StepReport.from_trace(
            logdir, hlo=compiled.as_text(), steps=args.steps
        )
        dev_step_s = report.step_us / 1e6
        out["trace_step_ms"] = round(dev_step_s * 1e3, 2)
        out["trace_mfu"] = round(model_flops / dev_step_s / PEAK_BF16, 4)
        out["trace_hw_util"] = round(
            executed_flops / dev_step_s / PEAK_BF16, 4
        )
        out["trace_report"] = report.to_dict()
    return out




def parse(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--preset", choices=sorted(PRESETS), default="760m")
    p.add_argument("--seq", type=int, default=2048)
    p.add_argument("--batch", type=int, default=2)
    p.add_argument("--attn", choices=["dense", "flash"], default="flash")
    p.add_argument("--block_q", type=int, default=1024)
    p.add_argument("--block_k", type=int, default=1024)
    p.add_argument("--remat", action="store_true", default=True)
    p.add_argument("--no_remat", dest="remat", action="store_false")
    p.add_argument("--remat_policy", choices=["dots", "dots_attn"],
                   default="dots")
    p.add_argument("--no_scan", action="store_true", default=True,
                   help="unrolled layers (the training winner; see module "
                   "docstring)")
    p.add_argument("--scan", dest="no_scan", action="store_false")
    # 12 chained steps: the tunnel charges ~110 ms per launch+fetch
    # regardless of chain length (CLAUDE.md), so a longer chain moves the
    # wall number toward the 256 ms/step device rate honestly
    p.add_argument("--steps", type=int, default=12)
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--trace", action="store_true")
    p.add_argument("--mem_only", action="store_true")
    p.add_argument("--fused", action="store_true",
                   help="also run the fused-tail arm (logits-free "
                   "ops.fused_loss + ops.fused_optim AdamW) and emit both "
                   "arms in the JSON")
    p.add_argument("--json", default=None)
    return p.parse_args(argv)


def main() -> None:
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    args = parse()
    if args.fused:
        # side-by-side receipt: identical model/batch/chain, only the
        # loss+optimizer tail differs between the arms
        base = argparse.Namespace(**vars(args))
        base.fused = False
        r = {"baseline": measure(base), "fused": measure(args)}
    else:
        r = measure(args)
    from pytorch_distributed_training_tutorials_tpu.obs import make_receipt, write_receipt

    r = make_receipt("lm_headline", r)
    print(json.dumps(r))
    write_receipt(args.json, r)


if __name__ == "__main__":
    main()
