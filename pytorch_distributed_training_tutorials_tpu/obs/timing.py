"""Honest wall-clock timing: the tunnel methodology as library code.

CLAUDE.md's timing rules existed only as prose; every one of them is a
mistake someone actually made (the async mirage, multi-second stalls on
individual launches, minute-to-minute H2D drift, per-launch fixed cost
misread as per-op time). This module is their executable form:

- :class:`MinOfN` — min-of-N with stall *flagging*: samples > k x median
  are reported separately instead of silently averaged in;
- :class:`DriftBracket` — bench.py's ``h2d_window_drift`` pattern: run a
  ceiling leg before AND after the main leg; only same-window legs are
  comparable, and the bracket quantifies how much the window moved;
- :func:`launch_overhead_fit` — the two-chain-length fit
  ``wall = fixed + per_op * len`` (scripts/launch_overhead_probe.py),
  which is how "no per-op floor — the floor is per LAUNCH" was
  established: a 32-long chain naively divided reports ~3 ms/op of pure
  roundtrip.

None of these time anything themselves: the measured callable must obey
the repo's contract — end with a real device fetch (``float(x[...])`` /
``block_until_ready``), first fetch primed outside the timed region. The
``naive-timing`` graftcheck rule polices that contract statically.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Sequence


@dataclass
class TimingResult:
    """Samples from a min-of-N run, stalls separated from steady state."""

    samples_s: list[float]
    stall_factor: float

    @property
    def best_s(self) -> float:
        return min(self.samples_s)

    @property
    def median_s(self) -> float:
        s = sorted(self.samples_s)
        n = len(s)
        mid = n // 2
        return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])

    @property
    def stalled_s(self) -> list[float]:
        """Samples that hit a tunnel stall (> stall_factor x median)."""
        med = self.median_s
        return [s for s in self.samples_s if s > self.stall_factor * med]

    @property
    def n_stalled(self) -> int:
        return len(self.stalled_s)

    def to_dict(self) -> dict:
        return {
            "best_s": round(self.best_s, 6),
            "median_s": round(self.median_s, 6),
            "n": len(self.samples_s),
            "n_stalled": self.n_stalled,
            "stall_factor": self.stall_factor,
            "samples_s": [round(s, 6) for s in self.samples_s],
        }


class MinOfN:
    """min-of-N timer for a callable that ENDS WITH A REAL FETCH.

    The tunnel hits individual launches with rare multi-second to
    multi-ten-second stalls (observed on ~half of min-of-3 runs in one
    session) — a single sample is meaningless, and a mean buries the
    steady state under the stalls. ``best_s`` is the honest steady-state
    estimate; stalled samples stay visible in the result instead of
    disappearing.

    ``fn`` is run once un-timed first when ``warmup`` is set (compile +
    first-fetch priming belongs OUTSIDE the timed region).
    """

    def __init__(self, n: int = 3, stall_factor: float = 5.0,
                 warmup: bool = True):
        if n < 1:
            raise ValueError("MinOfN needs n >= 1")
        self.n = n
        self.stall_factor = stall_factor
        self.warmup = warmup

    def measure(self, fn: Callable[[], object]) -> TimingResult:
        if self.warmup:
            fn()
        samples: list[float] = []
        for _ in range(self.n):
            t0 = time.perf_counter()
            fn()  # the contract: fn's last action is a device fetch
            samples.append(time.perf_counter() - t0)
        return TimingResult(samples_s=samples, stall_factor=self.stall_factor)


@dataclass
class BracketResult:
    """A main-leg measurement bracketed by before/after ceiling legs."""

    result: object
    before_s: float
    after_s: float
    payload_bytes: int = 0

    @property
    def drift(self) -> float:
        """max/min of the two ceiling legs — how much the window moved.

        H2D bandwidth over the tunnel drifts 2.5-11 MB/s minute to minute;
        a drift near 1.0 certifies the main leg and its ceiling are
        same-window comparable.
        """
        lo = min(self.before_s, self.after_s)
        hi = max(self.before_s, self.after_s)
        return hi / lo if lo > 0 else float("inf")

    @property
    def ceiling_s(self) -> float:
        return min(self.before_s, self.after_s)

    def bandwidth_mbs(self) -> float | None:
        if not self.payload_bytes:
            return None
        return self.payload_bytes / self.ceiling_s / 1e6

    def to_dict(self) -> dict:
        d = {
            "ceiling_before_s": round(self.before_s, 4),
            "ceiling_after_s": round(self.after_s, 4),
            "window_drift": round(self.drift, 2),
        }
        bw = self.bandwidth_mbs()
        if bw is not None:
            d["ceiling_mb_s"] = round(bw, 2)
        return d


class DriftBracket:
    """Bracket a main measurement with a repeated ceiling leg.

    The bench.py ``h2d_window_drift`` pattern generalized: ``ceiling_fn``
    (seconds for a raw reference transfer/compute, fetch-closed) runs
    immediately before and immediately after ``main_fn``; the ratio of the
    two runs bounds how much the environment moved while the main leg ran.
    Comparisons against a ceiling measured in a different window are the
    error this exists to prevent.
    """

    def __init__(self, ceiling_fn: Callable[[], object],
                 payload_bytes: int = 0):
        self.ceiling_fn = ceiling_fn
        self.payload_bytes = payload_bytes

    def _time_ceiling(self) -> float:
        t0 = time.perf_counter()
        self.ceiling_fn()  # contract: ends with a real fetch
        return time.perf_counter() - t0

    def around(self, main_fn: Callable[[], object]) -> BracketResult:
        before = self._time_ceiling()
        result = main_fn()
        after = self._time_ceiling()
        return BracketResult(
            result=result,
            before_s=before,
            after_s=after,
            payload_bytes=self.payload_bytes,
        )


@dataclass
class LaunchFit:
    """``wall = fixed + per_op * len`` decomposition over chain lengths."""

    fixed_ms: float
    per_op_us: float
    lens: tuple[int, ...]
    wall_s: tuple[float, ...] = field(default_factory=tuple)

    def naive_per_op_us(self, length: int) -> float:
        """What naively dividing one chain of ``length`` would report."""
        return self.fixed_ms * 1e3 / length + self.per_op_us

    def to_dict(self) -> dict:
        return {
            "fixed_ms": round(self.fixed_ms, 3),
            "per_op_us": round(self.per_op_us, 3),
            "lens": list(self.lens),
            "wall_s": [round(w, 6) for w in self.wall_s],
        }


def launch_overhead_fit(
    time_chain: Callable[[int], float],
    lens: Sequence[int] = (64, 1024),
) -> LaunchFit:
    """Separate the fixed per-launch cost from true per-op device time.

    ``time_chain(n)`` must return wall seconds for ONE launch of an
    n-long compiled op chain, fetch-closed and already stall-filtered
    (min-of-N). Two lengths give the slope (per-op) and intercept
    (launch+fetch roundtrip); the fit is what corrected the round-3
    "~2 ms/call floor on small-M matmuls" misread — the floor is per
    LAUNCH (~75-130 ms on the tunnel), not per op.
    """
    if len(lens) < 2:
        raise ValueError("need at least two chain lengths to fit")
    ls = sorted(set(int(n) for n in lens))
    walls = [time_chain(n) for n in ls]
    short_n, long_n = ls[0], ls[-1]
    short_t, long_t = walls[0], walls[-1]
    per_op_us = (long_t - short_t) / (long_n - short_n) * 1e6
    fixed_ms = (short_t - per_op_us * 1e-6 * short_n) * 1e3
    return LaunchFit(
        fixed_ms=fixed_ms,
        per_op_us=per_op_us,
        lens=tuple(ls),
        wall_s=tuple(walls),
    )
