"""Trace classification: where did the step's device time actually go.

:class:`StepReport` grows :func:`utils.profiling.device_op_durations` into a
categorized breakdown — convolution / matmul / collectives split by kind /
dynamic-update-slice / convert-copy / reduce / elementwise — the PROFILE_r04
analysis as one library call instead of a hand-run script.

The classifier exists because name-matching trace events is how round 2's
"BatchNorm is ~60% of the step" misread happened: XLA fuses convolutions
*with* the BN-stat reduces into fusions named ``convert_reduce_fusion``, so
the fusion's display name is marketing, not truth (PROFILE_r04.md). Two
defenses are built in:

- pass the compiled module's HLO text (``compiled.as_text()``) and every
  fusion is classified by what its *called fused computation* actually
  contains (convolution > dot > reduce > ...), never by its name;
- without HLO, fusions fall back to name tokens but their time is tallied
  separately as ``heuristic_us`` — a report that leans on guessed fusion
  classes says so instead of presenting the guess as ground truth.

Reference gap being closed: the source tutorial's observability is one
rank-tagged print (ddp_gpus.py:44); it declares profilers it never uses
(environment.yml:78-79; SURVEY.md section 5.5).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from pytorch_distributed_training_tutorials_tpu.utils.profiling import device_op_durations

# Category names (stable strings: they appear in receipts and tests).
CONVOLUTION = "convolution"
MATMUL = "matmul"
REDUCE = "reduce"
COPY = "convert/copy"
DUS = "dynamic-update-slice"
ELEMENTWISE = "elementwise"
OTHER = "other"
COLLECTIVE_PREFIX = "collective:"

# Collective opcodes -> split-by-kind category. Ordered: longer opcode
# strings first so "all-reduce-scatter"-style compounds can't mismatch
# ("reduce-scatter" must win before a bare "all-reduce" substring test).
_COLLECTIVES = (
    ("reduce-scatter", COLLECTIVE_PREFIX + "reduce-scatter"),
    ("all-reduce", COLLECTIVE_PREFIX + "all-reduce"),
    ("all-gather", COLLECTIVE_PREFIX + "all-gather"),
    ("all-to-all", COLLECTIVE_PREFIX + "all-to-all"),
    ("collective-permute", COLLECTIVE_PREFIX + "permute"),
)

# Data-movement / layout opcodes (one bucket: none of them is compute).
_COPY_OPS = frozenset({
    "copy", "copy-start", "copy-done", "convert", "transpose", "bitcast",
    "reshape", "pad",
})

# Compute opcodes that are honestly "elementwise or cheap memory traffic".
# Gather/slice/concatenate land here deliberately: on the workloads this
# repo profiles they are epsilon, and a wrong *named* bucket is worse than
# a coarse one (the misread lesson).
_ELEMENTWISE_OPS = frozenset({
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "exponential", "exponential-minus-one", "power", "rsqrt", "sqrt",
    "tanh", "logistic", "log", "log-plus-one", "negate", "abs", "sign",
    "compare", "select", "and", "or", "not", "xor", "clamp", "floor",
    "ceil", "round-nearest-afz", "round-nearest-even", "is-finite",
    "cosine", "sine", "atan2", "remainder", "shift-left",
    "shift-right-logical", "shift-right-arithmetic", "broadcast", "iota",
    "constant", "rng", "rng-bit-generator", "gather", "scatter", "slice",
    "dynamic-slice", "concatenate", "reverse", "partition-id",
    "replica-id", "tuple", "get-tuple-element", "bitcast-convert",
    "stochastic-convert", "cbrt", "erf", "expm1", "log1p", "popcnt",
    "clz", "map", "sort", "reduce-precision", "real", "imag", "complex",
    "after-all", "add-dependency", "optimization-barrier", "domain",
})

# Trailing ``.3`` / ``.clone`` / ``.3.clone`` disambiguators XLA appends to
# duplicated instruction names (observed on the CPU-mesh traces).
_SUFFIX = re.compile(r"(\.(\d+|clone|remat|sunk))+$")


def base_name(op: str) -> str:
    """Instruction name with XLA's clone/ordinal suffixes stripped."""
    return _SUFFIX.sub("", op)


def is_wrapper(op: str) -> bool:
    """True for events that *contain* leaf ops (counting them double-counts).

    Three families, all observed in real traces:

    - host-executor infra, C++-scoped names (``ThunkExecutor::Execute``,
      ``TfrtCpuExecutable::ExecuteHelper``, ``ThreadpoolListener::...``) —
      these dominate raw CPU-mesh totals and are pure bookkeeping;
    - XLA region wrappers: the module-level event (a bare ordinal like
      ``0``), ``jit_*`` program regions, ``while`` loop bodies, ``call``
      computation frames;
    - profiler metadata lanes.
    """
    if "::" in op:
        return True
    b = base_name(op)
    return (
        b.isdigit()
        or b.startswith("jit_")
        or b == "while"
        or b.startswith("while_")
        or b == "call"
        or b.startswith("call_")
    )


def _classify_opcode(opcode: str) -> str:
    """Category for a bare (non-fusion) HLO opcode."""
    if "convolution" in opcode:
        return CONVOLUTION
    for coll, cat in _COLLECTIVES:
        if coll in opcode:
            return cat
    if "dynamic-update-slice" in opcode:
        return DUS
    if opcode == "dot":
        return MATMUL
    if opcode in ("reduce", "reduce-window") or opcode.startswith("reduce."):
        return REDUCE
    if opcode in _COPY_OPS:
        return COPY
    if opcode in _ELEMENTWISE_OPS:
        return ELEMENTWISE
    return OTHER


def _classify_fusion_body(body: str) -> str:
    """Category for a fusion by what its fused computation CONTAINS.

    Priority mirrors scripts/profile_step.py's HLO-verified rules (the fix
    for the ``convert_reduce_fusion`` misread): the most expensive op class
    present names the fusion. A fusion with none of the heavy ops is
    elementwise by construction.
    """
    if "convolution(" in body:
        return CONVOLUTION
    if "dot(" in body:
        return MATMUL
    for coll, cat in _COLLECTIVES:
        if coll + "(" in body:
            return cat
    if "dynamic-update-slice(" in body:
        return DUS
    if "reduce(" in body or "reduce-window(" in body:
        return REDUCE
    return ELEMENTWISE


def _classify_name(base: str) -> str:
    """Name-token fallback for events with no HLO backing.

    Fusion names list (some of) the fused ops joined by ``_``; bare names
    are opcodes. Priority matches the HLO-body rules so the two paths can
    only disagree when the fusion NAME omits its heaviest op — exactly the
    case ``heuristic_us`` accounts for.
    """
    if "convolution" in base:
        return CONVOLUTION
    for coll, cat in _COLLECTIVES:
        if coll in base:
            return cat
    if "dynamic-update-slice" in base:
        return DUS
    tokens = [t for t in base.split("_") if t and t != "fusion"]
    if base == "dot" or "dot" in tokens:
        return MATMUL
    if base in ("reduce", "reduce-window") or "reduce" in tokens:
        return REDUCE
    if base in _COPY_OPS or any(t in _COPY_OPS for t in tokens):
        return COPY
    if base.endswith("fusion"):
        # a fusion whose name shows none of the heavy classes: elementwise
        # body (profile_step's fallback), but flagged heuristic upstream
        return ELEMENTWISE
    if base in _ELEMENTWISE_OPS:
        return ELEMENTWISE
    return OTHER


def classify_hlo(hlo: str) -> dict[str, tuple[str, str]]:
    """Map HLO instruction name -> (category, metadata op_name).

    The ground-truth classifier: fusions are resolved through their
    ``calls=%computation`` body. Generalizes scripts/profile_step.py's
    ``parse_hlo`` with collectives split by kind and dynamic-update-slice
    as its own class (the nn.scan layout lesson, TRAIN_LLM_r05.md).
    """
    comps: dict[str, str] = {}
    cur: str | None = None
    body: list[str] = []
    for line in hlo.splitlines():
        if cur is None and line.startswith("%") and line.rstrip().endswith("{"):
            cur = line.split()[0].lstrip("%")
            body = []
        elif cur is not None and line.startswith("}"):
            comps[cur] = "\n".join(body)
            cur = None
        elif cur is not None:
            body.append(line)
    info: dict[str, tuple[str, str]] = {}
    # "[ROOT] %name = <type> opcode(operands)...": the type may be a tuple
    # full of layout parens like (f32[64]{0:T(128)S(1)}, ...), so the
    # opcode is the first *lowercase* word directly preceding a "(" after
    # the type
    inst_re = re.compile(
        r"^\s+(?:ROOT\s+)?%?([\w\.\-]+)\s*=\s+"
        r"(?:\([^=]*?\)|[^\s(]+)\s+([a-z][\w\-]*)\("
    )
    for line in hlo.splitlines():
        m = inst_re.match(line)
        if not m:
            continue
        name, opcode = m.group(1), m.group(2)
        call = re.search(r"calls=%?([\w\.\-]+)", line)
        meta = re.search(r'op_name="([^"]+)"', line)
        op_name = meta.group(1) if meta else ""
        if opcode == "fusion" and call:
            cls = _classify_fusion_body(comps.get(call.group(1), ""))
        else:
            cls = _classify_opcode(opcode)
        info[name] = (cls, op_name)
    return info


@dataclass
class StepReport:
    """Categorized device-time breakdown of a captured trace.

    ``total_us`` is leaf device time (wrapper events that *contain* leaves
    are excluded and tallied in ``wrapper_us``); ``by_category`` always sums
    to ``total_us`` exactly. ``heuristic_us`` is the share classified from
    fusion *names* with no HLO to verify against — if it is large, pass
    ``hlo=compiled.as_text()`` before trusting the split.
    """

    total_us: float
    wrapper_us: float
    by_category: dict[str, float]
    ops: list[tuple[str, float, str]] = field(default_factory=list)
    heuristic_us: float = 0.0
    steps: int = 1

    @classmethod
    def from_trace(
        cls, logdir: str, hlo: str | None = None, steps: int = 1
    ) -> "StepReport":
        """Build a report from a trace directory written by profiling.trace.

        ``steps``: how many train steps the traced region executed (a jitted
        ``lax.scan`` chain counts as its length) — used only for the
        per-step convenience properties.
        """
        durations = device_op_durations(logdir)
        hlo_info = classify_hlo(hlo) if hlo else {}
        total = 0.0
        wrapper = 0.0
        heuristic = 0.0
        by_cat: dict[str, float] = {}
        ops: list[tuple[str, float, str]] = []
        for op, us in durations.items():
            if is_wrapper(op):
                wrapper += us
                continue
            base = base_name(op)
            known = hlo_info.get(op) or hlo_info.get(base)
            if known is not None:
                cat = known[0]
            else:
                cat = _classify_name(base)
                if base.endswith("fusion"):
                    heuristic += us
            total += us
            by_cat[cat] = by_cat.get(cat, 0.0) + us
            ops.append((op, us, cat))
        ops.sort(key=lambda r: -r[1])
        return cls(
            total_us=total,
            wrapper_us=wrapper,
            by_category=dict(
                sorted(by_cat.items(), key=lambda kv: -kv[1])
            ),
            ops=ops,
            heuristic_us=heuristic,
            steps=max(1, steps),
        )

    @property
    def step_us(self) -> float:
        return self.total_us / self.steps

    @property
    def unclassified_fraction(self) -> float:
        if self.total_us <= 0:
            return 0.0
        return self.by_category.get(OTHER, 0.0) / self.total_us

    @property
    def collective_us(self) -> dict[str, float]:
        """Collective time split by kind (the SPMD cost surface)."""
        return {
            k: v
            for k, v in self.by_category.items()
            if k.startswith(COLLECTIVE_PREFIX)
        }

    def fraction(self, category: str) -> float:
        if self.total_us <= 0:
            return 0.0
        return self.by_category.get(category, 0.0) / self.total_us

    def render(self, top: int = 0) -> str:
        """The "where did the step go" table, PROFILE_r04 style."""
        lines = [
            f"device time: {self.total_us / 1e3:.2f} ms over "
            f"{self.steps} step(s) -> {self.step_us / 1e3:.3f} ms/step",
            "| class | ms | % of device time |",
            "|---|---|---|",
        ]
        for cat, us in self.by_category.items():
            lines.append(
                f"| {cat} | {us / 1e3:.2f} | "
                f"{100 * us / self.total_us:.1f}% |"
                if self.total_us
                else f"| {cat} | 0.00 | 0.0% |"
            )
        if self.heuristic_us:
            lines.append(
                f"(name-heuristic share, no HLO backing: "
                f"{100 * self.heuristic_us / self.total_us:.1f}% — pass "
                "hlo=compiled.as_text() to verify)"
            )
        for op, us, cat in self.ops[: top or 0]:
            lines.append(f"  {op}: {us / 1e3:.3f} ms [{cat}]")
        return "\n".join(lines)

    def to_dict(self) -> dict:
        """JSON-ready payload (the receipt-pipeline form)."""
        return {
            "total_us": round(self.total_us, 3),
            "wrapper_us": round(self.wrapper_us, 3),
            "step_us": round(self.step_us, 3),
            "steps": self.steps,
            "by_category": {
                k: round(v, 3) for k, v in self.by_category.items()
            },
            "heuristic_us": round(self.heuristic_us, 3),
            "unclassified_fraction": round(self.unclassified_fraction, 4),
        }
