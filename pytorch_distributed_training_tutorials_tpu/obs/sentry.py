"""Runtime contract sentry: compile / fetch / re-upload attribution.

Every engine contract the reference-reproduction depends on — "nothing
recompiles per request" (CLAUDE.md serving invariants), "fetch budget =
chains + prefills + splices (+ handoffs_in + counted swaps)", "no host-numpy leaf
re-uploads per call" (the DECODE_r04 trap: 2.7 -> 508 tok/s) — is pinned
by monkeypatch spies and ``_cache_size()`` counts in CPU-mesh tests, but
on the real chip nothing watches them at runtime. :class:`ContractSentry`
is the production twin of those spies: threaded through ``ServeEngine``,
``FleetRouter`` and ``Trainer``, it makes a violation self-announcing
instead of silently eating a receipt round.

Three probes, all host-only bookkeeping (a counter bump and a dict walk
— never a device fetch, so the fetch budget it measures is unchanged by
measuring it):

- **Compile probe**: :meth:`install` subscribes to JAX's compilation
  events (``jax.monitoring.register_event_duration_secs_listener``,
  filtering to the ``backend_compile`` duration — the per-XLA-compile
  signal; a pjit-lower-wrapping fallback covers jax builds without the
  monitoring API). Every compilation becomes a typed ``compile`` flight
  event (phase label, wall ms). After :meth:`mark_steady` — the same
  warmup seam as ``flight.reset()`` — any further compilation is a
  VIOLATION: the event carries ``steady=True`` and the sentry explicitly
  dumps a ``graft-flightlog/v1`` snapshot naming it (warmup compiles are
  normal and never dump).
- **Fetch probe**: the installed ``jax.device_get`` wrapper counts every
  host fetch; the engine's budgeted call sites additionally route
  through :meth:`budgeted_fetch` (via ``ServeEngine._sentry_fetch``), so
  inside a :meth:`begin_round`/:meth:`end_round` window — one ``step()``
  scheduling round — ``fetched > budgeted`` means a stray sync leaked
  outside the budget (chains + prefills + splices + handoffs_in +
  swaps_out under SLO preemption, ISSUE 20; prefill-role budget 0). The
  violation records a ``budget_violation``
  event, which auto-dumps through the recorder's existing fault path.
- **Re-upload probe**: :meth:`check_args` walks a dispatched arg tree
  for host-``numpy`` leaves — the ``device_materialize`` trap, where a
  checkpoint-restored tree re-uploads per call (~16 s/launch for a 1.2B
  tree over the tunnel). H2D bytes accumulate every occurrence; the
  FIRST occurrence per site label records a ``reupload`` event
  (auto-dumped) so repeated per-call uploads surface once, loudly, not
  once per step.

This module is jax-free at import (it joins
``analysis.hostonly.HOST_ONLY_MODULES`` and the no-jax subprocess pin):
``install``/``check_args`` import jax function-locally — the sanctioned
lazy idiom — and a sentry that is constructed but never installed
touches jax not at all. Sentry-off engines/trainers keep byte-identical
state trees and compiled programs (the standard ``is not None``
off-path gating); ``summary()`` feeds ``sentry_stats()`` into
``engine.stats()`` / ``router.stats()`` and every receipt.
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

# The monitoring event that fires once per real XLA compilation (the
# trace/MLIR-lowering siblings fire alongside it and would triple-count).
_COMPILE_EVENT_FRAGMENT = "backend_compile"


class ContractSentry:
    """Runtime monitor for the three engine contracts (ISSUE 19).

    Parameters
    ----------
    flight: a :class:`..obs.flight.FlightRecorder` to stamp ``compile``
        / ``budget_violation`` / ``reupload`` events into (and to dump
        post-steady recompile snapshots through). ``None`` keeps the
        sentry counters-only.
    label: initial phase label attributed to compile events (default
        ``"warmup"``; :meth:`set_phase` and :meth:`begin_round` move it).
    max_compile_records: how many per-compile ``(label, ms)`` records to
        retain for post-mortem context (counters never truncate).
    """

    def __init__(self, flight: Any = None, label: str = "warmup",
                 max_compile_records: int = 64):
        self._flight = flight
        self.phase = label
        self.steady = False
        # compile probe
        self.n_compiles = 0
        self.n_steady_recompiles = 0
        self.compile_ms_total = 0.0
        self.compile_records: List[dict] = []
        self._max_compile_records = int(max_compile_records)
        self.compile_probe = "off"   # "monitoring" | "pjit" | "off"
        self._listener = None
        self._pjit_orig = None
        # fetch probe
        self.installed = False
        self._real_device_get = None
        self.n_fetched = 0
        self.n_budgeted = 0
        self.n_rounds = 0
        self.n_budget_violations = 0
        self._in_round = False
        self._round_fetched = 0
        self._round_budgeted = 0
        self._round_label: Optional[str] = None
        # re-upload probe
        self.n_reuploads = 0
        self.reupload_bytes = 0
        self.n_checked = 0
        self._reupload_sites: set = set()

    # -- lifecycle ---------------------------------------------------------

    def install(self) -> "ContractSentry":
        """Activate the compile listener and the fetch-counting
        ``jax.device_get`` wrapper. Idempotent; pair with
        :meth:`uninstall` (or use the sentry as a context manager) so a
        test-scoped sentry never leaks its global hooks."""
        if self.installed:
            return self
        import jax

        self._install_compile_probe()
        real = jax.device_get
        sentry = self

        def _sentry_device_get(x):
            sentry.n_fetched += 1
            if sentry._in_round:
                sentry._round_fetched += 1
            return real(x)

        # marker so uninstall only restores OUR wrapper (a later
        # monkeypatch spy layered on top is the spy's to undo)
        _sentry_device_get._contract_sentry = self  # type: ignore
        self._real_device_get = real
        jax.device_get = _sentry_device_get
        self.installed = True
        return self

    def uninstall(self) -> None:
        if not self.installed:
            return
        import jax

        current = jax.device_get
        if getattr(current, "_contract_sentry", None) is self:
            jax.device_get = self._real_device_get
        self._real_device_get = None
        self._uninstall_compile_probe()
        self.installed = False

    def __enter__(self) -> "ContractSentry":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    def mark_steady(self) -> None:
        """Declare the warmup boundary (the ``flight.reset()`` seam):
        every compilation from here on is a steady-state recompile —
        the violation the zero-recompile serving contract forbids."""
        self.steady = True
        self.phase = "steady"

    def set_phase(self, label: str) -> None:
        """Attribute subsequent compile events to ``label``."""
        self.phase = str(label)

    # -- compile probe -----------------------------------------------------

    def _install_compile_probe(self) -> None:
        try:
            from jax import monitoring

            sentry = self

            def _listener(event: str, duration_secs: float, **kw):
                if _COMPILE_EVENT_FRAGMENT in event:
                    sentry._on_compile(duration_secs * 1000.0)

            monitoring.register_event_duration_secs_listener(_listener)
            self._listener = _listener
            self.compile_probe = "monitoring"
            return
        except Exception:
            pass
        try:
            # fallback for jax builds without the monitoring API: count
            # pjit cache-miss lowerings (one per compilation; wall ms
            # unknown from here, recorded as 0.0)
            from jax._src import pjit as _pjit

            orig = _pjit._pjit_lower
            sentry = self

            def _counting_lower(*args, **kwargs):
                sentry._on_compile(0.0)
                return orig(*args, **kwargs)

            _pjit._pjit_lower = _counting_lower
            self._pjit_orig = orig
            self.compile_probe = "pjit"
        except Exception:
            self.compile_probe = "off"

    def _uninstall_compile_probe(self) -> None:
        if self._listener is not None:
            try:
                from jax._src import monitoring as _mon

                _mon._unregister_event_duration_listener_by_callback(
                    self._listener
                )
            except Exception:
                pass
            self._listener = None
        if self._pjit_orig is not None:
            try:
                from jax._src import pjit as _pjit

                _pjit._pjit_lower = self._pjit_orig
            except Exception:
                pass
            self._pjit_orig = None
        self.compile_probe = "off"

    def _on_compile(self, ms: float) -> None:
        self.n_compiles += 1
        self.compile_ms_total += ms
        record = {
            "label": self.phase, "ms": round(ms, 3),
            "steady": self.steady,
        }
        if len(self.compile_records) < self._max_compile_records:
            self.compile_records.append(record)
        if self.steady:
            self.n_steady_recompiles += 1
        if self._flight is not None:
            ev = self._flight.record(
                "compile", label=self.phase, ms=round(ms, 3),
                steady=self.steady,
            )
            if self.steady:
                # the violation dump: plain compile events never dump
                # (warmup compiles are normal), a POST-STEADY one is the
                # zero-recompile contract breaking — snapshot it now,
                # named by its phase label
                self._flight.dump(reason="compile", trigger=ev)

    # -- fetch probe -------------------------------------------------------

    def begin_round(self, label: Optional[str] = None) -> None:
        """Open one scheduling-round accounting window (the engine calls
        this at the top of ``step()``). Fetches outside a round — warmup,
        reference decodes, receipt assembly — never count against the
        budget."""
        self._in_round = True
        self._round_label = label
        self._round_fetched = 0
        self._round_budgeted = 0
        if label is not None:
            self.phase = str(label)

    def budgeted_fetch(self) -> None:
        """A budgeted engine call site is about to fetch (routed through
        ``ServeEngine._sentry_fetch``) — the fetch it precedes is inside
        the declared budget."""
        self.n_budgeted += 1
        if self._in_round:
            self._round_budgeted += 1

    def end_round(self) -> None:
        """Close the round; ``fetched > budgeted`` is a violation (one
        ``budget_violation`` event, auto-dumped via the recorder's fault
        path)."""
        if not self._in_round:
            return
        self._in_round = False
        self.n_rounds += 1
        if self._round_fetched > self._round_budgeted:
            self.n_budget_violations += 1
            if self._flight is not None:
                self._flight.record(
                    "budget_violation",
                    fetched=self._round_fetched,
                    budgeted=self._round_budgeted,
                    round=self._round_label or f"round {self.n_rounds}",
                )

    # -- re-upload probe ---------------------------------------------------

    def check_args(self, tree: Any, label: str = "dispatch") -> int:
        """Walk ``tree`` for host-``numpy`` leaves (each one re-uploads
        H2D on EVERY dispatch — pin restored trees with
        ``utils.tree.device_materialize``). Returns the host bytes
        found; 0 means clean. Isinstance checks only — never fetches."""
        self.n_checked += 1
        import jax

        host = [
            leaf for leaf in jax.tree_util.tree_leaves(tree)
            if isinstance(leaf, np.ndarray)
        ]
        if not host:
            return 0
        nbytes = sum(int(leaf.nbytes) for leaf in host)
        self.n_reuploads += 1
        self.reupload_bytes += nbytes
        if label not in self._reupload_sites:
            self._reupload_sites.add(label)
            if self._flight is not None:
                # first occurrence per site announces (and auto-dumps);
                # later occurrences only accumulate the counters — the
                # per-call repetition is visible as n_reuploads >> sites
                self._flight.record(
                    "reupload", label=label, n_leaves=len(host),
                    bytes=nbytes,
                )
        return nbytes

    # -- receipt surface ---------------------------------------------------

    def summary(self) -> dict:
        """Flat receipt-ready aggregate (``sentry_*`` keys). ``sentry``
        itself is CONFIG (regress.py fingerprints it so instrumented and
        bare rounds never gate each other); the rest are outcomes."""
        return {
            "sentry": 1,
            "sentry_compiles": self.n_compiles,
            "sentry_steady_recompiles": self.n_steady_recompiles,
            "sentry_compile_ms": round(self.compile_ms_total, 3),
            "sentry_rounds": self.n_rounds,
            "sentry_fetched": self.n_fetched,
            "sentry_budgeted": self.n_budgeted,
            "sentry_budget_violations": self.n_budget_violations,
            "sentry_fetch_budget_ok": int(self.n_budget_violations == 0),
            "sentry_reuploads": self.n_reuploads,
            "sentry_reupload_bytes": self.reupload_bytes,
        }
