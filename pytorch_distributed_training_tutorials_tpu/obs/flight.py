"""Serving flight recorder: request-lifecycle events, spans, fault dumps.

PR 3 built the *benchmarking* observability pillar (MinOfN, DriftBracket,
StepReport, receipts). This module is its production twin: when the
engine is serving a live request stream, the question is no longer "how
fast is a step" but "what was the engine doing when slot 3 went
nonfinite" — exactly the post-mortem ISSUE 9's quarantine/deadline paths
create and end-of-run counters cannot answer.

Three pieces, all pure host bookkeeping:

- **Event ring**: a bounded ``deque`` of typed, monotonic-timestamped
  events (``EVENT_KINDS``) stamped at the boundaries the engine already
  touches (submit / refill / chain dispatch / sweep / complete). The
  ring forgets old events (``dropped`` counts them) but NEVER blocks or
  grows — a recorder must be safe to leave on for a week of traffic.
- **Spans**: per-request lifecycle records (submit -> queue_pop ->
  prefill/splice -> first chain -> complete) kept in a dict keyed by
  request id, DELIBERATELY independent of the event ring so wraparound
  cannot corrupt a live request's span. Completed spans feed the
  streaming histograms and roll into their own bounded deque.
- **Histograms**: :class:`~..obs.histogram.LogHistogram` streams for
  TTFT, end-to-end latency, queue wait, and chain utilization —
  bounded-error p50/p95/p99 without retaining the sample list.

Contract with the serve/train stack (pinned by tests/test_serve.py and
tests/test_flight.py): the recorder is host-only — stamping an event
costs a clock read and a deque append, NEVER a device fetch, so the
engine's fetch budget stays exactly chains + prefills + splices (+
counted swaps under SLO preemption, ISSUE 20); a
recorder-off engine keeps byte-identical state trees and compiled
programs (the same off-path pattern the spec/adapter/robustness layers
use). Timestamping here uses ``time.perf_counter()`` in a jax-free
module — the graftcheck ``naive-timing`` rule only patrols jax-importing
files, and tests/test_static_analysis.py pins this file as exempt.

Fault dumps: on any fault_stats-visible event (nonfinite quarantine,
deadline expiry, prefill error, adapter_evicted, trainer skip/rollback)
the recorder snapshots the last N events + live spans as ONE schema'd
JSONL line (``graft-flightlog/v1``), written to ``dump_path`` when set.
``scripts/flight_view.py`` renders these as a timeline.
"""

from __future__ import annotations

import json
import time
from collections import Counter, deque
from typing import Any, Dict, List, Optional

from .histogram import LogHistogram

FLIGHT_SCHEMA = "graft-flightlog/v1"

# The typed vocabulary; record() rejects anything else so a dump is
# machine-readable without a per-producer schema.
EVENT_KINDS = frozenset({
    "submit",            # request accepted by the scheduler
    "queue_pop",         # request left the queue for a slot
    "prefill",           # full prefill into a slot
    "splice",            # prefix-cache splice + suffix prefill
    "prefill_chunk",     # one mid-prompt chunk of a chunked prefill
    "chain_start",       # decode chain dispatched (occupancy recorded)
    "chain_end",         # chain's batched fetch landed (tokens recorded)
    "sweep",             # chain-boundary sweep completed requests
    "complete",          # request finished (any finish_reason)
    "fault",             # fault_stats-visible anomaly (slot-aware)
    "adapter_register",  # tenant row assigned
    "adapter_evict",     # tenant row freed
    "adapter_refresh",   # engine re-merged a moved bank version
    "step_skipped",      # trainer nonfinite skip (rides the batched fetch)
    "rollback",          # trainer loss-spike rollback fired
    "stall",             # injected launch stall (utils/chaos.py)
    "replica_health",    # fleet router health transition (ISSUE 12)
    "redispatch",        # router moved a request off a dead/draining replica
    "hedge",             # router duplicated a straggler onto a second replica
    "pool_shed",         # paged KV: submit rejected, request > whole pool
    "page_cow",          # paged KV: copy-on-write split of a shared page
    "handoff_emit",      # prefill-role engine finished a transferable prefill
    "handoff_move",      # router moved a KV segment to a decode replica
    "handoff_accept",    # decode-role engine spliced a handoff into a slot
    "compile",           # contract sentry: one XLA compilation (ISSUE 19)
    "budget_violation",  # contract sentry: round fetches exceeded budget
    "reupload",          # contract sentry: host-numpy leaves in a dispatch
    "preempt",           # SLO: active slot swapped out to host (ISSUE 20)
    "resume",            # SLO: preempted request re-spliced into a slot
})

# Faults trigger an auto-dump when a dump_path is configured. The two
# sentry violation kinds (ISSUE 19) ride the same path — a budget or
# re-upload violation IS a fault-class post-mortem; plain "compile"
# events stay out (warmup compiles are normal; the sentry dumps a
# POST-STEADY recompile explicitly, so warmup never floods the log).
_AUTO_DUMP_KINDS = frozenset({
    "fault", "step_skipped", "rollback", "budget_violation", "reupload",
})


class FlightRecorder:
    """Bounded request-lifecycle recorder for ServeEngine / Trainer.

    Parameters
    ----------
    capacity: event-ring size (old events drop, counted in ``dropped``).
    dump_path: when set, fault-class events append one
        ``graft-flightlog/v1`` JSONL snapshot here automatically;
        :meth:`dump` can also be called explicitly (end-of-run).
    dump_events: how many trailing events a snapshot carries.
    max_done_spans: completed-span retention (histograms already hold
        the aggregate; the deque is for post-mortem context only).
    t0: epoch for the relative timestamps (a ``time.perf_counter()``
        reading). Defaults to construction time; a FLEET passes ONE
        shared ``t0`` to every replica's recorder (and the router's) so
        :func:`merge_snapshots` can interleave their events on a common
        timeline — recorders with private epochs merge fine but sort
        per-recorder-relative.
    """

    def __init__(self, capacity: int = 1024,
                 dump_path: Optional[str] = None,
                 dump_events: int = 64,
                 max_done_spans: int = 256,
                 t0: Optional[float] = None):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self.dump_path = dump_path
        self.dump_events = int(dump_events)
        self.max_done_spans = int(max_done_spans)
        self._t0 = time.perf_counter() if t0 is None else float(t0)
        self.reset()

    @property
    def t0(self) -> float:
        return self._t0

    # -- lifecycle ---------------------------------------------------------

    def reset(self) -> None:
        """Forget everything (events, spans, histograms, counters) but
        keep configuration and the epoch ``t0`` — the examples' warmup
        phase resets the recorder alongside the engine counters so the
        receipt reflects only the timed stream."""
        self.events: deque = deque(maxlen=self.capacity)
        self.n_events = 0
        self.n_dumps = 0
        self.n_faults = 0
        self.kind_counts: Counter = Counter()
        self.spans: Dict[Any, dict] = {}
        self.done_spans: deque = deque(maxlen=self.max_done_spans)
        self.hist = {
            "ttft": LogHistogram(),
            "e2e": LogHistogram(),
            "queue_wait": LogHistogram(),
            # utilization is a ratio in (0, 1]; finer floor, tight cap
            "chain_util": LogHistogram(min_value=1e-3, max_value=4.0),
            # pipeline overlap is a ratio too: fraction of a chain's
            # dispatch->fetch span during which a LATER chain was
            # already dispatched (0 = serial loop; -> 1 = the whole
            # host roundtrip is hidden). 0.0 lands in the underflow
            # bucket, so the count still reflects every chain.
            "chain_overlap": LogHistogram(min_value=1e-3, max_value=4.0),
            # swap-out -> swap-in wall time of preempted requests
            # (ISSUE 20) — the price a lower SLO class pays so a
            # higher class can hold its TTFT
            "preempt_wait": LogHistogram(),
        }
        # dispatch stamps of chains whose fetch has not landed yet,
        # keyed by the engine's chain sequence number — pipelined
        # engines keep several open at once
        self._open_chains: Dict[Any, float] = {}

    @property
    def dropped(self) -> int:
        """Events stamped but no longer in the ring (wraparound)."""
        return self.n_events - len(self.events)

    def _now(self) -> float:
        return time.perf_counter() - self._t0

    # -- generic intake ----------------------------------------------------

    def record(self, kind: str, **fields: Any) -> dict:
        """Stamp one typed event. Unknown kinds raise — the dump format
        is only machine-readable if the vocabulary is closed."""
        if kind not in EVENT_KINDS:
            raise ValueError(
                f"unknown flight event kind {kind!r}; "
                f"known: {sorted(EVENT_KINDS)}"
            )
        event = {"t": round(self._now(), 6), "kind": kind, **fields}
        self.events.append(event)
        self.n_events += 1
        self.kind_counts[kind] += 1
        if kind in _AUTO_DUMP_KINDS:
            self.n_faults += 1
            if self.dump_path is not None:
                self.dump(reason=kind, trigger=event)
        return event

    # -- request lifecycle (ServeEngine hooks) -----------------------------

    def request_submitted(self, rid: Any, p_len: int = 0,
                          max_new: int = 0, adapter: int = 0) -> None:
        t = self._now()
        self.record("submit", rid=rid, p_len=p_len, max_new=max_new,
                    adapter=adapter)
        # spans live OUTSIDE the ring: wraparound never corrupts them
        self.spans[rid] = {
            "rid": rid, "submit_t": t, "p_len": p_len, "max_new": max_new,
            "adapter": adapter,
        }

    def request_popped(self, rid: Any) -> None:
        t = self._now()
        self.record("queue_pop", rid=rid)
        span = self.spans.get(rid)
        if span is not None:
            span["queue_pop_t"] = t
            self.hist["queue_wait"].record(t - span["submit_t"])

    def request_prefilled(self, rid: Any, slot: int,
                          kind: str = "prefill",
                          cached_len: int = 0) -> None:
        """``kind`` is "prefill", "splice" (the prefix-cache path) or
        "handoff" (a decode-role engine accepting a transferred segment
        — ISSUE 18; ``prefill_t`` still stamps here, the moment the
        request's first token exists on THIS engine)."""
        t = self._now()
        if kind == "splice":
            self.record("splice", rid=rid, slot=slot, cached_len=cached_len)
        elif kind == "handoff":
            self.record("handoff_accept", rid=rid, slot=slot)
        else:
            self.record("prefill", rid=rid, slot=slot)
        span = self.spans.get(rid)
        if span is not None:
            span["prefill_t"] = t
            span["slot"] = slot
            span["path"] = kind
            if cached_len:
                span["cached_len"] = cached_len

    def prefill_chunk(self, rid: Any, slot: int, done: int = 0,
                      total: int = 0) -> None:
        """One mid-prompt chunk of a chunked prefill dispatched (async
        only — the request's ``prefill_t`` still stamps at the FINAL
        chunk, when its first token exists). ``done``/``total`` give the
        prompt progress for the timeline view."""
        self.record("prefill_chunk", rid=rid, slot=slot, done=done,
                    total=total)
        span = self.spans.get(rid)
        if span is not None:
            span["chunks"] = span.get("chunks", 0) + 1

    def request_completed(self, rid: Any, finish_reason: str,
                          tokens: int = 0,
                          latency_s: Optional[float] = None,
                          ttft_s: Optional[float] = None) -> None:
        """Close a span. ``latency_s``/``ttft_s`` are the engine's own
        Completion numbers when available — recording THOSE (not a
        re-derived clock delta) keeps the histogram percentiles
        sample-identical to the sort-based ones they replace."""
        t = self._now()
        self.record("complete", rid=rid, finish_reason=finish_reason,
                    tokens=tokens)
        span = self.spans.pop(rid, None)
        if span is None:
            span = {"rid": rid, "submit_t": None}
        span["complete_t"] = t
        span["finish_reason"] = finish_reason
        span["tokens"] = tokens
        e2e = latency_s
        if e2e is None and span.get("submit_t") is not None:
            e2e = t - span["submit_t"]
        if e2e is not None:
            span["e2e_s"] = round(e2e, 6)
            self.hist["e2e"].record(e2e)
        if ttft_s is None and span.get("submit_t") is not None \
                and span.get("prefill_t") is not None:
            ttft_s = span["prefill_t"] - span["submit_t"]
        if ttft_s is not None:
            span["ttft_s"] = round(ttft_s, 6)
            self.hist["ttft"].record(ttft_s)
            if e2e is not None and tokens > 1 and e2e > ttft_s:
                span["decode_tok_per_s"] = round(
                    (tokens - 1) / (e2e - ttft_s), 3
                )
        self.done_spans.append(span)

    # -- engine-wide events ------------------------------------------------

    def chain_start(self, occupancy: int, n_slots: int,
                    chain: Optional[int] = None) -> None:
        """``chain`` is the engine's chain sequence number; when given,
        the dispatch stamp opens the chain for the overlap histogram
        (and rides the event, so flight_view can pair start/end of
        overlapped chains without reordering the timeline)."""
        fields: dict = {"occupancy": occupancy, "n_slots": n_slots}
        if chain is not None:
            fields["chain"] = chain
        ev = self.record("chain_start", **fields)
        if chain is not None:
            self._open_chains[chain] = ev["t"]
        if n_slots:
            self.hist["chain_util"].record(occupancy / n_slots)

    def chain_end(self, tokens: int, occupancy: int,
                  chain: Optional[int] = None) -> None:
        fields: dict = {"tokens": tokens, "occupancy": occupancy}
        if chain is not None:
            fields["chain"] = chain
        ev = self.record("chain_end", **fields)
        if chain is None:
            return
        start = self._open_chains.pop(chain, None)
        if start is None:
            return
        span = ev["t"] - start
        # overlap = fraction of this chain's dispatch->fetch span during
        # which a LATER chain was already in flight — the pipelining
        # receipt, straight from the stamps the engine already makes
        later = [
            t0 for c, t0 in self._open_chains.items()
            if c > chain and t0 < ev["t"]
        ]
        overlap = 0.0
        if span > 0 and later:
            overlap = min(1.0, max(0.0, (ev["t"] - min(later)) / span))
        self.hist["chain_overlap"].record(overlap)

    def sweep(self, completed: int) -> None:
        self.record("sweep", completed=completed)

    def preempted(self, rid: Any, slot: int = 0, position: int = 0,
                  tokens: int = 0) -> None:
        """An SLO preemption swapped ``rid`` out of ``slot`` to host
        (ISSUE 20): ``position`` is the sequence position parked,
        ``tokens`` the generated tokens kept. Host-only like every
        stamp — the swap's device fetch is counted by the ENGINE
        (n_swaps_out), not here."""
        self.record("preempt", rid=rid, slot=slot, position=position,
                    tokens=tokens)

    def resumed(self, rid: Any, slot: int = 0,
                wait_s: float = 0.0) -> None:
        """A preempted request re-spliced into ``slot``; ``wait_s`` is
        the swap-out -> swap-in wall time, fed to the preempted-wait
        histogram."""
        self.record("resume", rid=rid, slot=slot,
                    wait_s=round(float(wait_s), 6))
        self.hist["preempt_wait"].record(wait_s)

    def fault(self, fault_kind: str, **fields: Any) -> None:
        """A fault_stats-visible anomaly (nonfinite / deadline /
        prefill_error / adapter_evicted ...). Auto-dumps when a
        ``dump_path`` is configured."""
        self.record("fault", fault_kind=fault_kind, **fields)

    # -- trainer hooks -----------------------------------------------------

    def step_skipped(self, step: int) -> None:
        """A Trainer nonfinite skip became host-visible. This fires from
        MetricsLogger's existing batched drain — never per step."""
        self.record("step_skipped", step=step)

    def rollback(self, step: int, loss: float) -> None:
        self.record("rollback", step=step, loss=float(loss))

    # -- snapshots ---------------------------------------------------------

    def snapshot(self, reason: str = "manual",
                 trigger: Optional[dict] = None) -> dict:
        """The ``graft-flightlog/v1`` dump object: trailing events, live
        spans, recent completed spans, histogram state, counters."""
        return {
            "schema": FLIGHT_SCHEMA,
            "reason": reason,
            "t": round(self._now(), 6),
            "trigger": trigger,
            "events": list(self.events)[-self.dump_events:],
            "live_spans": [dict(s) for s in self.spans.values()],
            "done_spans": [dict(s) for s in self.done_spans],
            "histograms": {k: h.to_dict() for k, h in self.hist.items()},
            "counts": dict(self.kind_counts),
            "n_events": self.n_events,
            "dropped": self.dropped,
        }

    def dump(self, reason: str = "manual",
             trigger: Optional[dict] = None) -> dict:
        """Append one snapshot line to ``dump_path`` (JSONL) and return
        it. With no path configured the snapshot is still built and
        returned (the selftest asserts on it in-process)."""
        snap = self.snapshot(reason=reason, trigger=trigger)
        self.n_dumps += 1
        if self.dump_path is not None:
            with open(self.dump_path, "a") as f:
                f.write(json.dumps(snap) + "\n")
        return snap

    # -- receipt surface ---------------------------------------------------

    def summary(self) -> dict:
        """Flat receipt-ready aggregate: recorder counters + the four
        histogram summaries (``ttft_p95_s``-style keys)."""
        out = {
            "flight": 1,
            "flight_events": self.n_events,
            "flight_dropped": self.dropped,
            "flight_faults": self.n_faults,
            "flight_dumps": self.n_dumps,
            "flight_spans_live": len(self.spans),
            "flight_spans_done": len(self.done_spans),
        }
        out.update(self.hist["ttft"].summary(prefix="ttft_", unit="s"))
        out.update(self.hist["e2e"].summary(prefix="e2e_", unit="s"))
        out.update(
            self.hist["queue_wait"].summary(prefix="queue_wait_", unit="s")
        )
        out.update(self.hist["chain_util"].summary(prefix="chain_util_"))
        out.update(
            self.hist["chain_overlap"].summary(prefix="chain_overlap_")
        )
        out.update(
            self.hist["preempt_wait"].summary(prefix="preempt_wait_",
                                              unit="s")
        )
        return {
            k: (round(v, 6) if isinstance(v, float) else v)
            for k, v in out.items()
        }


# -- fleet merge (serve/router.py, ISSUE 12) -------------------------------

def _merged_histograms(snaps: List[dict]) -> Dict[str, LogHistogram]:
    """Bucket-wise merge of every snapshot's histogram states, keyed by
    name. All recorders build the same geometry per name, so
    :meth:`..obs.histogram.LogHistogram.merge` applies directly — the
    merged counts are EXACTLY what one recorder observing all the
    traffic would hold; this is the mergeability LogHistogram was built
    for."""
    hists: Dict[str, LogHistogram] = {}
    for snap in snaps:
        for name, state in snap.get("histograms", {}).items():
            h = LogHistogram.from_dict(state)
            if name in hists:
                hists[name].merge(h)
            else:
                hists[name] = h
    return hists


def merge_snapshots(tagged: List[tuple], reason: str = "fleet") -> dict:
    """Merge N recorders' snapshots into ONE ``graft-flightlog/v1``
    snapshot: events and spans gain a ``replica`` tag (the caller's —
    an int index or "router"), events interleave by timestamp (pass one
    shared ``t0`` to every recorder for a common timeline), counts and
    totals sum, histograms merge bucket-wise. The result validates and
    renders exactly like a single-recorder dump, so
    ``scripts/flight_view.py`` needs no fleet mode — only the
    ``replica=`` field and health annotations."""
    events: List[dict] = []
    live: List[dict] = []
    done: List[dict] = []
    counts: Counter = Counter()
    n_events = 0
    dropped = 0
    t = 0.0
    for tag, snap in tagged:
        validate_flightlog(snap)
        for ev in snap["events"]:
            merged_ev = dict(ev)
            merged_ev.setdefault("replica", tag)
            events.append(merged_ev)
        for span in snap["live_spans"]:
            live.append({**span, "replica": tag})
        for span in snap["done_spans"]:
            done.append({**span, "replica": tag})
        counts.update(snap.get("counts", {}))
        n_events += snap.get("n_events", 0)
        dropped += snap.get("dropped", 0)
        t = max(t, snap.get("t", 0.0))
    events.sort(key=lambda e: e.get("t", 0.0))
    hists = _merged_histograms([snap for _, snap in tagged])
    return {
        "schema": FLIGHT_SCHEMA,
        "reason": reason,
        "t": t,
        "trigger": None,
        "events": events,
        "live_spans": live,
        "done_spans": done,
        "histograms": {k: h.to_dict() for k, h in hists.items()},
        "counts": dict(counts),
        "n_events": n_events,
        "dropped": dropped,
    }


def summarize_merged(snaps: List[dict]) -> dict:
    """The receipt-grade aggregate over N snapshots — same keys as
    :meth:`FlightRecorder.summary` so a fleet receipt drops into the
    slots a single-engine receipt used, but the percentile fields come
    from the MERGED histograms (averaging or summing per-replica p95s
    would be statistically meaningless)."""
    hists = _merged_histograms(snaps)
    out = {
        "flight": 1,
        "flight_events": sum(s.get("n_events", 0) for s in snaps),
        "flight_dropped": sum(s.get("dropped", 0) for s in snaps),
        "flight_faults": sum(
            s.get("counts", {}).get(k, 0)
            for s in snaps for k in _AUTO_DUMP_KINDS
        ),
        "flight_spans_live": sum(len(s["live_spans"]) for s in snaps),
        "flight_spans_done": sum(len(s["done_spans"]) for s in snaps),
    }
    prefixes = {
        "ttft": ("ttft_", "s"), "e2e": ("e2e_", "s"),
        "queue_wait": ("queue_wait_", "s"),
        "chain_util": ("chain_util_", None),
        "chain_overlap": ("chain_overlap_", None),
        "preempt_wait": ("preempt_wait_", "s"),
    }
    for name, (prefix, unit) in prefixes.items():
        h = hists.get(name)
        if h is None:
            continue
        if unit is None:
            out.update(h.summary(prefix=prefix))
        else:
            out.update(h.summary(prefix=prefix, unit=unit))
    return {
        k: (round(v, 6) if isinstance(v, float) else v)
        for k, v in out.items()
    }


# -- dump-file tooling (scripts/flight_view.py + tests) --------------------

def validate_flightlog(obj: dict) -> None:
    """Raise ValueError unless ``obj`` is a well-formed flight snapshot."""
    if not isinstance(obj, dict):
        raise ValueError("flightlog snapshot must be a dict")
    if obj.get("schema") != FLIGHT_SCHEMA:
        raise ValueError(
            f"schema mismatch: {obj.get('schema')!r} != {FLIGHT_SCHEMA!r}"
        )
    for key in ("reason", "t", "events", "live_spans", "done_spans",
                "histograms", "counts"):
        if key not in obj:
            raise ValueError(f"flightlog snapshot missing key {key!r}")
    for ev in obj["events"]:
        if ev.get("kind") not in EVENT_KINDS:
            raise ValueError(
                f"flightlog event has unknown kind {ev.get('kind')!r}"
            )


def load_flightlog(path: str) -> List[dict]:
    """Read + validate every snapshot line of a JSONL flight log."""
    snaps = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            validate_flightlog(obj)
            snaps.append(obj)
    return snaps
