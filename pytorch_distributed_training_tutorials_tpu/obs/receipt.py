"""One schema'd receipt writer for every performance claim.

Every number this repo has ever quoted (BENCH_*, SERVING_*, TRAIN_LLM_*,
PROFILE_*) was produced by a script writing its own ad-hoc JSON; nothing
stamped WHICH code, WHICH jax, WHICH mesh, or how stable the measurement
window was. This module is the single envelope all of them now write
through:

    receipt = make_receipt("bench_headline", payload, mesh=mesh, drift=...)
    write_receipt(path, receipt)

The envelope is FLAT-MERGED with the payload (payload keys stay top-level)
so existing consumers that read ``metric`` / ``value`` / ``tok_s`` keep
working; the envelope adds ``schema`` / ``kind`` / ``env`` / optional
``drift``. :func:`validate_receipt` checks both the schema'd form and (in
legacy mode) the payloads of receipts checked in before the schema existed.

Import purity: this module imports jax only inside :func:`environment_stamp`
— receipt validation (tests, tooling) must not initialize a backend.
"""

from __future__ import annotations

import json
import os
import subprocess

SCHEMA = "graft-receipt/v1"

# Known receipt kinds — one per number-producing entry point.
KINDS = frozenset({
    "bench_headline",    # bench.py
    "lm_headline",       # bench/lm_headline.py
    "llm_mfu_sweep",     # scripts/train_llm_mfu.py
    "serving",           # examples/serve_llm_int8.py
    "profile_step",      # scripts/profile_step.py
    "profile_decode",    # scripts/profile_decode.py
    "launch_probe",      # scripts/launch_overhead_probe.py
    "obs_selftest",      # python -m ...obs --selftest
    "serve_selftest",    # python -m ...serve --selftest
})

_ENVELOPE_KEYS = ("schema", "kind", "env", "drift")


def _git_sha() -> str | None:
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=root, capture_output=True, text=True, timeout=10,
        )
    except (OSError, subprocess.SubprocessError):
        return None
    sha = out.stdout.strip()
    return sha if out.returncode == 0 and sha else None


def environment_stamp(mesh=None) -> dict:
    """git sha + jax version + backend + device/mesh shape, best-effort.

    ``mesh``: an optional ``jax.sharding.Mesh`` — its axis dict is the
    honest answer to "what parallelism produced this number".
    """
    import jax  # deferred: stamping implies a backend already exists

    stamp = {
        "git_sha": _git_sha(),
        "jax_version": jax.__version__,
        "backend": jax.default_backend(),
        "device_count": jax.device_count(),
    }
    if mesh is not None:
        stamp["mesh"] = {str(k): int(v) for k, v in dict(mesh.shape).items()}
    return stamp


def make_receipt(kind: str, payload: dict, *, mesh=None,
                 drift: dict | None = None) -> dict:
    """Envelope ``payload`` (flat merge) with schema + environment stamp."""
    if kind not in KINDS:
        raise ValueError(f"unknown receipt kind {kind!r}; known: "
                         f"{', '.join(sorted(KINDS))}")
    clash = set(payload) & set(_ENVELOPE_KEYS)
    if clash:
        raise ValueError(f"payload keys collide with envelope: {clash}")
    receipt = dict(payload)
    receipt["schema"] = SCHEMA
    receipt["kind"] = kind
    receipt["env"] = environment_stamp(mesh=mesh)
    if drift is not None:
        receipt["drift"] = drift
    return receipt


def write_receipt(path: str | None, receipt: dict) -> dict:
    """Validate and write a receipt (no-op write when ``path`` is None)."""
    problems = validate_receipt(receipt)
    if problems:
        raise ValueError("invalid receipt: " + "; ".join(problems))
    if path:
        with open(path, "w") as f:
            json.dump(receipt, f, indent=2)
            f.write("\n")
    return receipt


def validate_receipt(obj, kind: str | None = None) -> list[str]:
    """Problems with a receipt (empty list == valid).

    Two modes:

    - schema'd (``schema`` key present): envelope keys are checked in
      full — known kind, env stamp with jax_version/backend/device_count;
    - legacy (no ``schema`` key): the pre-schema payloads checked in as
      ``BENCH_r0*.json`` / ``TRAIN_LLM_r05.json``. Those are still
      required to be non-empty dicts carrying at least one numeric
      measurement — retroactive validation, not a rubber stamp.
    """
    problems: list[str] = []
    if not isinstance(obj, dict):
        return ["receipt is not a dict"]
    if "schema" not in obj:
        return _validate_legacy(obj, kind)
    if obj["schema"] != SCHEMA:
        problems.append(f"unknown schema {obj['schema']!r}")
    k = obj.get("kind")
    if k not in KINDS:
        problems.append(f"unknown kind {k!r}")
    if kind is not None and k != kind:
        problems.append(f"kind {k!r} != expected {kind!r}")
    env = obj.get("env")
    if not isinstance(env, dict):
        problems.append("missing env stamp")
    else:
        for key in ("jax_version", "backend", "device_count"):
            if key not in env:
                problems.append(f"env stamp missing {key!r}")
    drift = obj.get("drift")
    if drift is not None and not isinstance(drift, dict):
        problems.append("drift must be a dict (DriftBracket.to_dict())")
    payload_keys = [key for key in obj if key not in _ENVELOPE_KEYS]
    if not payload_keys:
        problems.append("empty payload (envelope only)")
    return problems


def _validate_legacy(obj: dict, kind: str | None) -> list[str]:
    if not obj:
        return ["legacy receipt is empty"]

    def numbers(o):
        if isinstance(o, bool):
            return
        if isinstance(o, (int, float)):
            yield o
        elif isinstance(o, dict):
            for v in o.values():
                yield from numbers(v)
        elif isinstance(o, (list, tuple)):
            for v in o:
                yield from numbers(v)

    if not any(True for _ in numbers(obj)):
        return ["legacy receipt carries no numeric measurement"]
    if kind == "bench_headline":
        # the bench line itself, or the min-of-N wrapper that nests it
        # under "parsed" (the checked-in BENCH_r0*.json shape)
        line = obj.get("parsed") if isinstance(obj.get("parsed"), dict) \
            else obj
        missing = [k for k in ("metric", "value", "unit") if k not in line]
        if missing:
            return [f"legacy bench payload missing {missing}"]
    return []


def load_receipt(path: str) -> dict:
    with open(path) as f:
        return json.load(f)
