"""obs: the observability layer — telemetry, trace reports, honest timing.

The reference tutorial's observability is one rank-tagged print
(ddp_gpus.py:44); this repo's replacement grew as scattered scripts plus
CLAUDE.md prose. ``obs`` is that lore as library code, in four pillars:

- :mod:`.metrics` — :class:`MetricsLogger`: typed step/epoch events, ring
  buffer + JSONL, process-0 gated, no per-step host sync;
- :mod:`.trace` — :class:`StepReport`: trace-classified "where did the
  step go" breakdowns (the PROFILE_r04 analysis as one call), fusion
  classes HLO-verified so the ``convert_reduce_fusion`` misread cannot
  recur;
- :mod:`.timing` — :class:`MinOfN` (stall flagging), :class:`DriftBracket`
  (the ``h2d_window_drift`` pattern), :func:`launch_overhead_fit`
  (``wall = fixed + per_op * len``);
- :mod:`.receipt` — the single schema'd envelope every number-producing
  entry point writes through (git sha, jax version, mesh, drift window).

``python -m pytorch_distributed_training_tutorials_tpu.obs --selftest`` smoke-runs all four on a
tiny CPU-mesh workload.
"""

from pytorch_distributed_training_tutorials_tpu.obs.metrics import (  # noqa: F401
    MetricsLogger,
)
from pytorch_distributed_training_tutorials_tpu.obs.trace import (  # noqa: F401
    StepReport,
    classify_hlo,
)
from pytorch_distributed_training_tutorials_tpu.obs.timing import (  # noqa: F401
    BracketResult,
    DriftBracket,
    LaunchFit,
    MinOfN,
    TimingResult,
    launch_overhead_fit,
)
from pytorch_distributed_training_tutorials_tpu.obs.receipt import (  # noqa: F401
    KINDS,
    SCHEMA,
    environment_stamp,
    load_receipt,
    make_receipt,
    validate_receipt,
    write_receipt,
)
