"""obs: the observability layer — telemetry, trace reports, honest timing.

The reference tutorial's observability is one rank-tagged print
(ddp_gpus.py:44); this repo's replacement grew as scattered scripts plus
CLAUDE.md prose. ``obs`` is that lore as library code, in four pillars:

- :mod:`.metrics` — :class:`MetricsLogger`: typed step/epoch events, ring
  buffer + JSONL, process-0 gated, no per-step host sync;
- :mod:`.trace` — :class:`StepReport`: trace-classified "where did the
  step go" breakdowns (the PROFILE_r04 analysis as one call), fusion
  classes HLO-verified so the ``convert_reduce_fusion`` misread cannot
  recur;
- :mod:`.timing` — :class:`MinOfN` (stall flagging), :class:`DriftBracket`
  (the ``h2d_window_drift`` pattern), :func:`launch_overhead_fit`
  (``wall = fixed + per_op * len``);
- :mod:`.receipt` — the single schema'd envelope every number-producing
  entry point writes through (git sha, jax version, mesh, drift window).

Plus the production twin of the benchmarking pillars (ISSUE 10):

- :mod:`.flight` — :class:`FlightRecorder`: bounded request-lifecycle
  event ring + per-request spans + ``graft-flightlog/v1`` fault dumps,
  host-only and budget-neutral by contract;
- :mod:`.histogram` — :class:`LogHistogram`: streaming log2 histograms
  with mergeable state and bounded-error p50/p95/p99 (the serving
  percentile path — replaces sort-the-list);
- :mod:`.sentry` — :class:`ContractSentry` (ISSUE 19): runtime monitor
  for the three engine contracts — zero steady-state recompiles (JAX
  compilation events), the serve fetch budget (the production twin of
  the test monkeypatch spies), and no host-numpy re-uploads per
  dispatch; violations announce as typed flight events + auto-dumps.

``python -m pytorch_distributed_training_tutorials_tpu.obs --selftest`` smoke-runs all four on a
tiny CPU-mesh workload.

The re-exports below are PEP 562 LAZY (same pattern as the top-level
package init): importing ``pytorch_distributed_training_tutorials_tpu.obs`` does not import
jax, so jax-free tooling (``bench.regress``, receipt validation in CI)
can reach :mod:`.receipt` without initializing a backend.
"""

import importlib

# name -> submodule; resolved on first access via __getattr__.
_LAZY_EXPORTS = {
    "MetricsLogger": "pytorch_distributed_training_tutorials_tpu.obs.metrics",
    "StepReport": "pytorch_distributed_training_tutorials_tpu.obs.trace",
    "classify_hlo": "pytorch_distributed_training_tutorials_tpu.obs.trace",
    "BracketResult": "pytorch_distributed_training_tutorials_tpu.obs.timing",
    "DriftBracket": "pytorch_distributed_training_tutorials_tpu.obs.timing",
    "LaunchFit": "pytorch_distributed_training_tutorials_tpu.obs.timing",
    "MinOfN": "pytorch_distributed_training_tutorials_tpu.obs.timing",
    "TimingResult": "pytorch_distributed_training_tutorials_tpu.obs.timing",
    "launch_overhead_fit": "pytorch_distributed_training_tutorials_tpu.obs.timing",
    "KINDS": "pytorch_distributed_training_tutorials_tpu.obs.receipt",
    "SCHEMA": "pytorch_distributed_training_tutorials_tpu.obs.receipt",
    "environment_stamp": "pytorch_distributed_training_tutorials_tpu.obs.receipt",
    "load_receipt": "pytorch_distributed_training_tutorials_tpu.obs.receipt",
    "make_receipt": "pytorch_distributed_training_tutorials_tpu.obs.receipt",
    "validate_receipt": "pytorch_distributed_training_tutorials_tpu.obs.receipt",
    "write_receipt": "pytorch_distributed_training_tutorials_tpu.obs.receipt",
    "EVENT_KINDS": "pytorch_distributed_training_tutorials_tpu.obs.flight",
    "FLIGHT_SCHEMA": "pytorch_distributed_training_tutorials_tpu.obs.flight",
    "FlightRecorder": "pytorch_distributed_training_tutorials_tpu.obs.flight",
    "load_flightlog": "pytorch_distributed_training_tutorials_tpu.obs.flight",
    "merge_snapshots": "pytorch_distributed_training_tutorials_tpu.obs.flight",
    "summarize_merged": "pytorch_distributed_training_tutorials_tpu.obs.flight",
    "validate_flightlog": "pytorch_distributed_training_tutorials_tpu.obs.flight",
    "LogHistogram": "pytorch_distributed_training_tutorials_tpu.obs.histogram",
    "ContractSentry": "pytorch_distributed_training_tutorials_tpu.obs.sentry",
}

__all__ = sorted(_LAZY_EXPORTS)


def __getattr__(name: str):
    try:
        module_name = _LAZY_EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    value = getattr(importlib.import_module(module_name), name)
    globals()[name] = value  # cache: __getattr__ runs once per name
    return value


def __dir__():
    return sorted(set(globals()) | set(_LAZY_EXPORTS))
