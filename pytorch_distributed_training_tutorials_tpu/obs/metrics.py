"""MetricsLogger: typed per-step/per-epoch telemetry without host syncs.

The reference's entire log surface is one rank-tagged per-epoch print that
never includes the loss (ddp_gpus.py:44; SURVEY.md section 5.5). This is
the structured replacement: every event lands in an in-memory ring buffer
and (process 0 only) an optional JSONL sink, and the VERBOSE step line the
Trainer used to print directly now goes through the same code path — the
printed loss and the recorded loss are the same fetched float, so console
logging and structured metrics cannot diverge.

The hot-path contract (the whole point): ``log_step`` performs NO host
sync — device scalars are retained as-is and fetched in ONE batched
``jax.device_get`` at epoch/flush boundaries. With ``defer_host_fetch``
(the Trainer's deferred mode) even the epoch boundary skips the fetch;
pending scalars drain only at an explicit :meth:`flush`. The single
deliberate exception is a ``log_every``-opted verbose step line, which has
always cost one loss fetch (trainer.py's log_every docs).
"""

from __future__ import annotations

import collections
import json
from typing import IO

import jax

from pytorch_distributed_training_tutorials_tpu.utils.logging import log0


class MetricsLogger:
    """Ring buffer + JSONL sink for step/epoch events, process-0 gated.

    Parameters
    ----------
    jsonl_path: sink file (one JSON object per line); None = in-memory only.
    capacity: ring-buffer size for both flushed events and pending scalars.
    quiet: suppress ALL console lines (bench runs); events still record.
    defer_host_fetch: epoch boundaries do NOT fetch pending device
        scalars (the Trainer's defer contract) — only :meth:`flush` does.
    flops_per_token / peak_flops / tokens_per_sample: when set, epoch
        events gain ``tokens_per_sec`` and ``mfu`` derived from
        ``samples_per_sec`` (the analytic-FLOPs MFU convention —
        models.utils.model_flops_per_token, never cost_analysis on a
        scanned model, TRAIN_LLM_r05.md).
    flight: optional :class:`..obs.flight.FlightRecorder`. Skip-step
        observations become ``step_skipped`` flight events AT DRAIN TIME
        — the skip flag already rides the batched fetch, so the recorder
        learns about a skipped step without any new per-step host sync
        (it is simply as late as the loss itself).
    """

    def __init__(
        self,
        *,
        jsonl_path: str | None = None,
        capacity: int = 4096,
        quiet: bool = False,
        defer_host_fetch: bool = False,
        flops_per_token: float | None = None,
        peak_flops: float | None = None,
        tokens_per_sample: int | None = None,
        flight=None,
    ):
        self.events: collections.deque[dict] = collections.deque(
            maxlen=capacity
        )
        self._pending: collections.deque[tuple[int, object, dict | None]] = (
            collections.deque(maxlen=capacity)
        )
        self.jsonl_path = jsonl_path
        self.quiet = quiet
        self.defer_host_fetch = defer_host_fetch
        self.flops_per_token = flops_per_token
        self.peak_flops = peak_flops
        self.tokens_per_sample = tokens_per_sample
        self.flight = flight
        self._sink: IO[str] | None = None

    # -- gating ------------------------------------------------------------

    @property
    def is_process_zero(self) -> bool:
        return jax.process_index() == 0

    def say(self, msg: str) -> None:
        """Console line: process-0 gated, silenced by ``quiet``."""
        if not self.quiet:
            log0(msg)

    # -- event intake ------------------------------------------------------

    def log_step(
        self, step: int, loss, verbose: bool = False, extra: dict | None = None
    ) -> None:
        """Record a step's loss. NO host sync unless ``verbose``.

        ``loss`` may be a device scalar — it is retained un-fetched. A
        verbose call (the Trainer's ``log_every`` opt-in) fetches ONCE and
        prints + records the same float, the one deliberate per-step sync
        this module permits. ``extra`` is an optional dict of additional
        scalars (device or host — e.g. the skip-step counter ISSUE 9's
        guardrails emit); its values ride the SAME batched drain fetch as
        the loss, so extras never add a host sync either.
        """
        if verbose:
            loss = float(loss)  # the single opted-in fetch
            self.say(f"  step {step}: loss {loss:.4f}")
        self._pending.append((int(step), loss, extra))

    def log_epoch(self, metrics: dict) -> dict:
        """Record an epoch event (and drain pending steps, fetch rules
        permitting); prints the Trainer's epoch line unless quiet."""
        if not self.defer_host_fetch:
            self._drain_pending()
        event = {"kind": "epoch", **metrics}
        if self.tokens_per_sample and "samples_per_sec" in metrics:
            event["tokens_per_sec"] = (
                metrics["samples_per_sec"] * self.tokens_per_sample
            )
        if (
            self.flops_per_token
            and self.peak_flops
            and "tokens_per_sec" in event
        ):
            event["mfu"] = (
                event["tokens_per_sec"] * self.flops_per_token
                / self.peak_flops
            )
        self._record(event)
        self.say(
            f"  epoch {metrics['epoch']}: loss {metrics['loss']:.4f} | "
            f"{metrics['steps_per_sec']:.1f} steps/s | "
            f"{metrics['samples_per_sec']:.0f} samples/s"
        )
        return event

    # -- draining ----------------------------------------------------------

    def _drain_pending(self) -> None:
        if not self._pending:
            return
        pending = list(self._pending)
        self._pending.clear()
        # ONE batched fetch for everything accumulated since the last drain
        # (device_get walks the pytree, so loss + extras fetch together;
        # None extras are empty subtrees).
        values = jax.device_get([(v, e) for _, v, e in pending])
        for (step, _, _), (val, ext) in zip(pending, values):
            event = {"kind": "step", "step": step, "loss": float(val)}
            if ext:
                event.update({k: float(v) for k, v in ext.items()})
            if self.flight is not None and event.get("skipped"):
                # the skip became host-visible with THIS drain; stamp it
                # (auto-dumps when the recorder has a dump_path)
                self.flight.step_skipped(step=event["step"])
            self._record(event)

    def flush(self) -> None:
        """Drain pending device scalars (even under defer_host_fetch — this
        IS the explicit fetch point) and flush the JSONL sink."""
        self._drain_pending()
        if self._sink is not None:
            self._sink.flush()

    def close(self) -> None:
        self.flush()
        if self._sink is not None:
            self._sink.close()
            self._sink = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- storage -----------------------------------------------------------

    def _record(self, event: dict) -> None:
        self.events.append(event)
        if self.jsonl_path and self.is_process_zero:
            if self._sink is None:
                self._sink = open(self.jsonl_path, "a")
            self._sink.write(json.dumps(event) + "\n")

    # -- views -------------------------------------------------------------

    @property
    def last_epoch(self) -> dict | None:
        for ev in reversed(self.events):
            if ev.get("kind") == "epoch":
                return ev
        return None

    def step_events(self) -> list[dict]:
        return [e for e in self.events if e.get("kind") == "step"]

    def epoch_events(self) -> list[dict]:
        return [e for e in self.events if e.get("kind") == "epoch"]
