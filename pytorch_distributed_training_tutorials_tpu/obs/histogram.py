"""Streaming log2 histograms: bounded-error quantiles without the list.

Every serving percentile this repo quoted so far was sort-the-list over
completed requests (examples/serve_llm_int8.py's ``np.percentile`` over
``sorted(c.latency_s ...)``) — fine for a 12-request receipt arm, wrong
for the north-star request stream: the list grows without bound, and two
processes' lists cannot be combined without shipping every sample.
:class:`LogHistogram` is the standard fix (HDR-histogram-style
fixed-bucket geometric binning): O(bins) memory forever, O(1) record,
mergeable state (element-wise count addition — shard per worker, merge at
receipt time), and quantiles whose relative error is bounded by the
bucket ratio, a constant chosen at construction, never by the data.

Geometry: bucket 0 absorbs everything at or below ``min_value`` (zeros
included — a zero-latency sample is a degenerate reading, not a crash);
bucket ``i >= 1`` covers the half-open ratio interval
``(min_value * r^(i-1), min_value * r^i]`` with ``r = 2^(1/bins_per_octave)``;
values past ``max_value`` clamp into the last bucket (the true max is
kept separately, so the tail quantile stays honest). A quantile estimate
is the geometric midpoint of its bucket, clamped to the observed
[min, max] — so the worst-case relative error is ``sqrt(r) - 1`` against
any sample inside the bucket, and :attr:`rel_error_bound` (``r - 1``,
one full bucket) is the documented guarantee tests assert against
sort-based percentiles.

jax-free BY CONTRACT (stdlib ``math`` only): recorders run inside the
serving host loop where importing jax is fine but *initializing a
backend from tooling* is not — the no-jax subprocess pin in
tests/test_prefix.py covers this module alongside the scheduler and the
prefix index.
"""

from __future__ import annotations

import math


class LogHistogram:
    """Fixed-bucket log2 histogram with mergeable state.

    Parameters
    ----------
    min_value: lower edge of bucket 1; everything at or below lands in
        bucket 0 (the underflow bucket). Must be > 0.
    max_value: values above it clamp into the last bucket.
    bins_per_octave: buckets per factor-of-2 — the resolution/memory
        knob. 8 gives a bucket ratio of ~1.09 (relative error bound ~9%)
        at ~27 buckets per factor-of-1e8 span decade-octave.
    """

    __slots__ = (
        "min_value", "max_value", "bins_per_octave", "n_bins",
        "counts", "n", "total", "min_seen", "max_seen",
    )

    def __init__(self, min_value: float = 1e-4, max_value: float = 1e4,
                 bins_per_octave: int = 8):
        if min_value <= 0:
            raise ValueError(f"min_value must be > 0, got {min_value}")
        if max_value <= min_value:
            raise ValueError("max_value must exceed min_value")
        if bins_per_octave < 1:
            raise ValueError("bins_per_octave must be >= 1")
        self.min_value = float(min_value)
        self.max_value = float(max_value)
        self.bins_per_octave = int(bins_per_octave)
        octaves = math.log2(self.max_value / self.min_value)
        # +1 for the underflow bucket 0; ceil so max_value itself fits
        self.n_bins = int(math.ceil(octaves * self.bins_per_octave)) + 1
        self.counts = [0] * self.n_bins
        self.n = 0
        self.total = 0.0
        self.min_seen = math.inf
        self.max_seen = -math.inf

    # -- recording ---------------------------------------------------------

    def _bucket(self, value: float) -> int:
        if value <= self.min_value:
            return 0
        i = int(math.log2(value / self.min_value) * self.bins_per_octave)
        # log2 of an exact bucket edge can land on the edge index; the
        # interval is (lo, hi], so push exact-edge values down a bucket
        lo = self.min_value * 2.0 ** (i / self.bins_per_octave)
        if value <= lo and i > 0:
            i -= 1
        return min(i + 1, self.n_bins - 1)

    def record(self, value: float) -> None:
        """O(1) intake of one sample; NaNs are dropped (counted nowhere —
        a non-finite latency is a bug upstream, not a tail event)."""
        v = float(value)
        if math.isnan(v):
            return
        self.counts[self._bucket(v)] += 1
        self.n += 1
        self.total += v
        self.min_seen = min(self.min_seen, v)
        self.max_seen = max(self.max_seen, v)

    # -- merge -------------------------------------------------------------

    def merge(self, other: "LogHistogram") -> "LogHistogram":
        """Element-wise merge of ``other`` into self (both must share
        geometry). Recording shards independently and merging is EXACTLY
        recording everything into one histogram — bucketing is
        deterministic — which is what makes per-worker recorders safe."""
        if (other.min_value, other.max_value, other.bins_per_octave) != (
            self.min_value, self.max_value, self.bins_per_octave
        ):
            raise ValueError("cannot merge histograms of different geometry")
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.n += other.n
        self.total += other.total
        self.min_seen = min(self.min_seen, other.min_seen)
        self.max_seen = max(self.max_seen, other.max_seen)
        return self

    # -- quantiles ---------------------------------------------------------

    @property
    def rel_error_bound(self) -> float:
        """One full bucket's relative width — the documented worst-case
        quantile error vs an exact sort (the estimate itself is the
        geometric midpoint, so typically half this)."""
        return 2.0 ** (1.0 / self.bins_per_octave) - 1.0

    def quantile(self, q: float) -> float:
        """Bounded-error quantile: walk the cumulative counts to the
        bucket holding rank ``ceil(q * n)`` and return its geometric
        midpoint clamped to the observed [min, max]. Returns 0.0 on an
        empty histogram (receipts round-trip through JSON; NaN does not)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        if self.n == 0:
            return 0.0
        target = max(1, math.ceil(q * self.n))
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= target:
                if i == 0:
                    est = self.min_value
                else:
                    hi = self.min_value * 2.0 ** (i / self.bins_per_octave)
                    lo = self.min_value * 2.0 ** (
                        (i - 1) / self.bins_per_octave
                    )
                    est = math.sqrt(lo * hi)
                return min(max(est, self.min_seen), self.max_seen)
        return self.max_seen  # unreachable unless counts were mutated

    @property
    def mean(self) -> float:
        return self.total / self.n if self.n else 0.0

    def summary(self, prefix: str = "", unit: str = "") -> dict:
        """Flat receipt-ready dict: count/mean/min/max + p50/p95/p99.
        ``unit`` suffixes the value keys (``ttft_p95_s``-style names)."""
        u = f"_{unit}" if unit else ""
        return {
            f"{prefix}count": self.n,
            f"{prefix}mean{u}": self.mean,
            f"{prefix}min{u}": self.min_seen if self.n else 0.0,
            f"{prefix}max{u}": self.max_seen if self.n else 0.0,
            f"{prefix}p50{u}": self.quantile(0.50),
            f"{prefix}p95{u}": self.quantile(0.95),
            f"{prefix}p99{u}": self.quantile(0.99),
        }

    # -- (de)serialization -------------------------------------------------

    def to_dict(self) -> dict:
        """JSON-ready state; sparse counts keep flight-log dumps small."""
        return {
            "min_value": self.min_value,
            "max_value": self.max_value,
            "bins_per_octave": self.bins_per_octave,
            "n": self.n,
            "total": self.total,
            "min_seen": self.min_seen if self.n else None,
            "max_seen": self.max_seen if self.n else None,
            "counts": {
                str(i): c for i, c in enumerate(self.counts) if c
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "LogHistogram":
        h = cls(
            min_value=d["min_value"], max_value=d["max_value"],
            bins_per_octave=d["bins_per_octave"],
        )
        for i, c in d["counts"].items():
            h.counts[int(i)] = int(c)
        h.n = int(d["n"])
        h.total = float(d["total"])
        h.min_seen = (
            float(d["min_seen"]) if d.get("min_seen") is not None
            else math.inf
        )
        h.max_seen = (
            float(d["max_seen"]) if d.get("max_seen") is not None
            else -math.inf
        )
        return h
