"""``python -m pytorch_distributed_training_tutorials_tpu.obs --selftest``: end-to-end smoke of the
observability layer on a tiny workload.

Exercises all five pillars against whatever backend is available (the
tier-1 test runs it on the forced 8-device CPU mesh): trains a few steps
with a JSONL-sinked :class:`MetricsLogger`, captures a real profiler trace
of a jitted step chain, classifies it with :class:`StepReport` (HLO-
verified), drives the flight-recorder pillar (histogram sharding/merge vs
numpy percentiles, a full lifecycle span, a ``graft-flightlog/v1`` dump
round-tripped through disk and re-validated), and emits an
``obs_selftest`` receipt through the schema'd writer. Prints exactly one
JSON line on stdout and exits non-zero on any validation failure — a
living receipt that the pipeline works.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile


def selftest(json_path: str | None = None) -> dict:
    import jax
    import optax

    from pytorch_distributed_training_tutorials_tpu.data import ShardedLoader, synthetic_regression
    from pytorch_distributed_training_tutorials_tpu.models import LinearRegressor
    from pytorch_distributed_training_tutorials_tpu.obs import (
        MetricsLogger,
        MinOfN,
        StepReport,
        make_receipt,
        validate_receipt,
    )
    from pytorch_distributed_training_tutorials_tpu.parallel.mesh import create_mesh
    from pytorch_distributed_training_tutorials_tpu.train import Trainer
    from pytorch_distributed_training_tutorials_tpu.utils import profiling

    problems: list[str] = []
    workdir = tempfile.mkdtemp(prefix="obs-selftest-")
    jsonl_path = os.path.join(workdir, "metrics.jsonl")

    # pillar 1: metrics through a quiet, JSONL-sinked logger
    mesh = create_mesh({"data": jax.device_count()})
    loader = ShardedLoader(
        synthetic_regression(size=256, in_dim=8, out_dim=1), 8, mesh
    )
    metrics = MetricsLogger(jsonl_path=jsonl_path, quiet=True)
    trainer = Trainer(
        LinearRegressor(in_dim=8), loader, optax.sgd(1e-2), loss="mse",
        metrics=metrics, log_every=2,
    )
    trainer.train(2)
    metrics.close()
    if not metrics.epoch_events():
        problems.append("no epoch events recorded")
    if not metrics.step_events():
        problems.append("no step events recorded")
    with open(jsonl_path) as f:
        jsonl_lines = [json.loads(line) for line in f if line.strip()]
    if len(jsonl_lines) != len(metrics.events):
        problems.append(
            f"jsonl sink ({len(jsonl_lines)}) != ring buffer "
            f"({len(metrics.events)})"
        )

    # pillar 3: MinOfN on a fetch-closed chain (warmup primes first fetch)
    steps = 4
    batch = next(iter(loader))

    def chain(s, b):
        return jax.lax.scan(
            lambda st, _: (trainer.train_step(st, b)[0], None),
            s, None, length=steps,
        )[0]

    compiled = jax.jit(chain).lower(trainer.state, batch).compile()
    timing = MinOfN(n=3).measure(
        lambda: jax.block_until_ready(compiled(trainer.state, batch))
    )
    if timing.best_s <= 0:
        problems.append("MinOfN produced a non-positive sample")

    # pillar 2: a real trace, classified against the compiled HLO
    logdir = os.path.join(workdir, "trace")
    with profiling.trace(logdir):
        jax.block_until_ready(compiled(trainer.state, batch))
    report = StepReport.from_trace(
        logdir, hlo=compiled.as_text(), steps=steps
    )
    if report.total_us <= 0:
        problems.append("trace captured no device time")
    if report.unclassified_fraction > 0.10:
        problems.append(
            f"{100 * report.unclassified_fraction:.1f}% of device time "
            "unclassified (>10%)"
        )

    # pillar 5: flight recorder + streaming histograms (ISSUE 10) —
    # jax-free, so this leg runs identically on any backend
    import math
    import random

    from pytorch_distributed_training_tutorials_tpu.obs import (
        FlightRecorder,
        LogHistogram,
        load_flightlog,
        validate_flightlog,
    )

    # histograms: shard a heavy-tailed sample over two recorders, merge,
    # and require every quantile within the documented one-bucket bound
    # of the exact sorted-sample value
    rng = random.Random(7)
    samples = [rng.lognormvariate(-3.0, 1.5) for _ in range(4000)]
    whole = LogHistogram()
    sharded = [LogHistogram(), LogHistogram()]
    for i, v in enumerate(samples):
        whole.record(v)
        sharded[i % 2].record(v)
    merged = sharded[0].merge(sharded[1])
    if merged.counts != whole.counts or merged.n != whole.n:
        problems.append("sharded histogram merge != whole-sample record")
    svals = sorted(samples)
    for q in (0.5, 0.95, 0.99):
        exact = svals[max(1, math.ceil(q * len(svals))) - 1]
        if abs(whole.quantile(q) - exact) > whole.rel_error_bound * exact:
            problems.append(
                f"histogram q={q} off by more than one bucket: "
                f"{whole.quantile(q)} vs exact {exact}"
            )
    # flight dump round-trip: one synthetic lifecycle + a fault, dumped
    # to disk, loaded back, re-validated
    flight_path = os.path.join(workdir, "flight.jsonl")
    rec = FlightRecorder(capacity=32, dump_path=flight_path)
    rec.request_submitted(0, p_len=4, max_new=8)
    rec.request_popped(0)
    rec.request_prefilled(0, slot=1)
    rec.chain_start(1, 2)
    rec.chain_end(tokens=8, occupancy=1)
    rec.fault("nonfinite", rid=0, slot=1, chain_step=3)
    rec.request_completed(0, "nonfinite", tokens=3)
    try:
        snaps = load_flightlog(flight_path)
        for snap in snaps:
            validate_flightlog(snap)
        if len(snaps) != 1:
            problems.append(f"{len(snaps)} flight dumps, expected 1")
        elif snaps[0]["trigger"].get("slot") != 1:
            problems.append("flight dump trigger lost the faulty slot")
        hist_rt = LogHistogram.from_dict(
            json.loads(json.dumps(whole.to_dict()))
        )
        if hist_rt.counts != whole.counts or (
            hist_rt.quantile(0.95) != whole.quantile(0.95)
        ):
            problems.append("histogram JSON round-trip changed state")
    except ValueError as e:
        problems.append(f"flight dump failed validation: {e}")
    fsum = rec.summary()
    if fsum["flight_spans_done"] != 1 or fsum["e2e_count"] != 1:
        problems.append(f"flight summary inconsistent: {fsum}")

    # pillar 4: the schema'd receipt, validated before it is reported
    receipt = make_receipt(
        "obs_selftest",
        {
            "last_epoch": metrics.last_epoch,
            "n_events": len(metrics.events),
            "timing": timing.to_dict(),
            "step_report": report.to_dict(),
            "flight": fsum,
            "hist_rel_error_bound": whole.rel_error_bound,
            "problems": problems,
            "ok": not problems,
        },
        mesh=mesh,
    )
    problems.extend(validate_receipt(receipt, kind="obs_selftest"))
    receipt["ok"] = not problems
    receipt["problems"] = problems
    if json_path:
        with open(json_path, "w") as f:
            json.dump(receipt, f, indent=2)
            f.write("\n")
    shutil.rmtree(workdir, ignore_errors=True)
    return receipt


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="python -m pytorch_distributed_training_tutorials_tpu.obs")
    parser.add_argument(
        "--selftest", action="store_true",
        help="run the end-to-end observability smoke test",
    )
    parser.add_argument(
        "--json", default=None, help="also write the receipt to this path"
    )
    args = parser.parse_args(argv)
    if not args.selftest:
        parser.print_help()
        return 2
    # ad-hoc CPU runs need the config update as well as the env var
    # (sitecustomize pre-imports jax._src — see CLAUDE.md)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        if "xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""
        ):
            # a bare 1-device XLA:CPU run executes ops inline (no tf_XLA
            # executor threads), so the profiler trace carries no device
            # lanes; the forced mesh is also what tier-1 exercises
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8"
            ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    receipt = selftest(args.json)
    print(json.dumps(receipt))
    return 0 if receipt["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
