"""Spawn-flavor DDP training CLI: twin of reference ``ddp_gpus.py``.

Same flag surface (``--max_epochs``, ``--batch_size`` with *per-device*
semantics, reference ``ddp_gpus.py:98-102``) and the same workload
(``Linear(20, 1)`` on the 2,048-sample synthetic dataset, SGD lr=1e-2,
``ddp_gpus.py:81-82``). The launch shape is TPU-native: on TPU hardware one
process drives all local chips (``--nprocs 1``, the default — SPMD replaces
per-device forking), while ``--nprocs N`` forks an N-process jax.distributed
world with explicit coordinator rendezvous — the exact ``mp.spawn`` contract
(rank injected, master address fixed up front, ``ddp_gpus.py:12-17,104-105``).

``--loss mse`` is the default: the reference calls ``F.cross_entropy`` on a
1-logit output with random float targets (``ddp_gpus.py:37``), which is
degenerate (constant zero gradient for soft targets over one class); MSE is
the regression loss its synthetic data implies. ``--loss cross_entropy``
restores the literal reference behavior.

Run::

    python -m pytorch_distributed_training_tutorials_tpu.launch.train_ddp \
        --max_epochs 10 --batch_size 32
    # hardware-free 4-process world (the reference's 4-GPU demo):
    python -m ... --nprocs 4 --platform cpu
"""

from __future__ import annotations

import argparse

import optax

from pytorch_distributed_training_tutorials_tpu.data import (
    ShardedLoader,
    synthetic_regression,
)
from pytorch_distributed_training_tutorials_tpu.models import LinearRegressor
from pytorch_distributed_training_tutorials_tpu.parallel import distributed
from pytorch_distributed_training_tutorials_tpu.parallel.mesh import create_mesh
from pytorch_distributed_training_tutorials_tpu.train import Trainer

DATASET_SIZE = 2048  # reference ddp_gpus.py:72
LEARNING_RATE = 1e-2  # reference ddp_gpus.py:82


def main(
    rank: int,
    world_size: int,
    max_epochs: int,
    batch_size: int,
    coordinator: str | None = None,
    loss: str = "mse",
) -> None:
    """Per-process entry (twin of reference ``main``, ``ddp_gpus.py:69-93``).

    setup -> dataset -> sharded loader -> Linear(20,1) -> SGD -> Trainer ->
    train -> teardown, with the DDP wrap/allreduce replaced by SPMD sharding.
    """
    if world_size > 1:
        distributed.init(
            coordinator, num_processes=world_size, process_id=rank
        )
    mesh = create_mesh()  # {'data': all devices} — the world_size twin
    dataset = synthetic_regression(DATASET_SIZE)
    loader = ShardedLoader(dataset, batch_size, mesh)
    trainer = Trainer(
        LinearRegressor(), loader, optax.sgd(LEARNING_RATE), loss=loss
    )
    trainer.train(max_epochs)
    distributed.shutdown()


def build_parser(launch_flags: bool = True) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="TPU-native DDP training (spawn flavor)")
    # the reference's exact two flags (ddp_gpus.py:98-102)
    p.add_argument("--max_epochs", type=int, default=10,
                   help="Total epochs to train the model")
    p.add_argument("--batch_size", type=int, default=32,
                   help="Input batch size on each device (default: 32)")
    if launch_flags:
        p.add_argument("--nprocs", type=int, default=1,
                       help="Processes to fork (1 = pure SPMD over local "
                            "chips; >1 = multi-process world, the mp.spawn "
                            "twin)")
        p.add_argument("--platform", type=str, default=None,
                       help="Force a JAX platform in workers (e.g. 'cpu' for "
                            "the hardware-free multi-process harness)")
    p.add_argument("--loss", choices=("mse", "cross_entropy"), default="mse")
    return p


if __name__ == "__main__":
    args = build_parser().parse_args()
    if args.nprocs == 1:
        if args.platform is not None:
            # Backends aren't initialized yet (imports only trace modules),
            # so the config route still works here; mutating JAX_PLATFORMS
            # would be too late in this process.
            import jax

            jax.config.update("jax_platforms", args.platform)
        main(0, 1, args.max_epochs, args.batch_size, loss=args.loss)
    else:
        from pytorch_distributed_training_tutorials_tpu.launch import (
            coordinator_for_spawn,
            spawn,
        )

        coordinator = coordinator_for_spawn()
        spawn(
            main,
            args.nprocs,
            args=(args.nprocs, args.max_epochs, args.batch_size, coordinator,
                  args.loss),
            coordinator=coordinator,
            platform=args.platform,
        )
