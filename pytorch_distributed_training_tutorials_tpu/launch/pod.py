"""TPU-pod launch contract: run the same binary on every host.

The reference's multi-node story is torchrun's rendezvous agent
(``/root/reference/ddp_gpus_torchrun.py:12-14``). On a Cloud TPU pod the
agent's whole job — discover peers, assign ranks, point everyone at a
coordinator — is already done by the TPU runtime metadata:
``jax.distributed.initialize()`` (via :func:`..parallel.distributed.init`
with no arguments) autodetects coordinator/num_processes/process_id on every
pod host. The launch contract therefore collapses to **run the identical
command on all workers**, which is exactly what
``gcloud compute tpus tpu-vm ssh --worker=all`` does.

This module provides the command builder (pure, tested) and a thin runner.
There is nothing else to build: no env injection, no rendezvous server, no
rank bookkeeping — the SPMD program and the pod metadata carry all of it.
Elastic restart at pod scale is re-running the same command; combined with
:meth:`..train.trainer.Trainer.restore` the relaunched world resumes from
its latest checkpoint (the single-host twin is
``launch.spawn(..., max_restarts=N)``).
"""

from __future__ import annotations

import shlex
import subprocess
from collections.abc import Sequence


def pod_run_command(
    script: str,
    script_args: Sequence[str] = (),
    *,
    tpu_name: str,
    zone: str,
    project: str | None = None,
    worker: str = "all",
    python: str = "python3",
    workdir: str | None = None,
) -> list[str]:
    """The ``gcloud`` invocation that runs ``script`` on every pod worker.

    Twin of the torchrun command line (``02.ddp_toy_example.ipynb`` cells
    11-12) with the agent's responsibilities moved into the TPU runtime::

        gcloud compute tpus tpu-vm ssh NAME --zone=Z --worker=all \\
            --command='python3 train.py --max_epochs 10'

    Returns the argv list (pass to ``subprocess.run`` or print for the
    operator). Pure function — safe to unit test without gcloud installed.
    """
    inner = " ".join(
        [python, shlex.quote(script), *map(shlex.quote, script_args)]
    )
    if workdir:
        inner = f"cd {shlex.quote(workdir)} && {inner}"
    cmd = [
        "gcloud", "compute", "tpus", "tpu-vm", "ssh", tpu_name,
        f"--zone={zone}",
        f"--worker={worker}",
        f"--command={inner}",
    ]
    if project:
        cmd.insert(5, f"--project={project}")
    return cmd


def launch_pod(
    script: str,
    script_args: Sequence[str] = (),
    *,
    tpu_name: str,
    zone: str,
    max_restarts: int = 0,
    **kwargs,
) -> int:
    """Run ``script`` on all workers of ``tpu_name``; optionally re-run on
    failure (the pod-scale restart contract — workers resume from their
    latest checkpoint if the script uses ``Trainer.restore``).

    Returns the final exit code. Requires ``gcloud`` on PATH and SSH access
    to the pod; raises ``FileNotFoundError`` with a clear message otherwise.
    """
    if max_restarts < 0:
        raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
    cmd = pod_run_command(
        script, script_args, tpu_name=tpu_name, zone=zone, **kwargs
    )
    for attempt in range(max_restarts + 1):
        try:
            rc = subprocess.run(cmd).returncode
        except FileNotFoundError as e:
            raise FileNotFoundError(
                "gcloud not found — launch_pod drives `gcloud compute tpus "
                "tpu-vm ssh`; install the Cloud SDK or run the printed "
                f"command manually: {' '.join(map(shlex.quote, cmd))}"
            ) from e
        if rc == 0:
            return 0
        if attempt < max_restarts:
            print(
                f"launch_pod: workers exited {rc}; "
                f"restarting ({attempt + 1}/{max_restarts})"
            )
    return rc
