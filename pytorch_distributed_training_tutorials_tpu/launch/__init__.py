"""Launch layer: the reference's L5 (CLI + process launchers), TPU-native.

The reference exposes two launch contracts whose delta is *where topology
comes from* (SURVEY.md C9/C10):

- **spawn** (reference ``ddp_gpus.py:97-105``): the parent counts devices and
  forks one worker per device with ``mp.spawn``, passing the rank explicitly.
- **torchrun** (reference ``ddp_gpus_torchrun.py:92-99``): an external agent
  does rendezvous and injects ``RANK``/``WORLD_SIZE``/... env vars; the script
  reads them.

On TPU the unit of process parallelism is the *host*, not the chip — one SPMD
process drives all local chips — so:

- :func:`spawn` forks N local processes that form a jax.distributed world
  (the mp.spawn twin; on real pods it models one-process-per-host, and in
  tests it runs multi-"host" CPU worlds with gloo collectives on one machine,
  the reference's "multi-node without a cluster" posture, SURVEY.md section 4).
- ``python -m pytorch_distributed_training_tutorials_tpu.launch.train_ddp_env``
  is the torchrun-twin entrypoint: topology comes entirely from env
  (``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``, or a
  TPU pod's runtime metadata) — run the same command on every host.
"""

from pytorch_distributed_training_tutorials_tpu.launch._spawn import (  # noqa: F401
    coordinator_for_spawn,
    pick_unused_port,
    spawn,
)
from pytorch_distributed_training_tutorials_tpu.launch.pod import (  # noqa: F401
    launch_pod,
    pod_run_command,
)
