"""Multi-process spawn launcher: the ``mp.spawn`` twin.

Twin of the reference's launcher (``ddp_gpus.py:104-105``): fork ``nprocs``
workers, inject the rank as the target's first argument, join, and surface
child failures. The TPU-native differences:

- each worker is a full jax.distributed *process* (one per host on a real
  pod); the worker body calls :func:`..parallel.distributed.init` itself —
  either explicitly (spawn contract) or from env (torchrun contract,
  ``env_contract=True`` here plays the torchrun agent and injects
  ``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``).
- ``platform="cpu"`` runs the world on CPU devices with gloo collectives —
  the hardware-free multi-process harness (SURVEY.md section 4's
  "multi-node testing without a cluster").
"""

from __future__ import annotations

import multiprocessing as mp
import os
import socket
from collections.abc import Callable, Sequence

DEFAULT_JOIN_TIMEOUT_S = 300.0


def pick_unused_port() -> int:
    """An OS-assigned free TCP port for the coordinator rendezvous."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _worker_env(
    rank: int,
    nprocs: int,
    coordinator: str,
    platform: str | None,
    env_contract: bool,
    devices_per_process: int,
) -> dict[str, str | None]:
    """Env delta for one child. ``None`` value = remove the variable."""
    env: dict[str, str | None] = {}
    if platform is not None:
        env["JAX_PLATFORMS"] = platform
        if platform == "cpu":
            # This build's sitecustomize registers a TPU backend whenever
            # PALLAS_AXON_POOL_IPS is set; a CPU world must not claim it.
            env["PALLAS_AXON_POOL_IPS"] = None
            flags = os.environ.get("XLA_FLAGS", "")
            flags = " ".join(
                f for f in flags.split() if "host_platform_device_count" not in f
            )
            env["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{devices_per_process}"
            ).strip()
    if env_contract:
        # Play the torchrun agent: rendezvous + env injection
        # (reference 02.ddp_toy_example.ipynb cells 11-12).
        env["JAX_COORDINATOR_ADDRESS"] = coordinator
        env["JAX_NUM_PROCESSES"] = str(nprocs)
        env["JAX_PROCESS_ID"] = str(rank)
    return env


def _bootstrap(env_delta: dict, target: Callable, rank: int, args: Sequence):
    """Child-process entry: apply the env delta *inside the child* (before
    jax import/init in ``target``), then run ``target(rank, *args)``.

    Keeping the delta out of the parent's ``os.environ`` means concurrent
    ``spawn()`` calls (or other parent threads reading env mid-launch) can
    never observe another rank's ``JAX_PROCESS_ID``/``JAX_PLATFORMS``.
    """
    for k, v in env_delta.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    if env_delta.get("JAX_PLATFORMS"):
        # This build's sitecustomize pre-imports jax._src at interpreter
        # startup — before this function runs — so the env var alone can be
        # captured too late; forward it through the config API as well
        # (backends are not initialized yet; same pattern as tests/conftest).
        import jax

        jax.config.update("jax_platforms", env_delta["JAX_PLATFORMS"])
    target(rank, *args)


def _run_world(
    target: Callable,
    nprocs: int,
    args: Sequence,
    coordinator: str,
    platform: str | None,
    env_contract: bool,
    devices_per_process: int,
    join_timeout_s: float,
) -> list[tuple[int, int | None]]:
    """Fork one world and monitor it. Returns ``[(rank, exitcode|None)]``
    failures (empty on success).

    Monitoring is a poll loop with **early gang abort**: the moment any rank
    exits non-zero, the surviving ranks — likely blocked in a collective
    waiting for the dead peer — are terminated instead of being left to hang
    until the join timeout. This is the failure-*detection* half of the
    torchrun elastic agent's contract (SURVEY.md section 5.3).
    """
    import time

    ctx = mp.get_context("spawn")
    procs: list[mp.Process] = []
    try:
        for rank in range(nprocs):
            # Each child's env delta rides the process args and is applied by
            # _bootstrap inside the child — the parent's env is never touched.
            delta = _worker_env(
                rank, nprocs, coordinator, platform, env_contract,
                devices_per_process,
            )
            p = ctx.Process(
                target=_bootstrap,
                args=(delta, target, rank, tuple(args)),
                name=f"spawn-rank{rank}",
            )
            p.start()
            procs.append(p)
    except BaseException:
        # A failed start() mid-loop would leave earlier ranks blocked at the
        # rendezvous forever (their world can never reach nprocs) — reap them.
        for p in procs:
            if p.is_alive():
                p.terminate()
            p.join(10)
        raise

    deadline = time.monotonic() + join_timeout_s
    failed: list[tuple[int, int | None]] = []
    while True:
        alive = [p for p in procs if p.is_alive()]
        failed = [
            (r, p.exitcode)
            for r, p in enumerate(procs)
            if not p.is_alive() and p.exitcode != 0
        ]
        if not alive or failed:
            break
        if time.monotonic() > deadline:
            failed = [(r, None) for r, p in enumerate(procs) if p.is_alive()]
            break
        time.sleep(0.1)
    # gang abort: reap survivors of a failed/timed-out world; escalate to
    # SIGKILL for workers stuck in native code ignoring SIGTERM — a restart
    # must never fork a new world while zombies still hold the devices
    if failed:
        for p in procs:
            if p.is_alive():
                p.terminate()
    for p in procs:
        p.join(10)
        if p.is_alive():
            p.kill()
            p.join(10)
    return failed


def _failure_detail(failed: list[tuple[int, int | None]]) -> str:
    return ", ".join(
        f"rank {r}: {'timeout' if c is None else f'exit {c}'}"
        for r, c in failed
    )


def spawn(
    target: Callable,
    nprocs: int,
    args: Sequence = (),
    *,
    coordinator: str | None = None,
    platform: str | None = None,
    env_contract: bool = False,
    devices_per_process: int = 1,
    join_timeout_s: float = DEFAULT_JOIN_TIMEOUT_S,
    max_restarts: int = 0,
) -> None:
    """Fork ``nprocs`` workers running ``target(rank, *args)``; join all.

    Twin of ``mp.spawn(main, args=..., nprocs=world_size)``
    (reference ``ddp_gpus.py:105``): the rank is injected as argument 0.
    ``target`` must be a module-level (picklable) callable; it is responsible
    for calling :func:`..parallel.distributed.init` — with explicit
    ``(coordinator, nprocs, rank)`` for the spawn contract, or bare ``init()``
    with ``env_contract=True`` for the torchrun contract.

    ``max_restarts`` > 0 is the torchrun elastic-agent behavior the reference
    delegates to its launcher (``/root/reference/ddp_gpus_torchrun.py:12-14``
    is written against an agent that rendezvous, monitors, and *restarts*
    workers): when any rank dies, the whole gang is torn down and re-forked —
    same semantics as torchrun, which always restarts the full world — up to
    ``max_restarts`` times, with a fresh rendezvous endpoint per attempt.
    Stateful targets resume from their latest checkpoint
    (:meth:`..train.trainer.Trainer.restore`), turning restart-from-scratch
    into restart-and-resume; proven end-to-end in
    ``tests/test_restart_resume.py``.

    Raises ``RuntimeError`` naming the failed ranks if the final attempt
    fails (the reference inherits this from mp.spawn's error propagation).
    """
    if nprocs < 1:
        raise ValueError(f"nprocs must be >= 1, got {nprocs}")
    if max_restarts < 0:
        raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
    if max_restarts > 0 and not env_contract and nprocs > 1:
        import warnings

        # Spawn-contract targets receive their rendezvous endpoint through
        # `args`, which the launcher cannot refresh between attempts — a
        # restart would rendezvous on the dead world's endpoint. The env
        # contract (launcher-injected JAX_COORDINATOR_ADDRESS) restarts
        # cleanly; that asymmetry is exactly torchrun's (elasticity lives in
        # the agent, not in mp.spawn).
        warnings.warn(
            "spawn(max_restarts>0) with the explicit-coordinator contract "
            "reuses the coordinator baked into `args` across restarts; use "
            "env_contract=True for restart-safe rendezvous",
            stacklevel=2,
        )
    for attempt in range(max_restarts + 1):
        # Fresh rendezvous port per attempt unless the caller pinned one (a
        # dead world's coordinator socket may linger in TIME_WAIT).
        att_coordinator = coordinator or f"localhost:{pick_unused_port()}"
        failed = _run_world(
            target, nprocs, args, att_coordinator, platform, env_contract,
            devices_per_process, join_timeout_s,
        )
        if not failed:
            return
        if attempt < max_restarts:
            print(
                f"spawn: world failed ({_failure_detail(failed)}); "
                f"restarting ({attempt + 1}/{max_restarts})"
            )
            continue
        raise RuntimeError(
            f"spawn: {len(failed)}/{nprocs} workers failed "
            f"({_failure_detail(failed)})"
        )


def coordinator_for_spawn(port: int | None = None) -> str:
    """The spawn contract's rendezvous endpoint (twin of the reference's
    hardcoded ``MASTER_ADDR=localhost, MASTER_PORT=12345``,
    ``ddp_gpus.py:13-14``) — but with an OS-assigned port by default, since
    a hardcoded port is exactly what makes the reference flaky to re-run."""
    return f"localhost:{port if port is not None else pick_unused_port()}"
