"""Multi-process spawn launcher: the ``mp.spawn`` twin.

Twin of the reference's launcher (``ddp_gpus.py:104-105``): fork ``nprocs``
workers, inject the rank as the target's first argument, join, and surface
child failures. The TPU-native differences:

- each worker is a full jax.distributed *process* (one per host on a real
  pod); the worker body calls :func:`..parallel.distributed.init` itself —
  either explicitly (spawn contract) or from env (torchrun contract,
  ``env_contract=True`` here plays the torchrun agent and injects
  ``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``).
- ``platform="cpu"`` runs the world on CPU devices with gloo collectives —
  the hardware-free multi-process harness (SURVEY.md section 4's
  "multi-node testing without a cluster").
"""

from __future__ import annotations

import multiprocessing as mp
import os
import socket
from collections.abc import Callable, Sequence

DEFAULT_JOIN_TIMEOUT_S = 300.0


def pick_unused_port() -> int:
    """An OS-assigned free TCP port for the coordinator rendezvous."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _worker_env(
    rank: int,
    nprocs: int,
    coordinator: str,
    platform: str | None,
    env_contract: bool,
    devices_per_process: int,
) -> dict[str, str | None]:
    """Env delta for one child. ``None`` value = remove the variable."""
    env: dict[str, str | None] = {}
    if platform is not None:
        env["JAX_PLATFORMS"] = platform
        if platform == "cpu":
            # This build's sitecustomize registers a TPU backend whenever
            # PALLAS_AXON_POOL_IPS is set; a CPU world must not claim it.
            env["PALLAS_AXON_POOL_IPS"] = None
            flags = os.environ.get("XLA_FLAGS", "")
            flags = " ".join(
                f for f in flags.split() if "host_platform_device_count" not in f
            )
            env["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{devices_per_process}"
            ).strip()
    if env_contract:
        # Play the torchrun agent: rendezvous + env injection
        # (reference 02.ddp_toy_example.ipynb cells 11-12).
        env["JAX_COORDINATOR_ADDRESS"] = coordinator
        env["JAX_NUM_PROCESSES"] = str(nprocs)
        env["JAX_PROCESS_ID"] = str(rank)
    return env


def _bootstrap(env_delta: dict, target: Callable, rank: int, args: Sequence):
    """Child-process entry: apply the env delta *inside the child* (before
    jax import/init in ``target``), then run ``target(rank, *args)``.

    Keeping the delta out of the parent's ``os.environ`` means concurrent
    ``spawn()`` calls (or other parent threads reading env mid-launch) can
    never observe another rank's ``JAX_PROCESS_ID``/``JAX_PLATFORMS``.
    """
    for k, v in env_delta.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    if env_delta.get("JAX_PLATFORMS"):
        # This build's sitecustomize pre-imports jax._src at interpreter
        # startup — before this function runs — so the env var alone can be
        # captured too late; forward it through the config API as well
        # (backends are not initialized yet; same pattern as tests/conftest).
        import jax

        jax.config.update("jax_platforms", env_delta["JAX_PLATFORMS"])
    target(rank, *args)


def spawn(
    target: Callable,
    nprocs: int,
    args: Sequence = (),
    *,
    coordinator: str | None = None,
    platform: str | None = None,
    env_contract: bool = False,
    devices_per_process: int = 1,
    join_timeout_s: float = DEFAULT_JOIN_TIMEOUT_S,
) -> None:
    """Fork ``nprocs`` workers running ``target(rank, *args)``; join all.

    Twin of ``mp.spawn(main, args=..., nprocs=world_size)``
    (reference ``ddp_gpus.py:105``): the rank is injected as argument 0.
    ``target`` must be a module-level (picklable) callable; it is responsible
    for calling :func:`..parallel.distributed.init` — with explicit
    ``(coordinator, nprocs, rank)`` for the spawn contract, or bare ``init()``
    with ``env_contract=True`` for the torchrun contract.

    Raises ``RuntimeError`` naming the failed ranks if any child exits
    non-zero (the reference inherits this from mp.spawn's error propagation).
    """
    if nprocs < 1:
        raise ValueError(f"nprocs must be >= 1, got {nprocs}")
    coordinator = coordinator or f"localhost:{pick_unused_port()}"
    ctx = mp.get_context("spawn")
    procs: list[mp.Process] = []
    try:
        for rank in range(nprocs):
            # Each child's env delta rides the process args and is applied by
            # _bootstrap inside the child — the parent's env is never touched.
            delta = _worker_env(
                rank, nprocs, coordinator, platform, env_contract,
                devices_per_process,
            )
            p = ctx.Process(
                target=_bootstrap,
                args=(delta, target, rank, tuple(args)),
                name=f"spawn-rank{rank}",
            )
            p.start()
            procs.append(p)
    except BaseException:
        # A failed start() mid-loop would leave earlier ranks blocked at the
        # rendezvous forever (their world can never reach nprocs) — reap them.
        for p in procs:
            if p.is_alive():
                p.terminate()
            p.join(10)
        raise

    failed: list[tuple[int, int | None]] = []
    for rank, p in enumerate(procs):
        p.join(join_timeout_s)
        if p.is_alive():
            p.terminate()
            p.join(10)
            failed.append((rank, None))
        elif p.exitcode != 0:
            failed.append((rank, p.exitcode))
    if failed:
        detail = ", ".join(
            f"rank {r}: {'timeout' if c is None else f'exit {c}'}"
            for r, c in failed
        )
        raise RuntimeError(f"spawn: {len(failed)}/{nprocs} workers failed ({detail})")


def coordinator_for_spawn(port: int | None = None) -> str:
    """The spawn contract's rendezvous endpoint (twin of the reference's
    hardcoded ``MASTER_ADDR=localhost, MASTER_PORT=12345``,
    ``ddp_gpus.py:13-14``) — but with an OS-assigned port by default, since
    a hardcoded port is exactly what makes the reference flaky to re-run."""
    return f"localhost:{port if port is not None else pick_unused_port()}"
