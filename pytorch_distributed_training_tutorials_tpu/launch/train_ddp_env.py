"""Env-contract DDP training CLI: twin of reference ``ddp_gpus_torchrun.py``.

The torchrun lesson (SURVEY.md C10, reference ``ddp_gpus_torchrun.py:92-99``):
the script owns *no* topology — an external agent does rendezvous and injects
it via environment. Here the contract is ``JAX_COORDINATOR_ADDRESS`` /
``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID`` (read by
:func:`..parallel.distributed.init`), or nothing at all on a real TPU pod,
where ``jax.distributed.initialize`` autodetects topology from the runtime
metadata — the pod *is* the elastic agent. Run the same command on every
host::

    # single host (the bare-`torchrun` demo, Steps 64):
    python -m pytorch_distributed_training_tutorials_tpu.launch.train_ddp_env

    # N-process world, driven entirely by env (the --nproc-per-node demo):
    JAX_COORDINATOR_ADDRESS=host0:12355 JAX_NUM_PROCESSES=4 JAX_PROCESS_ID=$i \
        python -m pytorch_distributed_training_tutorials_tpu.launch.train_ddp_env
"""

from __future__ import annotations

import optax

from pytorch_distributed_training_tutorials_tpu.data import (
    ShardedLoader,
    synthetic_regression,
)
from pytorch_distributed_training_tutorials_tpu.launch.train_ddp import (
    DATASET_SIZE,
    LEARNING_RATE,
    build_parser,
)
from pytorch_distributed_training_tutorials_tpu.models import LinearRegressor
from pytorch_distributed_training_tutorials_tpu.parallel import distributed
from pytorch_distributed_training_tutorials_tpu.parallel.mesh import create_mesh
from pytorch_distributed_training_tutorials_tpu.train import Trainer


def main(max_epochs: int, batch_size: int, loss: str = "mse") -> None:
    """Twin of reference ``main(max_epochs, batch_size)``
    (``ddp_gpus_torchrun.py:65-88``): no rank/world arguments anywhere —
    topology is discovered, not passed."""
    distributed.init()  # env-driven / autodetect (the torchrun seam)
    mesh = create_mesh()
    dataset = synthetic_regression(DATASET_SIZE)
    loader = ShardedLoader(dataset, batch_size, mesh)
    trainer = Trainer(
        LinearRegressor(), loader, optax.sgd(LEARNING_RATE), loss=loss
    )
    trainer.train(max_epochs)
    distributed.shutdown()


def env_worker(rank: int, max_epochs: int, batch_size: int) -> None:
    """Spawn-compatible wrapper for tests: the launcher plays the torchrun
    agent (env injection); the worker body never sees its rank — it calls
    the rank-free :func:`main`, proving the env contract end to end."""
    del rank  # discovered from env inside main(), by design
    main(max_epochs, batch_size)


if __name__ == "__main__":
    # no launch flags: topology is owned by the environment, by design
    args = build_parser(launch_flags=False).parse_args()
    main(args.max_epochs, args.batch_size, loss=args.loss)
