"""Fused paged decode attention: walk the page table, never gather the window.

The gather path (``models/transformer.py`` paged decode branch) reads the
shared ``(pool_pages, page_size, kv_heads, head_dim)`` K/V pools by
materializing each row's whole logical window — ``jnp.take(pool, table,
axis=0)`` into a dense ``(B, W, kv, d)`` temporary — and then runs dense
attention over it. At the HBM roofline that temporary is pure wall time:
full-window KV traffic plus a full-window buffer, every decode step,
regardless of how deep each slot actually is.

This module is the vLLM PagedAttention design (SOSP '23 — the same paper
``serve/pages.py`` cites for the pool) fused the FlashAttention way
(:mod:`.flash_attention` is the house online-softmax template): a Pallas
kernel whose grid walks ``(batch row, kv head, logical page)`` with the
page table and per-row ``cache_index`` as **scalar-prefetch** operands, so
the K/V ``BlockSpec`` index_maps translate logical page -> physical pool
page per grid step and the kernel only ever touches one ``(page_size, d)``
tile at a time. Softmax runs as the streaming (m, l, acc) recurrence
across pages; no dense window exists at any point — the compiled HLO for
a kernel-path decode contains no ``(B, W, ...)`` gathered temporary
(tests/test_serve.py pins the shape sweep, fused_loss-style).

Numerics contract: :func:`paged_attention` matches
:func:`paged_attention_reference` — a pure-jnp restatement of the gather
path's exact math (same f32 score/context accumulation, same validity
rule, ``mode="fill"`` zeros for sentinel pages) — to float tolerance, and
greedy decode through the kernel is token-exact to the gather path
(tests/test_paged_attention.py, tests/test_serve.py). Quantized pools
dequantize **inside** the kernel per page tile (int8 x f32 scales, or
packed int4 nibbles x bf16 scales — :func:`..ops.quant.unpack_int4` is
the reference for the nibble math), so quantized decode traffic stays at
the packed footprint.

Sentinel semantics: the reference gather fills sentinel-backed positions
with 0.0 **rows** and lets the validity mask exclude them; the kernel
skips sentinel pages wholesale (``pl.when``). The two agree everywhere
the engine invariant holds — sentinel pages only back positions beyond a
row's valid length (a parked row, all-sentinel, yields l == 0 and a
discarded zero output). ``quant`` / geometry are ENGINE-STATIC Python
values (the kernel-vs-gather choice itself is ``cfg.paged_kernel``, a
config bool — never a traced value; graftcheck ``traced-control-flow``
has the fixture pair).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pytorch_distributed_training_tutorials_tpu.ops.quant import (
    dequantize_kv_int4,
    unpack_int4,
)

NEG_INF = float("-inf")  # plain float: no jax arrays at import time

_QUANT_MODES = (None, "int8", "int4")


def paged_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    table: jax.Array,
    pos: jax.Array,
    *,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
    quant: str | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Paged decode attention straight off the page pools.

    ``q``: (B, S, H, D) queries (already rope'd; S >= 1 covers the
    chunked-continuation decode). ``k_pool``/``v_pool``: (N_pages,
    page_size, KV, D) shared pools — (.., D // 2) packed uint8 when
    ``quant == "int4"``. ``table``: (B, P) int32 page table (sentinel =
    N_pages, out of range). ``pos``: (B,) int32 per-row cache depth
    (query row s sits at global position ``pos + s``; positions
    ``t <= pos + s`` are attended — the gather path's validity rule).
    ``k_scale``/``v_scale``: (N_pages, page_size, KV) per-token-per-head
    scales, required iff ``quant`` is "int8" (f32) or "int4" (bf16).

    ``quant`` and every shape are engine-static; ``table``/``pos`` are
    traced data and reach the kernel as scalar-prefetch operands (their
    values steer BlockSpec index_maps, never Python control flow).
    ``interpret=None`` auto-selects interpreter mode off-TPU, like every
    kernel in ops/. Returns (B, S, H, D) in ``q.dtype``.

    Real-TPU tiling note: ``D`` (lane) wants a multiple of 128 and
    ``page_size`` (sublane) a multiple of 8 for native Mosaic tiles —
    the serving presets satisfy both; other geometries pad.
    """
    if quant not in _QUANT_MODES:
        raise ValueError(f"quant must be one of {_QUANT_MODES}, got {quant!r}")
    if (quant is not None) != (k_scale is not None and v_scale is not None):
        raise ValueError(
            "k_scale/v_scale are required exactly when quant is set"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, s, h, d = q.shape
    n_pages, page_size, kv = k_pool.shape[0], k_pool.shape[1], k_pool.shape[2]
    if h % kv:
        raise ValueError(f"n_heads {h} must be a multiple of kv_heads {kv}")
    grp = h // kv
    p_cap = table.shape[1]
    d_store = d // 2 if quant == "int4" else d
    if k_pool.shape[3] != d_store:
        raise ValueError(
            f"pool head_dim {k_pool.shape[3]} != expected {d_store} "
            f"(quant={quant!r}, q head_dim {d})"
        )
    sg = s * grp
    # compute dtypes mirror the gather path: quantized pools dequantize to
    # the query compute dtype; full-precision scores promote q x storage
    kv_dtype = q.dtype if quant else k_pool.dtype
    score_dtype = jnp.promote_types(q.dtype, kv_dtype)
    sm_scale = 1.0 / (d**0.5)

    def kernel(tbl_ref, pos_ref, q_ref, k_ref, v_ref, *rest):
        if quant:
            ks_ref, vs_ref, o_ref, acc, m, l = rest
        else:
            o_ref, acc, m, l = rest
        bb = pl.program_id(0)
        p = pl.program_id(2)

        @pl.when(p == 0)
        def _init():
            acc[:] = jnp.zeros_like(acc)
            m[:] = jnp.full_like(m, NEG_INF)
            l[:] = jnp.zeros_like(l)

        pid = tbl_ref[bb, p]
        depth = pos_ref[bb]
        # whole-page skip: sentinel/unbacked pages and pages entirely past
        # the deepest query position contribute exact zeros either way
        # (exp(-inf - shift) == 0.0), so skipping them is free AND exact
        live = jnp.logical_and(pid < n_pages, p * page_size <= depth + (s - 1))

        @pl.when(live)
        def _page():
            if quant == "int4":
                kb = (
                    unpack_int4(k_ref[0, :, 0, :]).astype(jnp.float32)
                    * ks_ref[0, :, 0].astype(jnp.float32)[:, None]
                ).astype(kv_dtype)
                vb = (
                    unpack_int4(v_ref[0, :, 0, :]).astype(jnp.float32)
                    * vs_ref[0, :, 0].astype(jnp.float32)[:, None]
                ).astype(kv_dtype)
            elif quant == "int8":
                kb = (
                    k_ref[0, :, 0, :].astype(jnp.float32)
                    * ks_ref[0, :, 0][:, None]
                ).astype(kv_dtype)
                vb = (
                    v_ref[0, :, 0, :].astype(jnp.float32)
                    * vs_ref[0, :, 0][:, None]
                ).astype(kv_dtype)
            else:
                kb = k_ref[0, :, 0, :]
                vb = v_ref[0, :, 0, :]
            qb = q_ref[0].reshape(sg, d)
            scores = jax.lax.dot_general(
                qb.astype(score_dtype),
                kb.astype(score_dtype),
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * sm_scale
            # validity: global position t attends iff t <= pos + s_row
            # (row r of the (sg, page_size) tile is query s_row = r // grp)
            srow = jax.lax.broadcasted_iota(jnp.int32, (sg, page_size), 0)
            t = p * page_size + jax.lax.broadcasted_iota(
                jnp.int32, (sg, page_size), 1
            )
            scores = jnp.where(t <= depth + srow // grp, scores, NEG_INF)
            m_prev = m[:, :1]
            m_new = jnp.maximum(m_prev, scores.max(axis=-1, keepdims=True))
            shift = jnp.where(m_new == NEG_INF, 0.0, m_new)
            pexp = jnp.exp(scores - shift)
            corr = jnp.exp(m_prev - shift)
            l[:, :1] = l[:, :1] * corr + pexp.sum(axis=-1, keepdims=True)
            acc[:] = acc[:] * corr + jax.lax.dot(
                pexp.astype(vb.dtype), vb, preferred_element_type=jnp.float32
            )
            m[:, :1] = m_new

        @pl.when(p == pl.num_programs(2) - 1)
        def _flush():
            lv = l[:, :1]
            safe = jnp.where(lv == 0.0, 1.0, lv)  # all-parked row -> 0 out
            o_ref[0] = (acc[:] / safe).reshape(s, grp, d).astype(o_ref.dtype)

    # index_maps read the prefetched table: logical page p of row b lives
    # at pool page table[b, p] — sentinels clamp in-range for the FETCH
    # (the block must exist) and the kernel's `live` predicate masks them
    def _pool_map(bb, hh, p, tbl, _pos):
        return (jnp.minimum(tbl[bb, p], n_pages - 1), 0, hh, 0)

    def _pool_scale_map(bb, hh, p, tbl, _pos):
        return (jnp.minimum(tbl[bb, p], n_pages - 1), 0, hh)

    def _q_map(bb, hh, p, tbl, _pos):
        return (bb, 0, hh, 0)

    in_specs = [
        pl.BlockSpec((1, s, grp, d), _q_map),
        pl.BlockSpec((1, page_size, 1, d_store), _pool_map),
        pl.BlockSpec((1, page_size, 1, d_store), _pool_map),
    ]
    operands = [q, k_pool, v_pool]
    if quant:
        in_specs += [
            pl.BlockSpec((1, page_size, 1), _pool_scale_map),
            pl.BlockSpec((1, page_size, 1), _pool_scale_map),
        ]
        operands += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, kv, p_cap),  # pages innermost: the online-softmax carry
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, s, grp, d), _q_map),
        scratch_shapes=[
            pltpu.VMEM((sg, d), jnp.float32),
            pltpu.VMEM((sg, 128), jnp.float32),
            pltpu.VMEM((sg, 128), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, s, h, d), q.dtype),
        interpret=interpret,
    )(table, pos, *operands)


def paged_attention_reference(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    table: jax.Array,
    pos: jax.Array,
    *,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
    quant: str | None = None,
) -> jax.Array:
    """Pure-jnp statement of the gather path's math — the oracle the
    kernel pins against, self-contained so tests need no model: gather
    whole pages dense (``jnp.take`` ``mode="fill"`` zeros for sentinels),
    dequantize, then the grouped masked attention of
    ``models.transformer`` (f32 score/softmax/context accumulation,
    validity ``t <= pos + s``)."""
    if quant not in _QUANT_MODES:
        raise ValueError(f"quant must be one of {_QUANT_MODES}, got {quant!r}")
    b, s, h, d = q.shape
    page_size, kv = k_pool.shape[1], k_pool.shape[2]
    w = table.shape[1] * page_size

    def gather(pool):
        out = jnp.take(pool, table, axis=0, mode="fill", fill_value=0)
        return out.reshape((b, w) + pool.shape[2:])

    if quant == "int8":
        k = (
            gather(k_pool).astype(jnp.float32)
            * gather(k_scale)[..., None]
        ).astype(q.dtype)
        v = (
            gather(v_pool).astype(jnp.float32)
            * gather(v_scale)[..., None]
        ).astype(q.dtype)
    elif quant == "int4":
        k = dequantize_kv_int4(gather(k_pool), gather(k_scale), q.dtype)
        v = dequantize_kv_int4(gather(v_pool), gather(v_scale), q.dtype)
    else:
        k, v = gather(k_pool), gather(v_pool)

    qpos = pos[:, None] + jnp.arange(s)
    valid = jnp.arange(w) <= qpos[..., :, None]  # (B, S, W)
    grp = h // kv
    q5 = q.reshape(b, s, kv, grp, d)
    scores = jnp.einsum(
        "bqcgd,blcd->bcgql", q5, k, preferred_element_type=jnp.float32
    ) / jnp.sqrt(jnp.float32(d))
    scores = jnp.where(
        valid[:, None, :, :][:, :, None], scores, jnp.float32(-1e30)
    )
    weights = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum(
        "bcgql,blcd->bqcgd", weights, v, preferred_element_type=jnp.float32
    )
    return out.astype(q.dtype).reshape(b, s, h, d)
