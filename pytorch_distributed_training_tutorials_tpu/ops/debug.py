"""Sharding observability: the tutorials' shape-print lessons, TPU-style.

Lesson 01 proves the DataParallel scatter by printing ``Input shape: [8, 32]``
from each of 4 replicas (reference ``01.data_parallel.ipynb`` cells 9/16).
The SPMD twin: inspect the per-shard block of a sharded ``jax.Array``.
"""

from __future__ import annotations

import jax


def per_shard_shapes(x: jax.Array) -> list[tuple]:
    """Shapes of each addressable shard of ``x``.

    For a batch of 32 sharded over 4 devices this returns four ``(8, ...)``
    entries — the observable twin of lesson 01's ``Input shape: [8, 32]``
    prints (reference ``01.data_parallel.ipynb`` cell 16 stream output).
    """
    return [s.data.shape for s in x.addressable_shards]


def describe_sharding(x: jax.Array) -> str:
    """One-line device/shape audit of an array, like 03's param audit
    (reference ``03.model_parallel.ipynb`` cell 4)."""
    shards = ", ".join(
        f"{s.device}:{s.data.shape}" for s in x.addressable_shards
    )
    return f"global {x.shape} {x.dtype} -> [{shards}]"
