"""Int8 quantization + Pallas int8 matmul: the bitsandbytes twin.

Reference capability (SURVEY.md C13): ``from_pretrained(...,
BitsAndBytesConfig(load_in_8bit=True))`` loads Llama-7B with int8 matmul
weights and float16 norms (``03.model_parallel.ipynb`` cell 2; param audit
cell 4). The TPU-native equivalent implemented here:

- :func:`quantize_int8` — per-channel symmetric weight quantization
  (absmax / 127, the bitsandbytes vector-wise scheme) into an
  :class:`Int8Param` pytree leaf.
- :func:`int8_matmul` — a Pallas TPU kernel computing
  ``x @ dequant(q, scale)`` the LLM.int8 way: activations are quantized
  per-row *inside* the kernel, the MXU runs a true int8 x int8 -> int32
  matmul, and the int32 accumulator is dequantized by the outer product of
  row and column scales. HBM traffic for the weight is 1/4 of f32 — the
  point of 8-bit serving. Runs in interpreter mode off-TPU so tests are
  hardware-free (and cross-checked against the pure-jnp reference math).
- :class:`Int8Dense` — drop-in serving twin of ``nn.Dense`` over an
  :class:`Int8Param` (+f32 bias), for checkpoint-quantized models (see
  :func:`..parallel.auto.load_quantized`, the ``load_in_8bit`` seam).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from flax import linen as nn
from flax import struct
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from pytorch_distributed_training_tutorials_tpu.utils.compat import (
    shard_map_nocheck,
)


class Int8Param(struct.PyTreeNode):
    """Per-channel symmetric int8 weight: ``w ~= q * scale``.

    ``q``: int8, same shape as the original weight. ``scale``: float32,
    shape broadcastable to ``q`` (1 everywhere except the channel axis).
    """

    q: jax.Array
    scale: jax.Array

    @property
    def shape(self):
        return self.q.shape

    def dequantize(self) -> jax.Array:
        return self.q.astype(jnp.float32) * self.scale


def quantize_int8(w: jax.Array, channel_axis: int = -1) -> Int8Param:
    """absmax/127 per-channel symmetric quantization (the bitsandbytes
    vector-wise scheme). ``channel_axis`` is the output-feature axis that
    keeps its own scale (-1 for a Dense kernel (in, out))."""
    w = jnp.asarray(w, jnp.float32)
    reduce_axes = tuple(
        a for a in range(w.ndim) if a != channel_axis % w.ndim
    )
    absmax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return Int8Param(q=q, scale=scale)


def pack_int4(q: jax.Array) -> jax.Array:
    """Pack int4 values (any int dtype, range [-7, 7]) two-per-byte along
    the last axis: uint8 byte ``j`` holds element ``j`` in the low nibble
    and element ``j + D/2`` in the high nibble (the HALF-SPLIT layout —
    unpacking is one mask, one shift, and a concatenate, with no
    elementwise interleave for Mosaic to scalarize; the same front/back
    split :func:`..models.transformer.apply_rope` uses). Last axis must be
    even; output shape ``(..., D // 2)``.

    Reference capability (SURVEY.md C13 lineage): the 4-bit half of the
    bitsandbytes load_in_*bit family (``/root/reference/
    03.model_parallel.ipynb`` cell 2 loads the 8-bit variant; int4 is the
    same absmax scheme at half the bits). Inverse: :func:`unpack_int4`.
    """
    d = q.shape[-1]
    if d % 2:
        raise ValueError(f"pack_int4 needs an even last axis, got {d}")
    u = q.astype(jnp.uint8) & 0xF  # two's-complement nibble
    lo, hi = u[..., : d // 2], u[..., d // 2 :]
    return lo | (hi << 4)


def unpack_int4(packed: jax.Array) -> jax.Array:
    """Inverse of :func:`pack_int4`: uint8 ``(..., D/2)`` -> int8
    ``(..., D)``. Each nibble sign-extends through the two's-complement
    rule ``n >= 8 -> n - 16`` (branch-free ``jnp.where`` — values are
    traced data)."""
    lo = (packed & 0xF).astype(jnp.int8)
    hi = ((packed >> 4) & 0xF).astype(jnp.int8)
    ext = lambda n: jnp.where(n >= 8, n - 16, n)  # noqa: E731
    return jnp.concatenate([ext(lo), ext(hi)], axis=-1)


def quantize_kv_int4(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """int4 twin of ``models.transformer._quantize_kv``: quantize K/V
    ``(..., D)`` to packed int4 (two nibbles per byte, :func:`pack_int4`)
    with per-token-per-head scales (absmax over the head_dim vector /
    7 — the symmetric absmax scheme of :func:`quantize_int8` at 4 bits).

    Scales are stored **bfloat16**, not f32: that makes an int4 cache
    entry cost exactly half its int8 twin per token-head (``D/2 + 2``
    bytes vs ``D + 4``) — the "2x pages at equal HBM" claim is exact, not
    approximate. Quantization divides by the ROUNDED bf16 scale so
    dequantization with the stored scale is exact (no f32-vs-bf16 scale
    mismatch); bf16's 8 mantissa bits are noise next to the ~1/15
    relative step of 4-bit values. Inverse: :func:`dequantize_kv_int4`.
    """
    x32 = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x32), axis=-1)
    scale = (jnp.maximum(absmax, 1e-8) / 7.0).astype(jnp.bfloat16)
    q = jnp.clip(
        jnp.round(x32 / scale.astype(jnp.float32)[..., None]), -7, 7
    ).astype(jnp.int8)
    return pack_int4(q), scale


def dequantize_kv_int4(packed: jax.Array, scale: jax.Array, dtype) -> jax.Array:
    """Packed int4 cache + bf16 scales -> compute dtype (the
    ``_dequantize_kv`` twin). The unpack + multiply is elementwise, so XLA
    fuses it into the attention matmuls' operand reads on the gather
    path; the Pallas kernel (:mod:`.paged_attention`) runs the same
    nibble math per page tile in VMEM — this function is its numerics
    reference."""
    q = unpack_int4(packed)
    return (
        q.astype(jnp.float32) * scale.astype(jnp.float32)[..., None]
    ).astype(dtype)


def _int8_matmul_kernel(x_ref, q_ref, sw_ref, out_ref, acc_ref, *, n_k: int):
    """One (TM, TN, TK) tile: quantize the x tile per row, int8 MXU matmul,
    accumulate the dequantized partial in f32 VMEM scratch; write out on the
    last K tile."""
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[:].astype(jnp.float32)  # (TM, TK)
    absmax = jnp.max(jnp.abs(x), axis=1, keepdims=True)  # (TM, 1)
    sx = jnp.maximum(absmax, 1e-8) / 127.0
    xq = jnp.clip(jnp.round(x / sx), -127, 127).astype(jnp.int8)
    part = jnp.dot(
        xq, q_ref[:], preferred_element_type=jnp.int32
    )  # int8 x int8 -> int32 on the MXU
    acc_ref[:] += part.astype(jnp.float32) * sx

    @pl.when(kk == n_k - 1)
    def _flush():
        out_ref[:] = acc_ref[:] * sw_ref[:]


def int8_matmul(
    x: jax.Array,
    w: Int8Param,
    *,
    block_m: int = 256,
    block_n: int = 256,
    block_k: int = 512,
    interpret: bool | None = None,
) -> jax.Array:
    """``x @ (q * scale)`` with dynamic per-(row, K-tile) int8 activation
    quantization.

    ``x``: (M, K) float; ``w.q``: (K, N) int8 with per-column ``w.scale``.
    The contraction is **K-blocked**: each (TM, TN) output tile accumulates
    over K in ``block_k`` slabs through an f32 VMEM scratch accumulator, so
    VMEM residency is ``O(TM*TK + TK*TN + TM*TN)`` regardless of K —
    Llama-7B widths (K=4096, N=11008 and the transpose) fit comfortably
    where the old whole-K layout overflowed the ~16 MB VMEM budget.

    Activations quantize per (row, K-tile) rather than per full row — a
    strictly finer-grained scheme than LLM.int8's vector-wise scaling (each
    tile gets its own absmax), matched exactly by
    :func:`int8_matmul_reference` with the same ``block_k``.

    All three dims are padded to tile multiples internally (zero rows/cols
    contribute nothing and are sliced away), so any M, K, N works.
    ``interpret=None`` auto-selects interpreter mode off-TPU so the same
    code path tests on CPU.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    m, k = x.shape
    kq, n = w.q.shape
    assert k == kq, (x.shape, w.q.shape)
    if tuple(w.scale.shape) not in ((1, n), (n,)):
        raise ValueError(
            f"int8_matmul needs per-output-column scales of size {n} "
            f"(quantize with channel_axis=-1); got scale shape "
            f"{tuple(w.scale.shape)}"
        )
    scale_row = w.scale.reshape(1, n).astype(jnp.float32)

    # sublane alignment: f32 blocks need second-to-last dim % 8 == 0 on real
    # TPU (interpret mode would hide a violation); K tiles stay % 128 (lane
    # dim of x, sublane-int8 dim of q)
    block_m = min(block_m, max(8, m))
    block_m = -(-block_m // 8) * 8
    # N is the lane dim of the output/q blocks: round up to 128 like K (an
    # odd-vocab lm_head must not hand the real-TPU kernel a sub-lane tile;
    # pad_n below absorbs the rounding)
    block_n = min(block_n, max(128, n))
    block_n = -(-block_n // 128) * 128
    block_k = min(block_k, max(128, k))
    block_k = -(-block_k // 128) * 128
    pad_m = (-m) % block_m
    pad_n = (-n) % block_n
    pad_k = (-k) % block_k
    if pad_m or pad_k:
        x = jnp.pad(x, ((0, pad_m), (0, pad_k)))
    q = w.q
    if pad_n or pad_k:
        q = jnp.pad(q, ((0, pad_k), (0, pad_n)))
    if pad_n:
        scale_row = jnp.pad(
            scale_row, ((0, 0), (0, pad_n)), constant_values=1.0
        )
    mp, np_, kp = m + pad_m, n + pad_n, k + pad_k
    n_k = kp // block_k

    out = pl.pallas_call(
        functools.partial(_int8_matmul_kernel, n_k=n_k),
        grid=(mp // block_m, np_ // block_n, n_k),
        in_specs=[
            pl.BlockSpec(
                (block_m, block_k),
                lambda i, j, kk: (i, kk),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (block_k, block_n),
                lambda i, j, kk: (kk, j),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, block_n), lambda i, j, kk: (0, j), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (block_m, block_n), lambda i, j, kk: (i, j), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.float32),
        scratch_shapes=[pltpu.VMEM((block_m, block_n), jnp.float32)],
        interpret=interpret,
    )(x.astype(jnp.float32), q, scale_row)
    return out[:m, :n] if (pad_m or pad_n) else out


def int8_matmul_tp(
    x: jax.Array,
    w: Int8Param,
    mesh: Mesh,
    *,
    kind: str,
    axis: str = "model",
    data_axis: str = "data",
) -> jax.Array:
    """Tensor-parallel ``x @ (q * scale)``: the Pallas kernel under an
    explicit :func:`jax.shard_map` (a ``pallas_call`` is a single-device
    program — GSPMD cannot partition it, so the Megatron split is stated
    here rather than propagated).

    The int8 twin of the float TP layout
    (:data:`..models.transformer.TP_RULES`):

    - ``kind="column"``: ``q`` (K, N) and per-column ``scale`` split over
      ``axis`` on N; every device runs the full-K kernel on its column
      shard. Activation quantization sees the same (row, K-tile) groups as
      the unsharded kernel — numerics are identical.
    - ``kind="row"``: ``q`` split over ``axis`` on K, ``scale`` replicated;
      each device multiplies its K-shard (activations arrive feature-
      sharded from the previous column layer) and a ``psum`` over ``axis``
      sums the partials — the one allreduce per residual branch. Activation
      quantization groups are per (row, *local* K-tile), a regrouping of
      the unsharded kernel's tiles: same error scale, bit-different values
      (``tests/test_quant.py`` pins the sharded math exactly against a
      per-shard reference composition).

    ``x``: (M, K) with rows optionally sharded over ``data_axis`` (M must
    then divide by it). Requires N (column) / K (row) divisible by the
    ``axis`` size. Serving-only, like the kernel itself.
    """
    if axis not in mesh.shape:
        raise ValueError(f"mesh has no {axis!r} axis: {dict(mesh.shape)}")
    n_shards = mesh.shape[axis]
    m, k = x.shape
    _, n = w.q.shape
    scale_row = w.scale.reshape(1, n).astype(jnp.float32)
    # shard rows over the data axis only when they divide it — a decode
    # step's M is batch*1 and need not match the mesh (replicated rows are
    # correct, just unsharded work)
    dspec = (
        data_axis
        if data_axis in mesh.shape and m % mesh.shape[data_axis] == 0
        else None
    )

    if kind == "column":
        if n % n_shards:
            raise ValueError(f"column split needs N ({n}) % {n_shards} == 0")
        in_specs = (P(dspec, None), P(None, axis), P(None, axis))
        out_specs = P(dspec, axis)

        def f(xl, ql, sl):
            return int8_matmul(xl, Int8Param(q=ql, scale=sl))

    elif kind == "row":
        if k % n_shards:
            raise ValueError(f"row split needs K ({k}) % {n_shards} == 0")
        in_specs = (P(dspec, axis), P(axis, None), P(None, None))
        out_specs = P(dspec, None)

        def f(xl, ql, sl):
            part = int8_matmul(xl, Int8Param(q=ql, scale=sl))
            return jax.lax.psum(part, axis)

    else:
        raise ValueError(f"kind must be 'column' or 'row', got {kind!r}")

    # checking off: pallas_call outputs carry no replication/varying-axes
    # info for shard_map's static checker (check_rep/check_vma by jax
    # version — utils.compat owns the drift)
    return shard_map_nocheck(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
    )(x, w.q, scale_row)


def int8_matmul_reference(
    x: jax.Array, w: Int8Param, *, block_k: int = 512
) -> jax.Array:
    """Pure-jnp statement of the kernel's math (for tests and off-TPU use):
    per-(row, K-tile) activation quantization with the same ``block_k``
    tiling as :func:`int8_matmul`, f32 accumulation across tiles."""
    x = jnp.asarray(x, jnp.float32)
    m, k = x.shape
    block_k = min(block_k, max(128, k))
    block_k = -(-block_k // 128) * 128
    pad_k = (-k) % block_k
    if pad_k:
        x = jnp.pad(x, ((0, 0), (0, pad_k)))
    q = jnp.pad(w.q, ((0, pad_k), (0, 0))) if pad_k else w.q
    acc = jnp.zeros((m, q.shape[1]), jnp.float32)
    for lo in range(0, k + pad_k, block_k):
        xt = x[:, lo : lo + block_k]
        absmax = jnp.max(jnp.abs(xt), axis=1, keepdims=True)
        sx = jnp.maximum(absmax, 1e-8) / 127.0
        xq = jnp.clip(jnp.round(xt / sx), -127, 127).astype(jnp.int8)
        part = jnp.dot(
            xq, q[lo : lo + block_k], preferred_element_type=jnp.int32
        )
        acc = acc + part.astype(jnp.float32) * sx
    return acc * w.scale.reshape(1, -1)


def _int8_affine(mod: nn.Module, x, feats: tuple, n_in: int, use_bias: bool):
    """The shared body of the int8 serving layers: flattened 2-D ``q`` +
    per-column ``scale`` params, the K-blocked MXU matmul, reshape, bias —
    one copy for Int8Dense and Int8DenseGeneral. With ``mod.mesh`` +
    ``mod.shard_kind`` set (and the axis really in the mesh), the matmul
    runs tensor-parallel through :func:`int8_matmul_tp`."""
    in_dims = x.shape[x.ndim - n_in :]
    k = 1
    for d in in_dims:
        k *= d
    n_out = 1
    for f in feats:
        n_out *= f
    q = mod.param("q", nn.initializers.zeros, (k, n_out), jnp.int8)
    scale = mod.param(
        "scale", nn.initializers.ones, (1, n_out), jnp.float32
    )
    lead = x.shape[: x.ndim - n_in]
    w = Int8Param(q=q, scale=scale)
    x2 = x.reshape(-1, k)
    mesh = getattr(mod, "mesh", None)
    if (
        mesh is not None
        and mod.shard_kind is not None
        and mesh.shape.get(mod.shard_axis, 1) > 1
    ):
        out2 = int8_matmul_tp(
            x2, w, mesh, kind=mod.shard_kind, axis=mod.shard_axis
        )
    else:
        out2 = int8_matmul(x2, w)
    out = out2.reshape(*lead, *feats)
    if use_bias:
        out = out + mod.param(
            "bias", nn.initializers.zeros, feats, jnp.float32
        )
    return out.astype(x.dtype)


class Int8Dense(nn.Module):
    """Serving twin of ``nn.Dense`` over int8 weights.

    Parameters are ``q`` (int8 kernel), ``scale`` (per-output-column), and
    optionally ``bias`` — the tree produced by quantizing a trained Dense
    kernel (:func:`quantize_int8` / :func:`..parallel.auto.load_quantized`).
    Zero-initialized when built fresh: this module is for loading quantized
    checkpoints, not training (int8 has no useful gradient).

    ``mesh`` + ``shard_kind`` ('column' | 'row') switch the matmul to the
    tensor-parallel :func:`int8_matmul_tp`; param shardings come from
    :data:`..models.transformer.INT8_TP_RULES`.
    """

    features: int
    use_bias: bool = True
    mesh: Mesh | None = None
    shard_kind: str | None = None
    shard_axis: str = "model"

    @nn.compact
    def __call__(self, x):
        return _int8_affine(
            self, x, (self.features,), 1, self.use_bias
        )


class Int8DenseGeneral(nn.Module):
    """Serving twin of ``nn.DenseGeneral`` over int8 weights.

    Supports the two transformer shapes: ``axis=-1`` with tuple features
    (the q/k/v projections, ``d_model -> (H, D)``) and ``axis=(-2, -1)``
    (the o projection, ``(H, D) -> d_model``). The kernel is stored
    flattened 2-D (``q``: (in, prod(features)) int8 + per-column scales) so
    the K-blocked MXU kernel serves every case.
    """

    features: int | tuple[int, ...]
    axis: int | tuple[int, ...] = -1
    use_bias: bool = False
    mesh: Mesh | None = None
    shard_kind: str | None = None
    shard_axis: str = "model"

    @nn.compact
    def __call__(self, x):
        feats = (
            self.features
            if isinstance(self.features, tuple)
            else (self.features,)
        )
        axes = self.axis if isinstance(self.axis, tuple) else (self.axis,)
        return _int8_affine(self, x, feats, len(axes), self.use_bias)
