"""Ops: Pallas TPU kernels and debug/observability helpers.

The reference has no custom kernels (SURVEY.md section 2: zero native
components) — its hot ops are vendored cuDNN/cuBLAS. Here the hot path is
XLA-compiled; Pallas kernels live in this package where fusion beyond XLA's
pays off, and :mod:`.debug` holds the sharding-observability twins of the
tutorials' shape prints.
"""

from pytorch_distributed_training_tutorials_tpu.ops.debug import (  # noqa: F401
    per_shard_shapes,
    describe_sharding,
)
from pytorch_distributed_training_tutorials_tpu.ops.flash_attention import (  # noqa: F401
    flash_attention,
    make_flash_attention,
)
from pytorch_distributed_training_tutorials_tpu.ops.fused_loss import (  # noqa: F401
    fused_cross_entropy,
    fused_cross_entropy_reference,
    fused_cross_entropy_tp,
)
from pytorch_distributed_training_tutorials_tpu.ops.fused_optim import (  # noqa: F401
    FusedAdamWState,
    fused_adamw,
)
from pytorch_distributed_training_tutorials_tpu.ops.paged_attention import (  # noqa: F401
    paged_attention,
    paged_attention_reference,
)
from pytorch_distributed_training_tutorials_tpu.ops.quant import (  # noqa: F401
    Int8Dense,
    Int8Param,
    int8_matmul,
    pack_int4,
    quantize_int8,
    quantize_kv_int4,
    unpack_int4,
)
