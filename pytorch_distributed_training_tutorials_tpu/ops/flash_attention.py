"""Pallas blockwise flash attention: causal softmax attention without the
(S, S) score matrix.

The reference has no attention kernel at all (its only transformer is the
vendored Llama-7B loaded for the placement demo, never run —
``/root/reference/03.model_parallel.ipynb`` cell 2; SURVEY.md section 5.7).
Dense :func:`..models.transformer.causal_attention` materializes a
``(B, H, S, S)`` float32 score tensor — O(S^2) HBM that caps single-chip
context length. This module is the TPU-native fix: the standard
flash-attention decomposition (online softmax over key blocks) as a Pallas
kernel, so scores only ever exist as a ``(block_q, block_k)`` tile in VMEM.

- forward: one MXU pass per (q-block, k-block) pair with the running
  (m, l, acc) online-softmax state in VMEM scratch, carried across the
  innermost grid dimension (the K-blocked accumulator pattern of
  :func:`..ops.quant.int8_matmul`, this repo's house kernel template).
  Blocks entirely above the causal diagonal are predicated off with
  ``pl.when``.
- backward: custom VJP (the flash recompute strategy — O(S) residuals:
  per-row logsumexp + the output). Two Pallas kernels re-derive score
  tiles blockwise: dq accumulates over key blocks, dk/dv over query blocks.
- numerics: scores/softmax in float32 regardless of input dtype (matching
  ``masked_attention``'s mixed-precision contract); probabilities cast back
  to the value dtype for the MXU context matmul.

``flash_attention`` is a drop-in ``attention_fn`` for
:class:`..models.transformer.TransformerConfig` — same (B, S, H, D)
signature and causal semantics as ``causal_attention``, equivalence-tested
in ``tests/test_flash_attention.py``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float("-inf")  # plain float: no jax arrays at import time


def _causal_overlap(qi, kk, block_q: int, block_k: int):
    """True when key block ``kk`` has any position <= some query position
    of block ``qi`` (i.e. the block is not entirely above the diagonal)."""
    return kk * block_k <= qi * block_q + block_q - 1


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
    *, scale: float, block_q: int, block_k: int, n_k: int,
):
    """One (q-block, k-block) tile of the online-softmax forward."""
    qi, kk = pl.program_id(1), pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when(_causal_overlap(qi, kk, block_q, block_k))
    def _body():
        # matmul operands stay in the INPUT dtype: upcasting bf16->f32
        # adds no information (products accumulate f32 either way via
        # preferred_element_type), and Mosaic is what decides the MXU
        # pass structure — measured identical on v5e with or without the
        # explicit upcast (it folds the convert into the op), so the
        # native form is kept for clarity, not speed
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (BQ, BK) f32
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = kk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)

        m_prev = m_ref[:, :1]  # (BQ, 1)
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        # rows whose every key is causally masked keep m == -inf; exp(-inf
        # - -inf) would be NaN — guard the shift (those rows contribute 0)
        shift = jnp.where(m_new == NEG_INF, 0.0, m_new)
        p = jnp.exp(s - shift)  # (BQ, BK)
        corr = jnp.exp(m_prev - shift)  # (BQ, 1); exp(-inf-0)=0 at init
        l_ref[:, :1] = l_ref[:, :1] * corr + p.sum(axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot(
            p.astype(v_ref.dtype), v_ref[0],
            preferred_element_type=jnp.float32,
        )
        m_ref[:, :1] = m_new

    @pl.when(kk == n_k - 1)
    def _flush():
        l = l_ref[:, :1]
        # causal => every in-range row saw its own diagonal, l > 0; fully
        # masked rows only exist for padded sequence tails (sliced away by
        # the wrapper) — emit 0, not NaN, for them
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        m = m_ref[:, :1]
        lse = jnp.where(m == NEG_INF, NEG_INF, m + jnp.log(safe_l))
        # row vectors live as (8, block) tiles: Mosaic requires the last
        # two block dims (8, 128)-aligned, so a bare (1, block) row is not
        # expressible — broadcast over the 8 sublanes instead
        lse_ref[0] = jnp.broadcast_to(lse[:, 0][None, :], lse_ref.shape[1:])


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, acc_ref,
    *, scale: float, block_q: int, block_k: int, n_k: int,
):
    """dq = sum_k dS @ K * scale, accumulated over key blocks."""
    qi, kk = pl.program_id(1), pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(_causal_overlap(qi, kk, block_q, block_k))
    def _body():
        # native-dtype operands, f32 accumulation (see _fwd_kernel note)
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = kk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        lse = lse_ref[0, 0, :][:, None]  # (BQ, 1)
        # p = softmax row (exact, via the saved logsumexp); masked rows of a
        # padded tail have lse == -inf -> guard like the forward
        p = jnp.exp(s - jnp.where(lse == NEG_INF, 0.0, lse))
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BQ, BK) f32
        ds = p * (dp - delta_ref[0, 0, :][:, None])  # (BQ, BK)
        acc_ref[:] += jax.lax.dot(
            ds.astype(k_ref.dtype), k_ref[0],
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(kk == n_k - 1)
    def _flush():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc, dv_acc,
    *, scale: float, block_q: int, block_k: int, n_q: int,
):
    """dk/dv for one key block, accumulated over query blocks (transposed
    tiles: rows are keys, columns queries)."""
    kk, qi = pl.program_id(1), pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(_causal_overlap(qi, kk, block_q, block_k))
    def _body():
        # native-dtype operands, f32 accumulation (see _fwd_kernel note)
        st = jax.lax.dot_general(
            k_ref[0], q_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (BK, BQ) — transposed scores
        k_pos = kk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, block_q), 0
        )
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, block_q), 1
        )
        st = jnp.where(q_pos >= k_pos, st, NEG_INF)
        lse = lse_ref[0, 0, :][None, :]  # (1, BQ)
        pt = jnp.exp(st - jnp.where(lse == NEG_INF, 0.0, lse))  # (BK, BQ)
        dv_acc[:] += jax.lax.dot(
            pt.astype(do_ref.dtype), do_ref[0],
            preferred_element_type=jnp.float32,
        )
        dpt = jax.lax.dot_general(
            v_ref[0], do_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BK, BQ) f32
        dst = pt * (dpt - delta_ref[0, 0, :][None, :])
        dk_acc[:] += jax.lax.dot(
            dst.astype(q_ref.dtype), q_ref[0],
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(qi == n_q - 1)
    def _flush():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _to_bhsd(x):
    """(B, S, H, D) -> (B*H, S, D)."""
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _from_bhsd(x, b, h):
    bh, s, d = x.shape
    return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _block_sizes(
    s: int, block_q: int, block_k: int, interpret: bool
) -> tuple[int, int, int]:
    """Clamp blocks to the (8-aligned) sequence length and compute the pad
    that makes the padded length a multiple of both.

    On real TPU (``interpret=False``) Mosaic requires a block's lane dim to
    be a 128-multiple OR span the whole array, so sub-128 user block sizes
    are rounded up (the lse/delta row tiles put block_q in lanes).
    Interpreter mode has no tiling constraint — tests keep small blocks to
    exercise multi-block layouts on short sequences."""
    s8 = -(-max(8, s) // 8) * 8  # sublane alignment for small sequences

    def clamp(b: int) -> int:
        if not interpret:
            b = -(-b // 128) * 128
        return s8 if b >= s8 else b

    block_q, block_k = clamp(block_q), clamp(block_k)
    target = -(-s // block_q) * block_q
    target = -(-target // block_k) * block_k
    return block_q, block_k, target - s


def _fwd_impl(q, k, v, block_q, block_k, interpret):
    b, s, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    block_q, block_k, pad = _block_sizes(s, block_q, block_k, interpret)
    qf, kf, vf = _to_bhsd(q), _to_bhsd(k), _to_bhsd(v)
    if pad:
        # zero-padded tail keys sit above every real row's diagonal -> the
        # causal mask already excludes them; padded query rows are sliced
        # off below
        qf = jnp.pad(qf, ((0, 0), (0, pad), (0, 0)))
        kf = jnp.pad(kf, ((0, 0), (0, pad), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, pad), (0, 0)))
    sp = s + pad
    n_q, n_k = sp // block_q, sp // block_k
    grid = (b * h, n_q, n_k)
    qspec = pl.BlockSpec(
        (1, block_q, d), lambda bh, qi, kk: (bh, qi, 0),
        memory_space=pltpu.VMEM,
    )
    kspec = pl.BlockSpec(
        (1, block_k, d), lambda bh, qi, kk: (bh, kk, 0),
        memory_space=pltpu.VMEM,
    )
    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, scale=scale, block_q=block_q, block_k=block_k,
            n_k=n_k,
        ),
        grid=grid,
        in_specs=[qspec, kspec, kspec],
        out_specs=[
            qspec,
            pl.BlockSpec(
                (1, 8, block_q), lambda bh, qi, kk: (bh, 0, qi),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sp, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 8, sp), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out, lse, (qf, kf, vf), sp, pad


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    block_q: int = 512,
    block_k: int = 512,
    interpret: bool | None = None,
) -> jax.Array:
    """Causal flash attention; (B, S, H, D) in and out.

    Numerically equivalent to
    :func:`..models.transformer.causal_attention` (tested to float
    tolerance) without ever materializing an (S, S) score matrix: peak
    attention temp is O(block_q * block_k) VMEM per core plus the O(S)
    logsumexp residual. ``interpret=None`` auto-selects interpreter mode
    off-TPU so the same code path tests on the CPU mesh.

    Use directly as ``TransformerConfig(attention_fn=flash_attention)``,
    or via :func:`make_flash_attention` to fix block sizes.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    out, _, _, _, pad = _fwd_impl(q, k, v, block_q, block_k, interpret)
    b, s, h, _ = q.shape
    if pad:
        out = out[:, :s, :]
    return _from_bhsd(out, b, h)


def _flash_fwd(q, k, v, block_q, block_k, interpret):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    out, lse, (qf, kf, vf), sp, pad = _fwd_impl(
        q, k, v, block_q, block_k, interpret
    )
    b, s, h, _ = q.shape
    out_user = out[:, :s, :] if pad else out
    return _from_bhsd(out_user, b, h), (qf, kf, vf, out, lse, q.shape)


def _flash_bwd(block_q, block_k, interpret, res, g):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    qf, kf, vf, out, lse, qshape = res
    b, s, h, d = qshape
    bh, sp, _ = qf.shape
    block_q, block_k, _ = _block_sizes(s, block_q, block_k, interpret)
    scale = 1.0 / (d ** 0.5)
    n_q, n_k = sp // block_q, sp // block_k

    do = _to_bhsd(g)
    if sp != s:
        do = jnp.pad(do, ((0, 0), (0, sp - s), (0, 0)))
    # delta_i = rowsum(dO_i * O_i) — the softmax-jacobian diagonal term,
    # O(S) elementwise work outside the kernels. Stored (BH, 8, Sp) like
    # the lse (Mosaic row-vector tiling; see _fwd_kernel's flush note).
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )  # (BH, Sp)
    delta = jnp.broadcast_to(delta[:, None, :], (bh, 8, sp))

    qspec = pl.BlockSpec(
        (1, block_q, d), lambda bh_, qi, kk: (bh_, qi, 0),
        memory_space=pltpu.VMEM,
    )
    kspec = pl.BlockSpec(
        (1, block_k, d), lambda bh_, qi, kk: (bh_, kk, 0),
        memory_space=pltpu.VMEM,
    )
    rowq = pl.BlockSpec(
        (1, 8, block_q), lambda bh_, qi, kk: (bh_, 0, qi),
        memory_space=pltpu.VMEM,
    )
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, block_q=block_q, block_k=block_k,
            n_k=n_k,
        ),
        grid=(bh, n_q, n_k),
        in_specs=[qspec, kspec, kspec, qspec, rowq, rowq],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((bh, sp, d), qf.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, do, lse, delta)

    # transposed grid: outer over key blocks, inner accumulates over the
    # query blocks at/below the diagonal
    qspec_t = pl.BlockSpec(
        (1, block_q, d), lambda bh_, kk, qi: (bh_, qi, 0),
        memory_space=pltpu.VMEM,
    )
    kspec_t = pl.BlockSpec(
        (1, block_k, d), lambda bh_, kk, qi: (bh_, kk, 0),
        memory_space=pltpu.VMEM,
    )
    rowq_t = pl.BlockSpec(
        (1, 8, block_q), lambda bh_, kk, qi: (bh_, 0, qi),
        memory_space=pltpu.VMEM,
    )
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, block_q=block_q, block_k=block_k,
            n_q=n_q,
        ),
        grid=(bh, n_k, n_q),
        in_specs=[qspec_t, kspec_t, kspec_t, qspec_t, rowq_t, rowq_t],
        out_specs=[kspec_t, kspec_t],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sp, d), kf.dtype),
            jax.ShapeDtypeStruct((bh, sp, d), vf.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, do, lse, delta)

    if sp != s:
        dq, dk, dv = (a[:, :s, :] for a in (dq, dk, dv))
    return _from_bhsd(dq, b, h), _from_bhsd(dk, b, h), _from_bhsd(dv, b, h)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


def make_flash_attention(
    block_q: int = 512, block_k: int = 512, interpret: bool | None = None
):
    """Fix kernel block sizes; returns an ``attention_fn(q, k, v)`` for
    :class:`..models.transformer.TransformerConfig`."""

    def attention_fn(q, k, v):
        return flash_attention(
            q, k, v, block_q, block_k, interpret
        )

    return attention_fn
