"""Fused AdamW: the optimizer update as one Pallas pass per parameter.

The reference's update is an opaque ``optimizer.step()`` (reference
``ddp_gpus.py:39``); the optax twin (``optax.adamw``) traces to a chain of
~10 elementwise HLO ops per leaf — moment decay, bias correction, rsqrt,
weight decay, learning-rate scale — whose fusion boundaries XLA draws per
op-group, re-reading moments and params from HBM along the way. The
optimizer tail does zero matmul work; its floor is pure HBM bandwidth:
read each of grad/m/v/param once, write update/m/v once. This module
states that floor as a single Pallas kernel per leaf (``interpret=True``
off-TPU, the house pattern), with the moment buffers aliased in-place
(``input_output_aliases``) so XLA doesn't double-buffer them.

``fused_adamw`` is a drop-in :class:`optax.GradientTransformation` with
``optax.adamw``'s exact update math (``scale_by_adam`` with bias-corrected
moments, decoupled weight decay, ``-lr`` scaling): 100-step trajectory
equivalence is pinned by ``tests/test_fused_optim.py``. The Trainer's
``_apply_update`` consumes it unchanged — including the ISSUE 9 skip-step
guard (``Trainer(skip_nonfinite=True)``): the guard's ``jnp.where``
select runs AFTER ``tx.update`` on the update's outputs, so even with the
moment buffers aliased in-place here, a skipped step keeps params,
``mu``/``nu``, and ``count`` bitwise unchanged (XLA copies a donated
buffer whose pre-update value is still live in the select;
``tests/test_trainer.py::test_skip_step_through_grad_accum_and_fused_adamw``
pins it).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128  # fixed lane width; leaves are repacked to (rows, 128)


class FusedAdamWState(NamedTuple):
    """``optax.adamw``'s state fields (count + first/second moments)."""

    count: jax.Array
    mu: optax.Updates
    nu: optax.Updates


def _adamw_kernel(
    g_ref, m_ref, v_ref, p_ref, c_ref, u_ref, mo_ref, vo_ref,
    *, lr: float, b1: float, b2: float, eps: float, wd: float,
):
    """One row-block: grad/m/v/param in, update/m/v out — every value is
    touched exactly once."""
    g = g_ref[:].astype(jnp.float32)
    m = b1 * m_ref[:].astype(jnp.float32) + (1.0 - b1) * g
    v = b2 * v_ref[:].astype(jnp.float32) + (1.0 - b2) * g * g
    # c = (1 - b1^t, 1 - b2^t), precomputed on host-side scalars (SMEM)
    m_hat = m / c_ref[0, 0]
    v_hat = v / c_ref[0, 1]
    p = p_ref[:].astype(jnp.float32)
    u = -lr * (m_hat / (jnp.sqrt(v_hat) + eps) + wd * p)
    u_ref[:] = u.astype(u_ref.dtype)
    mo_ref[:] = m.astype(mo_ref.dtype)
    vo_ref[:] = v.astype(vo_ref.dtype)


def _leaf_update(
    g, m, v, p, c,
    *, lr, b1, b2, eps, wd, block_rows: int, interpret: bool,
):
    """Run the kernel over one (arbitrary-shape) leaf: flatten to
    (rows, 128) lanes, pad to an 8-aligned row block, unpack after."""
    shape, size = p.shape, p.size
    rows = -(-size // _LANES)
    rows8 = -(-max(rows, 8) // 8) * 8
    br = min(-(-block_rows // 8) * 8, rows8)
    rp = -(-rows8 // br) * br

    def pack(a):
        flat = jnp.pad(a.reshape(-1), (0, rp * _LANES - size))
        return flat.reshape(rp, _LANES)

    spec = pl.BlockSpec(
        (br, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    u2, m2, v2 = pl.pallas_call(
        functools.partial(
            _adamw_kernel, lr=lr, b1=b1, b2=b2, eps=eps, wd=wd
        ),
        grid=(rp // br,),
        in_specs=[
            spec, spec, spec, spec,
            pl.BlockSpec(
                (1, 2), lambda i: (0, 0), memory_space=pltpu.SMEM
            ),
        ],
        out_specs=[spec, spec, spec],
        out_shape=[
            jax.ShapeDtypeStruct((rp, _LANES), p.dtype),
            jax.ShapeDtypeStruct((rp, _LANES), m.dtype),
            jax.ShapeDtypeStruct((rp, _LANES), v.dtype),
        ],
        # moments update in place — no double-buffered m/v in HBM
        input_output_aliases={1: 1, 2: 2},
        interpret=interpret,
    )(pack(g), pack(m), pack(v), pack(p), c)

    def unpack(a):
        return a.reshape(-1)[:size].reshape(shape)

    return unpack(u2), unpack(m2), unpack(v2)


def fused_adamw(
    learning_rate: float,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 1e-4,
    *,
    mask=None,
    block_rows: int = 1024,
    interpret: bool | None = None,
) -> optax.GradientTransformation:
    """Drop-in ``optax.adamw`` with the update fused to one kernel pass
    per leaf (same defaults and update math as ``optax.adamw``; decay is
    applied to every updated leaf).

    ``mask`` (a boolean pytree matching params, or a callable producing
    one — e.g. :func:`..adapters.lora.lora_param_mask`) restricts the
    update to the True leaves: masked-out leaves get a hard-zero update
    (``optax.set_to_zero``, not a pass-through of the raw gradient) AND
    no moment buffers — a LoRA fine-tune pays optimizer memory only for
    the factor leaves, exactly like ``optax.masked(optax.adamw(...),
    mask)``.

    ``learning_rate`` must be a static float (it is baked into the
    kernel); schedules would need a per-step scalar operand — wrap with
    ``optax.inject_hyperparams`` upstream or use stock ``optax.adamw``
    when a schedule is required. ``interpret=None`` auto-selects Pallas
    interpreter mode off-TPU (the CPU-mesh test path).
    """
    if callable(learning_rate):
        raise TypeError(
            "fused_adamw takes a static float learning_rate (it is baked "
            "into the kernel); use optax.adamw for schedules"
        )
    lr = float(learning_rate)

    def init_fn(params):
        return FusedAdamWState(
            count=jnp.zeros((), jnp.int32),
            mu=jax.tree_util.tree_map(jnp.zeros_like, params),
            nu=jax.tree_util.tree_map(jnp.zeros_like, params),
        )

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError(
                "fused_adamw requires params (decoupled weight decay)"
            )
        itp = (
            interpret
            if interpret is not None
            else jax.default_backend() != "tpu"
        )
        count = optax.safe_int32_increment(state.count)
        t = count.astype(jnp.float32)
        # bias corrections (1 - b^t) as a (1, 2) SMEM scalar pair
        c = jnp.stack(
            [1.0 - jnp.float32(b1) ** t, 1.0 - jnp.float32(b2) ** t]
        ).reshape(1, 2)
        leaf = functools.partial(
            _leaf_update,
            lr=lr, b1=b1, b2=b2, eps=eps, wd=weight_decay,
            block_rows=block_rows, interpret=itp,
        )
        flat_g, treedef = jax.tree_util.tree_flatten(updates)
        flat = [
            leaf(g, m, v, p, c)
            for g, m, v, p in zip(
                flat_g,
                jax.tree_util.tree_leaves(state.mu),
                jax.tree_util.tree_leaves(state.nu),
                jax.tree_util.tree_leaves(params),
            )
        ]
        new_u = jax.tree_util.tree_unflatten(treedef, [f[0] for f in flat])
        new_m = jax.tree_util.tree_unflatten(treedef, [f[1] for f in flat])
        new_v = jax.tree_util.tree_unflatten(treedef, [f[2] for f in flat])
        return new_u, FusedAdamWState(count=count, mu=new_m, nu=new_v)

    tx = optax.GradientTransformation(init_fn, update_fn)
    if mask is None:
        return tx

    def inverted(params):
        m = mask(params) if callable(mask) else mask
        return jax.tree_util.tree_map(lambda b: not b, m)

    # masked kernel on the trainable leaves + hard zero on the frozen
    # ones: apply_updates then adds exact 0.0, so frozen leaves never
    # drift (a bare optax.masked would pass the RAW GRADIENT through as
    # the masked-out "update")
    return optax.chain(
        optax.masked(tx, mask),
        optax.masked(optax.set_to_zero(), inverted),
    )
