"""Pallas blockwise softmax cross entropy: the LM loss without the logits.

The reference computes its loss as ``F.cross_entropy(output, targets)`` over
fully materialized logits (reference ``ddp_gpus.py:37``); the TPU twin did
the same with ``optax.softmax_cross_entropy_with_integer_labels`` over the
``(B, S, V)`` lm_head output. At LM scale that tensor is the single largest
activation of the train step (350m config, B=8, S=2048: 2 GiB of bf16
logits plus the float32 softmax temps behind it) and every byte of it is
memory-bound tail work — the matmuls feeding it are already near-roofline
(TRAIN_LLM_r05.md). This module removes it with the same online-softmax
decomposition :mod:`.flash_attention` uses for the (S, S) score matrix:

- forward: one MXU pass per (row-block, vocab-block) tile of the lm_head
  matmul, folding each logits tile into a running (max, sum-exp, target
  logit) state in VMEM scratch — the ``(N, V)`` logits only ever exist as a
  ``(block_n, block_v)`` tile. Residual: the O(N) per-token logsumexp.
- backward (``jax.custom_vjp``): two kernels re-derive logits tiles
  blockwise from the saved logsumexp and fuse softmax-minus-one-hot into
  the gradient matmuls directly — ``dh`` accumulates over vocab blocks,
  ``dW`` over row blocks (the dq/dkv split of the flash backward).
- numerics: logits/softmax in float32 regardless of input dtype; matmul
  operands stay in the input dtype with f32 accumulation
  (``preferred_element_type``), matching the repo kernel template.

``interpret=None`` auto-selects Pallas interpreter mode off-TPU (the
:func:`.flash_attention.flash_attention` pattern) so the identical kernel
code path runs on the forced 8-device CPU test mesh, where it lowers to
plain HLO and composes with GSPMD sharding. On real multi-chip meshes a
``pallas_call`` is a single-device program; the tensor-parallel vocab-split
head (``TP_RULES``' ``lm_head: P(None, 'model')``) goes through
:func:`fused_cross_entropy_tp`, which states the Megatron layout in
``shard_map``: each shard runs the same kernels over its vocab columns with
locally shifted targets, then an axis-reduced logsumexp + psum of the
target logit stitch the global loss.

Equivalence with the optax path is pinned by ``tests/test_fused_loss.py``;
the ``compiled.memory_analysis()``/HLO receipt that no ``(B, S, V)`` float
intermediate survives compilation lives there too.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from pytorch_distributed_training_tutorials_tpu.utils.compat import (
    shard_map_nocheck,
)

NEG_INF = float("-inf")  # plain float: no jax arrays at import time

# Defaults sized for LM-head shapes (D ~ 1-4k, V ~ 32-256k): the VMEM
# working set per tile is block_n*D (rows) + D*block_v (weights) +
# block_n*block_v f32 (logits tile) + row scratch — ~6 MB at D=2048.
# block_n also sets the head-weight re-read factor (each row block streams
# the whole W): HBM traffic for W is ceil(N / block_n) * |W|, so prefer
# the largest block_n whose tiles still fit VMEM when tuning on-chip.
DEFAULT_BLOCK_N = 512
DEFAULT_BLOCK_V = 512


def _clamp_block(b: int, dim: int, interpret: bool) -> int:
    """Clamp a block size to the (8-aligned) dim. On real TPU Mosaic wants
    lane dims in 128-multiples OR spanning the whole array, so sub-128
    user blocks round up (the lse/loss row tiles put block_n in lanes;
    the logits tile puts block_v there). Interpreter mode has no tiling
    constraint — tests keep small blocks to exercise multi-block layouts
    on small problems (the :func:`.flash_attention._block_sizes` rule)."""
    d8 = -(-max(8, dim) // 8) * 8
    if not interpret:
        b = -(-b // 128) * 128
    return d8 if b >= d8 else b


def _row8(vec, total):
    """Pad a per-row (N,) vector to ``total`` and broadcast over the 8
    sublanes — Mosaic requires (8, 128)-alignable tiles, so a bare
    (1, block) row is not expressible (the flash lse layout)."""
    padded = jnp.pad(vec, (0, total - vec.shape[0]))
    return jnp.broadcast_to(padded[None, :], (8, total))


def _fwd_kernel(
    h_ref, w_ref, y_ref, lse_ref, tgt_ref, m_ref, l_ref, t_ref,
    *, block_n: int, block_v: int, n_v: int, vocab: int,
):
    """One (row-block, vocab-block) tile of the online-logsumexp forward."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        t_ref[:] = jnp.zeros_like(t_ref)

    # operands stay in the input dtype, accumulation f32 (house rule —
    # see the _fwd_kernel note in flash_attention)
    s = jax.lax.dot_general(
        h_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (BN, BV) f32 — the only form the logits ever take
    col = j * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (block_n, block_v), 1
    )
    # zero-padded vocab tail columns must not score
    s = jnp.where(col < vocab, s, NEG_INF)

    m_prev = m_ref[:, :1]  # (BN, 1)
    m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
    # a block whose every column is padded keeps m == -inf; exp(-inf - -inf)
    # would be NaN — guard the shift (those columns contribute 0)
    shift = jnp.where(m_new == NEG_INF, 0.0, m_new)
    p = jnp.exp(s - shift)  # (BN, BV)
    corr = jnp.exp(m_prev - shift)  # (BN, 1); exp(-inf - 0) = 0 at init
    l_ref[:, :1] = l_ref[:, :1] * corr + p.sum(axis=-1, keepdims=True)
    m_ref[:, :1] = m_new
    # target logit: exactly one (row, col) hit across all vocab blocks —
    # out-of-range targets (padded rows; other shards' tokens in the TP
    # variant) hit nothing and contribute 0. The col < vocab guard keeps a
    # shifted target that lands in the padded tail (TP variant, V_local
    # not a block multiple) off the -inf padding columns.
    y = y_ref[0, :]  # (BN,) int32
    hit = (col == y[:, None]) & (col < vocab)
    t_ref[:, :1] += jnp.where(hit, s, 0.0).sum(axis=-1, keepdims=True)

    @pl.when(j == n_v - 1)
    def _flush():
        l = l_ref[:, :1]
        # real vocab >= 1 column per row => l > 0; all-padded rows only
        # exist for row-padding tails (sliced away by the wrapper)
        safe_l = jnp.where(l == 0.0, 1.0, l)
        m = m_ref[:, :1]
        lse = jnp.where(m == NEG_INF, NEG_INF, m + jnp.log(safe_l))
        lse_ref[:] = jnp.broadcast_to(lse[:, 0][None, :], lse_ref.shape)
        tgt_ref[:] = jnp.broadcast_to(
            t_ref[:, 0][None, :], tgt_ref.shape
        )


def _softmax_minus_onehot(s, y_row, g_row, lse_row, col, vocab):
    """The shared dS tile of both backward kernels:
    ``g * (softmax(s) - onehot(y))`` recomputed from the saved logsumexp."""
    s = jnp.where(col < vocab, s, NEG_INF)
    lse = lse_row[:, None]  # (BN, 1)
    # padded rows carry lse == 0 with g == 0 — the g factor zeroes them
    p = jnp.exp(s - jnp.where(lse == NEG_INF, 0.0, lse))
    # col < vocab: see the forward's target-hit guard (padded-tail columns
    # must stay gradient-free even when a shifted target lands on them)
    hit = (col == y_row[:, None]) & (col < vocab)
    return (p - hit.astype(jnp.float32)) * g_row[:, None]


def _dh_kernel(
    h_ref, w_ref, y_ref, lse_ref, g_ref, dh_ref, acc_ref,
    *, block_n: int, block_v: int, n_v: int, vocab: int,
):
    """dh = sum_v dS @ W^T, accumulated over vocab blocks."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    s = jax.lax.dot_general(
        h_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    col = j * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (block_n, block_v), 1
    )
    ds = _softmax_minus_onehot(
        s, y_ref[0, :], g_ref[0, :], lse_ref[0, :], col, vocab
    )
    acc_ref[:] += jax.lax.dot_general(
        ds.astype(w_ref.dtype), w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(j == n_v - 1)
    def _flush():
        dh_ref[:] = acc_ref[:].astype(dh_ref.dtype)


def _dw_kernel(
    h_ref, w_ref, y_ref, lse_ref, g_ref, dw_ref, acc_ref,
    *, block_n: int, block_v: int, n_n: int, vocab: int,
):
    """dW = sum_rows H^T @ dS for one vocab block, accumulated over row
    blocks (the transposed-grid half, like the flash dk/dv kernel)."""
    vj, ri = pl.program_id(0), pl.program_id(1)

    @pl.when(ri == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    s = jax.lax.dot_general(
        h_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    col = vj * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (block_n, block_v), 1
    )
    ds = _softmax_minus_onehot(
        s, y_ref[0, :], g_ref[0, :], lse_ref[0, :], col, vocab
    )
    acc_ref[:] += jax.lax.dot_general(
        h_ref[:], ds.astype(h_ref.dtype), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(ri == n_n - 1)
    def _flush():
        dw_ref[:] = acc_ref[:].astype(dw_ref.dtype)


def _pad_inputs(h2, w, y, block_n, block_v, interpret):
    """Shared padding/blocking for the forward and backward calls."""
    n, _ = h2.shape
    v = w.shape[1]
    bn = _clamp_block(block_n, n, interpret)
    bv = _clamp_block(block_v, v, interpret)
    pad_n = -n % bn
    pad_v = -v % bv
    hf = jnp.pad(h2, ((0, pad_n), (0, 0))) if pad_n else h2
    wf = jnp.pad(w, ((0, 0), (0, pad_v))) if pad_v else w
    # padded rows carry target 0 — their loss/grad rows are sliced away,
    # and in the backward their cotangent is zero-padded
    y8 = _row8(y.astype(jnp.int32), n + pad_n)
    return hf, wf, y8, bn, bv, n + pad_n, v + pad_v


def _fwd_impl(h2, w, y, block_n, block_v, interpret):
    """(lse, target_logit) per row, both (N,) f32 — the logits-free pass."""
    n, d = h2.shape
    v = w.shape[1]
    hf, wf, y8, bn, bv, np_, vp = _pad_inputs(
        h2, w, y, block_n, block_v, interpret
    )
    n_n, n_v = np_ // bn, vp // bv
    hspec = pl.BlockSpec(
        (bn, d), lambda i, j: (i, 0), memory_space=pltpu.VMEM
    )
    wspec = pl.BlockSpec(
        (d, bv), lambda i, j: (0, j), memory_space=pltpu.VMEM
    )
    rowspec = pl.BlockSpec(
        (8, bn), lambda i, j: (0, i), memory_space=pltpu.VMEM
    )
    lse, tgt = pl.pallas_call(
        functools.partial(
            _fwd_kernel, block_n=bn, block_v=bv, n_v=n_v, vocab=v
        ),
        grid=(n_n, n_v),
        in_specs=[hspec, wspec, rowspec],
        out_specs=[rowspec, rowspec],
        out_shape=[jax.ShapeDtypeStruct((8, np_), jnp.float32)] * 2,
        scratch_shapes=[pltpu.VMEM((bn, 128), jnp.float32)] * 3,
        interpret=interpret,
    )(hf, wf, y8)
    return lse[0, :n], tgt[0, :n]


def _bwd_impl(h2, w, y, lse, g, block_n, block_v, interpret):
    """(dh, dW) via blockwise softmax recompute from the saved ``lse``."""
    n, d = h2.shape
    v = w.shape[1]
    hf, wf, y8, bn, bv, np_, vp = _pad_inputs(
        h2, w, y, block_n, block_v, interpret
    )
    n_n, n_v = np_ // bn, vp // bv
    lse8 = _row8(lse, np_)
    g8 = _row8(g.astype(jnp.float32), np_)

    hspec = pl.BlockSpec(
        (bn, d), lambda i, j: (i, 0), memory_space=pltpu.VMEM
    )
    wspec = pl.BlockSpec(
        (d, bv), lambda i, j: (0, j), memory_space=pltpu.VMEM
    )
    rowspec = pl.BlockSpec(
        (8, bn), lambda i, j: (0, i), memory_space=pltpu.VMEM
    )
    dh = pl.pallas_call(
        functools.partial(
            _dh_kernel, block_n=bn, block_v=bv, n_v=n_v, vocab=v
        ),
        grid=(n_n, n_v),
        in_specs=[hspec, wspec, rowspec, rowspec, rowspec],
        out_specs=hspec,
        out_shape=jax.ShapeDtypeStruct((np_, d), hf.dtype),
        scratch_shapes=[pltpu.VMEM((bn, d), jnp.float32)],
        interpret=interpret,
    )(hf, wf, y8, lse8, g8)

    # transposed grid: outer over vocab blocks, inner accumulates rows
    hspec_t = pl.BlockSpec(
        (bn, d), lambda vj, ri: (ri, 0), memory_space=pltpu.VMEM
    )
    wspec_t = pl.BlockSpec(
        (d, bv), lambda vj, ri: (0, vj), memory_space=pltpu.VMEM
    )
    rowspec_t = pl.BlockSpec(
        (8, bn), lambda vj, ri: (0, ri), memory_space=pltpu.VMEM
    )
    dw = pl.pallas_call(
        functools.partial(
            _dw_kernel, block_n=bn, block_v=bv, n_n=n_n, vocab=v
        ),
        grid=(n_v, n_n),
        in_specs=[hspec_t, wspec_t, rowspec_t, rowspec_t, rowspec_t],
        out_specs=wspec_t,
        out_shape=jax.ShapeDtypeStruct((d, vp), wf.dtype),
        scratch_shapes=[pltpu.VMEM((d, bv), jnp.float32)],
        interpret=interpret,
    )(hf, wf, y8, lse8, g8)

    return dh[:n], dw[:, :v]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fused_ce(h2, w, y, block_n, block_v, interpret):
    lse, tgt = _fwd_impl(h2, w, y, block_n, block_v, interpret)
    return lse - tgt


def _fused_ce_fwd(h2, w, y, block_n, block_v, interpret):
    lse, tgt = _fwd_impl(h2, w, y, block_n, block_v, interpret)
    return lse - tgt, (h2, w, y, lse)


def _fused_ce_bwd(block_n, block_v, interpret, res, g):
    h2, w, y, lse = res
    dh, dw = _bwd_impl(h2, w, y, lse, g, block_n, block_v, interpret)
    # integer targets take a float0 cotangent (jax's tangent type for
    # non-differentiable inputs)
    return dh, dw, np.zeros(y.shape, jax.dtypes.float0)


_fused_ce.defvjp(_fused_ce_fwd, _fused_ce_bwd)


def fused_cross_entropy(
    hidden: jax.Array,
    lm_head: jax.Array,
    targets: jax.Array,
    *,
    block_n: int = DEFAULT_BLOCK_N,
    block_v: int = DEFAULT_BLOCK_V,
    interpret: bool | None = None,
) -> jax.Array:
    """Per-token softmax cross entropy of ``hidden @ lm_head`` against
    integer ``targets``, logits-free.

    ``hidden``: (..., D) final hidden states; ``lm_head``: (D, V) head
    kernel; ``targets``: (...) int, same leading shape as ``hidden``.
    Returns per-token losses of ``targets.shape`` in float32 — the same
    contract as ``optax.softmax_cross_entropy_with_integer_labels(
    hidden @ lm_head, targets)`` (reference loss ``ddp_gpus.py:37``), so
    row-validity masks (``ShardedLoader.valid_mask``) weight it the same
    way. Peak temp is O(block_n * block_v) VMEM per core plus the O(N)
    logsumexp residual; the (..., V) logits never exist in HBM.

    ``interpret=None`` auto-selects interpreter mode off-TPU so the same
    code path tests on the CPU mesh.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    d = hidden.shape[-1]
    if hidden.shape[:-1] != targets.shape:
        raise ValueError(
            f"hidden {hidden.shape} / targets {targets.shape} mismatch: "
            "hidden must be targets.shape + (d_model,)"
        )
    h2 = hidden.reshape(-1, d)
    y = targets.reshape(-1)
    loss = _fused_ce(h2, lm_head, y, block_n, block_v, interpret)
    return loss.reshape(targets.shape)


def fused_cross_entropy_reference(
    hidden: jax.Array, lm_head: jax.Array, targets: jax.Array
) -> jax.Array:
    """Materialized-logits statement of the same math (tests/off-TPU): the
    f32-accumulated lm_head matmul followed by the standard logsumexp CE."""
    logits = jnp.einsum(
        "...d,dv->...v", hidden, lm_head,
        preferred_element_type=jnp.float32,
    )
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(
        logits, targets[..., None].astype(jnp.int32), axis=-1
    )[..., 0]
    return lse - tgt


# -- tensor-parallel vocab-split head (shard_map) ---------------------------


def _row_axis(mesh, data_axis, n):
    """Shard loss rows over the data axis only when they divide it (the
    int8_matmul_tp rule) — replicated rows are correct, just unsharded."""
    if data_axis in mesh.shape and n % mesh.shape[data_axis] == 0:
        return data_axis
    return None


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _fused_ce_tp(h2, w, y, mesh, axis, data_axis, block_n, block_v,
                 interpret):
    loss, _ = _fused_ce_tp_fwd(
        h2, w, y, mesh, axis, data_axis, block_n, block_v, interpret
    )
    return loss


def _fused_ce_tp_fwd(h2, w, y, mesh, axis, data_axis, block_n, block_v,
                     interpret):
    n = h2.shape[0]
    row = _row_axis(mesh, data_axis, n)

    def fwd_local(hl, wl, yl):
        v_local = wl.shape[1]
        # this shard owns global columns [off, off + v_local): shift the
        # targets into local coordinates — out-of-shard targets go out of
        # range and the kernel's one-hot hits nothing (contribution 0)
        off = jax.lax.axis_index(axis) * v_local
        lse_l, tgt_l = _fwd_impl(
            hl, wl, yl - off, block_n, block_v, interpret
        )
        # axis-reduced logsumexp over the vocab shards: shift by the
        # cross-shard max so the exp cannot overflow
        m = jax.lax.pmax(lse_l, axis)
        lse_g = m + jnp.log(jax.lax.psum(jnp.exp(lse_l - m), axis))
        # exactly one shard holds the target column
        tgt_g = jax.lax.psum(tgt_l, axis)
        return lse_g, tgt_g

    lse, tgt = shard_map_nocheck(
        fwd_local,
        mesh=mesh,
        in_specs=(P(row, None), P(None, axis), P(row)),
        out_specs=(P(row), P(row)),
    )(h2, w, y)
    return lse - tgt, (h2, w, y, lse)


def _fused_ce_tp_bwd(mesh, axis, data_axis, block_n, block_v, interpret,
                     res, g):
    h2, w, y, lse = res
    n = h2.shape[0]
    row = _row_axis(mesh, data_axis, n)

    def bwd_local(hl, wl, yl, lsel, gl):
        v_local = wl.shape[1]
        off = jax.lax.axis_index(axis) * v_local
        # the global lse makes each shard's recomputed tile the GLOBAL
        # softmax restricted to its columns, so the two partials compose:
        # dh sums over vocab shards (psum), dW is per-shard-exact
        dh_l, dw_l = _bwd_impl(
            hl, wl, yl - off, lsel, gl, block_n, block_v, interpret
        )
        dh_g = jax.lax.psum(dh_l, axis)
        if row is not None:
            # w is replicated over the data axis: its gradient sums the
            # row shards (the allreduce GSPMD would have inserted)
            dw_l = jax.lax.psum(dw_l, data_axis)
        return dh_g, dw_l

    dh, dw = shard_map_nocheck(
        bwd_local,
        mesh=mesh,
        in_specs=(P(row, None), P(None, axis), P(row), P(row), P(row)),
        out_specs=(P(row, None), P(None, axis)),
    )(h2, w, y, lse, g)
    return dh, dw, np.zeros(y.shape, jax.dtypes.float0)


_fused_ce_tp.defvjp(_fused_ce_tp_fwd, _fused_ce_tp_bwd)


def fused_cross_entropy_tp(
    hidden: jax.Array,
    lm_head: jax.Array,
    targets: jax.Array,
    mesh,
    *,
    axis: str = "model",
    data_axis: str = "data",
    block_n: int = DEFAULT_BLOCK_N,
    block_v: int = DEFAULT_BLOCK_V,
    interpret: bool | None = None,
) -> jax.Array:
    """:func:`fused_cross_entropy` for a tensor-parallel vocab-split head
    (``TP_RULES``' ``lm_head/kernel: P(None, 'model')``), stated in
    ``shard_map`` because a ``pallas_call`` is a single-device program
    GSPMD cannot partition (the :func:`..ops.quant.int8_matmul_tp` rule).

    Each shard streams its own vocab columns through the same kernels with
    locally shifted targets; an axis-reduced logsumexp
    (``pmax`` + ``log(psum(exp))``) and a psum of the per-shard target
    logit assemble the exact global loss — numerics match the unsharded
    op to float tolerance. Rows shard over ``data_axis`` when they divide
    it. Requires V divisible by the ``axis`` size (the TP head layout
    already does).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if axis not in mesh.shape:
        raise ValueError(f"mesh has no {axis!r} axis: {dict(mesh.shape)}")
    v = lm_head.shape[1]
    if v % mesh.shape[axis]:
        raise ValueError(
            f"vocab ({v}) not divisible by the {axis!r} axis "
            f"({mesh.shape[axis]})"
        )
    d = hidden.shape[-1]
    if hidden.shape[:-1] != targets.shape:
        raise ValueError(
            f"hidden {hidden.shape} / targets {targets.shape} mismatch: "
            "hidden must be targets.shape + (d_model,)"
        )
    h2 = hidden.reshape(-1, d)
    y = targets.reshape(-1).astype(jnp.int32)
    loss = _fused_ce_tp(
        h2, lm_head, y, mesh, axis, data_axis, block_n, block_v, interpret
    )
    return loss.reshape(targets.shape)
