"""Logging: the reference's observable log surface, process-0 gated.

The reference's only observability is a per-epoch rank-tagged print
``[GPU: {id} Epoch: {e}, Batch size: {b} | Steps {n}]`` (``ddp_gpus.py:44``)
— and it never logs the loss. Here: the same line shape (chip-tagged), emitted
once from the controller process (SPMD single-controller replaces per-rank
prints), plus structured per-step loss/throughput that the reference lacks
(SURVEY.md section 5.5 flags this as a gap to close, needed for the BASELINE
north-star measurement).
"""

from __future__ import annotations

import jax


def log0(msg: str) -> None:
    """Print from process 0 only (the reference's rank-0 convention)."""
    if jax.process_index() == 0:
        print(msg, flush=True)


def epoch_line(device_count: int, epoch: int, batch_size: int, steps: int) -> str:
    """Twin of the reference's epoch line (``ddp_gpus.py:44``).

    One line for the whole SPMD program instead of one per rank; ``Chips``
    replaces ``GPU`` and reports how many devices the batch is sharded over.
    """
    return (
        f"[Chips: {device_count} Epoch: {epoch}, "
        f"Batch size: {batch_size} | Steps {steps}]"
    )
