"""Deterministic fault injection for the robustness layer (ISSUE 9).

The failure paths this repo guards — poison-slot quarantine in
:mod:`..serve.engine`, skip-step / loss-spike rollback in
:mod:`..train.trainer`, request-level prefill isolation — would
otherwise only ever run when real hardware misbehaves. This module
makes them testable on the 8-device CPU mesh: a :class:`ChaosConfig`
names *exactly where* a fault lands (slot, step, request id, chain
index) and the injectors fire there and nowhere else, so every chaos
test is reproducible bit-for-bit run to run.

Two injector families:

- **Device-side** (:func:`poison_logits`, :func:`poison_grads`): pure
  ``jnp.where`` selects inside compiled code — the fault condition is
  DATA (a traced step counter), never Python control flow, so the
  graftcheck ``traced-control-flow`` rule holds and nothing recompiles
  between faulty and clean steps. These are how a NaN *enters* the
  compiled program; the guards under test are how it is contained.
- **Host-side** (:func:`maybe_fail_prefill`, :func:`maybe_stall`,
  :func:`host_spike_loss`): plain Python against host counters —
  raise-at-prefill exercises request-level isolation, the simulated
  launch stall exercises deadline expiry without wall-clock flakiness,
  and the loss spike drives the Trainer's rollback monitor (host-keyed
  so a post-rollback replay does not re-trigger the same spike — the
  restore-and-continue semantics rollback implements).

A third family arrived with the fleet router (ISSUE 12):
**replica-level** injectors (:class:`FleetChaosConfig`,
:func:`replica_killed`, :func:`replica_stall_pending`) simulate a whole
replica dying at a fixed chain count or freezing for N scheduling
rounds — consumed by :class:`..serve.router.FleetRouter`, which is
jax-free, so these are plain host predicates.

The module is jax-free at import (``jax.numpy`` is imported inside the
device-side injectors only when they run): host-only consumers — the
scheduler tests, the selftest argument parser, the fleet router — can
use configs without touching XLA, per the import-purity hard rule.
"""

from __future__ import annotations

import dataclasses
import time


class ChaosError(RuntimeError):
    """The injected prefill failure (:func:`maybe_fail_prefill`). A
    distinct type so tests can assert the engine survived *this* fault
    rather than swallowing an unrelated bug."""


@dataclasses.dataclass(frozen=True)
class ChaosConfig:
    """Where faults land. ``-1`` (the default) disables an injector.

    - ``nan_logit_slot`` / ``nan_logit_step``: overwrite that slot's
      logits row with NaN at that global decode-step index (the engine
      counts scan iterations across chains: chain ``c``'s iteration
      ``i`` is step ``c * tokens_per_launch + i``).
    - ``nan_grad_step``: replace every gradient leaf with NaN at that
      ``TrainState.step`` value (device-side, survives grad-accum — the
      poison lands on the averaged grads). NOTE: with the skip-step
      guard on, ``step`` freezes at the poisoned value, so this injector
      re-fires on every later attempt — state stays protected (the
      guard's whole point) but no further update ever applies. Use it
      for single-step bitwise assertions; for continue-after-fault runs
      use ``nan_batch_step``.
    - ``nan_batch_step``: poison the input batch (first leaf all-NaN) at
      that 1-based host dispatch index — host-keyed and monotonic, so it
      fires exactly ONCE even though the skipped step leaves
      ``TrainState.step`` unchanged (the guarded run continues and its
      final model equals a clean run with that one update elided).
    - ``spike_loss_step`` / ``spike_loss_len`` / ``spike_loss_factor``:
      multiply the loss the Trainer's rollback monitor SEES for
      ``spike_loss_len`` consecutive host steps starting at host step
      ``spike_loss_step`` (1-based, monotonic across rollbacks).
    - ``fail_prefill_request``: raise :class:`ChaosError` when the
      engine is about to prefill that request id.
    - ``stall_chain`` / ``stall_s``: sleep ``stall_s`` seconds before
      dispatching chain index ``stall_chain`` — a deterministic stand-in
      for the multi-second launch stalls CLAUDE.md documents.
    - ``preempt_slot`` / ``preempt_at_chain``: force the SLO engine to
      preempt that slot (KV swap-out to host) at the chain-boundary
      check once its chain counter reaches ``preempt_at_chain`` — the
      swap path is testable without manufacturing real pool pressure.
      Fires exactly ONCE (the engine latches the firing); the victim
      resumes through the ordinary swap-in path, token-exact. Requires
      ``priority_classes > 0`` on the engine; ignored otherwise.
    - ``seed`` rides into receipts/fingerprints so chaos runs are
      self-describing; the injectors themselves are deterministic.
    """

    nan_logit_slot: int = -1
    nan_logit_step: int = -1
    nan_grad_step: int = -1
    nan_batch_step: int = -1
    spike_loss_step: int = -1
    spike_loss_len: int = 1
    spike_loss_factor: float = 100.0
    fail_prefill_request: int = -1
    stall_chain: int = -1
    stall_s: float = 0.0
    preempt_slot: int = -1
    preempt_at_chain: int = -1
    seed: int = 0

    @property
    def poisons_logits(self) -> bool:
        return self.nan_logit_slot >= 0 and self.nan_logit_step >= 0

    @property
    def poisons_grads(self) -> bool:
        return self.nan_grad_step >= 0

    @property
    def poisons_batch(self) -> bool:
        return self.nan_batch_step >= 1

    @property
    def spikes_loss(self) -> bool:
        return self.spike_loss_step >= 0

    @property
    def fails_prefill(self) -> bool:
        return self.fail_prefill_request >= 0

    @property
    def stalls(self) -> bool:
        return self.stall_chain >= 0 and self.stall_s > 0

    @property
    def preempts(self) -> bool:
        return self.preempt_slot >= 0 and self.preempt_at_chain >= 0


@dataclasses.dataclass(frozen=True)
class FleetChaosConfig:
    """Replica-level fault injection for the fleet router (ISSUE 12).
    Same philosophy as :class:`ChaosConfig`: ``-1`` disables an
    injector, every firing is keyed to deterministic host counters
    (replica index, the replica's chain count, the router's own round
    counter) so a chaos fleet run is reproducible bit for bit.

    - ``kill_replica`` / ``kill_at_chain``: the router declares that
      replica dead once its chain counter reaches ``kill_at_chain`` —
      PERMANENTLY (a half-open probe against a chaos-killed replica
      fails, exercising the circuit re-open path). The engine process
      is untouched; death is simulated at the router boundary, which is
      exactly where a real death is observed.
    - ``stall_replica`` / ``stall_from_chain`` / ``stall_rounds``: once
      the replica's chain counter reaches ``stall_from_chain``, the
      router skips stepping it for ``stall_rounds`` scheduling rounds —
      a progress freeze (heartbeat ages, suspicion and hedging fire)
      with no wall-clock sleep, so chaos tests stay fast and flake-free.
    - ``seed`` rides into receipts/fingerprints; the injectors are
      deterministic.

    The poison-a-replica path needs no new injector: hand ONE replica's
    engine an engine-level :class:`ChaosConfig` with
    ``nan_logit_slot``/``nan_logit_step`` and the router observes the
    resulting fault-stat deltas.
    """

    kill_replica: int = -1
    kill_at_chain: int = -1
    stall_replica: int = -1
    stall_from_chain: int = 0
    stall_rounds: int = 0
    seed: int = 0

    @property
    def kills(self) -> bool:
        return self.kill_replica >= 0 and self.kill_at_chain >= 0

    @property
    def stalls(self) -> bool:
        return self.stall_replica >= 0 and self.stall_rounds > 0


def replica_killed(cfg: FleetChaosConfig, replica: int,
                   n_chains: int) -> bool:
    """True once the configured victim replica has dispatched
    ``kill_at_chain`` chains — and forever after (monotonic counter, so
    a killed replica stays killed across probe attempts)."""
    return (
        cfg.kills
        and replica == cfg.kill_replica
        and n_chains >= cfg.kill_at_chain
    )


def replica_stall_pending(cfg: FleetChaosConfig, replica: int,
                          n_chains: int, rounds_consumed: int) -> bool:
    """True while the configured replica should stay frozen: its chain
    counter passed ``stall_from_chain`` and fewer than ``stall_rounds``
    scheduling rounds have been skipped so far (the router counts the
    skips it performs and passes them back as ``rounds_consumed``)."""
    return (
        cfg.stalls
        and replica == cfg.stall_replica
        and n_chains >= cfg.stall_from_chain
        and rounds_consumed < cfg.stall_rounds
    )


# ---------------------------------------------------------------- device side


def poison_logits(logits, step_index, slot: int, step: int):
    """Return ``logits`` with row ``slot`` set to NaN when the traced
    ``step_index`` equals ``step`` — a ``jnp.where`` select, so the
    fault condition is data and the clean-step program is the same
    program. ``logits`` is the per-slot row block, shape
    ``(n_slots, ...)``; ``slot``/``step`` are Python ints from the
    config (compile-time constants)."""
    import jax.numpy as jnp

    poisoned = logits.at[slot].set(jnp.nan)
    return jnp.where(step_index == step, poisoned, logits)


def poison_grads(grads, step_counter, step: int):
    """Return ``grads`` with every leaf NaN when the traced training
    ``step_counter`` equals ``step`` (otherwise untouched). Lands after
    grad-accum averaging, so the skip-step guard sees exactly what a
    real non-finite reduction would produce."""
    import jax
    import jax.numpy as jnp

    def leaf(g):
        return jnp.where(step_counter == step, jnp.full_like(g, jnp.nan),
                         g)

    return jax.tree_util.tree_map(leaf, grads)


# ------------------------------------------------------------------ host side


def maybe_poison_batch(cfg: ChaosConfig, host_step: int, batch):
    """Return ``batch`` with its first leaf all-NaN when ``host_step``
    (the Trainer's 1-based, monotonic dispatch counter) matches
    ``nan_batch_step``; the batch unchanged otherwise. Elementwise
    multiply, so the leaf keeps its mesh sharding — the NaN flows
    forward into the loss/grads exactly as a corrupt data batch would,
    and the host key guarantees a single firing (see the class
    docstring's livelock note on ``nan_grad_step``)."""
    if not (cfg.poisons_batch and host_step == cfg.nan_batch_step):
        return batch
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree_util.tree_flatten(batch)
    leaves[0] = leaves[0] * jnp.nan
    return treedef.unflatten(leaves)


def maybe_fail_prefill(cfg: ChaosConfig, request_id: int) -> None:
    """Raise :class:`ChaosError` when ``request_id`` is the configured
    prefill victim. Called by the engine just before it dispatches the
    prefill/splice for a request."""
    if cfg.fails_prefill and request_id == cfg.fail_prefill_request:
        raise ChaosError(
            f"injected prefill failure for request {request_id}"
        )


def maybe_stall(cfg: ChaosConfig, chain_index: int, flight=None) -> None:
    """Sleep ``stall_s`` before the configured chain index — wall time
    passes (deadlines expire) with zero device-side effect, mimicking a
    launch stall. When a :class:`..obs.flight.FlightRecorder` rides
    along it stamps a ``stall`` event first, so the post-mortem timeline
    shows the gap as INJECTED rather than as a mystery launch stall."""
    if cfg.stalls and chain_index == cfg.stall_chain:
        if flight is not None:
            flight.record(
                "stall", chain=chain_index, stall_s=cfg.stall_s
            )
        time.sleep(cfg.stall_s)


def host_spike_loss(loss_value: float, host_step: int,
                    cfg: ChaosConfig) -> float:
    """The loss value the rollback monitor should see at ``host_step``
    (1-based, never replayed): spiked by ``spike_loss_factor`` inside
    the configured window, untouched outside it. Host-only — the
    compiled step and the real training state never see the spike."""
    if cfg.spikes_loss and (
        cfg.spike_loss_step
        <= host_step
        < cfg.spike_loss_step + cfg.spike_loss_len
    ):
        return float(loss_value) * cfg.spike_loss_factor
    return float(loss_value)
