"""Profiling: jax.profiler tracing around the hot loop.

The reference declares profilers (py-spy, memory-profiler,
``environment.yml:78-79``) but never uses them; its only timing is naive
``timeit`` (SURVEY.md section 5.1), which lies under XLA's async dispatch.
This module is the gap fix: :func:`trace` captures a real device trace
(XLA ops, ICI collectives, host callbacks) viewable in TensorBoard/Perfetto,
and :func:`annotate` marks host-side regions so loader/step boundaries show
up in the timeline.
"""

from __future__ import annotations

import contextlib
import os

import jax


@contextlib.contextmanager
def trace(logdir: str = "/tmp/jax-trace"):
    """Capture a device+host profiler trace of the enclosed region.

    Usage::

        with profiling.trace("/tmp/tr"):
            trainer.train(1)

    View with ``tensorboard --logdir /tmp/tr`` (or load the ``.trace.json.gz``
    in Perfetto). Wrap *steady-state* steps — the first step's compile time
    dominates a cold trace.
    """
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named host-side region for the trace timeline (context manager)."""
    return jax.profiler.TraceAnnotation(name)


def device_op_durations(logdir: str) -> dict[str, float]:
    """Aggregate on-device op durations (microseconds) from a trace dir.

    Parses the ``.trace.json.gz`` files :func:`trace` wrote, keeps only
    complete events on device lanes (``/device:TPU:*`` / GPU — host python
    frames are excluded), and sums duration per op name. This is the
    programmatic answer to "where did the step time actually go" — naive
    wall-clock timing of individual dispatches over-reports badly on
    remote/tunneled runtimes (measured up to ~60% on this build's TPU
    tunnel), while the device trace is ground truth. Used to find that the
    ResNet-18 train step is BatchNorm/elementwise-bound, not conv-bound.

    Returns ``{op_name: total_us}``, descending. Top-level module wrappers
    (``jit_*``) are included, so ``durations["jit_train_step(...)"] /
    num_calls`` gives honest per-step device time.
    """
    import collections
    import glob
    import gzip
    import json

    events: list[dict] = []
    for f in glob.glob(
        os.path.join(logdir, "**", "*.trace.json.gz"), recursive=True
    ):
        with gzip.open(f, "rt") as fh:
            events.extend(json.load(fh).get("traceEvents", []))
    pid_names = {
        e["pid"]: e["args"].get("name", "")
        for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    device_pids = {
        p
        for p, n in pid_names.items()
        if "/device:" in n or "TPU" in n or "GPU" in n
    }
    totals: collections.Counter = collections.Counter()
    if device_pids:
        for e in events:
            if (
                e.get("ph") == "X"
                and e.get("pid") in device_pids
                and "dur" in e
            ):
                totals[e.get("name", "?")] += e["dur"]
    else:
        # XLA:CPU (tests, virtual meshes): op events live on the host
        # process's executor threads, named "tf_XLA..."
        xla_threads = {
            (e["pid"], e["tid"])
            for e in events
            if e.get("ph") == "M"
            and e.get("name") == "thread_name"
            and e["args"].get("name", "").startswith("tf_XLA")
        }
        for e in events:
            if (
                e.get("ph") == "X"
                and (e.get("pid"), e.get("tid")) in xla_threads
                and "dur" in e
            ):
                totals[e.get("name", "?")] += e["dur"]
    return dict(totals.most_common())
