"""Profiling: jax.profiler tracing around the hot loop.

The reference declares profilers (py-spy, memory-profiler,
``environment.yml:78-79``) but never uses them; its only timing is naive
``timeit`` (SURVEY.md section 5.1), which lies under XLA's async dispatch.
This module is the gap fix: :func:`trace` captures a real device trace
(XLA ops, ICI collectives, host callbacks) viewable in TensorBoard/Perfetto,
and :func:`annotate` marks host-side regions so loader/step boundaries show
up in the timeline.
"""

from __future__ import annotations

import contextlib
import os

import jax


@contextlib.contextmanager
def trace(logdir: str = "/tmp/jax-trace"):
    """Capture a device+host profiler trace of the enclosed region.

    Usage::

        with profiling.trace("/tmp/tr"):
            trainer.train(1)

    View with ``tensorboard --logdir /tmp/tr`` (or load the ``.trace.json.gz``
    in Perfetto). Wrap *steady-state* steps — the first step's compile time
    dominates a cold trace.
    """
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named host-side region for the trace timeline (context manager)."""
    return jax.profiler.TraceAnnotation(name)
