"""Tiny pytree helpers shared across strategy/checkpoint modules.

Kept dependency-free (no orbax/flax imports) so hot-path modules can use it
without dragging in heavyweight packages.
"""

from __future__ import annotations


def keystr(key_path) -> str:
    """'block/attn/kernel'-style path string from a
    ``jax.tree_util.tree_map_with_path`` key path."""
    return "/".join(
        str(getattr(k, "key", getattr(k, "idx", k))) for k in key_path
    )


def device_materialize(tree):
    """Rewrite every array leaf as the OUTPUT of an on-device computation
    (a jitted exact identity: ``leaf + zeros((), dtype)``).

    Why this exists (measured, round 4 — DECODE_r04.md): checkpoint
    restores without an explicit sharding land leaves as HOST NUMPY
    (``parallel.auto.restore_leaf`` — by design, to keep host peak
    one-leaf-bounded), and jit re-uploads numpy arguments on EVERY call.
    On a PCIe host that is invisible; over the tunneled TPU's ~20 MB/s it
    made the 1.2B int8 serving tree pay ~16 s per generate() launch for
    ~0.14 s of device work. After this one-time pass the same launch took
    0.13 s, values bit-identical.

    Safe anywhere: a single fused launch for the whole tree, exact for
    every dtype (+0 in the leaf's own dtype), and jit's default sharding
    propagation preserves each leaf's placement (replicated or
    NamedSharding'd trees come back placed the same way). On non-tunneled
    runtimes it costs one pass of device memory bandwidth and changes
    nothing else. Non-array leaves pass through untouched.
    """
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    is_arr = [hasattr(l, "dtype") and hasattr(l, "ndim") for l in leaves]
    arrays = [l for l, a in zip(leaves, is_arr) if a]
    if arrays:
        arrays = jax.jit(
            lambda ls: [l + jnp.zeros((), l.dtype) for l in ls]
        )(arrays)
        arrays = jax.block_until_ready(arrays)
    it = iter(arrays)
    out = [next(it) if a else l for l, a in zip(leaves, is_arr)]
    return jax.tree_util.tree_unflatten(treedef, out)
