"""Tiny pytree helpers shared across strategy/checkpoint modules.

Kept dependency-free (no orbax/flax imports) so hot-path modules can use it
without dragging in heavyweight packages.
"""

from __future__ import annotations


def keystr(key_path) -> str:
    """'block/attn/kernel'-style path string from a
    ``jax.tree_util.tree_map_with_path`` key path."""
    return "/".join(
        str(getattr(k, "key", getattr(k, "idx", k))) for k in key_path
    )
