"""jax API-drift shims: one module owns every version seam.

The toolchain pins jax 0.4.37 while parts of the codebase target the
post-0.5 surface; each drift point below is a rename or addition that is
semantically identical across the line, so a thin adapter keeps every call
site on one spelling:

- ``shard_map`` moved from ``jax.experimental.shard_map`` to the top level.
- Its "skip the static output-replication check" flag was renamed
  ``check_rep`` (0.4.x, replication bookkeeping) -> ``check_vma`` (>= 0.5,
  varying-mesh-axes bookkeeping). Kernels whose outputs carry no such info
  (``pallas_call`` results, hand-rolled collectives) must disable it under
  either name.
- ``jax.lax.pcast(..., to="varying")`` (the explicit varying-axes tag for
  values entering a ``shard_map`` scan carry) does not exist before the vma
  machinery did; on older jax there is nothing to tag and the identity is
  the correct shim.

No jax arrays are created at import time (CLAUDE.md import-purity rule) —
``inspect.signature`` touches only Python metadata.
"""

from __future__ import annotations

import inspect

import jax

try:  # jax >= 0.5 re-exports shard_map at the top level
    from jax import shard_map as _shard_map
except ImportError:  # jax 0.4.x: experimental only
    from jax.experimental.shard_map import shard_map as _shard_map

_NOCHECK_KW = (
    {"check_vma": False}
    if "check_vma" in inspect.signature(_shard_map).parameters
    else {"check_rep": False}
)


def shard_map_nocheck(f, mesh, in_specs, out_specs):
    """``shard_map`` with replication/varying-axes checking disabled,
    whichever flag this jax spells it as. For bodies whose outputs carry no
    replication info the checker can follow (``pallas_call`` custom calls,
    unrolled ppermute rings)."""
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **_NOCHECK_KW
    )


def pcast_varying(tree, axis_names):
    """Tag ``tree`` as varying over ``axis_names`` where jax has the vma
    machinery (``jax.lax.pcast``, >= 0.6); identity on older jax, whose
    shard_map carries no varying-axes tags to reconcile."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is None:
        return tree
    return pcast(tree, tuple(axis_names), to="varying")
