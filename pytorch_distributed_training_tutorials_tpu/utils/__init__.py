"""Utilities: rank-0 logging, metrics formatting, pytree helpers."""

from pytorch_distributed_training_tutorials_tpu.utils.logging import (  # noqa: F401
    log0,
    epoch_line,
)
from pytorch_distributed_training_tutorials_tpu.utils.tree import (  # noqa: F401
    device_materialize,
)
