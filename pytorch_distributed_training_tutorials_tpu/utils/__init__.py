"""Utilities: rank-0 logging, metrics formatting."""

from pytorch_distributed_training_tutorials_tpu.utils.logging import (  # noqa: F401
    log0,
    epoch_line,
)
