"""Utilities: rank-0 logging, metrics formatting, pytree helpers, chaos.

The re-exports below are PEP 562 LAZY (same pattern as obs/ and serve/):
:mod:`.tree` imports jax, but :mod:`.chaos` is host-only by contract —
the fleet router's replica-level injectors must be importable on a
jax-less laptop (the subprocess pin in tests/test_prefix.py imports
``pytorch_distributed_training_tutorials_tpu.utils.chaos`` and asserts jax never loads), so the
package init must not eagerly drag :mod:`.tree` in.
"""

import importlib

# name -> submodule; resolved on first access via __getattr__.
_LAZY_EXPORTS = {
    "log0": "pytorch_distributed_training_tutorials_tpu.utils.logging",
    "epoch_line": "pytorch_distributed_training_tutorials_tpu.utils.logging",
    "device_materialize": "pytorch_distributed_training_tutorials_tpu.utils.tree",
}

__all__ = sorted(_LAZY_EXPORTS)


def __getattr__(name: str):
    try:
        module_name = _LAZY_EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    value = getattr(importlib.import_module(module_name), name)
    globals()[name] = value  # cache: __getattr__ runs once per name
    return value


def __dir__():
    return sorted(set(globals()) | set(_LAZY_EXPORTS))
