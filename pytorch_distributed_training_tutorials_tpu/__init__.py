"""TPU-native distributed-training framework and tutorial suite.

A brand-new JAX/XLA/pjit implementation of the capabilities exercised by the
reference tutorial suite ``duoan/pytorch_distributed_training_tutorials``
(see /root/repo/SURVEY.md for the full structural analysis):

- process-group bootstrap / rendezvous   -> :mod:`.parallel.distributed`
- device-mesh construction               -> :mod:`.parallel.mesh`
- sharded data loading (DistributedSampler semantics) -> :mod:`.data`
- SPMD data-parallel Trainer (DP + DDP twin)          -> :mod:`.train`
- manual + pipeline model parallelism                 -> :mod:`.parallel.pipeline`
- auto placement / sharded checkpoint restore         -> :mod:`.parallel.auto`
- models (MLP, ResNet-18/50) and utilities            -> :mod:`.models`
- benchmark harness                                   -> :mod:`.bench`

Design stance (SURVEY.md section 7): the reference's three distinct parallelism
APIs (nn.DataParallel, DistributedDataParallel, manual ``.to(device)`` splits)
collapse into one mesh + sharding abstraction with three configurations. The
observable semantics of the reference are preserved: per-device batch-size flag
meaning, steps-per-epoch math, epoch-seeded reshuffle, rank-0 logging, the
2-stage split, and the benchmark comparison.
"""

__version__ = "0.1.0"

from pytorch_distributed_training_tutorials_tpu.parallel.mesh import (  # noqa: F401
    create_mesh,
    DATA_AXIS,
    MODEL_AXIS,
    STAGE_AXIS,
    SEQ_AXIS,
)
from pytorch_distributed_training_tutorials_tpu.parallel.distributed import (  # noqa: F401
    init,
    shutdown,
    process_index,
    process_count,
)
