"""TPU-native distributed-training framework and tutorial suite.

A brand-new JAX/XLA/pjit implementation of the capabilities exercised by the
reference tutorial suite ``duoan/pytorch_distributed_training_tutorials``
(see /root/repo/SURVEY.md for the full structural analysis):

- process-group bootstrap / rendezvous   -> :mod:`.parallel.distributed`
- device-mesh construction               -> :mod:`.parallel.mesh`
- sharded data loading (DistributedSampler semantics) -> :mod:`.data`
- SPMD data-parallel Trainer (DP + DDP twin)          -> :mod:`.train`
- manual + pipeline model parallelism                 -> :mod:`.parallel.pipeline`
- auto placement / sharded checkpoint restore         -> :mod:`.parallel.auto`
- models (MLP, ResNet-18/50) and utilities            -> :mod:`.models`
- benchmark harness                                   -> :mod:`.bench`
- static invariant enforcement (graftcheck)           -> :mod:`.analysis`

Design stance (SURVEY.md section 7): the reference's three distinct parallelism
APIs (nn.DataParallel, DistributedDataParallel, manual ``.to(device)`` splits)
collapse into one mesh + sharding abstraction with three configurations. The
observable semantics of the reference are preserved: per-device batch-size flag
meaning, steps-per-epoch math, epoch-seeded reshuffle, rank-0 logging, the
2-stage split, and the benchmark comparison.

The top-level conveniences are PEP 562 lazy re-exports: importing this
package does not import jax. That keeps ``python -m
pytorch_distributed_training_tutorials_tpu.analysis`` (graftcheck) jax-free end to end, and is
one more layer of the import-purity hard rule — nothing can compute at
import time if nothing jax-flavored is even imported.
"""

import importlib

__version__ = "0.1.0"

# name -> (module, attribute); resolved on first access via __getattr__.
_LAZY_EXPORTS = {
    "create_mesh": ("pytorch_distributed_training_tutorials_tpu.parallel.mesh", "create_mesh"),
    "DATA_AXIS": ("pytorch_distributed_training_tutorials_tpu.parallel.mesh", "DATA_AXIS"),
    "MODEL_AXIS": ("pytorch_distributed_training_tutorials_tpu.parallel.mesh", "MODEL_AXIS"),
    "STAGE_AXIS": ("pytorch_distributed_training_tutorials_tpu.parallel.mesh", "STAGE_AXIS"),
    "SEQ_AXIS": ("pytorch_distributed_training_tutorials_tpu.parallel.mesh", "SEQ_AXIS"),
    "init": ("pytorch_distributed_training_tutorials_tpu.parallel.distributed", "init"),
    "shutdown": ("pytorch_distributed_training_tutorials_tpu.parallel.distributed", "shutdown"),
    "process_index": ("pytorch_distributed_training_tutorials_tpu.parallel.distributed", "process_index"),
    "process_count": ("pytorch_distributed_training_tutorials_tpu.parallel.distributed", "process_count"),
}

__all__ = ["__version__", *_LAZY_EXPORTS]


def __getattr__(name: str):
    try:
        module_name, attr = _LAZY_EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    value = getattr(importlib.import_module(module_name), attr)
    globals()[name] = value  # cache: __getattr__ runs once per name
    return value


def __dir__():
    return sorted(set(globals()) | set(_LAZY_EXPORTS))
