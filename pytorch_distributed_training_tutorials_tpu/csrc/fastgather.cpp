// fastgather: multithreaded host-side batch assembly for the input pipeline.
//
// The TPU input path is host RAM -> local HBM; the host-side cost per step is
// one row gather per dataset array (loader.py's batch assembly, the twin of
// the reference DataLoader's collate). numpy's fancy indexing is
// single-threaded; this library splits the row copies across threads, which
// matters once row_bytes * rows approaches tens of MB per step (ImageNet-size
// batches), keeping the host from becoming the bottleneck that pin_memory
// workers address in the reference's stack (ddp_gpus.py:75).
//
// Pure C ABI (loaded via ctypes, see data/native.py) — no Python.h, no numpy
// headers, so it builds with a bare g++ anywhere.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// dst[i, :] = src[indices[i], :] for i in [0, n_rows).
// row_bytes is the byte size of one row; indices must be in-range (the
// Python wrapper validates). n_threads <= 0 selects hardware concurrency.
void fg_gather_rows(const char* src, const int64_t* indices, char* dst,
                    int64_t n_rows, int64_t row_bytes, int32_t n_threads) {
  if (n_rows <= 0 || row_bytes <= 0) return;
  int nt = n_threads > 0
               ? n_threads
               : static_cast<int>(std::thread::hardware_concurrency());
  const int64_t total_bytes = n_rows * row_bytes;
  // below ~4MB thread spawn overhead beats the memcpy win
  if (nt <= 1 || total_bytes < (4LL << 20)) {
    for (int64_t i = 0; i < n_rows; ++i)
      std::memcpy(dst + i * row_bytes, src + indices[i] * row_bytes,
                  row_bytes);
    return;
  }
  nt = static_cast<int>(std::min<int64_t>(nt, n_rows));
  const int64_t chunk = (n_rows + nt - 1) / nt;
  std::vector<std::thread> threads;
  threads.reserve(nt);
  for (int t = 0; t < nt; ++t) {
    const int64_t lo = t * chunk;
    const int64_t hi = std::min(n_rows, lo + chunk);
    if (lo >= hi) break;
    threads.emplace_back([src, indices, dst, row_bytes, lo, hi] {
      for (int64_t i = lo; i < hi; ++i)
        std::memcpy(dst + i * row_bytes, src + indices[i] * row_bytes,
                    row_bytes);
    });
  }
  for (auto& th : threads) th.join();
}

}  // extern "C"
