"""Host-side radix prefix index: token-id prefixes -> retained KV segments.

Real request streams share massive prompt prefixes (system prompts,
few-shot headers, multi-turn history), and prefill is the one place the
continuous-batching engine still re-does work per request. This module is
the host half of the fix — the TPU/fixed-shape analogue of vLLM's shared
prefix blocks (SOSP '23): instead of paging the KV cache into shareable
blocks (XLA wants one compiled program over static shapes), whole
prefilled cache SEGMENTS are retained on device and a new request that
shares a prefix is seeded by one ``dynamic_update_slice`` splice plus a
prefill over only the uncached suffix (:meth:`..serve.engine.ServeEngine`
``_splice_fn``).

Like :mod:`.scheduler`, this file is deliberately jax-free (pinned by a
subprocess test, the same discipline the scheduler pins): segment handles
are OPAQUE to the index — it never inspects them, it only keeps them
alive. Byte sizes are computed by the caller (``slots.tree_nbytes``) from
leaf metadata, so accounting never touches the device.

Correctness facts the index leans on (established by
tests/test_transformer.py::test_chunked_decode_matches_full_prefill and
the masked-attention exactness note in models/transformer.py):

- K/V at position ``i`` depends only on tokens ``[0, i]``, so every
  segment whose key starts with the same ``d`` tokens carries IDENTICAL
  cache content on ``[0, d)`` — any segment in the matched trie subtree
  is a valid donor at the matched depth;
- segment content at positions ``>= d`` is stale for the new request but
  is overwritten by the suffix prefill (stores precede attention reads)
  or masked by the per-slot validity row, so it is never read.

Hence the radix structure: one trie over token ids, each stored segment
terminal at its key, each node counting the segments in its subtree so
longest-prefix-match is a single walk (descend while a child exists —
every resident node has count >= 1 — then surface any segment below).
"""

from __future__ import annotations

import collections
from typing import Any, Iterator, Sequence


class Segment:
    """One retained prefix: ``key`` (token-id tuple) -> ``handle`` (an
    opaque device cache tree, seq-sliced to ``bucket_len(len(key))`` by
    the engine). ``refcount`` pins the segment against LRU eviction while
    slots it seeded are in flight (:meth:`PrefixIndex.acquire` /
    :meth:`~PrefixIndex.release`)."""

    __slots__ = ("key", "handle", "nbytes", "refcount")

    def __init__(self, key: tuple[int, ...], handle: Any, nbytes: int):
        self.key = key
        self.handle = handle
        self.nbytes = int(nbytes)
        self.refcount = 0

    def __repr__(self) -> str:  # debugging aid only
        return (f"Segment(len={len(self.key)}, nbytes={self.nbytes}, "
                f"refcount={self.refcount})")


class _Node:
    """One trie node. ``count`` = segments terminal at or below this node
    (nodes are pruned at 0, so every resident node has ``count >= 1``)."""

    __slots__ = ("children", "count", "segment")

    def __init__(self):
        self.children: dict[int, _Node] = {}
        self.count = 0
        self.segment: Segment | None = None


class PrefixIndex:
    """Radix/trie prefix index with LRU eviction under a byte budget.

    - :meth:`insert` — insert-on-prefill: retain a segment keyed by its
      full token prefix; evicts least-recently-used UNPINNED segments
      until the new one fits (refuses, returning ``False``, when pinned
      segments leave no room — never evicts under a live refcount).
    - :meth:`lookup` — longest-prefix-match at pop time: the deepest
      resident trie node reachable through ``query[: len(query) - 1]``
      (at least one suffix token must always run — its logits sample the
      request's first token), returning ``(depth, segment)`` for any
      segment in that subtree. Refreshes the segment's LRU position.
    - :meth:`acquire` / :meth:`release` — refcount pin while a slot
      decodes from a splice of the segment. The engine acquires before
      splicing and releases at completion/parking, so eviction can only
      happen BETWEEN chains (inserts happen only during slot refill),
      never under a slot mid-decode.

    The index is pure host bookkeeping: dropping a ``Segment`` simply
    drops the last Python reference to its device tree; the runtime frees
    the buffers. ``evicted_bytes`` / ``hits`` / ``misses`` feed the
    serving receipt.
    """

    def __init__(self, byte_budget: int, on_evict=None):
        if byte_budget < 1:
            raise ValueError("byte_budget must be >= 1")
        self.byte_budget = int(byte_budget)
        # eviction hook, called with the Segment BEFORE its handle is
        # cleared — the paged engine (ISSUE 13) uses it to release the
        # segment's page refcounts back to the pool; the index itself
        # stays jax-free and handle-agnostic
        self._on_evict = on_evict
        self._root = _Node()
        # key -> Segment, in LRU order (front = coldest)
        self._lru: collections.OrderedDict[tuple[int, ...], Segment] = (
            collections.OrderedDict()
        )
        self.used_bytes = 0
        self.evicted_bytes = 0
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        return len(self._lru)

    def __contains__(self, key: Sequence[int]) -> bool:
        return tuple(key) in self._lru

    # ------------------------------------------------------------------
    # core operations
    # ------------------------------------------------------------------

    def insert(self, key: Sequence[int], handle: Any, nbytes: int) -> bool:
        """Retain ``handle`` under ``key``; returns whether it was stored.

        An existing identical key is refreshed (moved hot), NOT replaced —
        both trees carry the same cache content (K/V at position ``i``
        depends only on tokens ``[0, i]``), so the resident one wins and
        the caller's copy is dropped. Returns ``False`` without storing
        when ``nbytes`` exceeds the budget even after evicting every
        unpinned segment."""
        key = tuple(int(t) for t in key)
        if not key:
            raise ValueError("key must contain at least one token")
        if key in self._lru:
            self._lru.move_to_end(key)
            return False
        if not self._make_room(int(nbytes)):
            return False
        seg = Segment(key, handle, nbytes)
        node = self._root
        node.count += 1
        for tok in key:
            node = node.children.setdefault(tok, _Node())
            node.count += 1
        node.segment = seg
        self._lru[key] = seg
        self.used_bytes += seg.nbytes
        return True

    def lookup(
        self, query: Sequence[int], min_depth: int = 1
    ) -> tuple[int, Segment] | None:
        """Longest-prefix-match of ``query`` against the resident keys.

        Returns ``(depth, segment)`` — reuse the segment's cache content
        on ``[0, depth)`` — or ``None`` below ``min_depth``. ``depth`` is
        capped at ``len(query) - 1`` so at least one suffix token always
        prefills (its logits sample the first generated token). The
        returned segment's key shares the query's first ``depth`` tokens
        (it lies in the matched node's subtree) and is at least ``depth``
        long, so its cache covers every reused position."""
        node = self._root
        depth = 0
        for tok in query[: len(query) - 1]:
            child = node.children.get(int(tok))
            if child is None:
                break
            node = child
            depth += 1
        if depth < max(1, int(min_depth)):
            self.misses += 1
            return None
        seg = self._first_segment(node)
        self._lru.move_to_end(seg.key)
        self.hits += 1
        return depth, seg

    def acquire(self, segment: Segment) -> None:
        """Pin ``segment`` against eviction (a slot is decoding from its
        splice); also refreshes its LRU position."""
        segment.refcount += 1
        if segment.key in self._lru:
            self._lru.move_to_end(segment.key)

    def release(self, segment: Segment) -> None:
        """Drop one pin. A released-to-zero segment becomes evictable
        again (it is NOT removed — it stays hot for the next hit)."""
        if segment.refcount <= 0:
            raise ValueError("release() without matching acquire()")
        segment.refcount -= 1

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------

    def _make_room(self, nbytes: int) -> bool:
        """Evict cold unpinned segments until ``nbytes`` fits the budget;
        False when pinned segments make that impossible."""
        if nbytes > self.byte_budget:
            return False
        while self.used_bytes + nbytes > self.byte_budget:
            victim = next(
                (s for s in self._lru.values() if s.refcount == 0), None
            )
            if victim is None:
                return False
            self._evict(victim)
        return True

    def evict_coldest(self) -> bool:
        """Evict the coldest UNPINNED segment, if any; returns whether
        one was evicted. The paged engine calls this under page-pool
        pressure (a queued request needs pages and the pool is dry but
        cold segments still hold refcounts) — repeated calls terminate
        because every eviction removes a segment."""
        victim = next(
            (s for s in self._lru.values() if s.refcount == 0), None
        )
        if victim is None:
            return False
        self._evict(victim)
        return True

    def _evict(self, seg: Segment) -> None:
        if self._on_evict is not None:
            self._on_evict(seg)
        del self._lru[seg.key]
        node = self._root
        node.count -= 1
        path = []
        for tok in seg.key:
            path.append((node, tok))
            node = node.children[tok]
            node.count -= 1
        node.segment = None
        for parent, tok in reversed(path):
            if parent.children[tok].count == 0:
                del parent.children[tok]
        self.used_bytes -= seg.nbytes
        self.evicted_bytes += seg.nbytes
        seg.handle = None  # drop the device tree reference eagerly

    def _first_segment(self, node: _Node) -> Segment:
        """Any segment terminal at or below ``node`` (count >= 1
        guarantees one exists — nodes prune at 0)."""
        while node.segment is None:
            node = next(iter(node.children.values()))
        return node.segment

    # ------------------------------------------------------------------
    # introspection (receipts / tests)
    # ------------------------------------------------------------------

    def segments(self) -> Iterator[Segment]:
        """Resident segments, coldest first."""
        return iter(self._lru.values())

    def stats(self) -> dict[str, int]:
        return {
            "segments": len(self._lru),
            "used_bytes": self.used_bytes,
            "evicted_bytes": self.evicted_bytes,
            "hits": self.hits,
            "misses": self.misses,
        }
