"""Slot-indexed KV-cache state: the device side of continuous batching.

The engine serves ``n_slots`` concurrent requests out of ONE fixed-shape
cache tree whose batch axis is the slot axis — the TPU-native analogue of
vLLM's block-managed cache (SOSP '23): XLA wants one compiled program over
static shapes, so instead of paging, every request is given a whole
fixed-size slot and finished slots are REFILLED in place
(``dynamic_update_slice`` of a freshly prefilled K/V block plus a per-slot
position reset) without recompiling anything.

Three pieces live here:

- :func:`init_slot_state` — build the zeroed slot-state pytree from the
  model's own cache schema (``jax.eval_shape``: no FLOPs, no buffers until
  the zeros are actually created), with ``cache_index`` widened from the
  scalar ``generate()`` layout to a ``(n_slots,)`` vector so each slot
  decodes at its own depth (``models/transformer.py`` branches on the
  trace-time rank);
- :func:`bucket_len` — prompt-length buckets (powers of two, floor 8) so
  prefill compiles once per bucket instead of once per prompt length;
- :func:`write_slot` — the refill: one traced tree-surgery pass that
  splices a batch-1 prefill cache into slot ``s`` of the big cache and
  resets that slot's position counter, inside whatever jit it is called
  from (slot index and prompt length are traced scalars — no recompile
  per slot or per length);
- :func:`extract_segment` / :func:`seed_cache` / :func:`tree_nbytes` —
  the device half of the prefix cache (:mod:`.prefix`): cut a retained
  prefix segment out of a batch-1 prefilled cache (static bucket length
  on the sequence axis, so segment shapes reuse the pow2 bucket set and
  splices never recompile per prompt), seed a fresh batch-1 cache from
  one, and size a segment host-side from leaf metadata (no device
  fetch — the index's byte accounting must not break the engine's
  one-fetch-per-chain budget).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def bucket_len(p_len: int, window: int, floor: int = 8) -> int:
    """Static prefill length for a ``p_len``-token prompt: the next power
    of two >= ``p_len`` (>= ``floor``, TPU-sublane-friendly), capped at the
    serving window. Prompts are right-padded to the bucket; causal
    attention makes positions ``[0, p_len)`` independent of the padding
    tail, and the next-token logits are gathered at ``p_len - 1``
    (``TransformerLM.__call__(last_pos=...)``), so bucketing changes
    compile-cache hit rate, never results."""
    if p_len < 1:
        raise ValueError("p_len must be >= 1")
    b = floor
    while b < p_len:
        b *= 2
    return min(b, window)


def init_slot_state(model, params, n_slots: int, history: int = 0,
                    adapters: bool = False, paged: int = 0,
                    strategy=None):
    """Zero-initialized slot-state pytree for ``n_slots`` concurrent
    requests of ``model`` (a :class:`..models.transformer.TransformerLM`
    or anything sharing its cache contract).

    The cache schema comes from the model itself via ``jax.eval_shape`` of
    a decode apply — zero FLOPs, zero device buffers — so GQA, int8 KV
    scales, and ``scan_layers``-stacked leaves are all picked up without
    this module knowing their shapes. ``cache_index`` leaves (scalar per
    layer in the ``generate()`` layout; ``(L,)`` stacked under
    ``nn.scan``) grow a trailing ``(n_slots,)`` axis — the per-slot
    position counters.

    Returns ``{"cache", "last_tok", "keys", "remaining"}``:
    ``last_tok`` ``(S,)`` int32 — each slot's most recent token (the next
    decode input); ``keys`` ``(S, 2)`` uint32 — per-slot PRNG streams
    (:func:`..models.sampling.sample_logits_per_slot`); ``remaining``
    ``(S,)`` int32 — tokens still to generate, 0 = slot free/parked (the
    active mask is ``remaining > 0``).

    ``history > 0`` (the engine passes its window when speculate-k is on)
    adds the per-slot recent-token buffer the on-device n-gram draft
    feeds on (:func:`..models.sampling.ngram_draft`): ``hist`` ``(S,
    history)`` int32 — each slot's known tokens, prompt + emitted, junk
    beyond ``hist_len`` — and ``hist_len`` ``(S,)`` int32. Both are
    reseeded at refill and carried through the decode chain, so drafting
    never costs a host round-trip. Speculation off keeps the state tree
    (and therefore every compiled program) byte-identical to the
    pre-speculation engine.

    ``adapters=True`` (the engine passes it when an adapter bank is
    attached) adds ``adapter_ids`` ``(S,)`` int32 — each slot's LoRA bank
    row, set at prefill/splice and carried through the chain as the
    per-row gather index of :func:`..adapters.bank.apply_lora`. Same
    off-state contract as speculation: adapters off keeps the state tree
    byte-identical.

    ``paged`` (the pool's ``pool_pages``, 0 = off) builds the state for a
    PAGED model (``TransformerConfig(kv_pages=..., kv_page_size=...)``):
    the model's own schema already declares the shared pools, the
    ``(n_slots, P)`` page tables, and per-row ``(n_slots,)`` position
    counters — no widening needed — and every ``page_table`` leaf is
    filled with the sentinel id ``paged`` (== ``kv_pages``, out of
    range), so an unbacked slot's decode writes DROP instead of
    corrupting pool pages (see ``models/transformer.py
    _store_paged_kv``).

    ``strategy`` (a :class:`..parallel.tensor_parallel.TensorParallel`
    with ``tp_size > 1``; ISSUE 15) places the finished tree per the
    strategy's slot rules — K/V (and pool) leaves head-sharded to match
    the attention split, bookkeeping replicated. Committed sharded
    inputs are what make the engine's jits compile GSPMD-sharded decode
    programs; ``strategy=None`` (or tp 1) leaves placement untouched,
    byte-identical to the pre-sharding builder.
    """
    if n_slots < 1:
        raise ValueError("n_slots must be >= 1")

    def cache_shape(p, t):
        return model.apply(
            {"params": p}, t, decode=True, mutable=["cache"]
        )[1]["cache"]

    shapes = jax.eval_shape(
        cache_shape, params, jnp.zeros((n_slots, 1), jnp.int32)
    )

    def build(path, leaf):
        if _leaf_name(path) == "cache_index":
            if paged:
                # the paged schema already declares (S,) / (L, S)
                return jnp.zeros(leaf.shape, jnp.int32)
            # () -> (S,), or (L,) -> (L, S) under scan_layers
            return jnp.zeros(leaf.shape + (n_slots,), jnp.int32)
        if _leaf_name(path) == "page_table":
            return jnp.full(leaf.shape, paged, jnp.int32)
        return jnp.zeros(leaf.shape, leaf.dtype)

    state = {
        "cache": jax.tree_util.tree_map_with_path(build, shapes),
        "last_tok": jnp.zeros((n_slots,), jnp.int32),
        "keys": jnp.zeros((n_slots, 2), jnp.uint32),
        "remaining": jnp.zeros((n_slots,), jnp.int32),
    }
    if history > 0:
        state["hist"] = jnp.zeros((n_slots, history), jnp.int32)
        state["hist_len"] = jnp.zeros((n_slots,), jnp.int32)
    if adapters:
        state["adapter_ids"] = jnp.zeros((n_slots,), jnp.int32)
    if strategy is not None and getattr(strategy, "tp_size", 1) > 1:
        state = strategy.shard_slot_state(state)
    return state


def write_slot(cache, prefill_cache, slot, p_len, scan_layers: bool):
    """Splice a batch-1 prefilled cache into slot ``slot`` of the big
    slot-indexed ``cache`` and reset that slot's position to ``p_len`` —
    the refill that lets a finished slot host a new request without
    recompiling the decode program.

    ``slot`` and ``p_len`` may be traced scalars (they are, inside the
    engine's jitted prefill). K/V (and int8 scale) leaves update by
    ``dynamic_update_slice`` along the slot axis — axis 0, or axis 1 under
    ``scan_layers`` where every leaf carries a leading layer axis; the
    rank alone cannot distinguish the two layouts (a scanned int8 scale
    and an unrolled K/V block are both rank 4), hence the explicit flag.
    ``cache_index`` leaves set position ``slot`` on their trailing slot
    axis. Bucket padding beyond ``p_len`` carries garbage K/V; it is
    masked by the per-slot validity row until the decode writes of this
    very request overwrite it (positions advance from ``p_len``), so it
    is never read.
    """

    def upd(path, big, pre):
        if _leaf_name(path) == "cache_index":
            return big.at[..., slot].set(jnp.asarray(p_len, big.dtype))
        start = (0, slot) if scan_layers else (slot,)
        start = start + (0,) * (big.ndim - len(start))
        return jax.lax.dynamic_update_slice(
            big, pre.astype(big.dtype), start
        )

    return jax.tree_util.tree_map_with_path(upd, cache, prefill_cache)


# pool-leaf name -> the flat (unpaged) cache leaf it is filled from: the
# engine prefills through the UNPAGED model (classic whole-window batch-1
# cache), then write_slot_paged scatters that cache into the shared pools.
# Quantized KV reuses the same four names for BOTH families (int8 storage
# + f32 scales, and ISSUE 17's int4 packed-nibble uint8 storage + bf16
# scales — models/transformer.py _kv_storage): only dtypes and the packed
# head_dim change, so this map, the seq-axis reshape in write_slot_paged,
# and parallel.SLOT_STATE_RULES cover int4 without a new case.
_POOL_TO_FLAT = {
    "paged_key": "cached_key",
    "paged_value": "cached_value",
    "paged_key_scale": "cached_key_scale",
    "paged_value_scale": "cached_value_scale",
}


def _path_strs(path) -> tuple:
    """tree_map_with_path key path as a tuple of plain strings."""
    return tuple(
        str(getattr(k, "key", getattr(k, "idx", k))) for k in path
    )


def write_slot_paged(cache, prefill_cache, row, slot, p_len,
                     page_size: int, scan_layers: bool):
    """Paged refill: scatter a batch-1 UNPAGED prefilled cache into the
    page-pool ``cache`` at the page ids of ``row``, install ``row`` as
    slot ``slot``'s page table, and reset its position to ``p_len`` —
    the paged twin of :func:`write_slot`.

    ``row`` is the slot's full ``(P,)`` int32 page-table vector: freshly
    allocated ids for the pages the request backs, the sentinel
    (``kv_pages``) beyond. The prefill cache is full-window (prefill
    zero-inits ``(1, max_seq_len, ...)`` and writes ``[0, bucket)``), so
    reshaping its sequence axis to ``(P, page_size)`` yields every
    logical page; the ``mode="drop"`` scatter writes the allocated ones
    WHOLE — which doubles as the pool's sanitizer: any junk a previous
    holder's in-flight chain wrote into a recycled page is fully
    overwritten before this slot's first read (the engine dispatches the
    refill AFTER any chain still holding the old table — device program
    order). Sentinel rows drop. ``slot``/``p_len``/``row`` may be traced
    (they are, inside the engine's jitted paged prefill) — no recompile
    per slot, per length, or per page assignment."""
    flat = {
        _path_strs(p): leaf
        for p, leaf in jax.tree_util.tree_leaves_with_path(prefill_cache)
    }

    def upd(path, big):
        name = _leaf_name(path)
        if name == "page_table":
            return big.at[..., slot, :].set(jnp.asarray(row, big.dtype))
        if name == "cache_index":
            return big.at[..., slot].set(jnp.asarray(p_len, big.dtype))
        src = flat[_path_strs(path)[:-1] + (_POOL_TO_FLAT[name],)]
        if scan_layers:
            # (L, 1, W, ...) -> (L, P, page_size, ...)
            pages = src.reshape(
                (src.shape[0], -1, page_size) + src.shape[3:]
            )
            return big.at[:, row].set(pages.astype(big.dtype), mode="drop")
        # (1, W, ...) -> (P, page_size, ...)
        pages = src.reshape((-1, page_size) + src.shape[2:])
        return big.at[row].set(pages.astype(big.dtype), mode="drop")

    return jax.tree_util.tree_map_with_path(upd, cache)


def extract_segment(cache, seg_len: int, scan_layers: bool):
    """Cut the first ``seg_len`` sequence positions out of a batch-1
    prefilled ``cache`` tree — the retained prefix segment the radix
    index (:mod:`.prefix`) keeps alive, and since ISSUE 18 also the
    transfer payload of a prefill/decode handoff: a ``role="prefill"``
    engine cuts the prompt's whole pow2 bucket here and ships it as
    ``Handoff.segment`` (device resident, never fetched); the decode
    replica's accept replays the :func:`seed_cache` + :func:`write_slot`
    splice surgery, so the transplant is bitwise the monolithic
    post-prefill slot state.

    ``seg_len`` is STATIC (a pow2 ``bucket_len`` of the prefix length):
    segment shapes come from the same bucket set prefill compiles
    against, so a splice over any retained segment hits an existing
    compile instead of minting one per prompt length. The sequence axis
    is 1, or 2 under ``scan_layers`` (leading layer axis) — same layout
    rule as :func:`write_slot`. ``cache_index`` leaves pass through
    untouched; their value is dead weight (a handful of int32s) that
    :func:`seed_cache` overwrites with the matched depth. Positions in
    ``[real prefix, seg_len)`` hold bucket-padding garbage — safe because
    a consumer only reuses ``[0, depth)`` with ``depth <= real prefix``
    and overwrites/masks everything beyond (see :mod:`.prefix`)."""
    ax = 2 if scan_layers else 1

    def cut(path, leaf):
        if _leaf_name(path) == "cache_index":
            return leaf
        sl = [slice(None)] * leaf.ndim
        sl[ax] = slice(0, seg_len)
        return leaf[tuple(sl)]

    return jax.tree_util.tree_map_with_path(cut, cache)


def seed_cache(proto, segment, depth):
    """Build a batch-1 full-window cache whose ``[0, seg_len)`` positions
    come from a retained ``segment`` and whose position counters read
    ``depth`` — the device-side start state of a prefix-cache hit: the
    suffix prefill then continues from position ``depth`` exactly as if
    positions ``[0, depth)`` had just been prefilled (bit-equal for
    full-precision caches, tests/test_transformer.py pins it).

    ``proto`` is a shape/dtype pytree of the batch-1 decode cache (the
    engine evals it once at construction); ``depth`` may be traced. The
    segment lands at the tree origin (it IS the leading seq chunk, on
    every layout — unrolled, scanned, int8 scales), so one origin
    ``dynamic_update_slice`` per leaf covers all of them."""

    def seed(path, p, seg):
        if _leaf_name(path) == "cache_index":
            return jnp.full(p.shape, depth, jnp.int32)
        z = jnp.zeros(p.shape, p.dtype)
        return jax.lax.dynamic_update_slice(
            z, seg.astype(p.dtype), (0,) * z.ndim
        )

    return jax.tree_util.tree_map_with_path(seed, proto, segment)


def zero_cache(proto):
    """Zeroed batch-1 full-window cache from a shape/dtype ``proto`` —
    the start state of a from-scratch CHUNKED prefill (ISSUE 11):
    ``cache_index`` reads 0, so the first chunk's decode continuation
    writes from position 0 exactly as a whole prefill would, and every
    later chunk continues where the previous one stopped (the same
    bitwise-equal continuation :func:`seed_cache` splices rely on, just
    starting at depth 0)."""

    def z(path, p):
        if _leaf_name(path) == "cache_index":
            return jnp.zeros(p.shape, jnp.int32)
        return jnp.zeros(p.shape, p.dtype)

    return jax.tree_util.tree_map_with_path(z, proto)


def tree_nbytes(tree) -> int:
    """Total bytes of a pytree's array leaves, from shape/dtype metadata
    only — works on concrete arrays AND ``jax.eval_shape`` structs, and
    never touches the device (the prefix index budgets bytes without
    spending a host fetch)."""
    return sum(
        math.prod(leaf.shape) * jnp.dtype(leaf.dtype).itemsize
        for leaf in jax.tree_util.tree_leaves(tree)
    )


def tree_nbytes_sharded(tree) -> int:
    """Per-DEVICE bytes of a pytree's array leaves: each leaf priced at
    its shard shape (``sharding.shard_shape``) instead of its global
    shape, so a head-sharded KV segment on a tp-wide mesh costs
    ``1/tp`` of its global bytes — the honest per-chip HBM claim
    (ISSUE 15). Falls back to global shape for leaves without a
    concrete sharding (eval_shape structs, plain numpy), making it a
    drop-in for :func:`tree_nbytes` on replicated trees. Metadata only —
    never a device fetch."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        sharding = getattr(leaf, "sharding", None)
        shape = (
            sharding.shard_shape(leaf.shape)
            if sharding is not None else leaf.shape
        )
        total += math.prod(shape) * jnp.dtype(leaf.dtype).itemsize
    return total


def _leaf_name(path) -> str:
    """Last key of a tree_map_with_path key path, as a plain string."""
    k = path[-1]
    return str(getattr(k, "key", getattr(k, "idx", k)))
