"""Continuous-batching serving engine: one compiled decode program,
``n_slots`` concurrent requests, launch-amortized chains.

The reference's serving story stops at loading Llama-7B for placement
(``/root/reference/03.model_parallel.ipynb`` cell 2 — never generates a
token; SURVEY.md section 5.7), and this repo's own ``generate()`` is
one-shot batch inference: every request in the batch waits for the whole
batch, and nobody new can join until the loop drains. This module is the
Orca-style (OSDI '22) fix, built TPU-native:

- ONE jitted decode program over a fixed ``(n_slots, ...)`` slot-indexed
  KV cache (:mod:`.slots`); requests at different depths decode together,
  each slot carrying its own position counter and active mask
  (``remaining > 0``);
- decode runs in CHAINS of ``tokens_per_launch`` steps per dispatch
  (``lax.scan``, one launch + ONE batched ``jax.device_get`` for the
  whole chain) because the floor on the tunneled runtime is per LAUNCH,
  ~75-130 ms, regardless of how much work the launch carries (CLAUDE.md)
  — per-token host syncs would be two orders of magnitude slower than
  the device math;
- finished slots are refilled in place by a jitted prefill-into-slot
  (bucketed prompt lengths, :func:`.slots.bucket_len`; splice + position
  reset, :func:`.slots.write_slot`) — no recompile per request, per
  prompt length (beyond the bucket set), or per slot;
- with ``prefix_cache_bytes > 0``, refill first consults a host-side
  radix index (:class:`.prefix.PrefixIndex`, the vLLM SOSP '23
  shared-prefix idea rebuilt for fixed shapes): a longest-prefix-match
  seeds the slot from a RETAINED device cache segment
  (:func:`.slots.seed_cache` + the same ``write_slot`` surgery) and
  prefills only the uncached suffix through the decode path's chunked
  continuation (``models/transformer.py`` ``_store_decode_kv``) — a
  deep hit turns an O(prompt) prefill into an O(suffix) one. Segment
  and suffix lengths reuse the pow2 bucket set, so the prefix cache
  adds a bounded set of compiles, and greedy token-exactness is
  preserved BITWISE for full-precision caches
  (tests/test_transformer.py pins the chunk-vs-prefill equality,
  tests/test_serve.py the end-to-end cache-on-vs-off stream);
- sampling is the SAME pipeline ``generate()`` uses
  (:mod:`..models.sampling`), vmapped over per-slot PRNG streams: a
  request's draws depend only on its own ``seed`` and draw index, never
  on co-scheduling;
- with ``speculative_k > 0``, every chain iteration is self-speculative
  (Leviathan et al. 2023 verify + Saxena 2023 prompt-lookup draft, no
  second model): ``k`` draft tokens per slot come from an on-device
  n-gram match over the slot's recent-token history (carried in the
  decode state — no host round-trip), ONE ``(n_slots, k+1)`` decode
  forward verifies them through the same chunked-continuation path the
  prefix cache relies on, and the longest accepted prefix lands while
  rejected positions are rewound (``rewind_cache_index``; the stale K/V
  rows are provably overwritten before any query can attend to them —
  see models/transformer.py). ``k`` is STATIC; the accepted length is
  *data*, so nothing recompiles and the chain still costs one launch +
  ONE batched fetch — it just returns an ``(n_slots, steps, k+1)``
  token block plus per-step emit counts instead of one token per step;
- with ``adapter_bank=...``, every slot carries a per-request LoRA
  adapter id (:mod:`..adapters`): the bank's stacked factors ride in the
  params tree, each slot's id is DATA gathered by
  :func:`..adapters.bank.apply_lora` inside the same compiled programs,
  so tenants with different adapters co-batch with zero recompiles and
  id 0 (zero factors) is EXACTLY the base model. ``Request.adapter`` is
  validated at :meth:`submit` (admission, like the window check), which
  also snapshots the row's tenant-generation — bank rows recycle, so a
  request whose tenant is evicted (or whose row is re-registered) while
  it queues completes with ``finish_reason == "adapter_evicted"``
  instead of decoding under the wrong factors. Prefix keys are
  namespaced per (adapter, generation) so tenants never splice each
  other's KV — not even a later tenant reusing an evicted tenant's row.
  ``register``/``evict`` on a live engine take effect at the next
  :meth:`step` (the engine re-merges automatically when the bank's
  version moves). Bank off keeps the state tree and compiled programs
  byte-identical.

Failure handling (ISSUE 9) lives at the SAME boundaries the scheduler
does — between chains and at refill, never inside a compiled program:

- deadlines (``Request.deadline_s`` / engine ``default_deadline_s``)
  and host-side :meth:`cancel` complete a request ``"deadline"`` /
  ``"cancelled"`` at the next chain/refill boundary via the existing
  park path (partial tokens kept; a queued victim completes with zero
  device work, like ``"adapter_evicted"``);
- :meth:`close` stops admission (``QueueClosed`` backpressure) and
  :meth:`drain` runs every accepted request to completion — graceful
  shutdown without dropping in-flight work;
- with ``guard_nonfinite=True`` the chain also emits a per-slot
  finite-logits flag per step, riding the SAME batched fetch (budget
  unchanged): a request that drives logits to NaN/Inf completes
  ``"nonfinite"`` with its pre-poison tokens, its slot parks and is
  rewritten whole by the next refill (quarantine), and co-scheduled
  slots — independent across the batch dim — keep decoding
  token-identically to a clean run;
- a prefill that RAISES (hardware fault, injected chaos) is isolated to
  its request (``"error"``, slot parked, engine keeps serving);
- a :class:`..utils.chaos.ChaosConfig` injects deterministic faults
  (NaN logits at (slot, step), prefill failure, launch stall) so every
  path above is exercised by tests, not just reasoned about.

Guard/deadline/chaos OFF keeps the state tree and compiled programs
byte-identical to the pre-robustness engine (the same Python-default
trick the prefix cache, speculation, and adapter bank use).

Pipelining (ISSUE 11) hides the per-LAUNCH host roundtrip (~75-130 ms
on the tunneled runtime, vs ~3.6 ms of device work per 1.2B int8 step)
behind device execution:

- ``pipeline_depth=2`` double-buffers decode chains: chain ``i+1`` (and
  any prefill/splice for slots freed at chain ``i-1``'s observed
  boundary) is DISPATCHED before chain ``i``'s batched fetch — JAX
  async dispatch queues it device-side, so the device never idles on
  the roundtrip. Host bookkeeping (sweep, distribute, refill) runs one
  chain behind the device: "chain boundary" for deadlines / cancel /
  quarantine means the OBSERVED boundary (one chain late at depth 2;
  tokens earned before it are kept, exactly as before). Token-exactness
  is unaffected because chain ``i+1``'s inputs are device-resident
  state, never chain ``i``'s fetched tokens; a slot whose request
  finished in chain ``i`` junk-decodes one extra chain (its rows are
  dropped by an identity check against the slot view snapshotted at
  dispatch) and parks/refills as usual. Depth 1 IS the serial loop —
  byte-identical state tree and compiled programs;
- ``prefill_chunk=N`` caps prefill work per scheduling quantum: a
  prompt whose uncached length exceeds N prefills in N-token chunks
  through the SAME bitwise-equal chunked decode continuation splices
  use, one chunk per :meth:`ServeEngine.step`, interleaved with decode
  chains — a 2048-token prompt no longer freezes co-scheduled slots.
  Chunks accumulate in a batch-1 side cache (never the slot state); the
  final chunk splices into the slot exactly like a prefix-cache hit and
  only THAT chunk fetches the first token, so the fetch budget stays
  chains + prefills + splices in every configuration.

Greedy decoding is token-exact vs one-shot ``generate()`` (same math,
same cache semantics; pinned by tests/test_serve.py). Temperature /
top-k / top-p are ENGINE-level statics — per-request sampling params
would either recompile the decode program or drag filter branches into
every step; per-request randomness comes from per-request seeds.
"""

from __future__ import annotations

import collections
import dataclasses
import time

import jax
import jax.numpy as jnp

from pytorch_distributed_training_tutorials_tpu.models.sampling import (
    ngram_draft,
    sample_logits,
    sample_logits_per_slot,
    speculative_accept,
)
from pytorch_distributed_training_tutorials_tpu.models.transformer import (
    _kv_quant_mode,
    rewind_cache_index,
)
from pytorch_distributed_training_tutorials_tpu.parallel.tensor_parallel import (
    audit_hlo,
)
from pytorch_distributed_training_tutorials_tpu.serve.pages import (
    PagePool,
    PoolExhausted,
)
from pytorch_distributed_training_tutorials_tpu.serve.prefix import PrefixIndex
from pytorch_distributed_training_tutorials_tpu.serve.scheduler import (
    Completion,
    FifoScheduler,
    Handoff,
    Request,
)
from pytorch_distributed_training_tutorials_tpu.serve.slo import (
    PriorityScheduler,
    SwapRecord,
    choose_victim,
)
from pytorch_distributed_training_tutorials_tpu.serve.slots import (
    _POOL_TO_FLAT,
    _leaf_name,
    bucket_len,
    extract_segment,
    init_slot_state,
    seed_cache,
    tree_nbytes,
    tree_nbytes_sharded,
    write_slot,
    write_slot_paged,
    zero_cache,
)
from pytorch_distributed_training_tutorials_tpu.utils import chaos as chaos_lib


class _Active:
    """Host-side view of one occupied slot. ``segment`` pins the prefix
    segment this slot was spliced from (released at completion);
    ``ttft_s`` is submit-to-first-token wall time."""

    __slots__ = ("request", "tokens", "remaining", "segment", "ttft_s",
                 "pages")

    def __init__(self, request: Request, first_token: int):
        self.request = request
        self.tokens = [first_token]
        self.remaining = request.max_new_tokens - 1
        self.segment = None
        self.ttft_s = 0.0
        # paged engines (ISSUE 13): pool page ids this slot holds one
        # reference to each — released when the slot parks
        self.pages: list[int] = []


class _InFlight:
    """One dispatched-but-not-yet-fetched decode chain: the chain's
    output futures, a shallow snapshot of the slot views at dispatch
    (the identity guard — a slot completed or refilled inside the
    pipeline window must not consume this chain's junk rows), and the
    chain's sequence number for the flight recorder's overlap stamp."""

    __slots__ = ("out", "view", "chain_id")

    def __init__(self, out, view, chain_id: int):
        self.out = out
        self.view = view
        self.chain_id = chain_id


class _PendingPrefill:
    """Host-side record of a chunked prefill in progress: the request,
    its target slot, the accumulating batch-1 side cache (device
    futures — chunks are async dispatches, never fetched), and how many
    prompt tokens (``done``, INCLUDING any spliced prefix ``depth``)
    the cache already holds. The slot's device-side budget stays 0
    until the final chunk, so decode chains treat it as inactive."""

    __slots__ = ("request", "slot", "cache1", "prompt", "aid", "done",
                 "depth", "segment", "grow", "pkey", "pages")

    def __init__(self, request: Request, slot: int):
        self.request = request
        self.slot = slot
        self.cache1 = None
        self.prompt: list[int] = []
        self.aid = 0
        self.done = 0
        self.depth = 0
        self.segment = None
        self.grow = False
        self.pkey: list[int] = []
        # paged engines (ISSUE 13): pages pre-allocated for the slot at
        # chunking start (all fresh — chunked prompts don't share)
        self.pages: list[int] = []


class ServeEngine:
    """Request-level LM serving over a slot-indexed KV cache.

    ``model`` is a :class:`..models.transformer.TransformerLM` (or
    anything with the same decode/prefill/``last_pos`` apply contract and
    a ``cfg.max_seq_len``); its ``max_seq_len`` is the serving window
    every slot gets. ``params`` stays caller-owned and read-only (share
    one tree across engines; int8/TP placements pass straight through —
    the engine never touches leaf placement).

    Drive it with :meth:`submit` + :meth:`step`, or :meth:`run_until_idle`
    to drain everything. ``step()`` does at most: one prefill launch per
    freed slot (each with one scalar fetch of the first sampled token),
    then ONE ``tokens_per_launch``-step decode chain with ONE batched
    fetch — the no-per-token-host-sync contract tests/test_serve.py pins
    with a monkeypatched ``jax.device_get``.
    """

    def __init__(
        self,
        model,
        params,
        *,
        n_slots: int = 4,
        tokens_per_launch: int = 8,
        max_queue: int = 64,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        prefix_cache_bytes: int = 0,
        min_hit_depth: int = 1,
        speculative_k: int = 0,
        spec_ngram: int = 3,
        adapter_bank=None,
        default_deadline_s: float | None = None,
        guard_nonfinite: bool = False,
        chaos=None,
        flight=None,
        sentry=None,
        pipeline_depth: int = 1,
        prefill_chunk: int = 0,
        paged: bool = False,
        page_size: int = 0,
        pool_pages: int = 0,
        strategy=None,
        kv_bits: int | None = None,
        paged_kernel: bool = False,
        role: str | None = None,
        priority_classes: int = 0,
    ):
        if n_slots < 1:
            raise ValueError("n_slots must be >= 1")
        if tokens_per_launch < 1:
            raise ValueError("tokens_per_launch must be >= 1")
        # paged KV (ISSUE 13): off = byte-identical state tree + compiled
        # programs to the whole-slot engine (the geometry kwargs must not
        # be set, so an off engine can never half-configure a pool)
        if paged:
            if page_size < 1 or pool_pages < 1:
                raise ValueError(
                    "paged=True needs page_size >= 1 and pool_pages >= 1"
                )
        elif page_size or pool_pages:
            raise ValueError(
                "page_size/pool_pages require paged=True"
            )
        # quantized KV + fused kernel (ISSUE 17): both ENGINE-static —
        # kv_bits rebuilds the model config (a different cache storage
        # dtype is a different compiled program family) and paged_kernel
        # flips the decode read path between the jnp.take reference and
        # the Pallas page-walk kernel. Per-request values for either
        # would recompile; neither exists.
        if kv_bits not in (None, 4, 8):
            raise ValueError(
                "kv_bits must be None (follow the model config), 8 "
                "(int8 + f32 scales), or 4 (packed nibbles + bf16 "
                "scales)"
            )
        if paged_kernel and not paged:
            raise ValueError(
                "paged_kernel=True requires paged=True (the kernel "
                "walks the page pool; whole-slot decode has no pages)"
            )
        if speculative_k < 0:
            raise ValueError("speculative_k must be >= 0")
        if pipeline_depth < 1:
            raise ValueError("pipeline_depth must be >= 1 (1 = serial)")
        # disaggregation (ISSUE 18): role=None is the monolithic engine
        # (byte-identical state tree + compiled programs — no handoff
        # twins are even constructed). A prefill-role engine runs
        # admission + prefill only and EMITS segments; a decode-role
        # engine ACCEPTS them and decodes. Features that only make
        # sense on the other side are rejected at construction so a
        # half-configured role can never exist: prefill never decodes
        # (no paged pool, no speculation, no chains to pipeline) and
        # decode never prefills a prompt (prefix cache + chunked
        # prefill live where the prefill forward runs).
        if role not in (None, "prefill", "decode"):
            raise ValueError(
                f"role must be None (monolithic), 'prefill', or "
                f"'decode'; got {role!r}"
            )
        self._role = role
        if role == "prefill":
            if paged:
                raise ValueError(
                    "role='prefill' engines never decode — the paged "
                    "pool belongs on the decode side"
                )
            if speculative_k:
                raise ValueError(
                    "role='prefill' engines never decode — speculation "
                    "belongs on the decode side"
                )
            if pipeline_depth != 1:
                raise ValueError(
                    "role='prefill' engines dispatch no decode chains — "
                    "pipeline_depth belongs on the decode side"
                )
        if role == "decode":
            if prefix_cache_bytes:
                raise ValueError(
                    "role='decode' engines never prefill a prompt — the "
                    "prefix cache belongs on the prefill side"
                )
            if prefill_chunk:
                raise ValueError(
                    "role='decode' engines never prefill a prompt — "
                    "prefill_chunk belongs on the prefill side"
                )
        if prefill_chunk and (
            prefill_chunk < 8 or prefill_chunk & (prefill_chunk - 1)
        ):
            raise ValueError(
                "prefill_chunk must be 0 (off) or a power of two >= 8 "
                "(chunk lengths must come from the pow2 bucket set so "
                "compiles stay bounded)"
            )
        if default_deadline_s is not None and default_deadline_s <= 0:
            raise ValueError(
                "default_deadline_s must be > 0 (None = no deadline)"
            )
        # SLO tiers (ISSUE 20): 0 = off — the engine keeps the FIFO
        # scheduler and constructs NO swap programs, so off engines are
        # byte-identical (state tree + compiled-program census) to the
        # pre-SLO build. N >= 1 admits priority classes [0, N), pops by
        # (class, arrival), and under pressure preempts the lowest-tier
        # active slot at the chain boundary via the KV swap path below.
        if priority_classes < 0:
            raise ValueError(
                "priority_classes must be >= 0 (0 = single-class FIFO)"
            )
        if priority_classes and role is not None:
            raise ValueError(
                "priority_classes requires role=None: preemption swaps "
                "in through the monolithic refill path; role-split "
                "fleets shape traffic at the router"
            )
        self._slo = priority_classes > 0
        self._n_classes = int(priority_classes)
        # sharded serving (ISSUE 15): a TensorParallel strategy shards the
        # slot/KV state on the model (head) axis to match the attention
        # sharding the params already carry — TP serving is the existing
        # engine under jit on a mesh, not a second engine. tp=1 (or
        # strategy=None) is byte-identical to the replicated engine: the
        # gate below makes every _pin() a Python-level identity, so no
        # jaxpr, state leaf, or compile count changes off-path.
        self._strategy = strategy
        self._shard = (
            strategy is not None and getattr(strategy, "tp_size", 1) > 1
        )
        self._tp = strategy.tp_size if self._shard else 1
        self._tp_audit = None
        # per-chip byte accounting: a sharded leaf's honest HBM claim is
        # its SHARD size, not its global size (page pricing + prefix
        # index budgets below go through this)
        self._nbytes = tree_nbytes_sharded if self._shard else tree_nbytes
        # adapter bank: None = off (the engine then builds byte-identical
        # state and compiled programs to the adapter-free one). On, the
        # engine serves the bank's LoRA twin of the caller's model over
        # merged params (base tree + stacked factor subtrees); the base
        # tree stays caller-owned and untouched.
        self._bank = adapter_bank
        self._adapters = adapter_bank is not None
        if self._adapters:
            base_cfg = dataclasses.replace(
                model.cfg, lora_adapters=0, lora_rank=0
            )
            bank_base = dataclasses.replace(
                adapter_bank.model.cfg, lora_adapters=0, lora_rank=0
            )
            if base_cfg != bank_base:
                raise ValueError(
                    "adapter_bank was built for a different model config"
                )
            self._base_params = params
            model = adapter_bank.model
            params = adapter_bank.merge_params(params)
            # bank version this merge reflects; step() re-merges when
            # the bank moves past it (register/evict on a live engine)
            self._merged_version = adapter_bank.version
        if self._shard:
            # commit params to their rule shardings (idempotent for
            # already-placed trees): committed sharded inputs are what
            # make every jit below compile GSPMD-sharded programs
            # instead of replicated ones
            params = strategy.shard_state(params)
        # kv_bits (ISSUE 17): override the cache storage dtype on the
        # model the engine serves (bank twin included — the override
        # runs AFTER the bank substitution so tenants quantize too).
        # Params are untouched: kv_cache_dtype only shapes the mutable
        # cache collection, so None keeps engine + model byte-identical
        # to a no-kwarg construction. 8 -> int8 + f32 scales; 4 ->
        # packed-nibble uint8 + bf16 scales, EXACTLY half int8's bytes
        # per token-head (d/2 + 2 vs d + 4 — models/transformer.py
        # _kv_storage), which is what makes "2x pages at fixed HBM" an
        # identity rather than an approximation.
        if kv_bits is not None:
            model = type(model)(
                cfg=dataclasses.replace(
                    model.cfg,
                    kv_cache_dtype="int4" if kv_bits == 4 else jnp.int8,
                )
            )
        self._kv_bits = {None: 0, "int8": 8, "int4": 4}[
            _kv_quant_mode(model.cfg.kv_cache_dtype)
        ]
        self.model = model
        self.params = params
        self.n_slots = n_slots
        self.tokens_per_launch = tokens_per_launch
        self.window = int(model.cfg.max_seq_len)
        # paged KV decode (ISSUE 13): the DECODE-side model reads/writes
        # K/V through a shared page pool + per-slot page tables
        # (cfg.kv_pages/kv_page_size — models/transformer.py), so slot
        # count decouples from window size: n_slots * window may exceed
        # pool_pages * page_size, with admission backpressure
        # (PoolExhausted) when a request can never fit. Prefill/chunk
        # programs keep the UNPAGED batch-1 layout (self.model) and the
        # scatter into the pool happens in write_slot_paged. When off,
        # _dec_model IS self.model, so every chain jaxpr below is
        # byte-identical to the whole-slot engine's.
        self._paged = bool(paged)
        self._page_size = int(page_size)
        self._pool_pages = int(pool_pages)
        if self._paged:
            if self.window % self._page_size:
                raise ValueError(
                    f"page_size ({page_size}) must divide the window "
                    f"({self.window}) so slot page tables have one "
                    "fixed length"
                )
            self._pool = PagePool(pool_pages, page_size)
            self._pages_per_slot = self.window // self._page_size
            # paged_kernel rides the decode model's config: the flag is
            # trace-time structure (models/transformer.py branches on it
            # in Python, never on a traced value), so kernel-off paged
            # engines compile byte-identical programs to pre-kernel ones.
            self._dec_model = type(model)(
                cfg=dataclasses.replace(
                    model.cfg, kv_pages=pool_pages,
                    kv_page_size=page_size,
                    paged_kernel=bool(paged_kernel),
                )
            )
        else:
            self._pool = None
            self._pages_per_slot = 0
            self._dec_model = model
        self._paged_kernel = bool(paged_kernel)
        # speculate-k: 0 = off (the engine then compiles byte-identical
        # programs to the pre-speculation one — no hist state, old chain)
        self._spec = speculative_k > 0
        self._spec_k = int(speculative_k)
        self._spec_ngram = int(spec_ngram)
        if self._spec and speculative_k + 1 > self.window:
            raise ValueError("speculative_k + 1 must fit the window")
        if self._spec and spec_ngram < 1:
            raise ValueError("spec_ngram must be >= 1")
        self.scheduler = (
            PriorityScheduler(
                self.window, max_queue=max_queue,
                n_classes=self._n_classes,
            )
            if self._slo
            else FifoScheduler(self.window, max_queue=max_queue)
        )
        self._slots: list[_Active | None] = [None] * n_slots
        self._state = init_slot_state(
            self._dec_model, params, n_slots,
            history=self.window if self._spec else 0,
            adapters=self._adapters,
            paged=self._pool_pages if self._paged else 0,
            strategy=strategy if self._shard else None,
        )
        self._scan_layers = bool(getattr(model.cfg, "scan_layers", False))
        if self._paged:
            # per-page HBM footprint (all pool leaves / pool_pages) —
            # page_stats()'s hbm_high_water_bytes and the prefix index's
            # byte accounting both price pages with it. Host metadata
            # only; tree_nbytes never touches the device.
            pool_leaves = [
                leaf for path, leaf in
                jax.tree_util.tree_leaves_with_path(self._state["cache"])
                if _leaf_name(path) in _POOL_TO_FLAT
            ]
            self._page_bytes = self._nbytes(pool_leaves) // self._pool_pages
        else:
            self._page_bytes = 0
        self._temperature = float(temperature)
        self._top_k = int(top_k)
        self._top_p = float(top_p)
        # prefix cache: 0 bytes = off (the engine is then byte-identical
        # in behavior to the pre-prefix-cache one)
        self._retain = prefix_cache_bytes > 0
        # paged engines hand the index an eviction hook so a segment's
        # page refcounts flow back to the pool the moment the index
        # drops it (the index stays jax-free and handle-agnostic: a
        # paged handle is a tuple of page ids, not a device tree)
        self.prefix = (
            PrefixIndex(
                prefix_cache_bytes,
                on_evict=self._release_segment_pages if self._paged
                else None,
            )
            if self._retain else None
        )
        self._min_hit_depth = int(min_hit_depth)
        # software pipeline (ISSUE 11): depth 1 = today's serial loop
        # (dispatch then fetch in the same step — byte-identical state
        # tree and compiled programs); depth 2 keeps one chain in flight
        # across the host roundtrip. prefill_chunk = 0 disables chunked
        # prefill (every prompt prefills whole, as before).
        self._depth = int(pipeline_depth)
        self._chunk = int(prefill_chunk)
        self._inflight: collections.deque[_InFlight] = collections.deque()
        self._pending: dict[int, _PendingPrefill] = {}
        self.n_chunks = 0
        if self._retain or self._chunk or role == "decode" or self._slo:
            # shape/dtype proto of the batch-1 decode cache — seed_cache
            # builds the splice start state from it, chunked prefill its
            # zeroed side cache, a decode-role engine both validates
            # incoming handoff segments against it and seeds their
            # accept splice from it, and the SLO swap-in re-splices a
            # preempted request's parked segment through it
            # (eval_shape: no FLOPs, no buffers)
            self._proto1 = jax.eval_shape(
                lambda p, t: self.model.apply(
                    {"params": p}, t, decode=True, mutable=["cache"]
                )[1]["cache"],
                params, jnp.zeros((1, 1), jnp.int32),
            )
        # stats for receipts
        self.n_prefills = 0
        self.n_chains = 0
        self.n_splices = 0
        self.prefix_hit_tokens = 0
        self.generated_tokens = 0
        # speculative counters: sequential verify forwards dispatched,
        # verify steps whose tokens an active slot consumed, and draft
        # tokens accepted (emitted beyond the guaranteed 1/step)
        self.n_verify_forwards = 0
        self.spec_steps_consumed = 0
        self.spec_drafts_accepted = 0
        # requests served with a non-base adapter, and requests bounced
        # at refill because their tenant was evicted / their row
        # re-registered while queued (receipt counters)
        self.adapter_requests = 0
        self.adapter_rejected = 0
        # robustness layer (ISSUE 9): deadlines/cancel/drain are pure
        # host bookkeeping (no compiled-program impact at all); the
        # non-finite guard changes only the chain's OUTPUT (the flag
        # rides the existing batched fetch), never the state tree.
        self._deadline = default_deadline_s
        self._guard = bool(guard_nonfinite)
        self._chaos = chaos
        # flight recorder (ISSUE 10): None = off. On, lifecycle events
        # and spans are stamped at the SAME host boundaries the code
        # below already touches — a clock read + a deque append, never a
        # device fetch, so the fetch budget and the compiled programs are
        # IDENTICAL either way (tests/test_serve.py pins both).
        self._flight = flight
        # contract sentry (ISSUE 19): None = off (byte-identical state
        # tree + compiled programs — the sentry only ever counts on the
        # host). On, every step() round runs inside a begin/end fetch
        # accounting window, the budgeted call sites attribute their
        # fetches through _sentry_fetch, and the chain's dispatch args
        # are walked for host-numpy re-upload leaves.
        self._sentry = sentry
        self._inject_logits = chaos is not None and chaos.poisons_logits
        self._cancelled: set[int] = set()
        self.n_deadline_expired = 0
        self.n_cancelled = 0
        self.nonfinite_quarantined = 0
        self.n_prefill_errors = 0
        # disaggregation (ISSUE 18): transfer records waiting for the
        # router to collect (prefill role, keyed by request id) / to be
        # spliced at refill (decode role); host dicts holding device
        # futures — never fetched here
        self._handoffs: dict[int, Handoff] = {}
        self._handoff_in: dict[int, Handoff] = {}
        self.n_handoffs_out = 0
        self.n_handoffs_in = 0
        # SLO preemption (ISSUE 20): parked swap records by request id
        # (host numpy — the swap-out fetch already paid for the bytes),
        # the one-shot latch for the chaos force-preempt injector, and
        # the receipt counters. Attrs exist only when the feature is on
        # (the attrs-don't-exist off-path contract).
        if self._slo:
            self._swapped: dict[int, SwapRecord] = {}
            self._chaos_preempt_fired = False
            self.n_swaps_out = 0
            self.n_swaps_in = 0
        # donating the state tree lets XLA update the multi-hundred-MB
        # cache in place; CPU jit warns on donation (unsupported), so
        # only donate where it is real
        donate = (1,) if jax.default_backend() in ("tpu", "gpu") else ()
        # classic and paged prefill/splice programs are MUTUALLY
        # EXCLUSIVE per engine: an unpaged engine never constructs the
        # paged twins (so its compiled-program census is byte-identical
        # to the pre-paging engine), and a paged engine never constructs
        # the whole-slot ones.
        if self._paged:
            self._prefill_paged = jax.jit(
                self._prefill_paged_fn, donate_argnums=donate
            )
        else:
            self._prefill = jax.jit(
                self._prefill_fn, donate_argnums=donate
            )
        # logit-poison chaos threads a traced chain-base scalar into the
        # chain (an EXTRA operand) — a separate wrapper keeps the
        # chaos-free jaxpr byte-identical to the pre-robustness one
        if self._spec:
            chain_fn = (
                self._spec_chain_chaos_fn if self._inject_logits
                else self._spec_chain_fn
            )
        else:
            chain_fn = (
                self._chain_chaos_fn if self._inject_logits
                else self._chain_fn
            )
        self._chain = jax.jit(chain_fn, donate_argnums=donate)
        # splice: same donation as prefill (state is arg 1); the retained
        # segment (arg 2) must NEVER be donated — the index keeps serving
        # it to later requests. The two compile statics are keyword-only,
        # by NAME: for a jitted BOUND method argnums exclude self (unlike
        # the nn.remat(Block, static_argnums=...) idiom which counts it),
        # and names are unambiguous under both conventions.
        if self._paged:
            # paged splice: no static argnames — shared/boundary page
            # geometry rides as traced data (the row vector + the CoW
            # src/dst pair, sentinel = no-op), so compiles stay one per
            # suffix bucket. The parked-table program sentinels a slot's
            # page-table row so chains dispatched after a completion
            # never write through freed page ids.
            self._splice_paged = jax.jit(
                self._splice_paged_fn, donate_argnums=donate
            )
            self._paged_park = jax.jit(
                self._paged_park_fn, donate_argnums=(0,) if donate else ()
            )
        else:
            self._splice = jax.jit(
                self._splice_fn, static_argnames=("seg_len", "grow"),
                donate_argnums=donate,
            )
        self._park = jax.jit(
            _park_slot, donate_argnums=(0,) if donate else ()
        )
        # chunked-prefill programs exist only when the feature is on —
        # chunk-off engines compile (and trace) nothing new. The seeded
        # segment is never donated (the index keeps serving it); the
        # side cache IS donated between chunks (it has exactly one
        # consumer), as is the slot state into the final splice.
        if self._chunk:
            self._chunk_zero = jax.jit(
                lambda: self._pin(zero_cache(self._proto1))
            )
            self._chunk_step = jax.jit(
                self._chunk_step_fn, donate_argnums=donate
            )
            if self._paged:
                # paged seed: gather-COPY the donor's pages out of the
                # live pool into the unpaged batch-1 side cache. Reads
                # live state, so NEVER donated. The paged final chunk
                # scatters the side cache into the slot's fresh pages
                # (write_slot_paged) — side cache + slot state donated
                # as in the classic twin.
                self._chunk_seed_paged = jax.jit(
                    self._chunk_seed_paged_fn
                )
                self._chunk_final_paged = jax.jit(
                    self._chunk_final_paged_fn,
                    donate_argnums=(1, 2) if donate else (),
                )
            else:
                self._chunk_seed = jax.jit(
                    lambda segment, depth: self._pin(seed_cache(
                        self._proto1, segment, depth
                    ))
                )
                self._chunk_final = jax.jit(
                    self._chunk_final_fn,
                    static_argnames=("seg_len", "grow"),
                    donate_argnums=(1, 2) if donate else (),
                )
        # disaggregation programs (ISSUE 18): role=None constructs
        # NEITHER side, so monolithic engines keep a byte-identical
        # compiled-program census. The prefill role's programs end in
        # segment extraction instead of slot surgery; the decode role's
        # accept is the prefix-splice surgery (seed_cache + write_slot)
        # applied to a TRANSFERRED segment. The segment is never
        # donated on either side — the prefill engine's prefix index
        # (and the router, across replica death) may still serve it.
        if role == "prefill":
            self._handoff_prefill = jax.jit(self._handoff_prefill_fn)
            if self._retain:
                self._handoff_splice = jax.jit(
                    self._handoff_splice_fn,
                    static_argnames=("seg_len",),
                )
            if self._chunk:
                # the accumulated side cache has exactly one consumer
                self._handoff_final = jax.jit(
                    self._handoff_final_fn,
                    static_argnames=("seg_len",),
                    donate_argnums=donate,
                )
        elif role == "decode":
            self._accept_jit = jax.jit(
                self._accept_paged_fn if self._paged
                else self._accept_fn,
                donate_argnums=donate,
            )
        # SLO swap programs (ISSUE 20): constructed only under
        # priority_classes, so FIFO engines keep a byte-identical
        # compiled-program census. Swap-out reads live state (the slot
        # may keep decoding if the preemption re-check bails) — never
        # donated; its seg_len is STATIC from the same pow2 bucket
        # family as prefill, so swaps never mint per-length compiles.
        # Swap-in is the accept splice pointed at a host-parked segment:
        # slot state donated like every other refill-time surgery.
        if self._slo:
            self._swap_out_jit = jax.jit(
                self._swap_out_paged_fn if self._paged
                else self._swap_out_fn,
                static_argnames=("seg_len",),
            )
            self._swap_in_jit = jax.jit(
                self._swap_in_paged_fn if self._paged
                else self._swap_in_fn,
                donate_argnums=donate,
            )

    # ------------------------------------------------------------------
    # compiled programs (closures over model + static sampling params)
    # ------------------------------------------------------------------

    def _pin(self, tree):
        """Pin ``tree``'s cache leaves to the strategy's slot shardings.

        Sharded engines thread this through every compiled cache
        producer (prefill write, splice seed, chunk accumulate, chain
        carry) so GSPMD keeps K/V head-sharded END TO END — without the
        constraint, a DUS or gather whose index operands are replicated
        can tempt the partitioner into an all-gather + local-update +
        reshard round trip. Off-path (``strategy=None`` or tp=1) this is
        a Python-level identity: no constraint op enters the jaxpr, so
        the unsharded engine's compiled programs stay byte-identical
        (the same off-path trick as guard/chaos/spec/adapters). Specs
        resolve from the traced leaf shapes, so the ONE helper covers
        slot caches, batch-1 segments, and side caches alike."""
        if not self._shard:
            return tree
        return self._strategy.constrain_slot_tree(tree)

    def _prefill_fn(self, params, state, tokens, p_len, slot, seed,
                    max_new, aid=0):
        """Prefill ``tokens`` (1, bucket) into slot ``slot``: one batched
        forward populates the slot's K/V for ``[0, p_len)``, the first
        token is sampled from the logits gathered at the last REAL prompt
        position, and the slot's counters reset. All of ``p_len`` /
        ``slot`` / ``seed`` / ``max_new`` are traced scalars — one
        compile per prompt BUCKET, not per request.

        ``aid`` (the request's adapter id) is only PASSED when the bank
        is on — adapters off leave it the Python default 0, a jit-inert
        constant, so the adapter-free jaxpr is byte-identical to the
        pre-adapter engine's. On, it is a traced scalar threaded into the
        forward as ``adapter_ids`` and recorded in the slot state for the
        chain's per-slot gather.

        With the prefix cache on, the bucket-length leading chunk of the
        just-prefilled batch-1 cache rides out as a retained segment
        (:func:`.slots.extract_segment` — insert-on-prefill); ``()``
        otherwise, so the cache-off engine's compiled program is
        unchanged."""
        kw = {}
        if self._adapters:
            kw["adapter_ids"] = jnp.asarray(aid, jnp.int32)
        logits, upd = self.model.apply(
            {"params": params}, tokens, prefill=True, mutable=["cache"],
            last_pos=p_len - 1, **kw,
        )
        key = jax.random.PRNGKey(seed)
        first, key = sample_logits(
            logits[:, -1].astype(jnp.float32), key,
            self._temperature, self._top_k, self._top_p,
        )
        cache = self._pin(write_slot(
            state["cache"], upd["cache"], slot, p_len, self._scan_layers
        ))
        seg = (
            self._pin(extract_segment(
                upd["cache"], tokens.shape[1], self._scan_layers
            ))
            if self._retain
            else ()
        )
        new_state = {
            "cache": cache,
            "last_tok": state["last_tok"].at[slot].set(first[0]),
            "keys": state["keys"].at[slot].set(key),
            # the first generated token is already accounted for
            "remaining": state["remaining"].at[slot].set(max_new - 1),
        }
        if self._spec:
            new_state.update(_seed_history(
                state, tokens, p_len, slot, first[0]
            ))
        if self._adapters:
            new_state["adapter_ids"] = state["adapter_ids"].at[slot].set(
                jnp.asarray(aid, jnp.int32)
            )
        return new_state, first[0], seg

    def _splice_fn(self, params, state, segment, suffix, full, depth,
                   p_len, slot, seed, max_new, aid=0, *, seg_len, grow):
        """Prefix-cache-hit refill: seed a batch-1 cache from a retained
        ``segment`` at ``depth`` reused positions, run ONE chunked decode
        over the bucket-padded ``suffix`` (1, s_bucket) — the suffix
        prefill, same math as batched prefill (models/transformer.py
        decode S>1; bit-equal for full-precision caches,
        tests/test_transformer.py) — then splice the result into
        ``slot`` exactly like :meth:`_prefill_fn` does. The first token
        samples from the logits at the last REAL suffix token
        (``last_pos = p_len - 1 - depth``, local), so a hit is
        token-identical to a full prefill.

        ``seg_len`` / ``grow`` are STATIC: segment + suffix lengths come
        from the pow2 bucket set, so compiles stay bounded by (segment
        bucket, suffix bucket, grow) triples, never per request. With
        ``grow`` the full-prompt segment rides out for insertion —
        multi-turn streams deepen the index one splice at a time.

        ``full`` is the whole bucket-padded prompt (1, bucket) — the
        n-gram draft history must cover the REUSED prefix too, which
        ``suffix`` alone cannot seed. Speculation off passes the suffix
        array again; the operand is then dead and XLA drops it.

        ``aid`` follows the :meth:`_prefill_fn` contract (Python-default
        0 when adapters are off, traced scalar when on). Splices only
        ever reuse segments from the SAME adapter — ``_refill``
        namespaces prefix keys per adapter — so the seeded prefix K/V
        was computed under the same factors the suffix prefill applies."""
        kw = {}
        if self._adapters:
            kw["adapter_ids"] = jnp.asarray(aid, jnp.int32)
        cache1 = self._pin(seed_cache(self._proto1, segment, depth))
        return self._finish_prefill(
            params, cache1, state, suffix, p_len - 1 - depth, full,
            p_len, slot, seed, max_new, aid, kw, seg_len, grow,
        )

    def _finish_prefill(self, params, cache1, state, suffix, last_local,
                        full, p_len, slot, seed, max_new, aid, kw,
                        seg_len, grow):
        """Shared tail of :meth:`_splice_fn` and :meth:`_chunk_final_fn`:
        run the chunked decode continuation over ``suffix`` from the
        batch-1 ``cache1``, sample the first token from the logits at
        local position ``last_local``, and splice the result into
        ``slot``. A plain helper, not a jit target — it traces inline in
        its callers, so factoring it out changed neither jaxpr."""
        logits, upd = self.model.apply(
            {"params": params, "cache": cache1}, suffix, decode=True,
            mutable=["cache"], last_pos=last_local, **kw,
        )
        key = jax.random.PRNGKey(seed)
        first, key = sample_logits(
            logits[:, -1].astype(jnp.float32), key,
            self._temperature, self._top_k, self._top_p,
        )
        cache = self._pin(write_slot(
            state["cache"], upd["cache"], slot, p_len, self._scan_layers
        ))
        seg = (
            self._pin(
                extract_segment(upd["cache"], seg_len, self._scan_layers)
            )
            if grow
            else ()
        )
        new_state = {
            "cache": cache,
            "last_tok": state["last_tok"].at[slot].set(first[0]),
            "keys": state["keys"].at[slot].set(key),
            "remaining": state["remaining"].at[slot].set(max_new - 1),
        }
        if self._spec:
            new_state.update(_seed_history(
                state, full, p_len, slot, first[0]
            ))
        if self._adapters:
            new_state["adapter_ids"] = state["adapter_ids"].at[slot].set(
                jnp.asarray(aid, jnp.int32)
            )
        return new_state, first[0], seg

    def _chunk_step_fn(self, params, cache1, tokens, aid=0):
        """One mid-prompt prefill chunk (chunked prefill, ISSUE 11):
        the same chunked decode continuation the splice path relies on,
        over exactly ``prefill_chunk`` tokens, batch-1 side cache in ->
        side cache out. No sampling, no slot surgery, no fetch — the
        call is one async dispatch, so a long prompt costs its
        co-scheduled slots one chunk of device time per step, never the
        whole prompt. ``last_pos=0`` keeps the dead lm-head gather
        trivial (mid-chunk logits are never consumed)."""
        kw = {}
        if self._adapters:
            kw["adapter_ids"] = jnp.asarray(aid, jnp.int32)
        _, upd = self.model.apply(
            {"params": params, "cache": cache1}, tokens, decode=True,
            mutable=["cache"], last_pos=0, **kw,
        )
        return self._pin(upd["cache"])

    def _chunk_final_fn(self, params, cache1, state, suffix, full,
                        last_local, p_len, slot, seed, max_new, aid=0,
                        *, seg_len, grow):
        """Final chunk of a chunked prefill: identical math to
        :meth:`_splice_fn` except the batch-1 start cache arrives as an
        ARGUMENT (the accumulated side cache) instead of being seeded
        from a retained segment. With ``grow`` the FULL prompt's segment
        rides out for insertion — the side cache holds every position,
        so chunked prompts deepen the prefix index exactly like whole
        prefills do. ``seg_len``/``grow`` static, same bucket discipline
        as the splice; ``last_local`` is the final chunk's last REAL
        token position (traced)."""
        kw = {}
        if self._adapters:
            kw["adapter_ids"] = jnp.asarray(aid, jnp.int32)
        return self._finish_prefill(
            params, cache1, state, suffix, last_local, full,
            p_len, slot, seed, max_new, aid, kw, seg_len, grow,
        )

    # -- paged twins (ISSUE 13) --------------------------------------------

    def _prefill_paged_fn(self, params, state, tokens, row, p_len, slot,
                          seed, max_new, aid=0):
        """Paged-engine prefill: the forward is the SAME unpaged batch-1
        prefill as :meth:`_prefill_fn` (self.model — prefill math never
        pages), then :func:`.slots.write_slot_paged` scatters the full
        window into the pool pages named by ``row`` (the slot's new page
        table, sentinel-padded past its allocation) and installs the row
        at ``slot``. The full-row scatter doubles as the recycled-page
        sanitizer: any junk a completed slot's in-flight chains wrote
        through these page ids dispatched BEFORE this program, so
        program order guarantees the pages hold exactly this prompt's
        K/V afterwards. No segment extraction — paged prefix retention
        pins page ids host-side (``_insert_paged_segment``), zero device
        work. ``row`` is a traced (pages_per_slot,) int32 vector; one
        compile per prompt bucket, exactly like the classic twin."""
        kw = {}
        if self._adapters:
            kw["adapter_ids"] = jnp.asarray(aid, jnp.int32)
        logits, upd = self.model.apply(
            {"params": params}, tokens, prefill=True, mutable=["cache"],
            last_pos=p_len - 1, **kw,
        )
        key = jax.random.PRNGKey(seed)
        first, key = sample_logits(
            logits[:, -1].astype(jnp.float32), key,
            self._temperature, self._top_k, self._top_p,
        )
        cache = self._pin(write_slot_paged(
            state["cache"], upd["cache"], row, slot, p_len,
            self._page_size, self._scan_layers,
        ))
        new_state = {
            "cache": cache,
            "last_tok": state["last_tok"].at[slot].set(first[0]),
            "keys": state["keys"].at[slot].set(key),
            "remaining": state["remaining"].at[slot].set(max_new - 1),
        }
        if self._spec:
            new_state.update(_seed_history(
                state, tokens, p_len, slot, first[0]
            ))
        if self._adapters:
            new_state["adapter_ids"] = state["adapter_ids"].at[slot].set(
                jnp.asarray(aid, jnp.int32)
            )
        return new_state, first[0]

    def _splice_paged_fn(self, params, state, row, suffix, full, depth,
                         p_len, slot, seed, max_new, cow_src, cow_dst,
                         aid=0):
        """Paged prefix-cache-hit refill: O(suffix) HBM instead of the
        classic segment copy. The donor's FULL shared pages (indices
        ``< depth // page_size`` in ``row``) are referenced in place —
        never copied, never written (all new writes land at positions
        ``>= depth``, i.e. page index ``>= depth // page_size``). A
        partially-shared boundary page is copy-on-written: ``cow_src``
        (the donor's page) is gathered and scattered whole into
        ``cow_dst`` (a fresh page already at ``row[depth//page_size]``);
        positions beyond ``depth`` in the copy are the donor's stale
        tail, overwritten by this suffix prefill's stores (which precede
        attention reads) or masked by the validity row — the exact
        stale-tail argument the classic splice rests on. When ``depth``
        is page-aligned both ids arrive as the sentinel (pool_pages) and
        the gather/scatter no-op via fill/drop, so ONE compiled shape
        serves both cases.

        The suffix forward runs through ``self._dec_model`` over a
        batch-1 VIEW of the live pool: page_table = ``row``, cache_index
        = ``depth``, pool leaves shared — suffix K/V streams DIRECTLY
        into the slot's pages through the table. The merge-back installs
        ``row``/``p_len`` at ``slot`` and keeps the updated pool;
        everything else follows :meth:`_finish_prefill`. All page
        geometry is traced DATA (no static argnames): compiles stay one
        per suffix bucket."""
        kw = {}
        if self._adapters:
            kw["adapter_ids"] = jnp.asarray(aid, jnp.int32)
        ax = 1 if self._scan_layers else 0
        src = jnp.asarray([cow_src], jnp.int32)
        dst = jnp.asarray([cow_dst], jnp.int32)

        def cow(path, leaf):
            name = _leaf_name(path)
            if name not in _POOL_TO_FLAT:
                return leaf
            page = jnp.take(leaf, src, axis=ax, mode="fill", fill_value=0)
            if self._scan_layers:
                return leaf.at[:, dst].set(page, mode="drop")
            return leaf.at[dst].set(page, mode="drop")

        cache = jax.tree_util.tree_map_with_path(cow, state["cache"])
        p_cap = self._pages_per_slot

        def view(path, leaf):
            name = _leaf_name(path)
            if name == "page_table":
                return jnp.broadcast_to(
                    row, leaf.shape[:-2] + (1, p_cap)
                ).astype(jnp.int32)
            if name == "cache_index":
                return jnp.full(leaf.shape[:-1] + (1,), depth, jnp.int32)
            return leaf

        cache1 = jax.tree_util.tree_map_with_path(view, cache)
        logits, upd = self._dec_model.apply(
            {"params": params, "cache": cache1}, suffix, decode=True,
            mutable=["cache"], last_pos=p_len - 1 - depth, **kw,
        )
        key = jax.random.PRNGKey(seed)
        first, key = sample_logits(
            logits[:, -1].astype(jnp.float32), key,
            self._temperature, self._top_k, self._top_p,
        )

        def merge(path, big, new1):
            name = _leaf_name(path)
            if name == "page_table":
                return big.at[..., slot, :].set(
                    jnp.asarray(row, big.dtype)
                )
            if name == "cache_index":
                # the view's counter advanced by the suffix bucket; the
                # slot's true position is p_len, same as classic splice
                return big.at[..., slot].set(
                    jnp.asarray(p_len, big.dtype)
                )
            return new1  # pool leaf: the updated pool IS the new pool

        cache = self._pin(jax.tree_util.tree_map_with_path(
            merge, cache, upd["cache"]
        ))
        new_state = {
            "cache": cache,
            "last_tok": state["last_tok"].at[slot].set(first[0]),
            "keys": state["keys"].at[slot].set(key),
            "remaining": state["remaining"].at[slot].set(max_new - 1),
        }
        if self._spec:
            new_state.update(_seed_history(
                state, full, p_len, slot, first[0]
            ))
        if self._adapters:
            new_state["adapter_ids"] = state["adapter_ids"].at[slot].set(
                jnp.asarray(aid, jnp.int32)
            )
        return new_state, first[0]

    def _paged_park_fn(self, state, slot):
        """Sentinel ``slot``'s page-table row and zero its budget. Paged
        engines park on EVERY completion (classic ones only when budget
        remains): an inactive slot still K/V-writes at advancing
        positions each chain step, and through a live table those writes
        would land in pages the host has already freed — or handed to a
        prefix segment. Sentinel ids turn them into ``mode="drop"``
        no-ops for every chain dispatched after this program; writes
        from chains already in flight (pipelining) are sanitized by the
        next allocation's full-row prefill scatter, which the device
        runs after them in program order."""
        def upd(path, leaf):
            name = _leaf_name(path)
            if name == "page_table":
                return leaf.at[..., slot, :].set(self._pool_pages)
            return leaf

        new_state = dict(state)
        new_state["cache"] = jax.tree_util.tree_map_with_path(
            upd, state["cache"]
        )
        new_state["remaining"] = state["remaining"].at[slot].set(0)
        return new_state

    def _chunk_seed_paged_fn(self, cache, row, depth):
        """Paged seed for a chunked-prefill prefix hit: gather-COPY the
        donor's pages (``row``: ``ceil(depth/page_size)`` real ids,
        sentinel-padded to the fixed table length) out of the live pool
        into the UNPAGED batch-1 side cache the chunk steps accumulate
        through — the paged analogue of :func:`.slots.seed_cache`.
        Chunked prompts then prefill into all-fresh pages at the final
        scatter (sharing is lost for them; the copy here is what buys
        the reused-prefix FLOPs back). A partially-covered boundary page
        copies whole — its tail past ``depth`` is donor-stale, dead
        under the continuation's stores-then-reads order, the same
        argument as the paged splice. Sentinel rows gather as zeros,
        matching the zero-init the classic side cache starts from."""
        ax = 1 if self._scan_layers else 0
        flat = {
            tuple(
                str(getattr(k, "key", getattr(k, "idx", k)))
                for k in path
            ): leaf
            for path, leaf in
            jax.tree_util.tree_leaves_with_path(cache)
        }
        flat_to_pool = {v: k for k, v in _POOL_TO_FLAT.items()}

        def build(path, proto):
            name = _leaf_name(path)
            if name == "cache_index":
                return jnp.full(proto.shape, depth, jnp.int32)
            pkey = tuple(
                str(getattr(k, "key", getattr(k, "idx", k)))
                for k in path
            )[:-1] + (flat_to_pool[name],)
            g = jnp.take(
                flat[pkey], row, axis=ax, mode="fill", fill_value=0
            )
            if self._scan_layers:
                out = g.reshape((g.shape[0], 1, -1) + g.shape[3:])
            else:
                out = g.reshape((1, -1) + g.shape[2:])
            return out.astype(proto.dtype)

        return self._pin(
            jax.tree_util.tree_map_with_path(build, self._proto1)
        )

    def _chunk_final_paged_fn(self, params, cache1, state, suffix, full,
                              last_local, p_len, slot, seed, max_new,
                              row, aid=0):
        """Paged final chunk: the same decode continuation as
        :meth:`_chunk_final_fn` over the accumulated side cache, then
        :func:`.slots.write_slot_paged` scatters the whole window into
        the slot's fresh pages (``row``) — full-row, so it sanitizes
        recycled pages exactly like the paged prefill does. No segment
        rides out (paged retention pins page ids host-side)."""
        kw = {}
        if self._adapters:
            kw["adapter_ids"] = jnp.asarray(aid, jnp.int32)
        logits, upd = self.model.apply(
            {"params": params, "cache": cache1}, suffix, decode=True,
            mutable=["cache"], last_pos=last_local, **kw,
        )
        key = jax.random.PRNGKey(seed)
        first, key = sample_logits(
            logits[:, -1].astype(jnp.float32), key,
            self._temperature, self._top_k, self._top_p,
        )
        cache = self._pin(write_slot_paged(
            state["cache"], upd["cache"], row, slot, p_len,
            self._page_size, self._scan_layers,
        ))
        new_state = {
            "cache": cache,
            "last_tok": state["last_tok"].at[slot].set(first[0]),
            "keys": state["keys"].at[slot].set(key),
            "remaining": state["remaining"].at[slot].set(max_new - 1),
        }
        if self._spec:
            new_state.update(_seed_history(
                state, full, p_len, slot, first[0]
            ))
        if self._adapters:
            new_state["adapter_ids"] = state["adapter_ids"].at[slot].set(
                jnp.asarray(aid, jnp.int32)
            )
        return new_state, first[0]

    # -- disaggregation twins (ISSUE 18) -----------------------------------

    def _handoff_prefill_fn(self, params, tokens, p_len, seed, aid=0):
        """Prefill-role miss path: the SAME batched prefill forward as
        :meth:`_prefill_fn`, but instead of slot surgery the whole
        prompt-bucket batch-1 cache rides out as a transferable segment
        (:func:`.slots.extract_segment` over ``tokens.shape[1]`` — one
        compile per pow2 bucket, the prefix-splice discipline). Returns
        ``(segment, first, key)``, ALL device residents: the sampled
        first token and the post-sample PRNG key travel with the
        segment so the decode side continues the request's stream
        exactly where a monolithic engine would. No fetch happens on
        this engine, ever — the prefill-role budget is ZERO, pinned by
        the device_get spy in tests/test_serve.py."""
        kw = {}
        if self._adapters:
            kw["adapter_ids"] = jnp.asarray(aid, jnp.int32)
        logits, upd = self.model.apply(
            {"params": params}, tokens, prefill=True, mutable=["cache"],
            last_pos=p_len - 1, **kw,
        )
        key = jax.random.PRNGKey(seed)
        first, key = sample_logits(
            logits[:, -1].astype(jnp.float32), key,
            self._temperature, self._top_k, self._top_p,
        )
        seg = self._pin(extract_segment(
            upd["cache"], tokens.shape[1], self._scan_layers
        ))
        return seg, first[0], key

    def _handoff_splice_fn(self, params, segment, suffix, depth, p_len,
                           seed, aid=0, *, seg_len):
        """Prefill-role prefix-hit path: seed from the retained donor
        at ``depth`` and run the chunked decode continuation over the
        uncached suffix (the same bitwise-equal-to-prefill math
        :meth:`_splice_fn` uses), then extract the FULL prompt bucket
        as the outgoing segment. ``seg_len`` is static — the pow2
        bucket set keeps compiles bounded, never per request."""
        kw = {}
        if self._adapters:
            kw["adapter_ids"] = jnp.asarray(aid, jnp.int32)
        cache1 = self._pin(seed_cache(self._proto1, segment, depth))
        return self._handoff_from_cache(
            params, cache1, suffix, p_len - 1 - depth, seed, kw, seg_len
        )

    def _handoff_final_fn(self, params, cache1, suffix, last_local,
                          seed, aid=0, *, seg_len):
        """Prefill-role final chunk of a chunked prefill: the decode
        continuation over the accumulated side cache, ending in segment
        extraction instead of slot surgery (the :meth:`_chunk_final_fn`
        analogue — long prompts stream through the SAME exact-N mid
        chunks on a prefill-role engine, so a disaggregated fleet keeps
        the no-prefill-freeze property)."""
        kw = {}
        if self._adapters:
            kw["adapter_ids"] = jnp.asarray(aid, jnp.int32)
        return self._handoff_from_cache(
            params, cache1, suffix, last_local, seed, kw, seg_len
        )

    def _handoff_from_cache(self, params, cache1, suffix, last_local,
                            seed, kw, seg_len):
        """Shared tail of the prefill-role splice / final-chunk
        programs: continuation forward, first-token sample, full-bucket
        segment extraction. A plain helper traced inline by its
        callers, same pattern as :meth:`_finish_prefill`."""
        logits, upd = self.model.apply(
            {"params": params, "cache": cache1}, suffix, decode=True,
            mutable=["cache"], last_pos=last_local, **kw,
        )
        key = jax.random.PRNGKey(seed)
        first, key = sample_logits(
            logits[:, -1].astype(jnp.float32), key,
            self._temperature, self._top_k, self._top_p,
        )
        seg = self._pin(extract_segment(
            upd["cache"], seg_len, self._scan_layers
        ))
        return seg, first[0], key

    def _accept_fn(self, params, state, segment, full, first, key,
                   p_len, slot, max_new, aid=0):
        """Decode-role accept: rebuild the monolithic post-prefill slot
        state from a transferred segment. ``seed_cache`` zero-fills the
        batch-1 proto and lands the segment at the origin — positions
        ``[0, bucket)`` then hold exactly what the prefill forward
        wrote (pad-position K/V included) and everything beyond is
        zero, which is bitwise what ``upd["cache"]`` looked like on the
        prefill engine — and ``write_slot`` performs the IDENTICAL
        splice :meth:`_prefill_fn` would have. Disaggregated
        token-exactness is therefore BITWISE for every cache family
        (int8/int4 included: nothing is recomputed, so quantization
        never reassociates). ``first``/``key`` arrive as device
        residents from the :class:`..serve.scheduler.Handoff`;
        ``params`` is unused but keeps ``state`` at donate index 1 (the
        segment, arg 2, is NEVER donated — the router may re-dispatch
        it). ``full`` is the bucket-padded prompt seeding the n-gram
        history — a dead operand when speculation is off, exactly like
        :meth:`_splice_fn`'s."""
        del params  # decode accept recomputes nothing
        cache1 = self._pin(seed_cache(self._proto1, segment, p_len))
        cache = self._pin(write_slot(
            state["cache"], cache1, slot, p_len, self._scan_layers
        ))
        new_state = {
            "cache": cache,
            "last_tok": state["last_tok"].at[slot].set(first),
            "keys": state["keys"].at[slot].set(key),
            "remaining": state["remaining"].at[slot].set(max_new - 1),
        }
        if self._spec:
            new_state.update(_seed_history(
                state, full, p_len, slot, first
            ))
        if self._adapters:
            new_state["adapter_ids"] = state["adapter_ids"].at[slot].set(
                jnp.asarray(aid, jnp.int32)
            )
        return new_state, first

    def _accept_paged_fn(self, params, state, segment, full, row, first,
                         key, p_len, slot, max_new, aid=0):
        """Paged decode-role accept: reconstruct the batch-1 cache as
        in :meth:`_accept_fn`, then scatter it into the slot's fresh
        pages (:func:`.slots.write_slot_paged` — full-row, so it
        sanitizes recycled pages exactly like the paged prefill does).
        Page geometry rides as the traced ``row`` vector; one compile
        per segment bucket."""
        del params
        cache1 = self._pin(seed_cache(self._proto1, segment, p_len))
        cache = self._pin(write_slot_paged(
            state["cache"], cache1, row, slot, p_len,
            self._page_size, self._scan_layers,
        ))
        new_state = {
            "cache": cache,
            "last_tok": state["last_tok"].at[slot].set(first),
            "keys": state["keys"].at[slot].set(key),
            "remaining": state["remaining"].at[slot].set(max_new - 1),
        }
        if self._spec:
            new_state.update(_seed_history(
                state, full, p_len, slot, first
            ))
        if self._adapters:
            new_state["adapter_ids"] = state["adapter_ids"].at[slot].set(
                jnp.asarray(aid, jnp.int32)
            )
        return new_state, first

    # -- SLO preemption twins (ISSUE 20) -----------------------------------

    def _swap_leaves(self, state, slot, segment):
        """Shared tail of the swap-out programs: bundle the segment with
        the slot's sampling leaves (next decode input, PRNG stream
        mid-sequence, and the n-gram history when speculation is on) so
        the host parks EVERYTHING the swap-in needs behind ONE batched
        fetch — the swap's single budgeted ``device_get``."""
        out = {
            "segment": segment,
            "last_tok": state["last_tok"][slot],
            "key": state["keys"][slot],
        }
        if self._spec:
            out["hist"] = state["hist"][slot]
            out["hist_len"] = state["hist_len"][slot]
        return out

    def _swap_out_fn(self, state, slot, *, seg_len):
        """Swap-out (whole-slot): cut slot ``slot``'s cache down to a
        batch-1 tree (``dynamic_slice_in_dim`` along the slot axis —
        slot is traced, no per-slot compiles) and extract positions
        ``[0, seg_len)`` — the Handoff extraction pointed at host: the
        segment covers every position the slot has WRITTEN (``seg_len``
        is the static pow2 bucket of the current position, same compile
        family as prefill), so re-splicing it via ``seed_cache`` +
        ``write_slot`` rebuilds the slot bitwise — nothing is
        recomputed, so quantized caches round-trip exactly too. Reads
        live state (never donated): the host re-checks the victim after
        draining the pipeline and may keep it decoding."""

        def cut(path, leaf):
            if _leaf_name(path) == "cache_index":
                return jax.lax.dynamic_slice_in_dim(
                    leaf, slot, 1, axis=leaf.ndim - 1
                )
            ax = 1 if self._scan_layers else 0
            return jax.lax.dynamic_slice_in_dim(leaf, slot, 1, axis=ax)

        cache1 = jax.tree_util.tree_map_with_path(cut, state["cache"])
        return self._swap_leaves(state, slot, extract_segment(
            cache1, seg_len, self._scan_layers
        ))

    def _swap_out_paged_fn(self, state, row, slot, position, *, seg_len):
        """Paged swap-out: gather the slot's pool pages (``row``: its
        live page table, sentinel-padded) into the unpaged batch-1
        layout — the :meth:`_chunk_seed_paged_fn` gather reused as an
        extractor — then cut the position bucket exactly like the
        whole-slot twin. The pages themselves return to the pool on the
        host side the moment the fetch lands; this program only reads
        them."""
        cache1 = self._chunk_seed_paged_fn(state["cache"], row, position)
        return self._swap_leaves(state, slot, extract_segment(
            cache1, seg_len, self._scan_layers
        ))

    def _swap_in_fn(self, params, state, segment, last_tok, key,
                    position, slot, remaining, hist=None, hist_len=None,
                    aid=0):
        """Swap-in (whole-slot): the :meth:`_accept_fn` splice pointed
        at a host-parked segment — ``seed_cache`` + ``write_slot``
        rebuild the preempted slot at ``position`` bitwise (nothing
        recomputed: the disaggregation argument verbatim), and the
        sampling leaves restore VERBATIM instead of being re-seeded:
        ``remaining`` is the request's live budget (not ``max_new - 1``)
        and ``key`` the PRNG stream mid-sequence, so the resumed
        request's tokens are exactly the undisturbed run's. ``params``
        is unused but keeps ``state`` at donate index 1."""
        del params  # swap-in recomputes nothing
        cache1 = self._pin(seed_cache(self._proto1, segment, position))
        cache = self._pin(write_slot(
            state["cache"], cache1, slot, position, self._scan_layers
        ))
        return self._swap_in_rest(
            state, cache, last_tok, key, slot, remaining, hist,
            hist_len, aid,
        )

    def _swap_in_paged_fn(self, params, state, segment, row, last_tok,
                          key, position, slot, remaining, hist=None,
                          hist_len=None, aid=0):
        """Paged swap-in: scatter the rebuilt batch-1 cache into the
        slot's FRESH pages (``write_slot_paged`` full-row — sanitizing,
        like every paged refill); page ids were re-allocated host-side,
        so a resumed request may land on different physical pages than
        it held — invisible in the tokens, the page table is DATA."""
        del params
        cache1 = self._pin(seed_cache(self._proto1, segment, position))
        cache = self._pin(write_slot_paged(
            state["cache"], cache1, row, slot, position,
            self._page_size, self._scan_layers,
        ))
        return self._swap_in_rest(
            state, cache, last_tok, key, slot, remaining, hist,
            hist_len, aid,
        )

    def _swap_in_rest(self, state, cache, last_tok, key, slot,
                      remaining, hist, hist_len, aid):
        """Shared bookkeeping tail of the swap-in programs."""
        new_state = {
            "cache": cache,
            "last_tok": state["last_tok"].at[slot].set(last_tok),
            "keys": state["keys"].at[slot].set(key),
            "remaining": state["remaining"].at[slot].set(remaining),
        }
        if self._spec:
            new_state["hist"] = state["hist"].at[slot].set(
                hist.astype(state["hist"].dtype)
            )
            new_state["hist_len"] = state["hist_len"].at[slot].set(
                hist_len
            )
        if self._adapters:
            new_state["adapter_ids"] = state["adapter_ids"].at[slot].set(
                jnp.asarray(aid, jnp.int32)
            )
        return new_state

    def _chain_fn(self, params, state):
        """``tokens_per_launch`` decode steps as one ``lax.scan`` — one
        launch, one (S, T) token block out. Every slot steps every time
        (fixed shapes); inactive slots re-emit their last token, their
        K/V writes land at advancing positions whose reads are never
        consumed (and drop once past the window — ``_store_decode_kv``
        in models/transformer.py), and refill rewrites the whole slot
        anyway.

        With the adapter bank on, the per-slot adapter-id vector rides
        into every step as a scan CONSTANT (refill — the only writer —
        runs between chains), and each step's forward gathers each
        slot's factors by it (:func:`..adapters.bank.apply_lora`):
        heterogeneous tenants decode together in this one program.

        With ``guard_nonfinite`` the scan ALSO emits a per-slot
        per-step finite-logits flag (an ``isfinite`` reduction over the
        logits row — the flag is DATA, the host reads it from the
        chain's one batched fetch, never branches on it in here): the
        poison-slot quarantine signal. Guard off, the emitted pytree —
        and the whole jaxpr — is byte-identical to the pre-guard
        chain."""
        return self._chain_impl(params, state, None)

    def _chain_chaos_fn(self, params, state, chain_base):
        """Chaos twin of :meth:`_chain_fn`: ``chain_base`` (a traced
        scalar, ``n_chains * tokens_per_launch`` at dispatch) gives the
        injector a global decode-step index so a configured NaN lands
        at exactly one (slot, step) — deterministic, recompile-free."""
        return self._chain_impl(params, state, chain_base)

    def _chain_impl(self, params, state, chain_base):
        kw = (
            {"adapter_ids": state["adapter_ids"]}
            if self._adapters else {}
        )
        guard = self._guard

        def step(carry, x):
            cache, tok, keys, remaining = carry
            active = remaining > 0
            # _dec_model IS self.model unless paged (then it's the
            # pool+page-table twin) — unpaged chains trace byte-identical
            logits, upd = self._dec_model.apply(
                {"params": params, "cache": cache}, tok[:, None],
                decode=True, mutable=["cache"], **kw,
            )
            row = logits[:, -1].astype(jnp.float32)
            if chain_base is not None:
                row = chaos_lib.poison_logits(
                    row, chain_base + x,
                    self._chaos.nan_logit_slot, self._chaos.nan_logit_step,
                )
            nxt, keys = sample_logits_per_slot(
                row, keys,
                self._temperature, self._top_k, self._top_p,
            )
            nxt = jnp.where(active, nxt, tok)
            remaining = remaining - active.astype(remaining.dtype)
            out = (
                (nxt, jnp.all(jnp.isfinite(row), axis=-1))
                if guard else nxt
            )
            return (self._pin(upd["cache"]), nxt, keys, remaining), out

        carry = (
            state["cache"], state["last_tok"], state["keys"],
            state["remaining"],
        )
        xs = (
            jnp.arange(self.tokens_per_launch)
            if chain_base is not None else None
        )
        (cache, tok, keys, remaining), outs = jax.lax.scan(
            step, carry, xs, length=self.tokens_per_launch
        )
        out = {
            "cache": cache, "last_tok": tok, "keys": keys,
            "remaining": remaining,
        }
        if self._adapters:
            out["adapter_ids"] = state["adapter_ids"]
        if guard:
            toks, oks = outs
            # (n_slots, tokens_per_launch) tokens + finite flags, ONE
            # fetched pytree — the budget is still one fetch per chain
            return out, (toks.T, oks.T)
        return out, outs.T  # (n_slots, tokens_per_launch)

    def _spec_chain_fn(self, params, state):
        """Speculate-k decode chain: ``tokens_per_launch`` iterations of
        draft -> verify -> accept/rewind, one ``lax.scan``, one launch.

        Per iteration every slot (a) drafts ``k`` tokens via longest
        n-gram suffix match over its history buffer
        (:func:`..models.sampling.ngram_draft` — fixed-shape gather/
        compare, no host round-trip), (b) verifies ``[last_tok, drafts]``
        in ONE (S, k+1) decode forward — the chunked-continuation path,
        so logits at position i condition on drafts < i exactly as
        sequential decode would, (c) accepts the longest matching prefix
        plus the standard bonus/rejection token
        (:func:`..models.sampling.speculative_accept`) and REWINDS each
        slot's position counter by the rejected count
        (:func:`..models.transformer.rewind_cache_index` — the forward
        advanced all counters by k+1; stale K/V at rejected positions is
        overwritten by the next iteration's writes before any query can
        attend there, and out-of-window writes drop via the
        ``mode="drop"`` scatter).

        Accepted length is DATA: shapes never depend on it, so one
        compile serves every acceptance pattern. The chain emits a fixed
        (S, T, k+1) token block + (S, T) per-step emit counts; inactive
        slots emit count 0 and their history is untouched (their scatter
        columns clamp out via ``mode="drop"``). ``guard_nonfinite``
        appends a per-slot per-step finite flag over the (k+1, V) verify
        logits, same contract as :meth:`_chain_fn`."""
        return self._spec_chain_impl(params, state, None)

    def _spec_chain_chaos_fn(self, params, state, chain_base):
        """Chaos twin of :meth:`_spec_chain_fn` (``chain_base`` counts
        scan ITERATIONS across chains — each iteration verifies k+1
        positions, so the step index is per-verify, not per-token)."""
        return self._spec_chain_impl(params, state, chain_base)

    def _spec_chain_impl(self, params, state, chain_base):
        k = self._spec_k
        rows = jnp.arange(self.n_slots)
        offs = jnp.arange(k + 1)
        win = self.window
        guard = self._guard
        # same scan-constant contract as _chain_fn
        kw = (
            {"adapter_ids": state["adapter_ids"]}
            if self._adapters else {}
        )

        def step(carry, x):
            cache, tok, keys, remaining, hist, hist_len = carry
            active = remaining > 0
            draft = ngram_draft(hist, hist_len, k, self._spec_ngram)
            toks_in = jnp.concatenate([tok[:, None], draft], axis=1)
            logits, upd = self._dec_model.apply(
                {"params": params, "cache": cache}, toks_in,
                decode=True, mutable=["cache"], **kw,
            )
            lg = logits.astype(jnp.float32)
            if chain_base is not None:
                lg = chaos_lib.poison_logits(
                    lg, chain_base + x,
                    self._chaos.nan_logit_slot, self._chaos.nan_logit_step,
                )
            emitted, n_acc, keys = speculative_accept(
                lg, draft, keys,
                self._temperature, self._top_k, self._top_p,
            )
            # the verify forward advanced every counter by k+1; the slot
            # really produced 1 + n_acc tokens, so rewind the rest
            cache = self._pin(rewind_cache_index(upd["cache"], k - n_acc))
            n_emit = jnp.where(active, n_acc + 1, 0).astype(jnp.int32)
            new_tok = jnp.where(active, emitted[rows, n_acc], tok)
            cols = jnp.where(
                offs[None, :] < n_emit[:, None],
                hist_len[:, None] + offs[None, :], win,
            )
            hist = hist.at[rows[:, None], cols].set(
                emitted, mode="drop"
            )
            hist_len = jnp.minimum(hist_len + n_emit, win)
            remaining = jnp.maximum(
                remaining - n_emit, 0
            ).astype(remaining.dtype)
            carry = (cache, new_tok, keys, remaining, hist, hist_len)
            out = (emitted, n_emit)
            if guard:
                out = out + (jnp.all(jnp.isfinite(lg), axis=(1, 2)),)
            return carry, out

        carry = (
            state["cache"], state["last_tok"], state["keys"],
            state["remaining"], state["hist"], state["hist_len"],
        )
        xs = (
            jnp.arange(self.tokens_per_launch)
            if chain_base is not None else None
        )
        (cache, tok, keys, remaining, hist, hist_len), outs = (
            jax.lax.scan(step, carry, xs, length=self.tokens_per_launch)
        )
        out = {
            "cache": cache, "last_tok": tok, "keys": keys,
            "remaining": remaining, "hist": hist, "hist_len": hist_len,
        }
        if self._adapters:
            out["adapter_ids"] = state["adapter_ids"]
        if guard:
            toks, counts, oks = outs
            return out, (
                jnp.transpose(toks, (1, 0, 2)), counts.T, oks.T
            )
        toks, counts = outs
        # (S, T, k+1) token block + (S, T) counts
        return out, (jnp.transpose(toks, (1, 0, 2)), counts.T)

    # ------------------------------------------------------------------
    # host-side driver
    # ------------------------------------------------------------------

    def submit(self, request: Request) -> int:
        """Enqueue one request; returns its id. Raises
        :class:`..serve.scheduler.QueueFull` when the bounded queue is at
        capacity (backpressure), :class:`..serve.scheduler.QueueClosed`
        after :meth:`close` (shutdown), or ``ValueError`` when the
        request can never fit the window — or names an adapter this
        engine cannot serve (no bank, or an unregistered/out-of-range
        id): admission failures are always synchronous, never a
        mid-decode surprise.

        Admission also snapshots the adapter row's tenant-generation
        (rows recycle): :meth:`_refill` re-checks it, so a request whose
        tenant is evicted — or whose row is handed to a NEW tenant —
        while it queues completes as ``"adapter_evicted"`` instead of
        silently decoding under someone else's factors."""
        if self._role == "decode":
            raise ValueError(
                "role='decode' engines admit work via accept(request, "
                "handoff), not submit() — a prompt with no finished "
                "prefill attached has nothing to decode from"
            )
        return self._admit(request)

    def _admit(self, request: Request) -> int:
        """Shared admission body of :meth:`submit` and :meth:`accept`:
        adapter + paged checks, scheduler enqueue, flight stamp."""
        aid = int(getattr(request, "adapter", 0))
        if aid != 0 and not self._adapters:
            raise ValueError(
                f"request names adapter {aid} but the engine has no "
                "adapter bank (pass ServeEngine(adapter_bank=...))"
            )
        if self._adapters:
            self._bank.check_id(aid)
            request.adapter_gen = self._bank.generation(aid)
        if self._paged:
            # paged admission (ISSUE 13): a request whose prompt+budget
            # needs more pages than the whole pool holds can NEVER be
            # scheduled — synchronous backpressure, same contract as
            # QueueFull. (Transient pressure is different: a request
            # that fits the pool but not the current free list just
            # stays queued — _pop_request skips it until pages free.)
            need = self._pool.pages_needed(
                len(request.prompt) + request.max_new_tokens
            )
            if need > self._pool.pool_pages:
                self._pool.shed()
                if self._flight is not None:
                    self._flight.record(
                        "pool_shed", p_len=len(request.prompt),
                        max_new=request.max_new_tokens, pages=need,
                    )
                raise PoolExhausted(
                    f"request needs {need} pages but the pool holds "
                    f"{self._pool.pool_pages} "
                    f"({self._pool.page_size} tokens each) — shrink the "
                    "request or grow the pool"
                )
        rid = self.scheduler.submit(request)
        if self._flight is not None:
            # stamped AFTER admission: rejected submissions never open a
            # span (the caller got a synchronous exception instead)
            self._flight.request_submitted(
                rid, p_len=len(request.prompt),
                max_new=request.max_new_tokens, adapter=aid,
            )
        return rid

    def accept(self, request: Request, handoff: Handoff) -> int:
        """Decode-role admission: enqueue ``request`` with its finished
        prefill attached. The segment is validated against THIS
        engine's cache layout first (heterogeneous fleets differ in
        window / slot count per role — a mismatched segment must fail
        here, synchronously, never inside a compiled program); adapter
        and paged admission then run exactly as :meth:`submit`'s. The
        handoff's original ``submitted_s`` is restored after the
        scheduler re-stamps, so latency / TTFT span the ORIGINAL
        submit on the prefill side, not the transfer."""
        if self._role != "decode":
            raise ValueError(
                "accept() needs role='decode' — monolithic and "
                "prefill-role engines take work via submit()"
            )
        self._validate_segment(handoff.segment)
        rid = self._admit(request)
        if handoff.submitted_s:
            request.submitted_s = handoff.submitted_s
        self._handoff_in[rid] = handoff
        return rid

    def take_handoff(self, request_id: int) -> Handoff:
        """Pop the finished :class:`..serve.scheduler.Handoff` a
        prefill-role engine emitted for ``request_id`` (the router
        calls this when it sees the ``"handoff"`` completion). The
        record leaves this engine's ownership — device buffers stay
        alive through the handoff's own references."""
        if self._role != "prefill":
            raise ValueError(
                "take_handoff() needs role='prefill' — only prefill-"
                "role engines emit handoffs"
            )
        return self._handoffs.pop(request_id)

    def _validate_segment(self, segment) -> None:
        """Admission check for a transferred segment: the tree must
        have THIS engine's batch-1 cache structure (dtype + rank per
        leaf — a different KV quantization family is a different
        structure and fails here) and fit the serving window (at most
        one axis may differ from the window-length proto, and only
        downward)."""
        p_leaves, p_def = jax.tree_util.tree_flatten(self._proto1)
        leaves, tdef = jax.tree_util.tree_flatten(segment)
        if tdef != p_def:
            raise ValueError(
                "handoff segment does not match this engine's cache "
                "layout (different model config or KV cache family?)"
            )
        for leaf, proto in zip(leaves, p_leaves):
            if leaf.dtype != proto.dtype or leaf.ndim != proto.ndim:
                raise ValueError(
                    f"handoff segment leaf {leaf.dtype}/{leaf.ndim}d "
                    f"does not match this engine's "
                    f"{proto.dtype}/{proto.ndim}d cache leaf"
                )
            diff = [
                i for i in range(leaf.ndim)
                if leaf.shape[i] != proto.shape[i]
            ]
            if len(diff) > 1 or (
                diff and leaf.shape[diff[0]] > proto.shape[diff[0]]
            ):
                raise ValueError(
                    f"handoff segment leaf shape {leaf.shape} does not "
                    f"fit this engine's window (proto {proto.shape})"
                )

    @property
    def role(self) -> str | None:
        return self._role

    @property
    def active_slots(self) -> int:
        return sum(a is not None for a in self._slots)

    @property
    def idle(self) -> bool:
        return (
            self.active_slots == 0
            and len(self.scheduler) == 0
            and not self._pending
            and not self._inflight
            and not self._handoff_in
        )

    @property
    def load(self) -> int:
        """Host-visible backlog: active + pending + queued + accepted
        handoffs awaiting a slot. The router's least-loaded decode
        placement key (ISSUE 18) — pure host counting, no fetch."""
        return (
            self.active_slots
            + len(self._pending)
            + len(self.scheduler)
            + len(self._handoff_in)
        )

    def step(self) -> list[Completion]:
        """One scheduling round: sweep deadline/cancel state over the
        active slots (host bookkeeping at the OBSERVED chain boundary —
        the ONLY place in-flight requests are interrupted), advance any
        chunked prefills by one chunk, refill free slots from the queue
        (one prefill launch each), DISPATCH one decode chain over all
        slots, then fetch the oldest in-flight chain and hand out its
        tokens. At ``pipeline_depth=1`` the dispatched chain IS the
        fetched chain — today's serial loop, op for op; at depth 2 the
        fetch trails dispatch by one chain, so the ~100 ms host
        roundtrip overlaps device execution and host bookkeeping runs
        one chain behind the device. Returns the requests that finished
        this round (possibly mid-chain — surplus chain tokens for a
        finished slot are discarded, exactly like ``generate()``
        truncating at ``max_new_tokens``)."""
        if self._sentry is None:
            return self._step_impl()
        # one sentry accounting round per scheduling round: every fetch
        # inside must arrive through _sentry_fetch or end_round() flags
        # it — the production twin of the test monkeypatch spies
        self._sentry.begin_round(f"step:{self.n_chains}")
        try:
            return self._step_impl()
        finally:
            self._sentry.end_round()

    def _step_impl(self) -> list[Completion]:
        if self._adapters and self._bank.version != self._merged_version:
            # register/evict moved the bank since the last merge: pick
            # the new factors up BEFORE refilling, so freshly admitted
            # tenants never decode against a stale merge (in-flight
            # slots see the new factors too — register into a free row
            # before serving it and this is a non-event for them)
            self.refresh_adapters()
        done: list[Completion] = list(self._sweep())
        if self._flight is not None and done:
            self._flight.sweep(len(done))
        done.extend(self._advance_pending())
        if self._slo:
            # preemption decision at the chain boundary, BEFORE refill:
            # a freed (swapped-out) slot is refillable this very round,
            # so the waiting high-class request starts immediately
            done.extend(self._maybe_preempt())
        for s in range(self.n_slots):
            if self._slots[s] is not None or s in self._pending:
                continue
            req = self._pop_request()
            if req is None:
                break
            if self._flight is not None:
                self._flight.request_popped(req.request_id)
            done.extend(self._refill(s, req))
        if self.active_slots:
            chain_id = self.n_chains
            if self._flight is not None:
                # occupancy at dispatch = chain utilization sample
                self._flight.chain_start(
                    self.active_slots, self.n_slots, chain=chain_id
                )
            if self._chaos is not None:
                chaos_lib.maybe_stall(
                    self._chaos, self.n_chains, flight=self._flight
                )
            if self._inject_logits:
                # global decode-step base for the deterministic injector
                # — a traced scalar, so faulty and clean chains are the
                # same compiled program
                args = (self.params, self._state, jnp.asarray(
                    self.n_chains * self.tokens_per_launch, jnp.int32
                ))
            else:
                args = (self.params, self._state)
            if self._sentry is not None:
                # re-upload probe: a host-numpy leaf in the dispatch
                # tree re-uploads H2D every chain (the
                # device_materialize trap) — isinstance walk, no fetch
                self._sentry.check_args(args, label="decode_chain")
            # async dispatch: self._state becomes the chain's OUTPUT
            # futures. Later parks/prefills/chains consume them without
            # a host sync — device program order runs them after this
            # chain — so the fetch below is the only place the host
            # waits.
            self._state, out = self._chain(*args)
            self.n_chains += 1
            if self._spec:
                self.n_verify_forwards += self.tokens_per_launch
            self._inflight.append(
                _InFlight(out, list(self._slots), chain_id)
            )
        # fetch the oldest chain(s). While slots are active, keep
        # depth-1 chains in flight (depth 1: fetch what was just
        # dispatched — serial); once the observed stream is empty, drain
        # fully (trailing chains carry only junk-decode of parked or
        # naturally-exhausted slots, dropped by the view identity check).
        target = self._depth - 1 if self.active_slots else 0
        while len(self._inflight) > target:
            done.extend(self._collect_chain())
        return done

    def _sentry_fetch(self, x):
        """The budgeted host fetch: every budgeted call site
        (``_collect_chain`` / ``_refill`` / ``_refill_paged`` /
        ``_advance_one`` / ``_accept_refill`` / ``_swap_out``) fetches
        through here so
        the contract sentry (ISSUE 19) can attribute it — a bare
        ``jax.device_get`` anywhere else in the request loop is exactly
        what the sentry's round accounting flags at runtime (and the
        graftcheck ``fetch-budget`` rule flags statically; this wrapper
        is the rule's measuring-instrument exemption, like
        ``serve/__main__.py``). Sentry-off it IS ``jax.device_get`` —
        one extra host-side call frame, nothing else."""
        if self._sentry is not None:
            self._sentry.budgeted_fetch()
        return jax.device_get(x)

    def _collect_chain(self) -> list[Completion]:
        """Fetch the OLDEST in-flight chain (ONE batched ``device_get``
        — the chain's budgeted fetch) and hand its tokens to the slot
        views snapshotted at its dispatch. A slot that completed or was
        refilled inside the pipeline window fails the snapshot identity
        check in the distribute and ignores this chain's junk rows."""
        fl = self._inflight.popleft()
        fetched = self._sentry_fetch(fl.out)  # the chain's ONE host fetch
        gen_before = self.generated_tokens
        if self._spec:
            if self._guard:
                toks, counts, oks = fetched
            else:
                (toks, counts), oks = fetched, None
            done = self._distribute_spec(toks, counts, oks, view=fl.view)
        else:
            if self._guard:
                toks, oks = fetched
            else:
                toks, oks = fetched, None
            done = self._distribute(toks, oks, view=fl.view)
        if self._flight is not None:
            self._flight.chain_end(
                tokens=self.generated_tokens - gen_before,
                occupancy=self.active_slots,
                chain=fl.chain_id,
            )
        return done

    def _pop_request(self) -> Request | None:
        """Queue pop, chunk-aware when chunked prefill is on: with a
        long prompt already mid-chunked-prefill, only requests that fit
        one chunk pop (they slip around the long one into free slots
        instead of queueing a second multi-step prefill behind it).

        Paged engines (ISSUE 13) additionally pass a ``fits`` predicate
        — enough FREE pages for the request's whole prompt + budget
        (conservative: prefix sharing can only reduce the real need) — so
        oversubscribed slot counts degrade to queueing, never to a
        mid-decode allocation failure. When nothing fits but the queue
        is non-empty, cold unpinned prefix segments are evicted one at a
        time (each eviction returns pages to the pool) and the pop
        retried; the loop is bounded by the segment count."""
        fits = None
        if self._paged:
            pool = self._pool

            def fits(r):
                return pool.available >= pool.pages_needed(
                    len(r.prompt) + r.max_new_tokens
                )

        while True:
            if self._chunk:
                req = self.scheduler.pop(
                    chunk=self._chunk, pending_long=len(self._pending),
                    fits=fits,
                )
            else:
                req = self.scheduler.pop(fits=fits)
            if req is not None or fits is None:
                return req
            if (
                len(self.scheduler) == 0
                or self.prefix is None
                or not self.prefix.evict_coldest()
            ):
                return None

    def _deadline_for(self, req: Request) -> float | None:
        return (
            req.deadline_s if req.deadline_s is not None
            else self._deadline
        )

    def _sweep(self) -> list[Completion]:
        """Chain-boundary enforcement of host-side lifecycle state:
        complete active slots whose request was cancelled or whose
        deadline expired. Pure host bookkeeping + the park launch —
        never a device fetch, never a mid-chain interrupt (tokens a
        request earned before the boundary are kept)."""
        done: list[Completion] = []
        if not self._cancelled and self._deadline is None and not any(
            a is not None and a.request.deadline_s is not None
            for a in self._slots
        ):
            return done
        now = time.perf_counter()
        for s, act in enumerate(self._slots):
            if act is None:
                continue
            req = act.request
            reason = None
            if req.request_id in self._cancelled:
                reason = "cancelled"
                self._cancelled.discard(req.request_id)
                self.n_cancelled += 1
            else:
                dl = self._deadline_for(req)
                if dl is not None and now - req.submitted_s > dl:
                    reason = "deadline"
                    self.n_deadline_expired += 1
                    if self._flight is not None:
                        self._flight.fault(
                            "deadline", rid=req.request_id, slot=s
                        )
            if reason is not None:
                self._slots[s] = None
                if self._paged:
                    self._park_paged(s, act)
                elif act.remaining > 0:
                    self._state["remaining"] = self._park(
                        self._state["remaining"], s
                    )
                done.append(self._complete(act, reason))
        return done

    def _maybe_preempt(self) -> list[Completion]:
        """SLO preemption decision (ISSUE 20), at the chain boundary
        only. Pressure = a strictly higher class is waiting AND no slot
        can take it (every slot occupied/pending, or — paged — the pool
        cannot back the best waiter even with a free slot). Under
        pressure the lowest-tier active slot (:func:`..serve.slo.
        choose_victim` — strictly-lower tier only, most recent admit
        loses first) is swapped out. Before the swap the in-flight
        pipeline is DRAINED: the device is ahead of the host's token
        view at depth > 1, and the swap must capture exactly the state
        the host has accounted for — those collections are the chains'
        own already-budgeted fetches, so the budget stays chains +
        prefills + splices + swaps. After draining, the victim is
        re-checked (it may have completed inside a drained chain). The
        chaos ``preempt_at_chain`` injector forces a named slot through
        the same path, once, for pressure-free testing."""
        done: list[Completion] = []
        victim: int | None = None
        c = self._chaos
        if (
            c is not None
            and getattr(c, "preempts", False)
            and not self._chaos_preempt_fired
            and self.n_chains >= c.preempt_at_chain
        ):
            self._chaos_preempt_fired = True
            victim = int(c.preempt_slot)
            if (
                victim >= self.n_slots
                or self._slots[victim] is None
            ):
                return done
        else:
            wait = self.scheduler.peek_priority()
            if wait is None:
                return done
            free = any(
                self._slots[s] is None and s not in self._pending
                for s in range(self.n_slots)
            )
            pressure = not free
            if not pressure and self._paged:
                head = self.scheduler.peek_request()
                if head is not None and int(getattr(
                    head, "priority", 0
                )) == wait:
                    need = self._pool.pages_needed(
                        len(head.prompt) + head.max_new_tokens
                    )
                    pressure = self._pool.available < need
            if not pressure:
                return done
            victim = choose_victim(
                [
                    (s, int(getattr(a.request, "priority", 0)),
                     a.request.request_id)
                    for s, a in enumerate(self._slots)
                    if a is not None
                ],
                wait,
            )
            if victim is None:
                return done
        # drain the pipeline so device state == the host's token view
        # (each collection is that chain's own budgeted fetch)
        while self._inflight:
            done.extend(self._collect_chain())
        if self._slots[victim] is None:
            # the victim finished inside a drained chain — pressure is
            # already relieved by its free slot
            return done
        self._swap_out(victim)
        return done

    def _swap_out(self, slot: int) -> None:
        """Park slot ``slot``'s request to host: ONE budgeted batched
        ``device_get`` (segment + sampling leaves — the swap fetch the
        budget line counts), then the slot parks exactly like a
        completion would (pages return to the pool on paged engines)
        and the request re-enters the queue at its ARRIVAL position
        (``PriorityScheduler.requeue``) holding a
        :class:`..serve.slo.SwapRecord` for the swap-in."""
        act = self._slots[slot]
        req = act.request
        position = len(req.prompt) + len(act.tokens) - 1
        seg_len = bucket_len(position, self.window)
        if self._paged:
            row = jnp.asarray(
                act.pages
                + [self._pool_pages] * (
                    self._pages_per_slot - len(act.pages)
                ),
                jnp.int32,
            )
            out = self._swap_out_jit(
                self._state, row, slot, position, seg_len=seg_len
            )
        else:
            out = self._swap_out_jit(self._state, slot, seg_len=seg_len)
        host = self._sentry_fetch(out)  # the swap's ONE budgeted fetch
        self.n_swaps_out += 1
        self._slots[slot] = None
        if self._paged:
            self._park_paged(slot, act)
        else:
            self._state["remaining"] = self._park(
                self._state["remaining"], slot
            )
        if act.segment is not None:
            # the slot no longer decodes from its splice donor; swap-in
            # re-splices from the parked segment, not the donor
            self.prefix.release(act.segment)
            act.segment = None
        self._swapped[req.request_id] = SwapRecord(
            active=act,
            segment=host["segment"],
            last_tok=host["last_tok"],
            key=host["key"],
            position=position,
            seg_len=seg_len,
            hist=host.get("hist"),
            hist_len=host.get("hist_len"),
            preempt_t=time.perf_counter(),
        )
        self.scheduler.requeue(req)
        if self._flight is not None:
            self._flight.preempted(
                req.request_id, slot=slot, position=position,
                tokens=len(act.tokens),
            )

    def _swap_in(self, slot: int, req: Request,
                 rec: SwapRecord) -> list[Completion]:
        """Resume a preempted request into slot ``slot``: re-upload the
        parked leaves and replay the accept splice with the request's
        LIVE progress (``remaining``/``key``/history verbatim) — zero
        host fetches, so the budget line grows only by swap-OUTS. A
        failure isolates to this request (``"error"``, pre-preemption
        tokens kept), exactly like a raising prefill."""
        act = rec.active
        pages: list[int] = []
        try:
            segment = jax.tree_util.tree_map(jnp.asarray, rec.segment)
            kw = {}
            if self._spec:
                kw["hist"] = jnp.asarray(rec.hist)
                kw["hist_len"] = jnp.asarray(rec.hist_len)
            if self._adapters:
                kw["aid"] = int(getattr(req, "adapter", 0))
            if self._paged:
                pages = self._pool.alloc(self._pool.pages_needed(
                    len(req.prompt) + req.max_new_tokens
                ))
                row = jnp.asarray(
                    pages
                    + [self._pool_pages] * (
                        self._pages_per_slot - len(pages)
                    ),
                    jnp.int32,
                )
                self._state = self._swap_in_jit(
                    self.params, self._state, segment, row,
                    jnp.asarray(rec.last_tok), jnp.asarray(rec.key),
                    rec.position, slot, act.remaining, **kw,
                )
                act.pages = pages
            else:
                self._state = self._swap_in_jit(
                    self.params, self._state, segment,
                    jnp.asarray(rec.last_tok), jnp.asarray(rec.key),
                    rec.position, slot, act.remaining, **kw,
                )
        except Exception:
            if pages:
                self._pool.release_all(pages)
            self.n_prefill_errors += 1
            if self._flight is not None:
                self._flight.fault(
                    "swap_in_error", rid=req.request_id, slot=slot
                )
            return [self._complete(act, "error")]
        self.n_swaps_in += 1
        self._slots[slot] = act
        if self._flight is not None:
            self._flight.resumed(
                req.request_id, slot=slot,
                wait_s=time.perf_counter() - rec.preempt_t,
            )
        return []

    def run_until_idle(self, max_steps: int = 10_000) -> list[Completion]:
        """Drain queue + slots; returns completions in finish order."""
        out: list[Completion] = []
        for _ in range(max_steps):
            if self.idle:
                return out
            out.extend(self.step())
        raise RuntimeError(f"not idle after {max_steps} steps")

    def cancel(self, request_id: int) -> bool:
        """Host-side cancellation. Returns True when ``request_id`` is
        known (queued or decoding) — it will complete with
        ``finish_reason == "cancelled"`` at the next chain/refill
        boundary (queued: zero device work; decoding: tokens earned so
        far are kept, the slot parks). False for ids already finished or
        never submitted. Never interrupts a running chain and never
        costs a device fetch — cancellation is pure bookkeeping the
        boundary sweep enforces."""
        known = any(
            a is not None and a.request.request_id == request_id
            for a in self._slots
        ) or any(
            p.request.request_id == request_id
            for p in self._pending.values()
        ) or self.scheduler.has(request_id)
        if known:
            self._cancelled.add(request_id)
        return known

    @property
    def closed(self) -> bool:
        return self.scheduler.closed

    def close(self) -> None:
        """Stop admitting requests: every later :meth:`submit` raises
        :class:`..serve.scheduler.QueueClosed` (synchronous
        backpressure, like ``QueueFull``). Work already accepted —
        queued or decoding — is unaffected; pair with :meth:`drain` for
        a graceful shutdown. Idempotent."""
        self.scheduler.close()

    def drain(self, max_steps: int = 10_000) -> list[Completion]:
        """Graceful shutdown: :meth:`close` the queue, then run every
        accepted request to completion and return the completions in
        finish order. The engine stays usable for inspection (stats,
        counters) afterwards; it just admits nothing new."""
        self.close()
        return self.run_until_idle(max_steps)

    def _refill(self, slot: int, req: Request) -> list[Completion]:
        """Prefill ``req`` into ``slot``. One launch + one scalar fetch
        (the first sampled token — needed host-side for EOS/max_new==1
        admission into the decode phase).

        With the prefix cache on, a longest-prefix-match against the
        radix index turns the full prefill into a segment splice + a
        prefill over only the uncached suffix (:meth:`_splice_fn`) —
        still one launch + one scalar fetch. Either way the prompt's own
        prefix is inserted into the index (when not already resident),
        and a hit pins its donor segment until this request completes,
        so eviction only ever happens here, BETWEEN decode chains, and
        never under a slot mid-decode.

        Prefix keys are NAMESPACED by the request's (adapter id,
        tenant-generation) pair (:meth:`_prefix_key`): a tenant's K/V
        depends on its factors, so a cross-tenant splice would seed a
        slot with wrong-adapter prefixes — disjoint key ranges make that
        lookup structurally impossible while keeping the index itself
        adapter-oblivious, and the generation keeps it impossible when a
        later tenant recycles an evicted tenant's row.

        The same staleness check guards the request itself: if its
        tenant was evicted (or the row re-registered) since submit, the
        request completes here as ``"adapter_evicted"`` — zero device
        work, zero fetches — rather than decode under zeroed or, worse,
        another tenant's factors. Cancelled or deadline-expired requests
        complete here the same zero-work way (``"cancelled"`` /
        ``"deadline"`` — refill is the queue's boundary, the sweep is
        the active slots'). A prefill that RAISES is isolated to its
        request: the slot parks, the request completes ``"error"``, and
        the engine keeps serving everyone else — one poisoned prompt
        (or one injected :class:`..utils.chaos.ChaosError`) must never
        take the process down.

        A request carrying a :class:`..serve.slo.SwapRecord` (it was
        PREEMPTED while decoding — ISSUE 20) resumes through
        :meth:`_swap_in` instead of prefilling; if it was cancelled or
        expired while parked, it completes with the tokens it earned
        BEFORE the preemption (a preempted request is started work, not
        unstarted)."""
        rec = (
            self._swapped.pop(req.request_id, None)
            if self._slo else None
        )
        if req.request_id in self._cancelled:
            self._cancelled.discard(req.request_id)
            self.n_cancelled += 1
            return [self._bounce(req, rec, "cancelled")]
        dl = self._deadline_for(req)
        if dl is not None and time.perf_counter() - req.submitted_s > dl:
            self.n_deadline_expired += 1
            if self._flight is not None:
                self._flight.fault("deadline", rid=req.request_id)
            return [self._bounce(req, rec, "deadline")]
        aid = int(getattr(req, "adapter", 0))
        if aid and not (
            self._bank.registry.is_live(aid)
            and self._bank.generation(aid) == req.adapter_gen
        ):
            self.adapter_rejected += 1
            if self._flight is not None:
                self._flight.fault(
                    "adapter_evicted", rid=req.request_id, adapter=aid
                )
            return [self._bounce(req, rec, "adapter_evicted")]
        if aid:
            self.adapter_requests += 1
        if rec is not None:
            return self._swap_in(slot, req, rec)
        if self._role == "decode":
            # disaggregated refill (ISSUE 18): the prefill already ran
            # on another engine — splice its transferred segment in
            return self._accept_refill(slot, req)
        prompt = [int(t) for t in req.prompt]
        p_len = len(prompt)
        bucket = bucket_len(p_len, self.window)
        pkey = self._prefix_key(prompt, aid)
        hit = (
            self.prefix.lookup(pkey, self._min_hit_depth)
            if self.prefix is not None
            else None
        )
        grow = self.prefix is not None and tuple(pkey) not in self.prefix
        if self._chunk and (
            p_len - (hit[0] if hit is not None else 0) > self._chunk
        ):
            # chunked prefill: the uncached length exceeds the per-step
            # quantum — stream it in chunks instead of stalling every
            # co-scheduled slot for the whole prompt
            return self._begin_chunked(
                slot, req, prompt, p_len, pkey, hit, grow, aid
            )
        if self._role == "prefill":
            return self._refill_handoff(
                slot, req, prompt, p_len, bucket, pkey, hit, grow, aid
            )
        if self._paged:
            return self._refill_paged(
                slot, req, prompt, p_len, bucket, pkey, hit, grow, aid
            )
        segment = None
        try:
            if self._chaos is not None:
                chaos_lib.maybe_fail_prefill(self._chaos, req.request_id)
            if hit is not None:
                depth, segment = hit
                # pin the donor FIRST: in the except path below,
                # ``segment is not None`` then always means "acquired"
                self.prefix.acquire(segment)
                suffix = prompt[depth:]
                s_bucket = bucket_len(len(suffix), self.window)
                tokens = jnp.asarray(
                    [suffix + [0] * (s_bucket - len(suffix))], jnp.int32
                )
                full = (
                    jnp.asarray(
                        [prompt + [0] * (bucket - p_len)], jnp.int32
                    )
                    if self._spec
                    else tokens  # dead operand when speculation is off
                )
                # aid rides as a keyword ONLY when adapters are on: the
                # off engine's call signature (and so its jaxpr) stays
                # identical
                akw = {"aid": aid} if self._adapters else {}
                self._state, first, new_seg = self._splice(
                    self.params, self._state, segment.handle, tokens,
                    full, depth, p_len, slot, req.seed,
                    req.max_new_tokens, seg_len=bucket, grow=grow, **akw,
                )
                self.n_splices += 1
                self.prefix_hit_tokens += depth
            else:
                padded = prompt + [0] * (bucket - p_len)
                tokens = jnp.asarray([padded], jnp.int32)
                akw = {"aid": aid} if self._adapters else {}
                self._state, first, new_seg = self._prefill(
                    self.params, self._state, tokens, p_len, slot,
                    req.seed, req.max_new_tokens, **akw,
                )
                self.n_prefills += 1
            if grow:
                self.prefix.insert(
                    tuple(pkey), new_seg, self._nbytes(new_seg)
                )
            first = int(self._sentry_fetch(first))
        except Exception:
            # request-level isolation: unpin any splice donor, park the
            # slot (prefill may have set its device-side budget before
            # raising — the park makes later chains treat it as
            # inactive; refill rewrites the whole slot anyway) and keep
            # serving. The fault is reported through the completion.
            if segment is not None:
                self.prefix.release(segment)
            self.n_prefill_errors += 1
            if self._flight is not None:
                self._flight.fault(
                    "prefill_error", rid=req.request_id, slot=slot
                )
            self._state["remaining"] = self._park(
                self._state["remaining"], slot
            )
            return [self._complete_unstarted(req, "error")]
        return self._activate(
            slot, req, first, segment,
            hit[0] if segment is not None else 0,
        )

    def _refill_paged(self, slot: int, req: Request, prompt: list[int],
                      p_len: int, bucket: int, pkey: list[int], hit,
                      grow: bool, aid: int) -> list[Completion]:
        """Paged twin of :meth:`_refill`'s device leg. The host side owns
        all page arithmetic — which donor pages are shared in place,
        which one boundary page copy-on-writes, which fresh pages the
        pool hands out — and ships it to the device as one traced row
        vector plus a CoW id pair; the device programs never recompile
        on geometry. ``_pop_request``'s ``fits`` predicate guaranteed
        the fresh allocation below succeeds (conservatively — sharing
        only reduces the need), so ``PoolExhausted`` here would be a
        bookkeeping bug, caught by the same isolation path as a raising
        prefill."""
        pool = self._pool
        ps = self._page_size
        sentinel = self._pool_pages
        n_alloc = pool.pages_needed(p_len + req.max_new_tokens)
        segment = None
        pages: list[int] = []
        try:
            if self._chaos is not None:
                chaos_lib.maybe_fail_prefill(self._chaos, req.request_id)
            akw = {"aid": aid} if self._adapters else {}
            if hit is not None:
                depth, segment = hit
                # pin the donor FIRST, same contract as the classic path
                self.prefix.acquire(segment)
                shared_full = depth // ps
                boundary = depth % ps != 0
                # shared pages are refcounted BEFORE the fresh alloc so
                # the except path below can release `pages` uniformly
                for pid in segment.handle[:shared_full]:
                    pool.retain(pid)
                pages = list(segment.handle[:shared_full])
                pages = pages + pool.alloc(n_alloc - shared_full)
                # a partially-shared boundary page copy-on-writes into
                # the first fresh page; page-aligned depth passes the
                # sentinel pair (the compiled gather/scatter no-ops)
                cow_src = (
                    int(segment.handle[shared_full]) if boundary
                    else sentinel
                )
                cow_dst = pages[shared_full] if boundary else sentinel
                if boundary and self._flight is not None:
                    self._flight.record(
                        "page_cow", rid=req.request_id, slot=slot,
                        src=cow_src, dst=cow_dst, depth=depth,
                    )
                row = jnp.asarray(
                    pages + [sentinel] * (self._pages_per_slot - n_alloc),
                    jnp.int32,
                )
                suffix = prompt[depth:]
                s_bucket = bucket_len(len(suffix), self.window)
                tokens = jnp.asarray(
                    [suffix + [0] * (s_bucket - len(suffix))], jnp.int32
                )
                full = (
                    jnp.asarray(
                        [prompt + [0] * (bucket - p_len)], jnp.int32
                    )
                    if self._spec
                    else tokens  # dead operand when speculation is off
                )
                self._state, first = self._splice_paged(
                    self.params, self._state, row, tokens, full, depth,
                    p_len, slot, req.seed, req.max_new_tokens,
                    cow_src, cow_dst, **akw,
                )
                self.n_splices += 1
                self.prefix_hit_tokens += depth
            else:
                pages = pool.alloc(n_alloc)
                row = jnp.asarray(
                    pages + [sentinel] * (self._pages_per_slot - n_alloc),
                    jnp.int32,
                )
                padded = prompt + [0] * (bucket - p_len)
                tokens = jnp.asarray([padded], jnp.int32)
                self._state, first = self._prefill_paged(
                    self.params, self._state, tokens, row, p_len, slot,
                    req.seed, req.max_new_tokens, **akw,
                )
                self.n_prefills += 1
            if grow:
                self._insert_paged_segment(pkey, pages, p_len)
            first = int(self._sentry_fetch(first))
        except Exception:
            if segment is not None:
                self.prefix.release(segment)
            if pages:
                pool.release_all(pages)
            self.n_prefill_errors += 1
            if self._flight is not None:
                self._flight.fault(
                    "prefill_error", rid=req.request_id, slot=slot
                )
            # paged park: sentinel the table too — the failed prefill
            # may have scattered into pages just released above
            self._state = self._paged_park(self._state, slot)
            return [self._complete_unstarted(req, "error")]
        return self._activate(
            slot, req, first, segment,
            hit[0] if segment is not None else 0, pages=pages,
        )

    def _refill_handoff(self, slot: int, req: Request,
                        prompt: list[int], p_len: int, bucket: int,
                        pkey: list[int], hit, grow: bool,
                        aid: int) -> list[Completion]:
        """Prefill-role refill: run the prompt's prefill (or prefix
        splice) and EMIT the finished segment as a
        :class:`..serve.scheduler.Handoff` instead of occupying a slot.
        Pure async dispatch — segment, first token and PRNG key stay
        device futures, so a prefill-role engine performs ZERO fetches.
        The request completes immediately with ``finish_reason ==
        "handoff"`` (zero tokens here; the decode side reports them).
        Prefix-index growth is unchanged: the outgoing segment doubles
        as the insert candidate, so multi-turn streams deepen the
        prefill side's index exactly as a monolithic engine's. A
        raising prefill is isolated to its request (``"error"``, donor
        unpinned, nothing was written to slot state so no park is
        needed)."""
        segment = None
        try:
            if self._chaos is not None:
                chaos_lib.maybe_fail_prefill(self._chaos, req.request_id)
            akw = {"aid": aid} if self._adapters else {}
            if hit is not None:
                depth, segment = hit
                # pin the donor FIRST, same contract as _refill
                self.prefix.acquire(segment)
                suffix = prompt[depth:]
                s_bucket = bucket_len(len(suffix), self.window)
                tokens = jnp.asarray(
                    [suffix + [0] * (s_bucket - len(suffix))], jnp.int32
                )
                seg, first, key = self._handoff_splice(
                    self.params, segment.handle, tokens, depth, p_len,
                    req.seed, seg_len=bucket, **akw,
                )
                self.n_splices += 1
                self.prefix_hit_tokens += depth
                # the splice is dispatched; its computation holds its
                # own references, so the donor unpins at the SAME
                # boundary a monolithic engine's completion would
                self.prefix.release(segment)
                segment = None
            else:
                padded = prompt + [0] * (bucket - p_len)
                tokens = jnp.asarray([padded], jnp.int32)
                seg, first, key = self._handoff_prefill(
                    self.params, tokens, p_len, req.seed, **akw,
                )
                self.n_prefills += 1
            if grow:
                self.prefix.insert(tuple(pkey), seg, self._nbytes(seg))
        except Exception:
            if segment is not None:
                self.prefix.release(segment)
            self.n_prefill_errors += 1
            if self._flight is not None:
                self._flight.fault(
                    "prefill_error", rid=req.request_id, slot=slot
                )
            return [self._complete_unstarted(req, "error")]
        return self._emit_handoff(req, seg, first, key, p_len, bucket)

    def _emit_handoff(self, req: Request, seg, first, key, p_len: int,
                      bucket: int) -> list[Completion]:
        """Park a finished prefill in the outgoing handoff map and
        complete the request ``"handoff"`` — the router (or any
        caller) collects the record via :meth:`take_handoff`. Host
        bookkeeping only; every field stays a device future."""
        self._handoffs[req.request_id] = Handoff(
            segment=seg, first=first, key=key, p_len=p_len,
            bucket=bucket, aid=int(getattr(req, "adapter", 0)),
            submitted_s=req.submitted_s,
        )
        self.n_handoffs_out += 1
        if self._flight is not None:
            self._flight.record(
                "handoff_emit", rid=req.request_id, p_len=p_len
            )
        return [self._complete_unstarted(req, "handoff")]

    def _accept_refill(self, slot: int, req: Request) -> list[Completion]:
        """Decode-role refill: splice the request's transferred segment
        into ``slot`` (:meth:`_accept_fn` / :meth:`_accept_paged_fn`)
        and fetch the handoff's first token — THE one budgeted scalar
        fetch of the disaggregated path (graftcheck ``fetch-budget``
        names this function; the prefill side fetched nothing). The
        decode-role budget is therefore chains + handoffs, and the
        fleet budget stays the sum of per-role budgets. A failing
        accept is isolated exactly like a raising prefill: pages
        released, slot parked, ``"error"`` completion, the engine
        keeps serving."""
        h = self._handoff_in.pop(req.request_id)
        pages: list[int] = []
        p_len = h.p_len
        try:
            if self._chaos is not None:
                chaos_lib.maybe_fail_prefill(self._chaos, req.request_id)
            akw = {"aid": h.aid} if self._adapters else {}
            prompt = [int(t) for t in req.prompt]
            # bucket-padded prompt seeds the n-gram history (dead
            # operand when speculation is off, like _splice_fn's)
            full = jnp.asarray(
                [prompt + [0] * (h.bucket - p_len)], jnp.int32
            )
            if self._paged:
                n_alloc = self._pool.pages_needed(
                    p_len + req.max_new_tokens
                )
                pages = self._pool.alloc(n_alloc)
                row = jnp.asarray(
                    pages + [self._pool_pages]
                    * (self._pages_per_slot - n_alloc),
                    jnp.int32,
                )
                self._state, first = self._accept_jit(
                    self.params, self._state, h.segment, full, row,
                    h.first, h.key, p_len, slot, req.max_new_tokens,
                    **akw,
                )
            else:
                self._state, first = self._accept_jit(
                    self.params, self._state, h.segment, full,
                    h.first, h.key, p_len, slot, req.max_new_tokens,
                    **akw,
                )
            self.n_handoffs_in += 1
            first = int(self._sentry_fetch(first))  # the handoff's ONE fetch
        except Exception:
            if pages:
                self._pool.release_all(pages)
            self.n_prefill_errors += 1
            if self._flight is not None:
                self._flight.fault(
                    "prefill_error", rid=req.request_id, slot=slot
                )
            if self._paged:
                self._state = self._paged_park(self._state, slot)
            else:
                self._state["remaining"] = self._park(
                    self._state["remaining"], slot
                )
            return [self._complete_unstarted(req, "error")]
        return self._activate(
            slot, req, first, None, 0, pages=pages, kind="handoff"
        )

    def _insert_paged_segment(self, pkey: list[int], pages: list[int],
                              p_len: int) -> None:
        """Insert-on-prefill, paged flavor: the retained "segment" is the
        tuple of page ids covering the prompt's positions — the pool
        pages themselves are the storage, so retention costs ZERO extra
        HBM (the classic path copies a whole bucket-length cache tree).
        Page refs are taken FIRST; a refused insert (duplicate key /
        budget full of pinned segments) releases them, so pool
        accounting is exact either way. The index prices the segment at
        page granularity (pages x page_bytes)."""
        seg_ids = tuple(pages[: self._pool.pages_needed(p_len)])
        for pid in seg_ids:
            self._pool.retain(pid)
        if not self.prefix.insert(
            tuple(pkey), seg_ids, len(seg_ids) * self._page_bytes
        ):
            self._pool.release_all(seg_ids)

    def _release_segment_pages(self, seg) -> None:
        """Prefix-index eviction hook (paged engines): a dropped segment
        returns its page references to the pool. Runs BEFORE the index
        clears ``seg.handle``; eviction only ever happens at refill /
        pop time, and pinned (refcount > 0) segments are never victims,
        so no live slot is decoding through these pages when they
        free."""
        self._pool.release_all(seg.handle)

    def _park_paged(self, slot: int, act: _Active | None = None) -> None:
        """Host half of paged parking: dispatch the sentinel-table park
        program and hand the slot's page references back to the pool.
        Safe against in-flight chains by device program order — see
        :meth:`_paged_park_fn`."""
        self._state = self._paged_park(self._state, slot)
        if act is not None and act.pages:
            self._pool.release_all(act.pages)
            act.pages = []

    def _activate(self, slot: int, req: Request, first: int, segment,
                  cached_len: int, pages=None,
                  kind: str | None = None) -> list[Completion]:
        """Admit a just-prefilled request into the decode phase — the
        shared tail of :meth:`_refill` and a chunked prefill's final
        chunk. ``segment`` pins the splice donor until completion; an
        EOS / ``max_new == 1`` first token completes immediately and
        parks the slot (its device-side counter still shows budget).
        ``pages`` (paged engines) transfers the slot's page references
        onto the active record — released whenever the slot parks.
        ``kind`` overrides the flight-event classification (the
        disaggregated accept path stamps ``"handoff"``)."""
        self.generated_tokens += 1
        act = _Active(req, first)
        if pages:
            act.pages = pages
        act.ttft_s = time.perf_counter() - req.submitted_s
        if self._flight is not None:
            # stamped after the scalar fetch: the first token exists, so
            # the span's prefill_t is an honest first-token time
            self._flight.request_prefilled(
                req.request_id, slot,
                kind=kind
                or ("splice" if segment is not None else "prefill"),
                cached_len=cached_len,
            )
        if segment is not None:
            act.segment = segment
        if req.max_new_tokens == 1 or first == req.eos_token:
            reason = "eos" if first == req.eos_token else "length"
            if self._paged:
                self._park_paged(slot, act)
            elif act.remaining > 0:
                # early EOS: the device-side counter still shows budget;
                # park the slot so later chains treat it as inactive
                self._state["remaining"] = self._park(
                    self._state["remaining"], slot
                )
            return [self._complete(act, reason)]
        self._slots[slot] = act
        return []

    def _begin_chunked(self, slot: int, req: Request, prompt: list[int],
                       p_len: int, pkey: list[int], hit, grow: bool,
                       aid: int) -> list[Completion]:
        """Start a chunked prefill (ISSUE 11 leg b): seed a batch-1 side
        cache — zeroed, or spliced from a prefix-cache hit at its
        matched depth — and register the slot as pending. Chunks advance
        one per :meth:`step` via :meth:`_advance_pending`; until the
        final chunk lands, the slot's device budget stays 0 (decode
        chains treat it as inactive) and no fetch happens, so
        co-scheduled slots keep decoding while this prompt streams in."""
        pend = _PendingPrefill(req, slot)
        pend.prompt = prompt
        pend.aid = aid
        pend.grow = grow
        pend.pkey = pkey
        try:
            if self._chaos is not None:
                chaos_lib.maybe_fail_prefill(self._chaos, req.request_id)
            if self._paged:
                # all the slot's pages are FRESH for chunked prompts
                # (the side cache re-prefills shared positions too, so
                # the final scatter owns every page it writes — sharing
                # is lost for chunked prompts, a documented trade)
                pend.pages = self._pool.alloc(
                    self._pool.pages_needed(p_len + req.max_new_tokens)
                )
            if hit is not None:
                depth, segment = hit
                # pin the donor FIRST, same contract as _refill
                self.prefix.acquire(segment)
                pend.segment = segment
                pend.depth = depth
                if self._paged:
                    # gather-copy the donor's pages into the side cache
                    n_seg = self._pool.pages_needed(depth)
                    srow = jnp.asarray(
                        list(segment.handle[:n_seg])
                        + [self._pool_pages]
                        * (self._pages_per_slot - n_seg),
                        jnp.int32,
                    )
                    pend.cache1 = self._chunk_seed_paged(
                        self._state["cache"], srow, depth
                    )
                else:
                    pend.cache1 = self._chunk_seed(segment.handle, depth)
            else:
                pend.cache1 = self._chunk_zero()
        except Exception:
            if pend.segment is not None:
                self.prefix.release(pend.segment)
            if pend.pages:
                self._pool.release_all(pend.pages)
                pend.pages = []
            self.n_prefill_errors += 1
            if self._flight is not None:
                self._flight.fault(
                    "prefill_error", rid=req.request_id, slot=slot
                )
            # no park needed: the slot was free, its device budget is 0
            return [self._complete_unstarted(req, "error")]
        pend.done = pend.depth
        self._pending[slot] = pend
        # the first chunk runs in the SAME step the slot was claimed —
        # a pending prefill never wastes its admission round
        return self._advance_one(pend)

    def _advance_pending(self) -> list[Completion]:
        """Advance every chunked prefill by ONE chunk — the per-step
        prefill quantum. Mid chunks are a single async dispatch into the
        pending request's side cache (no fetch); a final chunk splices
        into the slot and fetches the first token (the budgeted
        prefill/splice fetch). Runs BEFORE refill in :meth:`step`, so a
        prefill begun this round is not advanced twice."""
        done: list[Completion] = []
        for slot in list(self._pending):
            done.extend(self._advance_one(self._pending[slot]))
        return done

    def _advance_one(self, pend: _PendingPrefill) -> list[Completion]:
        req = pend.request
        slot = pend.slot
        # pending prefills honor the same boundary lifecycle as queued
        # requests: cancel/deadline complete them with zero tokens (the
        # side cache is dropped, the donor segment unpinned)
        if req.request_id in self._cancelled:
            self._cancelled.discard(req.request_id)
            self.n_cancelled += 1
            self._abandon_pending(pend)
            return [self._complete_unstarted(req, "cancelled")]
        dl = self._deadline_for(req)
        if dl is not None and time.perf_counter() - req.submitted_s > dl:
            self.n_deadline_expired += 1
            if self._flight is not None:
                self._flight.fault(
                    "deadline", rid=req.request_id, slot=slot
                )
            self._abandon_pending(pend)
            return [self._complete_unstarted(req, "deadline")]
        p_len = len(pend.prompt)
        rem = p_len - pend.done
        akw = {"aid": pend.aid} if self._adapters else {}
        try:
            if rem > self._chunk:
                # mid chunk: exactly prefill_chunk tokens (full chunks
                # need no padding — ONE compiled shape), async dispatch
                # only
                tokens = jnp.asarray(
                    [pend.prompt[pend.done:pend.done + self._chunk]],
                    jnp.int32,
                )
                pend.cache1 = self._chunk_step(
                    self.params, pend.cache1, tokens, **akw
                )
                pend.done += self._chunk
                self.n_chunks += 1
                if self._flight is not None:
                    self._flight.prefill_chunk(
                        req.request_id, slot, done=pend.done, total=p_len
                    )
                return []
            # final chunk: splice into the slot + fetch the first token
            # (THE budgeted prefill/splice fetch for this request)
            f_bucket = bucket_len(rem, self.window)
            suffix = pend.prompt[pend.done:]
            tokens = jnp.asarray(
                [suffix + [0] * (f_bucket - rem)], jnp.int32
            )
            bucket = bucket_len(p_len, self.window)
            if self._role == "prefill":
                # disaggregated final chunk (ISSUE 18): extract the
                # finished segment from the side cache and EMIT it —
                # no slot splice, no fetch (the decode side fetches)
                seg, first, key = self._handoff_final(
                    self.params, pend.cache1, tokens, rem - 1,
                    req.seed, seg_len=bucket, **akw,
                )
                self.n_chunks += 1
                if pend.segment is not None:
                    self.n_splices += 1
                    self.prefix_hit_tokens += pend.depth
                    self.prefix.release(pend.segment)
                    pend.segment = None
                else:
                    self.n_prefills += 1
                if pend.grow:
                    self.prefix.insert(
                        tuple(pend.pkey), seg, self._nbytes(seg)
                    )
                del self._pending[slot]
                return self._emit_handoff(
                    req, seg, first, key, p_len, bucket
                )
            full = (
                jnp.asarray(
                    [pend.prompt + [0] * (bucket - p_len)], jnp.int32
                )
                if self._spec
                else tokens  # dead operand when speculation is off
            )
            if self._paged:
                row = jnp.asarray(
                    pend.pages
                    + [self._pool_pages]
                    * (self._pages_per_slot - len(pend.pages)),
                    jnp.int32,
                )
                self._state, first = self._chunk_final_paged(
                    self.params, pend.cache1, self._state, tokens, full,
                    rem - 1, p_len, slot, req.seed, req.max_new_tokens,
                    row, **akw,
                )
            else:
                self._state, first, new_seg = self._chunk_final(
                    self.params, pend.cache1, self._state, tokens, full,
                    rem - 1, p_len, slot, req.seed, req.max_new_tokens,
                    seg_len=bucket, grow=pend.grow, **akw,
                )
            self.n_chunks += 1
            if pend.segment is not None:
                self.n_splices += 1
                self.prefix_hit_tokens += pend.depth
            else:
                self.n_prefills += 1
            if pend.grow:
                if self._paged:
                    self._insert_paged_segment(
                        pend.pkey, pend.pages, p_len
                    )
                else:
                    self.prefix.insert(
                        tuple(pend.pkey), new_seg, self._nbytes(new_seg)
                    )
            first = int(self._sentry_fetch(first))
        except Exception:
            self._abandon_pending(pend)  # also releases pend.pages
            self.n_prefill_errors += 1
            if self._flight is not None:
                self._flight.fault(
                    "prefill_error", rid=req.request_id, slot=slot
                )
            # defensive park, same as _refill: the final chunk may have
            # set the slot's device budget before raising
            if self._paged:
                self._state = self._paged_park(self._state, slot)
            else:
                self._state["remaining"] = self._park(
                    self._state["remaining"], slot
                )
            return [self._complete_unstarted(req, "error")]
        segment = pend.segment
        cached_len = pend.depth
        pages = pend.pages
        pend.pages = []  # ownership moves to the active record
        del self._pending[slot]
        return self._activate(
            slot, req, first, segment, cached_len, pages=pages
        )

    def _abandon_pending(self, pend: _PendingPrefill) -> None:
        """Drop a pending chunked prefill: unpin its splice donor,
        return its pre-allocated pages (paged engines), and free the
        slot for the next refill. The side cache futures are simply
        released (nothing was spliced into slot state, and the slot's
        device budget — and page table — were never set, so no park is
        needed)."""
        if pend.segment is not None:
            self.prefix.release(pend.segment)
            pend.segment = None
        if pend.pages:
            self._pool.release_all(pend.pages)
            pend.pages = []
        self._pending.pop(pend.slot, None)

    def _prefix_key(self, prompt: list[int], aid: int) -> list[int]:
        """Tenant-scoped prefix-index key: shift every token by
        ``(generation * n_adapters + aid) * vocab_size`` so each tenant
        INCARNATION occupies a disjoint key range — same LPM depth
        within a tenant, zero matches across tenants. The generation
        matters because rows recycle: evict A, register B, and B lands
        on A's row — a bare-aid namespace would hand B LPM hits whose
        segments hold KV computed with A's factors. Segments keyed under
        a dead generation simply stop being reachable and age out of the
        byte budget via LRU. Host-only arithmetic (the index never sees
        real token ids for aid > 0, which is fine: keys are opaque to
        it); aid 0 keys are the raw prompt (row 0 is never reassigned,
        its generation is pinned 0), so base-model streams share the
        index exactly as before the bank existed."""
        if aid == 0:
            return prompt
        ns = self._bank.generation(aid) * self._bank.n_adapters + aid
        shift = ns * int(self.model.cfg.vocab_size)
        return [t + shift for t in prompt]

    def _distribute(self, toks, oks=None, view=None) -> list[Completion]:
        """Hand one fetched (S, T) chain block out to the slots' host
        views; free every slot that finished (budget exhausted or EOS
        mid-chain) and park early-EOS slots whose device counter still
        shows budget.

        ``view`` is the slot snapshot taken when this chain was
        DISPATCHED (``None`` = the live slots, the depth-1 case where
        nothing can change in between): a slot whose ``_Active`` is no
        longer the live one — completed or refilled inside the pipeline
        window — fails the identity check and ignores this chain's junk
        rows.

        ``oks`` (guard on) is the fetched (S, T) finite-logits flag: the
        first False step for a slot means that step's token — and
        everything after it — was sampled from NaN/Inf logits. The slot
        completes ``"nonfinite"`` with only its pre-poison tokens and is
        quarantined (parked; the next refill rewrites the slot whole,
        position counter included). Other slots' rows are untouched —
        the per-slot forward is independent across the batch dim, so
        co-scheduled requests decode token-identically to a clean run."""
        done: list[Completion] = []
        for s, act in enumerate(self._slots if view is None else view):
            if act is None or act is not self._slots[s]:
                continue
            reason = None
            for t, tok_ in enumerate(toks[s, : act.remaining]):
                if oks is not None and not oks[s, t]:
                    reason = "nonfinite"
                    self.nonfinite_quarantined += 1
                    if self._flight is not None:
                        self._flight.fault(
                            "nonfinite", rid=act.request.request_id,
                            slot=s, chain_step=t,
                        )
                    break
                tok = int(tok_)
                act.tokens.append(tok)
                act.remaining -= 1
                self.generated_tokens += 1
                if tok == act.request.eos_token:
                    reason = "eos"
                    break
            if reason is None and act.remaining == 0:
                reason = "length"
            if reason is not None:
                self._slots[s] = None
                if self._paged:
                    # paged parks on EVERY completion: an inactive slot
                    # still K/V-writes at advancing positions, and a
                    # live table would route them into freed pages
                    self._park_paged(s, act)
                elif act.remaining > 0:  # finished mid-chain (EOS/poison)
                    self._state["remaining"] = self._park(
                        self._state["remaining"], s
                    )
                done.append(self._complete(act, reason))
        return done

    def _distribute_spec(self, toks, counts, oks=None,
                         view=None) -> list[Completion]:
        """Speculative twin of :meth:`_distribute`: unpack one fetched
        (S, T, k+1) block. Step t of slot s contributed ``counts[s, t]``
        real tokens — the accepted draft prefix plus the bonus/rejection
        token — and the rest of the row is padding. The host truncates at
        the request's budget exactly like ``generate()`` does (the device
        may have verified past it within the chain; those writes land in
        the slot's own window and refill rewrites the whole slot).
        ``view`` follows the :meth:`_distribute` pipeline-window identity
        contract; ``oks`` the quarantine contract at verify-step
        granularity (a poisoned verify step discards all of that step's
        emissions)."""
        done: list[Completion] = []
        for s, act in enumerate(self._slots if view is None else view):
            if act is None or act is not self._slots[s]:
                continue
            reason = None
            for t in range(counts.shape[1]):
                if oks is not None and not oks[s, t]:
                    reason = "nonfinite"
                    self.nonfinite_quarantined += 1
                    if self._flight is not None:
                        self._flight.fault(
                            "nonfinite", rid=act.request.request_id,
                            slot=s, chain_step=t,
                        )
                    break
                n = int(counts[s, t])
                if n == 0:  # slot went inactive device-side
                    break
                self.spec_steps_consumed += 1
                self.spec_drafts_accepted += n - 1
                for tok_ in toks[s, t, : min(n, act.remaining)]:
                    tok = int(tok_)
                    act.tokens.append(tok)
                    act.remaining -= 1
                    self.generated_tokens += 1
                    if tok == act.request.eos_token:
                        reason = "eos"
                        break
                if reason is not None or act.remaining == 0:
                    break
            if reason is None and act.remaining == 0:
                reason = "length"
            if reason is not None:
                self._slots[s] = None
                if self._paged:
                    self._park_paged(s, act)
                elif act.remaining > 0:  # finished mid-chain via EOS
                    self._state["remaining"] = self._park(
                        self._state["remaining"], s
                    )
                done.append(self._complete(act, reason))
        return done

    def _complete_unstarted(self, req: Request, reason: str) -> Completion:
        """A zero-token completion for a request bounced at a boundary
        before any device work (cancelled / deadline / adapter_evicted /
        prefill error): zero fetches, zero tokens, synchronous. Drops
        any accepted-but-unspliced handoff for the request (a decode
        request cancelled while queued must not strand its transfer
        record — the device futures are simply released)."""
        self._handoff_in.pop(req.request_id, None)
        comp = Completion(
            request_id=req.request_id,
            prompt=[int(t) for t in req.prompt],
            tokens=[],
            finish_reason=reason,
            latency_s=time.perf_counter() - req.submitted_s,
        )
        if self._flight is not None:
            self._flight.request_completed(
                req.request_id, reason, tokens=0,
                latency_s=comp.latency_s,
            )
        return comp

    def _bounce(self, req: Request, rec, reason: str) -> Completion:
        """Boundary completion for a request the refill lifecycle checks
        reject: zero-work (:meth:`_complete_unstarted`) for a request
        that never started, but a PREEMPTED request (carrying a
        :class:`..serve.slo.SwapRecord`) keeps the tokens it earned
        before the swap — preemption must never silently discard
        delivered progress."""
        if rec is not None:
            return self._complete(rec.active, reason)
        return self._complete_unstarted(req, reason)

    def _complete(self, act: _Active, reason: str) -> Completion:
        if act.segment is not None:
            # the slot no longer decodes from this segment's splice;
            # unpin it (it stays resident + hot for the next hit)
            self.prefix.release(act.segment)
            act.segment = None
        comp = Completion(
            request_id=act.request.request_id,
            prompt=[int(t) for t in act.request.prompt],
            tokens=act.tokens,
            finish_reason=reason,
            latency_s=time.perf_counter() - act.request.submitted_s,
            ttft_s=act.ttft_s,
        )
        if self._flight is not None:
            # the span records the Completion's OWN numbers, so the
            # histogram percentiles are sample-identical to sorting the
            # completion list (only the bucket rounding differs)
            self._flight.request_completed(
                comp.request_id, reason, tokens=len(comp.tokens),
                latency_s=comp.latency_s, ttft_s=comp.ttft_s,
            )
        return comp

    def prefix_stats(self) -> dict[str, int | float]:
        """Prefix-cache counters for the serving receipt: index stats
        (segments / used+evicted bytes / hits / misses) plus the engine's
        splice count, reused-token total, and the resulting hit rate.
        All host bookkeeping — reading them costs no device fetch."""
        if self.prefix is None:
            return {"prefix_cache": 0}
        looked = self.prefix.hits + self.prefix.misses
        return {
            "prefix_cache": 1,
            **{f"prefix_{k}": v for k, v in self.prefix.stats().items()},
            "prefix_hit_rate": self.prefix.hits / max(1, looked),
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "n_splices": self.n_splices,
        }

    def spec_stats(self) -> dict[str, int | float]:
        """Speculation counters for the serving receipt. Mean accepted
        length is per CONSUMED verify step (1.0 would mean drafting never
        helped; the mechanism receipt wants > 1); acceptance rate is the
        fraction of offered draft tokens accepted. All host bookkeeping —
        no device fetch."""
        if not self._spec:
            return {"speculative": 0}
        steps = max(1, self.spec_steps_consumed)
        return {
            "speculative": 1,
            "spec_k": self._spec_k,
            "spec_ngram": self._spec_ngram,
            "n_verify_forwards": self.n_verify_forwards,
            "spec_steps_consumed": self.spec_steps_consumed,
            "spec_drafts_accepted": self.spec_drafts_accepted,
            "spec_mean_accepted_len":
                1.0 + self.spec_drafts_accepted / steps,
            "spec_acceptance_rate":
                self.spec_drafts_accepted / (steps * self._spec_k),
        }

    def fault_stats(self) -> dict[str, int | float]:
        """Robustness counters for the serving receipt (same pattern as
        :meth:`spec_stats` — host bookkeeping, no device fetch):
        configured deadline/guard/chaos state plus how much traffic each
        failure path handled. The counters are OUTCOMES, not config —
        regress.py fingerprints only ``chaos``/``deadline_s``/
        ``guard_nonfinite`` so chaos rounds never gate clean rounds."""
        return {
            "deadline_s": float(self._deadline or 0.0),
            "guard_nonfinite": int(self._guard),
            "chaos": int(self._chaos is not None),
            "deadline_expired": self.n_deadline_expired,
            "cancelled": self.n_cancelled,
            "nonfinite_quarantined": self.nonfinite_quarantined,
            "prefill_errors": self.n_prefill_errors,
        }

    def refresh_adapters(self) -> None:
        """Re-merge the bank's factors into the served params after a
        :meth:`..adapters.bank.AdapterBank.register` / ``evict`` on a
        LIVE engine. The factor arrays are functionally updated, so the
        engine's merged tree must be rebuilt — shapes are unchanged, so
        nothing recompiles. :meth:`step` calls this AUTOMATICALLY when
        the bank's version moved past the engine's last merge, so a
        plain register -> submit -> step sequence serves the new factors
        with no extra call; invoke it directly only to take the re-merge
        eagerly. Requests already decoding keep their slot's id but see
        the new factors (register into a FREE row before serving it and
        this is a non-event for in-flight traffic)."""
        if not self._adapters:
            raise ValueError("engine has no adapter bank")
        merged = self._bank.merge_params(self._base_params)
        if self._shard:
            # keep the re-merged tree committed to its rule shardings —
            # an uncommitted replacement would silently recompile every
            # program against replicated params
            merged = self._strategy.shard_state(merged)
        self.params = merged
        self._merged_version = self._bank.version
        if self._flight is not None:
            self._flight.record(
                "adapter_refresh", version=self._merged_version
            )

    def adapter_stats(self) -> dict[str, int | float]:
        """Multi-tenancy counters for the serving receipt (same pattern
        as :meth:`spec_stats`): bank geometry + registry occupancy + how
        much traffic ran under a non-base adapter. All host bookkeeping —
        no device fetch."""
        if not self._adapters:
            return {"adapters": 0}
        reg = self._bank.registry
        return {
            "adapters": 1,
            "n_adapters": self._bank.n_adapters,
            "lora_rank": self._bank.rank,
            "adapters_registered": len(reg),
            "adapter_requests": self.adapter_requests,
            "adapter_rejected": self.adapter_rejected,
            "adapter_bytes": reg.used_bytes,
        }

    def flight_stats(self) -> dict[str, int | float]:
        """Flight-recorder aggregate for the serving receipt: event /
        span / dump counters + the streaming-histogram percentiles
        (``ttft_p95_s``-style keys). ``{"flight": 0}`` when the recorder
        is off — regress.py fingerprints the flag so instrumented and
        bare rounds never gate each other. Host bookkeeping only."""
        if self._flight is None:
            return {"flight": 0}
        return self._flight.summary()

    def pipeline_stats(self) -> dict[str, int | float]:
        """Pipelining counters for the serving receipt (ISSUE 11):
        configured depth / prefill quantum plus how many prefill chunks
        ran. regress.py fingerprints ``pipeline_depth`` /
        ``prefill_chunk`` so pipelined and serial rounds never gate each
        other; ``n_chunks`` is an outcome and stays out. Host
        bookkeeping only — no device fetch."""
        return {
            "pipeline_depth": self._depth,
            "prefill_chunk": self._chunk,
            "n_chunks": self.n_chunks,
        }

    def page_stats(self) -> dict[str, int | float]:
        """Paged-KV counters for the serving receipt (ISSUE 13): pool
        geometry (config — regress.py fingerprints ``paged`` /
        ``page_size`` / ``pool_pages``) plus occupancy outcomes
        (``pages_*`` counters, excluded from the fingerprint).
        ``hbm_high_water_bytes`` is the pool HBM high-water mark —
        ``high_water`` pages priced at the per-page leaf footprint —
        the number the oversubscription win is stated in. ``kv_bits``
        (0 = full precision) and ``paged_kernel`` joined the
        fingerprint in ISSUE 17 so int4/kernel rounds never gate
        int8/gather ones; ``page_bytes`` already prices quantized
        leaves honestly (int4's packed uint8 + bf16 scales halve it vs
        int8 exactly). Host bookkeeping only — no device fetch."""
        if not self._paged:
            return {"paged": 0}
        return {
            "paged": 1,
            "page_size": self._page_size,
            "pool_pages": self._pool_pages,
            "page_bytes": self._page_bytes,
            "kv_bits": self._kv_bits,
            "paged_kernel": int(self._paged_kernel),
            "hbm_high_water_bytes":
                self._pool.high_water * self._page_bytes,
            **{f"pages_{k}": v for k, v in self._pool.stats().items()},
        }

    def audit_decode_hlo(
        self, whitelist: tuple[str, ...] = ("all-reduce",)
    ) -> dict:
        """Compile the decode chain AOT and audit its HLO for
        collectives (ISSUE 15): a correctly head-sharded engine's chain
        contains ONLY attention/FFN all-reduces — an all-gather or a
        reshard copy means a slot leaf lost its sharding somewhere and
        the per-chip HBM claim is a lie. Returns (and caches, for
        :meth:`tp_stats`) :func:`..parallel.tensor_parallel.audit_hlo`'s
        verdict dict.

        EXPLICIT, never automatic: ``lower().compile()`` is an AOT
        compile that does NOT populate the jit dispatch cache, so
        auditing costs one extra chain compile — fine on the CPU test
        mesh or once per receipt run, not something to hide in the
        constructor of a 1.2B engine."""
        args = [self.params, self._state]
        if self._inject_logits:
            args.append(jnp.asarray(0, jnp.int32))
        hlo = self._chain.lower(*args).compile().as_text()
        self._tp_audit = audit_hlo(hlo, whitelist=whitelist)
        return self._tp_audit

    def tp_stats(self) -> dict[str, int | float | str | bool]:
        """Sharded-serving fields for the receipt (ISSUE 15): tp size +
        mesh shape (config — regress.py fingerprints ``tp`` /
        ``mesh_shape`` so sharded and replicated rounds never gate each
        other) and the PER-CHIP KV footprint (shard sizes, the honest
        HBM claim). ``tp_collectives`` / ``tp_hlo_ok`` appear only
        after an explicit :meth:`audit_decode_hlo` (outcomes, excluded
        from the fingerprint). Host metadata only — sharding math, no
        device fetch."""
        if not self._shard:
            return {"tp": 1}
        out: dict[str, int | float | str | bool] = {
            "tp": self._tp,
            "mesh_shape": ",".join(
                f"{k}:{v}"
                for k, v in self._strategy.mesh.shape.items()
            ),
            "tp_kv_bytes_per_chip": self._nbytes(self._state["cache"]),
        }
        if self._tp_audit is not None:
            out["tp_collectives"] = sum(
                self._tp_audit["collectives"].values()
            )
            out["tp_hlo_ok"] = self._tp_audit["ok"]
        return out

    def role_stats(self) -> dict[str, int | str]:
        """Disaggregation fields for the receipt (ISSUE 18): the
        engine's role (config — regress.py fingerprints ``role`` so
        disaggregated and monolithic rounds never gate each other)
        plus the handoff counters (outcomes, excluded from the
        fingerprint). ``{"role": 0}`` when monolithic."""
        if self._role is None:
            return {"role": 0}
        return {
            "role": self._role,
            "handoffs_out": self.n_handoffs_out,
            "handoffs_in": self.n_handoffs_in,
        }

    def sentry_stats(self) -> dict[str, int | float]:
        """Contract-sentry fields for the receipt (ISSUE 19): the
        ``sentry`` flag is config (regress.py fingerprints it so
        instrumented and bare rounds never gate each other); compile /
        fetch / re-upload counters are outcomes. ``{"sentry": 0}`` when
        off. A fleet sharing ONE sentry reports fleet-global numbers —
        ``FleetRouter.stats()`` dedupes by sentry identity instead of
        summing the same counters once per replica."""
        if self._sentry is None:
            return {"sentry": 0}
        return self._sentry.summary()

    def slo_stats(self) -> dict[str, int | float]:
        """SLO-tier fields for the receipt (ISSUE 20):
        ``priority_classes`` / ``preemption`` are config (regress.py
        fingerprints both so SLO rounds never gate FIFO rounds); the
        swap counters are outcomes (excluded from the fingerprint).
        ``{"priority_classes": 0}`` when off."""
        if not self._slo:
            return {"priority_classes": 0}
        return {
            "priority_classes": self._n_classes,
            "preemption": 1,
            "n_preemptions": self.n_swaps_out,
            "n_swaps_out": self.n_swaps_out,
            "n_swaps_in": self.n_swaps_in,
            "swapped_now": len(self._swapped),
        }

    _STATS_PARTS = (
        "prefix", "spec", "adapters", "fault", "flight", "pipeline",
        "pages", "tp", "role", "sentry", "slo",
    )

    def stats(self, *parts: str) -> dict[str, int | float]:
        """ONE aggregate over every per-subsystem stats dict — the
        receipt/selftest call sites used to re-assemble these by hand.
        ``stats()`` returns everything; ``stats("spec", "fault")``
        selects subsystems (multi-engine callers merge stats from
        DIFFERENT engines, and an unfiltered merge would clobber e.g.
        one engine's ``prefix_cache: 1`` with another's ``0``). Key sets
        are disjoint across subsystems, so the full merge is lossless."""
        chosen = parts or self._STATS_PARTS
        unknown = set(chosen) - set(self._STATS_PARTS)
        if unknown:
            raise ValueError(
                f"unknown stats parts {sorted(unknown)}; "
                f"known: {list(self._STATS_PARTS)}"
            )
        fns = {
            "prefix": self.prefix_stats,
            "spec": self.spec_stats,
            "adapters": self.adapter_stats,
            "fault": self.fault_stats,
            "flight": self.flight_stats,
            "pipeline": self.pipeline_stats,
            "pages": self.page_stats,
            "tp": self.tp_stats,
            "role": self.role_stats,
            "sentry": self.sentry_stats,
            "slo": self.slo_stats,
        }
        out: dict[str, int | float] = {}
        for part in self._STATS_PARTS:
            if part in chosen:
                out.update(fns[part]())
        return out


def _seed_history(state, tokens, p_len, slot, first):
    """Reset slot ``slot``'s n-gram history to [prompt, first token]:
    the bucket-padded prompt row lands whole (junk beyond ``p_len`` is
    masked by ``hist_len`` in :func:`..models.sampling.ngram_draft`),
    the first sampled token overwrites the pad at position ``p_len``.
    ``slot`` / ``p_len`` are traced — no compile per slot or length."""
    hist = jax.lax.dynamic_update_slice(
        state["hist"], tokens, (slot, 0)
    )
    hist = hist.at[slot, p_len].set(first)
    return {
        "hist": hist,
        "hist_len": state["hist_len"].at[slot].set(p_len + 1),
    }


def _park_slot(remaining, slot):
    """Zero one slot's device-side budget counter (host freed it early)."""
    return remaining.at[slot].set(0)
