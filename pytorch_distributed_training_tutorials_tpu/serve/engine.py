"""Continuous-batching serving engine: one compiled decode program,
``n_slots`` concurrent requests, launch-amortized chains.

The reference's serving story stops at loading Llama-7B for placement
(``/root/reference/03.model_parallel.ipynb`` cell 2 — never generates a
token; SURVEY.md section 5.7), and this repo's own ``generate()`` is
one-shot batch inference: every request in the batch waits for the whole
batch, and nobody new can join until the loop drains. This module is the
Orca-style (OSDI '22) fix, built TPU-native:

- ONE jitted decode program over a fixed ``(n_slots, ...)`` slot-indexed
  KV cache (:mod:`.slots`); requests at different depths decode together,
  each slot carrying its own position counter and active mask
  (``remaining > 0``);
- decode runs in CHAINS of ``tokens_per_launch`` steps per dispatch
  (``lax.scan``, one launch + ONE batched ``jax.device_get`` for the
  whole chain) because the floor on the tunneled runtime is per LAUNCH,
  ~75-130 ms, regardless of how much work the launch carries (CLAUDE.md)
  — per-token host syncs would be two orders of magnitude slower than
  the device math;
- finished slots are refilled in place by a jitted prefill-into-slot
  (bucketed prompt lengths, :func:`.slots.bucket_len`; splice + position
  reset, :func:`.slots.write_slot`) — no recompile per request, per
  prompt length (beyond the bucket set), or per slot;
- sampling is the SAME pipeline ``generate()`` uses
  (:mod:`..models.sampling`), vmapped over per-slot PRNG streams: a
  request's draws depend only on its own ``seed`` and draw index, never
  on co-scheduling.

Greedy decoding is token-exact vs one-shot ``generate()`` (same math,
same cache semantics; pinned by tests/test_serve.py). Temperature /
top-k / top-p are ENGINE-level statics — per-request sampling params
would either recompile the decode program or drag filter branches into
every step; per-request randomness comes from per-request seeds.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp

from pytorch_distributed_training_tutorials_tpu.models.sampling import (
    sample_logits,
    sample_logits_per_slot,
)
from pytorch_distributed_training_tutorials_tpu.serve.scheduler import (
    Completion,
    FifoScheduler,
    Request,
)
from pytorch_distributed_training_tutorials_tpu.serve.slots import (
    bucket_len,
    init_slot_state,
    write_slot,
)


class _Active:
    """Host-side view of one occupied slot."""

    __slots__ = ("request", "tokens", "remaining")

    def __init__(self, request: Request, first_token: int):
        self.request = request
        self.tokens = [first_token]
        self.remaining = request.max_new_tokens - 1


class ServeEngine:
    """Request-level LM serving over a slot-indexed KV cache.

    ``model`` is a :class:`..models.transformer.TransformerLM` (or
    anything with the same decode/prefill/``last_pos`` apply contract and
    a ``cfg.max_seq_len``); its ``max_seq_len`` is the serving window
    every slot gets. ``params`` stays caller-owned and read-only (share
    one tree across engines; int8/TP placements pass straight through —
    the engine never touches leaf placement).

    Drive it with :meth:`submit` + :meth:`step`, or :meth:`run_until_idle`
    to drain everything. ``step()`` does at most: one prefill launch per
    freed slot (each with one scalar fetch of the first sampled token),
    then ONE ``tokens_per_launch``-step decode chain with ONE batched
    fetch — the no-per-token-host-sync contract tests/test_serve.py pins
    with a monkeypatched ``jax.device_get``.
    """

    def __init__(
        self,
        model,
        params,
        *,
        n_slots: int = 4,
        tokens_per_launch: int = 8,
        max_queue: int = 64,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
    ):
        if n_slots < 1:
            raise ValueError("n_slots must be >= 1")
        if tokens_per_launch < 1:
            raise ValueError("tokens_per_launch must be >= 1")
        self.model = model
        self.params = params
        self.n_slots = n_slots
        self.tokens_per_launch = tokens_per_launch
        self.window = int(model.cfg.max_seq_len)
        self.scheduler = FifoScheduler(self.window, max_queue=max_queue)
        self._slots: list[_Active | None] = [None] * n_slots
        self._state = init_slot_state(model, params, n_slots)
        self._scan_layers = bool(getattr(model.cfg, "scan_layers", False))
        self._temperature = float(temperature)
        self._top_k = int(top_k)
        self._top_p = float(top_p)
        # stats for receipts
        self.n_prefills = 0
        self.n_chains = 0
        self.generated_tokens = 0
        # donating the state tree lets XLA update the multi-hundred-MB
        # cache in place; CPU jit warns on donation (unsupported), so
        # only donate where it is real
        donate = (1,) if jax.default_backend() in ("tpu", "gpu") else ()
        self._prefill = jax.jit(self._prefill_fn, donate_argnums=donate)
        self._chain = jax.jit(self._chain_fn, donate_argnums=donate)
        self._park = jax.jit(
            _park_slot, donate_argnums=(0,) if donate else ()
        )

    # ------------------------------------------------------------------
    # compiled programs (closures over model + static sampling params)
    # ------------------------------------------------------------------

    def _prefill_fn(self, params, state, tokens, p_len, slot, seed,
                    max_new):
        """Prefill ``tokens`` (1, bucket) into slot ``slot``: one batched
        forward populates the slot's K/V for ``[0, p_len)``, the first
        token is sampled from the logits gathered at the last REAL prompt
        position, and the slot's counters reset. All of ``p_len`` /
        ``slot`` / ``seed`` / ``max_new`` are traced scalars — one
        compile per prompt BUCKET, not per request."""
        logits, upd = self.model.apply(
            {"params": params}, tokens, prefill=True, mutable=["cache"],
            last_pos=p_len - 1,
        )
        key = jax.random.PRNGKey(seed)
        first, key = sample_logits(
            logits[:, -1].astype(jnp.float32), key,
            self._temperature, self._top_k, self._top_p,
        )
        cache = write_slot(
            state["cache"], upd["cache"], slot, p_len, self._scan_layers
        )
        state = {
            "cache": cache,
            "last_tok": state["last_tok"].at[slot].set(first[0]),
            "keys": state["keys"].at[slot].set(key),
            # the first generated token is already accounted for
            "remaining": state["remaining"].at[slot].set(max_new - 1),
        }
        return state, first[0]

    def _chain_fn(self, params, state):
        """``tokens_per_launch`` decode steps as one ``lax.scan`` — one
        launch, one (S, T) token block out. Every slot steps every time
        (fixed shapes); inactive slots re-emit their last token, their
        K/V writes land at advancing positions whose reads are never
        consumed (and drop once past the window — ``_store_decode_kv``
        in models/transformer.py), and refill rewrites the whole slot
        anyway."""

        def step(carry, _):
            cache, tok, keys, remaining = carry
            active = remaining > 0
            logits, upd = self.model.apply(
                {"params": params, "cache": cache}, tok[:, None],
                decode=True, mutable=["cache"],
            )
            nxt, keys = sample_logits_per_slot(
                logits[:, -1].astype(jnp.float32), keys,
                self._temperature, self._top_k, self._top_p,
            )
            nxt = jnp.where(active, nxt, tok)
            remaining = remaining - active.astype(remaining.dtype)
            return (upd["cache"], nxt, keys, remaining), nxt

        carry = (
            state["cache"], state["last_tok"], state["keys"],
            state["remaining"],
        )
        (cache, tok, keys, remaining), toks = jax.lax.scan(
            step, carry, None, length=self.tokens_per_launch
        )
        state = {
            "cache": cache, "last_tok": tok, "keys": keys,
            "remaining": remaining,
        }
        return state, toks.T  # (n_slots, tokens_per_launch)

    # ------------------------------------------------------------------
    # host-side driver
    # ------------------------------------------------------------------

    def submit(self, request: Request) -> int:
        """Enqueue one request; returns its id. Raises
        :class:`..serve.scheduler.QueueFull` when the bounded queue is at
        capacity (backpressure) or ``ValueError`` when the request can
        never fit the window."""
        return self.scheduler.submit(request)

    @property
    def active_slots(self) -> int:
        return sum(a is not None for a in self._slots)

    @property
    def idle(self) -> bool:
        return self.active_slots == 0 and len(self.scheduler) == 0

    def step(self) -> list[Completion]:
        """One scheduling round: refill free slots from the queue (one
        prefill launch each), then run ONE decode chain over all slots
        and hand out its tokens. Returns the requests that finished this
        round (possibly mid-chain — surplus chain tokens for a finished
        slot are discarded, exactly like ``generate()`` truncating at
        ``max_new_tokens``)."""
        done: list[Completion] = []
        for s in range(self.n_slots):
            if self._slots[s] is not None:
                continue
            req = self.scheduler.pop()
            if req is None:
                break
            done.extend(self._refill(s, req))
        if self.active_slots:
            self._state, toks = self._chain(self.params, self._state)
            self.n_chains += 1
            toks = jax.device_get(toks)  # the chain's ONE host fetch
            done.extend(self._distribute(toks))
        return done

    def run_until_idle(self, max_steps: int = 10_000) -> list[Completion]:
        """Drain queue + slots; returns completions in finish order."""
        out: list[Completion] = []
        for _ in range(max_steps):
            if self.idle:
                return out
            out.extend(self.step())
        raise RuntimeError(f"not idle after {max_steps} steps")

    def _refill(self, slot: int, req: Request) -> list[Completion]:
        """Prefill ``req`` into ``slot``. One launch + one scalar fetch
        (the first sampled token — needed host-side for EOS/max_new==1
        admission into the decode phase)."""
        prompt = [int(t) for t in req.prompt]
        p_len = len(prompt)
        bucket = bucket_len(p_len, self.window)
        padded = prompt + [0] * (bucket - p_len)
        tokens = jnp.asarray([padded], jnp.int32)
        self._state, first = self._prefill(
            self.params, self._state, tokens, p_len, slot, req.seed,
            req.max_new_tokens,
        )
        self.n_prefills += 1
        first = int(jax.device_get(first))
        self.generated_tokens += 1
        act = _Active(req, first)
        if req.max_new_tokens == 1 or first == req.eos_token:
            reason = "eos" if first == req.eos_token else "length"
            if act.remaining > 0:
                # early EOS: the device-side counter still shows budget;
                # park the slot so later chains treat it as inactive
                self._state["remaining"] = self._park(
                    self._state["remaining"], slot
                )
            return [self._complete(act, reason)]
        self._slots[slot] = act
        return []

    def _distribute(self, toks) -> list[Completion]:
        """Hand one fetched (S, T) chain block out to the slots' host
        views; free every slot that finished (budget exhausted or EOS
        mid-chain) and park early-EOS slots whose device counter still
        shows budget."""
        done: list[Completion] = []
        for s, act in enumerate(self._slots):
            if act is None:
                continue
            reason = None
            for t in toks[s, : act.remaining]:
                tok = int(t)
                act.tokens.append(tok)
                act.remaining -= 1
                self.generated_tokens += 1
                if tok == act.request.eos_token:
                    reason = "eos"
                    break
            if reason is None and act.remaining == 0:
                reason = "length"
            if reason is not None:
                self._slots[s] = None
                if act.remaining > 0:  # finished mid-chain via EOS
                    self._state["remaining"] = self._park(
                        self._state["remaining"], s
                    )
                done.append(self._complete(act, reason))
        return done

    def _complete(self, act: _Active, reason: str) -> Completion:
        return Completion(
            request_id=act.request.request_id,
            prompt=[int(t) for t in act.request.prompt],
            tokens=act.tokens,
            finish_reason=reason,
            latency_s=time.perf_counter() - act.request.submitted_s,
        )


def _park_slot(remaining, slot):
    """Zero one slot's device-side budget counter (host freed it early)."""
    return remaining.at[slot].set(0)
