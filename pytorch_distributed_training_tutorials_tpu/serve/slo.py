"""SLO tiers: priority scheduling + the host side of KV-swap preemption.

ISSUE 20's traffic-shaping subsystem. Every request today rides one FIFO
class, so interactive traffic cannot hold its TTFT p95 while batch
traffic absorbs variance. This module is the POLICY half of the fix —
deliberately jax-free (HOST_ONLY_MODULES + the no-jax subprocess pin),
like the scheduler it extends: which request pops next, which active
slot is preempted, and the host-side record a swapped-out request parks
in are all pure Python. The MECHANISM half (the budgeted swap-out fetch,
the ``seed_cache``/``write_slot`` swap-in splice) lives in
:mod:`.engine` / :mod:`.slots`, where jax belongs.

Three pieces:

- :class:`PriorityScheduler` — pops by (class, arrival): class 0 is the
  highest tier, within a class strict arrival order, and the existing
  ``chunk=``/``pending_long=``/``fits=`` predicates apply unchanged (a
  high-class request that does not fit stays queued and a lower class
  may pop around it — pages freeing up, not priority, is what unblocks
  it). With ``n_classes=1`` every pop reduces to the first passing
  candidate in arrival order — order-identical to
  :class:`.scheduler.FifoScheduler` (tests/test_slo.py pins it).
- :func:`choose_victim` — the preemption policy: the engine may evict an
  active slot only for a STRICTLY higher waiting class, picks the
  numerically greatest (lowest-tier) active class, and among equals the
  most recently admitted request (largest id) — oldest work keeps its
  progress.
- :class:`SwapRecord` — the parked state of a preempted request: the
  engine's host-side active record (generated tokens kept), the fetched
  cache segment + sampling leaves, and the position/bucket needed to
  re-splice. It is the :class:`.scheduler.Handoff` idea pointed at host
  instead of a decode replica: leaves here are host numpy (the swap-out
  fetch already paid for them), so holding a record costs HBM nothing.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterable, Optional

from .scheduler import FifoScheduler, Request


class PriorityScheduler(FifoScheduler):
    """Bounded multi-class queue: pop by (priority class, arrival).

    ``n_classes`` fixes the admission range at construction —
    ``Request.priority`` must satisfy ``0 <= priority < n_classes`` or
    :meth:`~.scheduler.FifoScheduler.submit` raises ``ValueError``, the
    same synchronous admission contract as the window/deadline checks
    (the base class enforces it; this class only widens ``n_classes``).
    One arrival-ordered deque backs every class: a pop scans for the
    best (lowest) class passing the predicates, tie-broken by arrival,
    so within a class the FIFO fairness story is unchanged and a
    single-class instance is order-identical to the FIFO scheduler.
    """

    def __init__(self, window: int, max_queue: int = 64,
                 n_classes: int = 2):
        if n_classes < 1:
            raise ValueError(f"n_classes must be >= 1, got {n_classes}")
        super().__init__(window, max_queue=max_queue)
        self.n_classes = n_classes

    def pop(self, chunk: int = 0, pending_long: int = 0,
            fits=None) -> Request | None:
        """Best (class, arrival) request passing the predicates, or None.

        Predicate semantics are exactly the FIFO scheduler's: with
        ``chunk`` set and a long prompt mid chunked-prefill only
        single-chunk prompts are eligible, and ``fits`` filters on top.
        Among eligible requests the lowest ``priority`` wins; within a
        class, earliest arrival (the scan early-exits on the first
        class-0 candidate — arrival order IS deque order)."""
        if not self._queue:
            return None
        best: tuple[int, int] | None = None  # (priority, deque index)
        for i, r in enumerate(self._queue):
            if chunk and pending_long and len(r.prompt) > chunk:
                continue
            if fits is not None and not fits(r):
                continue
            p = int(getattr(r, "priority", 0))
            if best is None or p < best[0]:
                best = (p, i)
                if p == 0:
                    break
        if best is None:
            return None
        req = self._queue[best[1]]
        del self._queue[best[1]]
        return req

    def requeue(self, request: Request) -> None:
        """Re-insert a PREEMPTED request, keeping the deque sorted by
        arrival (``request_id`` is the admission counter, so id order is
        arrival order). Bypasses ``QueueFull``/``QueueClosed``
        deliberately: the request was already admitted once — preemption
        must never turn an accepted request into a shed one (the same
        no-accepted-request-dropped contract as ``drain``)."""
        idx = len(self._queue)
        for i, r in enumerate(self._queue):
            if r.request_id > request.request_id:
                idx = i
                break
        self._queue.insert(idx, request)

    def peek_priority(self) -> Optional[int]:
        """Best (numerically smallest) waiting class, or None when
        empty — the engine's pressure signal: preemption is considered
        only when this class outranks an active slot's."""
        if not self._queue:
            return None
        return min(int(getattr(r, "priority", 0)) for r in self._queue)

    def peek_request(self) -> Request | None:
        """The request a bare predicate-free :meth:`pop` would return,
        WITHOUT removing it — the paged engine inspects its page need to
        decide whether pool pressure (rather than slot pressure) calls
        for a preemption."""
        if not self._queue:
            return None
        best = None
        for r in self._queue:
            p = int(getattr(r, "priority", 0))
            if best is None or p < best[0]:
                best = (p, r)
                if p == 0:
                    break
        return best[1]


def choose_victim(active: Iterable[tuple[int, int, int]],
                  waiting_class: int) -> Optional[int]:
    """Pick the slot to preempt for a ``waiting_class`` request, or None.

    ``active`` yields ``(slot, priority, request_id)`` for every
    occupied slot. Only a slot whose class is STRICTLY lower-tier
    (numerically greater) than ``waiting_class`` is eligible — equal
    classes never preempt each other (arrival order already arbitrates
    within a class, and allowing ties would thrash). Among eligible
    slots the numerically greatest class loses first; ties break toward
    the most recently admitted request (largest id), so the oldest work
    keeps its accumulated decode progress."""
    victim: tuple[int, int, int] | None = None
    for slot, prio, rid in active:
        if prio <= waiting_class:
            continue
        if victim is None or (prio, rid) > (victim[1], victim[2]):
            victim = (slot, prio, rid)
    return None if victim is None else victim[0]


@dataclasses.dataclass
class SwapRecord:
    """A preempted request's parked state, host side (ISSUE 20).

    ``active`` is the engine's own ``_Active`` record — request, tokens
    generated so far, tokens remaining — kept whole so resume is a
    reinstatement, not a reconstruction. ``segment`` / ``last_tok`` /
    ``key`` (and ``hist`` / ``hist_len`` when speculation is on) are the
    HOST-fetched leaves of the slot at the swap boundary: the cache
    segment covers positions ``[0, position)`` at the pow2 bucket
    ``seg_len`` (the same bucket family prefill/splice compile against,
    so swap-in never mints a compile), ``last_tok`` is the next decode
    input and ``key`` the request's PRNG stream mid-sequence — exactly
    the :class:`.scheduler.Handoff` payload plus progress, fetched
    instead of device-resident because the whole point is returning the
    HBM to the pool. ``preempt_t`` stamps the swap for the flight
    recorder's preempted-wait histogram."""

    active: Any
    segment: Any
    last_tok: Any
    key: Any
    position: int
    seg_len: int
    hist: Any = None
    hist_len: Any = None
    preempt_t: float = 0.0
