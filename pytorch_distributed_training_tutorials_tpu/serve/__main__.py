"""``python -m pytorch_distributed_training_tutorials_tpu.serve --selftest``: end-to-end smoke of the
continuous-batching engine on a tiny model, any backend.

Exercises the whole serving loop the way tier-1 exercises ``obs``: a toy
:class:`..models.transformer.TransformerLM` serves a staggered stream of
mixed-length greedy requests through :class:`..serve.ServeEngine`, and
every completion is checked TOKEN-EXACT against one-shot
:func:`..models.generate.generate` of the same model/params — the
continuous-batching machinery (slot refill, bucketed prefill, per-slot
positions, chained decode) must be invisible in the outputs. Also pins
backpressure (:class:`..serve.QueueFull`) and the fetch discipline (at
most one ``jax.device_get`` per decode chain, counted by monkeypatching).
A second arm replays an overlapping-prompt stream (one shared prefix
family, per-request tails) through two engines — prefix cache OFF and ON
(``prefix_cache_bytes``) — and requires byte-identical greedy tokens, a
hit rate > 0, FEWER full prefills (splices replace them, counted not
estimated), and the same one-fetch-per-chain budget with splices
included. A third (``--spec-k``) arm replays a repetitive/templated
stream through a ``speculative_k > 0`` engine: greedy tokens must stay
byte-identical to the non-speculative engine, the fetch budget is
unchanged (the (S, T, k+1) block + counts ride the chain's ONE batched
fetch), and the MECHANISM must have fired — mean accepted length > 1
and sequential verify forwards strictly below tokens emitted (the whole
point of speculation: fewer sequential decode steps than tokens).
A fourth (``--adapters``) arm registers N-1 tenants with distinct LoRA
factors into an :class:`..adapters.AdapterBank` and replays a
mixed-tenant stream: every request's greedy tokens must be byte-identical
to a DEDICATED single-tenant engine over the same bank, id-0 requests
byte-identical to the bank-less base engine, the fetch budget unchanged,
and admission of an unregistered id must fail synchronously at submit.
A fifth (``--chaos``) arm runs the ISSUE 9 fault-injection gauntlet:
one guarded engine takes an injected NaN-logit (the poisoned request
must finish ``"nonfinite"`` with its clean prefix of tokens while the
co-scheduled request stays byte-identical to a fault-free run), a
deadline expiry, a host-side cancel and a close/drain — fetch budget
still counted — and a mini training leg drives the skip-step guard
(poisoned batch leaves TrainState bitwise unchanged, the skip counter
increments once). With a recorder riding along, the chaos injectors
auto-dump ``graft-flightlog/v1`` snapshots whose trigger names the
quarantined slot — the post-mortem contract tests assert on. A sixth
(``--flight``) arm replays the staggered stream through a
:class:`..obs.flight.FlightRecorder`-instrumented engine: tokens stay
byte-identical, the fetch budget is unchanged (the recorder is pure host
bookkeeping), every completed request carries a FULL lifecycle span
(submit -> queue_pop -> prefill -> complete), per-stage event counts
reconcile with the engine's own counters, and the streaming-histogram
p50/p95 match sort-based percentiles within one bucket's documented
relative error. The receipt gains the ``fault_stats()`` fields plus
``steps_skipped``, and the per-arm stats now flow through ONE
``engine.stats(part)`` aggregate. A seventh (``--pipeline``) arm replays
the staggered stream through a ``pipeline_depth=2`` + chunked-prefill
engine (ISSUE 11): greedy tokens must stay byte-identical to the serial
engine (double-buffering moves the fetch off the critical path, never
changes what was computed), the fetch budget is unchanged (mid-prefill
chunks are pure dispatch — no fetch until the final chunk), and the
chunking mechanism must have fired (``n_chunks > 0`` on a stream whose
longest prompt exceeds the chunk). An eighth (``--router``) arm runs a
3-replica fleet of real engines behind :class:`..serve.FleetRouter`
(ISSUE 12): a fault-free leg must be byte-identical to the single
engine (routing is invisible), then the same stream replays with one
replica chaos-killed mid-stream — the DispatchLedger must verify
exactly-once (no accepted request lost or completed twice),
re-dispatched requests must stay byte-identical to the fault-free leg,
and the SUMMED per-replica fetch budget stays chains + prefills +
splices. A ninth (``--paged``) arm replays a short+long mixed stream at
OVERSUBSCRIBED slot count (``n_slots * window > pool_pages *
page_size``) through a ``paged=True`` engine (ISSUE 13): greedy tokens
must stay byte-identical to the whole-slot engine (pages are invisible
in the outputs), the fetch budget is unchanged, a request that can
never fit the pool must shed synchronously at submit
(:class:`..serve.pages.PoolExhausted`), and an overlapping-prompt leg
with the prefix cache ON must show page SHARES (retained prefix pages
seeding new requests copy-free) while staying byte-identical to the
paged cache-off leg. ``page_stats()`` (occupancy high-water, shares,
sheds) rides into the receipt. The paged arm also runs the ISSUE 17
legs: the fused Pallas page-walk kernel (``paged_kernel=True``) must be
token-exact to the gather engine at full precision, and the int4
packed-KV engine (``kv_bits=4``) must price ``page_bytes`` at EXACTLY
half the int8 engine's — 2x the pages at equal pool HBM — while
completing the same stream through the kernel read path within the
unchanged fetch budget. A tenth (``--tp N``) arm replays the
base staggered stream through a :class:`..parallel.TensorParallel`-
sharded engine on a ``{'model': N}`` mesh (ISSUE 15): greedy tokens
must stay byte-identical to the replicated engine, the fetch budget is
unchanged (ONE batched fetch per chain regardless of mesh width), the
KV slot state must REALLY shard (per-chip bytes strictly below global),
and the compiled decode chain's HLO must pass the collective audit
(``audit_decode_hlo`` — nothing beyond the whitelisted all-reduces).
``tp_*`` receipt fields carry the audit verdict and per-chip KV bytes.
An eleventh (``--sentry``) arm runs the runtime contract sentry
(ISSUE 19) — the production twin of this harness's own monkeypatch
spies: a :class:`..obs.sentry.ContractSentry`-instrumented engine warms
up the base stream, ``mark_steady()``s, then replays it — the steady
leg must show ZERO steady recompiles, a fetch count equal to an
independent monkeypatch spy AND to the engine's declared budget, and
zero host-numpy re-uploads, with greedy tokens byte-identical to the
uninstrumented engine. Then one injected violation per probe class (a
post-steady jit of a fresh program over a prebuilt operand, a stray
``device_get`` inside one step round, a host-numpy arg tree) must each
yield exactly one typed flight event and one ``graft-flightlog/v1``
auto-dump whose trigger names the violation; the device-resident twin
of the numpy tree must stay silent. ``sentry_*`` receipt fields carry
the clean-leg summary plus the three caught-flags.
A twelfth (``--slo``) arm runs the SLO-tier gauntlet (ISSUE 20): a
``priority_classes=2`` engine decodes a low-class request on its only
slot when a class-0 request arrives — the engine must PREEMPT (KV
swap-out to host, counted ``n_swaps_out``), serve the interactive
request, then swap the victim back in; BOTH streams must be
token-exact to one-shot ``generate()`` (preemption is invisible in the
tokens), the fetch budget is chains + prefills + splices + counted
swaps (the monkeypatch spy counts the swap-out's one batched segment
fetch), and a :class:`..obs.sentry.ContractSentry` riding the same
stream must close every round balanced — the runtime proof that swap
fetches flow through the budgeted ``_sentry_fetch`` seam. A chaos leg
(``preempt_at_chain``) force-preempts a slot with NO real pressure: the
victim resumes token-exact and the co-scheduled slot's tokens are
byte-identical to a preemption-free run. A host-only leg pins
``PriorityScheduler(n_classes=1)`` pop-order-identical to
``FifoScheduler`` over the same submission sequence.
Prints exactly one JSON line (a ``graft-receipt/v1`` envelope) and
exits non-zero on any failure.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def selftest(json_path: str | None = None, spec_k: int = 2,
             adapters: int = 3, chaos: bool = False,
             flight: bool = False, pipeline: bool = False,
             router: bool = False, paged: bool = False,
             tp: int = 0, sentry: bool = False,
             slo: bool = False) -> dict:
    import math
    import tempfile

    import jax
    import jax.numpy as jnp

    from pytorch_distributed_training_tutorials_tpu.adapters import (
        AdapterBank,
        extract_adapter,
        lora_init,
    )
    from pytorch_distributed_training_tutorials_tpu.models.generate import generate
    from pytorch_distributed_training_tutorials_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
    )
    from pytorch_distributed_training_tutorials_tpu.obs import make_receipt, validate_receipt
    from pytorch_distributed_training_tutorials_tpu.serve import QueueFull, Request, ServeEngine

    problems: list[str] = []
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, max_seq_len=64
    )
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]

    engine = ServeEngine(
        model, params, n_slots=2, tokens_per_launch=8, max_queue=2
    )

    # a staggered stream with mixed prompt lengths and budgets: 2 slots,
    # 5 requests, the last submitted only after capacity frees up
    rng = jax.random.PRNGKey(1)
    prompts = []
    for i, (p_len, max_new) in enumerate(
        [(3, 9), (7, 12), (5, 1), (12, 6), (2, 17)]
    ):
        rng, sub = jax.random.split(rng)
        toks = jax.device_get(
            jax.random.randint(sub, (p_len,), 0, cfg.vocab_size)
        ).tolist()
        prompts.append((toks, max_new))

    fetches = {"n": 0}
    real_get = jax.device_get

    def counting_get(x):
        fetches["n"] += 1
        return real_get(x)

    jax.device_get = counting_get
    try:
        completions = {}
        backpressured = False
        pending = list(prompts)
        # submit two, then drip the rest in as steps run — staggered
        # arrivals against live slots
        for toks, max_new in pending[:2]:
            engine.submit(Request(prompt=toks, max_new_tokens=max_new))
        pending = pending[2:]
        while not engine.idle or pending:
            while pending:
                toks, max_new = pending[0]
                try:
                    engine.submit(
                        Request(prompt=toks, max_new_tokens=max_new)
                    )
                    pending.pop(0)
                except QueueFull:
                    backpressured = True
                    break
            for c in engine.step():
                completions[c.request_id] = c
        n_chains, n_fetch = engine.n_chains, fetches["n"]
    finally:
        jax.device_get = real_get
    if len(completions) != len(prompts):
        problems.append(
            f"{len(completions)} completions for {len(prompts)} requests"
        )
    # fetch discipline: one fetch per chain + one scalar per prefill
    budget = n_chains + engine.n_prefills
    if n_fetch > budget:
        problems.append(
            f"{n_fetch} host fetches > {budget} "
            f"({n_chains} chains + {engine.n_prefills} prefills)"
        )

    # token-exactness vs one-shot generate(), greedy, per request
    mismatches = 0
    for rid, (toks, max_new) in enumerate(prompts):
        ref = jax.device_get(
            generate(
                model, params, jnp.asarray([toks], jnp.int32), max_new
            )
        )[0, len(toks):].tolist()
        if completions[rid].tokens != ref:
            mismatches += 1
            problems.append(
                f"request {rid}: engine {completions[rid].tokens} != "
                f"generate {ref}"
            )
    # ------------------------------------------------------------------
    # prefix-cache arm: one shared prefix family, per-request tails;
    # cache ON must match cache OFF byte-for-byte while replacing full
    # prefills with splices (counted, not estimated)
    # ------------------------------------------------------------------
    rng, sub = jax.random.split(rng)
    shared = jax.device_get(
        jax.random.randint(sub, (16,), 0, cfg.vocab_size)
    ).tolist()
    overlap_reqs = []
    for i, (tail_len, max_new) in enumerate(
        [(3, 8), (5, 6), (2, 10), (4, 7), (3, 5), (6, 9)]
    ):
        rng, sub = jax.random.split(rng)
        tail = jax.device_get(
            jax.random.randint(sub, (tail_len,), 0, cfg.vocab_size)
        ).tolist()
        overlap_reqs.append((shared + tail, max_new))

    def run_stream(prefix_cache_bytes):
        eng = ServeEngine(
            model, params, n_slots=2, tokens_per_launch=8,
            prefix_cache_bytes=prefix_cache_bytes,
        )
        count = {"n": 0}

        def counting(x):
            count["n"] += 1
            return real_get(x)

        jax.device_get = counting
        try:
            out = {}
            pending = list(overlap_reqs)
            for toks, max_new in pending[:2]:
                eng.submit(Request(prompt=toks, max_new_tokens=max_new))
            pending = pending[2:]
            while not eng.idle or pending:
                while pending:
                    toks, max_new = pending[0]
                    try:
                        eng.submit(
                            Request(prompt=toks, max_new_tokens=max_new)
                        )
                        pending.pop(0)
                    except QueueFull:
                        break
                for c in eng.step():
                    out[c.request_id] = c.tokens
        finally:
            jax.device_get = real_get
        return eng, out, count["n"]

    eng_off, toks_off, _ = run_stream(0)
    eng_on, toks_on, fetches_on = run_stream(16 * 1024 * 1024)
    # the one stats() aggregate, part-filtered: each arm merges stats
    # from a DIFFERENT engine, and the filter keeps e.g. eng_spec's
    # "prefix_cache: 0" from clobbering eng_on's "prefix_cache: 1"
    stats = eng_on.stats("prefix")
    prefix_exact = toks_on == toks_off
    if not prefix_exact:
        problems.append(
            f"prefix cache changed greedy tokens: {toks_on} != {toks_off}"
        )
    if stats.get("prefix_hit_rate", 0) <= 0 or eng_on.n_splices < 1:
        problems.append(f"no prefix hits on an overlapping stream: {stats}")
    if eng_on.n_prefills >= eng_off.n_prefills:
        problems.append(
            f"prefix cache saved no prefills: {eng_on.n_prefills} on vs "
            f"{eng_off.n_prefills} off"
        )
    on_budget = eng_on.n_chains + eng_on.n_prefills + eng_on.n_splices
    if fetches_on > on_budget:
        problems.append(
            f"prefix arm: {fetches_on} host fetches > {on_budget} "
            f"({eng_on.n_chains} chains + {eng_on.n_prefills} prefills + "
            f"{eng_on.n_splices} splices)"
        )

    # ------------------------------------------------------------------
    # speculative arm: a repetitive/templated stream (the workload
    # prompt-lookup drafting exists for) through a speculate-k engine —
    # byte-identical greedy tokens vs the non-speculative engine, the
    # same fetch budget, AND the mechanism visibly firing: accepted
    # length > 1 and fewer sequential verify forwards than tokens out
    # ------------------------------------------------------------------
    template = [7, 8, 9, 10, 11]
    spec_reqs = []
    for i, (reps, max_new) in enumerate(
        [(4, 18), (3, 14), (4, 20), (3, 16)]
    ):
        spec_reqs.append((template * reps + [20 + i], max_new))

    def run_spec_stream(k):
        eng = ServeEngine(
            model, params, n_slots=2, tokens_per_launch=8,
            speculative_k=k,
        )
        count = {"n": 0}

        def counting(x):
            count["n"] += 1
            return real_get(x)

        jax.device_get = counting
        try:
            out = {}
            pending = list(spec_reqs)
            for toks, max_new in pending[:2]:
                eng.submit(Request(prompt=toks, max_new_tokens=max_new))
            pending = pending[2:]
            while not eng.idle or pending:
                while pending:
                    toks, max_new = pending[0]
                    try:
                        eng.submit(
                            Request(prompt=toks, max_new_tokens=max_new)
                        )
                        pending.pop(0)
                    except QueueFull:
                        break
                for c in eng.step():
                    out[c.request_id] = c.tokens
        finally:
            jax.device_get = real_get
        return eng, out, count["n"]

    eng_plain, toks_plain, _ = run_spec_stream(0)
    eng_spec, toks_spec, fetches_spec = run_spec_stream(spec_k)
    sstats = eng_spec.stats("spec")
    spec_exact = toks_spec == toks_plain
    if not spec_exact:
        problems.append(
            f"speculation changed greedy tokens: {toks_spec} != "
            f"{toks_plain}"
        )
    spec_budget = eng_spec.n_chains + eng_spec.n_prefills
    if fetches_spec > spec_budget:
        problems.append(
            f"spec arm: {fetches_spec} host fetches > {spec_budget} "
            f"({eng_spec.n_chains} chains + {eng_spec.n_prefills} "
            f"prefills)"
        )
    if sstats["spec_mean_accepted_len"] <= 1.0:
        problems.append(
            f"drafting never helped on a repetitive stream: {sstats}"
        )
    if sstats["n_verify_forwards"] >= eng_spec.generated_tokens:
        problems.append(
            f"{sstats['n_verify_forwards']} verify forwards >= "
            f"{eng_spec.generated_tokens} tokens emitted — speculation "
            f"saved no sequential steps"
        )

    # ------------------------------------------------------------------
    # multi-tenant adapter arm: N-1 tenants with distinct LoRA factors in
    # one bank; a mixed-tenant stream must match dedicated single-tenant
    # engines per request (one compiled program serves them all), id 0
    # must match the BANK-LESS base engine, the fetch budget is
    # unchanged, and unregistered ids are rejected at submit
    # ------------------------------------------------------------------
    bank = AdapterBank(model, n_adapters=adapters, rank=4)
    lparams = lora_init(
        bank.model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
        )["params"],
        jax.random.PRNGKey(5),
    )

    def fill_b(path, leaf):  # lora_init leaves B zero; tenants need deltas
        if str(getattr(path[-1], "key", path[-1])) != "lora_b":
            return leaf
        v = jax.random.normal(
            jax.random.PRNGKey(11), leaf.shape, leaf.dtype
        ) * 0.05
        return v.at[..., 0, :, :].set(0.0)

    lparams = jax.tree_util.tree_map_with_path(fill_b, lparams)
    base_row = extract_adapter(lparams, 1)
    for aid in range(1, adapters):
        # distinct factors per tenant (scaled copies — cheap, different)
        bank.register(f"tenant-{aid}", jax.tree_util.tree_map(
            lambda x, s=aid: x * (1.0 if s % 2 else -1.0) / s, base_row
        ))

    tenant_reqs = []  # (prompt, max_new, adapter id) — ids interleaved
    for i, (toks, max_new) in enumerate(prompts):
        tenant_reqs.append((toks, max_new, i % adapters))

    def run_tenant_stream(reqs, with_bank):
        eng = ServeEngine(
            model, params, n_slots=2, tokens_per_launch=8,
            adapter_bank=bank if with_bank else None,
        )
        count = {"n": 0}

        def counting(x):
            count["n"] += 1
            return real_get(x)

        jax.device_get = counting
        try:
            out = {}
            pending = list(reqs)
            for toks, max_new, aid in pending[:2]:
                eng.submit(Request(
                    prompt=toks, max_new_tokens=max_new, adapter=aid
                ))
            pending = pending[2:]
            while not eng.idle or pending:
                while pending:
                    toks, max_new, aid = pending[0]
                    try:
                        eng.submit(Request(
                            prompt=toks, max_new_tokens=max_new,
                            adapter=aid,
                        ))
                        pending.pop(0)
                    except QueueFull:
                        break
                for c in eng.step():
                    out[c.request_id] = c.tokens
        finally:
            jax.device_get = real_get
        return eng, out, count["n"]

    eng_mix, toks_mix, fetches_mix = run_tenant_stream(tenant_reqs, True)
    adapter_exact = True
    for aid in range(adapters):
        idx = [i for i, r in enumerate(tenant_reqs) if r[2] == aid]
        if not idx:
            continue
        solo_reqs = [tenant_reqs[i] for i in idx]
        _, toks_solo, _ = run_tenant_stream(solo_reqs, True)
        got = [toks_mix[i] for i in idx]
        want = [toks_solo[j] for j in sorted(toks_solo)]
        if got != want:
            adapter_exact = False
            problems.append(
                f"adapter {aid}: mixed-tenant tokens {got} != "
                f"dedicated-engine tokens {want}"
            )
    # id 0 through the bank == the bank-less base engine (zero factors
    # are EXACTLY the base model, not approximately)
    base_idx = [i for i, r in enumerate(tenant_reqs) if r[2] == 0]
    base_got = [toks_mix[i] for i in base_idx]
    base_want = [completions[i].tokens for i in base_idx]
    if base_got != base_want:
        adapter_exact = False
        problems.append(
            f"adapter 0 tokens {base_got} != base engine {base_want}"
        )
    mix_budget = eng_mix.n_chains + eng_mix.n_prefills
    if fetches_mix > mix_budget:
        problems.append(
            f"adapter arm: {fetches_mix} host fetches > {mix_budget} "
            f"({eng_mix.n_chains} chains + {eng_mix.n_prefills} prefills)"
        )
    try:
        eng_mix.submit(Request(
            prompt=[1, 2], max_new_tokens=2, adapter=adapters,
        ))
        problems.append(
            f"unregistered adapter id {adapters} admitted at submit"
        )
    except ValueError:
        pass
    astats = eng_mix.stats("adapters")
    if astats.get("adapter_requests", 0) < 1:
        problems.append(f"no tenant traffic recorded: {astats}")

    # ------------------------------------------------------------------
    # flight arm (--flight, ISSUE 10): the staggered base stream again,
    # now through a FlightRecorder-instrumented engine — tokens and the
    # fetch budget must be untouched (the recorder is host bookkeeping),
    # every completion must carry a FULL lifecycle span, per-stage event
    # counts must reconcile with the engine's counters, and the
    # streaming-histogram percentiles must match sort-based ones within
    # one bucket's documented relative error
    # ------------------------------------------------------------------
    flight_fields: dict = {}
    if flight:
        from pytorch_distributed_training_tutorials_tpu.obs import FlightRecorder

        rec = FlightRecorder(capacity=256)
        eng_f = ServeEngine(
            model, params, n_slots=2, tokens_per_launch=8, flight=rec
        )
        count = {"n": 0}

        def counting_f(x):
            count["n"] += 1
            return real_get(x)

        jax.device_get = counting_f
        try:
            comp_f = {}
            pending = list(prompts)
            for toks, max_new in pending[:2]:
                eng_f.submit(Request(prompt=toks, max_new_tokens=max_new))
            pending = pending[2:]
            while not eng_f.idle or pending:
                while pending:
                    toks, max_new = pending[0]
                    try:
                        eng_f.submit(
                            Request(prompt=toks, max_new_tokens=max_new)
                        )
                        pending.pop(0)
                    except QueueFull:
                        break
                for c in eng_f.step():
                    comp_f[c.request_id] = c
        finally:
            jax.device_get = real_get
        flight_budget = (
            eng_f.n_chains + eng_f.n_prefills + eng_f.n_splices
        )
        if count["n"] > flight_budget:
            problems.append(
                f"flight arm: {count['n']} host fetches > "
                f"{flight_budget} (recorder must cost zero fetches)"
            )
        if {r: c.tokens for r, c in comp_f.items()} != {
            r: c.tokens for r, c in completions.items()
        }:
            problems.append("flight recorder changed greedy tokens")
        spans = {s.get("rid"): s for s in rec.done_spans}
        span_keys = (
            "submit_t", "queue_pop_t", "prefill_t", "complete_t",
            "finish_reason",
        )
        span_full = len(spans) == len(prompts) and all(
            all(k in s for k in span_keys) for s in spans.values()
        )
        if not span_full:
            problems.append(
                f"flight arm: incomplete lifecycle spans: "
                f"{sorted(spans)} over {len(prompts)} requests"
            )
        kc = rec.kind_counts
        events_ok = (
            kc["submit"] == len(prompts)
            and kc["queue_pop"] == len(prompts)
            and kc["complete"] == len(prompts)
            and kc["prefill"] == eng_f.n_prefills
            and kc["chain_start"] == eng_f.n_chains
            and kc["chain_end"] == eng_f.n_chains
        )
        if not events_ok:
            problems.append(
                f"flight arm: event counts do not reconcile with the "
                f"engine counters: {dict(kc)} vs {eng_f.n_prefills} "
                f"prefills / {eng_f.n_chains} chains"
            )
        recon = all(
            abs(spans[r]["e2e_s"] - comp_f[r].latency_s) < 1e-5
            and abs(spans[r]["ttft_s"] - comp_f[r].ttft_s) < 1e-5
            for r in comp_f
        ) if span_full else False
        if span_full and not recon:
            problems.append(
                "flight arm: span timings diverge from Completion"
            )

        def hist_matches_sort(h, vals):
            # same rank convention as LogHistogram.quantile; the bound
            # is the histogram's own documented one-bucket error
            ok = True
            for q in (0.50, 0.95):
                sv = sorted(vals)[max(1, math.ceil(q * len(vals))) - 1]
                tol = h.rel_error_bound * max(sv, h.min_value) + 1e-9
                ok = ok and abs(h.quantile(q) - sv) <= tol
            return ok

        hist_ok = hist_matches_sort(
            rec.hist["e2e"], [c.latency_s for c in comp_f.values()]
        ) and hist_matches_sort(
            rec.hist["ttft"], [c.ttft_s for c in comp_f.values()]
        )
        if not hist_ok:
            problems.append(
                "flight arm: histogram p50/p95 outside one bucket of "
                "the sort-based percentiles"
            )
        flight_fields = {
            "flight_requests": len(prompts),
            "flight_span_full": span_full,
            "flight_events_consistent": events_ok,
            "flight_hist_vs_sort": hist_ok,
            "flight_host_fetches": count["n"],
            **eng_f.stats("flight"),
        }

    # ------------------------------------------------------------------
    # pipeline arm (--pipeline, ISSUE 11): the staggered base stream
    # again, now through a depth-2 double-buffered engine with chunked
    # prefill — tokens must stay byte-identical to the serial engine
    # (the pipeline only moves the fetch off the critical path), the
    # fetch budget is unchanged (mid-chunks are pure dispatch), and
    # chunking must visibly fire on the stream's 12-token prompt
    # ------------------------------------------------------------------
    pipeline_fields: dict = {}
    if pipeline:
        eng_p = ServeEngine(
            model, params, n_slots=2, tokens_per_launch=8,
            pipeline_depth=2, prefill_chunk=8,
        )
        count = {"n": 0}

        def counting_p(x):
            count["n"] += 1
            return real_get(x)

        jax.device_get = counting_p
        try:
            comp_p = {}
            pending = list(prompts)
            for toks, max_new in pending[:2]:
                eng_p.submit(Request(prompt=toks, max_new_tokens=max_new))
            pending = pending[2:]
            while not eng_p.idle or pending:
                while pending:
                    toks, max_new = pending[0]
                    try:
                        eng_p.submit(
                            Request(prompt=toks, max_new_tokens=max_new)
                        )
                        pending.pop(0)
                    except QueueFull:
                        break
                for c in eng_p.step():
                    comp_p[c.request_id] = c
        finally:
            jax.device_get = real_get
        pipeline_exact = {r: c.tokens for r, c in comp_p.items()} == {
            r: c.tokens for r, c in completions.items()
        }
        if not pipeline_exact:
            problems.append(
                "pipelined engine changed greedy tokens vs serial"
            )
        p_budget = eng_p.n_chains + eng_p.n_prefills + eng_p.n_splices
        if count["n"] > p_budget:
            problems.append(
                f"pipeline arm: {count['n']} host fetches > {p_budget} "
                f"({eng_p.n_chains} chains + {eng_p.n_prefills} prefills "
                f"+ {eng_p.n_splices} splices — chunks must add none)"
            )
        pstats = eng_p.stats("pipeline")
        if pstats.get("n_chunks", 0) < 1:
            problems.append(
                f"chunked prefill never fired on a 12-token prompt with "
                f"an 8-token chunk: {pstats}"
            )
        pipeline_fields = {
            "pipeline_requests": len(prompts),
            "pipeline_token_exact": pipeline_exact,
            "pipeline_host_fetches": count["n"],
            **pstats,
        }

    # ------------------------------------------------------------------
    # paged arm (--paged, ISSUE 13): a short+long mixed stream at
    # OVERSUBSCRIBED slot count (3 slots x 64-token windows = 192
    # claimable tokens over a 6-page x 8-token pool = 48) — admission is
    # by PAGES, tokens must stay byte-identical to the whole-slot
    # engine, the fetch budget is unchanged, a request that can never
    # fit the pool sheds synchronously at submit, and a prefix-cache
    # leg must show page SHARES (retained prefix pages seeding new
    # requests copy-free) while staying byte-identical to cache-off
    # ------------------------------------------------------------------
    paged_fields: dict = {}
    if paged:
        from pytorch_distributed_training_tutorials_tpu.serve import PoolExhausted

        paged_reqs = []
        for i, (p_len, max_new) in enumerate(
            [(3, 9), (17, 12), (5, 5), (12, 6), (2, 17), (9, 14)]
        ):
            rng, sub = jax.random.split(rng)
            paged_reqs.append((
                jax.device_get(jax.random.randint(
                    sub, (p_len,), 0, cfg.vocab_size
                )).tolist(),
                max_new,
            ))

        def run_paged_stream(reqs, prefix_bytes=0, page_kw=None):
            eng = ServeEngine(
                model, params, n_slots=3, tokens_per_launch=8,
                prefix_cache_bytes=prefix_bytes, **(page_kw or {}),
            )
            count = {"n": 0}

            def counting(x):
                count["n"] += 1
                return real_get(x)

            jax.device_get = counting
            try:
                out = {}
                pending = list(reqs)
                for toks, max_new in pending[:3]:
                    eng.submit(Request(prompt=toks, max_new_tokens=max_new))
                pending = pending[3:]
                while not eng.idle or pending:
                    while pending:
                        toks, max_new = pending[0]
                        try:
                            eng.submit(Request(
                                prompt=toks, max_new_tokens=max_new
                            ))
                            pending.pop(0)
                        except QueueFull:
                            break
                    for c in eng.step():
                        out[c.request_id] = c.tokens
            finally:
                jax.device_get = real_get
            return eng, out, count["n"]

        geometry = dict(paged=True, page_size=8, pool_pages=6)
        eng_ws, toks_ws, _ = run_paged_stream(paged_reqs)
        eng_pg, toks_pg, fetches_pg = run_paged_stream(
            paged_reqs, page_kw=geometry
        )
        paged_exact = toks_pg == toks_ws
        if not paged_exact:
            problems.append(
                f"paged engine changed greedy tokens: {toks_pg} != "
                f"{toks_ws}"
            )
        pg_budget = eng_pg.n_chains + eng_pg.n_prefills
        if fetches_pg > pg_budget:
            problems.append(
                f"paged arm: {fetches_pg} host fetches > {pg_budget} "
                f"({eng_pg.n_chains} chains + {eng_pg.n_prefills} "
                f"prefills)"
            )
        # a request that can never fit the 48-token pool (but WOULD fit
        # the 64-token window) must shed synchronously at submit
        paged_shed = False
        try:
            eng_pg.submit(Request(
                prompt=paged_reqs[1][0] * 2, max_new_tokens=30
            ))
            problems.append("pool-exceeding request admitted at submit")
        except PoolExhausted:
            paged_shed = True
        pgstats = eng_pg.stats("pages")
        if pgstats.get("pages_high_water", 0) < 1:
            problems.append(f"paged arm: pool never allocated: {pgstats}")
        if pgstats.get("pages_in_use", -1) != 0:
            problems.append(
                f"paged arm: {pgstats.get('pages_in_use')} pages leaked "
                f"after the stream drained"
            )
        # prefix leg: the overlapping stream through a paged cache-on
        # engine — tokens must match the (whole-slot) cache-off arm, and
        # the retained prefix pages must be SHARED, not copied
        eng_px, toks_px, fetches_px = run_paged_stream(
            overlap_reqs, prefix_bytes=16 * 1024 * 1024,
            page_kw=dict(paged=True, page_size=8, pool_pages=16),
        )
        paged_prefix_exact = toks_px == toks_off
        if not paged_prefix_exact:
            problems.append(
                f"paged prefix leg changed greedy tokens: {toks_px} != "
                f"{toks_off}"
            )
        px_budget = eng_px.n_chains + eng_px.n_prefills + eng_px.n_splices
        if fetches_px > px_budget:
            problems.append(
                f"paged prefix leg: {fetches_px} host fetches > "
                f"{px_budget} (chains + prefills + splices)"
            )
        pxstats = eng_px.stats("pages")
        if pxstats.get("pages_shares", 0) < 1:
            problems.append(
                f"paged prefix leg: no page shares on an overlapping "
                f"stream: {pxstats}"
            )
        # kernel leg (ISSUE 17): the same stream through the fused
        # Pallas page-walk read path — full-precision greedy must be
        # token-exact to the gather engine (and so to whole-slot), at
        # the unchanged fetch budget
        eng_kn, toks_kn, fetches_kn = run_paged_stream(
            paged_reqs, page_kw=dict(paged_kernel=True, **geometry),
        )
        kernel_exact = toks_kn == toks_ws
        if not kernel_exact:
            problems.append(
                f"paged kernel changed greedy tokens: {toks_kn} != "
                f"{toks_ws}"
            )
        kn_budget = eng_kn.n_chains + eng_kn.n_prefills
        if fetches_kn > kn_budget:
            problems.append(
                f"paged kernel leg: {fetches_kn} host fetches > "
                f"{kn_budget} (chains + prefills)"
            )
        # int4 leg (ISSUE 17): packed-nibble KV halves page_bytes
        # EXACTLY (bf16 scales: d/2 + 2 vs d + 4 per token-head), so
        # 2x the pages fit the int8 pool's HBM — the stream must still
        # complete every request (int4 rounding moves near-tie tokens,
        # so no exactness pin vs full precision) within budget
        eng_i8, _, _ = run_paged_stream(
            paged_reqs, page_kw=dict(kv_bits=8, **geometry),
        )
        eng_i4, toks_i4, fetches_i4 = run_paged_stream(
            paged_reqs,
            page_kw=dict(
                kv_bits=4, paged_kernel=True, paged=True,
                page_size=8, pool_pages=12,
            ),
        )
        pb8 = eng_i8.page_stats()["page_bytes"]
        pb4 = eng_i4.page_stats()["page_bytes"]
        int4_halved = pb4 * 2 == pb8
        if not int4_halved:
            problems.append(
                f"int4 page_bytes {pb4} is not exactly half of int8's "
                f"{pb8}"
            )
        int4_ok = (
            len(toks_i4) == len(paged_reqs)
            and all(
                len(toks_i4[rid]) > 0 for rid in toks_i4
            )
            and fetches_i4 <= eng_i4.n_chains + eng_i4.n_prefills
        )
        if not int4_ok:
            problems.append(
                f"int4 kernel leg incomplete or over budget: "
                f"{len(toks_i4)} completions, {fetches_i4} fetches"
            )
        paged_fields = {
            "paged_requests": len(paged_reqs),
            "paged_token_exact": paged_exact,
            "paged_host_fetches": fetches_pg,
            "paged_shed_ok": paged_shed,
            "paged_prefix_token_exact": paged_prefix_exact,
            "paged_prefix_shares": pxstats.get("pages_shares", 0),
            "paged_kernel_token_exact": kernel_exact,
            "paged_int4_page_bytes_halved": int4_halved,
            "paged_int4_ok": int4_ok,
            "paged_int4_pool_pages": 12,
            **pgstats,
        }

    # ------------------------------------------------------------------
    # router arm (--router, ISSUE 12): a 3-replica fleet of REAL engines
    # behind the FleetRouter. Leg 1 (fault-free) pins fleet == single
    # engine: every request's greedy tokens byte-identical to the base
    # arm's. Leg 2 re-runs the same stream with a chaos-killed replica
    # mid-stream: the DispatchLedger must verify exactly-once (no
    # accepted request lost or completed twice), every request that
    # still finished "length" must be byte-identical to the fault-free
    # run (re-dispatch is invisible in outputs — same template, same
    # seed), and the summed per-replica fetch budget stays exactly
    # chains + prefills + splices. The fleet flight summary (merged
    # histograms, shared t0) rides into the receipt.
    # ------------------------------------------------------------------
    router_fields: dict = {}
    if router:
        import time as _time

        from pytorch_distributed_training_tutorials_tpu.obs import FlightRecorder
        from pytorch_distributed_training_tutorials_tpu.serve import FleetRouter, affinity_hash
        from pytorch_distributed_training_tutorials_tpu.utils.chaos import FleetChaosConfig

        n_replicas = 3
        # the base stream plus two same-prompt clones of request 0, so
        # the kill target (request 0's affine replica) is guaranteed to
        # hold BOTH in-flight and queued work when it dies
        fleet_stream = list(prompts) + [prompts[0], prompts[0]]
        expected_gid = {g: completions[g].tokens for g in range(len(prompts))}
        expected_gid[len(prompts)] = completions[0].tokens
        expected_gid[len(prompts) + 1] = completions[0].tokens
        kill_target = affinity_hash(prompts[0][0], adapter=0,
                                    depth=16) % n_replicas

        def run_fleet(fleet_chaos):
            t0 = _time.perf_counter()
            engines = [
                ServeEngine(
                    model, params, n_slots=1, tokens_per_launch=4,
                    max_queue=8,
                    flight=FlightRecorder(capacity=256, t0=t0),
                )
                for _ in range(n_replicas)
            ]
            fr = FleetRouter(
                engines, chaos=fleet_chaos,
                flight=FlightRecorder(capacity=256, t0=t0),
            )
            count = {"n": 0}

            def counting(x):
                count["n"] += 1
                return real_get(x)

            jax.device_get = counting
            try:
                out = {}
                for toks, max_new in fleet_stream:
                    fr.submit(Request(prompt=toks, max_new_tokens=max_new))
                for c in fr.run_until_idle():
                    out[c.request_id] = c
            finally:
                jax.device_get = real_get
            return fr, engines, out, count["n"]

        # leg 1: fault-free fleet — byte-identical to the single engine
        fr_ok, eng_ok, out_ok, fetches_ok = run_fleet(None)
        fleet_exact = all(
            out_ok[g].tokens == expected_gid[g]
            and out_ok[g].finish_reason == "length"
            for g in expected_gid
        )
        if len(out_ok) != len(fleet_stream) or not fleet_exact:
            problems.append(
                f"fault-free fleet diverged from the single engine: "
                f"{[(g, c.finish_reason) for g, c in sorted(out_ok.items())]}"
            )
        ledger_ok = fr_ok.ledger.verify()
        if ledger_ok:
            problems.append(f"fault-free fleet ledger: {ledger_ok}")

        # leg 2: same stream, one replica chaos-killed mid-stream
        fr_x, eng_x2, out_x, fetches_x = run_fleet(FleetChaosConfig(
            kill_replica=kill_target, kill_at_chain=2,
        ))
        if len(out_x) != len(fleet_stream):
            problems.append(
                f"chaos fleet: {len(out_x)} completions for "
                f"{len(fleet_stream)} accepted requests"
            )
        ledger_x = fr_x.ledger.verify()
        if ledger_x:
            problems.append(f"chaos fleet ledger: {ledger_x}")
        if fr_x.replica_states()[kill_target] != "dead":
            problems.append(
                f"killed replica {kill_target} is "
                f"{fr_x.replica_states()[kill_target]!r}, expected dead"
            )
        moved = fr_x.ledger.n_redispatched + fr_x.n_dead_completions
        if moved < 1:
            problems.append(
                "chaos fleet: the killed replica held no work — the "
                "re-dispatch path never fired"
            )
        router_exact = all(
            c.tokens == expected_gid[g]
            for g, c in out_x.items()
            if c.finish_reason in ("length", "eos")
        )
        if not router_exact:
            problems.append(
                "chaos fleet: a re-dispatched request's tokens diverged "
                "from the fault-free run"
            )
        # summed per-replica fetch budget: the killed engine's counters
        # freeze at the kill (the router never steps it again)
        fleet_budget = sum(
            e.n_chains + e.n_prefills + e.n_splices for e in eng_x2
        )
        if fetches_x > fleet_budget:
            problems.append(
                f"chaos fleet: {fetches_x} host fetches > {fleet_budget} "
                f"(sum of per-replica chains + prefills + splices)"
            )
        rstats = fr_x.stats()
        if (fr_x.fleet_flight_summary() or {}).get("e2e_count", 0) < 1:
            problems.append("fleet flight summary recorded no requests")
        router_fields = {
            "router_requests": len(fleet_stream),
            "router_fleet_exact": fleet_exact and router_exact,
            "router_host_fetches_ok": fetches_ok,
            "router_host_fetches_chaos": fetches_x,
            "router_killed_replica": kill_target,
            **{f"router_{k}": v for k, v in rstats.items()
               if isinstance(v, (int, float, bool))},
        }

    # ------------------------------------------------------------------
    # chaos arm (--chaos, ISSUE 9): one staggered stream exercising every
    # serving failure path — injected NaN logits (quarantine), a deadline
    # expiry, a host-side cancel, close/drain — with the fetch budget
    # still counted, co-scheduled requests still token-identical to a
    # clean run; plus a mini training leg driving the skip-step guard
    # (poisoned batch -> state bitwise unchanged, counter increments)
    # ------------------------------------------------------------------
    fault_fields: dict = {}
    if chaos:
        import optax

        from pytorch_distributed_training_tutorials_tpu.models import (
            LinearRegressor,
        )
        from pytorch_distributed_training_tutorials_tpu.serve import (
            QueueClosed,
        )
        from pytorch_distributed_training_tutorials_tpu.train.trainer import (
            TrainState,
            make_train_step,
        )
        from pytorch_distributed_training_tutorials_tpu.utils import (
            chaos as chaos_lib,
        )

        p0, p1 = prompts[0][0], prompts[1][0]
        ccfg = chaos_lib.ChaosConfig(nan_logit_slot=0, nan_logit_step=3)

        # clean reference (guard on, NO faults) for token-identity
        eng_ref = ServeEngine(
            model, params, n_slots=2, tokens_per_launch=4,
            guard_nonfinite=True,
        )
        eng_ref.submit(Request(prompt=p0, max_new_tokens=12))
        eng_ref.submit(Request(prompt=p1, max_new_tokens=16))
        ref = {c.request_id: c for c in eng_ref.run_until_idle()}

        # the faulty engine carries a dump-path recorder: every injected
        # fault must auto-dump a graft-flightlog/v1 snapshot whose
        # trigger names the victim (the ISSUE 10 post-mortem contract)
        from pytorch_distributed_training_tutorials_tpu.obs import (
            FlightRecorder,
            load_flightlog,
        )

        fd, dump_path = tempfile.mkstemp(suffix=".flightlog.jsonl")
        os.close(fd)
        eng_x = ServeEngine(
            model, params, n_slots=2, tokens_per_launch=4,
            guard_nonfinite=True, chaos=ccfg,
            flight=FlightRecorder(capacity=128, dump_path=dump_path),
        )
        count = {"n": 0}

        def counting(x):
            count["n"] += 1
            return real_get(x)

        jax.device_get = counting
        try:
            r0 = eng_x.submit(Request(prompt=p0, max_new_tokens=12))
            r1 = eng_x.submit(Request(prompt=p1, max_new_tokens=16))
            r2 = eng_x.submit(
                Request(prompt=p0, max_new_tokens=8, deadline_s=1e-6)
            )
            r3 = eng_x.submit(Request(prompt=p1, max_new_tokens=8))
            eng_x.cancel(r3)
            out = {c.request_id: c for c in eng_x.drain()}
        finally:
            jax.device_get = real_get
        chaos_fetches = count["n"]
        try:
            eng_x.submit(Request(prompt=p0, max_new_tokens=2))
            problems.append("submit admitted after close()")
        except QueueClosed:
            pass
        if out[r0].finish_reason != "nonfinite":
            problems.append(
                f"poisoned slot finished {out[r0].finish_reason!r}, "
                "expected 'nonfinite'"
            )
        chaos_exact = (
            out[r0].tokens == ref[0].tokens[: len(out[r0].tokens)]
            and len(out[r0].tokens) < len(ref[0].tokens)
            and out[r1].tokens == ref[1].tokens
        )
        if not chaos_exact:
            problems.append(
                f"chaos arm tokens diverged from clean run: poisoned "
                f"{out[r0].tokens} vs clean {ref[0].tokens}, co-scheduled "
                f"{out[r1].tokens} vs {ref[1].tokens}"
            )
        if out[r2].finish_reason != "deadline" or out[r2].tokens:
            problems.append(
                f"deadline request finished {out[r2].finish_reason!r} "
                f"with {len(out[r2].tokens)} tokens"
            )
        if out[r3].finish_reason != "cancelled":
            problems.append(
                f"cancelled request finished {out[r3].finish_reason!r}"
            )
        chaos_budget = eng_x.n_chains + eng_x.n_prefills + eng_x.n_splices
        if chaos_fetches > chaos_budget:
            problems.append(
                f"chaos arm: {chaos_fetches} host fetches > "
                f"{chaos_budget} (chains + prefills + splices)"
            )
        fstats = eng_x.stats("fault")
        for key, want in (
            ("nonfinite_quarantined", 1),
            ("deadline_expired", 1),
            ("cancelled", 1),
        ):
            if fstats.get(key) != want:
                problems.append(
                    f"fault_stats[{key!r}] = {fstats.get(key)}, "
                    f"expected {want}"
                )
        # the flight dump: one snapshot per fault-class event, and the
        # nonfinite one must NAME the quarantined slot
        try:
            snaps = load_flightlog(dump_path)
        except ValueError as e:
            snaps = []
            problems.append(f"chaos flight dump failed validation: {e}")
        named_slot = any(
            s.get("trigger", {}).get("fault_kind") == "nonfinite"
            and s.get("trigger", {}).get("slot") == 0
            for s in snaps
            if s.get("trigger")
        )
        if len(snaps) < 2:  # nonfinite + deadline at minimum
            problems.append(
                f"chaos arm: {len(snaps)} flight dumps, expected >= 2 "
                "(nonfinite quarantine + deadline expiry)"
            )
        if not named_slot:
            problems.append(
                "chaos arm: no flight dump names the quarantined slot"
            )
        os.unlink(dump_path)

        # mini training leg: skip-step guard on a poisoned batch
        reg = LinearRegressor(in_dim=4)
        key = jax.random.PRNGKey(2)
        xb = jax.random.normal(key, (8, 4))
        yb = jnp.ones((8, 1), jnp.float32)
        st = TrainState.create(
            apply_fn=reg.apply,
            params=reg.init(key, xb)["params"],
            tx=optax.adam(1e-2),
        )
        gstep = make_train_step(loss="mse", skip_nonfinite=True)
        tcfg = chaos_lib.ChaosConfig(nan_batch_step=1)
        before = real_get((st.params, st.opt_state, st.step))
        st1, m1 = gstep(st, chaos_lib.maybe_poison_batch(tcfg, 1, (xb, yb)))
        after = real_get((st1.params, st1.opt_state, st1.step))
        st2, m2 = gstep(st1, chaos_lib.maybe_poison_batch(tcfg, 2, (xb, yb)))
        steps_skipped = int(real_get(m1["skipped"])) + int(
            real_get(m2["skipped"])
        )
        import numpy as np

        bitwise_skip = all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(
                jax.tree_util.tree_leaves(before),
                jax.tree_util.tree_leaves(after),
            )
        )
        if not bitwise_skip:
            problems.append(
                "skip-step left TrainState changed after a poisoned batch"
            )
        if steps_skipped != 1:
            problems.append(
                f"steps_skipped = {steps_skipped}, expected exactly 1 "
                "(poisoned batch skipped, clean batch applied)"
            )
        if int(real_get(st2.step)) != 1:
            problems.append("clean step after the skip did not apply")
        fault_fields = {
            **fstats,
            "steps_skipped": steps_skipped,
            "chaos_token_exact": chaos_exact,
            "chaos_host_fetches": chaos_fetches,
            "chaos_flight_dumps": len(snaps),
            "chaos_flight_named_slot": named_slot,
        }

    # ------------------------------------------------------------------
    # tp arm (--tp N, ISSUE 15): the base staggered stream through a
    # TensorParallel-sharded engine on a {'model': N} mesh. Greedy
    # tokens must be byte-identical to the replicated base arm (the
    # Megatron split is an implementation detail), the fetch budget is
    # unchanged (ONE batched device_get per chain regardless of mesh —
    # per-shard fetches would multiply the launch roundtrip by tp), the
    # KV cache must REALLY shard (per-chip bytes < global bytes), and
    # the compiled decode chain's HLO must contain no collectives
    # beyond the whitelisted all-reduces (audit_decode_hlo).
    # ------------------------------------------------------------------
    tp_fields: dict = {}
    if tp > 1:
        from pytorch_distributed_training_tutorials_tpu.models.transformer import TP_RULES
        from pytorch_distributed_training_tutorials_tpu.parallel import TensorParallel
        from pytorch_distributed_training_tutorials_tpu.parallel.mesh import create_mesh

        if len(jax.devices()) < tp:
            problems.append(
                f"tp arm: {len(jax.devices())} devices < tp={tp}"
            )
        else:
            mesh = create_mesh({"model": tp})
            eng_tp = ServeEngine(
                model, params, n_slots=2, tokens_per_launch=8,
                strategy=TensorParallel(mesh, TP_RULES),
            )
            count_tp = {"n": 0}

            def counting_tp(x):
                count_tp["n"] += 1
                return real_get(x)

            jax.device_get = counting_tp
            try:
                toks_tp = {}
                pending = list(prompts)
                for toks, max_new in pending[:2]:
                    eng_tp.submit(
                        Request(prompt=toks, max_new_tokens=max_new)
                    )
                pending = pending[2:]
                while not eng_tp.idle or pending:
                    while pending:
                        toks, max_new = pending[0]
                        try:
                            eng_tp.submit(Request(
                                prompt=toks, max_new_tokens=max_new
                            ))
                            pending.pop(0)
                        except QueueFull:
                            break
                    for c in eng_tp.step():
                        toks_tp[c.request_id] = c.tokens
                fetches_tp = count_tp["n"]
            finally:
                jax.device_get = real_get
            tp_exact = all(
                toks_tp.get(rid) == completions[rid].tokens
                for rid in range(len(prompts))
            )
            if not tp_exact:
                problems.append(
                    f"tp={tp} engine changed greedy tokens: {toks_tp}"
                )
            tp_budget = eng_tp.n_chains + eng_tp.n_prefills
            if fetches_tp > tp_budget:
                problems.append(
                    f"tp arm: {fetches_tp} host fetches > {tp_budget} "
                    f"({eng_tp.n_chains} chains + {eng_tp.n_prefills} "
                    f"prefills) — a per-shard fetch leaked in"
                )
            audit = eng_tp.audit_decode_hlo()
            if not audit["ok"]:
                problems.append(
                    f"tp arm: unexpected collectives in the decode "
                    f"HLO: {audit['problems'][:3]}"
                )
            tpstats = eng_tp.stats("tp")
            from pytorch_distributed_training_tutorials_tpu.serve.slots import tree_nbytes
            global_kv = tree_nbytes(eng_tp._state["cache"])
            if tpstats.get("tp_kv_bytes_per_chip", global_kv) >= global_kv:
                problems.append(
                    f"tp arm: per-chip KV bytes "
                    f"{tpstats.get('tp_kv_bytes_per_chip')} not below "
                    f"global {global_kv} — the cache never sharded"
                )
            tp_fields = {
                "tp_requests": len(prompts),
                "tp_token_exact": tp_exact,
                "tp_host_fetches": fetches_tp,
                "tp_kv_bytes_global": global_kv,
                **tpstats,
            }

    # ------------------------------------------------------------------
    # contract-sentry arm (ISSUE 19): the runtime twin of this harness's
    # own monkeypatch spies. A sentry-instrumented engine runs the base
    # stream clean (warmup, mark_steady, then a steady repeat that must
    # show ZERO steady recompiles, an exactly-balanced fetch budget, and
    # zero host-numpy re-uploads — with the sentry's counts equal to an
    # independent monkeypatch spy's). Then one injected violation per
    # probe class — a post-steady jit of a fresh program, a stray
    # device_get inside a step round, a host-numpy arg tree — must each
    # produce exactly one typed flight event and one graft-flightlog/v1
    # auto-dump naming its trigger.
    # ------------------------------------------------------------------
    sentry_fields: dict = {}
    if sentry:
        from pytorch_distributed_training_tutorials_tpu.obs import (
            ContractSentry,
            FlightRecorder,
            load_flightlog,
        )

        fd, sen_dump = tempfile.mkstemp(suffix=".flightlog.jsonl")
        os.close(fd)
        fl_sen = FlightRecorder(capacity=256, dump_path=sen_dump)
        sen = ContractSentry(flight=fl_sen)
        eng_sen = ServeEngine(
            model, params, n_slots=2, tokens_per_launch=8, max_queue=2,
            flight=fl_sen, sentry=sen,
        )
        count_sen = {"n": 0}

        def counting_sen(x):
            count_sen["n"] += 1
            return real_get(x)

        def run_sen_stream(collect):
            pending = list(prompts)
            for toks, max_new in pending[:2]:
                eng_sen.submit(Request(prompt=toks, max_new_tokens=max_new))
            pending = pending[2:]
            while not eng_sen.idle or pending:
                while pending:
                    toks, max_new = pending[0]
                    try:
                        eng_sen.submit(
                            Request(prompt=toks, max_new_tokens=max_new)
                        )
                        pending.pop(0)
                    except QueueFull:
                        break
                for c in eng_sen.step():
                    collect[c.request_id] = c.tokens

        # the spy goes UNDER the sentry wrapper: every fetch flows
        # sentry -> spy -> real, so the two counters must agree exactly
        jax.device_get = counting_sen
        sen.install()
        try:
            # warmup phase: every compiled program this stream needs
            run_sen_stream({})
            # prebuild the injection operands while compiles are still
            # legal — jnp.zeros/arange compile their own fill programs,
            # which must not pollute the steady-state count
            stray_scalar = jnp.zeros((), jnp.float32)
            fresh_arg = jnp.arange(11, dtype=jnp.float32)
            device_tree = {"w": jnp.ones((4, 4), jnp.float32)}
            sen.mark_steady()

            # steady clean leg: identical shapes, zero new programs
            toks_sen: dict = {}
            base_id = len(prompts)  # phase 1 consumed ids 0..N-1
            run_sen_stream(toks_sen)
            sen_exact = all(
                toks_sen.get(base_id + rid) == completions[rid].tokens
                for rid in range(len(prompts))
            )
            if not sen_exact:
                problems.append(
                    f"sentry arm: instrumented engine changed greedy "
                    f"tokens: {toks_sen}"
                )
            if sen.n_steady_recompiles:
                problems.append(
                    f"sentry arm: {sen.n_steady_recompiles} steady "
                    f"recompiles on a shape-identical repeat stream"
                )
            if sen.n_budget_violations:
                problems.append(
                    f"sentry arm: {sen.n_budget_violations} budget "
                    "violations on the clean stream"
                )
            sen_budget = eng_sen.n_chains + eng_sen.n_prefills
            if not (sen.n_fetched == count_sen["n"]
                    == sen.n_budgeted == sen_budget):
                problems.append(
                    f"sentry arm: fetch accounting disagrees — sentry "
                    f"{sen.n_fetched} fetched / {sen.n_budgeted} "
                    f"budgeted, spy {count_sen['n']}, engine budget "
                    f"{sen_budget}"
                )
            clean_summary = dict(sen.summary())

            # violation leg 1: a post-steady compilation (fresh program
            # over a PREBUILT operand) — exactly one steady recompile
            jax.jit(lambda v: v * 3.0 + 1.0)(fresh_arg)
            recompile_caught = sen.n_steady_recompiles == 1
            if not recompile_caught:
                problems.append(
                    f"sentry arm: injected recompile counted "
                    f"{sen.n_steady_recompiles} times (want 1; "
                    f"probe={sen.compile_probe})"
                )

            # violation leg 2: a stray un-budgeted device_get inside ONE
            # step round (the leak the fetch-budget rule exists to stop)
            orig_sweep = eng_sen._sweep

            def leaky_sweep():
                jax.device_get(stray_scalar)
                return orig_sweep()

            eng_sen.submit(Request(prompt=prompts[0][0], max_new_tokens=3))
            eng_sen._sweep = leaky_sweep
            eng_sen.step()  # exactly one over-budget round
            eng_sen._sweep = orig_sweep
            while not eng_sen.idle:
                eng_sen.step()
            budget_caught = sen.n_budget_violations == 1
            if not budget_caught:
                problems.append(
                    f"sentry arm: injected stray fetch flagged "
                    f"{sen.n_budget_violations} rounds (want 1)"
                )

            # violation leg 3: host-numpy leaves in an arg tree fire the
            # re-upload probe; the device-resident twin stays silent
            import numpy as np
            sen.check_args(
                {"w": np.ones((4, 4), np.float32)}, label="selftest_numpy"
            )
            clean_bytes = sen.check_args(device_tree, label="selftest_numpy")
            reupload_caught = sen.n_reuploads == 1 and clean_bytes == 0
            if not reupload_caught:
                problems.append(
                    f"sentry arm: reupload probe saw {sen.n_reuploads} "
                    f"hits / {clean_bytes} B on the device twin "
                    "(want 1 / 0)"
                )
        finally:
            sen.uninstall()
            jax.device_get = real_get

        # each injected violation class = one auto-dump naming its
        # trigger (the chaos-arm contract, extended to the sentry kinds)
        try:
            snaps = load_flightlog(sen_dump)
        except ValueError as e:
            snaps = []
            problems.append(f"sentry flight dump failed validation: {e}")
        by_reason: dict = {}
        for s in snaps:
            by_reason.setdefault(s["reason"], []).append(s)
        for reason, check in (
            ("compile", lambda t: t.get("steady") is True),
            ("budget_violation",
             lambda t: t.get("fetched", 0) > t.get("budgeted", 0)),
            ("reupload", lambda t: t.get("label") == "selftest_numpy"),
        ):
            got = by_reason.get(reason, [])
            if len(got) != 1:
                problems.append(
                    f"sentry arm: {len(got)} '{reason}' dumps (want "
                    "exactly 1)"
                )
            elif not check(got[0].get("trigger") or {}):
                problems.append(
                    f"sentry arm: '{reason}' dump trigger does not name "
                    f"its violation: {got[0].get('trigger')}"
                )
        os.unlink(sen_dump)
        sentry_fields = {
            **clean_summary,
            "sentry_token_exact": sen_exact,
            "sentry_injected_recompile_caught": recompile_caught,
            "sentry_injected_budget_caught": budget_caught,
            "sentry_injected_reupload_caught": reupload_caught,
            "sentry_dump_snapshots": len(snaps),
        }

    # ------------------------------------------------------------------
    # slo arm (--slo, ISSUE 20): priority scheduling + preemption by KV
    # swap. A 1-slot priority engine decoding a low-class request must
    # preempt for an arriving class-0 request (swap the victim's cache
    # segment to host — the counted swap fetch), serve the interactive
    # request, swap the victim back in, and finish BOTH token-exact to
    # generate(). Budget = chains + prefills + splices + swaps, pinned
    # by the monkeypatch spy AND a ContractSentry riding the stream.
    # Chaos leg: preempt_at_chain force-preempts with no pressure; the
    # victim resumes token-exact and the co-scheduled slot is
    # byte-identical to a clean run. Host leg: single-class
    # PriorityScheduler pop order == FifoScheduler.
    # ------------------------------------------------------------------
    slo_fields: dict = {}
    if slo:
        from pytorch_distributed_training_tutorials_tpu.obs import ContractSentry
        from pytorch_distributed_training_tutorials_tpu.serve.scheduler import FifoScheduler
        from pytorch_distributed_training_tutorials_tpu.serve.slo import PriorityScheduler
        from pytorch_distributed_training_tutorials_tpu.utils.chaos import ChaosConfig

        lo_toks, lo_new = prompts[4]   # (2, 17): 3 chains of decode
        hi_toks, hi_new = prompts[3]   # (12, 6): the interactive burst

        def one_shot(toks, max_new):
            return jax.device_get(
                generate(
                    model, params, jnp.asarray([toks], jnp.int32), max_new
                )
            )[0, len(toks):].tolist()

        lo_ref = one_shot(lo_toks, lo_new)
        hi_ref = one_shot(hi_toks, hi_new)

        sen_slo = ContractSentry()
        eng_slo = ServeEngine(
            model, params, n_slots=1, tokens_per_launch=8,
            priority_classes=2, sentry=sen_slo,
        )
        count_slo = {"n": 0}

        def counting_slo(x):
            count_slo["n"] += 1
            return real_get(x)

        # spy goes UNDER the sentry wrapper (sentry -> spy -> real), the
        # same layering the --sentry arm uses, so both counters see
        # every fetch including the swap-out's
        jax.device_get = counting_slo
        sen_slo.install()
        try:
            slo_done = {}
            lo_req = Request(prompt=list(lo_toks), max_new_tokens=lo_new,
                             priority=1)
            eng_slo.submit(lo_req)
            for c in eng_slo.step():   # prefill + first chain (9 of 17)
                slo_done[c.request_id] = c
            hi_req = Request(prompt=list(hi_toks), max_new_tokens=hi_new,
                             priority=0)
            eng_slo.submit(hi_req)
            while not eng_slo.idle:
                for c in eng_slo.step():
                    slo_done[c.request_id] = c
            slo_fetches = count_slo["n"]
        finally:
            sen_slo.uninstall()
            jax.device_get = real_get
        if eng_slo.n_swaps_out < 1 or eng_slo.n_swaps_in < 1:
            problems.append(
                f"slo arm: no preemption fired (swaps out "
                f"{eng_slo.n_swaps_out} / in {eng_slo.n_swaps_in})"
            )
        slo_exact = (
            slo_done[lo_req.request_id].tokens == lo_ref
            and slo_done[hi_req.request_id].tokens == hi_ref
        )
        if not slo_exact:
            problems.append(
                f"slo arm: preemption changed greedy tokens — lo "
                f"{slo_done[lo_req.request_id].tokens} vs {lo_ref}, hi "
                f"{slo_done[hi_req.request_id].tokens} vs {hi_ref}"
            )
        slo_budget = (
            eng_slo.n_chains + eng_slo.n_prefills + eng_slo.n_splices
            + eng_slo.n_swaps_out
        )
        if slo_fetches > slo_budget:
            problems.append(
                f"slo arm: {slo_fetches} host fetches > {slo_budget} "
                f"({eng_slo.n_chains} chains + {eng_slo.n_prefills} "
                f"prefills + {eng_slo.n_splices} splices + "
                f"{eng_slo.n_swaps_out} swaps)"
            )
        # the sentry's round accounting is the same claim at runtime:
        # every swap fetch flowed through the budgeted _sentry_fetch
        # seam, so no round closed with fetched > budgeted
        if sen_slo.n_budget_violations:
            problems.append(
                f"slo arm: {sen_slo.n_budget_violations} sentry budget "
                f"violations — a swap fetch escaped the budgeted seam"
            )
        if sen_slo.n_fetched != sen_slo.n_budgeted:
            problems.append(
                f"slo arm: sentry fetched {sen_slo.n_fetched} != "
                f"budgeted {sen_slo.n_budgeted}"
            )

        # chaos leg: forced preempt with NO pressure — the co-scheduled
        # slot must be byte-identical to a clean 2-slot run
        clean2 = ServeEngine(model, params, n_slots=2, tokens_per_launch=8)
        a_req = Request(prompt=list(lo_toks), max_new_tokens=lo_new)
        b_req = Request(prompt=list(hi_toks), max_new_tokens=hi_new)
        clean2.submit(a_req)
        clean2.submit(b_req)
        clean_out = {c.request_id: c.tokens for c in clean2.run_until_idle()}
        chaos2 = ServeEngine(
            model, params, n_slots=2, tokens_per_launch=8,
            priority_classes=2,
            chaos=ChaosConfig(preempt_slot=0, preempt_at_chain=1),
        )
        a2 = Request(prompt=list(lo_toks), max_new_tokens=lo_new, priority=1)
        b2 = Request(prompt=list(hi_toks), max_new_tokens=hi_new, priority=1)
        chaos2.submit(a2)
        chaos2.submit(b2)
        chaos_out = {c.request_id: c.tokens
                     for c in chaos2.run_until_idle()}
        if chaos2.n_swaps_out != 1:
            problems.append(
                f"slo arm: chaos preempt fired {chaos2.n_swaps_out} "
                "times (want exactly 1)"
            )
        chaos_exact = (
            chaos_out[a2.request_id] == clean_out[a_req.request_id]
            and chaos_out[b2.request_id] == clean_out[b_req.request_id]
        )
        if not chaos_exact:
            problems.append(
                f"slo arm: forced preempt changed tokens — "
                f"{chaos_out} vs clean {clean_out}"
            )

        # host leg: single-class PriorityScheduler pop order ==
        # FifoScheduler over the same submissions (jax-free)
        fifo = FifoScheduler(64, max_queue=16)
        single = PriorityScheduler(64, max_queue=16, n_classes=1)
        for p_len in (3, 7, 5, 12, 2):
            fifo.submit(Request(prompt=[1] * p_len, max_new_tokens=4))
            single.submit(Request(prompt=[1] * p_len, max_new_tokens=4))
        fifo_order = []
        single_order = []
        while True:
            f, s = fifo.pop(), single.pop()
            if f is None and s is None:
                break
            fifo_order.append(None if f is None else f.request_id)
            single_order.append(None if s is None else s.request_id)
        if fifo_order != single_order:
            problems.append(
                f"slo arm: single-class PriorityScheduler order "
                f"{single_order} != FIFO {fifo_order}"
            )

        slo_fields = {
            **eng_slo.stats("slo"),
            "slo_token_exact": slo_exact,
            "slo_chaos_token_exact": chaos_exact,
            "slo_host_fetches": slo_fetches,
            "slo_fetch_budget": slo_budget,
            "slo_single_class_fifo_identical": fifo_order == single_order,
        }

    receipt = make_receipt(
        "serve_selftest",
        {
            "n_requests": len(prompts),
            "n_slots": 2,
            "tokens_per_launch": 8,
            "n_chains": n_chains,
            "n_prefills": engine.n_prefills,
            "host_fetches": n_fetch,
            "generated_tokens": engine.generated_tokens,
            "token_exact_mismatches": mismatches,
            "backpressure_seen": backpressured,
            "prefix_requests": len(overlap_reqs),
            "prefix_token_exact": prefix_exact,
            "prefix_prefills_off": eng_off.n_prefills,
            "prefix_prefills_on": eng_on.n_prefills,
            **stats,
            "spec_requests": len(spec_reqs),
            "spec_token_exact": spec_exact,
            "spec_generated_tokens": eng_spec.generated_tokens,
            "spec_host_fetches": fetches_spec,
            **sstats,
            "adapter_requests_total": len(tenant_reqs),
            "adapter_token_exact": adapter_exact,
            "adapter_host_fetches": fetches_mix,
            **astats,
            **flight_fields,
            **pipeline_fields,
            **paged_fields,
            **router_fields,
            **fault_fields,
            **tp_fields,
            **sentry_fields,
            **slo_fields,
            "problems": problems,
            "ok": not problems,
        },
    )
    problems.extend(validate_receipt(receipt, kind="serve_selftest"))
    receipt["ok"] = not problems
    receipt["problems"] = problems
    if json_path:
        with open(json_path, "w") as f:
            json.dump(receipt, f, indent=2)
            f.write("\n")
    return receipt


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="python -m pytorch_distributed_training_tutorials_tpu.serve")
    parser.add_argument(
        "--selftest", action="store_true",
        help="run the end-to-end continuous-batching smoke test",
    )
    parser.add_argument(
        "--json", default=None, help="also write the receipt to this path"
    )
    parser.add_argument(
        "--spec-k", type=int, default=2,
        help="speculate-k for the speculative selftest arm (>= 1)",
    )
    parser.add_argument(
        "--adapters", type=int, default=3,
        help="bank rows for the multi-tenant selftest arm (>= 2; "
        "rows 1..N-1 become tenants, row 0 is the base model)",
    )
    parser.add_argument(
        "--chaos", action="store_true",
        help="also run the fault-injection arm: NaN-logit quarantine, "
        "deadline expiry, cancel, close/drain, and the training "
        "skip-step guard (ISSUE 9)",
    )
    parser.add_argument(
        "--flight", action="store_true",
        help="also run the flight-recorder arm: full lifecycle spans, "
        "histogram-vs-sort percentile parity, unchanged fetch budget "
        "(ISSUE 10)",
    )
    parser.add_argument(
        "--pipeline", action="store_true",
        help="also run the pipelined arm: depth-2 double-buffered "
        "chains + chunked prefill, token-identical to serial with the "
        "same fetch budget (ISSUE 11)",
    )
    parser.add_argument(
        "--router", action="store_true",
        help="also run the fleet arm: 3 real-engine replicas behind "
        "FleetRouter, fault-free parity vs the single engine, then a "
        "chaos-killed replica mid-stream with the exactly-once ledger, "
        "token-exact re-dispatch, and the summed per-replica fetch "
        "budget asserted (ISSUE 12)",
    )
    parser.add_argument(
        "--paged", action="store_true",
        help="also run the paged-KV arm: an oversubscribed mixed stream "
        "through a page-pool engine, token-identical to whole-slot with "
        "the same fetch budget, PoolExhausted shed at submit, and "
        "copy-free page sharing under the prefix cache (ISSUE 13); "
        "includes the ISSUE 17 legs — fused page-walk kernel "
        "token-exact at full precision, int4 page_bytes exactly half "
        "of int8's",
    )
    parser.add_argument(
        "--tp", type=int, default=0,
        help="also run the sharded-serving arm at this TP width: the "
        "base stream through a TensorParallel engine on a {'model': N} "
        "mesh, token-identical to replicated, same fetch budget, KV "
        "really sharded, and a clean decode-HLO collective audit "
        "(ISSUE 15)",
    )
    parser.add_argument(
        "--sentry", action="store_true",
        help="also run the contract-sentry arm: a sentry-instrumented "
        "engine over the base stream (zero steady recompiles, fetch "
        "accounting equal to an independent monkeypatch spy, zero "
        "re-uploads), then one injected violation per probe class — "
        "each must yield exactly one typed flight event and one "
        "auto-dump naming its trigger (ISSUE 19)",
    )
    parser.add_argument(
        "--slo", action="store_true",
        help="also run the SLO-tier arm: a priority_classes=2 engine "
        "preempting a low-class slot (KV swap to host) for a class-0 "
        "arrival, both streams token-exact to generate(), budget = "
        "chains + prefills + splices + counted swaps pinned by the spy "
        "AND the contract sentry, plus the chaos forced-preempt and "
        "single-class-equals-FIFO legs (ISSUE 20)",
    )
    args = parser.parse_args(argv)
    if not args.selftest:
        parser.print_help()
        return 2
    # ad-hoc CPU runs need the config update as well as the env var
    # (sitecustomize pre-imports jax._src — see CLAUDE.md)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        if "xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""
        ):
            # match the tier-1 forced 8-device mesh
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8"
            ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    receipt = selftest(args.json, spec_k=args.spec_k,
                       adapters=args.adapters, chaos=args.chaos,
                       flight=args.flight, pipeline=args.pipeline,
                       router=args.router, paged=args.paged,
                       tp=args.tp, sentry=args.sentry, slo=args.slo)
    print(json.dumps(receipt))
    return 0 if receipt["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
