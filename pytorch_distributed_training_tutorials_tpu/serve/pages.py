"""Host-side page-pool allocator for the paged KV cache (ISSUE 13).

This is the jax-free half of the paged-attention design (vLLM, SOSP '23)
rebuilt for XLA's fixed-shape discipline: the device holds ONE
``(pool_pages, page_size, ...)`` K/V pool per attention layer plus a
per-slot int32 page-table vector riding slot state as DATA
(models/transformer.py gathers pages by table entry with ``jnp.take``;
page ids are never Python control flow). THIS module is the other half:
a free-list allocator with per-page refcounts that admission, refill,
and the radix prefix index (serve/prefix.py) drive from the host.

Design rules (the engine's paged contracts lean on every one):

- **Allocation only at refill, never mid-decode.** The engine
  pre-allocates ``pages_needed(p_len + max_new_tokens)`` pages before a
  request enters a slot, so a decode chain can never fail an
  allocation. Transient exhaustion keeps the request QUEUED (the
  scheduler's ``fits`` predicate); only a request that could never fit
  the whole pool raises :class:`PoolExhausted` at submit —
  backpressure is synchronous, like ``QueueFull``, never a mid-decode
  failure. A disaggregated decode replica (ISSUE 18) lands handoff
  segments through this same refill-time path: ``accept`` is admission,
  the pages allocate when the handoff enters a slot, so a transferred
  prefill prices identically to a local one (``hbm_high_water_bytes``
  parity is pinned in tests/test_handoff.py).
- **Refcounts implement prefix sharing.** A prefix-cache hit RETAINS
  the donor segment's fully-shared pages (refcount + 1 per reader)
  instead of copying the segment; the first divergent write goes to a
  fresh copy-on-write page (the engine's splice does the one-page copy
  on device). A page returns to the free list only when its last
  holder releases it.
- **Lowest-id-first reuse** (a heap, not a LIFO stack) keeps the pool's
  occupied region dense, which makes the ``high_water`` counter an
  honest HBM high-water mark: ``high_water * page_bytes`` is the most
  pool memory that was ever live at once. On a tensor-parallel engine
  (ISSUE 15) ``page_bytes`` is priced PER SHARD — the pool leaves are
  head-sharded across the mesh, so a page costs each chip 1/tp of its
  global bytes (``slots.tree_nbytes_sharded``; the engine picks the
  pricing fn, this allocator never sees device arrays either way).

Host-only by contract: importing this module must not touch jax
(tests/test_prefix.py pins it in a subprocess alongside
prefix/scheduler/registry/router/chaos).
"""

from __future__ import annotations

import heapq
from typing import Dict, Iterable, List


class PoolExhausted(Exception):
    """Raised at ``ServeEngine.submit`` when a request needs more pages
    than the whole pool holds — it could NEVER be scheduled, so the
    caller gets synchronous backpressure (the ``QueueFull`` discipline).
    Transient pressure never raises: requests wait queued until enough
    pages free up."""


class PagePool:
    """Fixed pool of ``pool_pages`` KV pages of ``page_size`` tokens.

    Pure host bookkeeping — the device-side pool arrays live in the
    engine's slot state; this object only decides WHICH page ids a
    request owns and when they return to the free list.
    """

    def __init__(self, pool_pages: int, page_size: int):
        if pool_pages < 1:
            raise ValueError("pool_pages must be >= 1")
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        self.pool_pages = int(pool_pages)
        self.page_size = int(page_size)
        # lowest-first free list: heapq keeps reuse dense so high_water
        # is an honest HBM high-water mark
        self._free: List[int] = list(range(self.pool_pages))
        heapq.heapify(self._free)
        self._refs: List[int] = [0] * self.pool_pages
        self.n_allocs = 0
        self.n_frees = 0
        self.n_shares = 0
        self.n_sheds = 0
        self.high_water = 0

    # -- capacity ----------------------------------------------------------

    @property
    def available(self) -> int:
        """Pages currently on the free list."""
        return len(self._free)

    @property
    def in_use(self) -> int:
        """Pages with at least one live holder."""
        return self.pool_pages - len(self._free)

    def pages_needed(self, n_tokens: int) -> int:
        """Pages covering ``n_tokens`` tokens (ceiling division)."""
        if n_tokens < 0:
            raise ValueError("n_tokens must be >= 0")
        return -(-n_tokens // self.page_size)

    # -- allocation --------------------------------------------------------

    def alloc(self, n: int) -> List[int]:
        """Take ``n`` fresh pages (each at refcount 1), lowest ids first.

        Raises :class:`PoolExhausted` when fewer than ``n`` are free —
        the engine's admission predicate makes this unreachable in
        normal operation (it checks ``available`` on the same host
        thread before popping the request)."""
        if n < 0:
            raise ValueError("n must be >= 0")
        if n > len(self._free):
            raise PoolExhausted(
                f"need {n} pages, {len(self._free)} free "
                f"(pool_pages={self.pool_pages})"
            )
        out = [heapq.heappop(self._free) for _ in range(n)]
        for pid in out:
            self._refs[pid] = 1
        self.n_allocs += n
        self.high_water = max(self.high_water, self.in_use)
        return out

    def retain(self, pid: int) -> None:
        """Add a holder to a LIVE page (prefix sharing: a splice pins
        the donor segment's fully-shared pages instead of copying)."""
        if self._refs[pid] <= 0:
            raise ValueError(f"retain of free page {pid}")
        self._refs[pid] += 1
        self.n_shares += 1

    def release(self, pid: int) -> None:
        """Drop one holder; the page returns to the free list at zero."""
        if self._refs[pid] <= 0:
            raise ValueError(f"release of free page {pid}")
        self._refs[pid] -= 1
        if self._refs[pid] == 0:
            heapq.heappush(self._free, pid)
            self.n_frees += 1

    def release_all(self, pids: Iterable[int]) -> None:
        for pid in pids:
            self.release(pid)

    def refcount(self, pid: int) -> int:
        return self._refs[pid]

    # -- accounting --------------------------------------------------------

    def shed(self) -> None:
        """Count one admission-time :class:`PoolExhausted` rejection."""
        self.n_sheds += 1

    def stats(self) -> Dict[str, int]:
        return {
            "allocs": self.n_allocs,
            "frees": self.n_frees,
            "shares": self.n_shares,
            "sheds": self.n_sheds,
            "in_use": self.in_use,
            "high_water": self.high_water,
        }

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"PagePool(pages={self.pool_pages}, page_size={self.page_size}, "
            f"in_use={self.in_use}, high_water={self.high_water})"
        )
