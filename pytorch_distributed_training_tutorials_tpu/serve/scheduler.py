"""Host-side request scheduling for the continuous-batching engine.

Deliberately jax-free: admission control and queueing are pure Python so
they can be unit-tested (and reasoned about) without a backend, and so
importing the scheduler never risks touching XLA (the import-purity rule
this repo enforces machine-checked). The FIFO discipline is the Orca
(OSDI '22) baseline: requests join in arrival order, the engine drains
the queue into cache slots as they free up, and a bounded queue gives
callers backpressure instead of unbounded memory growth.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any


class QueueFull(Exception):
    """Raised by :meth:`FifoScheduler.submit` when the bounded queue is at
    capacity — the backpressure signal. Callers retry after draining
    (``ServeEngine.step``) or shed load; the engine never drops a request
    it has accepted."""


class QueueClosed(Exception):
    """Raised by :meth:`FifoScheduler.submit` after :meth:`~FifoScheduler.
    close`: the graceful-shutdown backpressure signal. Admission stops
    synchronously; requests already queued or decoding run to
    completion (``ServeEngine.drain``)."""


@dataclasses.dataclass
class Request:
    """One generation request.

    ``prompt`` is a 1-D sequence of int token ids (list/tuple/array);
    ``max_new_tokens`` counts generated tokens including the one sampled
    from the prefill logits. ``seed`` founds the request's private PRNG
    stream — sampled draws depend only on (seed, draw index), never on
    which other requests share the decode batch. ``eos_token`` stops the
    request early when sampled (the stop token is included in the
    output); ``None`` always runs to ``max_new_tokens``.

    ``adapter`` names the tenant's LoRA bank row (0 = base model).
    Validated at ``ServeEngine.submit`` against the engine's
    :class:`..adapters.bank.AdapterBank` — an unknown/unregistered id is
    a synchronous ``ValueError``, the same admission contract as the
    window check — then carried as DATA through prefill/splice/refill
    and the decode chain, so tenants with different adapters co-batch in
    one compiled program. Bank rows recycle, so submit also snapshots
    the row's tenant-generation (``adapter_gen``); if the tenant is
    evicted — or the row re-registered — while the request queues, the
    engine completes it with ``finish_reason == "adapter_evicted"``
    rather than decode under the wrong factors.

    ``deadline_s`` bounds submit-to-completion wall time: past it the
    engine completes the request ``finish_reason == "deadline"`` at the
    next chain/refill boundary (partial tokens kept — never a mid-chain
    interrupt). ``None`` falls back to the engine's
    ``default_deadline_s`` (itself ``None`` = no deadline).

    ``priority`` is the request's SLO class (ISSUE 20): 0 = highest,
    larger = lower tier. Validated at submit against the scheduler's
    class count — this FIFO scheduler admits only class 0 (one class),
    :class:`..serve.slo.PriorityScheduler` widens the range — the same
    synchronous admission contract as the deadline/window checks. Under
    a priority engine a lower-tier active request may be PREEMPTED (its
    KV swapped to host) for a higher-tier waiter and later resumed
    token-exact; priority never changes results, only ordering and
    preemption eligibility.
    """

    prompt: Any
    max_new_tokens: int
    seed: int = 0
    eos_token: int | None = None
    adapter: int = 0
    deadline_s: float | None = None
    priority: int = 0
    # engine-assigned bookkeeping (not caller inputs)
    request_id: int = -1
    submitted_s: float = 0.0
    adapter_gen: int = 0


@dataclasses.dataclass
class Handoff:
    """A finished prefill leaving a ``role="prefill"`` engine (ISSUE 18).

    The disaggregation transfer record: ``segment`` is the extracted
    batch-1 KV tree covering the prompt's whole pow2 bucket ``[0,
    bucket)`` (:func:`..serve.slots.extract_segment` — the prefix-splice
    machinery reused as a cache transplant), ``first`` the sampled
    first token and ``key`` the request's post-sample PRNG stream. All
    three stay DEVICE residents — unfetched futures; the prefill side
    never syncs on them, and the decode side's ``accept`` splice
    (:func:`..serve.slots.seed_cache` + ``write_slot``) reconstructs
    the monolithic post-prefill slot state bitwise before fetching only
    ``first`` (the one budgeted handoff fetch). This module stays
    jax-free: the device fields are opaque ``Any`` handles it never
    inspects.

    ``submitted_s`` carries the PREFILL side's admission stamp so the
    decode engine can restore it after its own scheduler re-stamps —
    end-to-end latency and TTFT span the original submit, not the
    transfer."""

    segment: Any
    first: Any
    key: Any
    p_len: int
    bucket: int
    aid: int = 0
    submitted_s: float = 0.0


@dataclasses.dataclass
class Completion:
    """A finished request: ``tokens`` are the generated ids (prompt
    excluded, stop token included when ``finish_reason == "eos"``);
    ``latency_s`` is submit-to-completion wall time and ``ttft_s``
    submit-to-first-token (the prefill/splice fetch) — the pair the
    serving receipt reports as p50/p95. ``"adapter_evicted"`` means the
    request's tenant was evicted (or its bank row re-registered) while
    it queued: zero tokens were generated — resubmit under a live id.

    Robustness outcomes (ISSUE 9): ``"deadline"`` — the request's
    deadline expired (tokens generated before expiry are kept);
    ``"cancelled"`` — the caller cancelled it host-side;
    ``"nonfinite"`` — the request drove logits to NaN/Inf and its slot
    was quarantined (tokens up to the poisoned step are kept);
    ``"error"`` — prefill raised and the request was isolated (zero
    tokens; the engine keeps serving).

    ``"handoff"`` (ISSUE 18) — a ``role="prefill"`` engine finished the
    prompt's prefill and parked the result for transfer (zero tokens
    HERE; collect the :class:`Handoff` via ``take_handoff`` and hand it
    to a decode engine's ``accept`` — the decode side's completion
    reports the generated tokens)."""

    request_id: int
    prompt: list[int]
    tokens: list[int]
    # "length" | "eos" | "adapter_evicted" | "deadline" | "cancelled"
    # | "nonfinite" | "error" | "handoff"
    finish_reason: str
    latency_s: float
    ttft_s: float = 0.0


class FifoScheduler:
    """Bounded FIFO request queue with admission control.

    ``window`` is the engine's cache window (``cfg.max_seq_len``): a
    request whose prompt + budget cannot fit is rejected at submit time
    with ``ValueError`` — admission is the ONE place length invariants
    are checked, so the compiled decode program never sees a request that
    could write outside its fixed-shape slot.
    """

    # SLO classes this scheduler admits: [0, n_classes). The FIFO
    # scheduler is the single-class baseline; PriorityScheduler
    # (serve/slo.py) widens it. Submit validates against this, so a
    # nonzero priority on a FIFO engine is a synchronous ValueError —
    # admission-validated like deadlines, never a silent ignore.
    n_classes = 1

    def __init__(self, window: int, max_queue: int = 64):
        if window < 1 or max_queue < 1:
            raise ValueError(f"window/max_queue must be >= 1, got "
                             f"{window}/{max_queue}")
        self.window = window
        self.max_queue = max_queue
        self._queue: collections.deque[Request] = collections.deque()
        self._next_id = 0
        self.closed = False

    def __len__(self) -> int:
        return len(self._queue)

    def close(self) -> None:
        """Stop admitting: every later :meth:`submit` raises
        :class:`QueueClosed`. Queued requests stay queued — the engine
        drains them (graceful shutdown leaves no accepted request
        behind). Idempotent."""
        self.closed = True

    def has(self, request_id: int) -> bool:
        """True while ``request_id`` is still queued (not yet popped
        into a slot). O(queue) host scan — cancellation-path only."""
        return any(r.request_id == request_id for r in self._queue)

    def submit(self, request: Request) -> int:
        """Validate + enqueue; returns the assigned request id. Raises
        :class:`QueueClosed` after :meth:`close` (shutdown),
        :class:`QueueFull` (backpressure) or ``ValueError`` (a request
        that can never be served at this window)."""
        if self.closed:
            raise QueueClosed(
                "scheduler is closed (draining); no new requests admitted"
            )
        p_len = len(request.prompt)
        if p_len < 1:
            raise ValueError("prompt must contain at least one token")
        if request.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if request.deadline_s is not None and request.deadline_s <= 0:
            raise ValueError("deadline_s must be > 0 (None = no deadline)")
        prio = int(getattr(request, "priority", 0))
        if not 0 <= prio < self.n_classes:
            raise ValueError(
                f"priority {prio} outside [0, {self.n_classes}); this "
                "scheduler admits only these SLO classes (use a "
                "PriorityScheduler engine for multi-class traffic)"
            )
        if p_len + request.max_new_tokens > self.window:
            raise ValueError(
                f"prompt ({p_len}) + max_new_tokens "
                f"({request.max_new_tokens}) exceeds the serving window "
                f"{self.window}"
            )
        if len(self._queue) >= self.max_queue:
            raise QueueFull(
                f"queue at capacity ({self.max_queue}); drain with "
                "step() before submitting more"
            )
        request.request_id = self._next_id
        request.submitted_s = time.perf_counter()
        self._next_id += 1
        self._queue.append(request)
        return request.request_id

    def pop(self, chunk: int = 0, pending_long: int = 0,
            fits=None) -> Request | None:
        """Next request in arrival order, or None when idle.

        Chunk-aware admission (ISSUE 11): with ``chunk`` set (the
        engine's ``prefill_chunk``) and a long prompt already mid
        chunked-prefill (``pending_long > 0``), only a request whose
        prompt fits a single chunk may pop — short requests slip AROUND
        the long one into free slots instead of queueing a second
        multi-step prefill behind it, and the long request keeps its
        arrival-order claim on the next free slot once the pending one
        lands. The defaults are the plain FIFO, byte-identical behavior
        for non-chunked engines.

        ``fits`` (ISSUE 13) is an optional host predicate over a
        :class:`Request` — the paged engine passes "enough free pages" —
        applied on top of the chunk rule: a request that doesn't fit
        stays QUEUED in arrival position (it will pop once pages free
        up; never a failure). ``fits=None`` is byte-identical to the
        predicate-free pop."""
        if not self._queue:
            return None
        if chunk and pending_long:
            for i, r in enumerate(self._queue):
                if len(r.prompt) <= chunk and (fits is None or fits(r)):
                    del self._queue[i]
                    return r
            return None
        if fits is not None:
            for i, r in enumerate(self._queue):
                if fits(r):
                    del self._queue[i]
                    return r
            return None
        return self._queue.popleft()
