"""Fleet resilience: a multi-replica router with replica health,
exactly-once re-dispatch, and hedged stragglers (ISSUE 12).

The engine layer (``serve/engine.py``) hardened a SINGLE replica — slot
quarantine, deadlines, chain-boundary cancellation. This module is the
layer above it: a pure-host, jax-free front door over N ``ServeEngine``
replicas that survives the failure mode dominating production serving —
a whole *replica* dying, stalling, or poisoning itself under live
traffic. Same mold as the scheduler/prefix/registry family: importable
without jax (tests/test_prefix.py pins it in a subprocess), engines are
duck-typed (the unit tests drive fakes), every decision is deterministic
given the injected ``clock``.

Four mechanisms, each with a receipt-grade invariant:

- **Health states** (``healthy -> suspect -> dead -> draining``), driven
  by observed symptoms only: chain-boundary heartbeat age (a replica
  that is neither idle nor advancing its chain/prefill counters is
  stalled), consecutive fault-stat deltas (a replica quarantining slot
  after slot is poisoning itself), and ``QueueFull`` streaks (overload).
  ``dead`` is a circuit breaker: the replica is no longer stepped and
  receives no traffic; after ``probe_after_s`` the circuit goes
  half-open — the NEXT submission routes to it as a probe, and a clean
  completion closes the circuit (``healthy``) while any fault re-opens
  it with a fresh timer.
- **Exactly-once re-dispatch**: every accepted request gets a router
  (global) id and a :class:`DispatchLedger` entry recording each
  dispatch (replica, local id, kind) and the ONE delivered completion.
  When a replica dies, its queued-but-unstarted requests re-route to
  healthy replicas (same ``Request`` template, same seed — greedy
  streams are byte-identical to a fault-free run) while in-flight ones
  complete ``finish_reason="replica_dead"`` (their partial tokens died
  with the replica). :meth:`DispatchLedger.verify` proves no accepted
  request is ever lost or completed twice — the selftest asserts it
  after a chaos-killed fleet run.
- **Hedged stragglers**: a request whose ONLY live dispatch sits on a
  ``suspect`` replica for more than ``hedge_after_s`` is duplicated onto
  a healthy replica; the first completion wins and the loser is
  ``cancel()``ed through the engine's existing chain-boundary path.
  Per-seed determinism (CLAUDE.md serving invariants) makes the two
  token streams identical, so hedging is invisible in outputs — only
  the ledger and the ``hedge`` flight event show it happened.
- **Prefix-affinity routing**: requests hash (:func:`affinity_hash`,
  FNV-1a over the adapter id + the first ``affinity_depth`` prompt
  tokens — NEVER Python ``hash()``, which is salted per process) onto a
  replica ring, so each replica's radix prefix cache sees a coherent key
  population; the hash is tenant-aware (adapter id is part of the key)
  and admission walks the ring — an unhealthy or full affine replica
  fails over to the next (``QueueFull`` spillover bumps the overload
  streak), and a replica that cannot serve the request's adapter is
  skipped. Only when NO replica admits does the caller get the
  synchronous backpressure exception, preserving the engine's
  admission-at-submit contract fleet-wide.

Fleet observability: each replica keeps its own
:class:`..obs.flight.FlightRecorder` (pass a shared ``t0`` so their
relative timestamps are comparable) and the router stamps its OWN
recorder with ``replica_health`` / ``redispatch`` / ``hedge`` events;
:meth:`FleetRouter.fleet_snapshot` merges all of them into one
``graft-flightlog/v1`` dump (events tagged ``replica=i``, histograms
merged bucket-wise — they are mergeable by design) that
``scripts/flight_view.py`` renders as an interleaved timeline.
:meth:`FleetRouter.stats` merges the replicas' ``stats(parts)`` dicts
into one fleet receipt (counters sum, config fields that agree pass
through, flight percentiles are recomputed from the MERGED histograms —
summing a p95 would be nonsense).

Router-off parity: an N=1 router with hedging off is pure plumbing —
global ids coincide with the single engine's local ids, completions,
state trees, and compiled-program counts are byte-identical to driving
the engine directly (tests/test_serve.py pins it).

Role-aware dispatch (ISSUE 18): a fleet mixing ``role="prefill"`` and
``role="decode"`` engines disaggregates the two phases. Submissions
ride the SAME affinity ring restricted to prefill replicas (each
prefill replica's prefix cache still sees a coherent key population);
a prefill replica finishing a request emits ``finish_reason ==
"handoff"``, which the router ABSORBS (never delivers — the ledger
entry stays open, holding the fleet non-idle), collects the
:class:`..serve.scheduler.Handoff` via ``take_handoff``, and moves it
to the least-``load`` HEALTHY decode replica via ``engine.accept`` —
recorded in the ledger as a ``"handoff"`` dispatch, so exactly-once
holds across the transfer: a decode replica dying with the request
still QUEUED re-dispatches the pristine template through the prefill
ring (per-seed determinism makes the re-prefill token-identical), one
dying mid-decode synthesizes ``replica_dead``, and a duplicate handoff
from a hedged prefill is absorbed and dropped. Dead decode replicas
half-open by receiving the next pending handoff as their probe (the
submission-side probe path cannot reach them — ``submit`` on a decode
engine raises). Roles must be all-or-nothing with at least one of
each; monolithic fleets take the pre-ISSUE-18 code paths untouched.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .scheduler import Completion, QueueClosed, QueueFull, Request

# Replica health vocabulary. "dead" doubles as the circuit-breaker open
# state; a dead replica being probed stays "dead" until the probe's
# clean completion closes the circuit.
HEALTHY = "healthy"
SUSPECT = "suspect"
DEAD = "dead"
DRAINING = "draining"
HEALTH_STATES = (HEALTHY, SUSPECT, DEAD, DRAINING)

# The finish_reason the router synthesizes for requests that were
# in-flight on a replica when it died: their partial tokens died with
# the replica's device state, so re-running them would break the
# "tokens earned are kept" accounting — the caller resubmits.
REPLICA_DEAD = "replica_dead"

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK64 = 0xFFFFFFFFFFFFFFFF


def affinity_hash(prompt, adapter: int = 0, depth: int = 16) -> int:
    """Deterministic 64-bit FNV-1a over the adapter id + the first
    ``depth`` prompt tokens. Python's builtin ``hash()`` is salted per
    process (PYTHONHASHSEED), which would scatter a restarted router's
    affinity and cold every replica's prefix cache — this hash is stable
    across processes and platforms. The adapter id leads the stream so
    two tenants sharing a prompt family land on (usually) different
    replicas, matching the tenant-scoped prefix-cache keys."""
    h = _FNV_OFFSET
    for tok in (int(adapter), *(int(t) for t in prompt[:depth])):
        h ^= tok & _MASK64
        h = (h * _FNV_PRIME) & _MASK64
    # Avalanche finalizer (the Murmur3 fmix64 constants): raw FNV-1a's
    # low bits are weak — the multiply preserves bit 0, so ``h % 2``
    # would be nothing but the XOR of token parities and a two-replica
    # ring would split traffic by prompt parity, not prompt identity.
    h ^= h >> 33
    h = (h * 0xFF51AFD7ED558CCD) & _MASK64
    h ^= h >> 33
    h = (h * 0xC4CEB9FE1A85EC53) & _MASK64
    h ^= h >> 33
    return h


@dataclasses.dataclass
class LedgerEntry:
    """One accepted request's dispatch history. ``dispatches`` holds
    ``(replica, local_rid, kind, t)`` rows — kind is "dispatch" |
    "redispatch" | "hedge" | "probe" | "handoff" (a prefill-role
    replica's finished segment moved onto a decode replica, ISSUE 18);
    ``delivered`` is the finish_reason
    of the ONE completion handed to the caller (None while open);
    ``absorbed`` records completions the router swallowed (hedge losers,
    drain-path cancellations) as ``(replica, local_rid, reason)``."""

    gid: int
    dispatches: List[Tuple[int, int, str, float]] = dataclasses.field(
        default_factory=list
    )
    delivered: Optional[str] = None
    delivered_by: int = -1
    absorbed: List[Tuple[int, int, str]] = dataclasses.field(
        default_factory=list
    )


class DispatchLedger:
    """The exactly-once proof object. Every accepted request opens an
    entry; every engine submission, delivered completion, and swallowed
    completion is recorded; :meth:`verify` re-derives the invariant from
    the records alone — no accepted request lost, none completed twice,
    no completion from a dispatch the router never made."""

    def __init__(self) -> None:
        self.entries: Dict[int, LedgerEntry] = {}
        self.n_redispatched = 0
        self.n_hedged = 0
        self.n_absorbed = 0

    def accepted(self, gid: int) -> None:
        if gid in self.entries:
            raise ValueError(f"gid {gid} already in ledger")
        self.entries[gid] = LedgerEntry(gid=gid)

    def dispatched(self, gid: int, replica: int, local_rid: int,
                   kind: str, t: float) -> None:
        self.entries[gid].dispatches.append((replica, local_rid, kind, t))
        if kind == "redispatch":
            self.n_redispatched += 1
        elif kind == "hedge":
            self.n_hedged += 1

    def delivered(self, gid: int, replica: int, reason: str) -> None:
        entry = self.entries[gid]
        if entry.delivered is not None:
            raise ValueError(
                f"gid {gid} delivered twice ({entry.delivered!r} then "
                f"{reason!r}) — exactly-once violated at record time"
            )
        entry.delivered = reason
        entry.delivered_by = replica

    def absorbed(self, gid: int, replica: int, local_rid: int,
                 reason: str) -> None:
        self.entries[gid].absorbed.append((replica, local_rid, reason))
        self.n_absorbed += 1

    def open_ids(self) -> List[int]:
        return [g for g, e in self.entries.items() if e.delivered is None]

    def verify(self, final: bool = True) -> List[str]:
        """Return the list of exactly-once violations (empty = proof
        holds). With ``final=True`` (end of run) an undelivered entry is
        itself a violation — an accepted request was LOST."""
        problems: List[str] = []
        for gid, e in sorted(self.entries.items()):
            if not e.dispatches:
                problems.append(f"gid {gid}: accepted but never dispatched")
            if final and e.delivered is None:
                problems.append(f"gid {gid}: accepted but never completed")
            pairs = {(r, l) for r, l, _, _ in e.dispatches}
            for r, l, reason in e.absorbed:
                if (r, l) not in pairs:
                    problems.append(
                        f"gid {gid}: absorbed completion from undisp"
                        f"atched (replica={r}, local={l}, {reason!r})"
                    )
            if e.delivered is not None and e.delivered_by >= 0:
                if e.delivered != REPLICA_DEAD and not any(
                    r == e.delivered_by for r, _, _, _ in e.dispatches
                ):
                    problems.append(
                        f"gid {gid}: delivered by replica "
                        f"{e.delivered_by} which never held a dispatch"
                    )
        return problems


class _Replica:
    """Per-replica router-side bookkeeping (the engine itself holds no
    fleet state). ``local_gid`` maps the engine's local request ids to
    router gids — a dispatch is LIVE while its pair is present here."""

    __slots__ = (
        "index", "engine", "role", "state", "heartbeat", "last_sig",
        "last_faults", "fault_streak", "queue_full_streak",
        "dead_since", "dead_reason", "probing", "probe_gid",
        "stall_skips", "local_gid",
    )

    def __init__(self, index: int, engine: Any):
        self.index = index
        self.engine = engine
        # disaggregation role (ISSUE 18): None = monolithic,
        # "prefill" / "decode" = the role-specialized halves
        self.role = getattr(engine, "role", None)
        self.state = HEALTHY
        self.heartbeat: Optional[float] = None
        self.last_sig: Optional[tuple] = None
        self.last_faults = 0
        self.fault_streak = 0
        self.queue_full_streak = 0
        self.dead_since: Optional[float] = None
        self.dead_reason = ""
        self.probing = False
        self.probe_gid: Optional[int] = None
        self.stall_skips = 0
        self.local_gid: Dict[int, int] = {}

    def progress_signature(self) -> tuple:
        """Anything that moves when the replica does real work — chains,
        prefills, splices, chunks, tokens. Observed at the chain
        boundary (after ``step()``), so an unchanged signature on a
        non-idle replica means a stalled launch, not a quiet one."""
        e = self.engine
        return (
            getattr(e, "n_chains", 0), getattr(e, "n_prefills", 0),
            getattr(e, "n_splices", 0), getattr(e, "n_chunks", 0),
            getattr(e, "generated_tokens", 0),
        )

    def fault_total(self) -> int:
        """Self-inflicted faults only: nonfinite quarantines + prefill
        errors. Deadline expiries and cancellations are the CALLER's
        outcomes, not replica symptoms — counting them would let one
        impatient client kill a healthy replica."""
        fn = getattr(self.engine, "fault_stats", None)
        if fn is None:
            return 0
        fs = fn()
        return int(fs.get("nonfinite_quarantined", 0)) + int(
            fs.get("prefill_errors", 0)
        )


def _is_queued(engine: Any, local_rid: int) -> bool:
    """Queued-but-unstarted test, duck-typed: real engines expose
    ``scheduler.has``; the unit tests' fakes expose ``has_queued``."""
    sched = getattr(engine, "scheduler", None)
    if sched is not None and hasattr(sched, "has"):
        return bool(sched.has(local_rid))
    return bool(engine.has_queued(local_rid))


class FleetRouter:
    """The fleet front door. Pure host, jax-free; engines are duck-typed
    against the ``ServeEngine`` surface (``submit`` / ``step`` /
    ``cancel`` / ``idle`` / counters / ``fault_stats`` / ``stats``).

    Parameters
    ----------
    engines: the N replicas. Replica index = position in this list.
    affinity_depth: prompt-prefix tokens feeding :func:`affinity_hash`.
    hedge_after_s: duplicate a request stuck on a SUSPECT replica after
        this many seconds (None = hedging off, the default). A dict maps
        SLO class -> threshold (ISSUE 20): interactive class 0 hedges
        aggressively while batch classes wait longer (a class missing
        from the map never hedges) — the per-request class comes from
        ``Request.priority``.
    class_deadline_s: per-SLO-class default deadline (ISSUE 20): a dict
        mapping ``Request.priority`` -> seconds, stamped onto a
        submission whose own ``deadline_s`` is None (an explicit
        per-request deadline always wins; classes missing from the map
        fall through to the engine's ``default_deadline_s``). Stamped
        BEFORE the re-dispatch template is frozen, so a request moved
        off a dead replica keeps its class deadline.
    suspect_after_s / dead_after_s: heartbeat ages (no observable
        progress while non-idle) that demote healthy -> suspect ->
        dead.
    fault_streak: consecutive faulty observations before a replica goes
        suspect (twice that: dead).
    queue_full_streak: consecutive ``QueueFull`` bounces before the
        replica is marked suspect (overload, not death — it recovers on
        its next observed progress).
    probe_after_s: circuit-breaker half-open delay — how long a dead
        replica rests before the next submission probes it.
    chaos: a :class:`..utils.chaos.FleetChaosConfig` for deterministic
        replica-level fault injection (kill at a chain count, stall for
        N scheduling rounds).
    flight: the ROUTER's own :class:`..obs.flight.FlightRecorder` for
        ``replica_health`` / ``redispatch`` / ``hedge`` / ``stall``
        events; replica engines carry their own recorders.
    clock: injectable monotonic clock (tests pin health/probe timing
        with a fake; defaults to ``time.perf_counter``).
    """

    def __init__(self, engines: List[Any], *,
                 affinity_depth: int = 16,
                 hedge_after_s: Any = None,
                 class_deadline_s: Optional[Dict[int, float]] = None,
                 suspect_after_s: float = 1.0,
                 dead_after_s: float = 5.0,
                 fault_streak: int = 3,
                 queue_full_streak: int = 3,
                 probe_after_s: float = 1.0,
                 chaos: Any = None,
                 flight: Any = None,
                 clock: Optional[Callable[[], float]] = None):
        if not engines:
            raise ValueError("FleetRouter needs at least one engine")
        self._replicas = [_Replica(i, e) for i, e in enumerate(engines)]
        roles = [r.role for r in self._replicas]
        self._disagg = any(r is not None for r in roles)
        if self._disagg:
            # roles are all-or-nothing: a monolithic replica in a
            # disaggregated fleet would race the handoff path for the
            # same requests, and a fleet missing either role can never
            # complete one
            if any(r is None for r in roles):
                raise ValueError(
                    "mixed fleet: every engine must carry a role when "
                    f"any does (roles={roles})"
                )
            if "prefill" not in roles or "decode" not in roles:
                raise ValueError(
                    "disaggregated fleet needs at least one prefill "
                    f"AND one decode replica (roles={roles})"
                )
        self._affinity_depth = int(affinity_depth)
        if isinstance(hedge_after_s, dict):
            self._hedge_after_s = {
                int(k): float(v) for k, v in hedge_after_s.items()
            }
        else:
            self._hedge_after_s = hedge_after_s
        self._class_deadline_s = (
            {int(k): float(v) for k, v in class_deadline_s.items()}
            if class_deadline_s else None
        )
        self._suspect_after_s = float(suspect_after_s)
        self._dead_after_s = float(dead_after_s)
        self._fault_streak_limit = int(fault_streak)
        self._queue_full_limit = int(queue_full_streak)
        self._probe_after_s = float(probe_after_s)
        self._chaos = chaos
        self._flight = flight
        self._clock = clock if clock is not None else time.perf_counter
        self.ledger = DispatchLedger()
        self._next_gid = 0
        self._requests: Dict[int, Request] = {}
        # (replica, local_rid) cancellations the ROUTER issued (hedge
        # losers, drain moves): their "cancelled" completions are
        # absorbed, never delivered.
        self._router_cancelled: set = set()
        self._closed = False
        self.n_spillovers = 0
        self.n_probes = 0
        self.n_dead_completions = 0
        self.n_health_transitions = 0
        # disaggregation state (ISSUE 18): handoffs collected from
        # prefill replicas awaiting a decode replica, gids whose
        # handoff was already staged/placed (a hedged prefill's
        # duplicate emit is absorbed, never staged twice), and gids
        # cancelled while their handoff waits (delivered "cancelled"
        # at the next move round — the chain-boundary contract).
        self._pending_handoffs: List[Tuple[int, Any]] = []
        self._handoff_done: set = set()
        self._cancelled_gids: set = set()
        self.n_handoffs_moved = 0

    # -- introspection -----------------------------------------------------

    @property
    def n_replicas(self) -> int:
        return len(self._replicas)

    def replica_states(self) -> List[str]:
        return [r.state for r in self._replicas]

    @property
    def idle(self) -> bool:
        """Nothing left that can change caller-visible state: every
        accepted request has its one delivered completion and no live
        replica still works on an UNdelivered one. A cancelled hedge
        loser grinding on a stalled replica does not hold the fleet
        non-idle — its eventual completion is absorbed, not delivered
        (dead replicas are resolved by the step loop, so their entries
        close without the engine going idle)."""
        if self.ledger.open_ids():
            return False
        return all(
            rep.state == DEAD or rep.engine.idle or all(
                self.ledger.entries[g].delivered is not None
                for g in rep.local_gid.values()
            )
            for rep in self._replicas
        )

    # -- admission ---------------------------------------------------------

    def submit(self, request: Request) -> int:
        """Place one request on the fleet; returns its GLOBAL id.
        Routing is prefix-affine with failover (see module docstring);
        the request object passed in is never mutated — a pristine
        template is kept for re-dispatch/hedging and a fresh clone goes
        to each engine (engines stamp ``request_id``/``submitted_s`` on
        what they are given). Raises ``QueueFull`` / ``QueueClosed`` /
        ``ValueError`` only when NO replica admits — the engine's
        synchronous-admission contract, fleet-wide."""
        if self._closed:
            raise QueueClosed("fleet router is closed")
        template = dataclasses.replace(request)
        if (self._class_deadline_s is not None
                and template.deadline_s is None):
            # class-indexed deadline policy (ISSUE 20): stamped on the
            # TEMPLATE, so every dispatch clone — including re-dispatch
            # off a dead replica — carries the same class deadline; an
            # explicit per-request deadline_s always wins
            template.deadline_s = self._class_deadline_s.get(
                int(getattr(template, "priority", 0))
            )
        now = self._clock()
        probe = self._probe_candidate(now, role="prefill")
        order = ([probe] if probe is not None else []) + self._route_order(
            template
        )
        last_exc: Optional[Exception] = None
        for rep in order:
            try:
                local = rep.engine.submit(dataclasses.replace(template))
            except QueueFull as e:
                rep.queue_full_streak += 1
                self.n_spillovers += 1
                if (rep.queue_full_streak >= self._queue_full_limit
                        and rep.state == HEALTHY):
                    self._transition(rep, SUSPECT, "queue_full_streak", now)
                last_exc = e
                continue
            except (QueueClosed, ValueError) as e:
                last_exc = e
                continue
            rep.queue_full_streak = 0
            gid = self._next_gid
            self._next_gid += 1
            self._requests[gid] = template
            rep.local_gid[local] = gid
            self.ledger.accepted(gid)
            kind = "probe" if rep is probe else "dispatch"
            self.ledger.dispatched(gid, rep.index, local, kind, now)
            if rep is probe:
                rep.probing = True
                rep.probe_gid = gid
                self.n_probes += 1
                self._record("replica_health", replica=rep.index,
                             frm=DEAD, to="probing", reason="half_open")
            return gid
        if last_exc is not None:
            raise last_exc
        raise QueueFull("no routable replica")

    def _route_order(self, request: Request) -> List[_Replica]:
        """The affinity ring from the request's hash: healthy replicas
        in ring order, then suspect ones (still serving, just avoided).
        Dead and draining replicas take no new traffic. Disaggregated
        fleets restrict the ring to PREFILL replicas (ISSUE 18):
        submissions — and re-dispatches after a decode death, which
        re-run the prefill — always enter through the prefill side;
        decode replicas receive work only via :meth:`_move_handoffs`."""
        h = affinity_hash(
            request.prompt, adapter=int(getattr(request, "adapter", 0)),
            depth=self._affinity_depth,
        )
        n = len(self._replicas)
        ring = [self._replicas[(h + k) % n] for k in range(n)]
        if self._disagg:
            ring = [r for r in ring if r.role == "prefill"]
        return (
            [r for r in ring if r.state == HEALTHY]
            + [r for r in ring if r.state == SUSPECT]
        )

    def _probe_candidate(self, now: float,
                         role: Optional[str] = None) -> Optional[_Replica]:
        """First dead replica (of ``role``, when disaggregated) whose
        circuit-breaker rest expired and has no probe outstanding — the
        half-open state. The next submission (prefill/monolithic) or
        pending handoff (decode) becomes its probe; exactly-once
        machinery makes the gamble safe (a failed probe's request is
        re-dispatched like any other)."""
        for rep in self._replicas:
            if self._disagg and rep.role != role:
                continue
            if (rep.state == DEAD and not rep.probing
                    and rep.dead_since is not None
                    and now - rep.dead_since >= self._probe_after_s):
                return rep
        return None

    # -- the scheduling round ---------------------------------------------

    def step(self) -> List[Completion]:
        """One fleet round: step every live replica, observe symptoms,
        apply health transitions, resolve dead replicas' outstanding
        work (re-dispatch queued, synthesize ``replica_dead`` for
        in-flight), then hedge stragglers. Returns completions with
        GLOBAL ids, exactly one per accepted request ever."""
        out: List[Completion] = []
        for rep in self._replicas:
            now = self._clock()
            if self._chaos_killed(rep):
                # a chaos kill is PERMANENT: never step the engine (it
                # is actually fine — death is simulated at the router
                # boundary), and a half-open probe against it fails,
                # re-opening the circuit with a fresh timer.
                if rep.state != DEAD:
                    self._mark_dead(rep, "chaos_kill", now)
                elif rep.probing:
                    rep.probing = False
                    rep.probe_gid = None
                    rep.dead_since = now
                    self._record("replica_health", replica=rep.index,
                                 frm="probing", to=DEAD,
                                 reason="probe_failed:chaos_kill")
                continue
            if rep.state == DEAD and not rep.probing:
                continue
            if self._chaos_stalled(rep):
                rep.stall_skips += 1
                self._record("stall", replica=rep.index,
                             skipped_round=rep.stall_skips)
                self._observe(rep, now, stalled=True)
                continue
            try:
                comps = rep.engine.step()
            except Exception as e:  # engine blew up: circuit opens
                self._mark_dead(
                    rep, f"step_raised:{type(e).__name__}", now
                )
                continue
            out.extend(self._collect(rep, comps, self._clock()))
            self._observe(rep, self._clock())
        now = self._clock()
        out.extend(self._resolve_dead(now))
        if self._disagg:
            out.extend(self._move_handoffs(now))
        self._maybe_hedge(now)
        return out

    def run_until_idle(self, max_steps: int = 10_000) -> List[Completion]:
        out: List[Completion] = []
        for _ in range(max_steps):
            if self.idle and self._engines_drained():
                return out
            out.extend(self.step())
        raise RuntimeError(f"fleet not idle after {max_steps} steps")

    def _engines_drained(self) -> bool:
        """Caller-visible idleness is not the whole story: a pipelined
        engine can hold a dispatched-but-uncollected trailing bubble
        chain (counted in ``n_chains`` at dispatch) after its last
        delivery. Keep stepping until every HEALTHY replica's engine is
        itself idle, so the fleet fetch budget stays exactly the SUM of
        per-replica budgets and no launch is left in flight. Only
        healthy replicas are waited on: a suspect/dead/frozen replica
        may never drain (the hedged-straggler case — its leftover work
        is a cancelled loser whose eventual completion is absorbed),
        and blocking on it would hang the loop; chaos-killed/-stalled
        replicas are skipped by the step loop entirely."""
        return all(
            rep.state != HEALTHY
            or self._chaos_killed(rep)
            or self._chaos_stalled(rep)
            or bool(getattr(rep.engine, "idle", True))
            for rep in self._replicas
        )

    def cancel(self, gid: int) -> bool:
        """Caller-side cancellation by GLOBAL id: forwarded to every
        live dispatch (the first resulting "cancelled" completion is
        delivered, any other is deduplicated by the ledger)."""
        entry = self.ledger.entries.get(gid)
        if entry is None or entry.delivered is not None:
            return False
        if any(g == gid for g, _ in self._pending_handoffs):
            # cancelled between prefill and decode (ISSUE 18): no
            # engine holds it — the next _move_handoffs round delivers
            # "cancelled" (that round IS this request's chain boundary)
            self._cancelled_gids.add(gid)
            return True
        any_known = False
        for rep_i, local, _, _ in entry.dispatches:
            rep = self._replicas[rep_i]
            if local in rep.local_gid:
                try:
                    any_known = bool(rep.engine.cancel(local)) or any_known
                except Exception:
                    pass
        return any_known

    def close(self) -> None:
        """Fleet-wide admission stop (synchronous ``QueueClosed``
        backpressure on later submits); accepted work is unaffected."""
        self._closed = True
        for rep in self._replicas:
            # decode replicas must keep ADMITTING during a drain: their
            # intake is accepted work's handoffs, not new requests —
            # the router's own closed flag is the fleet admission stop
            if rep.state != DEAD and rep.role != "decode":
                try:
                    rep.engine.close()
                except Exception:
                    pass

    def drain(self, max_steps: int = 10_000) -> List[Completion]:
        """Graceful fleet shutdown: close, then run every accepted
        request to its one completion."""
        self.close()
        return self.run_until_idle(max_steps)

    # -- rolling drain -----------------------------------------------------

    def drain_replica(self, index: int) -> int:
        """Put one replica into rolling drain: no new traffic, its
        QUEUED requests move to healthy replicas in submit order (the
        local cancellation's completion is absorbed — the move is
        invisible to callers), in-flight requests finish normally.
        Returns how many requests moved. Pair with
        :meth:`undrain_replica` for a rolling restart."""
        rep = self._replicas[index]
        if rep.state == DEAD:
            raise ValueError(f"replica {index} is dead, not drainable")
        if rep.state != DRAINING:
            self._transition(rep, DRAINING, "drain_replica", self._clock())
        moved = 0
        # dict preserves insertion order == local submit order
        for local, gid in list(rep.local_gid.items()):
            if not _is_queued(rep.engine, local):
                continue
            target = self._place(
                self._requests[gid], gid, kind="redispatch",
                exclude={rep.index},
            )
            if target is None:
                continue  # fleet saturated: it finishes on the drainer
            rep.engine.cancel(local)
            self._router_cancelled.add((rep.index, local))
            self._record("redispatch", gid=gid, frm=rep.index,
                         to=target.index, reason="drain")
            moved += 1
        return moved

    def undrain_replica(self, index: int) -> None:
        """Return a drained replica to service (rolling restart done)."""
        rep = self._replicas[index]
        if rep.state != DRAINING:
            raise ValueError(
                f"replica {index} is {rep.state!r}, not draining"
            )
        rep.fault_streak = 0
        rep.queue_full_streak = 0
        rep.heartbeat = None
        rep.last_sig = None
        self._transition(rep, HEALTHY, "undrain_replica", self._clock())

    # -- completion collection --------------------------------------------

    def _collect(self, rep: _Replica, comps: List[Completion],
                 now: float) -> List[Completion]:
        delivered: List[Completion] = []
        for c in comps:
            gid = rep.local_gid.pop(c.request_id, None)
            if gid is None:
                continue  # not router-placed (or already resolved)
            if (rep.index, c.request_id) in self._router_cancelled:
                self._router_cancelled.discard((rep.index, c.request_id))
                self.ledger.absorbed(
                    gid, rep.index, c.request_id, c.finish_reason
                )
                continue
            if c.finish_reason == "handoff":
                # a prefill replica finished its half (ISSUE 18): the
                # completion is ABSORBED — the ledger entry stays open
                # (holding the fleet non-idle) until the decode side
                # delivers. The segment moves at this round's
                # _move_handoffs; a duplicate emit from a hedged
                # prefill is collected (the emitter's map must drain)
                # but dropped.
                self.ledger.absorbed(
                    gid, rep.index, c.request_id, "handoff"
                )
                handoff = rep.engine.take_handoff(c.request_id)
                if rep.probing and gid == rep.probe_gid:
                    self._resolve_probe(rep, "handoff", now)
                if (gid not in self._handoff_done
                        and self.ledger.entries[gid].delivered is None):
                    self._handoff_done.add(gid)
                    self._pending_handoffs.append((gid, handoff))
                continue
            entry = self.ledger.entries[gid]
            if entry.delivered is not None:
                # hedge race: the other replica already won
                self.ledger.absorbed(
                    gid, rep.index, c.request_id, c.finish_reason
                )
                continue
            # first completion wins; cancel any other live dispatch
            for rep_i, local, _, _ in entry.dispatches:
                if rep_i == rep.index and local == c.request_id:
                    continue
                loser = self._replicas[rep_i]
                if local in loser.local_gid:
                    try:
                        loser.engine.cancel(local)
                    except Exception:
                        pass
                    self._router_cancelled.add((rep_i, local))
            self.ledger.delivered(gid, rep.index, c.finish_reason)
            if rep.probing and gid == rep.probe_gid:
                self._resolve_probe(rep, c.finish_reason, now)
            if c.request_id == gid:
                delivered.append(c)  # N=1 parity: identical object
            else:
                delivered.append(dataclasses.replace(c, request_id=gid))
        return delivered

    def _resolve_probe(self, rep: _Replica, reason: str,
                       now: float) -> None:
        rep.probing = False
        rep.probe_gid = None
        # "handoff" is the prefill-role success outcome (ISSUE 18):
        # monolithic/decode replicas never emit it
        if reason in ("length", "eos", "handoff"):
            rep.fault_streak = 0
            rep.queue_full_streak = 0
            rep.heartbeat = now
            rep.last_faults = rep.fault_total()
            self._transition(rep, HEALTHY, "probe_ok", now)
        else:
            rep.dead_since = now  # circuit re-opens, timer restarts
            self._record("replica_health", replica=rep.index,
                         frm="probing", to=DEAD,
                         reason=f"probe_failed:{reason}")

    # -- health observation ------------------------------------------------

    def _observe(self, rep: _Replica, now: float,
                 stalled: bool = False) -> None:
        sig = rep.progress_signature()
        idle = bool(getattr(rep.engine, "idle", False))
        progressed = (not stalled) and (
            idle or rep.last_sig is None or sig != rep.last_sig
        )
        rep.last_sig = sig
        faults = rep.fault_total()
        if faults > rep.last_faults:
            rep.fault_streak += 1
        elif progressed:
            rep.fault_streak = 0
        rep.last_faults = faults
        if rep.heartbeat is None:
            rep.heartbeat = now
        if progressed:
            rep.heartbeat = now
            if rep.state == SUSPECT and rep.fault_streak == 0:
                self._transition(rep, HEALTHY, "progress", now)
        if rep.state not in (HEALTHY, SUSPECT):
            return
        if rep.fault_streak >= 2 * self._fault_streak_limit:
            self._mark_dead(rep, "fault_streak", now)
            return
        if (rep.fault_streak >= self._fault_streak_limit
                and rep.state == HEALTHY):
            self._transition(rep, SUSPECT, "fault_streak", now)
        age = now - rep.heartbeat
        if age > self._dead_after_s:
            self._mark_dead(rep, "heartbeat", now)
        elif age > self._suspect_after_s and rep.state == HEALTHY:
            self._transition(rep, SUSPECT, "heartbeat", now)

    def _transition(self, rep: _Replica, to: str, reason: str,
                    now: float) -> None:
        frm = rep.state
        if frm == to:
            return
        rep.state = to
        self.n_health_transitions += 1
        self._record("replica_health", replica=rep.index, frm=frm,
                     to=to, reason=reason)

    def _mark_dead(self, rep: _Replica, reason: str, now: float) -> None:
        rep.dead_since = now
        rep.dead_reason = reason
        rep.probing = False
        rep.probe_gid = None
        self._transition(rep, DEAD, reason, now)

    # -- dead-replica resolution ------------------------------------------

    def _resolve_dead(self, now: float) -> List[Completion]:
        """Exactly-once re-dispatch: move a dead replica's queued
        requests to live replicas (same template, same seed — token
        streams identical) and synthesize ``replica_dead`` completions
        for the in-flight ones. Every local id is also cancelled on the
        dead engine, so a later probe revival cannot replay work the
        router already resolved."""
        out: List[Completion] = []
        for rep in self._replicas:
            # a probing replica is half-open, not dead-dead: its probe
            # request must be left to complete (or fail) on it —
            # resolving it here would cancel the probe every round and
            # the circuit could never close.
            if rep.state != DEAD or rep.probing or not rep.local_gid:
                continue
            for local, gid in list(rep.local_gid.items()):
                try:
                    queued = _is_queued(rep.engine, local)
                except Exception:
                    queued = False
                try:
                    rep.engine.cancel(local)
                except Exception:
                    pass
                del rep.local_gid[local]
                self._router_cancelled.add((rep.index, local))
                if rep.role == "decode":
                    # the transferred segment died with the replica: a
                    # re-dispatch re-runs the PREFILL (the ring is the
                    # prefill subset), whose fresh handoff must be
                    # allowed to stage again
                    self._handoff_done.discard(gid)
                entry = self.ledger.entries[gid]
                if entry.delivered is not None:
                    continue  # hedge twin already completed it
                if queued and self._live_dispatches(entry):
                    continue  # hedge twin still running elsewhere
                target = None
                if queued:
                    target = self._place(
                        self._requests[gid], gid, kind="redispatch",
                        exclude={rep.index},
                    )
                if target is not None:
                    self._record("redispatch", gid=gid, frm=rep.index,
                                 to=target.index, reason="replica_dead")
                    continue
                if self._live_dispatches(entry):
                    continue  # a hedge twin will deliver
                template = self._requests[gid]
                self.ledger.delivered(gid, rep.index, REPLICA_DEAD)
                self.n_dead_completions += 1
                out.append(Completion(
                    request_id=gid, prompt=template.prompt, tokens=[],
                    finish_reason=REPLICA_DEAD, latency_s=0.0,
                ))
        return out

    def _live_dispatches(
        self, entry: LedgerEntry
    ) -> List[Tuple[int, int]]:
        return [
            (r, l) for r, l, _, _ in entry.dispatches
            if l in self._replicas[r].local_gid
            and self._replicas[r].local_gid[l] == entry.gid
        ]

    def _place(self, template: Request, gid: int, kind: str,
               exclude: set) -> Optional[_Replica]:
        """Re-dispatch/hedge placement: the affinity ring minus
        ``exclude``. Hedges go to HEALTHY replicas only (a hedge onto a
        suspect replica would just mint a second straggler);
        re-dispatches fall back to suspect replicas — a slow completion
        beats a synthesized loss. Returns the chosen replica, or None
        when the fleet has nowhere to put it."""
        now = self._clock()
        allow_suspect = kind == "redispatch"
        for rep in self._route_order(template):
            if rep.index in exclude:
                continue
            if rep.state != HEALTHY and not allow_suspect:
                continue
            try:
                local = rep.engine.submit(dataclasses.replace(template))
            except (QueueFull, QueueClosed, ValueError):
                continue
            rep.local_gid[local] = gid
            self.ledger.dispatched(gid, rep.index, local, kind, now)
            return rep
        return None

    # -- handoff movement (ISSUE 18) ---------------------------------------

    def _move_handoffs(self, now: float) -> List[Completion]:
        """Move each pending handoff onto the least-``load`` HEALTHY
        decode replica via ``engine.accept`` — a ``"handoff"`` ledger
        dispatch, so exactly-once spans the transfer. A gid cancelled
        while its handoff waited delivers ``"cancelled"`` here (the
        handoff's chain boundary); a fleet with no admitting decode
        replica keeps the handoff pending — retried every round, and
        the open ledger entry keeps the fleet non-idle. A rested dead
        decode replica takes the first moved handoff as its half-open
        probe (delivery heals it, any fault re-opens the circuit)."""
        out: List[Completion] = []
        if not self._pending_handoffs:
            return out
        still: List[Tuple[int, Any]] = []
        probe = self._probe_candidate(now, role="decode")
        for gid, handoff in self._pending_handoffs:
            template = self._requests[gid]
            if gid in self._cancelled_gids:
                self._cancelled_gids.discard(gid)
                self._handoff_done.discard(gid)
                self.ledger.delivered(gid, -1, "cancelled")
                out.append(Completion(
                    request_id=gid, prompt=list(template.prompt),
                    tokens=[], finish_reason="cancelled", latency_s=0.0,
                ))
                continue
            targets = sorted(
                (r for r in self._replicas
                 if r.role == "decode" and r.state == HEALTHY),
                key=lambda r: int(getattr(r.engine, "load", 0)),
            )
            if probe is not None:
                targets.append(probe)  # last resort: the half-open gamble
            placed = False
            for rep in targets:
                try:
                    local = rep.engine.accept(
                        dataclasses.replace(template), handoff
                    )
                except (QueueFull, QueueClosed, ValueError):
                    continue
                rep.local_gid[local] = gid
                self.ledger.dispatched(
                    gid, rep.index, local, "handoff", now
                )
                self.n_handoffs_moved += 1
                self._record("handoff_move", gid=gid, to=rep.index)
                if rep is probe:
                    rep.probing = True
                    rep.probe_gid = gid
                    self.n_probes += 1
                    probe = None
                    self._record("replica_health", replica=rep.index,
                                 frm=DEAD, to="probing",
                                 reason="half_open")
                placed = True
                break
            if not placed:
                still.append((gid, handoff))
        self._pending_handoffs = still
        return out

    # -- hedging -----------------------------------------------------------

    def _hedge_threshold(self, gid: int) -> Optional[float]:
        """The hedge age for this request: the scalar config, or — when
        ``hedge_after_s`` is a class-indexed map (ISSUE 20) — the
        request's SLO-class entry (None = that class never hedges)."""
        if not isinstance(self._hedge_after_s, dict):
            return self._hedge_after_s
        req = self._requests.get(gid)
        return self._hedge_after_s.get(
            int(getattr(req, "priority", 0)) if req is not None else 0
        )

    def _maybe_hedge(self, now: float) -> None:
        if self._hedge_after_s is None:
            return
        for gid in self.ledger.open_ids():
            if self._disagg and gid in self._handoff_done:
                # past the handoff: a hedge would re-run the PREFILL
                # (the ring is the prefill subset) whose duplicate emit
                # is dropped — pure waste. Prefill-side stragglers
                # (not yet handed off) still hedge normally.
                continue
            entry = self.ledger.entries[gid]
            live = self._live_dispatches(entry)
            if len(live) != 1:
                continue  # already hedged (or being resolved)
            rep_i, _local = live[0]
            rep = self._replicas[rep_i]
            if rep.state != SUSPECT:
                continue
            threshold = self._hedge_threshold(gid)
            if threshold is None:
                continue
            age = now - entry.dispatches[-1][3]
            if age < threshold:
                continue
            target = self._place(
                self._requests[gid], gid, kind="hedge",
                exclude={rep_i},
            )
            if target is not None:
                self._record("hedge", gid=gid, frm=rep_i,
                             to=target.index)

    # -- chaos -------------------------------------------------------------

    def _chaos_killed(self, rep: _Replica) -> bool:
        if self._chaos is None or not getattr(self._chaos, "kills", False):
            return False
        from ..utils.chaos import replica_killed

        return replica_killed(
            self._chaos, rep.index, rep.progress_signature()[0]
        )

    def _chaos_stalled(self, rep: _Replica) -> bool:
        if self._chaos is None or not getattr(self._chaos, "stalls", False):
            return False
        from ..utils.chaos import replica_stall_pending

        return replica_stall_pending(
            self._chaos, rep.index, rep.progress_signature()[0],
            rep.stall_skips,
        )

    # -- observability / receipts -----------------------------------------

    def _record(self, kind: str, **fields: Any) -> None:
        if self._flight is not None:
            self._flight.record(kind, **fields)

    def router_stats(self) -> Dict[str, Any]:
        """The fleet part of the receipt. Config fields (``n_replicas``,
        ``hedge``, ``affinity``) are fingerprinted by regress.py so
        fleet and single-engine rounds never gate each other; the
        health/ledger counters are OUTCOMES and deliberately stay out of
        the fingerprint, mirroring the chaos precedent."""
        states = self.replica_states()
        roles = [r.role for r in self._replicas]
        if isinstance(self._hedge_after_s, dict):
            # class-indexed hedging (ISSUE 20): serialized as a stable
            # "class:seconds" string so the fingerprint stays hashable
            hedge: Any = ",".join(
                f"{k}:{v}" for k, v in sorted(self._hedge_after_s.items())
            )
        else:
            hedge = float(self._hedge_after_s or 0.0)
        return {
            "n_replicas": self.n_replicas,
            "hedge": hedge,
            "class_deadline_s": ",".join(
                f"{k}:{v}"
                for k, v in sorted((self._class_deadline_s or {}).items())
            ),
            "affinity": self._affinity_depth,
            # disaggregation geometry (ISSUE 18): config, fingerprinted
            # by regress.py; 0/0 = monolithic fleet
            "n_prefill_replicas": roles.count("prefill"),
            "n_decode_replicas": roles.count("decode"),
            "handoffs_moved": self.n_handoffs_moved,
            "replicas_dead": states.count(DEAD),
            "replicas_draining": states.count(DRAINING),
            "requests_accepted": len(self.ledger.entries),
            "redispatched": self.ledger.n_redispatched,
            "hedged": self.ledger.n_hedged,
            "absorbed": self.ledger.n_absorbed,
            "replica_dead_completions": self.n_dead_completions,
            "queue_spillovers": self.n_spillovers,
            "probes": self.n_probes,
            "health_transitions": self.n_health_transitions,
        }

    # Engine-stats keys that describe CONFIGURATION (identical across a
    # homogeneous fleet): the merge passes the first replica's value
    # through. Everything else numeric is a traffic counter and SUMS —
    # equality across replicas must not suppress the sum (two replicas
    # that each served 4 requests served 8).
    _CONFIG_STAT_KEYS = frozenset({
        "prefix_cache", "speculative", "spec_k", "spec_ngram",
        "adapters", "n_adapters", "lora_rank", "deadline_s",
        "guard_nonfinite", "chaos", "flight", "pipeline_depth",
        "prefill_chunk",
        # sharded serving (ISSUE 15): identical across a homogeneous
        # fleet (one mesh geometry, one compiled program set) — summing
        # tp sizes or and-ing audit booleans would both lie
        "tp", "mesh_shape", "tp_collectives", "tp_hlo_ok",
        # disaggregation (ISSUE 18): per-engine role is a string (the
        # first replica's passes through — a heterogeneous fleet's
        # geometry lives in router_stats' n_prefill/n_decode_replicas);
        # the handoff counters below it stay counters and SUM
        "role",
        # SLO tiers (ISSUE 20): class count and the preemption flag are
        # engine geometry (identical across a homogeneous fleet); the
        # swap counters stay counters and SUM
        "priority_classes", "preemption",
    })
    # Derived ratios: recomputed or dropped rather than summed.
    _RATIO_STAT_KEYS = frozenset({
        "prefix_hit_rate", "spec_mean_accepted_len",
        "spec_acceptance_rate",
    })

    def stats(self, *parts: str) -> Dict[str, Any]:
        """One merged fleet receipt over ``router_stats`` + every
        replica's ``stats(parts)``: config keys pass through, traffic
        counters SUM, derived ratios are dropped (a mean of means
        lies), and flight keys are recomputed from the bucket-wise
        MERGED histograms via :meth:`fleet_flight_summary` (summing a
        p95 across replicas would be meaningless)."""
        out = self.router_stats()
        per: List[dict] = []
        for rep in self._replicas:
            fn = getattr(rep.engine, "stats", None)
            if fn is not None:
                per.append(dict(fn(*parts)))
        flight = self.fleet_flight_summary()
        sentry = self.fleet_sentry_summary()
        merged: Dict[str, Any] = {}
        for d in per:
            for k, v in d.items():
                if k in self._RATIO_STAT_KEYS:
                    continue
                if flight is not None and k.startswith((
                    "flight", "ttft_", "e2e_", "queue_wait_",
                    "chain_util_", "chain_overlap_", "preempt_wait_",
                )):
                    continue  # superseded by the histogram merge
                if sentry is not None and k.startswith("sentry"):
                    # superseded by the identity-deduped sentry merge:
                    # a fleet typically shares ONE sentry, and summing
                    # the same counters once per replica would
                    # N-multiply every fleet-global count
                    continue
                if k not in merged:
                    merged[k] = v
                elif k not in self._CONFIG_STAT_KEYS and isinstance(
                    v, (int, float)
                ) and isinstance(merged[k], (int, float)):
                    merged[k] = merged[k] + v
        out.update(merged)
        if flight is not None:
            out.update(flight)
        if sentry is not None:
            out.update(sentry)
        return out

    def fleet_sentry_summary(self) -> Optional[Dict[str, Any]]:
        """Contract-sentry aggregate across the fleet (ISSUE 19), or
        None when no replica carries one. Sentries dedupe by IDENTITY:
        the normal deployment shares one sentry (one process, one
        ``jax.device_get`` wrapper, one compile listener) across every
        replica, so its summary is already fleet-global; distinct
        sentries sum counters, and ``sentry_fetch_budget_ok`` is
        re-derived from the summed violations (and-ing per-replica
        booleans via addition would lie)."""
        seen: Dict[int, Any] = {}
        for rep in self._replicas:
            s = getattr(rep.engine, "_sentry", None)
            if s is not None and id(s) not in seen:
                seen[id(s)] = s
        if not seen:
            return None
        sentries = list(seen.values())
        out: Dict[str, Any] = dict(sentries[0].summary())
        for s in sentries[1:]:
            for k, v in s.summary().items():
                if k in out and isinstance(v, (int, float)) and isinstance(
                    out[k], (int, float)
                ):
                    out[k] = out[k] + v
                else:
                    out.setdefault(k, v)
        out["sentry"] = 1
        out["sentry_fetch_budget_ok"] = int(
            out.get("sentry_budget_violations", 0) == 0
        )
        return out

    def _tagged_snapshots(self) -> List[Tuple[Any, dict]]:
        tagged: List[Tuple[Any, dict]] = []
        if self._flight is not None:
            tagged.append(("router", self._flight.snapshot()))
        for rep in self._replicas:
            rec = getattr(rep.engine, "_flight", None)
            if rec is None:
                rec = getattr(rep.engine, "flight", None)
            if rec is not None and hasattr(rec, "snapshot"):
                tagged.append((rep.index, rec.snapshot()))
        return tagged

    def fleet_flight_summary(self) -> Optional[Dict[str, Any]]:
        """Receipt-grade flight aggregate across the fleet, or None when
        no recorder is attached anywhere. Percentiles come from the
        MERGED histograms — mergeability is why LogHistogram exists."""
        from ..obs.flight import summarize_merged

        tagged = self._tagged_snapshots()
        if not tagged:
            return None
        return summarize_merged([snap for _, snap in tagged])

    def _gid_map(self) -> Dict[Tuple[Any, Any], int]:
        """(replica index, local request id) -> global id, re-derived
        from the ledger's dispatch records — the same rows
        :meth:`DispatchLedger.verify` proves exactly-once over. Hedged
        / re-dispatched gids map from EVERY replica that held them, so
        a journey shows both sides of a failover."""
        m: Dict[Tuple[Any, Any], int] = {}
        for gid, entry in self.ledger.entries.items():
            for replica, local, _kind, _t in entry.dispatches:
                m[(replica, local)] = gid
        return m

    def fleet_snapshot(self, reason: str = "fleet") -> Optional[dict]:
        """One merged ``graft-flightlog/v1`` snapshot over the router's
        and every replica's recorder: events tagged ``replica=i`` (the
        router's as ``replica="router"``), interleaved by timestamp —
        pass the same ``t0`` to every recorder or the interleaving is
        per-recorder-relative. ``scripts/flight_view.py`` renders it.

        Journey stitching (ISSUE 19): replica-local events and spans
        that carry a ``rid`` gain the request's GLOBAL ``gid`` (from
        the ledger's dispatch records), so one request's journey —
        submit -> prefill replica -> ``handoff_move`` -> decode-replica
        ``handoff_accept`` -> chains -> complete — is one
        ``gid=``-filtered slice of the merged timeline
        (``scripts/flight_view.py --journey GID`` renders it)."""
        from ..obs.flight import merge_snapshots

        tagged = self._tagged_snapshots()
        if not tagged:
            return None
        snap = merge_snapshots(tagged, reason=reason)
        gid_map = self._gid_map()
        for ev in snap["events"]:
            if "gid" in ev:
                continue  # router events (handoff_move ...) name gids
            key = (ev.get("replica"), ev.get("rid"))
            if ev.get("rid") is not None and key in gid_map:
                ev["gid"] = gid_map[key]
        for span in snap["live_spans"] + snap["done_spans"]:
            key = (span.get("replica"), span.get("rid"))
            if "gid" not in span and key in gid_map:
                span["gid"] = gid_map[key]
        return snap

    def dump_fleet(self, path: str, reason: str = "fleet") -> Optional[dict]:
        """Append the merged fleet snapshot to ``path`` (JSONL)."""
        import json

        snap = self.fleet_snapshot(reason=reason)
        if snap is not None:
            with open(path, "a") as f:
                f.write(json.dumps(snap) + "\n")
        return snap
