"""Continuous-batching LM serving: slot-indexed KV cache, launch-amortized
decode chains, FIFO admission with backpressure.

Public surface:

- :class:`.engine.ServeEngine` — the engine (submit / step /
  run_until_idle);
- :class:`.scheduler.Request` / :class:`.scheduler.Completion` — the
  request/response records — and :class:`.scheduler.Handoff`, the
  prefill→decode transfer record of the disaggregated path (ISSUE 18:
  ``ServeEngine(role="prefill"/"decode")`` + role-aware routing);
- :class:`.scheduler.FifoScheduler` / :class:`.scheduler.QueueFull` /
  :class:`.scheduler.QueueClosed` — the host-side queue and its
  backpressure/shutdown signals (``ServeEngine.close``/``drain`` stop
  admission and run accepted work to completion);
- :func:`.slots.bucket_len` / :func:`.slots.init_slot_state` /
  :func:`.slots.write_slot` — the slot-state building blocks (exposed
  for tests and for engines over non-TransformerLM models);
- :class:`.pages.PagePool` / :class:`.pages.PoolExhausted` — the
  jax-free page-pool allocator behind ``ServeEngine(paged=True)``
  (ISSUE 13): fixed pages, refcounted prefix sharing, synchronous
  admission backpressure;
- :class:`.prefix.PrefixIndex` / :class:`.prefix.Segment` — the
  jax-free radix prefix index behind ``ServeEngine(prefix_cache_bytes=
  ...)``: shared-prompt KV reuse via retained cache segments
  (longest-prefix-match, refcount pinning, LRU byte budget);
- :class:`.router.FleetRouter` / :class:`.router.DispatchLedger` /
  :func:`.router.affinity_hash` — the jax-free multi-replica fleet
  front door (ISSUE 12): replica health states with a circuit breaker,
  exactly-once re-dispatch off dead/draining replicas, hedged
  stragglers, prefix-affinity routing, merged fleet receipts;
- :class:`.slo.PriorityScheduler` — the jax-free multi-class queue
  behind ``ServeEngine(priority_classes=N)`` (ISSUE 20): pop by
  (SLO class, arrival), plus chain-boundary preemption by KV swap —
  a lower-class active slot parks to host for a higher-class waiter
  and later resumes token-exact.

``python -m pytorch_distributed_training_tutorials_tpu.serve --selftest`` runs the end-to-end smoke
(token-exactness vs ``generate()`` included) and prints one receipt line
— tier-1 wires it in via tests/test_serve.py.

The re-exports below are PEP 562 LAZY (same pattern as obs/ and bench/):
the host-only halves (:mod:`.scheduler`, :mod:`.prefix`) must stay
importable without initializing a backend — tests/test_prefix.py pins it
in a subprocess — and an eager ``from .engine import ...`` here would
drag jax into every ``import ...serve.prefix``.
"""

import importlib

# name -> submodule; resolved on first access via __getattr__.
_LAZY_EXPORTS = {
    "ServeEngine": "pytorch_distributed_training_tutorials_tpu.serve.engine",
    "DispatchLedger": "pytorch_distributed_training_tutorials_tpu.serve.router",
    "FleetRouter": "pytorch_distributed_training_tutorials_tpu.serve.router",
    "affinity_hash": "pytorch_distributed_training_tutorials_tpu.serve.router",
    "PagePool": "pytorch_distributed_training_tutorials_tpu.serve.pages",
    "PoolExhausted": "pytorch_distributed_training_tutorials_tpu.serve.pages",
    "PrefixIndex": "pytorch_distributed_training_tutorials_tpu.serve.prefix",
    "Segment": "pytorch_distributed_training_tutorials_tpu.serve.prefix",
    "Completion": "pytorch_distributed_training_tutorials_tpu.serve.scheduler",
    "FifoScheduler": "pytorch_distributed_training_tutorials_tpu.serve.scheduler",
    "Handoff": "pytorch_distributed_training_tutorials_tpu.serve.scheduler",
    "QueueClosed": "pytorch_distributed_training_tutorials_tpu.serve.scheduler",
    "QueueFull": "pytorch_distributed_training_tutorials_tpu.serve.scheduler",
    "Request": "pytorch_distributed_training_tutorials_tpu.serve.scheduler",
    "PriorityScheduler": "pytorch_distributed_training_tutorials_tpu.serve.slo",
    "SwapRecord": "pytorch_distributed_training_tutorials_tpu.serve.slo",
    "bucket_len": "pytorch_distributed_training_tutorials_tpu.serve.slots",
    "extract_segment": "pytorch_distributed_training_tutorials_tpu.serve.slots",
    "init_slot_state": "pytorch_distributed_training_tutorials_tpu.serve.slots",
    "seed_cache": "pytorch_distributed_training_tutorials_tpu.serve.slots",
    "tree_nbytes": "pytorch_distributed_training_tutorials_tpu.serve.slots",
    "write_slot": "pytorch_distributed_training_tutorials_tpu.serve.slots",
}

__all__ = sorted(_LAZY_EXPORTS)


def __getattr__(name: str):
    try:
        module_name = _LAZY_EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    value = getattr(importlib.import_module(module_name), name)
    globals()[name] = value  # cache: __getattr__ runs once per name
    return value


def __dir__():
    return sorted(set(globals()) | set(_LAZY_EXPORTS))
