"""Continuous-batching LM serving: slot-indexed KV cache, launch-amortized
decode chains, FIFO admission with backpressure.

Public surface:

- :class:`.engine.ServeEngine` — the engine (submit / step /
  run_until_idle);
- :class:`.scheduler.Request` / :class:`.scheduler.Completion` — the
  request/response records;
- :class:`.scheduler.FifoScheduler` / :class:`.scheduler.QueueFull` —
  the host-side queue and its backpressure signal;
- :func:`.slots.bucket_len` / :func:`.slots.init_slot_state` /
  :func:`.slots.write_slot` — the slot-state building blocks (exposed
  for tests and for engines over non-TransformerLM models).

``python -m pytorch_distributed_training_tutorials_tpu.serve --selftest`` runs the end-to-end smoke
(token-exactness vs ``generate()`` included) and prints one receipt line
— tier-1 wires it in via tests/test_serve.py.
"""

from pytorch_distributed_training_tutorials_tpu.serve.engine import ServeEngine
from pytorch_distributed_training_tutorials_tpu.serve.scheduler import (
    Completion,
    FifoScheduler,
    QueueFull,
    Request,
)
from pytorch_distributed_training_tutorials_tpu.serve.slots import (
    bucket_len,
    init_slot_state,
    write_slot,
)

__all__ = [
    "Completion",
    "FifoScheduler",
    "QueueFull",
    "Request",
    "ServeEngine",
    "bucket_len",
    "init_slot_state",
    "write_slot",
]
