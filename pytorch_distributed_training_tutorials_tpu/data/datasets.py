"""Datasets: synthetic twins of the reference's, plus MNIST/CIFAR-10 loaders.

The reference materializes whole datasets in host memory up front
(``MyTrainDataset`` builds all 2,048 samples in ``__init__``, reference
``ddp_gpus.py:56-62``). We keep that map-style, fully-materialized model — it
is the right one for TPU input pipelines at tutorial scale: host numpy arrays,
batch-gathered and ``device_put`` straight to the mesh.

BASELINE.json upgrades the toy workloads to ResNet-18 on MNIST / CIFAR-10, so
real loaders are included. They read the standard binary formats from a local
directory (``DATA_DIR`` env var, default ``~/.cache/tpu_ddp_data``); when the
files are absent (this build environment has no network egress) they fall back
to a *deterministic, clearly-labeled* synthetic surrogate with identical
shapes/dtypes/cardinalities so every code path stays runnable.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
from dataclasses import dataclass

import numpy as np

from pytorch_distributed_training_tutorials_tpu.data.native import gather_rows

DATA_DIR = os.environ.get("DATA_DIR", os.path.expanduser("~/.cache/tpu_ddp_data"))


@dataclass
class ArrayDataset:
    """A fully-materialized map-style dataset: parallel numpy arrays.

    Twin of the reference's map-style ``Dataset.__len__/__getitem__`` surface
    (``ddp_gpus.py:63-67``), but batch-gather oriented: ``gather(indices)``
    returns the batch in one vectorized fancy-index instead of a Python loop
    over ``__getitem__`` — the host-side work per step is one numpy gather.
    """

    arrays: tuple[np.ndarray, ...]
    synthetic: bool = False  # True when this is a no-network surrogate

    def __post_init__(self):
        n = len(self.arrays[0])
        for a in self.arrays[1:]:
            if len(a) != n:
                raise ValueError("all arrays must share dim 0")

    def __len__(self) -> int:
        return len(self.arrays[0])

    def __getitem__(self, i: int):
        return tuple(a[i] for a in self.arrays)

    def gather(self, indices: np.ndarray) -> tuple[np.ndarray, ...]:
        return tuple(gather_rows(a, indices) for a in self.arrays)


def synthetic_regression(
    size: int = 2048, in_dim: int = 20, out_dim: int = 1, seed: int = 0
) -> ArrayDataset:
    """Twin of ``MyTrainDataset``: ``size`` samples of ``(rand(20), rand(1))``.

    Reference ``ddp_gpus.py:56-62`` (duplicated at
    ``ddp_gpus_torchrun.py:52-63`` and ``02.ddp_toy_example.ipynb`` cell 5).
    Uniform [0,1) features and targets, materialized up front.
    """
    rng = np.random.Generator(np.random.PCG64(seed))
    x = rng.random((size, in_dim), dtype=np.float32)
    y = rng.random((size, out_dim), dtype=np.float32)
    return ArrayDataset((x, y))


def random_dataset(size: int = 32, length: int = 1024, seed: int = 0) -> ArrayDataset:
    """Twin of 01's ``RandomDataset(32, 1024)``: ``length`` samples of randn(size).

    Reference ``01.data_parallel.ipynb`` cell 6 (line 118).
    """
    rng = np.random.Generator(np.random.PCG64(seed))
    x = rng.standard_normal((length, size)).astype(np.float32)
    return ArrayDataset((x,))


def synthetic_lm(
    size: int = 512,
    seq_len: int = 64,
    vocab_size: int = 64,
    seed: int = 0,
    peakedness: float = 3.0,
) -> ArrayDataset:
    """Learnable causal-LM data: tokens drawn from a fixed random bigram
    transition table (temperature set by ``peakedness``), so next-token
    cross-entropy is reducible well below ``log(vocab_size)`` by any model
    that can learn the table. Returns ``(inputs, targets)`` where targets are
    inputs shifted left by one.
    """
    rng = np.random.Generator(np.random.PCG64(seed))
    logits = rng.standard_normal((vocab_size, vocab_size)) * peakedness
    probs = np.exp(logits - logits.max(axis=1, keepdims=True))
    cdf = np.cumsum(probs / probs.sum(axis=1, keepdims=True), axis=1)
    seqs = np.empty((size, seq_len + 1), np.int32)
    seqs[:, 0] = rng.integers(0, vocab_size, size)
    for t in range(seq_len):
        u = rng.random(size)[:, None]
        seqs[:, t + 1] = (u > cdf[seqs[:, t]]).sum(axis=1)
    return ArrayDataset((seqs[:, :-1], np.ascontiguousarray(seqs[:, 1:])))


def _synthetic_images(
    n: int,
    shape: tuple[int, ...],
    num_classes: int,
    template_seed: int,
    noise_seed: int,
    raw: bool = False,
    modes: int = 4,
    signal: float = 0.35,
) -> ArrayDataset:
    """Deterministic learnable surrogate for an image dataset — hard enough
    that the 0.99 accuracy target is *falsifiable*.

    Each class is a mixture of ``modes`` fixed random templates (a
    multi-modal class manifold); a sample is ``signal * template +
    sqrt(1-signal^2) * noise``. Round 3's single-template 1:1-SNR version
    saturated healthy training at ``eval_accuracy 1.0 / eval_loss 0.0``,
    which certifies nothing (round-3 verdict, weak #3): at ``signal=0.35``
    over 784 pixels a healthily-trained ResNet-18 reaches ~0.996 with
    visibly nonzero loss (measured round 4: 0.9961 / 0.0132 after the
    bench's 7 epochs; signal=0.30 misses the target at 0.9867), while a broken config (diverged lr, BN off) lands far
    below — ``tests/test_accuracy_falsifiable.py`` pins both directions.
    Templates are seeded separately from noise so train/test share one
    distribution (same manifolds, fresh samples).

    Like the real datasets, the surrogate is **uint8 at rest** (quantized to
    ~N(128, 32) pixel values): ``raw=True`` returns the uint8 bytes (for
    device-resident pipelines that normalize on device — 4x less HBM gather
    traffic), ``raw=False`` the float32 ``uint8 / 255`` view, so the two
    modes see byte-identical data.
    """
    t_rng = np.random.Generator(np.random.PCG64(template_seed))
    templates = t_rng.standard_normal(
        (num_classes, modes, *shape)
    ).astype(np.float32)
    rng = np.random.Generator(np.random.PCG64(noise_seed))
    labels = rng.integers(0, num_classes, size=n).astype(np.int32)
    mode_ids = rng.integers(0, modes, size=n)
    noise_amp = float(np.sqrt(1.0 - signal * signal))
    images = templates[labels, mode_ids] * signal + (
        noise_amp * rng.standard_normal((n, *shape)).astype(np.float32)
    )
    u8 = np.clip(images * 64.0 + 128.0, 0, 255).astype(np.uint8)
    if raw:
        return ArrayDataset((u8, labels), synthetic=True)
    return ArrayDataset(
        (u8.astype(np.float32) / 255.0, labels), synthetic=True
    )


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(dims)


def mnist(
    split: str = "train",
    data_dir: str | None = None,
    *,
    raw: bool = False,
) -> ArrayDataset:
    """MNIST as (N, 28, 28, 1) float32 in [0,1] + int32 labels (NHWC for TPU).

    Reads the standard idx(.gz) files if present under ``data_dir``; otherwise
    returns a deterministic synthetic surrogate with identical shape/classes
    (``.synthetic`` is set so callers/benchmarks can report it honestly).

    ``raw=True`` returns the images as **uint8** (the on-disk dtype): the
    device-resident pipeline keeps the dataset at 1/4 the HBM and fuses the
    ``/255`` normalize into the compiled step (see ``bench.py``).
    """
    data_dir = data_dir or DATA_DIR
    prefix = "train" if split == "train" else "t10k"
    for ext in ("", ".gz"):
        img_p = os.path.join(data_dir, f"{prefix}-images-idx3-ubyte{ext}")
        lbl_p = os.path.join(data_dir, f"{prefix}-labels-idx1-ubyte{ext}")
        if os.path.exists(img_p) and os.path.exists(lbl_p):
            u8 = _read_idx(img_p)[..., None]
            labels = _read_idx(lbl_p).astype(np.int32)
            images = u8 if raw else u8.astype(np.float32) / 255.0
            return ArrayDataset((images, labels))
    n = 60000 if split == "train" else 10000
    # Fixed constants: hash() is interpreter-randomized and would desync the
    # surrogate across processes/runs. Shared template seed across splits.
    return _synthetic_images(
        n, (28, 28, 1), 10, template_seed=101,
        noise_seed=1 if split == "train" else 2, raw=raw,
    )


def cifar10(
    split: str = "train",
    data_dir: str | None = None,
    *,
    raw: bool = False,
) -> ArrayDataset:
    """CIFAR-10 as (N, 32, 32, 3) float32 in [0,1] + int32 labels (NHWC).

    Reads the python-pickle batches from ``cifar-10-batches-py`` (or the
    ``.tar.gz``) if present; otherwise a deterministic synthetic surrogate.
    ``raw=True`` keeps the images uint8 (see :func:`mnist`).
    """
    data_dir = data_dir or DATA_DIR
    batch_dir = os.path.join(data_dir, "cifar-10-batches-py")
    tar_path = os.path.join(data_dir, "cifar-10-python.tar.gz")
    if not os.path.isdir(batch_dir) and os.path.exists(tar_path):
        with tarfile.open(tar_path) as t:
            try:
                t.extractall(data_dir, filter="data")
            except TypeError:  # filter= needs >= 3.10.12 / 3.11.4
                t.extractall(data_dir)
    if os.path.isdir(batch_dir):
        names = (
            [f"data_batch_{i}" for i in range(1, 6)]
            if split == "train"
            else ["test_batch"]
        )
        xs, ys = [], []
        for name in names:
            with open(os.path.join(batch_dir, name), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(d[b"data"])
            ys.extend(d[b"labels"])
        u8 = (
            np.concatenate(xs)
            .reshape(-1, 3, 32, 32)
            .transpose(0, 2, 3, 1)
        )
        u8 = np.ascontiguousarray(u8)
        images = u8 if raw else u8.astype(np.float32) / 255.0
        return ArrayDataset((images, np.asarray(ys, dtype=np.int32)))
    n = 50000 if split == "train" else 10000
    return _synthetic_images(
        n, (32, 32, 3), 10, template_seed=103,
        noise_seed=3 if split == "train" else 4, raw=raw,
    )
