"""ctypes bridge to the native batch-assembly library (csrc/fastgather.cpp).

Builds the shared library with g++ on first use (cached beside the source,
rebuilt when the source is newer) and falls back to numpy fancy indexing if
anything goes wrong — the native path is a throughput optimization, never a
correctness dependency. Disable explicitly with ``TPU_DDP_NO_NATIVE=1``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading

import numpy as np

_CSRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "csrc"
)
_SRC = os.path.join(_CSRC, "fastgather.cpp")
_SO = os.path.join(_CSRC, "_fastgather.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> None:
    # atomic: compile to a temp name, rename over the target, so concurrent
    # builders (spawned test workers) never load a half-written .so
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_CSRC)
    os.close(fd)
    try:
        subprocess.run(
            [
                "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
                _SRC, "-o", tmp,
            ],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, _SO)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    if _tried:
        return _lib
    with _lock:
        if _tried:
            return _lib
        try:
            if os.environ.get("TPU_DDP_NO_NATIVE"):
                return None
            if not os.path.exists(_SO) or (
                os.path.getmtime(_SO) < os.path.getmtime(_SRC)
            ):
                _build()
            lib = ctypes.CDLL(_SO)
            lib.fg_gather_rows.argtypes = [
                ctypes.c_void_p,  # src
                ctypes.POINTER(ctypes.c_int64),  # indices
                ctypes.c_void_p,  # dst
                ctypes.c_int64,  # n_rows
                ctypes.c_int64,  # row_bytes
                ctypes.c_int32,  # n_threads
            ]
            lib.fg_gather_rows.restype = None
            _lib = lib
        except Exception:
            _lib = None
        finally:
            _tried = True
    return _lib


def native_available() -> bool:
    return _load() is not None


def gather_rows(arr: np.ndarray, rows: np.ndarray) -> np.ndarray:
    """``arr[rows]`` with the multithreaded native copy when possible.

    Exact numpy semantics for in-range indices (validated here; the C side
    does raw memcpys). Falls back to numpy for non-contiguous or 0-d-row
    arrays and when the library is unavailable.
    """
    lib = _load()
    rows = np.asarray(rows)
    if (
        lib is None
        or arr.ndim < 1
        or not arr.flags["C_CONTIGUOUS"]
        or arr.dtype.hasobject
        # only plain 1-d integer indexing maps to the raw row-memcpy; boolean
        # masks, 0-d and n-d index arrays keep exact numpy semantics
        or rows.ndim != 1
        or rows.dtype.kind not in "iu"
    ):
        return arr[rows]
    rows = np.ascontiguousarray(rows, dtype=np.int64)
    n = len(arr)
    if rows.size and (rows.min() < -n or rows.max() >= n):
        raise IndexError(
            f"index out of range for axis 0 with size {n}"
        )
    rows = np.where(rows < 0, rows + n, rows)
    out = np.empty((rows.shape[0], *arr.shape[1:]), arr.dtype)
    row_bytes = arr.dtype.itemsize * int(
        np.prod(arr.shape[1:], dtype=np.int64)
    )
    lib.fg_gather_rows(
        arr.ctypes.data_as(ctypes.c_void_p),
        rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        out.ctypes.data_as(ctypes.c_void_p),
        rows.shape[0],
        row_bytes,
        0,
    )
    return out
