"""Sharded input pipeline: the TPU twin of the reference's L2 data layer.

Reference surface (SURVEY.md C6/C7): map-style ``Dataset``, ``DataLoader`` with
``sampler=DistributedSampler(ds)`` for per-rank disjoint shards padded to equal
length, and ``sampler.set_epoch(epoch)`` for epoch-seeded reshuffle
(reference ``ddp_gpus.py:56-79``, ``:45``).
"""

from pytorch_distributed_training_tutorials_tpu.data.sampler import (  # noqa: F401
    DistributedSampler,
)
from pytorch_distributed_training_tutorials_tpu.data.datasets import (  # noqa: F401
    ArrayDataset,
    synthetic_regression,
    synthetic_lm,
    random_dataset,
    mnist,
    cifar10,
)
from pytorch_distributed_training_tutorials_tpu.data.loader import (  # noqa: F401
    ShardedLoader,
)
from pytorch_distributed_training_tutorials_tpu.data.prefetch import (  # noqa: F401
    PrefetchLoader,
)
from pytorch_distributed_training_tutorials_tpu.data.resident import (  # noqa: F401
    DeviceResidentLoader,
)
from pytorch_distributed_training_tutorials_tpu.data.streaming import (  # noqa: F401
    ChunkedStreamingLoader,
)
