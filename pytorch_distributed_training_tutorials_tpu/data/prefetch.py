"""Prefetching: overlap host batch assembly/H2D with device compute.

The reference's DataLoader gets this from worker processes + ``pin_memory``
(``ddp_gpus.py:73-79``); the TPU twin is a single background thread that runs
the inner loader's gather + ``make_array_from_callback`` (which enqueues the
H2D copies) one-to-two steps ahead of the training loop, so by the time
``train_step`` needs batch N+1 its transfers are already in flight. XLA's
async dispatch does the rest — the device never waits on the host for
tutorial-scale data.
"""

from __future__ import annotations

import queue
import threading

_SENTINEL = object()


def prefetch_iterable(iterable, depth: int = 2):
    """Yield ``iterable``'s items, produced ``depth`` ahead in a background
    thread. The generic engine under :class:`PrefetchLoader`, also used
    directly for chunk streams (:class:`.streaming.ChunkedStreamingLoader`).

    Exceptions in the producer re-raise in the consumer; abandoning the
    generator stops the producer promptly.
    """
    if depth < 1:
        raise ValueError("prefetch depth must be >= 1")
    q: queue.Queue = queue.Queue(maxsize=depth)
    err: list[BaseException] = []
    stop = threading.Event()

    def put_or_stop(item) -> bool:
        """Blocking put that aborts when the consumer bailed; returns
        False on abort. The sentinel MUST go through here too — a
        dropped sentinel leaves the consumer blocked forever."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for item in iterable:
                if not put_or_stop(item):
                    return
        except BaseException as e:  # surfaced in the consumer
            err.append(e)
        finally:
            put_or_stop(_SENTINEL)

    t = threading.Thread(target=producer, daemon=True, name="prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                break
            yield item
        if err:
            raise err[0]
    finally:
        stop.set()
        t.join(timeout=10)


class PrefetchLoader:
    """Wrap any epoch-iterable loader; yields identical batches, ahead of
    time. Delegates the loader surface (``set_epoch``, lengths, mesh)."""

    def __init__(self, loader, prefetch: int = 2):
        if prefetch < 1:
            raise ValueError("prefetch must be >= 1")
        self.loader = loader
        self.prefetch = prefetch

    # --- delegated surface -------------------------------------------------
    def set_epoch(self, epoch: int) -> None:
        self.loader.set_epoch(epoch)

    def __len__(self) -> int:
        return len(self.loader)

    def __getattr__(self, name):
        return getattr(self.loader, name)

    # --- iteration ---------------------------------------------------------
    def __iter__(self):
        yield from prefetch_iterable(self.loader, self.prefetch)
