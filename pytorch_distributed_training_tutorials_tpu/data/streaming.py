"""Chunked streaming: amortize per-batch H2D latency over many steps.

The round-2 profile showed the per-step streaming path running at <10% of
the train step's throughput: each 512-row batch paid a full host->device
round trip (on a tunneled runtime that latency is ~100 ms — far more than
the 400 KB transfer itself). The reference hides the same latency with
worker processes + ``pin_memory`` (``/root/reference/ddp_gpus.py:73-79``);
the TPU-idiomatic equivalent restructures the transfer, not just the
scheduling:

1. **chunking** — gather ``steps_per_chunk`` steps' rows at once and ship
   them as ONE sharded ``(steps, global_batch, ...)`` array: one H2D
   enqueue per chunk instead of per step, so the fixed dispatch/roundtrip
   cost divides by the chunk length;
2. **prefetch** — the next chunk's gather + H2D runs in a background
   thread (:func:`.prefetch.prefetch_iterable`) while the device trains on
   the current one;
3. **scanned consumption** — the Trainer runs each chunk as one jitted
   ``lax.scan`` of train steps (``Trainer._run_epoch_chunked``), so launch
   overhead amortizes the same way the device-resident epoch scan does.

Together the streaming path approaches the device-resident one while
holding only ``prefetch * steps_per_chunk`` batches in HBM — the input
pipeline for datasets that do NOT fit on device.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from pytorch_distributed_training_tutorials_tpu.data.datasets import ArrayDataset
from pytorch_distributed_training_tutorials_tpu.data.loader import ShardedLoader
from pytorch_distributed_training_tutorials_tpu.data.native import gather_rows
from pytorch_distributed_training_tutorials_tpu.data.prefetch import (
    prefetch_iterable,
)


class ChunkedStreamingLoader(ShardedLoader):
    """A :class:`ShardedLoader` that also serves whole multi-step chunks.

    Per-step iteration (``__iter__``) keeps the parent's semantics, so
    everything written against ``ShardedLoader`` still works; consumers
    that know about :meth:`iter_chunks` (``Trainer``) stream
    ``(steps_per_chunk, global_batch, ...)`` arrays — dim 1 sharded over
    the data axis, dim 0 the scan axis — with the next chunk prefetched in
    the background.

    ``transform`` runs inside the consumer's compiled scan (the Trainer
    threads ``self.transform`` into its chunk-scan body), exactly like the
    device-resident epoch scan.
    """

    def __init__(
        self,
        dataset: ArrayDataset,
        batch_size: int,
        mesh: Mesh,
        *,
        steps_per_chunk: int = 16,
        prefetch: int = 2,
        transform=None,
        **kwargs,
    ):
        if kwargs.get("batch_spec") is not None:
            raise NotImplementedError(
                "ChunkedStreamingLoader shards batches over the data axis "
                "only; use ShardedLoader for custom batch_specs"
            )
        if steps_per_chunk < 1:
            raise ValueError("steps_per_chunk must be >= 1")
        super().__init__(
            dataset, batch_size, mesh, transform=transform, **kwargs
        )
        self.steps_per_chunk = steps_per_chunk
        self.prefetch = prefetch
        # (steps, rows, ...): rows over the data axis, steps unsharded
        self._chunk_shardings = [
            NamedSharding(mesh, PartitionSpec(None, self.axis))
            for _ in dataset.arrays
        ]

    def _make_chunk(self, step_rows: np.ndarray):
        """One chunk: ``step_rows`` is (c, global_batch) dataset indices in
        replica-major per-step order. Returns a tuple of sharded
        ``(c, global_batch, ...)`` arrays; the per-device callback gathers
        only that device's rows (for all c steps) in one native gather."""
        c = step_rows.shape[0]

        def make(ai: int):
            arr = self.dataset.arrays[ai]
            gshape = (c, self.global_batch, *arr.shape[1:])

            def cb(index):
                rows = step_rows[:, index[1]]  # (c, rows_per_device)
                flat = gather_rows(arr, rows.reshape(-1))
                return flat.reshape(c, -1, *arr.shape[1:])

            return jax.make_array_from_callback(
                gshape, self._chunk_shardings[ai], cb
            )

        return tuple(make(ai) for ai in range(len(self.dataset.arrays)))

    def iter_chunks(self):
        """Yield the epoch as prefetched multi-step chunks (the last chunk
        may be shorter — at most two distinct scan lengths compile)."""
        shards = self._epoch_index_matrix()  # (world, steps * bs)
        bs = self.per_device_batch
        idx = (
            shards.reshape(self.world, self.steps_per_epoch, bs)
            .transpose(1, 0, 2)
            .reshape(self.steps_per_epoch, self.global_batch)
        )

        def chunks():
            for lo in range(0, self.steps_per_epoch, self.steps_per_chunk):
                yield self._make_chunk(
                    idx[lo : lo + self.steps_per_chunk]
                )

        return prefetch_iterable(chunks(), self.prefetch)
