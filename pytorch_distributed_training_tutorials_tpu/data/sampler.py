"""DistributedSampler-exact index sharding.

The reference shards its dataset with ``torch.utils.data.DistributedSampler``
(reference ``ddp_gpus.py:78``) and reshuffles per epoch via
``sampler.set_epoch(epoch)`` (``ddp_gpus.py:45``). Under SPMD this padding is a
*correctness* requirement, not a convenience: every rank must run the same
number of steps or collectives deadlock (SURVEY.md section 7, hard part 1).

Semantics replicated exactly (validated against torch's sampler in
``tests/test_sampler.py``):

- ``num_samples = ceil(len(ds) / world)`` (or ``floor`` with ``drop_last`` when
  the dataset doesn't divide evenly), ``total = num_samples * world``.
- shuffle: a permutation of ``range(len(ds))`` seeded by ``seed + epoch``;
  without shuffle, ``arange``.
- padding: indices are extended by wrapping from the beginning until ``total``
  (or truncated to ``total`` with ``drop_last``).
- rank r takes the strided slice ``indices[r::world]`` — disjoint across ranks.
"""

from __future__ import annotations

import numpy as np


class DistributedSampler:
    """Per-rank disjoint, equal-length index shards with epoch reshuffle."""

    def __init__(
        self,
        dataset_size: int,
        num_replicas: int,
        rank: int,
        *,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ):
        if not 0 <= rank < num_replicas:
            raise ValueError(f"rank {rank} out of range for {num_replicas} replicas")
        self.dataset_size = dataset_size
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0

        if drop_last and dataset_size % num_replicas:
            self.num_samples = dataset_size // num_replicas
        else:
            self.num_samples = -(-dataset_size // num_replicas)  # ceil
        self.total_size = self.num_samples * num_replicas

    def set_epoch(self, epoch: int) -> None:
        """Reseed the shard permutation; twin of reference ``ddp_gpus.py:45``."""
        self.epoch = epoch

    def _global_indices(self) -> np.ndarray:
        if self.shuffle:
            rng = np.random.Generator(np.random.PCG64(self.seed + self.epoch))
            indices = rng.permutation(self.dataset_size)
        else:
            indices = np.arange(self.dataset_size)
        if not self.drop_last:
            pad = self.total_size - len(indices)
            if pad > 0:
                # Wrap-around padding, repeating the prefix as many times as
                # needed (matters when world size exceeds dataset size).
                reps = -(-pad // len(indices))
                indices = np.concatenate([indices] + [indices] * reps)[: self.total_size]
        else:
            indices = indices[: self.total_size]
        return indices

    def local_indices(self) -> np.ndarray:
        """This rank's shard: the strided slice ``indices[rank::world]``."""
        return self._global_indices()[self.rank :: self.num_replicas]

    def __iter__(self):
        return iter(self.local_indices().tolist())

    def __len__(self) -> int:
        return self.num_samples
