"""Sharded loader: host arrays -> mesh-sharded ``jax.Array`` batches.

Twin of the reference's ``DataLoader(..., sampler=DistributedSampler(ds))``
(reference ``ddp_gpus.py:73-79``) with the semantics SPMD requires:

- **per-device batch-size flag meaning** is preserved (the reference documents
  ``--batch_size`` as "Input batch size on each device", ``ddp_gpus.py:101``):
  a step's *global* batch is ``per_device_batch * mesh.shape['data']``.
- **steps-per-epoch math** is preserved: 2048 samples / 32 per device / 4
  devices -> 16 steps (the ``Steps 16`` proof, reference
  ``02.ddp_toy_example.ipynb`` cell 10), and 1 device -> 64 steps (cell 11).
- **epoch-seeded reshuffle** via :meth:`ShardedLoader.set_epoch`
  (reference ``ddp_gpus.py:45``).
- every shard is equal-length (wrap-padded), so all devices/processes run the
  same step count — the SPMD deadlock-freedom requirement.

For the 01 lesson (``nn.DataParallel``: one *global* batch of 32 scattered
4 x 8, reference ``01.data_parallel.ipynb`` cell 16) pass
``batch_mode="global"``.

Multi-host: batches are materialized with ``jax.make_array_from_callback`` —
each process gathers only the rows for its addressable shards, so no host ever
holds the global batch. This is the DCN-free input path: host RAM -> local HBM.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from pytorch_distributed_training_tutorials_tpu.data.datasets import ArrayDataset
from pytorch_distributed_training_tutorials_tpu.data.native import gather_rows
from pytorch_distributed_training_tutorials_tpu.data.sampler import DistributedSampler
from pytorch_distributed_training_tutorials_tpu.parallel.mesh import DATA_AXIS


class ShardedLoader:
    """Iterate mesh-sharded global batches from a host-resident dataset."""

    def __init__(
        self,
        dataset: ArrayDataset,
        batch_size: int,
        mesh: Mesh,
        *,
        axis: str = DATA_AXIS,
        batch_mode: str = "per_device",
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
        batch_spec: PartitionSpec | None = None,
        transform=None,
    ):
        if batch_mode not in ("per_device", "global"):
            raise ValueError(f"unknown batch_mode {batch_mode!r}")
        self.dataset = dataset
        self.mesh = mesh
        # transform runs ON DEVICE, after the batch lands (or inside the
        # compiled scan for the resident/chunked subclasses) — e.g. uint8
        # images to normalized float. Jitted here so dtype semantics match
        # the compiled paths exactly: numpy would promote
        # `x.astype(bfloat16) / 255.0` to float32; JAX weak-typing keeps
        # bfloat16 under jit.
        self.transform = transform
        self._jit_transform = jax.jit(transform) if transform else None
        self.axis = axis
        self.world = mesh.shape.get(axis, 1)
        if batch_mode == "global":
            if batch_size % self.world:
                raise ValueError(
                    f"global batch {batch_size} not divisible by "
                    f"{self.world} devices on axis {axis!r}"
                )
            self.per_device_batch = batch_size // self.world
        else:
            self.per_device_batch = batch_size
        self.global_batch = self.per_device_batch * self.world
        # batch_spec overrides the default dim-0-over-data layout, e.g.
        # P('data', 'seq') shards tokens over the sequence axis too (sequence
        # parallelism). Dim 0 must still map to `axis` — the steps/shard math
        # is defined by the data-parallel world size.
        spec = batch_spec if batch_spec is not None else PartitionSpec(axis)
        dim0 = tuple(spec)[0] if len(tuple(spec)) else None
        if self.world > 1 and dim0 != axis:
            raise ValueError(
                f"batch_spec dim 0 must map to the loader axis {axis!r} "
                f"(got {dim0!r}): steps/shard math assumes it"
            )
        # Per-array shardings: the spec truncates to each array's rank so a
        # (B, S) token array and a (B,) label array can share one batch_spec.
        self._shardings = [
            NamedSharding(mesh, PartitionSpec(*tuple(spec)[: a.ndim]))
            for a in dataset.arrays
        ]
        self.sharding = self._shardings[0]
        # One logical sampler per data-parallel replica; we enumerate all
        # replicas' shards from rank 0's view because under SPMD a single
        # controller feeds every local device.
        self._sampler = DistributedSampler(
            len(dataset), self.world, 0, shuffle=shuffle, seed=seed, drop_last=drop_last
        )
        # Steps per epoch: ceil over the padded per-replica shard, then the
        # shard itself is wrap-padded up to steps*batch so shapes are static.
        self.steps_per_epoch = -(-self._sampler.num_samples // self.per_device_batch)

    def set_epoch(self, epoch: int) -> None:
        """Reseed the shard permutation (reference ``ddp_gpus.py:45``)."""
        self._sampler.set_epoch(epoch)

    def __len__(self) -> int:
        return self.steps_per_epoch

    def _apply_transform(self, batch):
        if self._jit_transform is None:
            return batch
        if isinstance(batch, tuple):
            return self._jit_transform(*batch)
        return self._jit_transform(batch)

    def sample_batch(self):
        """A representative (host) sample for model init — the loader-owned
        seam that keeps consumers (Trainer) out of the dataset's internals.
        Without a ``transform``, returns full-length views (numpy slices are
        views, not copies) so init-time consumers can slice whatever row
        count their mesh needs; with one, a batch-sized slice is transformed
        first — init must see the shapes/dtypes training actually uses."""
        arrays = self.dataset.arrays
        sample = tuple(a[:] for a in arrays)
        if self._jit_transform is None:
            return sample if len(arrays) > 1 else sample[0]
        rows = min(len(self.dataset), self.global_batch)
        sample = tuple(a[:rows] for a in sample)
        # unwrap single-array datasets BEFORE transforming: the transform's
        # return is its own (arbitrary) pytree, not indexable by convention
        return self._apply_transform(
            sample if len(arrays) > 1 else sample[0]
        )

    def valid_mask(self, step: int) -> np.ndarray:
        """(global_batch,) bool mask, replica-major like the batch rows:
        True for real samples, False for wrap-padding duplicates.

        The reference's DistributedSampler *counts* its padded duplicates in
        every metric (it has no way to tell them apart downstream); here the
        loader computes the pad exactly — a slot is padding iff its position
        in the flat enumeration falls beyond the dataset, either in the
        sampler's wrap to equal shards or in the loader's wrap to a whole
        number of steps. Used by ``Trainer.evaluate`` for unbiased eval.
        """
        n = len(self.dataset)
        num_samples = self._sampler.num_samples
        lo = step * self.per_device_batch
        cols = np.arange(lo, lo + self.per_device_batch)
        ranks = np.arange(self.world)[:, None]  # (world, 1)
        # shards[r, c] = flat[c * world + r]; tiled columns (c >= num_samples)
        # and flat positions past the dataset are padding
        real = (cols[None, :] < num_samples) & (
            cols[None, :] * self.world + ranks < n
        )
        return real.reshape(-1)  # replica-major, matches __iter__ row order

    def _epoch_index_matrix(self) -> np.ndarray:
        """(world, steps * per_device_batch) index matrix for this epoch."""
        flat = self._sampler._global_indices()  # (num_samples * world,)
        # rank r's shard is flat[r::world]  -> rows of the transposed reshape
        shards = flat.reshape(self._sampler.num_samples, self.world).T
        need = self.steps_per_epoch * self.per_device_batch
        if shards.shape[1] < need:
            reps = -(-need // shards.shape[1])
            shards = np.tile(shards, (1, reps))[:, :need]
        return shards

    def __iter__(self):
        shards = self._epoch_index_matrix()
        n_arrays = len(self.dataset.arrays)
        gshape_tail = [a.shape[1:] for a in self.dataset.arrays]
        for step in range(self.steps_per_epoch):
            lo = step * self.per_device_batch
            step_idx = shards[:, lo : lo + self.per_device_batch]  # (world, bs)
            flat_idx = step_idx.reshape(-1)  # global batch order: replica-major

            def make(ai: int):
                arr = self.dataset.arrays[ai]
                gshape = (self.global_batch, *gshape_tail[ai])
                # memoize per row-slice: with non-batch axes sharded too
                # (e.g. P('data','seq')), the callback fires once per
                # (row, col) block — gather each row block only once
                gathered: dict = {}

                def cb(index):
                    key = (index[0].start, index[0].stop)
                    if key not in gathered:
                        # native multithreaded row gather (numpy fallback)
                        gathered[key] = gather_rows(arr, flat_idx[index[0]])
                    return np.ascontiguousarray(
                        gathered[key][(slice(None), *index[1:])]
                    )

                return jax.make_array_from_callback(
                    gshape, self._shardings[ai], cb
                )

            batch = tuple(make(ai) for ai in range(n_arrays))
            # unwrap single-array datasets BEFORE transforming (the
            # transform sees what the consumer sees)
            yield self._apply_transform(
                batch if n_arrays > 1 else batch[0]
            )
