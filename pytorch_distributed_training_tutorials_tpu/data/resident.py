"""Device-resident dataset: whole epochs as one compiled program.

The reference streams every batch host->device per step
(``ddp_gpus.py:46-48``: DataLoader iteration + ``.to(gpu)``). On TPU that
per-step Python dispatch is the wrong shape twice over: each step is a
separate XLA program launch, and on tunneled/remote runtimes the per-call
overhead compounds (measured: the per-step path degrades ~15x once a few
hundred dispatches are in flight). For datasets that fit in HBM — MNIST is
188 MB, CIFAR-10 614 MB, against 16 GB on one v5e — the TPU-idiomatic input
pipeline is:

1. put the dataset arrays on device **once** (replicated over the mesh),
2. compute the epoch's `(steps, global_batch)` index matrix on host with the
   exact DistributedSampler semantics (shuffle seeded by epoch, wrap-padded
   equal shards — ``sampler.py``),
3. run the whole epoch as **one** jitted ``lax.scan`` whose body gathers the
   step's batch from the resident arrays and applies the train step; the
   gather + normalize fuse into the step's first convolution.

This keeps every observable the reference defines — per-device batch-size
meaning, steps-per-epoch math, ``set_epoch`` reshuffle — while replacing ~235
program launches per MNIST epoch with one. ``ShardedLoader`` remains the
streaming path for datasets that don't fit.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from pytorch_distributed_training_tutorials_tpu.data.datasets import ArrayDataset
from pytorch_distributed_training_tutorials_tpu.data.loader import ShardedLoader


class DeviceResidentLoader(ShardedLoader):
    """A :class:`ShardedLoader` whose dataset lives in device memory.

    Iterating it yields batches like the parent (so everything written
    against the streaming loader still works), but trainers that know about
    ``device_arrays`` / :meth:`epoch_index_array` run the epoch as a single
    ``lax.scan`` instead.

    ``transform`` (optional) is applied to the gathered batch tuple *on
    device inside the compiled epoch* — e.g. uint8 images to normalized
    float: ``lambda x, y: (x.astype(jnp.float32) / 255.0, y)``.
    """

    def __init__(
        self,
        dataset: ArrayDataset,
        batch_size: int,
        mesh: Mesh,
        *,
        transform=None,
        **kwargs,
    ):
        if kwargs.get("batch_spec") is not None:
            raise NotImplementedError(
                "DeviceResidentLoader shards batches over the data axis only; "
                "use ShardedLoader for custom batch_specs (e.g. sequence "
                "parallelism)"
            )
        super().__init__(dataset, batch_size, mesh, transform=transform, **kwargs)
        # Replicated residency: every device holds the dataset, so the
        # per-step gather is local (no collectives). Tutorial-scale datasets
        # are far smaller than HBM; shard-over-data residency is the natural
        # extension when they aren't.
        rep = NamedSharding(mesh, PartitionSpec())
        self.device_arrays = tuple(
            jax.device_put(a, rep) for a in dataset.arrays
        )

    def epoch_index_array(self, epoch: int) -> jax.Array:
        """The epoch's ``(steps, global_batch)`` int32 index matrix, on
        device, sharded so each data-parallel replica holds exactly its own
        per-step indices (dim 1 over the data axis, replica-major order —
        identical to the streaming loader's batch layout)."""
        self.set_epoch(epoch)
        shards = self._epoch_index_matrix()  # (world, steps * bs)
        idx = (
            shards.reshape(self.world, self.steps_per_epoch, self.per_device_batch)
            .transpose(1, 0, 2)
            .reshape(self.steps_per_epoch, self.global_batch)
            .astype(np.int32)
        )
        sharding = NamedSharding(self.mesh, PartitionSpec(None, self.axis))
        return jax.device_put(idx, sharding)
