"""FSDP / ZeRO: parameter + optimizer-state sharding over the data axis.

Beyond-parity capability. The reference *declares* deepspeed and
megatron-fsdp in its environment (``/root/reference/environment.yml:62-63``)
but never imports either — SURVEY.md section 2 records FSDP/ZeRO as absent.
This module makes the capability real, the TPU way:

- **ZeRO-1/2** (optimizer-state + gradient sharding) and **ZeRO-3 / FSDP**
  (parameter sharding with gather-at-use) collapse into *one* sharding
  recipe under GSPMD: annotate every large parameter (and, via the same
  shape-driven rule, its optimizer-state moments) as sharded over the
  ``data`` mesh axis. XLA's sharding propagation then compiles exactly the
  FSDP schedule — an ``all-gather`` of each weight immediately before its
  use in forward/backward and a ``reduce-scatter`` of its gradient — and
  overlaps both with compute, the hand-written overlap torch FSDP
  implements in its pre-forward/post-backward hooks.
- No wrapper module, no hooks, no flattening: models stay plain pytrees.
  The strategy object is a drop-in for
  :class:`.data_parallel.DataParallel` in the Trainer (same
  ``variable_shardings`` / ``shard_state`` / ``shard_batch`` interface).

Per-parameter HBM drops from ``P`` (DDP: every device holds every param,
moment, and gradient) to ``P / world`` for everything sharded — the ZeRO-3
memory curve.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from pytorch_distributed_training_tutorials_tpu.parallel.mesh import DATA_AXIS
from pytorch_distributed_training_tutorials_tpu.utils.tree import keystr


def shard_dim_for(shape: tuple[int, ...], world: int, min_size: int) -> int | None:
    """Pick the dimension to shard over ``world`` devices, or None.

    The *largest* dimension divisible by ``world`` wins (ties -> the earliest),
    maximizing the per-device memory saving; arrays smaller than ``min_size``
    elements stay replicated (sharding a bias of 10 floats buys nothing and
    costs an all-gather dispatch).
    """
    if not shape:
        return None
    total = 1
    for d in shape:
        total *= d
    if total < min_size:
        return None
    best: int | None = None
    for i, d in enumerate(shape):
        if d % world == 0 and (best is None or d > shape[best]):
            best = i
    return best


class FSDP:
    """Shape-driven ZeRO-3 sharding strategy over one mesh axis.

    Usage (drop-in for ``DataParallel`` in the Trainer)::

        mesh = create_mesh()                     # {'data': N}
        trainer = Trainer(model, loader, tx, strategy=FSDP(mesh))

    Every parameter (and optimizer moment — same shapes, same rule) with at
    least ``min_size`` elements and a dimension divisible by the axis size is
    sharded on that dimension; the rest replicate. Batches shard over the
    same axis, so gradients come out reduce-scattered rather than
    all-reduced — ZeRO's bandwidth trade.
    """

    def __init__(
        self,
        mesh: Mesh,
        axis: str = DATA_AXIS,
        *,
        min_size: int = 1024,
    ):
        self.mesh = mesh
        self.axis = axis
        self.min_size = min_size
        self.batch_sharding = NamedSharding(mesh, PartitionSpec(axis))
        self._replicated = NamedSharding(mesh, PartitionSpec())

    @property
    def num_devices(self) -> int:
        return self.mesh.shape.get(self.axis, 1)

    def spec_for(self, shape: tuple[int, ...]) -> PartitionSpec:
        dim = shard_dim_for(tuple(shape), self.num_devices, self.min_size)
        if dim is None:
            return PartitionSpec()
        parts: list = [None] * len(shape)
        parts[dim] = self.axis
        return PartitionSpec(*parts)

    def _leaf_sharding(self, leaf) -> NamedSharding:
        shape = tuple(getattr(leaf, "shape", ()) or ())
        if not shape:
            return self._replicated
        return NamedSharding(self.mesh, self.spec_for(shape))

    def variable_shardings(self, abstract_variables):
        """Pytree of NamedShardings (the ``out_shardings`` for a sharded
        ``model.init``) — every leaf placed by shape alone."""
        return jax.tree_util.tree_map(self._leaf_sharding, abstract_variables)

    def shard_state(self, state):
        """Place an existing train state: params *and* optimizer moments
        follow the shape rule (ZeRO-1's optimizer sharding falls out of
        ZeRO-3's because optax moments mirror param shapes)."""
        return jax.tree_util.tree_map(
            lambda leaf: jax.device_put(leaf, self._leaf_sharding(leaf)),
            state,
        )

    def shard_batch(self, batch):
        return jax.device_put(batch, self.batch_sharding)

    def audit(self, params) -> list[str]:
        """Path -> spec lines (the 03-notebook placement-audit twin)."""
        lines: list[str] = []

        def visit(kp, leaf):
            path = keystr(kp)
            spec = self.spec_for(tuple(leaf.shape))
            lines.append(f"{path}: {tuple(leaf.shape)} -> {tuple(spec)}")

        jax.tree_util.tree_map_with_path(visit, params)
        return lines
