"""FSDP / ZeRO: parameter + optimizer-state sharding over the data axis.

Beyond-parity capability. The reference *declares* deepspeed and
megatron-fsdp in its environment (``/root/reference/environment.yml:62-63``)
but never imports either — SURVEY.md section 2 records FSDP/ZeRO as absent.
This module makes the capability real, the TPU way:

- **ZeRO-1/2** (optimizer-state + gradient sharding) and **ZeRO-3 / FSDP**
  (parameter sharding with gather-at-use) collapse into *one* sharding
  recipe under GSPMD: annotate every large parameter (and, via the same
  shape-driven rule, its optimizer-state moments) as sharded over the
  ``data`` mesh axis. XLA's sharding propagation then compiles exactly the
  FSDP schedule — an ``all-gather`` of each weight immediately before its
  use in forward/backward and a ``reduce-scatter`` of its gradient — and
  overlaps both with compute, the hand-written overlap torch FSDP
  implements in its pre-forward/post-backward hooks.
- No wrapper module, no hooks, no flattening: models stay plain pytrees.
  The strategy object is a drop-in for
  :class:`.data_parallel.DataParallel` in the Trainer (same
  ``variable_shardings`` / ``shard_state`` / ``shard_batch`` interface).

Per-parameter HBM drops from ``P`` (DDP: every device holds every param,
moment, and gradient) to ``P / world`` for everything sharded — the ZeRO-3
memory curve.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from pytorch_distributed_training_tutorials_tpu.parallel.mesh import DATA_AXIS
from pytorch_distributed_training_tutorials_tpu.utils.tree import keystr


def shard_dim_for(
    shape: tuple[int, ...],
    world: int,
    min_size: int,
    exclude: tuple[int, ...] = (),
) -> int | None:
    """Pick the dimension to shard over ``world`` devices, or None.

    The *largest* dimension divisible by ``world`` wins (ties -> the earliest),
    maximizing the per-device memory saving; arrays smaller than ``min_size``
    elements stay replicated (sharding a bias of 10 floats buys nothing and
    costs an all-gather dispatch). ``exclude`` lists dimensions already
    claimed by another axis (HybridFSDP's TP pass).
    """
    if not shape:
        return None
    total = 1
    for d in shape:
        total *= d
    if total < min_size:
        return None
    best: int | None = None
    for i, d in enumerate(shape):
        if i in exclude:
            continue
        if d % world == 0 and (best is None or d > shape[best]):
            best = i
    return best


class FSDP:
    """Shape-driven ZeRO-3 sharding strategy over one mesh axis.

    Usage (drop-in for ``DataParallel`` in the Trainer)::

        mesh = create_mesh()                     # {'data': N}
        trainer = Trainer(model, loader, tx, strategy=FSDP(mesh))

    Every parameter (and optimizer moment — same shapes, same rule) with at
    least ``min_size`` elements and a dimension divisible by the axis size is
    sharded on that dimension; the rest replicate. Batches shard over the
    same axis, so gradients come out reduce-scattered rather than
    all-reduced — ZeRO's bandwidth trade.
    """

    def __init__(
        self,
        mesh: Mesh,
        axis: str = DATA_AXIS,
        *,
        min_size: int = 1024,
    ):
        self.mesh = mesh
        self.axis = axis
        self.min_size = min_size
        self.batch_sharding = NamedSharding(mesh, PartitionSpec(axis))
        self._replicated = NamedSharding(mesh, PartitionSpec())

    @property
    def num_devices(self) -> int:
        return self.mesh.shape.get(self.axis, 1)

    def spec_for(self, shape: tuple[int, ...]) -> PartitionSpec:
        dim = shard_dim_for(tuple(shape), self.num_devices, self.min_size)
        if dim is None:
            return PartitionSpec()
        parts: list = [None] * len(shape)
        parts[dim] = self.axis
        return PartitionSpec(*parts)

    def _leaf_sharding(self, leaf, key_path=None) -> NamedSharding:
        """Placement for one leaf. Base FSDP is shape-driven and ignores
        ``key_path``; subclasses (HybridFSDP) consult it."""
        shape = tuple(getattr(leaf, "shape", ()) or ())
        if not shape:
            return self._replicated
        return NamedSharding(self.mesh, self.spec_for(shape))

    def variable_shardings(self, abstract_variables):
        """Pytree of NamedShardings (the ``out_shardings`` for a sharded
        ``model.init``)."""
        return jax.tree_util.tree_map_with_path(
            lambda kp, leaf: self._leaf_sharding(leaf, kp),
            abstract_variables,
        )

    def shard_state(self, state):
        """Place an existing train state: params *and* optimizer moments
        follow the same rule (ZeRO-1's optimizer sharding falls out of
        ZeRO-3's because optax moments mirror param shapes)."""
        return jax.tree_util.tree_map_with_path(
            lambda kp, leaf: jax.device_put(
                leaf, self._leaf_sharding(leaf, kp)
            ),
            state,
        )

    def shard_batch(self, batch):
        return jax.device_put(batch, self.batch_sharding)

    def audit(self, params) -> list[str]:
        """Path -> spec lines (the 03-notebook placement-audit twin)."""
        lines: list[str] = []

        def visit(kp, leaf):
            spec = self._leaf_sharding(leaf, kp).spec
            lines.append(
                f"{keystr(kp)}: {tuple(leaf.shape)} -> {tuple(spec)}"
            )

        jax.tree_util.tree_map_with_path(visit, params)
        return lines


class HybridFSDP(FSDP):
    """2D sharding: tensor-parallel rules over ``model``, FSDP over ``data``.

    The production llama-style layout: each weight is first matched against
    the TP rules (:data:`..models.transformer.TP_RULES`-style path regexes
    -> specs over the ``model`` axis); whatever dimension the rules leave
    unsharded is then eligible for FSDP's shape-driven shard over ``data``.
    Rule-matched-and-fully-replicated or unmatched leaves fall back to plain
    FSDP. Gradient reduce-scatter rides ``data``; activation collectives
    ride ``model`` (lay ``model`` innermost so they stay on ICI).

    Drop-in for the other strategies in the Trainer::

        mesh = create_mesh({'data': D, 'model': M})
        strategy = HybridFSDP(mesh, TP_RULES)
    """

    def __init__(
        self,
        mesh: Mesh,
        rules,
        *,
        axis: str = DATA_AXIS,
        model_axis: str = "model",
        min_size: int = 1024,
    ):
        super().__init__(mesh, axis, min_size=min_size)
        from pytorch_distributed_training_tutorials_tpu.parallel.tensor_parallel import (
            spec_for_path,
        )

        self.rules = list(rules)
        self.model_axis = model_axis
        self._spec_for_path = spec_for_path

    def _leaf_sharding(self, leaf, key_path=None) -> NamedSharding:
        shape = tuple(getattr(leaf, "shape", ()) or ())
        if not shape:
            return NamedSharding(self.mesh, PartitionSpec())
        path = keystr(key_path) if key_path is not None else ""
        tp_spec = tuple(
            self._spec_for_path(
                path, len(shape), self.rules, mesh=self.mesh
            )
        )
        tp_spec = tp_spec + (None,) * (len(shape) - len(tp_spec))
        # FSDP pass: shard the largest dim the TP rules left unclaimed
        claimed = tuple(i for i, p in enumerate(tp_spec) if p is not None)
        best = shard_dim_for(
            shape, self.num_devices, self.min_size, exclude=claimed
        )
        parts = list(tp_spec)
        if best is not None:
            parts[best] = self.axis
        return NamedSharding(self.mesh, PartitionSpec(*parts))

    def spec_for(self, shape):  # shape-only: ambiguous for 2D layouts
        raise NotImplementedError(
            "HybridFSDP placements depend on the param path, not shape "
            "alone — use variable_shardings/audit"
        )
