"""Published-checkpoint ingestion: HF-layout (safetensors) Llama weights.

The reference's flagship lesson loads a *published* pretrained Llama-7B
from the HF hub with quantize-on-load, streaming 33 shards
(``/root/reference/03.model_parallel.ipynb:52-57``). The orbax path
(:mod:`.auto`) covers checkpoints this framework wrote itself; this module
closes the external-format gap: a directory in the Hugging Face layout —
``config.json`` + ``model.safetensors`` (or ``model.safetensors.index.json``
plus shards) — loads into a :class:`..models.transformer.TransformerLM`
parameter tree, **streaming one tensor at a time** (host peak = the largest
single tensor plus the accumulated output tree, the same bound
:func:`.auto.load_quantized` gives orbax checkpoints), optionally
quantizing each matmul weight to int8 as it is read (the
``load_in_8bit=True`` twin) — entirely offline, no network.

The safetensors container is parsed directly (8-byte little-endian header
length, JSON header mapping tensor name -> dtype/shape/offsets, then raw
little-endian data) so per-tensor reads are plain ``seek`` + ``read`` —
no safetensors package dependency, nothing but numpy.

Weight-layout conventions handled (torch ``nn.Linear`` stores ``(out, in)``;
flax ``nn.Dense`` kernels are ``(in, out)``):

- ``model.embed_tokens.weight`` (V, d)        -> ``tok_emb/embedding`` (V, d)
- ``...self_attn.{q,k,v}_proj.weight`` (H*D, d) -> ``block_i/attn/{q,k,v}_proj/kernel``
  (d, H, D): transpose then split heads
- ``...self_attn.o_proj.weight`` (d, H*D)     -> ``block_i/attn/o_proj/kernel``
  (H, D, d): transpose then split heads
- ``...mlp.{gate,up}_proj.weight`` (ff, d)    -> ``(d, ff)`` transpose
- ``...mlp.down_proj.weight`` (d, ff)         -> ``(ff, d)`` transpose
- ``input_layernorm`` / ``post_attention_layernorm`` / ``model.norm``
  -> ``attn_norm`` / ``mlp_norm`` / ``final_norm`` scales
- ``lm_head.weight`` (V, d) -> ``lm_head/kernel`` (d, V); absent when
  ``tie_word_embeddings`` — then the embedding matrix is reused.

The rotary convention matches by construction: HF checkpoints are permuted
for the ``rotate_half`` formulation, which is exactly
:func:`..models.transformer.apply_rope`'s ``[:half] / [half:]`` split.
Logit parity against ``transformers.LlamaForCausalLM`` is pinned by
``tests/test_hf_llama.py`` (torch is the oracle, as in test_sampler.py).
"""

from __future__ import annotations

import json
import os
import struct

import numpy as np

# safetensors dtype tag -> numpy dtype. BF16 needs ml_dtypes (a jax
# dependency, always present here); torch's save path emits "F32"/"F16"/
# "BF16" for float checkpoints.
_DTYPES = {
    "F64": np.float64,
    "F32": np.float32,
    "F16": np.float16,
    "I64": np.int64,
    "I32": np.int32,
    "I16": np.int16,
    "I8": np.int8,
    "U8": np.uint8,
    "BOOL": np.bool_,
}


def _np_dtype(tag: str):
    if tag == "BF16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    try:
        return np.dtype(_DTYPES[tag])
    except KeyError:
        raise ValueError(f"unsupported safetensors dtype {tag!r}") from None


class SafetensorsFile:
    """Lazy per-tensor reader for one ``.safetensors`` file.

    ``get(name)`` seeks to that tensor's byte range and reads it alone —
    the file is never mapped or read whole, so host memory is bounded by
    the largest single tensor regardless of checkpoint size.
    """

    def __init__(self, path: str | os.PathLike):
        self.path = os.fspath(path)
        with open(self.path, "rb") as f:
            (header_len,) = struct.unpack("<Q", f.read(8))
            header = json.loads(f.read(header_len))
        self._data_start = 8 + header_len
        header.pop("__metadata__", None)
        self.tensors = {
            name: (
                str(info["dtype"]),
                tuple(info["shape"]),
                tuple(info["data_offsets"]),
            )
            for name, info in header.items()
        }

    def keys(self):
        return self.tensors.keys()

    def get(self, name: str) -> np.ndarray:
        dtype_tag, shape, (start, end) = self.tensors[name]
        dtype = _np_dtype(dtype_tag)
        with open(self.path, "rb") as f:
            f.seek(self._data_start + start)
            buf = f.read(end - start)
        arr = np.frombuffer(buf, dtype=dtype).reshape(shape)
        return arr


class HFCheckpoint:
    """A HF-layout checkpoint directory: tensor name -> file resolution.

    Handles the single-file layout (``model.safetensors``), the sharded
    layout (``model.safetensors.index.json`` with a ``weight_map``), and a
    bare glob of ``*.safetensors`` shards (each shard's own header lists
    its tensors — the index file is an optimization, not a requirement).
    """

    def __init__(self, path: str | os.PathLike):
        self.dir = os.fspath(path)
        index = os.path.join(self.dir, "model.safetensors.index.json")
        self._files: dict[str, SafetensorsFile] = {}
        self._where: dict[str, str] = {}
        if os.path.exists(index):
            with open(index) as f:
                weight_map = json.load(f)["weight_map"]
            for name, fname in weight_map.items():
                self._where[name] = os.path.join(self.dir, fname)
        else:
            shards = sorted(
                fn
                for fn in os.listdir(self.dir)
                if fn.endswith(".safetensors")
            )
            if not shards:
                raise FileNotFoundError(
                    f"no .safetensors files under {self.dir}"
                )
            for fn in shards:
                full = os.path.join(self.dir, fn)
                for name in SafetensorsFile(full).keys():
                    self._where[name] = full

    def __contains__(self, name: str) -> bool:
        return name in self._where

    def keys(self):
        return self._where.keys()

    def get(self, name: str) -> np.ndarray:
        path = self._where[name]
        f = self._files.get(path)
        if f is None:
            f = self._files[path] = SafetensorsFile(path)
        return f.get(name)


def config_from_hf(path: str | os.PathLike, **overrides):
    """Build a :class:`TransformerConfig` from a checkpoint's ``config.json``.

    Maps the HF Llama field names (hidden_size, num_hidden_layers,
    num_attention_heads, num_key_value_heads, intermediate_size,
    max_position_embeddings, rope_theta, rms_norm_eps) onto the framework
    config. ``overrides`` win — e.g. ``max_seq_len=2080`` to serve with a
    smaller cache than the model's trained maximum.
    """
    from pytorch_distributed_training_tutorials_tpu.models.transformer import (
        TransformerConfig,
    )

    with open(os.path.join(os.fspath(path), "config.json")) as f:
        hf = json.load(f)
    act = hf.get("hidden_act", "silu")
    if act not in ("silu", "swish"):
        raise ValueError(
            f"unsupported hidden_act {act!r}: TransformerLM's FFN is "
            "SwiGLU (silu) — loading this checkpoint would silently "
            "change the activation"
        )
    if hf.get("rope_scaling") is not None:
        raise ValueError(
            "rope_scaling is not supported: apply_rope implements plain "
            "rotary embedding; a scaled-rope checkpoint would produce "
            "wrong positions beyond the original context"
        )
    kw = dict(
        vocab_size=hf["vocab_size"],
        d_model=hf["hidden_size"],
        n_layers=hf["num_hidden_layers"],
        n_heads=hf["num_attention_heads"],
        n_kv_heads=hf.get("num_key_value_heads"),
        d_ff=hf["intermediate_size"],
        max_seq_len=hf.get("max_position_embeddings", 2048),
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        norm_eps=float(hf.get("rms_norm_eps", 1e-6)),
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


def _llama_layer_entries(i: int, cfg):
    """(our relative path, hf tensor name, transform) for layer ``i``.

    Transforms take the raw (already dtype-cast) numpy array to the flax
    kernel layout. ``d`` = d_model, ``h``/``kv`` = query/KV head counts.
    """
    d, h, kv, hd = cfg.d_model, cfg.n_heads, cfg.kv_heads, cfg.head_dim
    pre = f"model.layers.{i}."

    def qkv(heads):
        return lambda w: np.ascontiguousarray(w.T).reshape(d, heads, hd)

    def o(w):
        return np.ascontiguousarray(w.T).reshape(h, hd, d)

    def t(w):
        return np.ascontiguousarray(w.T)

    return [
        (("attn", "q_proj", "kernel"), pre + "self_attn.q_proj.weight", qkv(h)),
        (("attn", "k_proj", "kernel"), pre + "self_attn.k_proj.weight", qkv(kv)),
        (("attn", "v_proj", "kernel"), pre + "self_attn.v_proj.weight", qkv(kv)),
        (("attn", "o_proj", "kernel"), pre + "self_attn.o_proj.weight", o),
        (("attn_norm", "scale"), pre + "input_layernorm.weight", None),
        (("mlp", "gate_proj", "kernel"), pre + "mlp.gate_proj.weight", t),
        (("mlp", "up_proj", "kernel"), pre + "mlp.up_proj.weight", t),
        (("mlp", "down_proj", "kernel"), pre + "mlp.down_proj.weight", t),
        (("mlp_norm", "scale"), pre + "post_attention_layernorm.weight", None),
    ]


def _set(tree: dict, path: tuple, leaf) -> None:
    node = tree
    for k in path[:-1]:
        node = node.setdefault(k, {})
    node[path[-1]] = leaf


def load_hf_llama(
    path: str | os.PathLike,
    cfg=None,
    *,
    dtype=np.float32,
    quantize: bool = False,
    scan_layers: bool | None = None,
    strict: bool = True,
    materialize: bool = True,
):
    """Load a HF-layout Llama checkpoint into a TransformerLM param tree.

    Returns ``(cfg, params)``. ``cfg`` defaults to :func:`config_from_hf`
    on the directory's ``config.json``. Tensors stream one at a time:
    read -> cast to ``dtype`` -> transpose/reshape to the flax layout ->
    (optionally) quantize to int8 — the float checkpoint is never resident
    in full, matching the reference's 33-shards-through-bitsandbytes bound
    and :func:`.auto.load_quantized`'s RSS test.

    ``quantize=True`` emits the :class:`..ops.quant.Int8Dense` serving
    layout (``{'q', 'scale'}`` per matmul weight, norms/embeddings float)
    — serve with ``dataclasses.replace(cfg, quantized=True)``.
    ``scan_layers`` (default: follow ``cfg.scan_layers``) stacks the L
    per-layer subtrees under ``layers/block/...`` with a leading layer
    axis — the one-program layout (DECODE_r04.md) — stacking int8 leaves
    (4x smaller than float), never the float originals.

    ``strict=True`` (default) fails loud if the checkpoint contains
    tensors the mapping did not consume — e.g. ``attention_bias=True``
    checkpoints store ``*.bias`` tensors TransformerLM has no slot for;
    dropping them silently would serve wrong logits. ``materialize=True``
    returns device-resident jax arrays (host-numpy leaves re-upload on
    every consuming launch — CLAUDE.md / DECODE_r04.md); pass ``False``
    to keep host numpy for tree surgery before placement.
    """
    ckpt = HFCheckpoint(path)
    if cfg is None:
        cfg = config_from_hf(path)
    if scan_layers is None:
        scan_layers = cfg.scan_layers
    consumed: set[str] = set()

    if quantize:
        from pytorch_distributed_training_tutorials_tpu.models.transformer import (
            _quantize_kernel,
        )
        from pytorch_distributed_training_tutorials_tpu.ops.quant import (
            quantize_int8,
        )

    def fetch(name: str, transform):
        consumed.add(name)
        arr = ckpt.get(name).astype(dtype)
        if transform is not None:
            arr = transform(arr)
        return arr

    def maybe_quant(our_path: tuple, leaf):
        if quantize and our_path[-1] == "kernel" and our_path[0] != "tok_emb":
            part = _quantize_kernel(our_path[-2], leaf, quantize_int8)
            return {"q": part["q"], "scale": part["scale"]}
        return leaf

    params: dict = {}
    _set(params, ("tok_emb", "embedding"),
         fetch("model.embed_tokens.weight", None))
    _set(params, ("final_norm", "scale"), fetch("model.norm.weight", None))
    if "lm_head.weight" in ckpt:
        head = fetch("lm_head.weight", lambda w: np.ascontiguousarray(w.T))
    else:  # tie_word_embeddings: reuse the embedding matrix
        head = np.ascontiguousarray(params["tok_emb"]["embedding"].T)
    q_head = maybe_quant(("lm_head", "kernel"), head)
    if isinstance(q_head, dict):
        params["lm_head"] = q_head
    else:
        _set(params, ("lm_head", "kernel"), q_head)

    layers = []
    for i in range(cfg.n_layers):
        block: dict = {}
        for our_path, hf_name, transform in _llama_layer_entries(i, cfg):
            leaf = maybe_quant(our_path, fetch(hf_name, transform))
            if isinstance(leaf, dict):
                _set(block, our_path[:-1] + ("q",), leaf["q"])
                _set(block, our_path[:-1] + ("scale",), leaf["scale"])
            else:
                _set(block, our_path, leaf)
        layers.append(block)

    if strict:
        leftover = sorted(set(ckpt.keys()) - consumed)
        if leftover:
            raise ValueError(
                f"{len(leftover)} checkpoint tensor(s) were not consumed "
                f"by the Llama mapping (first few: {leftover[:5]}) — "
                "loading would silently drop weights. Pass strict=False "
                "only if you know they are genuinely unused."
            )

    if scan_layers:
        import jax
        import jax.numpy as jnp

        params["layers"] = {
            "block": jax.tree_util.tree_map(
                lambda *leaves: jnp.stack([jnp.asarray(x) for x in leaves]),
                *layers,
            )
        }
    else:
        for i, block in enumerate(layers):
            params[f"block_{i}"] = block
    if materialize:
        from pytorch_distributed_training_tutorials_tpu.utils.tree import (
            device_materialize,
        )

        params = device_materialize(params)
    return cfg, params
