"""Ring attention: sequence/context parallelism over the ``seq`` mesh axis.

First-class long-context capability (the reference has *no* attention at all
in repo-authored code — SURVEY.md section 5.7 — so this is beyond-parity by
design; the mesh reserved the ``seq`` axis for it from day one). The design is
the TPU-native ring: every device holds one sequence block of Q/K/V; K/V
blocks rotate around the ring with ``lax.ppermute`` over ICI while each
device folds the incoming block into its queries' attention state with the
numerically-stable online-softmax update (running max ``m``, normalizer
``l``, unnormalized accumulator ``o`` — the blockwise/flash decomposition).
Peak memory per device is O(S/n * S/n) scores instead of O(S^2): sequence
length scales linearly with the ring size. The bound holds through
**backward** too: each hop is ``jax.checkpoint``-ed (see :func:`_ring_hop`),
so ``jax.grad`` re-derives score blocks instead of storing one per hop.

The ring is unrolled (ring size is a static mesh property), so XLA can
overlap each step's ppermute with the previous step's matmuls — communication
hides behind compute exactly like the NCCL bucket overlap the reference's DDP
relies on, but compiled rather than hand-scheduled.

Composes with the other axes: batch stays sharded on ``data``, heads on
``model`` (heads are independent in attention, so tensor parallelism passes
straight through), sequence on ``seq``. Plug the returned function into
:class:`..models.transformer.TransformerConfig` via ``attention_fn``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from pytorch_distributed_training_tutorials_tpu.utils.compat import (
    pcast_varying,
    shard_map_nocheck,
)
from pytorch_distributed_training_tutorials_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
)

# plain float, NOT jnp.float32(...): creating a jax array at import time
# would initialize the XLA backend, which breaks multi-process workers that
# must call jax.distributed.initialize() before any JAX computation
NEG_INF = float("-inf")


def _qkv_spec(mesh: Mesh, data_axis: str, seq_axis: str, model_axis: str) -> P:
    """(B, S, H, D) spec using only the axes the mesh actually has."""
    has = mesh.shape
    return P(
        data_axis if data_axis in has else None,
        seq_axis if seq_axis in has else None,
        model_axis if model_axis in has else None,
        None,
    )


def _fold_block(carry, xs, qb, q_pos, scale):
    """Fold ONE key sub-block into the online-softmax state — the flash-
    attention inner body, shared by every hop."""
    o, l, m = carry
    kb, vb, k_pos = xs
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", qb, kb, preferred_element_type=jnp.float32
    ) * scale
    causal = q_pos[:, None] >= k_pos[None, :]  # (s_blk, blk) global
    scores = jnp.where(causal[None, None], scores, NEG_INF)

    m_new = jnp.maximum(m, scores.max(axis=-1))
    # m_new is finite from t=0 on: src==idx at t=0, so every query row sees
    # its own diagonal key first. (If the rotation start is ever changed,
    # -inf rows would need exp-of-nan guards here.)
    # (at t=0, corr = exp(-inf - finite) = 0 exactly, zeroing the empty
    # initial accumulators — no NaN guard needed)
    p = jnp.exp(scores - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l = l * corr + p.sum(axis=-1)
    o = o * corr[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, vb.astype(jnp.float32)
    )
    return (o, l, m_new), None


@partial(jax.checkpoint, static_argnums=(9,))
def _ring_hop(qb, k_t, v_t, o, l, m, q_pos, k_pos, scale, block=512):
    """One ring hop: fold an incoming K/V block into the online-softmax
    state ``(o, l, m)`` — itself BLOCKWISE (the flash decomposition), so
    even the per-hop score tile is (s_blk, block), not (s_blk, s_blk).

    Two memory properties compose here:

    - ``jax.checkpoint`` on the hop makes the module's O((S/n)^2)-or-
      better claim true *through backward*: without it, ``jax.grad`` over
      the unrolled ring stores every hop's probability blocks — n of
      them, i.e. O(S^2/n) per device, roughly the thing the ring exists
      to avoid (``tests/test_ring_attention.py`` pins the residual
      footprint vs dense attention).
    - the inner ``lax.scan`` over ``block``-sized key sub-blocks (each
      fold itself checkpointed) bounds LIVE memory to O(s_blk * block)
      per device in forward and in the hop's rematerialized backward —
      the same blockwise-online-softmax structure as the single-chip
      Pallas kernel (``ops/flash_attention.py``), here as compiler-
      friendly scanned jnp so XLA can still overlap the ring ppermute
      with compute.
    """
    s_blk = k_t.shape[1]
    block = min(block, s_blk)
    if s_blk % block:
        # ragged tails fall back to one fold over the whole hop block
        block = s_blk
    nb = s_blk // block

    def to_blocks(a):  # (b, s_blk, h, d) -> (nb, b, block, h, d)
        return a.reshape(
            a.shape[0], nb, block, *a.shape[2:]
        ).swapaxes(0, 1)

    xs = (to_blocks(k_t), to_blocks(v_t), k_pos.reshape(nb, block))
    fold = jax.checkpoint(
        lambda c, x: _fold_block(c, x, qb, q_pos, scale),
        prevent_cse=False,
    )
    (o, l, m), _ = jax.lax.scan(fold, (o, l, m), xs)
    return o, l, m


def make_ring_attention(
    mesh: Mesh,
    *,
    seq_axis: str = SEQ_AXIS,
    data_axis: str = DATA_AXIS,
    model_axis: str = MODEL_AXIS,
    hop_block: int = 512,
):
    """Build a causal ``attention_fn(q, k, v) -> out`` ((B, S, H, D) each)
    that computes attention sequence-parallel over ``mesh[seq_axis]``.

    Numerically equivalent to :func:`..models.transformer.causal_attention`
    (verified to float tolerance in ``tests/test_ring_attention.py``); the
    difference is where the bytes live: no device ever materializes the full
    (S, S) score matrix or the full K/V. ``hop_block`` bounds the per-hop
    score tile (see :func:`_ring_hop`): live score memory is
    O(s_blk * hop_block) per device, forward and backward.
    """
    if seq_axis not in mesh.shape:
        raise ValueError(f"mesh has no {seq_axis!r} axis: {dict(mesh.shape)}")
    n = mesh.shape[seq_axis]
    spec = _qkv_spec(mesh, data_axis, seq_axis, model_axis)

    # checking off: 0.4.x's check_rep cannot reconcile the fresh (o, l, m)
    # scan carry with the ppermute-fed fold outputs (the vma-era fix is the
    # pcast tag below; utils.compat owns both sides of the seam)
    @partial(
        shard_map_nocheck,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    def ring_attention(qb: jax.Array, kb: jax.Array, vb: jax.Array) -> jax.Array:
        b, s_blk, h, d = qb.shape
        idx = jax.lax.axis_index(seq_axis)
        q_pos = idx * s_blk + jnp.arange(s_blk)  # global positions of my queries

        scale = 1.0 / jnp.sqrt(jnp.float32(d))
        o = jnp.zeros((b, h, s_blk, d), jnp.float32)
        l = jnp.zeros((b, h, s_blk), jnp.float32)
        # strong f32 (a weak-typed full() would flip type across the
        # blockwise scan carry)
        m = jnp.full((b, h, s_blk), NEG_INF, jnp.float32)
        # the hop's inner scan requires carry types stable across
        # iterations, including the varying-manual-axis tags the folded
        # (sharded) K/V blocks impart — mark the fresh state varying over
        # every mesh axis up front (the fold output's tag is the union of
        # the carry's and the sharded operands'). Identity on jax without
        # the vma machinery (utils.compat owns the version seam).
        o, l, m = pcast_varying((o, l, m), mesh.axis_names)

        k_t, v_t = kb, vb
        shift = [(j, (j + 1) % n) for j in range(n)]
        for t in range(n):  # static ring, unrolled for ppermute/compute overlap
            # after t hops I hold the block that started on device (idx - t)
            src = (idx - t) % n
            k_pos = src * s_blk + jnp.arange(s_blk)
            o, l, m = _ring_hop(
                qb, k_t, v_t, o, l, m, q_pos, k_pos, scale, hop_block
            )
            if t < n - 1:
                k_t, v_t = jax.lax.ppermute(
                    (k_t, v_t), seq_axis, perm=shift
                )

        # causal => every query row saw at least its own diagonal block
        out = o / l[..., None]
        return out.transpose(0, 2, 1, 3).astype(qb.dtype)

    # generate()'s prefill checks this: ring needs S to divide the seq
    # axis, so non-divisible prompt lengths prefill via the dense path
    # (divisible ones keep the ring and its memory bound)
    ring_attention.requires_seq_divisible = n
    return ring_attention
