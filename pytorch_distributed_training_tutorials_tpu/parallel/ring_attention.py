"""Ring attention: sequence/context parallelism over the ``seq`` mesh axis.

First-class long-context capability (the reference has *no* attention at all
in repo-authored code — SURVEY.md section 5.7 — so this is beyond-parity by
design; the mesh reserved the ``seq`` axis for it from day one). The design is
the TPU-native ring: every device holds one sequence block of Q/K/V; K/V
blocks rotate around the ring with ``lax.ppermute`` over ICI while each
device folds the incoming block into its queries' attention state with the
numerically-stable online-softmax update (running max ``m``, normalizer
``l``, unnormalized accumulator ``o`` — the blockwise/flash decomposition).
Peak memory per device is O(S/n * S/n) scores instead of O(S^2): sequence
length scales linearly with the ring size. The bound holds through
**backward** too: each hop is ``jax.checkpoint``-ed (see :func:`_ring_hop`),
so ``jax.grad`` re-derives score blocks instead of storing one per hop.

The ring is unrolled (ring size is a static mesh property), so XLA can
overlap each step's ppermute with the previous step's matmuls — communication
hides behind compute exactly like the NCCL bucket overlap the reference's DDP
relies on, but compiled rather than hand-scheduled.

Composes with the other axes: batch stays sharded on ``data``, heads on
``model`` (heads are independent in attention, so tensor parallelism passes
straight through), sequence on ``seq``. Plug the returned function into
:class:`..models.transformer.TransformerConfig` via ``attention_fn``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from pytorch_distributed_training_tutorials_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
)

# plain float, NOT jnp.float32(...): creating a jax array at import time
# would initialize the XLA backend, which breaks multi-process workers that
# must call jax.distributed.initialize() before any JAX computation
NEG_INF = float("-inf")


def _qkv_spec(mesh: Mesh, data_axis: str, seq_axis: str, model_axis: str) -> P:
    """(B, S, H, D) spec using only the axes the mesh actually has."""
    has = mesh.shape
    return P(
        data_axis if data_axis in has else None,
        seq_axis if seq_axis in has else None,
        model_axis if model_axis in has else None,
        None,
    )


@jax.checkpoint
def _ring_hop(qb, k_t, v_t, o, l, m, q_pos, k_pos, scale):
    """One ring hop: fold an incoming K/V block into the online-softmax
    state ``(o, l, m)``.

    ``jax.checkpoint`` here is what makes the module's O((S/n)^2) memory
    claim true *through backward*: without it, ``jax.grad`` over the
    unrolled ring stores every hop's (b, h, s_blk, s_blk) probability
    block — n of them, i.e. O(S^2/n) per device, roughly the thing the
    ring exists to avoid. Rematerialized, backward re-derives each hop's
    scores/probabilities from its O(s_blk * d) inputs, so only one score
    block is ever live (``tests/test_ring_attention.py`` pins the residual
    footprint vs dense attention).
    """
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", qb, k_t, preferred_element_type=jnp.float32
    ) * scale
    causal = q_pos[:, None] >= k_pos[None, :]  # (s_blk, s_blk) global
    scores = jnp.where(causal[None, None], scores, NEG_INF)

    m_new = jnp.maximum(m, scores.max(axis=-1))
    # m_new is finite from t=0 on: src==idx at t=0, so every query row sees
    # its own diagonal key first. (If the rotation start is ever changed,
    # -inf rows would need exp-of-nan guards here.)
    # (at t=0, corr = exp(-inf - finite) = 0 exactly, zeroing the empty
    # initial accumulators — no NaN guard needed)
    p = jnp.exp(scores - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l = l * corr + p.sum(axis=-1)
    o = o * corr[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v_t.astype(jnp.float32)
    )
    return o, l, m_new


def make_ring_attention(
    mesh: Mesh,
    *,
    seq_axis: str = SEQ_AXIS,
    data_axis: str = DATA_AXIS,
    model_axis: str = MODEL_AXIS,
):
    """Build a causal ``attention_fn(q, k, v) -> out`` ((B, S, H, D) each)
    that computes attention sequence-parallel over ``mesh[seq_axis]``.

    Numerically equivalent to :func:`..models.transformer.causal_attention`
    (verified to float tolerance in ``tests/test_ring_attention.py``); the
    difference is where the bytes live: no device ever materializes the full
    (S, S) score matrix or the full K/V.
    """
    if seq_axis not in mesh.shape:
        raise ValueError(f"mesh has no {seq_axis!r} axis: {dict(mesh.shape)}")
    n = mesh.shape[seq_axis]
    spec = _qkv_spec(mesh, data_axis, seq_axis, model_axis)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    def ring_attention(qb: jax.Array, kb: jax.Array, vb: jax.Array) -> jax.Array:
        b, s_blk, h, d = qb.shape
        idx = jax.lax.axis_index(seq_axis)
        q_pos = idx * s_blk + jnp.arange(s_blk)  # global positions of my queries

        scale = 1.0 / jnp.sqrt(jnp.float32(d))
        o = jnp.zeros((b, h, s_blk, d), jnp.float32)
        l = jnp.zeros((b, h, s_blk), jnp.float32)
        m = jnp.full((b, h, s_blk), NEG_INF)

        k_t, v_t = kb, vb
        shift = [(j, (j + 1) % n) for j in range(n)]
        for t in range(n):  # static ring, unrolled for ppermute/compute overlap
            # after t hops I hold the block that started on device (idx - t)
            src = (idx - t) % n
            k_pos = src * s_blk + jnp.arange(s_blk)
            o, l, m = _ring_hop(
                qb, k_t, v_t, o, l, m, q_pos, k_pos, scale
            )
            if t < n - 1:
                k_t, v_t = jax.lax.ppermute(
                    (k_t, v_t), seq_axis, perm=shift
                )

        # causal => every query row saw at least its own diagonal block
        out = o / l[..., None]
        return out.transpose(0, 2, 1, 3).astype(qb.dtype)

    # generate()'s prefill checks this: ring needs S to divide the seq
    # axis, so non-divisible prompt lengths prefill via the dense path
    # (divisible ones keep the ring and its memory bound)
    ring_attention.requires_seq_divisible = n
    return ring_attention
