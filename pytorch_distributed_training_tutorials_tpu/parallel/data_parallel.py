"""Data-parallel strategy: replicated params, batch-sharded data.

This single sharding configuration is the TPU twin of *both* reference
data-parallel APIs (SURVEY.md section 7):

- ``nn.DataParallel`` (reference ``01.data_parallel.ipynb`` cell 14): its
  per-step replicate/scatter/parallel_apply/gather collapses into one compiled
  SPMD program — params live replicated (no per-step broadcast), the batch is
  sharded on the ``data`` axis, outputs stay sharded.
- ``DistributedDataParallel`` (reference ``ddp_gpus.py:32``): the param
  broadcast at construction becomes the replicated placement; the bucketed
  NCCL grad allreduce in ``backward()`` (``ddp_gpus.py:38``) becomes the
  allreduce XLA inserts — and overlaps with the backward — when it propagates
  the replicated-param sharding through ``jax.grad``.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from pytorch_distributed_training_tutorials_tpu.parallel.mesh import (
    DATA_AXIS,
    create_mesh,
)


class DataParallel:
    """Sharding recipe for data parallelism over one mesh axis."""

    def __init__(self, mesh: Mesh | None = None, axis: str = DATA_AXIS):
        self.mesh = mesh if mesh is not None else create_mesh()
        self.axis = axis
        self.param_sharding = NamedSharding(self.mesh, PartitionSpec())
        self.batch_sharding = NamedSharding(self.mesh, PartitionSpec(axis))

    @property
    def num_devices(self) -> int:
        # INTERFACE CONTRACT (all strategies): the DATA-axis width — how
        # many ways the batch's dim 0 is sharded — NOT the total device
        # count. Trainer's grad-accum divisibility math relies on this.
        return self.mesh.shape.get(self.axis, 1)

    def variable_shardings(self, abstract_variables):
        """Uniform strategy interface: every variable replicated (the DDP
        param-broadcast invariant), as a pytree matching the input."""
        return jax.tree_util.tree_map(
            lambda _: self.param_sharding, abstract_variables
        )

    def shard_state(self, state):
        """Place a train state replicated on the mesh (the 'DDP broadcast')."""
        return jax.device_put(state, self.param_sharding)

    def shard_batch(self, batch):
        """Shard a host batch along dim 0 (the 'DataParallel scatter')."""
        return jax.device_put(batch, self.batch_sharding)
