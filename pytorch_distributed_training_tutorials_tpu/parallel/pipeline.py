"""Inter-layer (pipeline) model parallelism: the 03-notebook lessons, TPU-native.

Reference semantics being reproduced (SURVEY.md C14/C15):

- ``ToyModel``: ``net1`` on cuda:0, ``net2`` on cuda:1, explicit
  ``x.to("cuda:1")`` hop in forward (``03.model_parallel.ipynb:440-450``),
  full train step crossing the boundary in backward (``:532-542``).
- ``ModelParallelResNet50``: conv1..layer2 on cuda:0, layer3..fc on cuda:1,
  one batch flows stage0 -> stage1 with **no microbatch interleave**
  (``:807-834``, ``:830-833``) — stage 0 idles while stage 1 computes, which
  is exactly what the reference's benchmark (C17) measures against single-GPU.

TPU-native design: each stage is its own jitted XLA program committed to its
device; the activation hop is an explicit ``jax.device_put`` (ICI transfer on
real hardware — the twin of the reference's P2P copy). The backward re-crosses
the boundaries in reverse. Stage backward uses **rematerialization**: instead
of shipping vjp residuals between separately-compiled programs, each stage's
backward recomputes its forward under ``jax.vjp`` — the standard TPU trade of
FLOPs for HBM bandwidth/residency.

Parameters are *partitioned*, not replicated: each device holds only its
stage's variable subtree (the reference's memory-splitting motivation),
verified by the param-count invariance test (25,557,032 summed across stages).
"""

from __future__ import annotations

import inspect
from collections.abc import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from pytorch_distributed_training_tutorials_tpu.parallel.mesh import (
    DATA_AXIS,
    STAGE_AXIS,
)


def partition_variables(
    variables: dict, partition: Callable[[str], int], num_stages: int
) -> list[dict]:
    """Split a flax variables dict into per-stage dicts by top-level module key.

    ``partition`` maps a top-level module name (e.g. ``"conv1"``,
    ``"layer_groups_2_0"``, ``"fc"``) to its stage index. Every collection
    (params, batch_stats, ...) is split the same way. A stage method touching
    a variable assigned elsewhere fails loudly at trace time — the same
    guarantee the reference gets from per-device tensors.
    """
    out: list[dict] = [{} for _ in range(num_stages)]
    for coll, tree in variables.items():
        for name, sub in tree.items():
            s = partition(name)
            if not 0 <= s < num_stages:
                raise ValueError(f"partition({name!r}) -> {s} out of range")
            out[s].setdefault(coll, {})[name] = sub
    return out


def _method_takes_train(method) -> bool:
    return "train" in inspect.signature(method).parameters


def linen_stage_fn(model, method, *, train: bool = True) -> Callable:
    """Wrap a linen stage method as ``fn(variables, x) -> (out, updates)``.

    ``updates`` is a dict of mutated non-param collections (BN
    ``batch_stats``) or ``None``.
    """
    takes_train = _method_takes_train(method)

    def fn(variables, x):
        kwargs = {"train": train} if takes_train else {}
        mutable = [c for c in variables if c != "params"] if train else False
        if mutable:
            out, upd = model.apply(
                variables, x, method=method, mutable=mutable, **kwargs
            )
            return out, upd
        return model.apply(variables, x, method=method, **kwargs), None

    return fn


class ManualPipeline:
    """N sequential stages on N devices with explicit activation hops.

    ``stage_fns[i](variables_i, x) -> (out, updates_or_None)``; the last
    stage's output feeds the loss. Usage (twin of the reference's cells 12/26
    train loops)::

        pipe = ManualPipeline.from_linen(
            model, sample_x, devices=jax.devices()[:2],
            loss="mse", optimizer=optax.sgd(1e-3))
        out = pipe.forward(x)             # 2 programs + 1 hop
        loss = pipe.train_step(x, y)      # backward re-crosses the hop
    """

    def __init__(
        self,
        stage_fns: Sequence[Callable],
        stage_vars: Sequence[dict],
        devices: Sequence[jax.Device] | None = None,
        *,
        loss: str = "mse",
        optimizer: optax.GradientTransformation | None = None,
        eval_stage_fns: Sequence[Callable] | None = None,
    ):
        if devices is None:
            devices = jax.devices()[: len(stage_fns)]
        if len(stage_fns) != len(stage_vars):
            raise ValueError("one variables tree per stage required")
        if len(devices) < len(stage_fns):
            raise ValueError(
                f"{len(stage_fns)} stages but only {len(devices)} devices"
            )
        if loss not in ("mse", "cross_entropy"):
            raise ValueError(f"unknown loss {loss!r}")
        self.num_stages = len(stage_fns)
        self.devices = list(devices[: self.num_stages])
        self.stage_fns = list(stage_fns)
        # Commit each stage's variables to its device — the .to(f"cuda:{i}")
        # twin (reference 03.model_parallel.ipynb:812-827).
        self.stage_vars = [
            jax.device_put(v, d) for v, d in zip(stage_vars, self.devices)
        ]
        self.loss_name = loss
        self.tx = optimizer
        if optimizer is not None:
            self.opt_states = [
                jax.jit(optimizer.init)(v.get("params", {}))
                for v in self.stage_vars
            ]
            self._upd = jax.jit(self._opt_update)
        self._fwd = [jax.jit(fn) for fn in self.stage_fns]
        # Eval-mode programs (BN running averages) for inference forward.
        self._eval_fwd = (
            [jax.jit(fn) for fn in eval_stage_fns]
            if eval_stage_fns is not None
            else self._fwd
        )
        self._bwd_last = jax.jit(self._stage_bwd_last)
        # Stage 0 never needs the cotangent w.r.t. the raw input batch, so its
        # backward differentiates w.r.t. params only.
        self._bwd_mid = [
            jax.jit(self._make_stage_bwd(i, need_dx=i > 0))
            for i in range(self.num_stages - 1)
        ]

    @classmethod
    def from_linen(
        cls,
        model,
        sample_input,
        *,
        methods: Sequence | None = None,
        partition: Callable[[str], int] | None = None,
        devices=None,
        train: bool = True,
        seed: int = 0,
        **kwargs,
    ) -> "ManualPipeline":
        """Build from a linen model exposing ``stage0``/``stage1`` methods and
        a ``stage_partition(name) -> stage`` rule (ToyModel, ResNet)."""
        if methods is None:
            methods = [model.stage0, model.stage1]
        if partition is None:
            partition = model.stage_partition
        x = jnp.asarray(sample_input)
        variables = model.init(jax.random.PRNGKey(seed), x)
        stage_vars = partition_variables(dict(variables), partition, len(methods))
        stage_fns = [linen_stage_fn(model, m, train=train) for m in methods]
        eval_fns = [linen_stage_fn(model, m, train=False) for m in methods]
        return cls(stage_fns, stage_vars, devices, eval_stage_fns=eval_fns, **kwargs)

    # -- forward ----------------------------------------------------------
    def forward(self, x) -> jax.Array:
        """Inference forward (eval mode — BN running averages): stage i ->
        device hop -> stage i+1.

        The ``jax.device_put`` between stages is the explicit twin of the
        reference's ``x.to("cuda:1")`` (``03.model_parallel.ipynb:831``).
        """
        for i in range(self.num_stages):
            x = jax.device_put(x, self.devices[i])
            x, _ = self._eval_fwd[i](self.stage_vars[i], x)
        return x

    # -- loss -------------------------------------------------------------
    def _loss_fn(self, out, y):
        if self.loss_name == "mse":
            return ((out - y.astype(out.dtype)) ** 2).mean()
        if y.ndim == out.ndim:
            return optax.softmax_cross_entropy(out, y).mean()
        return optax.softmax_cross_entropy_with_integer_labels(out, y).mean()

    # -- backward ---------------------------------------------------------
    def _stage_bwd_last(self, variables, x, y):
        """Last stage: loss + grads wrt (params, stage input). Remat forward."""
        fn = self.stage_fns[-1]
        params = variables.get("params", {})
        rest = {k: v for k, v in variables.items() if k != "params"}

        def f(p, x_):
            out, upd = fn({"params": p, **rest}, x_)
            return self._loss_fn(out, y), upd

        loss, vjp_fn, upd = jax.vjp(f, params, x, has_aux=True)
        dparams, dx = vjp_fn(jnp.ones_like(loss))
        return loss, dparams, dx, upd

    def _make_stage_bwd(self, i: int, *, need_dx: bool):
        fn = self.stage_fns[i]

        def bwd(variables, x, ct):
            params = variables.get("params", {})
            rest = {k: v for k, v in variables.items() if k != "params"}

            if need_dx:
                def f(p, x_):
                    return fn({"params": p, **rest}, x_)

                _, vjp_fn, upd = jax.vjp(f, params, x, has_aux=True)
                dparams, dx = vjp_fn(ct)
                return dparams, dx, upd

            def f_params(p):
                return fn({"params": p, **rest}, x)

            _, vjp_fn, upd = jax.vjp(f_params, params, has_aux=True)
            (dparams,) = vjp_fn(ct)
            return dparams, None, upd

        return bwd

    def _opt_update(self, grads, opt_state, params):
        updates, new_opt = self.tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_opt

    def _apply_stage(self, i: int, grads, upd) -> None:
        v = dict(self.stage_vars[i])
        if self.tx is not None:
            v["params"], self.opt_states[i] = self._upd(
                grads, self.opt_states[i], v["params"]
            )
        if upd:
            v.update(upd)
        self.stage_vars[i] = v

    def train_step(self, x, y) -> jax.Array:
        """One optimizer step across all stages (reference ``:532-542``).

        Forward hops device-to-device saving stage inputs; backward walks the
        stages in reverse, each stage rematerializing its forward, handing the
        input-cotangent back across the boundary (the reference's backward
        P2P re-crossing), and applying its optimizer update in place.
        """
        if self.tx is None:
            raise ValueError("construct with optimizer=... to train")
        stage_inputs = []
        a = x
        for i in range(self.num_stages):
            a = jax.device_put(a, self.devices[i])
            stage_inputs.append(a)
            if i < self.num_stages - 1:
                a, _ = self._fwd[i](self.stage_vars[i], a)
        y = jax.device_put(y, self.devices[-1])

        loss, grads, ct, upd = self._bwd_last(
            self.stage_vars[-1], stage_inputs[-1], y
        )
        self._apply_stage(self.num_stages - 1, grads, upd)
        for i in range(self.num_stages - 2, -1, -1):
            ct = jax.device_put(ct, self.devices[i])
            grads, ct, upd = self._bwd_mid[i](
                self.stage_vars[i], stage_inputs[i], ct
            )
            self._apply_stage(i, grads, upd)
        return loss

    # -- introspection ----------------------------------------------------
    def stage_param_counts(self) -> list[int]:
        """Per-stage parameter counts (sums to the unsplit model's count —
        the 25,557,032 invariance check, reference cells 20/22)."""
        from pytorch_distributed_training_tutorials_tpu.models.utils import model_size

        return [model_size(v.get("params", {})) for v in self.stage_vars]

    def placement_audit(self) -> list[str]:
        """Device audit lines, twin of 03's param device/dtype audit (cell 4)."""
        return [
            f"stage {i}: {n:,} params on {d}"
            for i, (n, d) in enumerate(
                zip(self.stage_param_counts(), self.devices)
            )
        ]


def _tree_add(acc, tree):
    if tree is None:
        return acc
    if acc is None:
        return tree
    return jax.tree_util.tree_map(jnp.add, acc, tree)


def _tree_scale(tree, factor: float):
    if tree is None:
        return None
    return jax.tree_util.tree_map(lambda t: t * factor, tree)


class GPipe(ManualPipeline):
    """Microbatched dp x pp pipeline over a ``{'data': D, 'stage': S}`` mesh,
    for *heterogeneous* stages (the ResNet cut).

    Where :class:`ManualPipeline` reproduces the reference lesson exactly —
    one whole device per stage, one batch, stage 0 idle while stage 1 runs
    (``/root/reference/03.model_parallel.ipynb:830-833``) — ``GPipe`` is the
    production schedule the lesson motivates, composed with data parallelism:

    - each stage occupies one *column* of the device grid (its own sub-mesh
      with a ``data`` axis): stage params replicate over the column, and the
      per-stage gradient allreduce over ``data`` is compiled into each
      stage's backward by XLA, exactly as in pure DP.
    - the batch splits into ``num_microbatches`` microbatches that fill and
      drain the pipeline; stage programs live on disjoint device columns, so
      async dispatch CAN execute different microbatches concurrently — but
      the schedule itself is PYTHON-DRIVEN: ``train_step`` issues
      ``(n-1)*m`` forward + ``n*m`` backward stage programs + ``n`` applies
      as separate XLA launches (pinned by
      ``tests/test_gpipe.py::test_gpipe_dispatch_count_scales_with_
      microbatches``), plus a ``device_put`` per microbatch hop. On a
      runtime whose per-launch cost L is large this floors the step at
      ~``2*n*m*L`` regardless of compute — the tunneled v5e measures
      L ~ 75-130 ms (``scripts/launch_overhead_probe.py``), i.e. a
      2-stage x 4-microbatch step pays ~1-2 s of pure dispatch there.
      Choose by runtime: homogeneous layer stacks -> :mod:`.pipeline_spmd`
      (ONE compiled program, microbatching inside ``lax.scan``); direct
      low-launch-cost hosts with heterogeneous stages -> this class;
      lesson parity / no microbatching -> :class:`ManualPipeline`
      (``3n`` launches).
    - gradients (and BatchNorm statistics) accumulate across microbatches
      and apply once per step, averaged — numerically the step is plain
      gradient accumulation, verified against a single-device comparator in
      ``tests/test_gpipe.py``.

    Heterogeneous stages cannot ride a single ``shard_map`` program (no
    common stacked-parameter axis to shard over ``stage`` — see
    :mod:`.pipeline_spmd` for the homogeneous single-program schedule), so
    each stage is its own XLA program committed to its column; the
    microbatch hop is an ICI transfer between neighboring columns.

    Build with ``GPipe.from_linen(model, x, devices=mesh,
    num_microbatches=M, ...)`` — the mesh rides the ``devices`` slot.
    """

    def __init__(
        self,
        stage_fns: Sequence[Callable],
        stage_vars: Sequence[dict],
        mesh: Mesh,
        *,
        num_microbatches: int,
        data_axis: str = DATA_AXIS,
        stage_axis: str = STAGE_AXIS,
        **kwargs,
    ):
        if not isinstance(mesh, Mesh):
            raise TypeError(
                "GPipe places stages on a jax.sharding.Mesh with "
                f"'{data_axis}' and '{stage_axis}' axes; got {type(mesh)}"
            )
        if stage_axis not in mesh.shape:
            raise ValueError(f"mesh has no {stage_axis!r} axis: {mesh.shape}")
        num_stages = mesh.shape[stage_axis]
        if num_stages < len(stage_fns):
            raise ValueError(
                f"{len(stage_fns)} stages but mesh {stage_axis!r} axis is "
                f"{num_stages}"
            )
        if num_microbatches < 1:
            raise ValueError("num_microbatches must be >= 1")
        self.mesh = mesh
        self.num_microbatches = num_microbatches
        s_ax = mesh.axis_names.index(stage_axis)
        rep, act = [], []
        for s in range(len(stage_fns)):
            col = np.take(mesh.devices, s, axis=s_ax).reshape(-1)
            sub = Mesh(col, (data_axis,))
            rep.append(NamedSharding(sub, PartitionSpec()))
            act.append(NamedSharding(sub, PartitionSpec(data_axis)))
        self.act_shardings = act
        super().__init__(stage_fns, stage_vars, rep, **kwargs)

    @property
    def dp_size(self) -> int:
        return self.act_shardings[0].mesh.size

    def forward(self, x) -> jax.Array:
        """Inference forward: full batch, stage i column -> stage i+1 column
        (each hop reshards ``data``-split activations to the next column)."""
        for i in range(self.num_stages):
            x = jax.device_put(x, self.act_shardings[i])
            x, _ = self._eval_fwd[i](self.stage_vars[i], x)
        return x

    def _microbatches(self, arr):
        m = self.num_microbatches
        b = arr.shape[0]
        if b % m:
            raise ValueError(f"batch {b} not divisible by {m} microbatches")
        mbs = b // m
        if mbs % self.dp_size:
            raise ValueError(
                f"microbatch {mbs} rows not divisible by dp width "
                f"{self.dp_size}"
            )
        return [arr[i * mbs : (i + 1) * mbs] for i in range(m)]

    def train_step(self, x, y) -> jax.Array:
        """One optimizer step: GPipe fill (all microbatch forwards), drain
        (all microbatch backwards), then one averaged update per stage."""
        if self.tx is None:
            raise ValueError("construct with optimizer=... to train")
        n, m = self.num_stages, self.num_microbatches
        xs, ys = self._microbatches(x), self._microbatches(y)

        stage_inputs = [[None] * m for _ in range(n)]
        for mb in range(m):
            a = xs[mb]
            for i in range(n):
                a = jax.device_put(a, self.act_shardings[i])
                stage_inputs[i][mb] = a
                if i < n - 1:
                    a, _ = self._fwd[i](self.stage_vars[i], a)

        grad_acc: list = [None] * n
        upd_acc: list = [None] * n
        losses = []
        for mb in range(m):
            y_mb = jax.device_put(ys[mb], self.act_shardings[-1])
            loss, grads, ct, upd = self._bwd_last(
                self.stage_vars[-1], stage_inputs[-1][mb], y_mb
            )
            losses.append(loss)
            grad_acc[-1] = _tree_add(grad_acc[-1], grads)
            upd_acc[-1] = _tree_add(upd_acc[-1], upd)
            for i in range(n - 2, -1, -1):
                ct = jax.device_put(ct, self.act_shardings[i])
                grads, ct, upd = self._bwd_mid[i](
                    self.stage_vars[i], stage_inputs[i][mb], ct
                )
                grad_acc[i] = _tree_add(grad_acc[i], grads)
                upd_acc[i] = _tree_add(upd_acc[i], upd)

        inv = 1.0 / m
        for i in range(n):
            self._apply_stage(
                i, _tree_scale(grad_acc[i], inv), _tree_scale(upd_acc[i], inv)
            )
        return jnp.mean(jnp.stack([jnp.asarray(l) for l in losses]))
