"""Single-program SPMD pipeline parallelism: microbatched GPipe in shard_map.

Beyond-parity capability. The reference's pipeline lesson is a 2-stage split
with **no microbatch interleave** — one batch flows stage0 -> stage1 while
stage 0 idles (``/root/reference/03.model_parallel.ipynb:830-833``);
:class:`.pipeline.ManualPipeline` is that literal lesson twin. This module is
the production shape the lesson motivates: a GPipe fill/drain schedule with
``M`` microbatches over a ``{'data': D, 'stage': S}`` mesh, composed *with*
data parallelism, compiled as **one** XLA program.

TPU-native design (the scaling-book pipelining recipe):

- the transformer's layer stack is built with ``nn.scan``
  (``scan_layers=True``), so every block parameter has a leading
  ``n_layers`` axis. Sharding that axis over ``stage`` puts a contiguous
  block of ``n_layers / S`` layers on each stage — pipeline placement *is* a
  sharding annotation, no wrapper modules.
- inside :func:`~jax.experimental.shard_map.shard_map`, each tick of a
  ``lax.scan`` runs every stage in parallel on its resident layers; the
  activation hop to the next stage is a ``lax.ppermute`` along ``stage``
  (ICI neighbor transfer on hardware). ``M + S - 1`` ticks drain the
  pipeline — the familiar GPipe bubble, amortized by ``M``.
- data parallelism rides the ``data`` axis of the same mesh: the microbatch
  rows are sharded over it, and XLA inserts the gradient allreduce exactly
  as in pure DP. dp x pp needs no new code, just the mesh.
- backward is ``jax.grad`` straight through the shard_map (ppermute
  transposes to the reverse hop) — forward and backward compile into the
  same program, overlap scheduled by XLA.

Numerics are *identical* to the unpipelined model: the schedule reorders
computation, not math (microbatches are rows of the same batch; the loss is
the same mean over all rows).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_training_tutorials_tpu.utils.compat import (
    shard_map_nocheck,
)

from pytorch_distributed_training_tutorials_tpu.parallel.mesh import (
    DATA_AXIS,
    STAGE_AXIS,
)


def spmd_pipeline(
    stage_fn,
    mesh: Mesh,
    *,
    num_microbatches: int,
    data_axis: str = DATA_AXIS,
    stage_axis: str = STAGE_AXIS,
):
    """Wrap ``stage_fn`` in a microbatched GPipe schedule over ``mesh``.

    ``stage_fn(local_params, x) -> y`` applies one stage's resident layers to
    one microbatch (``y`` must have ``x``'s shape/dtype — a residual-block
    stack). Returns ``fn(stacked_params, x_mb)`` where ``stacked_params``
    leaves carry the leading layer axis (sharded over ``stage``) and
    ``x_mb`` is ``(M, rows, ...)`` (rows sharded over ``data``), computing
    the full ``S``-stage composition for every microbatch.
    """
    num_stages = mesh.shape[stage_axis]
    ticks = num_microbatches + num_stages - 1
    fwd_perm = [(i, i + 1) for i in range(num_stages - 1)]

    def local_schedule(layer_params, x_mb):
        s = jax.lax.axis_index(stage_axis)
        out = jnp.zeros(x_mb.shape, x_mb.dtype)
        state0 = jnp.zeros(x_mb.shape[1:], x_mb.dtype)

        def tick(carry, t):
            out, state = carry
            # stage 0 ingests microbatch t; later stages consume the
            # activation ppermuted from their predecessor last tick
            inject = x_mb[jnp.clip(t, 0, num_microbatches - 1)]
            x_in = jnp.where(s == 0, inject, state)
            y = stage_fn(layer_params, x_in)
            # the last stage finishes microbatch t - (S-1) at tick t
            mb = t - (num_stages - 1)
            mb_c = jnp.clip(mb, 0, num_microbatches - 1)
            valid = (s == num_stages - 1) & (mb >= 0)
            out = out.at[mb_c].set(jnp.where(valid, y, out[mb_c]))
            state = (
                jax.lax.ppermute(y, stage_axis, fwd_perm)
                if fwd_perm
                else y
            )
            return (out, state), None

        (out, _), _ = jax.lax.scan(
            tick, (out, state0), jnp.arange(ticks)
        )
        # only the last stage holds real outputs (others contributed zeros);
        # the psum makes the result stage-invariant so out_specs can
        # replicate it over the stage axis
        return jax.lax.psum(out, stage_axis)

    # checking off: the hand-rolled ppermute schedule carries no
    # replication/varying-axes info the static checker can follow
    # (check_rep/check_vma by jax version — utils.compat owns the drift)
    return shard_map_nocheck(
        local_schedule,
        mesh=mesh,
        in_specs=(P(stage_axis), P(None, data_axis)),
        out_specs=P(None, data_axis),
    )


class PipelinedTransformerLM:
    """dp x pp transformer LM: same params/numerics as
    :class:`..models.transformer.TransformerLM` (``scan_layers=True``), with
    the layer stack executed as a GPipe schedule.

    Drop-in for the Trainer together with :class:`PipelineParallel`::

        mesh = create_mesh({'data': D, 'stage': S})
        model = PipelinedTransformerLM(cfg, mesh, num_microbatches=4)
        strategy = PipelineParallel(mesh, num_microbatches=4)
        Trainer(model, loader, tx, strategy=strategy, loss='cross_entropy')

    Constraints: ``cfg.n_layers % S == 0``; per-step batch ``B`` must satisfy
    ``B % M == 0`` and ``(B / M) % D == 0``; dense FFN only (MoE's sown
    aux losses compose with expert parallelism, not the pipeline schedule).
    """

    def __init__(
        self,
        cfg,
        mesh: Mesh,
        *,
        num_microbatches: int,
        data_axis: str = DATA_AXIS,
        stage_axis: str = STAGE_AXIS,
    ):
        from pytorch_distributed_training_tutorials_tpu.models.transformer import (
            Block,
            TransformerLM,
        )

        if not cfg.scan_layers:
            import dataclasses

            cfg = dataclasses.replace(cfg, scan_layers=True)
        if cfg.moe_experts:
            raise ValueError(
                "PipelinedTransformerLM supports dense blocks only "
                "(MoE aux-loss sowing does not thread the pipeline scan)"
            )
        num_stages = mesh.shape[stage_axis]
        if cfg.n_layers % num_stages:
            raise ValueError(
                f"n_layers {cfg.n_layers} not divisible by "
                f"{num_stages} pipeline stages"
            )
        self.cfg = cfg
        self.mesh = mesh
        self.num_microbatches = num_microbatches
        self.inner = TransformerLM(cfg)
        block = Block(cfg)

        def stage_fn(layer_params, x):
            # layer_params leaves: (n_layers/S, ...) — this stage's block
            def body(x, p):
                return block.apply({"params": p}, x), None

            x, _ = jax.lax.scan(body, x, layer_params)
            return x

        self._pipeline = spmd_pipeline(
            stage_fn,
            mesh,
            num_microbatches=num_microbatches,
            data_axis=data_axis,
            stage_axis=stage_axis,
        )

    def init(self, key, tokens):
        return self.inner.init(key, tokens)

    def apply(self, variables, tokens):
        from pytorch_distributed_training_tutorials_tpu.models.transformer import (
            RMSNorm,
        )

        cfg = self.cfg
        params = variables["params"]
        m = self.num_microbatches
        b = tokens.shape[0]
        if b % m:
            raise ValueError(f"batch {b} not divisible by {m} microbatches")
        if tokens.shape[1] > cfg.max_seq_len:
            # same validation contract as the unpipelined TransformerLM
            raise ValueError(
                f"sequence length {tokens.shape[1]} exceeds "
                f"max_seq_len {cfg.max_seq_len}"
            )
        x = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype).apply(
            {"params": params["tok_emb"]}, tokens
        )
        x_mb = x.reshape(m, b // m, *x.shape[1:])
        y_mb = self._pipeline(params["layers"]["block"], x_mb)
        y = y_mb.reshape(b, *x.shape[1:])
        y = RMSNorm(cfg.norm_eps).apply({"params": params["final_norm"]}, y)
        return nn.Dense(
            cfg.vocab_size, use_bias=False, dtype=cfg.dtype
        ).apply({"params": params["lm_head"]}, y)

    # Trainer calls model.apply(variables, x); __call__ for plain use
    __call__ = apply


class PipelineParallel:
    """dp x pp sharding strategy: stacked layer params over ``stage``,
    embeddings/head replicated, batches over ``data``.

    Drop-in for :class:`.data_parallel.DataParallel` in the Trainer. The
    optimizer state follows the same placement because optax moments mirror
    the param tree (their key paths contain the same ``layers`` segment).
    """

    def __init__(
        self,
        mesh: Mesh,
        *,
        num_microbatches: int = 1,
        data_axis: str = DATA_AXIS,
        stage_axis: str = STAGE_AXIS,
        layers_key: str = "layers",
    ):
        self.mesh = mesh
        self.num_microbatches = num_microbatches
        self.data_axis = data_axis
        self.stage_axis = stage_axis
        self.layers_key = layers_key
        self.batch_sharding = NamedSharding(mesh, P(data_axis))
        self._stage0 = NamedSharding(mesh, P(stage_axis))
        self._replicated = NamedSharding(mesh, P())

    @property
    def num_devices(self) -> int:
        return self.mesh.shape.get(self.data_axis, 1)

    @property
    def num_stages(self) -> int:
        return self.mesh.shape.get(self.stage_axis, 1)

    def _leaf_sharding(self, key_path) -> NamedSharding:
        in_stack = any(
            getattr(k, "key", None) == self.layers_key for k in key_path
        )
        return self._stage0 if in_stack else self._replicated

    def variable_shardings(self, abstract_variables):
        return jax.tree_util.tree_map_with_path(
            lambda kp, _: self._leaf_sharding(kp), abstract_variables
        )

    def shard_state(self, state):
        return jax.tree_util.tree_map_with_path(
            lambda kp, leaf: jax.device_put(leaf, self._leaf_sharding(kp)),
            state,
        )

    def shard_batch(self, batch):
        return jax.device_put(batch, self.batch_sharding)
