"""Distributed runtime bootstrap: the TPU twin of the reference's L1 layer.

The reference has two bootstrap flavors (SURVEY.md C1/C2) whose *only* delta is
where rank/world-size/rendezvous come from:

- **spawn flavor** (reference ``ddp_gpus.py:12-17``): explicit
  ``rank``/``world_size`` arguments plus a hardcoded
  ``MASTER_ADDR=localhost, MASTER_PORT=12345`` TCPStore rendezvous.
- **torchrun flavor** (reference ``ddp_gpus_torchrun.py:12-14``): everything is
  read from launcher-injected environment variables.

:func:`init` keeps that seam but with one code path: pass explicit
``coordinator_address``/``num_processes``/``process_id`` for the spawn
contract, pass nothing for the environmental contract
(``jax.distributed.initialize()`` autodetects on TPU pods from the runtime
metadata, and honors ``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/
``JAX_PROCESS_ID`` env vars — the torchrun contract). Single-process runs
(one host, N local chips — the reference's ``nn.DataParallel`` setting) need no
initialization at all, and :func:`init` detects that and no-ops.

Teardown (reference ``destroy_process_group()``, ``ddp_gpus.py:93``) is
:func:`shutdown`.
"""

from __future__ import annotations

import os

import jax

# Default rendezvous endpoint for the spawn-style contract; twin of the
# reference's hardcoded MASTER_ADDR/MASTER_PORT (ddp_gpus.py:13-14).
DEFAULT_COORDINATOR = "localhost:12355"

_initialized = False


def init(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    *,
    local_device_ids: list[int] | None = None,
) -> None:
    """Initialize the multi-process runtime (no-op for single-process runs).

    Spawn contract (explicit args, reference ``ddp_gpus.py:12-17``)::

        init("localhost:12355", num_processes=4, process_id=rank)

    Environmental contract (reference ``ddp_gpus_torchrun.py:12-14``; the
    launcher — a pod launcher or :mod:`..launch` — injects
    ``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``, or a
    TPU pod autodetects from runtime metadata)::

        init()
    """
    global _initialized
    if _initialized:
        return

    env_keys = [
        "JAX_COORDINATOR_ADDRESS",
        "JAX_NUM_PROCESSES",
        "JAX_PROCESS_ID",
        "COORDINATOR_ADDRESS",
    ]
    # TPU pod metadata only counts as a topology signal when we're actually
    # going to run on TPU — a CPU-forced run (tests, notebooks) on a TPU VM
    # must not try to rendezvous against the pod runtime.
    if not os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        env_keys += ["TPU_WORKER_HOSTNAMES", "MEGASCALE_COORDINATOR_ADDRESS"]
    env_driven = any(k in os.environ for k in env_keys)
    explicit = coordinator_address is not None or num_processes is not None

    if not explicit and not env_driven:
        # Single-process, possibly multi-chip: the nn.DataParallel setting.
        # jax.distributed.initialize is unnecessary and would hang waiting for
        # peers; device "pinning" is implicit in the TPU topology.
        return

    # The env contract: jax reads JAX_COORDINATOR_ADDRESS natively, but has
    # no JAX_NUM_PROCESSES/JAX_PROCESS_ID autodetection outside managed
    # clusters (SLURM/MPI/Cloud TPU metadata) — so this layer provides it,
    # completing the torchrun-style env seam (RANK/WORLD_SIZE twin).
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])

    kwargs: dict = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    jax.distributed.initialize(**kwargs)
    _initialized = True


def shutdown() -> None:
    """Tear down the multi-process runtime.

    Twin of the reference's ``destroy_process_group()`` (``ddp_gpus.py:93``,
    ``ddp_gpus_torchrun.py:88``). Safe to call when :func:`init` no-opped.
    """
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False


def process_index() -> int:
    """This process's rank. Twin of ``RANK`` / ``dist.get_rank()``."""
    return jax.process_index()


def process_count() -> int:
    """Number of processes. Twin of ``WORLD_SIZE`` / ``dist.get_world_size()``."""
    return jax.process_count()


def is_primary() -> bool:
    """True on the logging process (the reference's rank-0 convention)."""
    return jax.process_index() == 0
