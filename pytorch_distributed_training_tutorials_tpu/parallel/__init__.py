"""Parallelism: mesh construction, distributed bootstrap, strategy configs.

TPU-native twin of the reference's L1 (process group) and L3 (parallelism
strategy) layers — see SURVEY.md sections 1-2. One mesh + sharding abstraction
replaces ``nn.DataParallel`` / ``DistributedDataParallel`` / manual device
placement.
"""

from pytorch_distributed_training_tutorials_tpu.parallel.mesh import (  # noqa: F401
    create_mesh,
    DATA_AXIS,
    MODEL_AXIS,
    STAGE_AXIS,
    SEQ_AXIS,
)
from pytorch_distributed_training_tutorials_tpu.parallel.distributed import (  # noqa: F401
    init,
    shutdown,
    process_index,
    process_count,
)
from pytorch_distributed_training_tutorials_tpu.parallel.data_parallel import (  # noqa: F401
    DataParallel,
)
from pytorch_distributed_training_tutorials_tpu.parallel.pipeline import (  # noqa: F401
    GPipe,
    ManualPipeline,
    partition_variables,
)
from pytorch_distributed_training_tutorials_tpu.parallel.pipeline_spmd import (  # noqa: F401
    PipelinedTransformerLM,
    PipelineParallel,
    spmd_pipeline,
)
from pytorch_distributed_training_tutorials_tpu.parallel.tensor_parallel import (  # noqa: F401
    SLOT_STATE_RULES,
    TensorParallel,
    audit_hlo,
)
from pytorch_distributed_training_tutorials_tpu.parallel.fsdp import (  # noqa: F401
    FSDP,
    HybridFSDP,
)
from pytorch_distributed_training_tutorials_tpu.parallel.ring_attention import (  # noqa: F401
    make_ring_attention,
)
from pytorch_distributed_training_tutorials_tpu.parallel.ulysses import (  # noqa: F401
    make_ulysses_attention,
)

# .auto (orbax checkpointing / auto placement) is imported lazily by users —
# orbax is a heavyweight import and not needed on the hot path.
